"""Device-mesh sweep drivers: scale design/case evaluations over TPUs.

The reference sweeps designs with nested serial Python loops
(``/root/reference/raft/parametersweep.py:56-100``) and has no
distributed backend (SURVEY.md §2.1).  Here a sweep is one batched
tensor program laid out over a ``jax.sharding.Mesh``:

* the **batch** axis (designs x cases — embarrassingly parallel, each a
  ~6-DOF problem) shards over the ``dp`` mesh axis and rides ICI;
* the **frequency** axis — the workload's 'sequence' axis — can shard
  over ``sp``; the only cross-frequency couplings are the
  drag-linearisation RMS statistics and the convergence norm
  (raft_member.py:2084-2090), which XLA lowers to all-reduces when the
  sharded program is compiled (the moral equivalent of context
  parallelism for this physics).

Everything goes through GSPMD: we annotate in/out shardings and let the
compiler insert the collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from raft_tpu.obs import metrics
from raft_tpu.obs.spans import span
from raft_tpu.utils.dtypes import compute_dtypes
from raft_tpu.utils.structlog import log_event


def _mesh_key(mesh):
    return (tuple(mesh.axis_names), mesh.devices.shape,
            tuple(d.id for d in mesh.devices.flat))


def _flags_key():
    """Trace-time RAFT_TPU_* flag values that shape the compiled
    program.  Part of every memo key: the registry promises flags are
    re-read per call, so a sweep after a flag flip must re-trace
    instead of silently reusing the old-flag program.  The
    solver-health flags belong here too: the escalation re-solver
    (raft_tpu.parallel.resilience) flips ITER_SCALE/DTYPE around solo
    re-evaluations and relies on this key to get the rung's program
    instead of the cached base one."""
    from raft_tpu.utils import config

    return tuple(config.get(k) for k in
                 ("SOLVER", "FIXED_POINT", "SCAN_CHUNK", "DTYPE",
                  "COND_CHECK", "COND_THRESHOLD", "ITER_SCALE", "FUSED"))


def _cached_jit(evaluate, key, build):
    """The dispatchable sweep program for (evaluate, key), built at
    most once and fronted by the AOT program bank.

    `jax.jit(vmap(...))` built inside the sweep call would be a FRESH
    function object every invocation, so a second identical sweep
    re-traced and re-compiled the whole batched program (observed by
    the recompilation sentinel, raft_tpu.analysis.recompile).  The memo
    lives in the evaluator's own attribute dict — the wrapper closes
    over the evaluator, so the two form a plain reference cycle the gc
    reclaims together once the caller drops the evaluator (a
    module-level cache keyed on the evaluator would pin its closed-over
    model build tensors for process lifetime).

    What the memo holds is a :class:`raft_tpu.aot.bank.BankedProgram`:
    under ``RAFT_TPU_AOT=off`` it is a transparent shim over the
    jitted wrapper; under ``load``/``require`` it consults the on-disk
    program bank BEFORE tracing — a warmed fresh process dispatches
    its first sweep from a deserialized executable with zero backend
    compilations, and a miss exports the freshly-compiled program for
    the next process (see :mod:`raft_tpu.aot.bank`).

    Trace-once contract: an evaluator is traced at most once per
    (out_keys, mesh, trace-time flags) key — closed-over state mutated
    AFTER the first sweep is not picked up (build a fresh evaluator, or
    ``del evaluate._raft_sweep_jit`` to force a re-trace).  The same
    caveat applies to the bank with more force: banked executables
    outlive the process, so evaluators whose closures differ must
    differ in the memo key (the code/flag/aval fingerprints cover
    everything else)."""
    from raft_tpu.aot import bank

    # the bank's cross-process key additionally carries the program
    # identity the evaluator factory stamped (a content hash of the
    # design + factory arguments — raft_tpu.aot.bank.content_fingerprint);
    # an unstamped closure is memoized but never banked, because
    # nothing else in the key distinguishes its baked-in constants
    pk = bank.program_key(evaluate)
    key = key + (("program", pk),)
    if getattr(evaluate, "__self__", None) is not None:
        # bound method: its attribute dict is the CLASS function's,
        # shared by every instance — memoizing there would hand
        # instance B a program compiled over instance A's state
        return bank.BankedProgram(key[0], key, build,
                                  bankable=pk is not None)
    try:
        per = evaluate.__dict__.setdefault("_raft_sweep_jit", {})
    except AttributeError:  # no attribute dict: no memoization
        return bank.BankedProgram(key[0], key, build,
                                  bankable=pk is not None)
    if key not in per:
        # first build for this memo key: the next dispatch loads from
        # the bank or traces+compiles — worth a telemetry mark, because
        # an unexpected growth of this counter IS the recompile storm
        # the sentinel (raft_tpu.analysis.recompile) exists to catch
        metrics.counter("sweep_programs_built").inc()
        log_event("sweep_program_built", kind=key[0],
                  out_keys=list(key[1]))
        per[key] = bank.BankedProgram(key[0], key, build,
                                      bankable=pk is not None)
    return per[key]


def ensure_distributed(dryrun=False):
    """Wire ``jax.distributed.initialize`` in for multi-host meshes.

    Gated on ``RAFT_TPU_DIST``; coordinator address / process id /
    process count come from the ``RAFT_TPU_DIST_*`` flags (set them
    per host in the pod launcher).  Must run before the first backend
    init — after it, ``jax.devices()`` (and therefore
    :func:`make_mesh`) spans every process's devices and GSPMD inserts
    the cross-host collectives itself.  ``dryrun=True`` validates and
    returns the parsed config without touching jax (the CI-testable
    path on a single-host CPU container).  Returns the config dict, or
    ``None`` when distribution is off.  Idempotent: a second call in
    an already-initialized process is a no-op."""
    from raft_tpu.utils import config

    if not config.get("DIST"):
        return None
    cfg = {
        "coordinator": str(config.get("DIST_COORDINATOR")),
        "process_id": int(config.get("DIST_PROCESS_ID")),
        "num_processes": int(config.get("DIST_NUM_PROCESSES")),
    }
    if ":" not in cfg["coordinator"]:
        raise ValueError(
            f"RAFT_TPU_DIST_COORDINATOR={cfg['coordinator']!r}: expected "
            "host:port")
    if not 0 <= cfg["process_id"] < cfg["num_processes"]:
        raise ValueError(
            f"RAFT_TPU_DIST_PROCESS_ID={cfg['process_id']} out of range "
            f"for RAFT_TPU_DIST_NUM_PROCESSES={cfg['num_processes']}")
    log_event("distributed_init", coordinator=cfg["coordinator"],
              process_id=cfg["process_id"],
              num_processes=cfg["num_processes"], dryrun=bool(dryrun))
    if dryrun:
        return cfg
    if _DIST_DONE[0]:
        return cfg  # already initialized (resume / second sweep)
    try:
        jax.distributed.initialize(
            coordinator_address=cfg["coordinator"],
            num_processes=cfg["num_processes"],
            process_id=cfg["process_id"])
    except RuntimeError as e:
        # e.g. initialize() called twice by an outer launcher — the
        # runtime is already distributed, which is what we wanted
        if "already" not in str(e).lower():
            raise
    _DIST_DONE[0] = True
    return cfg


_DIST_DONE = [False]


def make_mesh(n_devices=None, axis_names=("dp",)):
    devices = np.array(jax.devices()[: n_devices or len(jax.devices())])
    if len(axis_names) == 1:
        shape = (len(devices),)
    else:
        # split devices as evenly as possible over two axes
        n = len(devices)
        dp = max(d for d in range(1, n + 1) if n % d == 0 and d * d <= n)
        shape = (n // dp, dp)
    return Mesh(devices.reshape(shape), axis_names)


def _autopad_rows(n, mesh):
    """Rows to append so GSPMD can lay out the batch over the dp axis.

    Ragged batches used to raise here; now the sweep pads the tail by
    repeating the last row (masked rows — they are dropped again when
    the results are gathered) and keeps a ``dp_autopad`` warning event
    so silently-padded dispatches stay visible in the event stream.
    Note the drop itself is a device-side slice: the first ragged
    dispatch of a given shape compiles one small one-off slice program
    (a backend_compile event the recompile sentinel sees) — divisible
    batches keep the strictly compile-free dispatch."""
    dp = mesh.shape.get("dp", 1)
    if n == 0:
        raise ValueError(
            f"empty batch cannot be laid out over the dp mesh axis "
            f"(mesh {dict(mesh.shape)})")
    pad = (-n) % dp
    if pad:
        metrics.counter("dp_autopad_rows").inc(pad)
        log_event("dp_autopad", rows=n, pad=pad, dp=dp)
    return pad


def _pad_tail(a, pad):
    """Repeat the last row ``pad`` times (host numpy, no device work)."""
    a = np.asarray(a)
    if not pad:
        return a
    return np.concatenate([a, np.repeat(a[-1:], pad, axis=0)])


def sweep_cases(evaluate, Hs, Tp, beta, mesh=None, out_keys=("PSD", "X0")):
    """Evaluate a batch of sea states, sharded over the mesh's dp axis.

    evaluate : scalar-case function from :func:`raft_tpu.api.make_case_evaluator`
    Hs/Tp/beta : (N,) arrays; a batch not divisible by the dp axis size
        is auto-padded with masked repeat rows (dropped on gather,
        ``dp_autopad`` warning event — see :func:`_autopad_rows`)
    """
    from raft_tpu.utils.devices import enable_compile_cache

    enable_compile_cache()
    if mesh is None:
        mesh = make_mesh()
    n = len(np.asarray(Hs))
    pad = _autopad_rows(n, mesh)
    sharding = NamedSharding(mesh, P("dp"))

    def build():
        def one(h, t, b):
            # named_scope: device ops from the sweep body carry this
            # name on profiler timelines (metadata only — adds no
            # primitives, jaxpr contracts unchanged)
            with jax.named_scope("sweep_cases"):
                return {k: evaluate(h, t, b)[k] for k in out_keys}

        return jax.jit(jax.vmap(one),
                       in_shardings=(sharding, sharding, sharding))

    fn = _cached_jit(evaluate, ("cases", tuple(out_keys), _mesh_key(mesh),
                                _flags_key()), build)
    # device_put from HOST numpy: the runtime scatters host buffers to
    # the sharding directly, whereas device_put of an uncommitted jax
    # array reshards through a tiny jitted _multi_slice program — an
    # avoidable compile (and a spurious backend_compile event) on the
    # very dispatch the AOT bank promises is compile-free
    args = [jax.device_put(_pad_tail(x, pad), sharding)
            for x in (Hs, Tp, beta)]
    with span("sweep_dispatch", kind="cases", rows=len(args[0])):
        out = fn(*args)
    if pad:  # drop the masked tail rows on gather
        out = {k: v[:n] for k, v in out.items()}
    return out


def sweep_cases_full(evaluate, cases, mesh=None, out_keys=("PSD", "X0"),
                     shard_freq=False):
    """Evaluate a batch of FULL-physics cases, sharded over the mesh.

    evaluate : case-dict function from :func:`raft_tpu.api.make_full_evaluator`
        (or the farm/flexible variants)
    cases : dict of (N,) arrays — any subset of the evaluator's case
        keys (wind_speed, TI, Hs, Tp, beta_deg, geometry scales, ...);
        a ragged N auto-pads to dp-divisibility with masked repeat
        rows (dropped on gather, ``dp_autopad`` warning event).
    shard_freq : also partition the FREQUENCY axis of the outputs over
        the mesh's "sp" axis (requires a 2D ("dp","sp") mesh).  The
        frequency axis is the workload's sequence axis (SURVEY §5.7);
        annotating the out-sharding makes GSPMD propagate the partition
        back through the response solve / excitation chain and insert
        the cross-frequency collectives (drag-linearisation RMS
        statistics) itself.

    ``"status"`` is a first-class out_key: every traced evaluator
    emits the per-case int32 solver-health word
    (:mod:`raft_tpu.utils.health`) and requesting it here persists it
    into shards, where the checkpointed drivers' quarantine/escalation
    logic (:mod:`raft_tpu.parallel.resilience`) consumes it.

    Returns the dict of stacked outputs (sharded jax arrays).
    """
    from raft_tpu.utils.devices import enable_compile_cache

    enable_compile_cache()
    if mesh is None:
        mesh = make_mesh()
    lengths = {k: len(np.asarray(v)) for k, v in cases.items()}
    if len(set(lengths.values())) > 1:
        raise ValueError(
            f"ragged case dict: all case arrays must have equal length, "
            f"got {lengths}")
    n = next(iter(lengths.values()))
    pad = _autopad_rows(n, mesh)
    in_sh = jax.tree_util.tree_map(
        lambda _: NamedSharding(mesh, P("dp")), cases)

    def out_spec(k):
        if shard_freq and k in ("PSD", "Xi", "RAO", "S"):
            # (..., nw) — frequency is the trailing axis on these
            nfree = {"PSD": 2, "Xi": 3, "RAO": 2, "S": 2}[k]
            return NamedSharding(mesh, P("dp", *([None] * (nfree - 1)), "sp"))
        return NamedSharding(mesh, P("dp"))

    def build():
        def one(c):
            with jax.named_scope("sweep_cases_full"):
                return {k: evaluate(c)[k] for k in out_keys}

        out_sh = {k: out_spec(k) for k in out_keys}
        return jax.jit(jax.vmap(one), in_shardings=(in_sh,),
                       out_shardings=out_sh)

    fn = _cached_jit(
        evaluate, ("cases_full", tuple(out_keys), tuple(sorted(cases)),
                   bool(shard_freq), _mesh_key(mesh), _flags_key()), build)
    # host-numpy device_put: no resharding program, no compile event
    # (see sweep_cases)
    args = jax.tree_util.tree_map(
        lambda x, s: jax.device_put(_pad_tail(x, pad), s), dict(cases), in_sh)
    with span("sweep_dispatch", kind="cases_full", rows=n):
        out = fn(args)
    if pad:  # drop the masked tail rows on gather
        out = {k: v[:n] for k, v in out.items()}
    return out


def sweep_heterogeneous(models, Hs, Tp, beta, mesh=None,
                        out_keys=("PSD", "X0", "status")):
    """Sweep a batch of sea states over ARBITRARY MIXED designs with a
    compile-bounded program count (SURVEY §7.3 hard part 2).

    models : sequence of :class:`raft_tpu.Model`, one per case row
        (repeat an object to evaluate it under several sea states; the
        packed design pytree is built once per distinct model).
    Hs/Tp/beta : (N,) sea-state arrays aligned with ``models``.

    Designs are auto-binned by their bucket signature
    (:func:`raft_tpu.structure.bucketing.bucket_signature`): every
    group dispatches through ONE compiled program — the bucket
    evaluator vmapped over (sea state x packed design) — so a sweep
    over B distinct member layouts costs at most ``n_buckets``
    backend compilations (``n_buckets <= B``, typically far fewer),
    and each bucket program is AOT-bankable (its identity is the
    signature, not any design).  Groups are padded to dp-divisibility
    with masked repeat rows (dropped on gather) and results are
    reassembled in input order.  Groups larger than
    ``RAFT_TPU_BUCKET_ROWS`` (default 512; 0 = unlimited) dispatch in
    fixed-size chunks of that many rows, capping the materialized
    packed-design batch (each row carries its design's padded pytree,
    Imat included) while every chunk reuses one compiled program.

    Returns a dict of HOST numpy arrays of length N (reassembly is a
    host-side scatter across buckets).
    """
    from raft_tpu.structure import bucketing
    from raft_tpu.utils.devices import enable_compile_cache

    enable_compile_cache()
    if mesh is None:
        mesh = make_mesh()
    Hs = np.asarray(Hs, dtype=float)
    Tp = np.asarray(Tp, dtype=float)
    beta = np.asarray(beta, dtype=float)
    n = len(Hs)
    if n == 0:
        raise ValueError("empty batch: no case rows to sweep")
    if not (len(Tp) == len(beta) == n):
        raise ValueError("Hs/Tp/beta must have equal length")
    if len(models) != n:
        raise ValueError(
            f"need one model per case row: {len(models)} models for "
            f"{n} rows (repeat model objects to reuse a design)")

    # pack each DISTINCT model once; bin rows by bucket signature
    packed_by_model = {}
    row_sigs = []
    for m in models:
        ent = packed_by_model.get(id(m))
        if ent is None:
            sig = bucketing.bucket_signature(m)
            ent = packed_by_model[id(m)] = (
                sig, bucketing.pack_design(m, sig),
                bucketing.axis_counts(m, sig))
        row_sigs.append(ent[0])
    w_grids = {tuple(bucketing.signature_meta(s)["w"])
               for s in set(row_sigs)}
    if len(w_grids) > 1:
        raise ValueError(
            "mixed frequency grids in one heterogeneous sweep: outputs "
            "cannot be stacked; group the sweep by settings.min/max_freq")
    groups = {}
    for i, s in enumerate(row_sigs):
        groups.setdefault(s, []).append(i)

    # the packed design batch is materialized per ROW (np.stack below
    # duplicates a repeated model's Imat for every row that uses it),
    # so groups larger than RAFT_TPU_BUCKET_ROWS dispatch in fixed-size
    # chunks of exactly that many rows — peak host/device memory stays
    # chunk x design, and the last chunk pads up to the SAME row count
    # (masked repeat rows) so every chunk reuses ONE compiled program
    from raft_tpu.utils import config

    dp = mesh.shape.get("dp", 1)
    cap = int(config.get("BUCKET_ROWS"))
    if cap:
        cap = -(-cap // dp) * dp

    sharding = NamedSharding(mesh, P("dp"))
    out = {}
    n_row_pad = 0
    for sig, idxs in groups.items():
        ev = bucketing.get_bucket_evaluator(sig)
        if cap and len(idxs) > cap:
            chunks = [idxs[i:i + cap] for i in range(0, len(idxs), cap)]
        else:
            chunks = [idxs]
        for chunk in chunks:
            rows = len(chunk)
            pad = (cap - rows) if len(chunks) > 1 else \
                _autopad_rows(rows, mesh)
            n_row_pad += pad
            take = chunk + [chunk[-1]] * pad
            design = bucketing.stack_packed(
                [packed_by_model[id(models[i])][1] for i in chunk],
                rows + pad)
            case = dict(design=design, Hs=Hs[take], Tp=Tp[take],
                        beta=beta[take])
            in_sh = jax.tree_util.tree_map(lambda _: sharding, case)

            def build(ev=ev, in_sh=in_sh, keys=tuple(out_keys)):
                def one(c):
                    with jax.named_scope("sweep_bucket"):
                        return {kk: ev(c)[kk] for kk in keys}

                return jax.jit(jax.vmap(one), in_shardings=(in_sh,))

            # key[1] must stay the out_keys tuple: _cached_jit's
            # sweep_program_built event logs it under that field name
            fn = _cached_jit(
                ev, ("bucket", tuple(out_keys), sig, _mesh_key(mesh),
                     _flags_key()), build)
            # host-numpy device_put: no resharding program (see
            # sweep_cases)
            args = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(np.asarray(x), s), case, in_sh)
            with span("sweep_dispatch", kind="bucket", rows=rows,
                      bucket=bucketing.signature_fingerprint(sig)):
                res = fn(args)
            # reassemble in input order (host scatter; padded rows
            # dropped)
            for kk in out_keys:
                host = np.asarray(res[kk])[:rows]
                if kk not in out:
                    out[kk] = np.zeros((n,) + host.shape[1:],
                                       dtype=host.dtype)
                out[kk][chunk] = host
    # waste is ROW-weighted (one packed entry per dispatched row, the
    # README definition and what bench.py reports), not per distinct
    # design — 990 floor-bucket rows + 10 big-semi rows must not log
    # the unweighted 2-design mean.  The per-axis decomposition rides
    # the same rows: strips reproduce padding_waste_frac exactly,
    # nodes/lines/batch-rows name where the rest of the pad budget
    # goes (counters + per-row histograms; run records carry them)
    row_axes = [packed_by_model[id(m)][2] for m in models]
    bucketing.observe_axis_waste(row_axes, rows_valid=n,
                                 rows_padded=n + n_row_pad)
    log_event("bucket_sweep", rows=n, n_buckets=len(groups),
              n_designs=len(packed_by_model),
              padding_waste_frac=round(bucketing.padding_waste_frac(
                  [packed_by_model[id(m)][1] for m in models]), 4),
              waste_by_axis=bucketing.waste_by_axis(row_axes))
    metrics.counter("bucket_sweeps").inc()
    return out


def full_compute(evaluate, out_keys=("PSD", "X0"), shard_freq=False):
    """The per-shard compute callable of the FULL checkpointed driver:
    ``compute(chunk_dict, mesh) -> dict`` padding the chunk to the
    device count and dispatching :func:`sweep_cases_full`.

    Module-level (not a driver-internal closure) so the serial runner
    and every fabric worker (:mod:`raft_tpu.parallel.fabric`) evaluate
    shards through the IDENTICAL code path — the N-worker sweep is
    bit-identical to the serial one by construction.  The evaluator's
    fabric entry stamp (``_raft_fabric_entry``) is propagated onto the
    returned callable so :func:`raft_tpu.parallel.resilience.
    run_checkpointed` can route the sweep onto the fabric."""
    def compute(chunk, mesh_):
        ndev = mesh_.devices.size
        pad = (-len(next(iter(chunk.values())))) % ndev
        if pad:
            chunk = {k: np.concatenate([v, np.repeat(v[-1:], pad, 0)])
                     for k, v in chunk.items()}
        return sweep_cases_full(evaluate, chunk, mesh=mesh_,
                                out_keys=out_keys, shard_freq=shard_freq)

    _stamp_fabric(compute, evaluate, out_keys, shard_freq=shard_freq)
    return compute


def case_compute(evaluate, out_keys=("PSD", "X0")):
    """Per-shard compute of the legacy (Hs, Tp, beta) checkpointed
    driver — see :func:`full_compute` for why this is module-level."""
    def compute(chunk, mesh_):
        ndev = mesh_.devices.size
        h, t, b = chunk["Hs"], chunk["Tp"], chunk["beta"]
        pad = (-len(h)) % ndev  # pad the tail shard to the device count
        if pad:
            h = np.concatenate([h, np.full(pad, h[-1])])
            t = np.concatenate([t, np.full(pad, t[-1])])
            b = np.concatenate([b, np.full(pad, b[-1])])
        return sweep_cases(evaluate, h, t, b, mesh=mesh_, out_keys=out_keys)

    _stamp_fabric(compute, evaluate, out_keys)
    return compute


def _routes_to_fabric(evaluate):
    """True when the checkpointed runner will hand this sweep to the
    worker fabric — mirrors the routing condition in
    ``resilience.run_checkpointed`` so the drivers can skip resolving
    a mesh (and the jax backend init it costs) in a coordinator that
    never dispatches a program itself."""
    from raft_tpu.utils import config

    return (int(config.get("FABRIC_WORKERS") or 0) > 1
            and getattr(evaluate, "_raft_fabric_entry", None) is not None)


def _stamp_fabric(compute, evaluate, out_keys, shard_freq=False):
    """Copy the evaluator's fabric entry spec onto its compute closure,
    folding in the call-time sweep arguments (out_keys, shard_freq) so
    a worker's entry rebuilds the SAME sweep the caller requested.  An
    evaluator without a stamp simply cannot run on the fabric (the
    ledger ships an importable entry, never a pickled closure)."""
    spec = getattr(evaluate, "_raft_fabric_entry", None)
    if not spec:
        return
    compute._raft_fabric_entry = {
        "entry": spec["entry"],
        "kwargs": {**(spec.get("kwargs") or {}),
                   "out_keys": list(out_keys),
                   "shard_freq": bool(shard_freq)},
        "warmup": spec.get("warmup"),
    }


def run_sweep_checkpointed_full(evaluate, cases, out_dir, shard_size=256,
                                mesh=None, out_keys=("PSD", "X0"),
                                shard_freq=False, on_shard=None,
                                max_retries=3, backoff_s=0.5,
                                quarantine_retry=True):
    """Checkpointed full-physics sweep over a case/design dict.

    Generalizes :func:`run_sweep_checkpointed` to the full evaluator's
    case dict (VERDICT r2 weak #5): each shard of the (N,)-array batch
    runs as one sharded program and lands in ``shard_NNNN.npz``;
    re-running skips completed shards (resume after preemption).

    ``on_shard(done, total, fresh)``: optional progress callback after
    each shard (``fresh`` False when the shard was resumed from disk) —
    lets long sweeps persist incremental summaries so a preempted run
    still leaves an auditable artifact.

    Fault tolerance (see :mod:`raft_tpu.parallel.resilience` and the
    README "Fault tolerance" section): shard files are written
    atomically and validated on resume (a truncated/corrupt/stale shard
    is recomputed, not crashed on); ``manifest.json`` fingerprints the
    inputs so resuming with changed cases/out_keys/shard_size raises
    :class:`~raft_tpu.parallel.resilience.ManifestMismatchError`;
    transient evaluator errors retry with exponential backoff
    (``max_retries``/``backoff_s``), device OOM halves the shard batch;
    non-finite rows are quarantined to ``quarantine.json`` (after an
    optional solo CPU re-evaluation, ``quarantine_retry``) instead of
    silently poisoning downstream aggregates.
    """
    from raft_tpu.parallel import resilience

    if not _routes_to_fabric(evaluate):
        from raft_tpu.utils.devices import enable_compile_cache

        enable_compile_cache()
        if mesh is None:
            mesh = resilience.resolve_mesh(make_mesh)

    return resilience.run_checkpointed(
        full_compute(evaluate, out_keys=out_keys, shard_freq=shard_freq),
        cases, out_dir, shard_size, mesh, out_keys,
        on_shard=on_shard, max_retries=max_retries, backoff_s=backoff_s,
        quarantine_retry=quarantine_retry)


def qtf_slender_sharded(model, waveHeadInd=0, Xi0=None, ifowt=0, mesh=None):
    """Slender-body QTF with the w1 x w2 PAIR axis physically
    partitioned over the device mesh (SURVEY §5.8: the QTF grid is the
    2nd-order context-parallel axis; min_freq2nd-driven grids reach
    thousands of bins, examples/OC4semi-RAFT_QTF.yaml:6-7).

    Mirrors :func:`raft_tpu.physics.qtf_slender.fowt_qtf_slender` but
    evaluates the upper-triangle pair forces through a jit whose pair
    index arrays (and outputs) are sharded over ALL mesh devices; the
    static Pinkster-IV and Kim & Yue terms stay host-side.

    Returns qtf (nw2, nw2, 1, nDOF) complex, bitwise-compatible with
    the unsharded path.
    """
    from raft_tpu.physics.qtf_slender import kim_yue_correction, member_qtf

    fs = model.fowtList[ifowt]
    fh = model.hydro[ifowt]
    stat = model.statics(ifowt)
    w2nd, k2nd = model.w1_2nd, model.k1_2nd
    nw2 = len(w2nd)
    nDOF = fs.nDOF
    beta = fh.beta[waveHeadInd]
    if mesh is None:
        mesh = make_mesh()
    ndev = mesh.devices.size
    flat_spec = NamedSharding(mesh, P(mesh.axis_names))

    if Xi0 is None:
        Xi0 = np.zeros((nDOF, model.nw), dtype=np.complex128)
    Xi = np.zeros((nDOF, nw2), dtype=np.complex128)
    for i in range(nDOF):
        Xi[i] = np.interp(w2nd, model.w, Xi0[i], left=0, right=0)

    # pair axis, padded to the device count and physically partitioned
    idx1, idx2 = np.triu_indices(nw2)
    npairs = len(idx1)
    pad = (-npairs) % ndev
    i1 = jax.device_put(jnp.asarray(np.concatenate([idx1, idx1[:1].repeat(pad)])),
                        flat_spec)
    i2 = jax.device_put(jnp.asarray(np.concatenate([idx2, idx2[:1].repeat(pad)])),
                        flat_spec)

    a_i_all = np.asarray(fh.hc0["a_i"])
    members, ofs = [], 0
    for mem in fs.members:
        members.append((mem, a_i_all[ofs:ofs + mem.ns]))
        ofs += mem.ns

    def all_members(i1_, i2_):
        F = jnp.zeros((i1_.shape[0], 6), dtype=compute_dtypes()[1])
        for mem, a_i_m in members:
            F = F + member_qtf(mem, a_i_m, Xi[:6], beta, w2nd, k2nd,
                               fs.depth, fs.rho_water, fs.g,
                               pair_idx=(i1_, i2_))
        return F

    fn = jax.jit(all_members, in_shardings=(flat_spec, flat_spec),
                 out_shardings=flat_spec)
    Fpairs = np.asarray(fn(i1, i2))[:npairs]

    qtf = np.zeros((nw2, nw2, 1, nDOF), dtype=np.complex128)
    qtf[idx1, idx2, 0, :6] = Fpairs

    # Pinkster IV rotation term: one blocked broadcast, not an
    # O(nw2^2) Python loop (large min_freq2nd grids)
    from raft_tpu.physics.qtf_slender import pinkster_iv

    F1st = np.asarray(stat["M_struc"]) @ (-(np.asarray(w2nd) ** 2) * Xi)
    qtf[:, :, 0, :6] += pinkster_iv(Xi, F1st)

    for mem, _ in members:
        qtf[:, :, 0, :6] += kim_yue_correction(
            mem, beta, w2nd, k2nd, fs.depth, fs.rho_water, fs.g)

    for i in range(nDOF):
        q_ = qtf[:, :, 0, i]
        qtf[:, :, 0, i] = q_ + np.conj(q_).T - np.diag(np.diag(np.conj(q_)))
    return qtf


def run_sweep_checkpointed(evaluate, Hs, Tp, beta, out_dir, shard_size=256,
                           mesh=None, out_keys=("PSD", "X0"),
                           on_shard=None, max_retries=3, backoff_s=0.5,
                           quarantine_retry=True):
    """Large design/case sweep with per-shard checkpointing and resume.

    The reference has no checkpoint/resume story for sweeps (SURVEY.md
    §5.4); here each shard of the batch is evaluated as one sharded
    program and written to ``<out_dir>/shard_NNNN.npz`` — re-running
    skips completed shards, so a pre-empted pod job resumes where it
    stopped.  Returns the dict of concatenated results.

    Shares the fault-tolerant runtime of
    :func:`run_sweep_checkpointed_full` (atomic writes, manifest
    validation, retry/backoff, OOM halving, NaN quarantine) via
    :mod:`raft_tpu.parallel.resilience`.
    """
    from raft_tpu.parallel import resilience
    from raft_tpu.utils.devices import enable_compile_cache

    if not _routes_to_fabric(evaluate):
        enable_compile_cache()
        if mesh is None:
            mesh = resilience.resolve_mesh(make_mesh)
    cases = {"Hs": np.asarray(Hs), "Tp": np.asarray(Tp),
             "beta": np.asarray(beta)}

    return resilience.run_checkpointed(
        case_compute(evaluate, out_keys=out_keys),
        cases, out_dir, shard_size, mesh, out_keys,
        on_shard=on_shard, max_retries=max_retries, backoff_s=backoff_s,
        quarantine_retry=quarantine_retry)
