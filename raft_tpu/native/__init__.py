"""ctypes bindings for the native (C++) kernels.

The shared library is built from ``native/panel_bem.cpp`` (CMake or a
one-line g++ invocation); if no prebuilt ``.so`` is found next to the
sources it is compiled on first use.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "panel_bem.cpp")
_LIB = os.path.join(_REPO, "native", "libpanel_bem.so")

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        subprocess.check_call(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC]
        )
    _lib = ctypes.CDLL(_LIB)
    _lib.panel_radiation_added_mass.restype = ctypes.c_int
    _lib.panel_radiation_added_mass.argtypes = [
        ctypes.c_int,
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
        ctypes.c_int,
        ctypes.c_double,
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
    ]
    return _lib


def radiation_added_mass(vertices, centroids, normals, areas, mirror=-1,
                         rho=1025.0, ref=(0.0, 0.0, 0.0)):
    """6x6 frequency-limit added-mass matrix from the native panel solver.

    mirror = -1 : high-frequency free surface (phi = 0) -> A(w->inf)
    mirror = +1 : rigid lid -> A(w->0)
    """
    lib = _load()
    n = len(areas)
    A = np.zeros(36)
    rc = lib.panel_radiation_added_mass(
        n,
        np.ascontiguousarray(vertices, dtype=np.float64).reshape(-1),
        np.ascontiguousarray(centroids, dtype=np.float64).reshape(-1),
        np.ascontiguousarray(normals, dtype=np.float64).reshape(-1),
        np.ascontiguousarray(areas, dtype=np.float64),
        int(mirror),
        float(rho),
        np.ascontiguousarray(ref, dtype=np.float64),
        A,
    )
    if rc != 0:
        raise RuntimeError("panel radiation solve failed (singular system)")
    return A.reshape(6, 6)
