"""ctypes bindings for the native (C++) kernels.

The shared library is built from ``native/panel_bem.cpp`` (CMake or a
one-line g++ invocation); if no prebuilt ``.so`` is found next to the
sources it is compiled on first use.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO, "native", "panel_bem.cpp")
_LIB = os.path.join(_REPO, "native", "libpanel_bem.so")

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        subprocess.check_call(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC]
        )
    _lib = ctypes.CDLL(_LIB)
    _lib.panel_radiation_added_mass.restype = ctypes.c_int
    _lib.panel_radiation_added_mass.argtypes = [
        ctypes.c_int,
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
        ctypes.c_int,
        ctypes.c_double,
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS"),
    ]
    return _lib


def _load_freq(lib):
    if getattr(lib, "_freq_ready", False):
        return
    dbl = lambda: np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
    lib.panel_solve_frequency.restype = ctypes.c_int
    lib.panel_solve_frequency.argtypes = [
        ctypes.c_int, dbl(), dbl(), dbl(), dbl(),             # mesh
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        dbl(),                                                # ref
        ctypes.c_int, dbl(),                                  # headings
        ctypes.c_int, ctypes.c_int, dbl(), dbl(), dbl(), dbl(),  # tables
        dbl(), dbl(), dbl(),                                  # outputs
    ]
    lib.panel_solve_frequency_fd.restype = ctypes.c_int
    lib.panel_solve_frequency_fd.argtypes = [
        ctypes.c_int, dbl(), dbl(), dbl(), dbl(),             # mesh
        ctypes.c_double, ctypes.c_double, ctypes.c_double, ctypes.c_double,
        dbl(),                                                # ref
        ctypes.c_int, dbl(),                                  # headings
        ctypes.c_int, ctypes.c_double, dbl(), dbl(),          # modes
        dbl(), dbl(), dbl(),                                  # outputs
    ]
    lib._freq_ready = True


def _fd_mode_count(depth, areas, n_modes, n_cap=16384):
    """Evanescent mode count that keeps the C++ kernel's small-R
    extrapolation cutoff Rc = 40 h / (pi n_modes) at or below HALF the
    panel edge scale (sqrt of the smallest panel area), so near-field
    accuracy tracks mesh refinement: on fine meshes or shallow sites the
    default 512 modes put Rc above the panel spacing and every
    near-diagonal wave influence (incl. the self term) came from the
    quadratic-in-R^2 extrapolation.  The kernel's per-pair adaptive
    cutoff (K0 decay) means the extra modes only cost work on the
    near-diagonal pairs that need them."""
    import warnings

    d_panel = float(np.sqrt(np.min(np.asarray(areas))))
    need = int(np.ceil(80.0 * depth / (np.pi * max(d_panel, 1e-9))))
    if need <= n_modes:
        return n_modes
    if need > n_cap:
        warnings.warn(
            f"finite-depth Green function: {need} evanescent modes needed "
            f"to resolve panel spacing {d_panel:.3g} m at depth {depth:.3g} "
            f"m exceeds the cap {n_cap}; near-diagonal influences use the "
            "smooth-remainder extrapolation below "
            f"Rc={40.0 * depth / (np.pi * n_cap):.3g} m")
        return n_cap
    return need


def solve_bem_frequency(vertices, centroids, normals, areas, omega,
                        headings_rad=(0.0,), depth=np.inf, rho=1025.0,
                        g=9.81, ref=(0.0, 0.0, 0.0), n_modes=512):
    """Radiation + diffraction at one frequency from the native panel
    solver with the free-surface wave Green function.

    Finite depth with K h = omega^2 depth / g <= 6 solves the TRUE
    finite-depth problem: John's eigenfunction-series Green function
    (propagating cosh-profile mode + n_modes evanescent K0 modes,
    seabed no-flux satisfied exactly; prototype + PV-integral
    validation in :mod:`raft_tpu.native.green_fd`), with the dispersion
    roots solved here and passed to the C++ kernel.  For K h > 6 the
    finite- and infinite-depth kernels agree to ~e^{-2Kh} (< 1e-5) and
    the tabulated infinite-depth kernel is used at the finite-depth
    wavenumber — which also keeps the FD series' smooth-remainder
    small-R treatment inside its validity range k0 R_c << 1.

    Returns (A (6,6), B (6,6), X (nh, 6) complex).
    """
    lib = _load()
    _load_freq(lib)

    n = len(areas)
    nh = len(headings_rad)
    A = np.zeros(36)
    B = np.zeros(36)
    X = np.zeros(nh * 12)

    Kdeep = omega * omega / g
    if np.isfinite(depth) and Kdeep * depth <= 6.0:
        from raft_tpu.native.green_fd import _evan_coeffs, dispersion_roots

        K = omega * omega / g
        n_modes = _fd_mode_count(float(depth), areas, int(n_modes))
        k0, km = dispersion_roots(K, float(depth), int(n_modes))
        Cm = _evan_coeffs(km, K, float(depth))
        rc = lib.panel_solve_frequency_fd(
            n,
            np.ascontiguousarray(vertices, dtype=np.float64).reshape(-1),
            np.ascontiguousarray(centroids, dtype=np.float64).reshape(-1),
            np.ascontiguousarray(normals, dtype=np.float64).reshape(-1),
            np.ascontiguousarray(areas, dtype=np.float64),
            float(omega), float(rho), float(g), float(depth),
            np.ascontiguousarray(ref, dtype=np.float64),
            nh, np.ascontiguousarray(headings_rad, dtype=np.float64),
            int(n_modes), float(k0),
            np.ascontiguousarray(km), np.ascontiguousarray(Cm),
            A, B, X,
        )
    else:
        from raft_tpu.native.green_table import build_tables
        from raft_tpu.ops.waves import wave_number

        t = build_tables()
        if np.isfinite(depth):
            K = float(np.asarray(wave_number(np.asarray([omega]), depth,
                                             g=g))[0])
        else:
            K = Kdeep
        rc = lib.panel_solve_frequency(
            n,
            np.ascontiguousarray(vertices, dtype=np.float64).reshape(-1),
            np.ascontiguousarray(centroids, dtype=np.float64).reshape(-1),
            np.ascontiguousarray(normals, dtype=np.float64).reshape(-1),
            np.ascontiguousarray(areas, dtype=np.float64),
            float(K), float(omega), float(rho), float(g),
            np.ascontiguousarray(ref, dtype=np.float64),
            nh, np.ascontiguousarray(headings_rad, dtype=np.float64),
            len(t["lnd"]), len(t["alpha"]),
            np.ascontiguousarray(t["lnd"]), np.ascontiguousarray(t["alpha"]),
            np.ascontiguousarray(t["L"]).reshape(-1),
            np.ascontiguousarray(t["M"]).reshape(-1),
            A, B, X,
        )
    if rc != 0:
        raise RuntimeError("panel frequency solve failed (singular system)")
    Xc = X.reshape(nh, 6, 2)
    return A.reshape(6, 6), B.reshape(6, 6), Xc[..., 0] + 1j * Xc[..., 1]


def solve_bem(vertices, centroids, normals, areas, omegas,
              headings_deg=(0.0,), depth=np.inf, rho=1025.0, g=9.81,
              ref=(0.0, 0.0, 0.0), workers=None):
    """Frequency sweep: A (6,6,nw), B (6,6,nw), X (nh, 6, nw) complex.

    The native calcBEM-equivalent (reference runs pyHAMS here,
    raft_fowt.py:1288-1442).  Frequencies are independent dense solves;
    they run in a thread pool (the ctypes call releases the GIL)."""
    import concurrent.futures as cf
    import os as _os

    omegas = np.asarray(omegas, dtype=float)
    nh = len(headings_deg)
    heads = np.deg2rad(np.asarray(headings_deg, dtype=float))
    A = np.zeros((6, 6, len(omegas)))
    B = np.zeros((6, 6, len(omegas)))
    X = np.zeros((nh, 6, len(omegas)), dtype=np.complex128)

    # table built once up front (not thread-safe lazily)
    from raft_tpu.native.green_table import build_tables
    build_tables()
    _load_freq(_load())

    def one(iw):
        A[:, :, iw], B[:, :, iw], X[:, :, iw] = solve_bem_frequency(
            vertices, centroids, normals, areas, omegas[iw], heads,
            depth, rho, g, ref)

    workers = workers or min(8, max(1, (_os.cpu_count() or 2) - 1))
    with cf.ThreadPoolExecutor(max_workers=workers) as ex:
        list(ex.map(one, range(len(omegas))))
    return A, B, X


def radiation_added_mass(vertices, centroids, normals, areas, mirror=-1,
                         rho=1025.0, ref=(0.0, 0.0, 0.0)):
    """6x6 frequency-limit added-mass matrix from the native panel solver.

    mirror = -1 : high-frequency free surface (phi = 0) -> A(w->inf)
    mirror = +1 : rigid lid -> A(w->0)
    """
    lib = _load()
    n = len(areas)
    A = np.zeros(36)
    rc = lib.panel_radiation_added_mass(
        n,
        np.ascontiguousarray(vertices, dtype=np.float64).reshape(-1),
        np.ascontiguousarray(centroids, dtype=np.float64).reshape(-1),
        np.ascontiguousarray(normals, dtype=np.float64).reshape(-1),
        np.ascontiguousarray(areas, dtype=np.float64),
        int(mirror),
        float(rho),
        np.ascontiguousarray(ref, dtype=np.float64),
        A,
    )
    if rc != 0:
        raise RuntimeError("panel radiation solve failed (singular system)")
    return A.reshape(6, 6)
