"""Farm wake coupling and AEP (the FLORIS-coupling capability).

The reference couples RAFT to the external FLORIS package
(``/root/reference/raft/raft_model.py``: ``florisCoupling`` :1956,
``florisFindEquilibrium`` :2055, ``florisCalcAEP`` :2219): FLORIS
computes waked rotor-averaged wind speeds at the (offset-displaced)
turbine positions, RAFT re-solves the array equilibrium with the waked
speeds, and the loop iterates until powers and positions converge.

FLORIS is not available in this image, so the wake model here is
built in: the Bastankhah & Porte-Agel (2014) Gaussian wake deficit
with sum-of-squares superposition and a front-to-back sweep — the same
class of model as FLORIS's default 'gauss' velocity model.  Thrust
coefficients come from the framework's OWN vmapped BEMT power/thrust
curve, so the whole coupling runs without external dependencies (and
the deficit math is plain vectorised numpy/jax, batchable over wind
rose states).

The position-feedback loop (platform drift changes turbine spacing
changes the wakes) mirrors florisFindEquilibrium's 0.9/0.1
under-relaxation and its power/position convergence checks.
"""

from __future__ import annotations

import numpy as np


def gaussian_deficit(dx, dy, D, Ct, TI):
    """Bastankhah-Porte-Agel Gaussian wake velocity deficit fraction at
    (dx, dy) downstream/crosswind of a turbine of diameter D.

    dx, dy : distances in the wind frame [m] (dx > 0 downstream)
    Ct     : thrust coefficient of the waking turbine
    TI     : turbulence intensity (fraction) — sets wake growth
    """
    dx = np.asarray(dx, dtype=float)
    dy = np.asarray(dy, dtype=float)
    kstar = 0.38 * TI + 0.004          # wake growth rate (Niayifar & Porte-Agel)
    Ct = np.clip(Ct, 1e-4, 0.999)
    eps = 0.2 * np.sqrt(0.5 * (1 + np.sqrt(1 - Ct)) / np.sqrt(1 - Ct))
    sigma_D = kstar * dx / D + eps      # sigma / D
    with np.errstate(invalid="ignore", divide="ignore"):
        radicand = 1.0 - Ct / (8.0 * sigma_D**2)
        C = 1.0 - np.sqrt(np.maximum(radicand, 0.0))
        deficit = C * np.exp(-(dy / D) ** 2 / (2.0 * sigma_D**2))
    return np.where(dx > 0.1 * D, deficit, 0.0)


def farm_velocities(xy, D, ct_funcs, U_inf, wind_heading_deg, TI):
    """Rotor-averaged waked wind speed per turbine.

    xy : (n, 2) turbine positions (global); D : (n,) rotor diameters;
    ct_funcs : list of callables U -> Ct; U_inf free-stream speed;
    wind_heading_deg : wind propagation heading (deg from +x, RAFT
    convention); TI turbulence intensity.

    Front-to-back sweep: upstream turbines' deficits (at each turbine's
    own waked speed) combine by sum of squares (Katic).
    Returns (U_eff (n,), Ct (n,)).
    """
    xy = np.asarray(xy, dtype=float)
    n = len(xy)
    b = np.deg2rad(wind_heading_deg)
    # wind-frame coordinates: x_w downstream, y_w crosswind
    ex = np.array([np.cos(b), np.sin(b)])
    ey = np.array([-np.sin(b), np.cos(b)])
    xw = xy @ ex
    yw = xy @ ey

    order = np.argsort(xw)
    U_eff = np.full(n, float(U_inf))
    Ct = np.zeros(n)
    for idx in order:
        dsq = 0.0
        for j in order:
            if xw[j] >= xw[idx] or Ct[j] <= 0:
                continue
            d = gaussian_deficit(xw[idx] - xw[j], yw[idx] - yw[j],
                                 D[j], Ct[j], TI) * (U_eff[j] / U_inf)
            dsq += float(d) ** 2
        U_eff[idx] = U_inf * (1.0 - np.sqrt(dsq))
        Ct[idx] = float(ct_funcs[idx](U_eff[idx]))
    return U_eff, Ct


class WakeCoupling:
    """Wake-coupled farm equilibrium + AEP on a Model
    (florisCoupling / florisFindEquilibrium / florisCalcAEP analog)."""

    def __init__(self, model, u_grid=None):
        from raft_tpu.drivers import power_thrust_curve

        self.model = model
        self.u_grid = np.asarray(
            u_grid if u_grid is not None else np.arange(3.0, 25.5, 0.5))
        # per-FOWT power/thrust curves from the vmapped BEMT
        self.curves = []
        for i, fs in enumerate(model.fowtList):
            if fs.nrotors == 0 or not model.rotor_aero:
                self.curves.append(None)
                continue
            pc = power_thrust_curve(model, self.u_grid, ifowt=i, ir=0)
            rprops = fs.rotors[0]
            R = model.rotor_aero[0].Rtip
            A = np.pi * R**2
            rho = model.rotor_aero[0].rho
            Ct = pc["thrust"] / (0.5 * rho * A * np.maximum(self.u_grid, 0.1) ** 2)
            self.curves.append(dict(D=2 * R, power=pc["power"], Ct=Ct))

    def _ct_fn(self, i):
        c = self.curves[i]
        return lambda U: np.interp(U, self.u_grid, c["Ct"], left=0, right=0)

    def _power(self, i, U):
        c = self.curves[i]
        return float(np.interp(U, self.u_grid, c["power"], left=0, right=0))

    def find_equilibrium(self, case, cutin=3.0, n_iter=100, power_tol=10.0,
                         pos_tol=0.01):
        """Wake/position fixed point for one case
        (florisFindEquilibrium, raft_model.py:2055-2218).

        Returns (winds, xpositions, ypositions, powers) iteration
        histories as arrays, reference-compatible."""
        import copy

        model = self.model
        n = model.nFOWT
        TI = float(np.atleast_1d(np.asarray(
            case.get("turbulence", 0.06), dtype=float))[0]) or 0.06
        U_inf = float(np.atleast_1d(np.asarray(case["wind_speed"],
                                               dtype=float))[0])
        heading = float(np.atleast_1d(np.asarray(
            case.get("wind_heading", 0.0), dtype=float))[0])
        D = np.array([c["D"] if c else 100.0 for c in self.curves])
        refs = np.array([[fs.x_ref, fs.y_ref] for fs in model.fowtList])

        case = copy.deepcopy(case)
        winds, xs, ys, powers = [], [], [], []
        offs = model.dof_offsets
        for it in range(n_iter):
            X = np.asarray(model.solve_statics(case))
            pos = np.stack([X[offs[i]:offs[i] + 2] for i in range(n)])
            if it > 0:
                pos = 0.9 * pos + 0.1 * np.c_[xs[-1], ys[-1]]
            U_eff, Ct = farm_velocities(
                pos, D, [self._ct_fn(i) for i in range(n)], U_inf, heading, TI)
            case["wind_speed"] = list(U_eff)
            winds.append(U_eff)
            xs.append(pos[:, 0])
            ys.append(pos[:, 1])
            if np.min(U_eff) > cutin:
                powers.append(np.array([self._power(i, U_eff[i])
                                        for i in range(n)]))
            else:
                powers.append(np.zeros(n))
            if it > 1:
                dp = np.max(np.abs(powers[-1] - powers[-2]))
                dx = np.max(np.abs(xs[-1] - xs[-2]))
                if (np.min(U_eff) > cutin and dp < power_tol and dx < pos_tol) \
                        or (np.min(U_eff) <= cutin and dx < pos_tol):
                    break
        return (np.array(winds), np.array(xs), np.array(ys), np.array(powers))

    def calc_aep(self, windspeeds, winddirs, probabilities, cutin=3.0,
                 cutout=25.0, TI=0.06, hours=8760.0, n_iter=30):
        """Probability-weighted AEP over a wind rose
        (florisCalcAEP, raft_model.py:2219-2245).

        Returns (powers per state (n_states, nFOWT) [W],
        aep per state [Wh], total AEP [Wh])."""
        model = self.model
        keys = model.design["cases"]["keys"]
        powers, aeps = [], []
        for ws, wd, pr in zip(windspeeds, winddirs, probabilities):
            if not (cutin <= ws <= cutout):
                powers.append(np.zeros(model.nFOWT))
                aeps.append(np.zeros(model.nFOWT))
                continue
            case = dict(zip(keys, [ws, wd, TI, "operating", 0,
                                   "JONSWAP", 0, 0, 0]))
            _, _, _, p_hist = self.find_equilibrium(case, cutin=cutin,
                                                    n_iter=n_iter)
            powers.append(p_hist[-1])
            aeps.append(p_hist[-1] * pr * hours)
        return np.array(powers), np.array(aeps), float(np.sum(aeps))
