"""The AOT program bank: serialize compiled XLA executables, reload
them in a fresh process without tracing or compiling.

Mechanism: ``jit(...).lower(*args).compile()`` produces a loaded
executable that :mod:`jax.experimental.serialize_executable` can
serialize/deserialize; deserialization emits NO
``backend_compile`` monitoring event, so a bank hit is invisible to
the recompile sentinel — exactly the property the cold-start budget
(``RAFT_TPU_COMPILE_BUDGET``) asserts.

Bank entries live under ``<RAFT_TPU_AOT_DIR>/v<FORMAT>/`` as a
``<key>.json`` metadata sidecar plus a ``<key>.bin`` pickled payload.
The key is a hash over everything that makes an executable valid to
run:

* the sweep memo key (kind, out_keys, case keys, mesh axis/device
  layout, and the trace-time ``RAFT_TPU_*`` flags from
  :func:`raft_tpu.parallel.sweep._flags_key`);
* the input avals (tree structure + shape/dtype/weak-type of every
  leaf) — a compiled program is shape-specialized;
* the **environment fingerprint** — backend platform, local device
  count/kind, ``jax_enable_x64`` — variants that legitimately coexist
  in one bank (a CPU-warmed bank does not answer for a TPU process);
* the **version fingerprint** — jax/jaxlib versions and a content hash
  of every ``raft_tpu`` source file (the cheap, trace-free stand-in
  for the jaxpr fingerprint: any code change invalidates the entry and
  forces a clean re-lower, never a stale execution).  The exact
  StableHLO hash of the lowered module is recorded in the metadata at
  store time for audit (``python -m raft_tpu.aot list/verify``).

Because staleness is part of the key, the load path never has to
*judge* an entry — a stale one simply never matches and becomes gc
fodder (``python -m raft_tpu.aot gc``).  Corruption is caught by a
stored payload sha256 checked before unpickling.

Modes (``RAFT_TPU_AOT``, re-read per dispatch like every flag):

* ``off`` — bank untouched; plain jit dispatch (the default);
* ``load`` — consult the bank first; on a miss, lower + compile as
  usual and export the result so the NEXT process loads it;
* ``require`` — consult the bank; a miss raises
  :class:`BankMissError` (or logs and compiles, with
  ``RAFT_TPU_AOT_MISS=compile``) — serving mode, where an unwarmed
  key is an operational bug, not a 33-second stall.

Every load/miss/store feeds the :mod:`raft_tpu.obs.metrics` registry
(``aot_programs_loaded`` / ``aot_bank_misses`` /
``aot_programs_compiled`` / ``aot_bank_errors``) and the structured
log (events ``aot_load`` / ``aot_miss`` / ``aot_store`` /
``aot_error``), so sweep manifests and the bench breakdown can state
"N bank loads, 0 compiles" instead of inferring it.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time

from raft_tpu.obs import metrics
from raft_tpu.utils import config
from raft_tpu.utils.structlog import log_event

#: bump when the on-disk layout or payload format changes — old
#: directories are simply never read (and ``gc`` removes them)
BANK_FORMAT = 1

_META_SUFFIX = ".json"
_BIN_SUFFIX = ".bin"


class BankMissError(RuntimeError):
    """``RAFT_TPU_AOT=require`` and the program bank has no entry for
    this key (run ``python -m raft_tpu.aot warmup`` first, or warm the
    bank with one ``RAFT_TPU_AOT=load`` run of the same workload)."""


def mode():
    """Current bank mode (off | load | require), re-read per call."""
    return config.get("AOT")


def bank_dir():
    """The versioned bank directory for the current format."""
    return os.path.join(config.get("AOT_DIR"), f"v{BANK_FORMAT}")


# --------------------------------------------------------------- fingerprints

_CODE_FP_CACHE: dict = {}


def code_fingerprint():
    """Content hash over every ``raft_tpu`` source file.

    The trace-free proxy for the jaxpr fingerprint: any edit anywhere
    in the package changes the key, so a bank entry can never serve a
    program the current code would not have produced.  Coarse on
    purpose — a false invalidation costs one re-lower, a false hit
    would silently run old physics.  Cached per process (~100 files,
    single-digit milliseconds)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root in _CODE_FP_CACHE:
        return _CODE_FP_CACHE[root]
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        if "__pycache__" in dirpath:
            continue
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            h.update(os.path.relpath(path, root).encode())
            with open(path, "rb") as f:
                h.update(f.read())
    fp = h.hexdigest()[:16]
    _CODE_FP_CACHE[root] = fp
    return fp


def content_fingerprint(obj):
    """Deterministic hash of a nested plain-data structure (dicts,
    lists/tuples, scalars, strings, numpy/jax arrays) — the *program
    identity* stamp.

    The bank key's flag/aval/code fingerprints cover everything except
    the data a traced closure baked in as constants: two models whose
    sweeps share kind/out_keys/mesh/shapes would otherwise collide on
    one entry and silently serve each other's physics.  Evaluator
    factories therefore stamp ``evaluate._raft_program_key`` with a
    hash of the design content (plus factory arguments), and the sweep
    funnel refuses to bank closures that carry no stamp."""
    import numpy as np

    h = hashlib.sha256()

    def walk(o):
        if o is None or isinstance(o, (bool, int, float, complex,
                                       str, bytes)):
            h.update(repr(o).encode())
        elif isinstance(o, np.ndarray):
            if o.dtype == object:
                # tobytes() on an object array would hash the POINTERS
                # — different every process, a key that can never hit
                walk(o.tolist())
            else:
                h.update(str((o.dtype, o.shape)).encode())
                h.update(np.ascontiguousarray(o).tobytes())
        elif isinstance(o, np.generic):
            h.update(repr(o.item()).encode())
        elif isinstance(o, dict):
            h.update(b"{")
            for k in sorted(o, key=repr):
                walk(k)
                walk(o[k])
            h.update(b"}")
        elif isinstance(o, (list, tuple)):
            h.update(b"[")
            for v in o:
                walk(v)
            h.update(b"]")
        else:
            # arbitrary objects: np.asarray would "succeed" as a 0-d
            # object array (pointer bytes again), so only numeric
            # coercions count; everything else degrades to type
            # identity — deterministic, but blind to content, so stamp
            # explicit keys for such objects
            try:
                arr = np.asarray(o)
            except Exception:
                arr = None
            if arr is not None and arr.dtype != object:
                walk(arr)
            else:
                h.update(repr(type(o)).encode())

    walk(obj)
    return h.hexdigest()[:16]


def file_fingerprint(path):
    """Content hash of one source file — for traced code living
    OUTSIDE the ``raft_tpu`` package (bench.py, sweep_10k.py, user
    sweep scripts), which :func:`code_fingerprint` cannot see: mix
    this into the program stamp so an edit there misses the bank."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()[:16]


def program_key(evaluate):
    """The evaluator's bank identity stamp, or None when it has none
    (unstamped closures are never banked — see
    :func:`content_fingerprint`)."""
    return getattr(evaluate, "_raft_program_key", None)


def version_fingerprint():
    """Toolchain identity: entries from another jax/jaxlib or another
    state of the raft_tpu sources are dead (gc'd), not variants."""
    import jax
    import jaxlib

    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__,
            "code": code_fingerprint(), "format": BANK_FORMAT}


def environment_fingerprint():
    """Runtime identity: legitimate coexisting variants of one bank
    (platform, device topology, x64 mode) — never grounds for gc."""
    import jax

    devs = jax.devices()
    return {"platform": devs[0].platform,
            "device_kind": devs[0].device_kind,
            "n_devices": len(devs),
            "x64": bool(jax.config.jax_enable_x64)}


def _aval_sig(args):
    """Canonical signature of the dispatch arguments: tree structure
    plus (shape, dtype, weak_type) per leaf.  Compiled executables are
    specialized to exactly this."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = tuple(
        (str(getattr(x, "shape", ())), str(getattr(x, "dtype", type(x))),
         bool(getattr(getattr(x, "aval", None), "weak_type", False)))
        for x in leaves)
    return (str(treedef), sig)


def entry_key(kind, memo_key, args):
    """(hash, metadata) for one bank entry.  The hash covers every
    validity condition, so lookup is a pure existence check."""
    ver = version_fingerprint()
    env = environment_fingerprint()
    treedef, avals = _aval_sig(args)
    ident = repr((BANK_FORMAT, kind, memo_key, treedef, avals,
                  sorted(ver.items()), sorted(env.items())))
    key = hashlib.sha256(ident.encode()).hexdigest()[:24]
    meta = {
        "format": BANK_FORMAT,
        "kind": kind,
        "key": key,
        "memo_key": repr(memo_key),
        "treedef": treedef,
        "avals": [list(a) for a in avals],
        "version": ver,
        "environment": env,
    }
    return key, meta


def _paths(key):
    d = bank_dir()
    return (os.path.join(d, key + _META_SUFFIX),
            os.path.join(d, key + _BIN_SUFFIX))


def _atomic_write(path, data):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# ------------------------------------------------------------ cost ledger

#: key -> running cost/dispatch stats of every program this process
#: loaded or compiled through the bank: the device-cost ledger behind
#: ``obs report``'s per-program table, ``/healthz`` and the bench
#: blocks.  Populated at load/store time from ``cost_analysis`` (the
#: sidecar is authoritative for loads — a deserialized executable may
#: refuse the query), updated per dispatch by :class:`BankedProgram`.
#: Guarded by ``_STATS_LOCK``: the batcher tick thread mutates it while
#: ``/healthz`` (asyncio thread) iterates ``ledger_summary``.
_STATS_LOCK = threading.Lock()
PROGRAM_STATS: dict[str, dict] = {}  # raft-lint: guarded-by=_STATS_LOCK


def cost_analysis_dict(compiled, args=None):
    """Normalized ``compiled.cost_analysis()``: ``{"flops",
    "bytes_accessed", "out_bytes", "transcendentals", "arg_bytes"}``
    (numeric, finite; absent keys omitted).  ``{}`` when the backend
    refuses the query — the ledger is telemetry, never a dispatch
    gate."""
    out = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        for src, dst in (("flops", "flops"),
                         ("bytes accessed", "bytes_accessed"),
                         ("bytes accessedout{}", "out_bytes"),
                         ("transcendentals", "transcendentals")):
            v = ca.get(src)
            if isinstance(v, (int, float)) and v == v and v >= 0:
                out[dst] = float(v)
    except Exception:
        pass
    if args is not None:
        try:
            import jax
            import numpy as np

            out["arg_bytes"] = int(sum(
                int(np.prod(getattr(x, "shape", ()) or (1,)))
                * np.dtype(getattr(x, "dtype", type(x))).itemsize
                for x in jax.tree_util.tree_leaves(args)))
        except Exception:
            pass
    return out


def record_cost(kind, key, cost, source):
    """Fold one program's cost block into the in-process ledger and the
    event stream (``program_cost``).  Idempotent per key."""
    if not cost:
        return
    with _STATS_LOCK:
        st = PROGRAM_STATS.setdefault(
            key, {"kind": kind, "dispatches": 0, "wall_s": 0.0})
        st.update(cost)
    log_event("program_cost", kind=kind, key=key, source=source,
              **{k: cost[k] for k in ("flops", "bytes_accessed",
                                      "arg_bytes", "transcendentals")
                 if k in cost})


def record_dispatch(key, wall_s):
    """One execution of a ledgered program: update its dispatch count /
    wall totals and the process-wide achieved-GFLOP/s + utilization
    metrics (vs ``RAFT_TPU_PEAK_TFLOPS``)."""
    with _STATS_LOCK:
        st = PROGRAM_STATS.get(key)
        if st is None:
            return
        st["dispatches"] += 1
        st["wall_s"] += wall_s
        # best-observed wall: the noise-robust signal the serve
        # batch-ladder tuner prefers over the mean (a single scheduler
        # pause must not mis-shape a ladder for the server's lifetime)
        if wall_s < st.get("wall_min_s", float("inf")):
            st["wall_min_s"] = wall_s
        flops = st.get("flops")
        kind = st.get("kind")
    metrics.counter("program_dispatches").inc()
    if not flops or wall_s <= 0:
        return
    gflops = flops / wall_s / 1e9
    peak = float(config.get("PEAK_TFLOPS")) * 1e3  # GFLOP/s
    util = gflops / peak if peak > 0 else None
    metrics.histogram("program_gflops_s").observe(gflops)
    if util is not None:
        metrics.histogram("program_utilization").observe(util)
    kw = {"gflops_s": round(gflops, 3)}
    if util is not None:
        kw["utilization"] = round(util, 6)
    log_event("program_dispatch", key=key, kind=kind,
              wall_s=round(wall_s, 6), **kw)


def program_stats(key):
    """Thread-safe snapshot of one ledgered program's running stats
    (``{}`` when the key has never been loaded/compiled through the
    bank) — the serve batch-ladder tuner reads measured dispatch walls
    through this instead of touching the locked dict."""
    with _STATS_LOCK:
        return dict(PROGRAM_STATS.get(key) or {})


def ledger_summary():
    """JSON-ready per-program ledger rows (``/healthz``, the bench
    serve/fabric blocks, fabric worker status files): key, kind, flops,
    dispatches, and the dispatch-weighted mean achieved GFLOP/s."""
    with _STATS_LOCK:
        stats = {k: dict(v) for k, v in PROGRAM_STATS.items()}
    rows = []
    for key, st in sorted(stats.items()):
        row = {"key": key, "kind": st.get("kind"),
               "dispatches": st["dispatches"],
               "wall_s": round(st["wall_s"], 4)}
        for k in ("flops", "bytes_accessed", "arg_bytes", "out_bytes"):
            if k in st:
                row[k] = st[k]
        flops = st.get("flops")
        if flops and st["wall_s"] > 0 and st["dispatches"]:
            # 6/9 decimals: toy/bench programs legitimately achieve
            # micro-GFLOP/s rates that 3 decimals would round to 0
            row["gflops_s_mean"] = round(
                flops * st["dispatches"] / st["wall_s"] / 1e9, 6)
            peak = float(config.get("PEAK_TFLOPS")) * 1e3
            if peak > 0:
                row["utilization_mean"] = round(
                    row["gflops_s_mean"] / peak, 9)
        rows.append(row)
    return rows


def merge_ledgers(row_lists):
    """Fold several :func:`ledger_summary` row lists (e.g. every fabric
    worker's published ledger) into one fleet-wide view: dispatches and
    wall sum per key, the dispatch-weighted mean GFLOP/s recomputed.
    Garbled rows are skipped — telemetry pooling must never crash."""
    merged: dict[str, dict] = {}
    for rows in row_lists:
        for row in rows or ():
            try:
                key = row["key"]
                m = merged.setdefault(
                    key, {"key": key, "kind": row.get("kind"),
                          "dispatches": 0, "wall_s": 0.0})
                m["dispatches"] += int(row.get("dispatches") or 0)
                m["wall_s"] += float(row.get("wall_s") or 0.0)
                for k in ("flops", "bytes_accessed", "arg_bytes",
                          "out_bytes"):
                    if k in row:
                        m[k] = row[k]
            except (KeyError, TypeError, ValueError):
                continue
    for m in merged.values():
        flops = m.get("flops")
        if flops and m["wall_s"] > 0 and m["dispatches"]:
            m["gflops_s_mean"] = round(
                flops * m["dispatches"] / m["wall_s"] / 1e9, 6)
        m["wall_s"] = round(m["wall_s"], 4)
    return [merged[k] for k in sorted(merged)]


# ------------------------------------------------------------------ load/store

_NATIVE_CALLBACKS_ARMED = [False]


def _arm_native_callbacks():
    """Bind the CPU custom-call kernels a deserialized executable may
    reference.  jax registers the LAPACK/BLAS custom-call *targets* at
    ``jaxlib.lapack`` import, but the kernel function pointers behind
    them are only bound by ``_lapack.initialize()`` — which normally
    happens lazily at LOWERING time (``prepare_lapack_call``).  A bank
    hit never lowers anything, so a fresh process would execute e.g.
    ``blas_dtrsm`` through an uninitialized trampoline and segfault
    (observed: any program containing ``jnp.linalg.solve``).  Arm them
    once, before the first deserialization; a few ms, idempotent."""
    if _NATIVE_CALLBACKS_ARMED[0]:
        return
    try:
        import jaxlib.lapack  # noqa: F401  (registers the targets)
        from jaxlib.cpu import _lapack

        _lapack.initialize()  # binds the BLAS/LAPACK kernel pointers
    except Exception:  # other backends / future jaxlib layouts
        pass
    _NATIVE_CALLBACKS_ARMED[0] = True


def peek(kind, memo_key, args):
    """The entry's metadata dict when the bank holds this program,
    else None — a pure file check (no deserialization, no counters),
    for callers budgeting wall time around a potential miss (e.g. the
    bench breakdown heuristics)."""
    key, _ = entry_key(kind, memo_key, args)
    meta_path, bin_path = _paths(key)
    if not (os.path.exists(meta_path) and os.path.exists(bin_path)):
        return None
    try:
        with open(meta_path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def lookup(kind, memo_key, args):
    """Deserialize the banked executable for (kind, memo_key, avals),
    or None on miss.  Corrupt/unreadable entries are logged, counted
    (``aot_bank_errors``) and treated as misses — never a crash."""
    key, _ = entry_key(kind, memo_key, args)
    meta_path, bin_path = _paths(key)
    if not (os.path.exists(meta_path) and os.path.exists(bin_path)):
        return None
    t0 = time.perf_counter()
    try:
        with open(meta_path, encoding="utf-8") as f:
            meta = json.load(f)
        with open(bin_path, "rb") as f:
            buf = f.read()
        if meta.get("payload_sha256") != hashlib.sha256(buf).hexdigest():
            raise ValueError("payload sha256 mismatch (truncated or "
                             "externally modified .bin)")
        from jax.experimental import serialize_executable

        _arm_native_callbacks()
        compiled = serialize_executable.deserialize_and_load(
            *pickle.loads(buf))
    except Exception as e:  # corrupt entry: miss, loudly
        metrics.counter("aot_bank_errors").inc()
        log_event("aot_error", kind=kind, key=key, error=repr(e)[:300])
        return None
    wall = time.perf_counter() - t0
    metrics.counter("aot_programs_loaded").inc()
    log_event("aot_load", kind=kind, key=key, bytes=len(buf),
              wall_s=round(wall, 4))
    # the sidecar's cost block is authoritative (recorded at export);
    # entries predating the ledger fall back to querying the
    # deserialized executable, which may refuse — then no ledger row
    record_cost(kind, key,
                meta.get("cost_analysis") or cost_analysis_dict(compiled,
                                                                args),
                source="load")
    return compiled


def _compile_fresh(lowered):
    """Compile bypassing the XLA persistent disk cache.

    An executable *retrieved* from the disk cache re-serializes into a
    payload missing its symbol definitions (observed on jaxlib 0.4.36
    CPU: a later deserialize fails with ``INTERNAL: Symbols not
    found``) — storing one would mint a poison bank entry.  One full
    compile is the honest price of a durable artifact; the entry then
    supersedes the disk cache for every future process.

    Flipping ``jax_enable_compilation_cache`` alone is NOT enough:
    ``compilation_cache.is_cache_used`` latches its decision in module
    globals at the first compile of the process, so once anything
    compiled with the cache on, the flag flip is ignored and the cache
    still answers (the self-check then rejects every export — a bank
    that can never be re-warmed while the XLA cache holds the program).
    ``reset_cache()`` clears the latch; a second reset afterwards lets
    the next ordinary compile re-latch with the cache enabled."""
    import jax
    from jax._src import compilation_cache

    if not jax.config.jax_enable_compilation_cache:
        return lowered.compile()
    jax.config.update("jax_enable_compilation_cache", False)
    compilation_cache.reset_cache()
    try:
        return lowered.compile()
    finally:
        jax.config.update("jax_enable_compilation_cache", True)
        compilation_cache.reset_cache()


def store(kind, memo_key, args, lowered, compiled, compile_s, cost=None):
    """Export a freshly-compiled executable into the bank (best
    effort: serialization failures are logged, never fatal).  The
    ``.bin`` payload lands before its ``.json`` sidecar — the loader
    requires both, so a crash between the writes leaves an orphan the
    ``gc``/``verify`` CLIs surface, not a half-entry that loads."""
    key, meta = entry_key(kind, memo_key, args)
    if cost is None:
        cost = cost_analysis_dict(compiled, args)
    try:
        from jax.experimental import serialize_executable

        buf = pickle.dumps(serialize_executable.serialize(compiled))
        # round-trip self-check: a payload that cannot deserialize NOW
        # (e.g. the executable secretly came from the XLA disk cache)
        # must never be committed for a future process to trip over
        serialize_executable.deserialize_and_load(*pickle.loads(buf))
        try:
            hlo_hash = hashlib.sha256(
                lowered.as_text().encode()).hexdigest()[:16]
        except Exception:
            hlo_hash = None
        meta.update(payload_sha256=hashlib.sha256(buf).hexdigest(),
                    payload_bytes=len(buf),
                    stablehlo_sha256=hlo_hash,
                    compile_s=round(float(compile_s), 3),
                    cost_analysis=cost,
                    created=time.time(),
                    raft_flags={k: config.get(k) for k in
                                ("SOLVER", "FIXED_POINT", "SCAN_CHUNK",
                                 "DTYPE", "COND_CHECK", "COND_THRESHOLD",
                                 "ITER_SCALE")})
        os.makedirs(bank_dir(), exist_ok=True)
        meta_path, bin_path = _paths(key)
        _atomic_write(bin_path, buf)
        _atomic_write(meta_path,
                      (json.dumps(meta, indent=1, sort_keys=True) + "\n")
                      .encode())
    except Exception as e:
        metrics.counter("aot_bank_errors").inc()
        log_event("aot_error", kind=kind, key=key, error=repr(e)[:300])
        return None
    log_event("aot_store", kind=kind, key=key, bytes=len(buf),
              compile_s=round(float(compile_s), 3))
    return bin_path


def _on_miss(kind, memo_key, args):
    """Account for a bank miss; in ``require`` mode this is where the
    sweep fails loudly (or, flag-controlled, falls back to a compile)."""
    m = mode()
    key, _ = entry_key(kind, memo_key, args)
    metrics.counter("aot_bank_misses").inc()
    log_event("aot_miss", kind=kind, key=key, mode=m)
    if m == "require" and config.get("AOT_MISS") == "error":
        raise BankMissError(
            f"AOT bank miss for {kind!r} key {key} under "
            f"RAFT_TPU_AOT=require (bank: {bank_dir()}).  Warm the bank "
            "with `python -m raft_tpu.aot warmup` or one "
            "RAFT_TPU_AOT=load run of this workload; set "
            "RAFT_TPU_AOT_MISS=compile to log and fall back instead.")


# ------------------------------------------------------------------ dispatch

def compile_or_load(fn, args, kind, memo_key=(), bankable=True):
    """AOT-compile ``fn`` for ``args`` through the bank.

    Returns ``(compiled, loaded, seconds)``: a ready-to-call loaded
    executable, whether it came from the bank, and the wall time of the
    load or lower+compile.  Used directly by ``bench.py`` (whose
    programs don't route through the sweep memo) and by
    :class:`BankedProgram` for everything that does.  ``bankable=False``
    keeps the explicit lower+compile+count behavior but never touches
    the bank (programs whose closed-over content has no identity in
    ``memo_key``)."""
    t0 = time.perf_counter()
    m = mode() if bankable else "off"
    if m != "off":
        exe = lookup(kind, memo_key, args)
        if exe is not None:
            return exe, True, time.perf_counter() - t0
        _on_miss(kind, memo_key, args)
    lowered = fn.lower(*args)
    # a miss that will be exported must compile for real — a disk-cache
    # retrieval is not serializable (see _compile_fresh)
    compiled = _compile_fresh(lowered) if m != "off" else lowered.compile()
    dt = time.perf_counter() - t0
    metrics.counter("aot_programs_compiled").inc()
    cost = cost_analysis_dict(compiled, args)
    key, _ = entry_key(kind, memo_key, args)
    record_cost(kind, key, cost, source="compile")
    if m != "off":
        store(kind, memo_key, args, lowered, compiled, dt, cost=cost)
    return compiled, False, dt


class BankedProgram:
    """The callable :func:`raft_tpu.parallel.sweep._cached_jit` memoizes:
    a jitted sweep wrapper fronted by the program bank.

    * ``RAFT_TPU_AOT=off``: transparent — dispatches the plain jitted
      function (built once), byte-for-byte the pre-bank behavior.
    * otherwise: per input-aval signature, the first dispatch loads the
      banked executable (no trace, no compile) or — on a miss —
      lowers, compiles, executes AND exports, so the next process
      loads.  Executables are cached in-process per aval signature
      (shard tails dispatch a second, smaller-batch program).
    """

    def __init__(self, kind, memo_key, build, bankable=True):
        self._kind = kind
        self._memo_key = memo_key
        self._build = build
        self._bankable = bankable
        self._warned_unbankable = False
        self._fn = None      # the jitted wrapper, built at most once
        self._execs = {}     # aval signature -> loaded executable

    def _jit(self):
        if self._fn is None:
            self._fn = self._build()
        return self._fn

    def __call__(self, *args):
        if mode() == "off":
            return self._jit()(*args)
        if not self._bankable:
            # a closure with no program-identity stamp cannot be
            # banked safely (cross-process keys would collide on
            # closed-over content) — say so once, then dispatch plain
            if not self._warned_unbankable:
                self._warned_unbankable = True
                log_event("aot_unbankable", kind=self._kind)
            return self._jit()(*args)
        sig = _aval_sig(args)
        ent = self._execs.get(sig)
        if ent is None:
            exe, _, _ = compile_or_load(self._jit(), args,
                                        self._kind, self._memo_key)
            key, _ = entry_key(self._kind, self._memo_key, args)
            ent = self._execs[sig] = (exe, key)
        exe, key = ent
        if key not in PROGRAM_STATS:
            return exe(*args)
        # cost-ledgered dispatch: block before reading the clock so the
        # achieved GFLOP/s is real execution, not async dispatch (the
        # callers all np.asarray the outputs right after anyway)
        import jax

        t0 = time.perf_counter()
        out = exe(*args)
        jax.block_until_ready(out)
        record_dispatch(key, time.perf_counter() - t0)
        return out


# ------------------------------------------------------- bank maintenance

def stray_tmp_files():
    """Leftover ``*.tmp`` files from interrupted :func:`_atomic_write`
    calls (a crash between write and ``os.replace``): never valid,
    invisible to :func:`scan`'s key pairing — ``verify`` notes them,
    ``gc`` removes them."""
    d = bank_dir()
    if not os.path.isdir(d):
        return []
    return [os.path.join(d, n) for n in sorted(os.listdir(d))
            if n.endswith(".tmp")]


def scan():
    """Yield ``(key, meta | None, meta_path, bin_path)`` for every
    entry in the bank directory; ``meta`` is None when the sidecar is
    missing or unparseable (orphan/corrupt)."""
    d = bank_dir()
    if not os.path.isdir(d):
        return
    names = sorted(os.listdir(d))
    keys = {n[:-len(_META_SUFFIX)] for n in names if n.endswith(_META_SUFFIX)}
    keys |= {n[:-len(_BIN_SUFFIX)] for n in names if n.endswith(_BIN_SUFFIX)}
    for key in sorted(keys):
        meta_path, bin_path = _paths(key)
        meta = None
        if os.path.exists(meta_path):
            try:
                with open(meta_path, encoding="utf-8") as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                meta = None
        yield key, meta, meta_path, bin_path


def read_meta(key):
    """One entry's sidecar metadata by bank key — or None when the
    entry (or its payload) is missing/unparseable.  Jax-free: the
    release machinery resolves manifest entries through this without
    initializing a backend."""
    meta_path, bin_path = _paths(key)
    try:
        with open(meta_path, encoding="utf-8") as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(meta, dict) or not os.path.exists(bin_path):
        return None
    return meta


def is_stale(meta):
    """True when an entry's version fingerprint no longer matches the
    running toolchain/sources (it can never be loaded again)."""
    return meta is None or meta.get("version") != version_fingerprint()


def verify_bank():
    """Integrity check for the bank directory (the ``verify`` CLI and
    the lint gate).  Returns ``(problems, notes, n_entries)``:
    ``problems`` fail CI (unparseable metadata, missing/orphaned/
    truncated payloads, checksum mismatches); ``notes`` are benign
    observations (stale entries awaiting gc, foreign-environment
    variants)."""
    problems, notes = [], []
    n = 0
    for key, meta, meta_path, bin_path in scan():
        n += 1
        if meta is None:
            if os.path.exists(meta_path):
                problems.append(f"{key}: metadata sidecar unparseable")
            else:
                problems.append(f"{key}: orphan payload (no .json sidecar "
                                "— interrupted store; gc removes it)")
            continue
        if not os.path.exists(bin_path):
            problems.append(f"{key}: metadata without payload (.bin missing)")
            continue
        size = os.path.getsize(bin_path)
        if size != meta.get("payload_bytes"):
            problems.append(
                f"{key}: payload is {size} bytes, metadata promises "
                f"{meta.get('payload_bytes')} (truncated write?)")
            continue
        with open(bin_path, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()
        if digest != meta.get("payload_sha256"):
            problems.append(f"{key}: payload sha256 mismatch")
            continue
        if is_stale(meta):
            got = (meta.get("version") or {})
            notes.append(
                f"{key}: stale ({meta.get('kind')}; jax "
                f"{got.get('jax')}, code {got.get('code')}) — "
                "never loaded by this toolchain; `gc` reclaims it")
    for tmp in stray_tmp_files():
        # a .tmp may also be another process mid-store, so it is a
        # note, not a CI failure; gc reclaims the dead ones
        notes.append(f"{os.path.basename(tmp)}: interrupted write "
                     "(or a store in progress); `gc` reclaims it")
    return problems, notes, n


def gc_bank(max_age_days=None, remove_all=False, dry_run=False):
    """Remove dead entries: stale version fingerprints, orphans,
    corrupt sidecars, and (optionally) anything older than
    ``max_age_days``.  Foreign *environment* variants (other platform/
    topology/x64) are kept — they are live entries for other processes.
    Returns a summary dict."""
    removed, kept, freed = [], 0, 0
    now = time.time()
    for key, meta, meta_path, bin_path in scan():
        dead = remove_all or is_stale(meta)
        if (not dead and max_age_days is not None
                and now - (meta.get("created") or 0) > max_age_days * 86400):
            dead = True
        if not dead:
            kept += 1
            continue
        for p in (meta_path, bin_path):
            if os.path.exists(p):
                freed += os.path.getsize(p)
                if not dry_run:
                    os.remove(p)
        removed.append(key)
    for tmp in stray_tmp_files():   # interrupted-write leftovers
        try:
            freed += os.path.getsize(tmp)
            if not dry_run:
                os.remove(tmp)
            removed.append(os.path.basename(tmp))
        except OSError:
            pass
    summary = dict(removed=len(removed), kept=kept, bytes_freed=freed,
                   dry_run=bool(dry_run))
    log_event("aot_gc", **summary)
    return summary
