"""Rigid-body transform kernels.

Pure jax.numpy implementations of the frame-transform algebra used
throughout the framework.  Functional equivalents of the reference
helpers (``/root/reference/raft/helpers.py``: ``getH`` :428,
``rotationMatrix`` :439, ``translateForce3to6DOF`` :468,
``translateMatrix3to6DOF`` :537, ``translateMatrix6to6DOF`` :563,
``rotateMatrix3/6`` :604-655, ``getWeightOfPointMass`` :1060), but
written as batched, broadcast-friendly ops: every function accepts
leading batch dimensions on its array arguments so it vmaps for free.

Conventions (matching the reference so golden values carry over):
* ``skew(r) @ th == cross(th, r)`` — i.e. ``skew`` is the *alternator*
  matrix H with H[0,1]=r_z, H[0,2]=-r_y, ... (helpers.py:428-437).
* Small-rotation displacement of a point at ``r`` under rotation vector
  ``th`` is ``th x r`` = ``skew(r) @ th``.
* ``rotation_matrix(x3, x2, x1) = Rz(x1) @ Ry(x2) @ Rx(x3)`` —
  intrinsic z-y-x (yaw-pitch-roll applied in that order).
"""

from __future__ import annotations

import jax.numpy as jnp


def skew(r):
    """Alternator matrix H of a 3-vector: ``H @ v == cross(v, r)``.

    Matches helpers.py:428 ``getH``. Supports leading batch dims:
    r: (..., 3) -> (..., 3, 3).
    """
    r = jnp.asarray(r)
    z = jnp.zeros_like(r[..., 0])
    return jnp.stack(
        [
            jnp.stack([z, r[..., 2], -r[..., 1]], axis=-1),
            jnp.stack([-r[..., 2], z, r[..., 0]], axis=-1),
            jnp.stack([r[..., 1], -r[..., 0], z], axis=-1),
        ],
        axis=-2,
    )


def rotation_matrix(x3, x2, x1):
    """Intrinsic z-y-x rotation matrix; helpers.py:439-466.

    ``R = Rz(x1) Ry(x2) Rx(x3)`` with (x3, x2, x1) ~ (roll, pitch, yaw).
    Scalar or batched inputs (broadcast against each other).
    """
    s1, c1 = jnp.sin(x1), jnp.cos(x1)
    s2, c2 = jnp.sin(x2), jnp.cos(x2)
    s3, c3 = jnp.sin(x3), jnp.cos(x3)
    r00 = c1 * c2
    r01 = c1 * s2 * s3 - c3 * s1
    r02 = s1 * s3 + c1 * c3 * s2
    r10 = c2 * s1
    r11 = c1 * c3 + s1 * s2 * s3
    r12 = c3 * s1 * s2 - c1 * s3
    r20 = -s2
    r21 = c2 * s3
    r22 = c2 * c3
    return jnp.stack(
        [
            jnp.stack([r00, r01, r02], axis=-1),
            jnp.stack([r10, r11, r12], axis=-1),
            jnp.stack([r20, r21, r22], axis=-1),
        ],
        axis=-2,
    )


def small_rotate(r, th):
    """Displacement of point ``r`` under small rotation vector ``th``:
    ``th x r``.  Matches helpers.py:396-408 ``SmallRotate``."""
    return jnp.cross(th, r)


def translate_force_3to6(F, r):
    """Force at point ``r`` -> equivalent 6-DOF force/moment about origin.

    helpers.py:468-483. F: (..., 3), r: (..., 3) -> (..., 6).
    Works for real or complex F.
    """
    return jnp.concatenate([F, jnp.cross(r, F)], axis=-1)


def translate_matrix_3to6(M3, r):
    """3x3 mass-like matrix at point ``r`` -> 6x6 about origin.

    helpers.py:537-560.  M3: (..., 3, 3), r: (..., 3) -> (..., 6, 6).
    """
    H = skew(r)
    MH = M3 @ H
    top = jnp.concatenate([M3, MH], axis=-1)
    bot = jnp.concatenate([jnp.swapaxes(MH, -1, -2), H @ M3 @ jnp.swapaxes(H, -1, -2)], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def translate_matrix_6to6(M6, r):
    """Translate a 6x6 matrix to a new reference point; helpers.py:563-585.

    ``r`` points from the *new* reference point to the old one.
    """
    H = skew(r)
    Ht = jnp.swapaxes(H, -1, -2)
    m = M6[..., :3, :3]
    J = M6[..., :3, 3:]
    Jt = M6[..., 3:, :3]
    I = M6[..., 3:, 3:]
    J2 = m @ H + J
    I2 = H @ m @ Ht + Jt @ H + Ht @ J + I
    top = jnp.concatenate([m, J2], axis=-1)
    bot = jnp.concatenate([jnp.swapaxes(J2, -1, -2), I2], axis=-2 + 1)
    return jnp.concatenate([top, bot], axis=-2)


def rotate_matrix_3(M3, R):
    """``R @ M3 @ R.T``; helpers.py:642-655."""
    return R @ M3 @ jnp.swapaxes(R, -1, -2)


def rotate_matrix_6(M6, R):
    """Rotate a 6x6 mass/inertia tensor block-wise; helpers.py:604-639."""
    Rt = jnp.swapaxes(R, -1, -2)
    m = R @ M6[..., :3, :3] @ Rt
    J = R @ M6[..., :3, 3:] @ Rt
    I = R @ M6[..., 3:, 3:] @ Rt
    top = jnp.concatenate([m, J], axis=-1)
    bot = jnp.concatenate([jnp.swapaxes(J, -1, -2), I], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def transform_force_6(f6, offset):
    """Shift a 6-component force/moment vector by ``offset`` (adds r x F
    to the moment); helpers.py:486-533 (translation branch only)."""
    return jnp.concatenate(
        [f6[..., :3], f6[..., 3:] + jnp.cross(offset, f6[..., :3])], axis=-1
    )


def weight_of_point_mass(mass, dR, g=9.81):
    """6-DOF weight load and 6x6 weight ('hydrostatic') stiffness of a
    point mass whose CG sits at ``dR`` from the reference point.

    helpers.py:1060-1082.  Returns (W:(...,6), C:(...,6,6)).
    """
    mass = jnp.asarray(mass)
    Fz = -g * mass
    zeros = jnp.zeros_like(Fz)
    F3 = jnp.stack([zeros, zeros, Fz], axis=-1)
    W = translate_force_3to6(F3, dR)
    C = jnp.zeros(mass.shape + (6, 6), dtype=W.dtype)
    C = C.at[..., 3, 3].set(-mass * g * dR[..., 2])
    C = C.at[..., 4, 4].set(-mass * g * dR[..., 2])
    return W, C


def heading_rotation(heading_deg):
    """Rotation about global z by ``heading_deg`` degrees;
    helpers.py:587-602 ``applyHeadingToPoint`` as a matrix."""
    c = jnp.cos(jnp.deg2rad(heading_deg))
    s = jnp.sin(jnp.deg2rad(heading_deg))
    z = jnp.zeros_like(c)
    o = jnp.ones_like(c)
    return jnp.stack(
        [
            jnp.stack([c, -s, z], axis=-1),
            jnp.stack([s, c, z], axis=-1),
            jnp.stack([z, z, o], axis=-1),
        ],
        axis=-2,
    )


def vec_vec_trans(v):
    """Outer product v v^T; helpers.py:412-420."""
    return v[..., :, None] * v[..., None, :]
