"""Mean-offset (static equilibrium) solve.

Equivalent of ``Model.solveStatics`` (``/root/reference/raft/
raft_model.py:550-964``) with the linearised-hydrostatics approach
(staticsMod=0) and constant environmental forcing (forcingsMod=0):

    F(X) = F_undisplaced - K_hydrostatic (X - X_ref) + F_env + F_moor(X)
    K(X) = K_hydrostatic + C_elast + C_moor(X)
    X   <- X + K^{-1} F          (damped Newton)

Mooring reactions enter through caller-provided closures (single-FOWT
catenary systems, per-FOWT systems in an array, and shared-line
networks all compose into the same two functions), with exact tangent
stiffness from the jax catenary module, so the iteration is a clean
Newton method (the reference's ad-hoc diagonal-inflation fallbacks,
raft_model.py:847-878, are unnecessary).  The loop is a
``lax.while_loop`` so the whole equilibrium solve jits and vmaps over
load cases and designs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from raft_tpu.physics.mooring import mooring_force, mooring_stiffness
from raft_tpu.utils import config, health


def make_tolerances(fowtList):
    """Per-DOF solver tolerances and step caps mirroring the reference
    (0.05 m / 0.005 rad; 30 m / 5 m / 0.1 rad caps,
    raft_model.py:658-669)."""
    tols, caps, refs = [], [], []
    for fs in fowtList:
        for dof in fs.reducedDOF:
            tols.append(0.05 if dof[1] < 3 else 0.005)
            caps.append(30.0 if dof[1] < 2 else 5.0 if dof[1] == 2 else 0.1)
            refs.append(
                fs.x_ref if dof[1] == 0 else fs.y_ref if dof[1] == 1 else 0.0
            )
    return jnp.asarray(tols), jnp.asarray(caps), jnp.asarray(refs)


def single_ms_closures(ms, nDOF):
    """Force/stiffness closures for one FOWT's own catenary system."""

    def force(X):
        F = jnp.zeros(nDOF)
        if ms is not None:
            Fm, _ = mooring_force(ms, X[:6])
            F = F.at[:6].add(Fm)
        return F

    def stiff(X):
        K = jnp.zeros((nDOF, nDOF))
        if ms is not None:
            K = K.at[:6, :6].add(mooring_stiffness(ms, X[:6]))
        return K

    return force, stiff


def solve_equilibrium_general(
    K_hydrostatic,
    F_undisplaced,
    F_env,
    mooring_force_fn,
    mooring_stiffness_fn,
    tol_vec,
    step_cap,
    X_ref,
    C_elast=None,
    X0=None,
    max_iter=30,
    discard_subtol_step=True,
):
    """Damped Newton equilibrium with the reference's stopping rule.

    The hydrostatic reaction acts on the offset from the reference
    position X_ref (array FOWTs sit at nonzero x/y; raft_model.py:698-707).
    ``discard_subtol_step`` reproduces dsolve2's convergence semantics
    (the final sub-tolerance step is not applied), which the reference's
    published equilibria correspond to.

    Returns ``(X, F_resid, n_iter, converged, status)``: the
    equilibrium pose, the residual force at it, the realized Newton
    iteration count, the stopping-rule verdict, and the solver-health
    word (:mod:`raft_tpu.utils.health`) carrying ``STATICS_MAX_ITER``
    when the budget struck unconverged and ``STATICS_STEP_CAPPED`` when
    any applied step saturated the per-DOF cap — all traced, vmap-safe
    values (no host exception can carry them out of a pjit sweep).

    ``RAFT_TPU_ITER_SCALE`` (trace-time, default 1) multiplies
    ``max_iter`` — the escalation re-solver's "larger budget" rung;
    at 1 the loop is iteration-for-iteration the reference's."""
    nDOF = F_undisplaced.shape[0]
    if X0 is None:
        X0 = jnp.asarray(X_ref)
    if C_elast is None:
        # derive the placeholder's dtype from the stiffness it joins:
        # a default-f64 zeros would silently promote an f32 solve
        C_elast = jnp.zeros((nDOF, nDOF),
                            dtype=jnp.asarray(K_hydrostatic).dtype)
    max_iter_eff = max_iter * max(int(config.get("ITER_SCALE")), 1)
    # aux counters in the solve's own float dtype: custom_root's JVP
    # rule cannot produce the float0 tangents int/bool aux would need
    # (same pattern as the drag fixed point, models/dynamics.py)
    ft = jnp.asarray(X0).dtype
    zero = jnp.zeros((), dtype=ft)
    one = jnp.ones((), dtype=ft)

    def net_force(X):
        return (
            F_undisplaced
            - K_hydrostatic @ (X - X_ref)
            + F_env
            + mooring_force_fn(X)
            - C_elast @ (X - X_ref)
        )

    def step(X):
        F = net_force(X)
        K = K_hydrostatic + C_elast + mooring_stiffness_fn(X)
        dX = jnp.linalg.solve(K, F)
        return jnp.clip(dX, -step_cap, step_cap), dX

    def body(carry):
        X, it, _, capped = carry
        dX, dX_raw = step(X)
        done = jnp.all(jnp.abs(dX) < tol_vec)
        hit = jnp.any(jnp.abs(dX_raw) > step_cap)
        if discard_subtol_step:
            X = jnp.where(done, X, X + dX)
        else:
            X = X + dX
        # count cap-saturated steps that were actually applied (the
        # discarded sub-tolerance step cannot saturate the cap anyway)
        capped = capped + jnp.where(done | ~hit, zero, one)
        return X, it + one, done, capped

    def cond(carry):
        _, it, done, _ = carry
        return (it < max_iter_eff) & (~done)

    def run_newton(f, Xinit):
        X, it, done, capped = jax.lax.while_loop(
            cond, body, (Xinit, zero, jnp.asarray(False), zero))
        return X, (it, jnp.where(done, one, zero), capped)

    def tangent_solve(g, y):
        # g is the linearized residual (the equilibrium Jacobian); the
        # system is small (nDOF), so materialise and solve directly
        J = jax.jacfwd(g)(jnp.zeros_like(y))
        return jnp.linalg.solve(J, y)

    # implicit differentiation of the converged equilibrium
    # (lax.custom_root): forward value identical to the plain Newton
    # while_loop; gradients flow through the implicit function theorem,
    # enabling jax.grad (reverse mode) of response metrics wrt design
    # parameters (SURVEY.md §7.1)
    X, (it_f, done_f, capped_f) = jax.lax.custom_root(
        net_force, X0, run_newton, tangent_solve, has_aux=True)
    n_iter = jnp.asarray(jax.lax.stop_gradient(it_f), dtype=jnp.int32)
    converged = jax.lax.stop_gradient(done_f) > 0.5
    step_capped = jax.lax.stop_gradient(capped_f) > 0.5
    status = health.set_bit(
        jnp.zeros((), dtype=jnp.int32), health.STATICS_MAX_ITER, ~converged)
    status = health.set_bit(status, health.STATICS_STEP_CAPPED, step_capped)
    return X, net_force(X), n_iter, converged, status


def solve_equilibrium(
    fs,
    ms,
    K_hydrostatic,
    F_undisplaced,
    F_env,
    C_elast=None,
    X0=None,
    max_iter=30,
    tol="reference",
    step_cap=None,
):
    """Single-FOWT convenience wrapper (original API)."""
    tol_vec, caps, refs = make_tolerances([fs])
    if step_cap is not None:
        caps = step_cap
    if not (isinstance(tol, str) and tol == "reference"):
        tol_vec = jnp.full(fs.nDOF, tol)
    force, stiff = single_ms_closures(ms, fs.nDOF)
    return solve_equilibrium_general(
        jnp.asarray(K_hydrostatic), jnp.asarray(F_undisplaced), jnp.asarray(F_env),
        force, stiff, tol_vec, caps, refs, C_elast=C_elast, X0=X0,
        max_iter=max_iter,
    )
