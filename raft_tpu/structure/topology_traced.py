"""Traced (jax) twin of the topology kinematics for flexible/multibody
FOWTs, so the 150-DOF displaced-pose chain can live inside one jit.

The numpy path (:mod:`raft_tpu.structure.topology`) computes

* ``displacements`` — nonlinear mean node displacements (rigid links
  rotate exactly, ball joints keep their own linear rotation, beam
  chains get the end node's nonlinear-minus-linear correction;
  setNodesPosition, raft_fowt.py:669-752), and
* ``reduce(positions)`` — the structure transformation matrix T whose
  rigid-link blocks depend on the current node positions
  (reduceDOF/attachToNode, raft_fowt.py:553-624, raft_node.py:79-159)

by breadth-first traversals whose *structure* (visit order, joint
types, column bookkeeping) is fully determined at build time.  Here the
traversals are recorded once into static schedules and replayed with
``jax.numpy`` values, making both maps pure traced functions of the
reduced displacement vector.  The builder validates the replay against
the numpy path at machine precision before returning.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu.ops import transforms as tf


class TracedTopology:
    """Static schedules + traced replays of displacements/reduce."""

    def __init__(self, fs):
        topo = fs.topology
        self.n_nodes = len(topo.nodes)
        self.nDOF = fs.nDOF
        self.root_id = fs.root_id
        self.T0 = np.asarray(fs.T)
        self.node_r0 = np.asarray(fs.node_r0)
        self._build_disp_schedule(topo)
        self._build_reduce_schedule(topo)
        self._validate(topo, fs)

    # ------------------------------------------------ displacement map
    def _build_disp_schedule(self, topo):
        """Replay Topology.displacements' BFS, recording the op list."""
        nodes = topo.nodes
        links_by_node = {}
        for a, b in topo._links:
            links_by_node.setdefault(a, []).append(b)
            links_by_node.setdefault(b, []).append(a)
        joint_groups = {}
        for nd in nodes:
            if nd.joint_id is not None:
                joint_groups.setdefault(nd.joint_id, []).append(nd.id)
        chains_by_node = {}
        for chain in topo._chains:
            for nid in chain:
                chains_by_node[nid] = chain

        sched = []
        root = nodes[self.root_id]
        visited = {root.id}
        queue = [root]
        while queue:
            node = queue.pop(0)
            for pid in links_by_node.get(node.id, []):
                p = nodes[pid]
                if p.id in visited:
                    continue
                sched.append(("rigid", p.id, node.id,
                              np.asarray(p.r0 - node.r0)))
                visited.add(p.id)
                queue.append(p)
            if node.joint_id is not None:
                for nid in joint_groups.get(node.joint_id, []):
                    nn = nodes[nid]
                    if nn.id in visited:
                        continue
                    sched.append(("joint", nn.id, node.id,
                                  nn.joint_type == "ball"))
                    visited.add(nn.id)
                    queue.append(nn)
            if node.end_node and node.id in chains_by_node:
                chain_new = [nid for nid in chains_by_node[node.id]
                             if nid not in visited]
                if chain_new:
                    sched.append(("chain", tuple(chain_new), node.id, None))
                for nid in chain_new:
                    visited.add(nid)
                    queue.append(nodes[nid])
        self.disp_schedule = sched

    def displacements(self, Xi0):
        """Traced twin of Topology.displacements with the build-time T
        (the reference's one-step solver lag; see
        Topology.self_consistent_displacements)."""
        Xi0 = jnp.asarray(Xi0)
        lin = (jnp.asarray(self.T0) @ Xi0).reshape(self.n_nodes, 6)
        disp = [None] * self.n_nodes
        disp[self.root_id] = lin[self.root_id]
        for kind, child, parent, aux in self.disp_schedule:
            if kind == "rigid":
                th = lin[parent][3:]
                R = tf.rotation_matrix(th[0], th[1], th[2])
                dd = (R - jnp.eye(3)) @ jnp.asarray(aux)
                disp[child] = jnp.concatenate(
                    [disp[parent][:3] + dd, disp[parent][3:]])
            elif kind == "joint":
                d = disp[parent]
                if aux:  # ball joint keeps its own linear rotation
                    d = jnp.concatenate([d[:3], lin[child][3:]])
                disp[child] = d
            else:  # beam chain: linear + end-node nonlinear correction
                dR = disp[parent] - lin[parent]
                for nid in child:
                    disp[nid] = lin[nid] + dR
        # unreached nodes fall back to the linear map
        for i in range(self.n_nodes):
            if disp[i] is None:
                disp[i] = lin[i]
        return jnp.stack(disp)

    # --------------------------------------------------- T(positions)
    def _build_reduce_schedule(self, topo):
        """Replay Topology.reduce at the reference pose, recording per
        node: parent, op kind, the child->[parent cols (+new)] column
        permutation, and the node's global column indices."""
        # run the numpy reduce to land the per-node reducedDOF state
        T_ref, reducedDOF, root_id = topo.reduce()
        assert root_id == self.root_id
        nodes = topo.nodes
        glob_col = {tuple(d): i for i, d in enumerate(reducedDOF)}
        self.node_cols = [
            np.asarray([glob_col[tuple(d)] for d in n.reducedDOF], dtype=int)
            for n in nodes
        ]

        links_by_node = {}
        for a, b in topo._links:
            links_by_node.setdefault(a, []).append(b)
            links_by_node.setdefault(b, []).append(a)
        joint_groups = {}
        for nd in nodes:
            if nd.joint_id is not None:
                joint_groups.setdefault(nd.joint_id, []).append(nd.id)
        chains_by_node = {}
        for chain in topo._chains:
            for nid in chain:
                chains_by_node[nid] = chain

        sched = []
        visited = {root_id}
        queue = [nodes[root_id]]
        identity_nodes = {root_id}
        while queue:
            node = queue.pop(0)
            for pid in links_by_node.get(node.id, []):
                p = nodes[pid]
                if p.id in visited:
                    continue
                sched.append(self._attach_record(nodes, p, node, "rigid_link"))
                visited.add(p.id)
                queue.append(p)
            if node.joint_id is not None:
                for nid in joint_groups.get(node.joint_id, []):
                    nn = nodes[nid]
                    if nn.id in visited:
                        continue
                    sched.append(
                        self._attach_record(nodes, nn, node, nn.joint_type))
                    visited.add(nn.id)
                    queue.append(nn)
            if node.end_node and node.id in chains_by_node:
                for nid in chains_by_node[node.id]:
                    if nid not in visited:
                        visited.add(nid)
                        identity_nodes.add(nid)
                        queue.append(nodes[nid])
        self.reduce_schedule = sched
        self.identity_nodes = sorted(identity_nodes)

    @staticmethod
    def _attach_record(nodes, child, parent, jtype):
        """Static column bookkeeping of one attach (topology.attach)."""
        p_dofs = [tuple(d) for d in parent.reducedDOF]
        c_dofs = [tuple(d) for d in child.reducedDOF]
        if jtype == "rigid_link" or jtype == "cantilever":
            # same column set; child order is the (node,dof) sort of the
            # parent's columns
            perm = np.asarray([p_dofs.index(d) for d in c_dofs], dtype=int)
            return (jtype, child.id, parent.id, perm, None)
        elif jtype in ("ball", "universal"):
            # columns = kept subset of [parent cols..., 3 new rot cols]
            ext = p_dofs + [(child.id, i) for i in range(3, 6)]
            sel = np.asarray([ext.index(d) for d in c_dofs], dtype=int)
            new_rows = np.asarray(
                [c_dofs.index((child.id, i)) if (child.id, i) in c_dofs
                 else -1 for i in range(3, 6)], dtype=int)
            return (jtype, child.id, parent.id, sel, new_rows)
        raise ValueError(f"joint type {jtype!r} not supported")

    def reduce_T(self, positions):
        """Traced T at the given node positions (n_nodes, 3)."""
        r = jnp.asarray(positions)
        Taux = {}
        for nid in self.identity_nodes:
            Taux[nid] = jnp.eye(6)
        for kind, child, parent, sel, new_rows in self.reduce_schedule:
            Tp = Taux[parent]
            if kind == "rigid_link":
                H = tf.skew(r[child] - r[parent])  # == topology._getH
                Tc = jnp.concatenate(
                    [Tp[:3] + H @ Tp[3:6], Tp[3:6]], axis=0)
                Taux[child] = Tc[:, sel]
            elif kind == "cantilever":
                Taux[child] = Tp[:, sel]
            else:  # ball/universal
                ncols_p = Tp.shape[1]
                ext = jnp.concatenate([Tp, jnp.zeros((6, 3))], axis=1)
                ext = ext.at[3:6, :].set(0.0)
                for j, row in enumerate(range(3, 6)):
                    ext = ext.at[row, ncols_p + j].set(1.0)
                Taux[child] = ext[:, sel]
        T = jnp.zeros((6 * self.n_nodes, self.nDOF))
        for nid in range(self.n_nodes):
            T = T.at[6 * nid:6 * nid + 6,
                     jnp.asarray(self.node_cols[nid])].set(Taux[nid])
        return T

    # ------------------------------------------------------ validation
    def _validate(self, topo, fs, atol=1e-12):
        rng = np.random.default_rng(0)
        Xi0 = 1e-2 * rng.standard_normal(self.nDOF)
        disp_np = topo.displacements(fs.T, fs.reducedDOF, fs.root_id, Xi0)
        # build-time eager validation: the host pull is the point here
        # raft-lint: disable=host-coercion
        disp_tr = np.asarray(self.displacements(jnp.asarray(Xi0)))
        if not np.allclose(disp_tr, disp_np, atol=atol):
            raise RuntimeError("traced displacement map mismatch")
        r_np = self.node_r0 + disp_np[:, :3]
        T_np, _, _ = topo.reduce(positions=r_np)
        topo.reduce()  # restore reference-pose traversal state
        # raft-lint: disable=host-coercion
        T_tr = np.asarray(self.reduce_T(jnp.asarray(r_np)))
        if not np.allclose(T_tr, T_np, atol=atol):
            raise RuntimeError("traced reduce(T) mismatch")

    # --------------------------------------------------- kinematics map
    def kinematics(self, Xi0):
        """(r_nodes, node_rot, Tn) at the displaced pose — the traced
        twin of FOWTHydro._kinematics' flexible branch (one-lag
        semantics: displacements with the build-time T, T rebuilt once
        at the displaced positions)."""
        disp = self.displacements(Xi0)
        r_nodes = jnp.asarray(self.node_r0) + disp[:, :3]
        T_disp = self.reduce_T(r_nodes)
        Tn = T_disp.reshape(self.n_nodes, 6, self.nDOF)
        return r_nodes, disp[:, 3:], Tn
