"""CLI: ``python -m raft_tpu design.yaml [--csv out.csv]``.

The orchestrated analysis path is the float64 host-side parity path
(the TPU path is the traced evaluator used by bench/sweeps), so the
CLI pins the CPU backend + x64 unless RAFT_TPU_CLI_PLATFORM overrides
it — accelerator plugins without f64 support would otherwise fail.
"""

import argparse

from raft_tpu.utils import config


def main():
    platform = config.get("CLI_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
        if platform == "cpu":
            jax.config.update("jax_enable_x64", True)
    p = argparse.ArgumentParser(
        description="raft_tpu: TPU-native frequency-domain FOWT analysis")
    p.add_argument("design", help="design YAML (RAFT-compatible schema)")
    p.add_argument("--csv", default=None, help="write channel statistics CSV")
    args = p.parse_args()

    from raft_tpu.drivers import run

    model = run(args.design, save_csv=args.csv)
    for iCase, per_fowt in model.results["case_metrics"].items():
        for ifowt, m in per_fowt.items():
            print(f"case {iCase} fowt {ifowt}: "
                  f"surge {float(m['surge_avg']):+.2f}±{float(m['surge_std']):.2f} m, "
                  f"heave {float(m['heave_avg']):+.2f}±{float(m['heave_std']):.2f} m, "
                  f"pitch {float(m['pitch_avg']):+.2f}±{float(m['pitch_std']):.2f} deg")


if __name__ == "__main__":
    main()
