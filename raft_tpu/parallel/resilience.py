"""Fault-tolerant runtime for checkpointed sweeps.

The north-star workload (10k+ designs x 12 cases, hours of wall time,
20+ checkpoint shards) fails in ways the happy-path driver in
:mod:`raft_tpu.parallel.sweep` used to ignore: a preemption mid-write
leaves a truncated ``.npz`` that poisons the resume; a resumed run with
*changed* inputs silently mixes stale shards into fresh results; one
non-converged drag linearization emits a NaN row that propagates into
every aggregate; and a dead accelerator tunnel kills the whole sweep
instead of degrading to the CPU backend.  This module supplies the
missing pieces:

* **atomic shard writes** — tmp file in the same directory +
  ``os.replace``, so a shard file either exists complete or not at all;
* **corrupt-shard detection** — resume loads with
  ``np.load(allow_pickle=False)``, verifies the stored keys cover the
  requested ``out_keys`` and row counts match, and re-queues (never
  crashes on) a truncated/corrupt/stale shard;
* **sweep manifest** — ``manifest.json`` per ``out_dir`` records a
  config fingerprint (case-array hashes, ``out_keys``, ``shard_size``,
  mesh shape, package version) plus per-shard status; resuming against
  a manifest whose *input-determining* fields differ raises
  :class:`ManifestMismatchError` instead of mixing stale data;
* **retry with exponential backoff** for transient evaluator/runtime
  errors, OOM degradation by halving the shard batch, and CPU-backend
  fallback when :func:`raft_tpu.utils.devices.probe_backend` says the
  accelerator is unhealthy;
* **NaN/Inf quarantine** — a per-row finiteness check after each shard;
  offending case parameters land in ``quarantine.json`` (with an
  optional solo re-evaluation on the CPU backend) so non-finite rows
  are auditable instead of silently poisoning aggregates;
* **status-aware quarantine + escalation ladder** — when the sweep
  carries the ``"status"`` out_key (the in-band int32 solver-health
  word, :mod:`raft_tpu.utils.health`), rows with SEVERE bits —
  finite-but-unconverged Newton/drag solves, ill-conditioned impedance
  — are caught too, not just NaNs.  ``RAFT_TPU_ESCALATE`` selects the
  degradation path: ``off`` (flag + record only), ``retol`` (re-solve
  the row with ``RAFT_TPU_ESCALATE_ITER_SCALE`` x the iteration
  budget), ``f64_cpu`` (retol, then float64 on the CPU backend).  Each
  rung's outcome — cleared vs persistent bits, original-vs-escalated
  result deltas — lands per case in ``quarantine.json`` (schema v2).

Every event flows through :mod:`raft_tpu.utils.structlog` (JSONL):
``sweep_start``, ``shard_start``, ``shard_done``, ``shard_resume``,
``shard_corrupt``, ``shard_retry``, ``shard_oom_split``,
``shard_quarantine``, ``shard_escalate``, ``shard_escalate_failed``,
``backend_fallback``, ``manifest_mismatch``, ``sweep_done``.  Failure
paths are exercised deterministically via :mod:`raft_tpu.utils.faults`.

Telemetry (:mod:`raft_tpu.obs`, README "Observability"): the sweep,
every shard, every retry attempt and every escalation rung run inside
spans, so a captured JSONL stream reconstructs the full wall-time tree
(``python -m raft_tpu.obs report``/``trace``); the metrics registry
counts shards done/resumed, rows retried/quarantined/flagged and
escalation outcomes, and its snapshot lands in the sweep manifest and
``<out_dir>/metrics.json`` at ``sweep_done`` (Prometheus text to
``RAFT_TPU_METRICS`` when set).  ``RAFT_TPU_HEARTBEAT_S`` samples
device memory between shards; ``RAFT_TPU_PROFILE`` captures a jax
profiler trace of the whole checkpointed sweep.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import tempfile
import time

import numpy as np

from raft_tpu.obs import metrics
from raft_tpu.obs.heartbeat import maybe_heartbeat
from raft_tpu.obs.spans import span
from raft_tpu.utils import config, faults, health
from raft_tpu.utils.structlog import log_event

MANIFEST_NAME = "manifest.json"
METRICS_NAME = "metrics.json"
QUARANTINE_NAME = "quarantine.json"

# fingerprint fields that determine the numerical content and layout of
# the shard files; any difference on resume means the existing shards
# answer a different question and must not be mixed in
_STRICT_FINGERPRINT_KEYS = ("case_hashes", "n_cases", "out_keys", "shard_size")


class ManifestMismatchError(RuntimeError):
    """Resume attempted with inputs that differ from the manifest."""


class ShardCorruptError(RuntimeError):
    """A checkpoint shard failed to load or failed validation."""


# --------------------------------------------------------------- atomic I/O


def _atomic_write(path, writer, mode="wb"):
    """Write a file atomically: tmp file in the same dir, ``writer(f)``,
    then ``os.replace`` — atomic on POSIX within one filesystem, so a
    preempted/killed process leaves either the complete previous file or
    no file, never a truncated one at the final path."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=".tmp")
    try:
        with os.fdopen(fd, mode) as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_savez(path, **arrays):
    """Write an ``.npz`` atomically (tmp file + rename)."""
    _atomic_write(path, lambda f: np.savez(f, **arrays))
    if faults.take("truncate", "shard_write"):
        # simulate dying mid-write on a pre-atomic driver / a filesystem
        # that lost the tail: corrupt the final file, then "crash"
        faults.truncate_file(path)
        raise faults.InjectedFault(f"injected truncation of {path}")


def _atomic_json(path, obj):
    _atomic_write(path, lambda f: json.dump(obj, f, indent=1, default=str),
                  mode="w")


def load_shard(path, out_keys, expect_rows=None):
    """Load and validate one checkpoint shard.

    Loads with ``allow_pickle=False`` (checkpoints are plain arrays; a
    pickled object in one is corruption or tampering), verifies every
    requested output key is present, and optionally checks the row
    count.  Raises :class:`ShardCorruptError` on any failure so the
    caller can re-queue the shard instead of crashing."""
    try:
        with np.load(path, allow_pickle=False) as z:
            stored = set(z.files)
            missing = [k for k in out_keys if k not in stored]
            if missing:
                raise ShardCorruptError(
                    f"{path}: stored keys {sorted(stored)} missing "
                    f"requested out_keys {missing}")
            out = {k: z[k] for k in out_keys}
    except ShardCorruptError:
        raise
    except Exception as e:  # truncated zip, bad CRC, unreadable header...
        raise ShardCorruptError(f"{path}: failed to load ({e})") from e
    if expect_rows is not None:
        bad = {k: v.shape for k, v in out.items()
               if v.shape[:1] != (expect_rows,)}
        if bad:
            raise ShardCorruptError(
                f"{path}: expected {expect_rows} rows, got {bad}")
    return out


# ----------------------------------------------------------------- manifest


def compute_fingerprint(cases, out_keys, shard_size, mesh=None):
    """Config fingerprint of one checkpointed sweep.

    ``case_hashes`` digests each case array's dtype+shape+bytes, so any
    change to the inputs — values, order, length — changes the
    fingerprint.  Mesh shape and package version are recorded for audit
    but compared only advisorily (results do not depend on device
    layout; a fabric coordinator fingerprints with ``mesh=None`` — it
    never initializes a backend — and each worker records its own)."""
    import raft_tpu

    case_hashes = {}
    for k in sorted(cases):
        v = np.ascontiguousarray(cases[k])
        h = hashlib.sha256()
        h.update(str(v.dtype).encode())
        h.update(str(v.shape).encode())
        h.update(v.tobytes())
        case_hashes[k] = h.hexdigest()
    return {
        "case_hashes": case_hashes,
        "n_cases": int(len(next(iter(cases.values())))),
        "out_keys": list(out_keys),
        "shard_size": int(shard_size),
        "mesh_shape": ([int(s) for s in mesh.devices.shape]
                       if mesh is not None else []),
        "mesh_axes": (list(mesh.axis_names) if mesh is not None else []),
        "package_version": getattr(raft_tpu, "__version__", "unknown"),
    }


def _manifest_path(out_dir):
    return os.path.join(out_dir, MANIFEST_NAME)


def init_manifest(out_dir, fingerprint, n_shards):
    """Create or validate the sweep manifest for ``out_dir``.

    First run: writes a fresh manifest.  Resume: the strict fingerprint
    fields must match or :class:`ManifestMismatchError` is raised —
    changed inputs silently mixed with stale shards is the one failure
    mode this layer exists to make loud.  Advisory fields (mesh shape,
    package version) only log a ``manifest_mismatch`` warning event.

    Returns the manifest dict (fresh or loaded)."""
    path = _manifest_path(out_dir)
    if os.path.exists(path):
        try:
            with open(path) as f:
                manifest = json.load(f)
            old = manifest["fingerprint"]
        except Exception as e:
            raise ManifestMismatchError(
                f"{path} exists but is unreadable ({e}); refusing to "
                "resume against an unvalidatable checkpoint directory — "
                "delete the directory to start fresh") from e
        mismatched = [k for k in _STRICT_FINGERPRINT_KEYS
                      if old.get(k) != fingerprint[k]]
        if mismatched:
            log_event("manifest_mismatch", out_dir=out_dir,
                      fields=mismatched, fatal=True)
            raise ManifestMismatchError(
                f"resume fingerprint mismatch in {path} on fields "
                f"{mismatched}: the existing shards were produced from "
                "different inputs/config and cannot be mixed with this "
                "sweep — use a fresh out_dir (or delete this one)")
        advisory = [k for k in ("mesh_shape", "mesh_axes", "package_version")
                    if old.get(k) != fingerprint[k]]
        # adopt current advisory fields, keep shard statuses; persist so
        # the advisory mismatch is logged once, not on every resume
        manifest["fingerprint"] = fingerprint
        manifest.setdefault("shards", {})
        if advisory:
            log_event("manifest_mismatch", out_dir=out_dir,
                      fields=advisory, fatal=False)
            _atomic_json(path, manifest)
        return manifest
    manifest = {
        "version": 1,
        "fingerprint": fingerprint,
        "n_shards": int(n_shards),
        "shards": {},
    }
    _atomic_json(path, manifest)
    return manifest


def validate_manifest(out_dir, fingerprint):
    """Read-only strict-fingerprint check against an existing manifest.

    The concurrent-safe face of :func:`init_manifest` for fabric
    workers: N workers validating the same ``out_dir`` must not race
    each other with advisory-field rewrites (last-writer-wins would
    drop another worker's adoption), so this only *reads* — a strict
    mismatch raises :class:`ManifestMismatchError`, advisory drift is
    ignored (every worker's mesh legitimately differs).  A missing
    manifest is an error: the coordinator writes it at init, so its
    absence means ``out_dir`` was never fabric-initialized."""
    path = _manifest_path(out_dir)
    try:
        with open(path) as f:
            old = json.load(f)["fingerprint"]
    except Exception as e:
        raise ManifestMismatchError(
            f"{path} is missing or unreadable ({e}); this out_dir was "
            "not initialized for this sweep") from e
    mismatched = [k for k in _STRICT_FINGERPRINT_KEYS
                  if old.get(k) != fingerprint[k]]
    if mismatched:
        log_event("manifest_mismatch", out_dir=out_dir, fields=mismatched,
                  fatal=True)
        raise ManifestMismatchError(
            f"fingerprint mismatch in {path} on fields {mismatched}: "
            "this worker's sweep spec differs from the ledger's")


def mark_shard(manifest, out_dir, shard, status, **extra):
    """Record one shard's status in the manifest (atomic rewrite)."""
    rec = {"status": status, "file": f"shard_{shard:04d}.npz"}
    rec.update(extra)
    manifest["shards"][str(shard)] = rec
    _atomic_json(_manifest_path(out_dir), manifest)


# --------------------------------------------------------------- quarantine


def _quarantine_path(out_dir):
    return os.path.join(out_dir, QUARANTINE_NAME)


def record_quarantine(out_dir, shard, entries):
    """Merge quarantine ``entries`` for one shard into quarantine.json.

    Entries for the same shard from an earlier (superseded) computation
    are replaced, so a recomputed shard re-judges its own rows.

    Schema v2 (see README "Solver health"): every entry carries
    ``status`` (int32 solver-health word) and ``reason``
    (:func:`raft_tpu.utils.health.describe`), so NaN rows, cap-hit rows
    and ill-conditioned rows are distinguishable; escalated rows add an
    ``escalation`` block (rungs tried, resolving rung, status/reason
    after, original-vs-escalated result deltas)."""
    path = _quarantine_path(out_dir)
    existing = []
    if os.path.exists(path):
        try:
            with open(path) as f:
                existing = json.load(f).get("entries", [])
        except Exception as e:
            # externally damaged audit file: resetting it silently would
            # erase every prior entry — leave a loud trace first
            log_event("quarantine_corrupt", out_dir=out_dir,
                      error=str(e)[:200])
            existing = []
    existing = [e for e in existing if e.get("shard") != shard]
    existing.extend(entries)
    existing.sort(key=lambda e: (e.get("shard", 0), e.get("index", 0)))
    _atomic_json(path, {"version": 2, "entries": existing})


def load_quarantine(out_dir):
    """Return the list of quarantine entries for ``out_dir`` ([] if none)."""
    path = _quarantine_path(out_dir)
    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            return json.load(f).get("entries", [])
    except Exception as e:
        log_event("quarantine_corrupt", out_dir=out_dir, error=str(e)[:200])
        return []


def nonfinite_rows(out):
    """Indices of batch rows with any non-finite value in any output."""
    bad = None
    for v in out.values():
        a = np.asarray(v)
        if not np.issubdtype(a.dtype, np.number):
            continue
        row_ok = np.isfinite(a).reshape(a.shape[0], -1).all(axis=1)
        bad = ~row_ok if bad is None else (bad | ~row_ok)
    if bad is None:
        return np.array([], dtype=int)
    return np.nonzero(bad)[0]


def flagged_rows(out, mask=health.SEVERE):
    """Indices of batch rows whose ``"status"`` word carries any bit of
    ``mask`` ([] when the sweep did not request the status out_key)."""
    st = out.get("status")
    if st is None:
        return np.array([], dtype=int)
    st = np.asarray(st)
    bad = (st & np.int32(mask)).reshape(st.shape[0], -1).any(axis=1)
    return np.nonzero(bad)[0]


def _row_status(out, i):
    """OR-fold of one row's status word(s) as a host int (0 when the
    sweep carries no status column)."""
    st = out.get("status")
    if st is None:
        return 0
    return int(np.bitwise_or.reduce(
        np.asarray(st[i], dtype=np.int64).ravel(), initial=0))


# --------------------------------------------------------------- escalation

_RUNGS = {"off": (), "retol": ("retol",), "f64_cpu": ("retol", "f64_cpu")}


def escalation_rungs():
    """The active escalation ladder (``RAFT_TPU_ESCALATE``, re-read per
    call): the ordered rungs a flagged row climbs until healthy."""
    return _RUNGS[config.get("ESCALATE")]


@contextlib.contextmanager
def _rung_flags(rung):
    """Pin one rung's trace-time flags around a solo re-evaluation.

    ``retol`` grants the solvers ``RAFT_TPU_ESCALATE_ITER_SCALE`` x
    their AMBIENT iteration budgets (``RAFT_TPU_ITER_SCALE``, read at
    trace time by ``solve_equilibrium_general`` and
    ``solve_dynamics_fowt``) — relative, not absolute, so a base sweep
    already running with a scaled budget still escalates to a strictly
    larger one; ``f64_cpu`` additionally forces the float64 compute
    policy (under x64 semantics when the process runs without them).
    The sweep memo key includes these flags
    (:func:`raft_tpu.parallel.sweep._flags_key`), so each rung traces
    its own program and the base program stays cached."""
    ambient = max(int(config.get("ITER_SCALE")), 1)
    flags = {"ITER_SCALE":
             str(ambient * max(int(config.get("ESCALATE_ITER_SCALE")), 2)),
             # a rung's flag flip changes the AOT-bank key, and warmup
             # does not mint rung-variant programs — under the serving
             # config (RAFT_TPU_AOT=require + RAFT_TPU_COMPILE_BUDGET=0)
             # the rung's first re-solve would miss the bank and raise
             # instead of healing the row.  Escalation is a rare
             # solo-row recovery path where availability beats
             # cold-start purity: rungs may always compile (and, in
             # load/require mode, export — the NEXT rung hit loads).
             "AOT_MISS": "compile", "COMPILE_BUDGET": "-1"}
    if rung == "f64_cpu":
        flags["DTYPE"] = "float64"
    old = {}
    try:
        for name, val in flags.items():
            env = config.env_name(name)
            old[env] = os.environ.get(env)
            os.environ[env] = val
        if rung == "f64_cpu":
            import jax

            if not jax.config.jax_enable_x64:
                try:
                    from jax.experimental import enable_x64
                except ImportError:
                    enable_x64 = None
                if enable_x64 is not None:
                    with enable_x64():
                        yield
                    return
        yield
    finally:
        for env, val in old.items():
            if val is None:
                os.environ.pop(env, None)
            else:
                os.environ[env] = val


def _rung_mesh(rung, mesh):
    """The rung's target mesh: ``f64_cpu`` re-solves on the CPU backend
    (falling back to the sweep mesh when no CPU backend exists)."""
    if rung == "f64_cpu":
        return _cpu_mesh(mesh) or mesh
    return mesh


def _result_delta(orig_row, new_row):
    """Max-abs difference between one row's original and escalated
    values (None for non-numeric keys or NaN-poisoned originals) — the
    compact original-vs-escalated record for quarantine.json."""
    try:
        a, b = np.asarray(orig_row), np.asarray(new_row)
        if not (np.issubdtype(a.dtype, np.number) and a.shape == b.shape):
            return None
        d = float(np.max(np.abs(a.astype(np.complex128)
                                - b.astype(np.complex128))))
        return d if np.isfinite(d) else None
    except Exception:
        return None


def _escalate_row(compute, solo, status_before, mesh, shard, index):
    """Climb the escalation ladder for one flagged row.

    Returns ``(retried_row_or_None, rungs_tried, resolved_rung,
    status_after)``.  A retried row is returned only when a rung
    produced a HEALTHY one (finite, no SEVERE status bits) — an
    escalated result that is still flagged is never adopted, the
    original (auditable) values stay in the shard."""
    tried = []
    status_after = status_before
    for rung in escalation_rungs():
        tried.append(rung)
        metrics.counter("escalation_rungs").inc()
        try:
            with span("escalation_rung", shard=shard, index=index,
                      rung=rung), _rung_flags(rung):
                retried = {k: np.asarray(v)[:1]
                           for k, v in compute(solo,
                                               _rung_mesh(rung, mesh)).items()}
        except Exception as e:
            log_event("shard_escalate_failed", shard=shard, index=index,
                      rung=rung, error=str(e)[:200])
            continue
        st = _row_status(retried, 0)
        if nonfinite_rows(retried).size:
            st |= health.NONFINITE_INTERMEDIATE
        status_after = st
        healthy = not bool(health.any_bit(st))
        log_event("shard_escalate", shard=shard, index=index, rung=rung,
                  status_before=int(status_before), status_after=int(st),
                  resolved=healthy)
        if healthy:
            metrics.counter("escalations_resolved").inc()
            return retried, tried, rung, st
    return None, tried, None, status_after


# ------------------------------------------------------- retry / degradation


def _is_oom(e):
    s = str(e)
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s)


def _is_transient(e):
    if isinstance(e, faults.TransientInjectedError):
        return True
    s = str(e)
    return any(tok in s for tok in (
        "UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED",
        "Socket closed", "Connection reset", "failed to connect"))


def eval_with_recovery(compute, chunk, shard, max_retries=3, backoff_s=0.5,
                       _depth=0):
    """Evaluate one shard chunk with retry/backoff and OOM halving.

    compute : callable(chunk_dict) -> dict of per-row numpy arrays (same
        leading length as the chunk).
    Transient errors (dead-tunnel RPC strings, injected faults) retry up
    to ``max_retries`` times with exponential backoff; a device OOM
    halves the batch and evaluates the two halves independently
    (recursively, down to single rows).  Anything else propagates."""
    n = len(next(iter(chunk.values())))
    attempt = 0
    while True:
        try:
            # span per attempt: a failing attempt is a span with
            # ok=False + error, so retries are visible in the
            # wall-time tree, not just as shard_retry events
            with span("shard_attempt", shard=shard, rows=n,
                      attempt=attempt + 1):
                faults.check("shard_eval")
                return compute(chunk)
        except Exception as e:
            if _is_oom(e) and n > 1:
                half = n // 2
                metrics.counter("shard_oom_splits").inc()
                log_event("shard_oom_split", shard=shard, rows=n,
                          split=[half, n - half], error=str(e)[:200])
                lo = eval_with_recovery(
                    compute, {k: v[:half] for k, v in chunk.items()},
                    shard, max_retries, backoff_s, _depth + 1)
                hi = eval_with_recovery(
                    compute, {k: v[half:] for k, v in chunk.items()},
                    shard, max_retries, backoff_s, _depth + 1)
                return {k: np.concatenate([lo[k], hi[k]]) for k in lo}
            if _is_transient(e) and attempt < max_retries:
                attempt += 1
                delay = backoff_s * (2.0 ** (attempt - 1))
                metrics.counter("shard_retries").inc()
                log_event("shard_retry", shard=shard, attempt=attempt,
                          max_retries=max_retries, delay_s=round(delay, 3),
                          error=str(e)[:200])
                time.sleep(delay)
                continue
            raise


_PROBE_VERDICT = None  # per-process cache: backend health doesn't flap


def resolve_mesh(make_mesh, mesh=None):
    """Resolve the sweep mesh, degrading to the CPU backend when the
    accelerator is unhealthy.

    When no mesh is given and the platform is not explicitly cpu, the
    backend is health-probed in a subprocess first
    (:func:`raft_tpu.utils.devices.probe_backend`) — a dead tunnel hangs
    in-process jax init, which would otherwise take the whole sweep down
    with it.  On probe failure the process is pinned to the CPU platform
    and a ``backend_fallback`` event is logged; the pin only takes
    effect before the first in-process backend init, so call this before
    any jax computation (``backend_fallback_failed`` is logged when the
    pin could not be applied).  The probe verdict is cached per process
    (one subprocess, not one per sweep)."""
    global _PROBE_VERDICT
    if mesh is not None:
        return mesh
    # multi-host pods: RAFT_TPU_DIST wires jax.distributed.initialize
    # in BEFORE any backend init, so make_mesh() sees the global device
    # set (jax.devices() spans every process after initialize)
    from raft_tpu.parallel.sweep import ensure_distributed

    ensure_distributed()
    from raft_tpu.utils.devices import probe_backend

    # an installed accelerator plugin (axon) selects its platform with
    # JAX_PLATFORMS *unset*, so an empty env var means "unknown, possibly
    # accelerator" — probe unless the platform is explicitly cpu
    platform = (os.environ.get("JAX_PLATFORMS", "") or "").split(",")[0]
    forced = faults.take("unhealthy", "backend_probe")
    unhealthy = forced
    if not forced and platform != "cpu":
        if _PROBE_VERDICT is None:
            _PROBE_VERDICT = probe_backend()
        unhealthy = not _PROBE_VERDICT
    if unhealthy:
        import jax

        pinned = False
        try:
            jax.config.update("jax_platforms", "cpu")
            pinned = jax.default_backend() == "cpu"
        except Exception:
            pinned = False
        if pinned:
            log_event("backend_fallback", from_platform=platform or "default",
                      to_platform="cpu", forced_by_fault=forced)
        else:
            # a backend was already initialized in-process; the sweep
            # will run (or fail) on it — don't log a fallback that
            # didn't happen
            log_event("backend_fallback_failed",
                      from_platform=platform or "default",
                      reason="jax backend already initialized; cpu pin "
                             "had no effect")
    return make_mesh()


# ------------------------------------------------------------- sweep runner


@contextlib.contextmanager
def _maybe_profile():
    """Capture a jax profiler trace of the block when ``RAFT_TPU_PROFILE``
    is set (generalizes the bench-only capture to any checkpointed
    sweep).  Profiling must never take the sweep down: start/stop
    failures are logged (``profile_failed``) and swallowed."""
    prof_dir = config.get("PROFILE")
    if not prof_dir:
        yield
        return
    started = False
    try:
        import jax

        jax.profiler.start_trace(prof_dir)
        started = True
        log_event("profile_start", dir=prof_dir)
    except Exception as e:
        log_event("profile_failed", error=str(e)[:200])
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                log_event("profile_stop", dir=prof_dir)
            except Exception as e:
                log_event("profile_failed", error=str(e)[:200])


def _dump_metrics(out_dir, manifest, counters0):
    """Snapshot the metrics registry at sweep_done: ``metrics.json`` in
    the checkpoint directory, a copy inside the manifest (so a resumed
    run's manifest still carries the last completed picture), a
    ``metrics_snapshot`` event, and the Prometheus text export when
    ``RAFT_TPU_METRICS`` points somewhere.

    The registry is process-cumulative; ``counters0`` is the counter
    picture taken at sweep start, so ``counters`` in the dumped
    snapshot is THIS sweep's delta (a second sweep in the same process
    must not claim the first one's shards) — the raw totals stay
    available under ``counters_total``."""
    snap = metrics.snapshot()
    snap["counters_total"] = dict(snap["counters"])
    snap["counters"] = {k: v - counters0.get(k, 0)
                        for k, v in snap["counters"].items()
                        if v - counters0.get(k, 0)}
    try:
        _atomic_json(os.path.join(out_dir, METRICS_NAME), snap)
        manifest["metrics"] = snap
        _atomic_json(_manifest_path(out_dir), manifest)
    except OSError:
        pass  # telemetry must not fail the sweep that produced it
    log_event("metrics_snapshot", snapshot=snap)
    prom_path = config.get("METRICS")
    if prom_path:
        metrics.export(prom_path)
    return snap


def evaluate_shard(compute, chunk, shard, offset, mesh, max_retries=3,
                   backoff_s=0.5, quarantine_retry=True, on_result=None):
    """One shard's full fault-tolerant evaluation — the unit of work
    shared by the serial checkpointed runner and the fabric workers
    (:mod:`raft_tpu.parallel.fabric`), so an N-worker sweep judges and
    records a shard EXACTLY like the serial path does.

    Orchestration: retry/backoff/OOM-halving eval -> injected-NaN
    fault -> non-finite + status-flagged row quarantine/escalation ->
    ``on_result(out, entries)`` (the caller persists the shard inside
    the shard span so write time stays on the telemetry tree) ->
    counters + the ``shard_wall_s`` histogram (which feeds the fabric's
    straggler-steal threshold).  Returns ``(out, entries, wall_s)``."""
    rows = len(next(iter(chunk.values())))
    with span("shard", shard=shard, rows=rows):
        log_event("shard_start", shard=shard, rows=rows)
        t_sh = time.perf_counter()
        if faults.take("delay", "shard_eval"):
            # deliberately slowed dispatch (fixed 0.25 s): the drill
            # the perf-regression sentinel (`obs runs regress`) must
            # catch as a shard_wall_s / span-histogram regression
            time.sleep(0.25)
        out = eval_with_recovery(
            lambda c: {k: np.asarray(v)[: len(next(iter(c.values())))]
                       for k, v in compute(c, mesh).items()},
            chunk, shard, max_retries=max_retries, backoff_s=backoff_s)
        if faults.take("nan", "shard_result"):
            for k, v in out.items():
                a = np.array(v)
                if np.issubdtype(a.dtype, np.inexact):
                    a[0] = np.nan
                    out[k] = a
        bad = nonfinite_rows(out)
        flagged = flagged_rows(out)
        entries = []
        if bad.size or flagged.size:
            out, entries = _quarantine_shard(
                compute, chunk, out, bad, flagged, shard, offset, mesh,
                retry_solo=quarantine_retry)
        if on_result is not None:
            on_result(out, entries)
        wall = time.perf_counter() - t_sh
        metrics.counter("shards_done").inc()
        metrics.counter("rows_evaluated").inc(rows)
        metrics.counter("rows_quarantined").inc(
            sum(1 for e in entries if not e.get("resolved")))
        metrics.counter("rows_flagged").inc(len(flagged_rows(out)))
        # latency exemplar: enough identity to name the p99 SHARD from
        # a /metrics scrape or a flight dump — which shard, how many
        # rows, how many rows stayed quarantined, on which worker —
        # and the span ids to pull its retry/escalation subtree out of
        # a merged trace
        from raft_tpu.obs import spans as spans_mod

        ex = {"shard": int(shard), "rows": int(rows),
              "quarantined": sum(1 for e in entries
                                 if not e.get("resolved"))}
        wid = config.raw("WORKER_ID")
        if wid:
            ex["worker"] = wid
        ids = spans_mod.current_ids()
        if ids is not None:
            ex["trace_id"], ex["span_id"] = ids
        metrics.histogram("shard_wall_s").observe(wall, exemplar=ex)
        log_event("shard_done", shard=shard, rows=rows,
                  wall_s=round(wall, 3))
    return out, entries, wall


def run_checkpointed(compute, cases, out_dir, shard_size, mesh, out_keys,
                     on_shard=None, max_retries=3, backoff_s=0.5,
                     quarantine_retry=True):
    """Shared fault-tolerant core of the checkpointed sweep drivers.

    compute : callable(chunk_dict, mesh) -> dict of stacked outputs
        (jax or numpy arrays, leading axis == chunk length; the callable
        must pad to the mesh itself if needed — the core always passes
        chunks whose length it reports truthfully and trims nothing).
    cases : dict of equal-length (N, ...) numpy arrays.

    Orchestration per shard: resume-validate -> (recompute on
    corruption) -> retry/backoff/OOM-halving eval -> NaN quarantine ->
    atomic write -> manifest update.  Returns the dict of concatenated
    results; quarantined row indices/params are in
    ``<out_dir>/quarantine.json`` and the rows themselves are left
    non-finite (aggregate nan-aware, or drop via the quarantine list)."""
    os.makedirs(out_dir, exist_ok=True)
    cases = {k: np.asarray(v) for k, v in cases.items()}
    lengths = {k: len(v) for k, v in cases.items()}
    if len(set(lengths.values())) > 1:
        raise ValueError(
            f"ragged case dict: all case arrays must have equal length, "
            f"got {lengths}")

    # elastic fabric path: RAFT_TPU_FABRIC_WORKERS > 1 routes the sweep
    # through N worker subprocesses claiming shards from the lease
    # ledger — zero caller changes, same out_dir layout, same results.
    # Needs a fabric entry spec on the compute closure (the sweep
    # drivers propagate it from the evaluator's `_raft_fabric_entry`
    # stamp) so workers can rebuild the evaluator in their own process.
    workers = int(config.get("FABRIC_WORKERS") or 0)
    if workers > 1:
        spec = getattr(compute, "_raft_fabric_entry", None)
        if spec:
            from raft_tpu.parallel import fabric

            return fabric.run_fabric(
                out_dir, workers=workers, entry=spec["entry"],
                entry_kwargs=spec.get("kwargs"), warmup=spec.get("warmup"),
                cases=cases, out_keys=out_keys, shard_size=shard_size,
                on_shard=on_shard, max_retries=max_retries,
                backoff_s=backoff_s, quarantine_retry=quarantine_retry)
        log_event("fabric_unavailable", out_dir=out_dir,
                  reason="RAFT_TPU_FABRIC_WORKERS set but the evaluator "
                         "carries no _raft_fabric_entry spec; running "
                         "serial in-process")

    n = next(iter(lengths.values()))
    n_shards = (n + shard_size - 1) // shard_size

    fingerprint = compute_fingerprint(cases, out_keys, shard_size, mesh)
    progress = {"out_dir": out_dir, "shards_done": 0, "n_shards": n_shards}
    # profiler outermost: the sweep span's TraceAnnotation must begin
    # INSIDE the active profiler session to land on the timeline
    with _maybe_profile(), \
            span("sweep", out_dir=out_dir, n_cases=n, n_shards=n_shards), \
            maybe_heartbeat(devices=list(mesh.devices.flat),
                            progress=progress) as heartbeat:
        manifest = init_manifest(out_dir, fingerprint, n_shards)
        log_event("sweep_start", out_dir=out_dir, n_cases=n,
                  n_shards=n_shards, shard_size=shard_size,
                  out_keys=list(out_keys),
                  mesh_shape=fingerprint["mesh_shape"])

        t0 = time.perf_counter()
        counters0 = dict(metrics.snapshot()["counters"])
        results = []
        n_quarantined = 0
        n_flagged = 0
        for s in range(n_shards):
            path = os.path.join(out_dir, f"shard_{s:04d}.npz")
            sl = slice(s * shard_size, min((s + 1) * shard_size, n))
            rows = sl.stop - sl.start
            if os.path.exists(path):
                try:
                    out = load_shard(path, out_keys, expect_rows=rows)
                    results.append(out)
                    resumed_flagged = len(flagged_rows(out))
                    # rows still bad in the stored shard (NaN or severe
                    # bits) ARE this sweep's quarantined rows even when
                    # the shard resumed from disk — otherwise a resumed
                    # run reports n_quarantined=0 while the shard data
                    # and quarantine.json still carry the poison
                    resumed_bad = len({int(i) for i in nonfinite_rows(out)}
                                      | {int(i) for i in flagged_rows(out)})
                    n_flagged += resumed_flagged
                    n_quarantined += resumed_bad
                    metrics.counter("shards_resumed").inc()
                    metrics.counter("rows_flagged").inc(resumed_flagged)
                    metrics.counter("rows_quarantined").inc(resumed_bad)
                    log_event("shard_resume", shard=s, rows=rows)
                    progress["shards_done"] = s + 1
                    if on_shard is not None:
                        on_shard(s + 1, n_shards, False)
                    continue
                except ShardCorruptError as e:
                    # re-queue: a truncated/stale shard is recomputed,
                    # not fatal
                    metrics.counter("shards_corrupt").inc()
                    log_event("shard_corrupt", shard=s, error=str(e)[:300])
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
            mark_shard(manifest, out_dir, s, "running")
            chunk = {k: v[sl] for k, v in cases.items()}

            def persist(out_, entries_, _s=s, _path=path):
                # re-judge even when clean: a recomputed shard must
                # clear its own stale quarantine entries from a
                # previous run (no file is created for sweeps that
                # never quarantined anything)
                if entries_ or os.path.exists(_quarantine_path(out_dir)):
                    record_quarantine(out_dir, _s, entries_)
                atomic_savez(_path, **out_)

            out, entries, wall = evaluate_shard(
                compute, chunk, s, sl.start, mesh, max_retries=max_retries,
                backoff_s=backoff_s, quarantine_retry=quarantine_retry,
                on_result=persist)
            # rows still bad after recovery/escalation (resolved
            # escalation entries are audit records, not quarantined rows)
            shard_quarantined = sum(
                1 for e in entries if not e.get("resolved"))
            n_quarantined += shard_quarantined
            shard_flagged = len(flagged_rows(out))  # severe bits left
            n_flagged += shard_flagged
            mark_shard(manifest, out_dir, s, "done",
                       wall_s=round(wall, 3),
                       quarantined=shard_quarantined,
                       flagged=shard_flagged)
            results.append(out)
            progress["shards_done"] = s + 1
            if on_shard is not None:
                on_shard(s + 1, n_shards, True)

        if heartbeat is not None:
            # terminal beat BEFORE the snapshot: the end-of-run memory
            # watermark must be inside metrics.json, not after it
            heartbeat.stop()
        _dump_metrics(out_dir, manifest, counters0)
        log_event("sweep_done", out_dir=out_dir, n_cases=n,
                  n_quarantined=n_quarantined, n_flagged=n_flagged,
                  wall_s=round(time.perf_counter() - t0, 3))
        # longitudinal perf trajectory: one schema-versioned run record
        # per sweep session when RAFT_TPU_RUNS_DIR is set (no-op and
        # never fatal otherwise) — what `obs runs regress` gates on
        from raft_tpu.obs import runs as obs_runs

        obs_runs.maybe_record(
            "sweep", label=os.path.basename(os.path.normpath(out_dir)),
            wall_s=time.perf_counter() - t0,
            extra={"n_cases": n, "n_shards": n_shards,
                   "n_quarantined": n_quarantined, "n_flagged": n_flagged})
    return {k: np.concatenate([r[k] for r in results]) for k in out_keys}


def _quarantine_shard(compute, chunk, out, bad, flagged, shard, offset, mesh,
                      retry_solo=True):
    """Handle non-finite AND status-flagged rows in one computed shard.

    With the escalation ladder OFF, NaN rows keep the original
    behavior — optional solo re-evaluation on the CPU backend (a
    TPU-side numerical pathology — e.g. f32 overflow in the drag
    linearization — can converge fine in host f64), quarantine entry
    only when the row stays non-finite — and finite-but-flagged rows
    are recorded (reason included) without a re-solve.  With
    ``RAFT_TPU_ESCALATE`` active, every bad row climbs the ladder and
    gets an entry either way: resolved rows document which rung cleared
    which bits plus original-vs-escalated result deltas, persistent
    rows document the surviving reason.  Unhealthy rows stay as
    computed (NaN rows stay NaN) so downstream aggregation can never
    mistake them for physics."""
    out = {k: np.array(v) for k, v in out.items()}
    entries = []
    rungs = escalation_rungs()
    cpu_mesh = _cpu_mesh(mesh) if retry_solo else None
    bad_set = {int(b) for b in bad}
    severe_unresolved = 0
    for i in sorted(bad_set | {int(f) for f in flagged}):
        nonfinite = i in bad_set
        keys_bad = [k for k, v in out.items()
                    if np.issubdtype(np.asarray(v).dtype, np.number)
                    and not np.isfinite(np.asarray(v[i])).all()]
        status_before = _row_status(out, i)
        if nonfinite:
            status_before |= health.NONFINITE_INTERMEDIATE
        solo = {k: v[i:i + 1] for k, v in chunk.items()}
        recovered = False
        escalation = None
        status_after = status_before
        if rungs:
            retried, tried, resolved_by, status_after = _escalate_row(
                compute, solo, status_before, mesh, shard, offset + i)
            delta = None
            if retried is not None:
                delta = {k: _result_delta(out[k][i], retried[k][0])
                         for k in out if k != "status"}
                for k in out:
                    out[k][i] = retried[k][0]
                recovered = True
            escalation = {
                "mode": config.get("ESCALATE"),
                "rungs_tried": list(tried),
                "resolved_by": resolved_by,
                "result_delta": delta,
            }
        elif nonfinite and cpu_mesh is not None:
            try:
                retried = {k: np.asarray(v)[:1]
                           for k, v in compute(solo, cpu_mesh).items()}
                if not nonfinite_rows(retried).size:
                    for k in out:
                        out[k][i] = retried[k][0]
                    recovered = True
                    status_after = _row_status(out, i)
            except Exception as e:
                log_event("shard_quarantine_retry_failed", shard=shard,
                          index=offset + i, error=str(e)[:200])
        log_event("shard_quarantine", shard=shard, index=offset + i,
                  keys=keys_bad, recovered=recovered,
                  status=int(status_before),
                  reason=health.describe(status_before))
        if not recovered and (status_after & health.SEVERE
                              or nonfinite):
            severe_unresolved += 1
        # escalated rows are recorded even when resolved (the ladder's
        # outcome is part of the audit trail); the legacy NaN-only path
        # records only rows that stayed bad
        if rungs or not recovered:
            entry = {
                "shard": shard,
                "index": offset + i,
                "keys_nonfinite": keys_bad,
                "status": int(status_before),
                "reason": health.describe(status_before),
                "status_after": int(status_after),
                "reason_after": health.describe(status_after),
                "resolved": bool(recovered),
                "case": {k: np.asarray(v[i]).tolist()
                         for k, v in chunk.items()},
            }
            if escalation is not None:
                entry["escalation"] = escalation
            entries.append(entry)
    if severe_unresolved:
        # a SEVERE row the ladder could not clear is a postmortem
        # moment: persist the flight ring (one dump per shard, after
        # the loop — not one per row) with the recent solve/dispatch
        # history that led to it
        from raft_tpu.obs import flight

        flight.dump(trigger="quarantine-severe")
    return out, entries


def _cpu_mesh(mesh):
    """A single-CPU-device mesh with the same axis names as ``mesh``
    (for solo quarantine retries); None when no CPU backend exists."""
    import jax
    from jax.sharding import Mesh

    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        return None
    devs = np.array([cpu]).reshape((1,) * len(mesh.axis_names))
    return Mesh(devs, mesh.axis_names)
