"""Longitudinal run-record store + perf-regression sentinel.

Every number the repo's telemetry produces today is a one-shot
snapshot: ``BENCH_rNN.json`` artifacts are hand-compared, and a perf
regression between two PRs is invisible until a human rereads JSON.
This module gives the trajectory a home and a gate:

* every bench / checkpointed-sweep / serve session ends by writing a
  canonical, **schema-versioned run record** — environment fingerprint
  (platform, device count, x64, jax/jaxlib versions, raft_tpu source
  hash), the full metrics-registry snapshot (counters / gauges /
  histograms / sliding windows, which carries the per-axis padding-
  waste and serve-stage attribution histograms), the per-program
  device-cost ledger, compile counts, wall time and the git SHA when
  available — into an append-only store under ``RAFT_TPU_RUNS_DIR``
  (unset = recording disabled, zero overhead);
* ``python -m raft_tpu.obs runs regress`` compares the newest record
  against a **pinned baseline** record with noise-aware per-metric
  thresholds (relative tolerance + a minimum-absolute floor so a
  near-zero baseline cannot fail CI on microseconds of jitter),
  exiting 1 on regression and naming the regressed metric; an
  environment-fingerprint mismatch downgrades failures to warnings —
  numbers from different hosts/backends are not comparable;
* ``python -m raft_tpu.obs runs ingest BENCH_*.json`` imports the
  existing bench artifacts so the trajectory starts populated.

Pure stdlib at import time; jax is consulted only when it is already
loaded in the recording process (the CLI verbs never initialize a
backend).  Recording is best-effort end to end — telemetry must never
take down the run that produced it.
"""

from __future__ import annotations

import fnmatch
import json
import os
import re
import subprocess
import sys
import time
import uuid

from raft_tpu.obs import metrics
from raft_tpu.utils import config
from raft_tpu.utils.structlog import log_event, run_id

SCHEMA_VERSION = 1

#: name of the baseline-pin file inside the store: its content is the
#: FILENAME of the pinned baseline record (``runs pin`` writes it)
BASELINE_NAME = "BASELINE"

#: env-fingerprint keys that must match for two records' numbers to be
#: comparable.  The raft_tpu source hash is deliberately absent — the
#: whole point of the sentinel is comparing across code changes.
ENV_COMPARE_KEYS = ("platform", "device_kind", "n_devices", "x64",
                    "host", "jax", "jaxlib")


def runs_dir(create=False):
    """The store directory from ``RAFT_TPU_RUNS_DIR`` (None when unset
    — recording disabled)."""
    d = config.get("RUNS_DIR") or ""
    if not d:
        return None
    if create:
        os.makedirs(d, exist_ok=True)
    return d


# ------------------------------------------------------------ record build


def env_fingerprint():
    """Where these numbers were measured: host + toolchain + backend.

    jax is queried only when the recording process already imported it
    (a sweep/serve/bench process has); a jax-free CLI record carries
    the host keys only and is treated as not-comparable by
    :func:`regress_records`."""
    import platform as _platform

    env = {"host": _platform.node(),
           "python": _platform.python_version()}
    try:
        from raft_tpu.aot.bank import code_fingerprint

        env["code"] = code_fingerprint()
    except Exception:
        pass
    if "jax" in sys.modules:
        try:
            import jax
            import jaxlib

            env["jax"] = jax.__version__
            env["jaxlib"] = jaxlib.__version__
            devs = jax.devices()
            env.update(platform=devs[0].platform,
                       device_kind=devs[0].device_kind,
                       n_devices=len(devs),
                       x64=bool(jax.config.jax_enable_x64))
        except Exception:
            pass
    return env


def git_sha():
    """HEAD SHA of the enclosing checkout, or None (best-effort: the
    store must work outside a git tree too)."""
    try:
        p = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            timeout=5.0,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
        sha = (p.stdout or "").strip()
        return sha if p.returncode == 0 and re.fullmatch(r"[0-9a-f]{40}",
                                                         sha) else None
    except Exception:
        return None


def _compile_counts():
    """Real-vs-total XLA compile counts from the recompile sentinel,
    when it is armed in this process (disk-cache hits emit compile
    events too; the sentinel pairs them — see raft_tpu.analysis
    .recompile)."""
    mod = sys.modules.get("raft_tpu.analysis.recompile")
    if mod is None:
        return {}
    try:
        return {"xla_compiles": mod.PROCESS_LOG.count,
                "xla_real_compiles": mod.PROCESS_LOG.real_count}
    except Exception:
        return {}


def build_record(kind, label=None, wall_s=None, extra=None, events=None):
    """Assemble one run record from the live process state.

    events : optional parsed JSONL capture (list of event dicts): its
        :func:`raft_tpu.obs.report.report_data` sections are embedded
        under ``report`` — the machine-readable twin of ``obs report``
        — instead of re-parsing rendered text.
    """
    record = {
        "schema": SCHEMA_VERSION,
        "kind": str(kind),
        "label": str(label) if label else None,
        "t_unix": round(time.time(), 3),
        "wall_s": round(float(wall_s), 3) if wall_s is not None else None,
        "run_id": run_id(),
        "git_sha": git_sha(),
        "env": env_fingerprint(),
        "snapshot": metrics.snapshot(),
        "compiles": _compile_counts(),
        "extra": dict(extra) if extra else {},
    }
    try:
        from raft_tpu.aot.bank import ledger_summary

        ledger = ledger_summary()
    except Exception:
        ledger = []
    if ledger:
        record["cost_ledger"] = ledger
    if events:
        from raft_tpu.obs import report

        record["report"] = report.report_data(events)
    return record


def write_record(record, dir=None):
    """Append one record to the store (atomic tmp + rename; filenames
    sort chronologically, nothing is ever overwritten).  Returns the
    path."""
    d = dir or runs_dir(create=True)
    if d is None:
        raise ValueError("no store: set RAFT_TPU_RUNS_DIR or pass --dir")
    os.makedirs(d, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(
        record.get("t_unix") or time.time()))
    name = f"run-{stamp}-{os.getpid()}-{uuid.uuid4().hex[:6]}.json"
    path = os.path.join(d, name)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=1, default=str)
    os.replace(tmp, path)
    log_event("run_record", kind=record.get("kind"), path=path,
              label=record.get("label"))
    return path


def maybe_record(kind, label=None, wall_s=None, extra=None, events=None):
    """Record-if-enabled hook for the runtime exit points (sweep_done,
    serve shutdown, bench modes): no-op unless ``RAFT_TPU_RUNS_DIR`` is
    set, and never raises — a failed record must not fail the run."""
    try:
        if runs_dir() is None:
            return None
        return write_record(build_record(kind, label=label, wall_s=wall_s,
                                         extra=extra, events=events))
    except Exception:
        return None


# ------------------------------------------------------------- store reads


def load_record(path):
    with open(path) as f:
        record = json.load(f)
    if not isinstance(record, dict) or "schema" not in record:
        raise ValueError(f"{path}: not a run record (no 'schema' field)")
    if int(record["schema"]) > SCHEMA_VERSION:
        raise ValueError(
            f"{path}: schema v{record['schema']} is newer than this "
            f"tool (v{SCHEMA_VERSION})")
    return record


def list_records(dir=None):
    """``[(path, record), ...]`` chronological by recorded ``t_unix``
    (filename as the tiebreak — same-second records would otherwise
    order by their random uniqueness suffix); unparseable files are
    skipped, not fatal."""
    d = dir or runs_dir()
    if d is None or not os.path.isdir(d):
        return []
    out = []
    for name in sorted(os.listdir(d)):
        if not (name.startswith("run-") and name.endswith(".json")):
            continue
        path = os.path.join(d, name)
        try:
            out.append((path, load_record(path)))
        except (OSError, ValueError):
            continue
    out.sort(key=lambda pr: (pr[1].get("t_unix") or 0.0,
                             os.path.basename(pr[0])))
    return out


def pinned_baseline(dir=None):
    """Path of the pinned baseline record, or None."""
    d = dir or runs_dir()
    if d is None:
        return None
    pin = os.path.join(d, BASELINE_NAME)
    try:
        with open(pin) as f:
            name = f.read().strip()
    except OSError:
        return None
    path = name if os.path.isabs(name) else os.path.join(d, name)
    return path if os.path.exists(path) else None


def pin_baseline(record_path, dir=None):
    """Pin one record as THE baseline `regress` compares against."""
    d = dir or runs_dir(create=True)
    if d is None:
        raise ValueError("no store: set RAFT_TPU_RUNS_DIR or pass --dir")
    load_record(record_path)  # must parse before we pin it
    rel = (os.path.basename(record_path)
           if os.path.dirname(os.path.abspath(record_path))
           == os.path.abspath(d) else os.path.abspath(record_path))
    tmp = os.path.join(d, BASELINE_NAME + ".tmp")
    with open(tmp, "w") as f:
        f.write(rel + "\n")
    os.replace(tmp, os.path.join(d, BASELINE_NAME))
    return os.path.join(d, BASELINE_NAME)


# --------------------------------------------------------------- flattening


def _num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and v == v


def _flatten_extra(prefix, obj, out):
    if isinstance(obj, dict):
        for k in sorted(obj):
            _flatten_extra(f"{prefix}.{k}" if prefix else str(k),
                           obj[k], out)
    elif _num(obj):
        out[f"extra:{prefix}"] = float(obj)


def flatten(record):
    """One flat ``{metric_name: float}`` view of a record — the
    comparison domain of ``compare``/``regress``.  Names are
    namespaced by source: ``counter:<name>``, ``gauge:<name>:max``,
    ``hist:<name>:{mean,p50,p95}``, ``window:<name>:{p50,p95}``,
    ``stage:<name>:{p50,p95}`` (serve tail attribution),
    ``waste:<axis>`` (row-weighted per-axis padding waste) and
    ``extra:<dotted.path>`` for caller-provided scalars."""
    out = {}
    snap = record.get("snapshot") or {}
    for name, v in (snap.get("counters") or {}).items():
        if _num(v):
            out[f"counter:{name}"] = float(v)
    for name, g in (snap.get("gauges") or {}).items():
        if isinstance(g, dict) and _num(g.get("max")):
            out[f"gauge:{name}:max"] = float(g["max"])
    for name, h in (snap.get("histograms") or {}).items():
        if isinstance(h, dict) and h.get("count"):
            for k in ("mean", "p50", "p95"):
                if _num(h.get(k)):
                    out[f"hist:{name}:{k}"] = float(h[k])
    for name, w in (snap.get("windows") or {}).items():
        if isinstance(w, dict) and w.get("count"):
            for k in ("p50", "p95"):
                if _num(w.get(k)):
                    out[f"window:{name}:{k}"] = float(w[k])
    report = record.get("report") or {}
    stages = (report.get("serve_stages") or {})
    for col in ("p50", "p95"):
        rep = stages.get(col) or {}
        for name, v in (rep.get("stages") or {}).items():
            if _num(v):
                out[f"stage:{name}:{col}"] = float(v)
        if _num(rep.get("total_s")):
            out[f"stage:total:{col}"] = float(rep["total_s"])
    # per-axis padding waste: prefer the exact counter ratios (sum of
    # valid / padded entries over every dispatched row) so the flat
    # view reproduces the aggregate row-weighted waste bit-for-bit
    from raft_tpu.obs.report import waste_axes_from_counters

    for axis, a in waste_axes_from_counters(
            snap.get("counters") or {}).items():
        out[f"waste:{axis}"] = a["waste_frac"]
    # records built from a capture (`runs record --events`) carry the
    # waste table inside the embedded report, not the live registry
    for axis, a in ((report.get("waste") or {}).get("axes") or {}).items():
        if _num(a.get("waste_frac")):
            out.setdefault(f"waste:{axis}", float(a["waste_frac"]))
    _flatten_extra("", record.get("extra") or {}, out)
    if _num(record.get("wall_s")):
        out["wall_s"] = float(record["wall_s"])
    return out


# ---------------------------------------------------------------- regress

#: watch rules, first match wins: (fnmatch pattern over flattened
#: names, which direction is BETTER, relative tolerance [None = the
#: RAFT_TPU_RUNS_REL_TOL flag], minimum absolute worsening).  Metrics
#: matching no rule are informational — compared, never gated.
#: ``better="lower"`` means an increase can regress; floors are in the
#: metric's own unit and scaled by RAFT_TPU_RUNS_ABS_FLOOR.
#:
#: Latency histogram percentiles pin rel_tol=1.0: the registry's
#: histograms are log-bucketed at 4/decade, so a percentile moves in
#: x10^0.25 ≈ 1.78 quantization steps — any tolerance below 0.78 flags
#: single-bucket jitter on clean back-to-back runs, while 1.0 passes
#: one bucket step and fails two (≥ 2.2x, which a real slowdown is).
#: Throughput rules must stay tighter — at rel_tol 1.0 a higher-is-
#: better metric could never regress (worse-by > baseline needs a
#: negative rate).
WATCH_RULES = (
    # achieved-rate metrics (end in _s but higher is better): before
    # the generic latency rules
    ("hist:program_gflops_s:*", "higher", 0.5, 0.5),
    ("extra:*evals_per_s*", "higher", 0.5, 1.0),
    ("extra:*evals/s*", "higher", 0.5, 1.0),
    # padding waste (fraction of device work spent on masked pad rows)
    ("hist:pad_waste_*:mean", "lower", 0.5, 0.02),
    ("waste:*", "lower", 0.5, 0.02),
    # reliability counters: one stray event is noise, a jump is not
    ("counter:serve_errors", "lower", 0.5, 0.5),
    ("counter:serve_slo_breaches", "lower", 0.5, 1.5),
    ("counter:rows_quarantined", "lower", 0.5, 0.5),
    ("counter:shards_corrupt", "lower", 0.5, 0.5),
    ("counter:shard_retries", "lower", 0.5, 1.5),
    ("counter:shard_oom_splits", "lower", 0.5, 0.5),
    # per-TICK wall is a scheduling-policy metric, not a latency SLO:
    # the adaptive tick (PR 15) makes it bimodal BY DESIGN (near-empty
    # floor-window ticks vs dispatching ticks), so its percentiles
    # straddle log-bucket boundaries and flap on clean reruns.  The
    # request-level gates below (serve_request*/window/stage) are the
    # user-facing latency contract; tick walls stay informational.
    ("hist:serve_tick_s:*", "none", None, 0.0),
    ("hist:span_serve_tick_s:*", "none", None, 0.0),
    # latency-like: every *_s histogram/window/stage percentile
    ("hist:*_s:p50", "lower", 1.0, 0.02),
    ("hist:*_s:p95", "lower", 1.0, 0.05),
    ("hist:*_s:mean", "lower", 1.0, 0.02),
    ("window:*_s:p50", "lower", 1.0, 0.02),
    ("window:*_s:p95", "lower", 1.0, 0.05),
    ("stage:*:p50", "lower", 1.0, 0.02),
    ("stage:*:p95", "lower", 1.0, 0.05),
)


def watch_rule(name):
    """``(better, rel_tol | None, abs_floor)`` of the first matching
    rule, or None.  A rule with ``better="none"`` EXEMPTS its metrics:
    first-match-wins, so it shields them from a later catch-all
    pattern (informational — compared, never gated)."""
    for pattern, better, rel, floor in WATCH_RULES:
        if fnmatch.fnmatchcase(name, pattern):
            return None if better == "none" else (better, rel, floor)
    return None


def env_mismatch(a, b):
    """Comparison keys on which two records' environments differ (a
    non-empty result means their numbers are not comparable)."""
    ea, eb = a.get("env") or {}, b.get("env") or {}
    if ea.get("ingested") or eb.get("ingested"):
        return ["ingested"]
    return [k for k in ENV_COMPARE_KEYS if ea.get(k) != eb.get(k)]


def compare_records(new, base):
    """Per-metric delta rows over the union of both records' flattened
    metrics (``runs compare``): name, base, new, delta, pct, watched
    direction."""
    fn, fb = flatten(new), flatten(base)
    rows = []
    for name in sorted(set(fn) | set(fb)):
        b, n = fb.get(name), fn.get(name)
        rule = watch_rule(name)
        row = {"metric": name, "base": b, "new": n,
               "better": rule[0] if rule else None}
        if b is not None and n is not None:
            row["delta"] = round(n - b, 6)
            if b:
                row["pct"] = round(100.0 * (n - b) / abs(b), 2)
        rows.append(row)
    return rows


def regress_records(new, base, rel_tol=None, floor_scale=None):
    """Noise-aware regression verdict of ``new`` against ``base``.

    A watched metric regresses when it moves in the WORSE direction by
    more than ``max(rule_rel_tol * |baseline|, abs_floor)`` — the
    relative tolerance absorbs proportional noise (per-rule: latency
    histogram percentiles use 1.0 to absorb their log-bucket
    quantization step, see WATCH_RULES), the absolute floor keeps
    near-zero baselines (a 2 ms p95) from failing on jitter.  An
    explicit ``rel_tol`` argument (the CLI ``--rel-tol``), or
    ``RAFT_TPU_RUNS_REL_TOL`` set in the environment, overrides every
    rule's tolerance — the noisier-host loosening knob.  An
    environment mismatch downgrades every failure to a warning: the
    numbers were measured on different hardware/toolchains.
    """
    # the env flag only overrides when actually SET — its default must
    # not shadow the per-rule tolerances
    env_rel = (float(config.get("RUNS_REL_TOL"))
               if config.raw("RUNS_REL_TOL") else None)
    default_rel = float(config.get("RUNS_REL_TOL"))
    floor_scale = (float(config.get("RUNS_ABS_FLOOR"))
                   if floor_scale is None else float(floor_scale))
    mismatch = env_mismatch(new, base)
    fn, fb = flatten(new), flatten(base)
    regressions, improvements = [], []
    checked = 0
    for name in sorted(set(fn) & set(fb)):
        rule = watch_rule(name)
        if rule is None:
            continue
        better, rule_rel, floor = rule
        b, n = fb[name], fn[name]
        checked += 1
        worsening = (n - b) if better == "lower" else (b - n)
        rel = (float(rel_tol) if rel_tol is not None
               else env_rel if env_rel is not None
               else rule_rel if rule_rel is not None else default_rel)
        threshold = max(rel * abs(b), floor * floor_scale)
        entry = {"metric": name, "base": round(b, 6), "new": round(n, 6),
                 "worsening": round(worsening, 6),
                 "threshold": round(threshold, 6), "better": better}
        if worsening > threshold:
            regressions.append(entry)
        elif -worsening > threshold:
            improvements.append(entry)
    return {
        "comparable": not mismatch,
        "env_mismatch": mismatch,
        # different kinds (a serve session vs a sweep baseline) still
        # compare on their metric intersection, but the caller should
        # know the workloads differ
        "kind_mismatch": (new.get("kind") != base.get("kind")),
        "checked": checked,
        "regressions": regressions,
        "improvements": improvements,
        "ok": not regressions or bool(mismatch),
    }


# ----------------------------------------------------------------- ingest


def ingest_bench(path):
    """One ``BENCH_rNN.json`` artifact as a run record: the headline
    value + every numeric breakdown leaf become ``extra`` metrics, the
    environment is marked ``ingested`` (pre-store numbers have no env
    fingerprint, so `regress` will only ever warn against them), and
    the record timestamp is the artifact's mtime so the imported
    trajectory keeps its real order."""
    with open(path) as f:
        bench = json.load(f)
    label = os.path.basename(path)
    m = re.search(r"(r\d+)", label)
    if isinstance(bench, dict) and "metric" not in bench and "rc" in bench:
        # early-round driver wrapper: {n, cmd, rc, tail, parsed: {...}}.
        # A round that produced NO parsed result (timeout/crash) still
        # belongs in the trajectory — as an explicitly failed record,
        # not a silent gap
        parsed = bench.get("parsed")
        if not isinstance(parsed, dict):
            return {
                "schema": SCHEMA_VERSION, "kind": "bench",
                "label": m.group(1) if m else label,
                "t_unix": round(os.path.getmtime(path), 3),
                "wall_s": None, "run_id": None, "git_sha": None,
                "env": {"ingested": True, "source": label},
                "snapshot": {}, "compiles": {},
                "extra": {"rc": bench.get("rc")},
                "headline": {"metric": None, "unit": None, "value": None,
                             "failed": True},
            }
        bench = parsed
    if not isinstance(bench, dict) or "metric" not in bench:
        raise ValueError(f"{path}: not a bench artifact (no 'metric')")
    extra = {k: bench[k] for k in ("value", "vs_baseline") if _num(bench.get(k))}
    unit = str(bench.get("unit") or "")
    if "evals/s" in unit and _num(bench.get("value")):
        extra["evals_per_s"] = float(bench["value"])
    _ingest_breakdown(bench.get("breakdown"), extra)
    return {
        "schema": SCHEMA_VERSION,
        "kind": "bench",
        "label": m.group(1) if m else label,
        "t_unix": round(os.path.getmtime(path), 3),
        "wall_s": None,
        "run_id": None,
        "git_sha": None,
        "env": {"ingested": True, "source": label},
        "snapshot": {},
        "compiles": {},
        "extra": extra,
        "headline": {"metric": bench.get("metric"), "unit": unit,
                     "value": bench.get("value")},
    }


def _ingest_breakdown(obj, extra, prefix="breakdown"):
    if isinstance(obj, dict):
        for k in sorted(obj):
            _ingest_breakdown(obj[k], extra, f"{prefix}.{k}")
    elif _num(obj):
        extra[prefix] = float(obj)
