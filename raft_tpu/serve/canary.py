"""Golden-answer canary prober for the serving fleet.

Health bits, breakers and lease TTLs catch replicas that are *dead or
erroring* — none of them catches a replica that is **silently wrong**:
warmed from a stale AOT bank, running under divergent flags, or on a
skewed toolchain, it answers 200 with numbers that differ from every
other replica's.  The canary is the in-band verification layer:

* **golden capture** — at serve/router warmup, each registered design
  gets a content-addressed golden row: the design content hash + the
  exact canary case bits + the probed out_keys (REUSING
  :func:`raft_tpu.serve.cache.result_cache_key`, so the golden's key
  IS the serving cache key) mapping to the selected outputs and the
  int32 status word.  On a replica, :func:`capture_goldens` dispatches
  each design once through the production funnel; at the router, the
  first probe response per key becomes the golden.
* **probing** — :class:`RouterCanary` is a daemon thread in the router
  process (blocking HTTP on THIS thread, never the event loop — the
  membership prober's pattern): every ``RAFT_TPU_CANARY_S`` seconds it
  sends one synthetic ``/evaluate`` per (replica, design) pair,
  pinned to each replica directly at its ledger endpoint (the ring
  routes a named design to ONE owner, so probing through the ladder
  would never see the others).
* **comparison** — the status word must match the golden **bit for
  bit**; float outputs compare within ``RAFT_TPU_CANARY_RTOL`` /
  ``ATOL``.  ``canary_pass`` / ``canary_fail`` counters feed the
  ``canary-failure`` alert rule.
* **provenance cross-check** — every probe response carries the
  ``x-raft-provenance`` header (bank key + bank sidecar sha + code
  hash + flags key + replica id); the canary groups them per design
  and requires all replicas to agree on everything but the replica id
  (:func:`raft_tpu.obs.alerts.provenance_consistency`).  Two replicas
  serving from different bank versions alarm **even while both are
  individually numerically fine** — the ``canary_parity_ok`` gauge
  drops to 0, the offending provenance is published as the
  ``canary_parity`` alert context, and the ``canary-parity`` rule
  fires.

Zero overhead when ``RAFT_TPU_CANARY_S`` is unset: no thread, no
goldens, no per-request cost (the provenance header is stamped by the
server regardless — it is one precomputed string).
"""

from __future__ import annotations

import http.client
import json
import threading
import time

import numpy as np

from raft_tpu.obs import alerts, metrics
from raft_tpu.serve.cache import result_cache_key
from raft_tpu.utils import config
from raft_tpu.utils.structlog import log_event

#: the fixed synthetic sea state every canary probe evaluates —
#: deterministic by construction (the golden is whatever the first
#: dispatch answered, healthy or flagged; only DIVERGENCE alarms)
CANARY_CASE = (4.0, 9.0, 0.0)

#: alert-context key the parity verdict publishes under (the
#: ``canary-parity`` / ``canary-failure`` default rules attach it)
CONTEXT_KEY = "canary_parity"


def canary_out_keys(served=None):
    """The out_keys canary probes request (``RAFT_TPU_CANARY_OUT_KEYS``,
    default ``X0,status`` — small arrays, cheap probes), intersected
    with the served set when given; ``status`` is always included."""
    raw = config.get("CANARY_OUT_KEYS") or "X0,status"
    keys = tuple(k.strip() for k in raw.split(",") if k.strip())
    if served is not None:
        keys = tuple(k for k in keys if k in served)
    if "status" not in keys:
        keys = keys + ("status",)
    return keys


def golden_key(fingerprint, case, out_keys):
    """The content-addressed golden key: design content hash + exact
    case bits + probed out_keys — :func:`raft_tpu.serve.cache.
    result_cache_key` verbatim."""
    Hs, Tp, beta = case
    return result_cache_key(fingerprint,
                            {"Hs": float(Hs), "Tp": float(Tp),
                             "beta": float(beta)}, out_keys)


def _release_view():
    """The release-parity context for :func:`raft_tpu.obs.alerts.
    provenance_consistency` — which release ids are legitimately in
    the fleet right now (mid-rollout: two of them) and which bank shas
    each shipped.  None (pre-release behavior) when no release
    infrastructure is present or readable; the canary must keep
    working against pointer-less banks."""
    try:
        from raft_tpu.aot import release

        return release.parity_context()
    except Exception:  # noqa: BLE001 — parity must not die on IO
        return None


def decode_outputs(outputs_json):
    """Host numpy arrays from one ``/evaluate`` response's ``outputs``
    payload (complex values arrive split as ``{"real", "imag"}`` —
    see ``raft_tpu.serve.http._json_value``)."""
    out = {}
    for k, v in (outputs_json or {}).items():
        if isinstance(v, dict) and "real" in v and "imag" in v:
            out[k] = (np.asarray(v["real"], dtype=float)
                      + 1j * np.asarray(v["imag"], dtype=float))
        else:
            out[k] = np.asarray(v)
    return out


class CanaryState:
    """The socket-free canary core: golden store + comparison +
    cross-replica provenance bookkeeping.  Thread-safe — the router's
    canary thread and the ``/alerts`` endpoint share one instance."""

    def __init__(self, rtol=None, atol=None):
        self.rtol = float(rtol if rtol is not None
                          else config.get("CANARY_RTOL"))
        self.atol = float(atol if atol is not None
                          else config.get("CANARY_ATOL"))
        self._lock = threading.Lock()
        self._goldens: dict = {}  # raft-lint: guarded-by=self._lock
        #: {design: {replica: provenance dict}} — the parity check's view
        self._provenance: dict = {}  # raft-lint: guarded-by=self._lock
        #: {(design, replica): "addr:port" the stamp was probed from} —
        #: a same-rid TAKEOVER (rolling upgrade) replaces the process
        #: behind the endpoint, so a stamp observed from the previous
        #: endpoint is void, not evidence of skew
        self._prov_from: dict = {}  # raft-lint: guarded-by=self._lock
        #: {replica: {"endpoint", "n"}} — observations folded in AT the
        #: replica's current endpoint; the run restarts when the
        #: endpoint changes or the replica departs, so the rollout
        #: gate's fresh-probe count only credits the CURRENT process.
        #: (A gate counting fleet-wide passes goes green off the
        #: candidate's healthy neighbors; one counting per-rid probes
        #: still goes green off the OLD process, which keeps answering
        #: its drain window while the canary's membership snapshot is
        #: a beat stale — the endpoint is the process identity.)
        self._probes: dict = {}  # raft-lint: guarded-by=self._lock
        #: {golden key or "provenance": failure detail} currently failing
        self._failing: dict = {}  # raft-lint: guarded-by=self._lock

    # ------------------------------------------------------------ goldens

    def capture(self, key, design, case, out_keys, outputs, status,
                replica=None, provenance=None):
        """Store one golden row under its content key (first capture
        wins — a golden is immutable).  Returns True when THIS call
        created it.  The stored dict is the ``canary-golden`` schema
        family."""
        status = int(np.asarray(status))
        rec = {
            "key": str(key),
            "design": str(design),
            "case": tuple(float(c) for c in case),
            "out_keys": tuple(out_keys),
            "outputs": {k: np.array(v) for k, v in (outputs or {}).items()},
            "status": status,
            "replica": str(replica) if replica else None,
            "provenance": dict(provenance) if provenance else None,
            "t_unix": round(time.time(), 3),
        }
        with self._lock:
            if key in self._goldens:
                return False
            self._goldens[key] = rec
        metrics.counter("canary_goldens").inc()
        log_event("canary_golden", design=str(design), key=str(key)[:24],
                  status=status, replica=str(replica) if replica else None)
        return True

    def compare(self, golden, outputs, status):
        """One row against its golden: the int32 status word must match
        **bit for bit**; float/complex outputs within rtol/atol.
        Returns ``(ok, reason)``."""
        status = int(np.asarray(status))
        if status != golden["status"]:
            return False, (f"status {status} != golden "
                           f"{golden['status']} (bit-exact contract)")
        gold_outputs = golden["outputs"]
        for k, gv in gold_outputs.items():
            if k == "status":
                continue
            v = (outputs or {}).get(k)
            if v is None:
                return False, f"output {k!r} missing from probe response"
            v, gv = np.asarray(v), np.asarray(gv)
            if v.shape != gv.shape:
                return False, (f"output {k!r} shape {v.shape} != golden "
                               f"{gv.shape}")
            if not np.allclose(v, gv, rtol=self.rtol, atol=self.atol,
                               equal_nan=True):
                delta = float(np.max(np.abs(v - gv)))
                return False, (f"output {k!r} max |delta| {delta:.3e} "
                               f"outside rtol={self.rtol} "
                               f"atol={self.atol}")
        return True, "match"

    # ------------------------------------------------------------ observe

    def observe(self, design, replica, fingerprint, case, out_keys,
                outputs, status, provenance=None, endpoint=None):
        """Fold one probe response in: first response per golden key
        becomes the golden, later ones compare; the provenance joins
        the per-design cross-replica view.  Returns the verdict dict
        (also emitted as a ``canary_check`` event)."""
        key = golden_key(fingerprint, case, out_keys)
        created = self.capture(key, design, case, out_keys, outputs,
                               status, replica=replica,
                               provenance=provenance)
        with self._lock:
            golden = self._goldens[key]
        if created:
            ok, reason = True, "golden"
        else:
            ok, reason = self.compare(golden, outputs, status)
        with self._lock:
            run = self._probes.get(str(replica))
            if run is None or (endpoint is not None
                               and run.get("endpoint") != str(endpoint)):
                run = {"endpoint": (str(endpoint) if endpoint is not None
                                    else None), "n": 0}
                self._probes[str(replica)] = run
            run["n"] += 1
            if provenance is not None:
                self._provenance.setdefault(str(design), {})[
                    str(replica)] = dict(provenance)
                if endpoint is not None:
                    self._prov_from[(str(design), str(replica))] = \
                        str(endpoint)
            if ok:
                self._failing.pop(key, None)
            else:
                self._failing[key] = {"design": str(design),
                                      "replica": str(replica),
                                      "reason": reason}
        _failing, prov = self._refresh_parity()
        if ok and prov["consistent"]:
            metrics.counter("canary_pass").inc()
        else:
            metrics.counter("canary_fail").inc()
        verdict = {"design": str(design), "replica": str(replica),
                   "ok": bool(ok and prov["consistent"]),
                   "golden_created": created, "reason": reason,
                   "provenance_ok": prov["consistent"], "key": key}
        log_event("canary_check", design=verdict["design"],
                  replica=verdict["replica"], ok=verdict["ok"],
                  reason=reason, provenance_ok=prov["consistent"],
                  key=str(key)[:24])
        return verdict

    def _refresh_parity(self):
        """Recompute the cross-replica provenance verdict from current
        state and publish the parity gauge + alert context.  Returns
        ``(failing, provenance_verdict)``."""
        with self._lock:
            prov_view = {d: dict(m) for d, m in self._provenance.items()}
        prov = alerts.provenance_consistency(prov_view,
                                             releases=_release_view())
        with self._lock:
            if prov["consistent"]:
                self._failing.pop("provenance", None)
            else:
                self._failing["provenance"] = {"splits": prov["splits"]}
            failing = {k: dict(v) for k, v in self._failing.items()}
        parity_ok = not failing
        metrics.gauge("canary_parity_ok").set(1.0 if parity_ok else 0.0)
        alerts.set_context(
            CONTEXT_KEY,
            None if parity_ok else {"failing": failing,
                                    "provenance": prov})
        return failing, prov

    def prune(self, replicas):
        """Forget canary state of replicas no longer in the fleet
        membership: a drained/evicted/replaced replica's provenance
        stamp must not ghost-split parity forever (a rolling upgrade
        REPLACES stamps, it does not accumulate them).  Goldens stay —
        they are content-addressed and replica-agnostic.

        ``replicas`` is the membership view: an iterable of replica
        ids, or ``{rid: {"addr", "port", ...}}`` (the router's live
        snapshot) — with endpoints, a stamp observed from an endpoint
        the rid no longer answers at is ALSO dropped (a same-rid
        takeover mid-rolling-upgrade: the old process's stamp would
        otherwise red-flag parity for one probe interval right as the
        expected-skew window closes).  Returns True when anything was
        dropped."""
        keep = {str(r) for r in replicas}
        endpoints = {}
        if isinstance(replicas, dict):
            for rid, info in replicas.items():
                if isinstance(info, dict) and "port" in info:
                    endpoints[str(rid)] = \
                        f"{info.get('addr')}:{info['port']}"
        changed = False
        reset_rids = set()
        with self._lock:
            for design in list(self._provenance):
                members = self._provenance[design]
                for rid in list(members):
                    seen_at = self._prov_from.get((design, rid))
                    stale = (rid not in keep
                             or (seen_at is not None
                                 and rid in endpoints
                                 and seen_at != endpoints[rid]))
                    if stale:
                        del members[rid]
                        self._prov_from.pop((design, rid), None)
                        reset_rids.add(rid)
                        changed = True
                if not members:
                    del self._provenance[design]
            for rid in list(self._probes):
                if rid not in keep or rid in reset_rids:
                    # departed or replaced: the observation run restarts
                    # for the new process (observe() also restarts it on
                    # its own when the probed endpoint changes)
                    del self._probes[rid]
                    changed = True
            for key in list(self._failing):
                if key != "provenance" and \
                        self._failing[key].get("replica") not in keep:
                    del self._failing[key]
                    changed = True
        if changed:
            self._refresh_parity()
        return changed

    # ------------------------------------------------------------ queries

    def summary(self):
        """JSON-ready canary state (joined into ``GET /alerts``)."""
        with self._lock:
            goldens = len(self._goldens)
            failing = {k: dict(v) for k, v in self._failing.items()}
            prov_view = {d: dict(m) for d, m in self._provenance.items()}
            probes = {rid: dict(run) for rid, run in self._probes.items()}
        return {
            "goldens": goldens,
            "passes": metrics.counter("canary_pass").value,
            "fails": metrics.counter("canary_fail").value,
            "probes": probes,
            "parity_ok": not failing,
            "failing": failing,
            "provenance": alerts.provenance_consistency(
                prov_view, releases=_release_view()),
        }


# ------------------------------------------------- replica-side goldens

_REPLICA_LOCK = threading.Lock()
#: the replica's own golden store, captured at warmup (None until
#: RAFT_TPU_CANARY_S enables the canary path)
_REPLICA_CANARY: list = []  # raft-lint: guarded-by=_REPLICA_LOCK


def capture_goldens(entries, mesh=None, out_keys=None, state=None):
    """Replica-side warmup capture: dispatch each registered design
    ONCE at the canary case through the production funnel
    (:func:`raft_tpu.serve.engine.dispatch`) and store the golden
    rows.  ``out_keys`` is the SERVER's dispatched out_keys tuple —
    the capture reuses the already-warmed program (dispatching a
    canary-only out_keys subset would mint a different bank key and
    fail a require-mode replica at startup); the golden stores only
    the canary subset.  Returns the :class:`CanaryState` (also
    installed as the process replica store ``GET /alerts`` reports)."""
    from raft_tpu.serve import engine

    state = state if state is not None else CanaryState()
    served = tuple(out_keys) if out_keys else engine.DEFAULT_OUT_KEYS
    keys = canary_out_keys(served=served)
    Hs, Tp, beta = CANARY_CASE
    for entry in entries:
        out = engine.dispatch([entry], [Hs], [Tp], [beta],
                              out_keys=served, mesh=mesh,
                              record_metrics=False)
        row = {k: out[k][0] for k in keys}
        state.capture(golden_key(entry.fingerprint, CANARY_CASE, keys),
                      entry.name, CANARY_CASE, keys, row,
                      row["status"])
    with _REPLICA_LOCK:
        _REPLICA_CANARY[:] = [state]
    return state


def replica_summary():
    """The replica's golden-store summary for ``GET /alerts`` (None
    when the canary path is disabled)."""
    with _REPLICA_LOCK:
        state = _REPLICA_CANARY[0] if _REPLICA_CANARY else None
    return state.summary() if state is not None else None


# ---------------------------------------------------- router-side prober


def _http_evaluate(addr, port, design, case, out_keys, timeout_s=60.0):
    """One blocking probe request (canary THREAD only, never the event
    loop).  Returns ``(status_code, body_dict, provenance_dict)`` or
    None when the replica is unreachable/garbled — a dead replica is
    the membership prober's finding, not a canary failure."""
    Hs, Tp, beta = case
    payload = {"design": str(design), "Hs": Hs, "Tp": Tp, "beta": beta,
               "out_keys": list(out_keys), "client": "canary"}
    conn = http.client.HTTPConnection(addr, int(port), timeout=timeout_s)
    try:
        conn.request("POST", "/evaluate", body=json.dumps(payload),
                     headers={"Content-Type": "application/json",
                              "X-Client": "canary"})
        resp = conn.getresponse()
        body = resp.read()
        headers = {k.lower(): v for k, v in resp.getheaders()}
        data = json.loads(body)
        if not isinstance(data, dict):
            return None
        return (resp.status, data,
                alerts.parse_provenance(headers.get("x-raft-provenance")))
    except (OSError, http.client.HTTPException, ValueError):
        return None
    finally:
        conn.close()


class RouterCanary(threading.Thread):
    """Daemon thread probing every (replica, design) pair directly at
    its ledger endpoint every ``RAFT_TPU_CANARY_S`` seconds and
    feeding :class:`CanaryState` — low-rate by construction (one tiny
    request per pair per period; after the first probe per key the
    replica answers from its result cache)."""

    def __init__(self, state, canary=None, interval_s=None,
                 case=CANARY_CASE, probe=None):
        super().__init__(name="raft-router-canary", daemon=True)
        #: the router's RouterState (membership + design fingerprints)
        self.state = state
        self.canary = canary if canary is not None else CanaryState()
        self.interval_s = float(interval_s if interval_s is not None
                                else config.get("CANARY_S"))
        self.case = tuple(case)
        #: injectable probe fn (tests): (addr, port, design, case,
        #: out_keys) -> (status_code, body, provenance) | None
        self._probe = probe if probe is not None else _http_evaluate
        self._stop_evt = threading.Event()

    def probe_once(self):
        """One canary pass over the current membership; returns the
        verdict list."""
        snap = self.state.snapshot()
        # departed replicas (drained/evicted) must not ghost-split the
        # provenance parity view forever, and a REPLACED replica's
        # stamp (same rid, new endpoint after a rolling-upgrade
        # takeover) is the old process's — void it before comparing
        self.canary.prune(snap["replicas"])
        fingerprints = self.state.design_fingerprints()
        verdicts = []
        for rid, info in sorted(snap["replicas"].items()):
            # intersect with what THIS replica's lease says it serves
            # (replica-side capture does the same): probing an unserved
            # out_key would be a 400, not a canary verdict.  Pre-
            # out_keys leases declare nothing -> the configured default.
            served = self.state.served_out_keys(rid)
            out_keys = canary_out_keys(served=served or None)
            for design in info["designs"]:
                fp = fingerprints.get(design)
                if not fp:
                    continue  # no content hash -> no golden identity
                resp = self._probe(info["addr"], info["port"], design,
                                   self.case, out_keys)
                if resp is None:
                    continue  # dead/unreachable: membership's problem
                code, body, prov = resp
                if code not in (200, 422) or "status" not in body:
                    metrics.counter("canary_errors").inc()
                    continue
                verdicts.append(self.canary.observe(
                    design, rid, fp, self.case, out_keys,
                    decode_outputs(body.get("outputs")), body["status"],
                    provenance=prov,
                    endpoint=f"{info['addr']}:{info['port']}"))
        return verdicts

    def run(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.probe_once()
            except Exception:
                pass  # a bad pass must never kill the canary

    def stop(self):
        self._stop_evt.set()
        self.join(timeout=2.0)
