"""Recompilation sentinel: count XLA backend compiles, assert budgets.

A recompilation storm is the quiet failure mode of a jit-heavy
pipeline: a shape that varies per call, a config arg traced instead of
static, a closure rebuilt per invocation — each turns a cached-in-
microseconds dispatch into seconds of XLA work, silently.  The
sentinel makes the count observable and assertable:

    from raft_tpu.analysis import recompile

    with recompile.count_compilations() as log:
        run_sweep(...)
    print(log.count)

    # steady state must be compile-free: second identical run => 0
    run_sweep(...)                       # warm (compiles, fills caches)
    with recompile.assert_compile_budget(0):
        run_sweep(...)                   # identical -> raises if any

Counting hooks jax's own monitoring stream (the
``/jax/core/compile/backend_compile_duration`` event fires once per
actual backend compilation, cache hits don't emit it), so eager-op
compiles are counted too — exactly the ones that sneak past
jit-centric reasoning.  ``bench.py`` reports the steady-state count in
its breakdown (``steady_state_recompiles``), and
``tests/test_trace_contracts.py`` asserts the zero-budget invariant on
a repeated sweep invocation in the tier-1 suite.

Beyond the scoped context managers, ``RAFT_TPU_COMPILE_BUDGET`` arms a
*process-wide* enforceable budget: compilation number budget+1 raises
(or, with ``RAFT_TPU_COMPILE_BUDGET_ACTION=warn``, logs) at the call
that compiled.  Budget 0 plus a warm AOT program bank
(:mod:`raft_tpu.aot`) is the serving-grade cold-start invariant:
``aot_programs_loaded`` counts up while :data:`PROCESS_LOG` stays at
zero — "N bank loads, 0 compiles", distinguishable at a glance from a
real recompile storm.
"""

from __future__ import annotations

import contextlib

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
# fired by jax INSIDE the compile-event scope when the persistent disk
# cache answered — on a hit, BOTH events fire (jax wraps
# compile_or_get_cached, not the raw backend compile), so telling real
# XLA work from a millisecond disk retrieval needs the pair
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"


class RecompilationError(AssertionError):
    """More backend compilations than the declared budget."""


class CompileLog:
    """Mutable counter the listener writes into (exposed by the
    context managers).

    ``count`` is every ``backend_compile`` event — including
    persistent-disk-cache retrievals, which jax wraps in the same
    event; ``disk_hits`` is the subset the disk cache answered;
    ``real_count`` is the compilations that actually ran the XLA
    pipeline.  The scoped steady-state invariant budgets ``count`` (a
    steady state should dispatch from the in-process jit cache and
    emit NOTHING), while the process-wide ``RAFT_TPU_COMPILE_BUDGET``
    budgets ``real_count`` (a warmed cold start legitimately retrieves
    its eager helpers from disk)."""

    def __init__(self):
        self.count = 0
        self.seconds = []
        self.disk_hits = 0

    @property
    def real_count(self):
        return self.count - self.disk_hits

    @property
    def total_seconds(self):
        return sum(self.seconds)

    def __repr__(self):
        return (f"CompileLog(count={self.count}, "
                f"disk_hits={self.disk_hits}, "
                f"total_seconds={self.total_seconds:.3f})")


# ONE process-wide listener dispatching to the currently-active logs:
# jax's public monitoring API has no unregister, so per-use listeners
# would accumulate forever in a long-running process (one sentinel
# scope per sweep iteration is the advertised pattern).  The single
# listener costs a string compare per event when no scope is active.
_ACTIVE_LOGS: list = []
_registered = False

#: every backend compilation since install() — the denominator of the
#: process-wide budget (RAFT_TPU_COMPILE_BUDGET) and the counterpart
#: of the AOT bank's aot_programs_loaded counter: a warmed cold start
#: reads "N bank loads, 0 compiles" (PROCESS_LOG.count == 0), whereas
#: a recompile storm grows THIS regardless of what the bank served.
PROCESS_LOG = CompileLog()


def _enforce_env_budget():
    """The enforceable budget (``RAFT_TPU_COMPILE_BUDGET``): once the
    process exceeds it, every further REAL backend compilation logs a
    ``compile_budget_exceeded`` event and — under the default
    ``RAFT_TPU_COMPILE_BUDGET_ACTION=error`` — raises
    :class:`RecompilationError` at the dispatch that compiled.
    Persistent-disk-cache retrievals are exempt (milliseconds, no XLA
    pipeline).  Budget 0 is the serving invariant: with a warm AOT
    bank + XLA disk cache, a fresh process must answer its first sweep
    without any XLA work, and this makes that loud instead of a
    33-second stall."""
    from raft_tpu.utils import config

    budget = config.get("COMPILE_BUDGET")
    if budget is None or budget < 0 or PROCESS_LOG.real_count <= budget:
        return
    from raft_tpu.obs import metrics
    from raft_tpu.utils.structlog import log_event

    action = config.get("COMPILE_BUDGET_ACTION")
    metrics.counter("compile_budget_exceeded").inc()
    log_event("compile_budget_exceeded", count=PROCESS_LOG.real_count,
              budget=budget, action=action)
    # a breach is a postmortem moment: persist the flight ring (what
    # dispatched, which signatures, what the caches did) before the
    # raise can unwind the process
    from raft_tpu.obs import flight

    flight.dump(trigger="compile-budget")
    if action == "error":
        raise RecompilationError(
            f"backend compilation #{PROCESS_LOG.real_count} exceeds "
            f"RAFT_TPU_COMPILE_BUDGET={budget} "
            f"({PROCESS_LOG.total_seconds:.2f}s of XLA work so far) — "
            "either the AOT bank is cold for this key "
            "(`python -m raft_tpu.aot warmup`, or one "
            "RAFT_TPU_AOT=load run), or a shape/config/closure is "
            "varying between calls that should hit the jit cache")


# plain-event listener feed: a CACHE_HIT_EVENT always precedes the
# COMPILE_EVENT of the same compile_or_get_cached call, so a nonzero
# pending count classifies the next duration event as a disk
# retrieval, not a real compilation
_PENDING_DISK_HITS = [0]


def _event_listener(event, **kwargs):
    if event == CACHE_HIT_EVENT:
        _PENDING_DISK_HITS[0] += 1


def _listener(event, duration_secs, **kwargs):
    if event == COMPILE_EVENT:
        # feed the telemetry registry unconditionally: total compile
        # count + time are part of every metrics snapshot
        # (raft_tpu.obs.metrics), not just of sentinel scopes
        from raft_tpu.obs import metrics

        disk_hit = _PENDING_DISK_HITS[0] > 0
        if disk_hit:
            _PENDING_DISK_HITS[0] -= 1
            metrics.counter("xla_cache_hits").inc()
        metrics.counter("xla_compiles").inc()
        metrics.histogram("xla_compile_s").observe(duration_secs)
        PROCESS_LOG.count += 1
        PROCESS_LOG.seconds.append(duration_secs)
        PROCESS_LOG.disk_hits += int(disk_hit)
        for log in _ACTIVE_LOGS:
            log.count += 1
            log.seconds.append(duration_secs)
            log.disk_hits += int(disk_hit)
        _enforce_env_budget()


def install():
    """Register the process-wide compile listener (idempotent) so the
    ``xla_compiles`` counter / ``xla_compile_s`` histogram count every
    backend compilation from now on — called by
    :func:`raft_tpu.utils.devices.enable_compile_cache`, i.e. by every
    driver/sweep/bench entry point."""
    import jax.monitoring

    global _registered
    if not _registered:
        jax.monitoring.register_event_duration_secs_listener(_listener)
        jax.monitoring.register_event_listener(_event_listener)
        _registered = True


@contextlib.contextmanager
def count_compilations():
    """Context manager yielding a :class:`CompileLog` that counts every
    XLA backend compilation inside the block (nesting-safe)."""
    install()
    log = CompileLog()
    _ACTIVE_LOGS.append(log)
    try:
        yield log
    finally:
        _ACTIVE_LOGS.remove(log)


@contextlib.contextmanager
def assert_compile_budget(budget=0, what="this block"):
    """Assert at most ``budget`` backend compilations happen inside the
    block (default 0: the steady-state invariant — a second identical
    driver/sweep run must be compile-free)."""
    with count_compilations() as log:
        yield log
    if log.count > budget:
        raise RecompilationError(
            f"{log.count} backend compilation(s) in {what} "
            f"(budget {budget}, {log.total_seconds:.2f}s of XLA work) — "
            "a shape/config/closure is varying between calls that "
            "should hit the jit cache")
