"""Fixture: span() call sites with unregistered literal names (the
span-name rule must flag both the bare-name and attribute forms, and
must NOT flag dynamic names or registered ones)."""
from raft_tpu import obs
from raft_tpu.obs.spans import span


def work(name):
    with span("shrad"):            # typo'd name: flagged
        pass
    with obs.span("sweep_dispach", rows=4):   # typo'd name: flagged
        pass
    with span("sweep"):            # registered: clean
        pass
    with span(name):               # dynamic: not checkable, clean
        pass
