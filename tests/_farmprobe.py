"""Probe: farm wind-parity deviation magnitudes."""

import jax
import jax.numpy as jnp
import numpy as np

from tests.conftest import ref_data

import raft_tpu
from raft_tpu.api import make_farm_evaluator

WAVE_CASE = {
    "wind_speed": [10.0, 8.5], "wind_heading": 0, "turbulence": 0.1,
    "turbine_status": "operating", "yaw_misalign": 0,
    "wave_spectrum": "JONSWAP", "wave_period": 10, "wave_height": 4,
    "wave_heading": -30, "current_speed": 0, "current_heading": 0,
}


def test_probe_farm_wind():
    model = raft_tpu.Model(ref_data("VolturnUS-S_farm.yaml"))
    X0_o = np.asarray(model.solve_statics(WAVE_CASE))
    Xi_o, info = model.solve_dynamics(WAVE_CASE, X0=X0_o)
    evaluate = jax.jit(make_farm_evaluator(model))
    out = evaluate(dict(wind_speed=jnp.asarray([10.0, 8.5]), TI=0.1,
                        Hs=4.0, Tp=10.0, beta_deg=-30.0))
    X0_t = np.asarray(out["X0"])
    print("\nX0 orch :", X0_o[:6], X0_o[6:])
    print("X0 trace:", X0_t[:6], X0_t[6:])
    print("X0 maxdiff:", np.max(np.abs(X0_t - X0_o)))
    Xi_o = np.asarray(Xi_o)
    Xi_t = np.asarray(out["Xi"])
    print("Xi maxdiff rel:", np.max(np.abs(Xi_t - Xi_o)) / np.max(np.abs(Xi_o)))
    # per-FOWT mean aero force comparison
    for i in range(2):
        tc = model.turbine_constants(WAVE_CASE, i)
        print(f"fowt {i} orch f_aero0:", tc["f_aero0"][:3, 0])
