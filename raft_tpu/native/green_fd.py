"""Finite-depth free-surface Green function (John's eigenfunction
series) — host-side prototype, validation reference, and the
per-frequency constants fed to the native C++ kernel.

The finite-depth wave source potential (Wehausen & Laitone eq. 13.19;
the kernel HAMS evaluates in Fortran for the reference's calcBEM,
``/root/reference/raft/raft_fowt.py:1288-1442``) is

    G = 1/r + 1/r2 + Gw,     r2 = image about the SEABED z = -h,

    Gw = 2 PV int_0^inf  (mu+K) e^{-mu h} cosh mu(z+h) cosh mu(zeta+h)
                         / (mu sinh mu h - K cosh mu h) J0(mu R) dmu
         + i 2 pi (k0+K) e^{-k0 h} cosh k0(z+h) cosh k0(zeta+h)
                         / D'(k0) J0(k0 R)

with K = omega^2/g and k0 the real dispersion root
k0 tanh k0 h = K.  The equivalent eigenfunction (John's) series for the
TOTAL G — exponentially convergent in the evanescent modes for R > 0 —
is

    G = 2 pi C0 cosh k0(z+h) cosh k0(zeta+h) (-Y0(k0 R) + i J0(k0 R))
        + 4 sum_m Cm cos km(z+h) cos km(zeta+h) K0(km R)

    C0 = (k0^2 - K^2) / ( h (k0^2 - K^2) + K )
       = k0^2 / ( h k0^2 + K cosh^2 k0 h )    * cosh^2 k0 h  (stable form)
    Cm = (km^2 + K^2) / ( h (km^2 + K^2) - K )
       = km^2 / ( h km^2 - K cos^2 km h )     * cos^2 km h   (stable form)

with km the evanescent roots km tan km h = -K (one per interval
((m-1/2) pi/h, m pi/h)).  The stable forms divide the unbounded
cosh factors through the coefficient so every exponent is <= 0
(``_prop_factor``).  Both representations are validated against each
other and against the infinite-depth table kernel in
tests/test_native_bem.py.
"""

from __future__ import annotations

import numpy as np


def dispersion_roots(K, h, n_modes):
    """k0 (real root of k tanh kh = K) and km (m=1..n_modes roots of
    k tan kh = -K, km in ((m-1/2) pi/h, m pi/h))."""
    # real root: Newton on y tanh y = Kh (y = k h) from the deep guess
    Kh = K * h
    y = max(Kh, np.sqrt(max(Kh, 1e-12)))
    for _ in range(100):
        t = np.tanh(y)
        f = y * t - Kh
        df = t + y * (1 - t * t)
        dy = f / df
        y -= dy
        if abs(dy) < 1e-14 * max(y, 1.0):
            break
    k0 = y / h

    km = np.zeros(n_modes)
    for m in range(1, n_modes + 1):
        # f(y) = y tan y + Kh on ((m-1/2) pi, m pi): -inf at the left
        # endpoint, +Kh at the right — bracketed bisection, then Newton
        lo = (m - 0.5) * np.pi + 1e-9
        hi = m * np.pi - 1e-12
        for _ in range(80):
            y = 0.5 * (lo + hi)
            if y * np.tan(y) + Kh < 0:
                lo = y
            else:
                hi = y
        y = 0.5 * (lo + hi)
        for _ in range(5):
            t = np.tan(y)
            f = y * t + Kh
            df = t + y * (1 + t * t)
            if df == 0:
                break
            y -= f / df
        km[m - 1] = y / h
    return k0, km


def _prop_factor(k0, K, h, z, zeta):
    """C0 cosh k0(z+h) cosh k0(zeta+h) with no overflow:
    = k0^2 [cosh k0(z+h) cosh k0(zeta+h) / cosh^2 k0 h]
      / (h k0^2 + K cosh^2 k0 h / cosh^2 k0 h * ... )

    Using k0^2 - K^2 = k0^2 / cosh^2(k0 h) * (cosh^2 - sinh^2 ... ):
    exactly, K = k0 tanh k0 h so k0^2 - K^2 = k0^2 (1 - tanh^2)
    = k0^2 / cosh^2 k0 h, hence

    C0 cosh a cosh b = k0^2 * [cosh a cosh b / cosh^2 k0 h]
                       / ( h k0^2 / cosh^2 k0 h + K ).

    cosh a cosh b / cosh^2 k0h is evaluated in exp form with all
    exponents <= 0 (a, b <= k0 h for z, zeta in [-h, 0])."""
    a = k0 * (np.asarray(z) + h)
    b = k0 * (np.asarray(zeta) + h)
    c = k0 * h
    # cosh a / cosh c = e^{a-c} (1+e^{-2a}) / (1+e^{-2c})
    f = (np.exp(a + b - 2 * c) * (1 + np.exp(-2 * a)) * (1 + np.exp(-2 * b))
         / (1 + np.exp(-2 * c)) ** 2)
    sech2 = 1.0 / np.cosh(c) ** 2 if c < 350 else 4.0 * np.exp(-2 * c)
    return k0 ** 2 * f / (h * k0 ** 2 * sech2 + K)


def _evan_coeffs(km, K, h):
    """Cm for the stable form: Cm = km^2 / (h km^2 - K cos^2 km h)
    times cos^2 km h absorbed into the cos-product normalisation —
    returned as the plain Cm = (km^2+K^2)/(h(km^2+K^2)-K)."""
    k2K2 = km ** 2 + K ** 2
    return k2K2 / (h * k2K2 - K)


def green_fd_series(Rh, z, zeta, K, h, n_modes=80):
    """Total finite-depth G (WITHOUT any Rankine subtraction) by the
    eigenfunction series; scalar/broadcast numpy.  Valid for Rh > 0."""
    from scipy.special import j0, k0 as K0, y0

    kr, km = dispersion_roots(K, h, n_modes)
    A0 = _prop_factor(kr, K, h, z, zeta)
    G = 2 * np.pi * A0 * (-y0(kr * Rh) + 1j * j0(kr * Rh))
    Cm = _evan_coeffs(km, K, h)
    zc = (np.asarray(z) + h)
    zz = (np.asarray(zeta) + h)
    for m in range(n_modes):
        G = G + 4 * Cm[m] * np.cos(km[m] * zc) * np.cos(km[m] * zz) * K0(km[m] * Rh)
    return G


def green_fd_reference(Rh, z, zeta, K, h):
    """Scipy PV-integral evaluation of the WAVE part Gw (see module
    docstring) plus the two Rankine terms 1/r(=0 here; Rh>0 assumed
    with z != zeta possible) — returns the TOTAL G for validation.

    The ratios N/D and N/D' are evaluated in exp-normalised form (every
    exponent <= 0 for z, zeta in [-h, 0]: a+b-2c = mu(z+zeta) <= 0), so
    the integrand never overflows even for near-surface point pairs at
    large mu*h where the naive cosh/sinh factors exceed float range.

    Slow; used only in tests."""
    from scipy.integrate import quad
    from scipy.special import j0

    k0v, _ = dispersion_roots(K, h, 1)

    def N_over_D(mu):
        # N = (mu+K) e^{-c} cosh a cosh b,  D = mu sinh c - K cosh c
        # with a = mu(z+h), b = mu(zeta+h), c = mu h (all >= 0):
        # N/D = (mu+K) e^{a+b-2c} (1+e^{-2a})(1+e^{-2b})
        #       / (2 [mu(1-e^{-2c}) - K(1+e^{-2c})])
        a = mu * (z + h)
        b = mu * (zeta + h)
        c = mu * h
        num = (mu + K) * np.exp(a + b - 2 * c) \
            * (1 + np.exp(-2 * a)) * (1 + np.exp(-2 * b))
        den = 2.0 * (mu * (1 - np.exp(-2 * c)) - K * (1 + np.exp(-2 * c)))
        return num / den

    def N_over_dD(mu):
        # D' = sinh c + mu h cosh c - K h sinh c
        #    = e^c/2 [(1-Kh)(1-e^{-2c}) + mu h (1+e^{-2c})]
        a = mu * (z + h)
        b = mu * (zeta + h)
        c = mu * h
        num = (mu + K) * np.exp(a + b - 2 * c) \
            * (1 + np.exp(-2 * a)) * (1 + np.exp(-2 * b))
        den = 2.0 * ((1 - K * h) * (1 - np.exp(-2 * c))
                     + mu * h * (1 + np.exp(-2 * c)))
        return num / den

    def integrand(mu):
        return 2.0 * N_over_D(mu) * j0(mu * Rh)

    # PV: split at the pole k0 with symmetric excision + Cauchy weight
    def f_cauchy(mu):
        # integrand = fc(mu)/(mu - k0): fc = 2 N J0 (mu-k0)/D
        if abs(mu - k0v) < 1e-12:
            return 2.0 * N_over_dD(mu) * j0(mu * Rh)  # derivative limit
        return 2.0 * N_over_D(mu) * j0(mu * Rh) * (mu - k0v)

    a, b = max(k0v - 0.5 * k0v, 1e-10), k0v + 0.5 * k0v
    pv, _ = quad(f_cauchy, a, b, weight="cauchy", wvar=k0v, limit=400)
    head, _ = quad(integrand, 0, a, limit=400)
    # tail: decays like e^{mu(z+zeta)} J0 oscillation; integrate far
    span = max(60.0 / max(-(z + zeta), 1e-3), 30.0 / max(Rh, 1e-3), 50 / h)
    tail, _ = quad(integrand, b, b + span, limit=2000)

    res_term = 2j * np.pi * N_over_dD(k0v) * j0(k0v * Rh)

    Gw = head + pv + tail + res_term
    r = np.sqrt(Rh ** 2 + (z - zeta) ** 2)
    r2 = np.sqrt(Rh ** 2 + (z + zeta + 2 * h) ** 2)
    return 1.0 / r + 1.0 / r2 + Gw
