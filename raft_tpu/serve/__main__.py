"""CLI for the always-on evaluation service.

    python -m raft_tpu.serve --designs spar=raft_tpu/designs/spar_demo.yaml \
        [--designs semi=...] [--host 127.0.0.1] [--port 8787] \
        [--out-keys PSD,X0,status] [--no-warm] [--platform cpu] [--x64]

Startup order is the serving contract: build + pack every registered
design, WARM every (bucket x batch-ladder) program through the AOT
bank (:func:`raft_tpu.serve.engine.warm`), and only then bind the
socket — a client can never reach a server that would trace on its
request.  Under ``RAFT_TPU_AOT=require`` a cold bank fails here, at
startup, not mid-request; fill it first with

    python -m raft_tpu.aot warmup --kinds serve --design <yaml>

``--port 0`` binds an ephemeral port; the ready line on stdout
(``serving N design(s) on http://host:port ...``) reports the actual
one (load harnesses parse it).  SIGTERM/SIGINT drains gracefully:
in-flight requests finish, new work gets 503, metrics flush to
``RAFT_TPU_METRICS`` when set.

Tuning flags (see ``python -m raft_tpu.analysis flags``):
``RAFT_TPU_SERVE_TICK_MS``, ``SERVE_MAX_BATCH``, ``SERVE_CACHE_MB``,
``SERVE_QUEUE``, ``SERVE_QPS``, ``SERVE_BURST``, ``SERVE_TIMEOUT_S``,
``SERVE_DRAIN_S``.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys


def _parse_designs(specs):
    """``name=path`` (or bare path — name = file stem) from repeated /
    comma-separated ``--designs`` values."""
    out = {}
    for spec in specs:
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            if "=" in item:
                name, path = item.split("=", 1)
            else:
                name = os.path.splitext(os.path.basename(item))[0]
                path = item
            out[name.strip()] = path.strip()
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.serve")
    ap.add_argument("--designs", action="append", required=True,
                    help="name=design.yaml (repeatable / comma list)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8787,
                    help="0 binds an ephemeral port (see the ready line)")
    ap.add_argument("--out-keys", default=",".join(
        ("PSD", "X0", "status")),
        help="out_keys this server dispatches (requests may ask for "
             "subsets; 'status' is always included)")
    ap.add_argument("--no-warm", action="store_true",
                    help="skip the pre-bind warmup (first requests pay "
                         "the trace/compile; testing only)")
    ap.add_argument("--platform", default=None,
                    help="jax platform pin (default: RAFT_TPU_CLI_PLATFORM)")
    ap.add_argument("--x64", action="store_true",
                    help="serve under jax_enable_x64 (warm the bank with "
                         "--x64 too — x64 is part of the bank key)")
    args = ap.parse_args(argv)

    from raft_tpu.utils import config

    platform = (args.platform if args.platform is not None
                else config.get("CLI_PLATFORM"))
    import jax

    if platform:
        jax.config.update("jax_platforms", platform)
    if args.x64:
        jax.config.update("jax_enable_x64", True)

    from raft_tpu.serve import engine
    from raft_tpu.serve.batcher import Batcher
    from raft_tpu.serve.http import run_server
    from raft_tpu.structure.bucketing import signature_fingerprint
    from raft_tpu.utils.devices import enable_compile_cache

    enable_compile_cache()
    registry = engine.Registry()
    designs = _parse_designs(args.designs)
    if not designs:
        print("no designs registered (--designs name=path)", file=sys.stderr)
        return 2
    for name, path in designs.items():
        entry = registry.register(name, path)
        print(f"registered {name}: bucket "
              f"{signature_fingerprint(entry.sig)}", flush=True)

    out_keys = tuple(k.strip() for k in args.out_keys.split(",") if k.strip())
    batcher = Batcher(registry, out_keys=out_keys)
    if not args.no_warm:
        reports = engine.warm(
            [registry.get(n) for n in registry.names()],
            mesh=batcher.mesh, out_keys=batcher.out_keys,
            sizes=batcher.sizes)
        loaded = sum(r["loaded"] for r in reports)
        compiled = sum(r["compiled"] for r in reports)
        wall = sum(r["wall_s"] for r in reports)
        print(f"warmup: {len(reports)} program(s) "
              f"({loaded} bank-loaded, {compiled} compiled) in {wall:.1f}s",
              flush=True)

    def ready(server):
        print(f"serving {len(registry)} design(s) on "
              f"http://{server.host}:{server.port} "
              f"(tick {batcher.tick_s * 1e3:.0f}ms, "
              f"batch ladder {list(batcher.sizes)})", flush=True)

    asyncio.run(run_server(batcher, host=args.host, port=args.port,
                           ready=ready))
    return 0


if __name__ == "__main__":
    sys.exit(main())
