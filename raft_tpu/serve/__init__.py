"""Always-on evaluation service: continuous batching over the design
axis (ROADMAP item 3).

The "millions of users" north star needs a long-lived server, not a
batch CLI.  This package is that server, assembled from the pillars
the earlier PRs shipped:

* the **AOT program bank** (:mod:`raft_tpu.aot`) answers a fresh
  process in seconds — the service warms every program it will
  dispatch BEFORE binding its socket;
* **shape-bucketed batching** (:mod:`raft_tpu.structure.bucketing`)
  lets arbitrary mixed-topology tenants share one compiled program —
  the batcher groups pending requests by bucket signature, so one
  dispatch serves many tenants;
* the in-band int32 **status word** (:mod:`raft_tpu.utils.health`)
  gives per-request error semantics (SEVERE bits → HTTP 422 with
  ``describe()`` text, quarantine-style f64 re-solve opt-in per
  request);
* the **obs** metrics registry (:mod:`raft_tpu.obs.metrics`) is the
  dashboard, served live over HTTP ``/metrics``.

Layout (everything stdlib-only — asyncio, http-free hand parser, no
new dependencies):

``cache``    content-addressed LRU result cache with a byte budget
             (design-pytree hash + case + out_keys → outputs; sweeps
             and optimizer loops are full of duplicate corners)
``quota``    per-client token buckets (429) and the bounded admission
             queue semantics (503)
``engine``   design registry + the packed-row dispatch through the
             SAME ``_cached_jit``/AOT-bank funnel the sweeps use, at a
             fixed ladder of padded batch sizes
``batcher``  the socket-free continuous-batching core: submit →
             pending queue → fixed-tick coalescing → per-request
             fan-out (unit-testable without a server)
``http``     the asyncio HTTP front end: ``POST /evaluate``,
             ``GET /healthz``, ``GET /metrics``; graceful drain on
             SIGTERM (finish in-flight ticks, refuse new work, flush
             metrics)
``client``   minimal stdlib client for load harnesses and tests, with
             the shared 429/503 backoff schedule (``backoff_delay``)
``wire``     the shared HTTP/1.1 parser/formatter + the router's
             asyncio upstream client
``fleet``    replica membership for the horizontal serving fleet: a
             ``_fleet/`` lease ledger on the fabric's atomic
             primitives (claim = join, renewed = alive, expired =
             dead, release = drain) + the local fleet coordinator
``router``   the consistent-hash failover front: hash ring by (bucket
             signature, design content hash), retry-with-backoff onto
             the next replica, per-replica circuit breakers, hedged
             requests, 503 + Retry-After only when nobody can answer
``canary``   golden-answer canary prober: content-addressed golden
             rows per design, low-rate probes pinned per replica,
             bit-for-status / tolerance-for-floats comparison and the
             cross-replica provenance consistency check feeding the
             canary_parity alert (see raft_tpu.obs.alerts)

Start a server::

    python -m raft_tpu.aot warmup --kinds serve        # fill the bank
    python -m raft_tpu.serve --designs spar=raft_tpu/designs/spar_demo.yaml \
        --port 8787

Or a fault-tolerant fleet behind one endpoint::

    python -m raft_tpu.serve fleet --replicas 2 --fleet-dir /srv/raft \
        --designs spar=raft_tpu/designs/spar_demo.yaml --warm-bank
    python -m raft_tpu.serve router --fleet-dir /srv/raft --port 8788

See the README "Evaluation service" + "Serving fleet" sections for the
API schema, the tick/batching model, the failover ladder and the
flag/event tables.
"""

from __future__ import annotations

from raft_tpu.serve.batcher import (Batcher, Draining, QueueFull,  # noqa: F401
                                    QuotaExceeded, RejectError)
from raft_tpu.serve.cache import ResultCache, result_cache_key  # noqa: F401
from raft_tpu.serve.engine import DesignEntry, Registry  # noqa: F401
from raft_tpu.serve.quota import TokenBucket  # noqa: F401
