"""Multi-device sharding tests: sweep drivers on the 8-device CPU mesh.

Validates the distributed layer (SURVEY.md §5.8) that the reference does
not have: a sweep of design evaluations laid out over a
``jax.sharding.Mesh`` must produce exactly what the unsharded evaluator
produces case by case, and the checkpointed driver must resume after a
lost shard without recomputing completed ones.
"""

import os

import jax
import numpy as np
import pytest

import raft_tpu
from raft_tpu.api import make_case_evaluator, make_full_evaluator
from raft_tpu.parallel.sweep import (
    make_mesh, qtf_slender_sharded, run_sweep_checkpointed,
    run_sweep_checkpointed_full, sweep_cases, sweep_cases_full)

pytestmark = pytest.mark.slow

HERE = os.path.dirname(os.path.abspath(__file__))
SPAR = os.path.join(HERE, "..", "raft_tpu", "designs", "spar_demo.yaml")


@pytest.fixture(scope="module")
def spar_eval():
    model = raft_tpu.Model(SPAR)
    return model, make_case_evaluator(model)


def _case_grid(n):
    rng = np.random.default_rng(7)
    return (2.0 + 6.0 * rng.random(n), 8.0 + 8.0 * rng.random(n),
            2 * np.pi * rng.random(n))


def test_eight_virtual_devices():
    assert len(jax.devices()) >= 8


def test_sweep_cases_matches_unsharded(spar_eval):
    """Sharded batch over the 8-device dp mesh == per-case unsharded jit."""
    model, evaluate = spar_eval
    n = 16
    Hs, Tp, beta = _case_grid(n)
    mesh = make_mesh(8)
    out = sweep_cases(evaluate, Hs, Tp, beta, mesh=mesh, out_keys=("PSD", "X0"))

    single = jax.jit(lambda h, t, b: evaluate(h, t, b))
    for i in range(n):
        ref = single(Hs[i], Tp[i], beta[i])
        np.testing.assert_allclose(np.asarray(out["X0"])[i], np.asarray(ref["X0"]),
                                   rtol=1e-10, atol=1e-12)
        np.testing.assert_allclose(np.asarray(out["PSD"])[i], np.asarray(ref["PSD"]),
                                   rtol=1e-8, atol=1e-10)


def test_sweep_cases_2d_mesh(spar_eval):
    """A (2,4) dp x sp mesh still evaluates the batch correctly."""
    model, evaluate = spar_eval
    n = 8
    Hs, Tp, beta = _case_grid(n)
    mesh = make_mesh(8, axis_names=("sp", "dp"))
    assert mesh.devices.shape == (4, 2)
    out = sweep_cases(evaluate, Hs, Tp, beta, mesh=mesh, out_keys=("PSD",))
    ref = sweep_cases(evaluate, Hs, Tp, beta, mesh=make_mesh(8), out_keys=("PSD",))
    np.testing.assert_allclose(np.asarray(out["PSD"]), np.asarray(ref["PSD"]),
                               rtol=1e-10, atol=1e-12)


def test_checkpointed_sweep_and_resume(spar_eval, tmp_path):
    """Per-shard npz checkpointing: resume recomputes only missing shards."""
    model, evaluate = spar_eval
    n = 20  # 3 shards of <=8 with shard_size=8 (tail padded to the mesh)
    Hs, Tp, beta = _case_grid(n)
    mesh = make_mesh(8)
    out_dir = str(tmp_path / "sweep")

    out1 = run_sweep_checkpointed(evaluate, Hs, Tp, beta, out_dir,
                                  shard_size=8, mesh=mesh, out_keys=("PSD", "X0"))
    assert out1["PSD"].shape[0] == n
    shards = sorted(f for f in os.listdir(out_dir) if f.endswith(".npz"))
    assert shards == ["shard_0000.npz", "shard_0001.npz", "shard_0002.npz"]
    assert os.path.exists(os.path.join(out_dir, "manifest.json"))

    # parity with the plain sharded sweep
    ref = sweep_cases(evaluate, Hs[:8], Tp[:8], beta[:8], mesh=mesh,
                      out_keys=("PSD", "X0"))
    np.testing.assert_allclose(out1["PSD"][:8], np.asarray(ref["PSD"]),
                               rtol=1e-10, atol=1e-12)

    # delete the middle shard; poison the surviving ones so any recompute
    # of them would be detected
    os.remove(os.path.join(out_dir, "shard_0001.npz"))
    kept = dict(np.load(os.path.join(out_dir, "shard_0000.npz")))
    np.savez(os.path.join(out_dir, "shard_0000.npz"),
             **{k: v + 123.0 for k, v in kept.items()})

    out2 = run_sweep_checkpointed(evaluate, Hs, Tp, beta, out_dir,
                                  shard_size=8, mesh=mesh, out_keys=("PSD", "X0"))
    # shard 0 was loaded from disk (poisoned), shard 1 recomputed
    np.testing.assert_allclose(out2["PSD"][:8], out1["PSD"][:8] + 123.0)
    np.testing.assert_allclose(out2["PSD"][8:16], out1["PSD"][8:16],
                               rtol=1e-10, atol=1e-12)
    np.testing.assert_allclose(out2["PSD"][16:], out1["PSD"][16:],
                               rtol=1e-10, atol=1e-12)


# --------------------------- full-evaluator sweeps + frequency sharding


@pytest.fixture(scope="module")
def spar_full():
    model = raft_tpu.Model(SPAR)
    return model, make_full_evaluator(model)


def _full_cases(n):
    rng = np.random.default_rng(3)
    return dict(Hs=2.0 + 6.0 * rng.random(n), Tp=8.0 + 8.0 * rng.random(n),
                beta_deg=360.0 * rng.random(n))


def test_sweep_full_sharded_parity(spar_full):
    """Full-evaluator case-dict sweep over the dp mesh == unsharded."""
    model, evaluate = spar_full
    cases = _full_cases(16)
    mesh = make_mesh(8)
    out = sweep_cases_full(evaluate, cases, mesh=mesh)
    single = jax.jit(lambda c: evaluate(c))
    for i in (0, 7, 15):
        ref = single({k: v[i] for k, v in cases.items()})
        np.testing.assert_allclose(np.asarray(out["PSD"])[i],
                                   np.asarray(ref["PSD"]),
                                   rtol=1e-8, atol=1e-12)


def test_sweep_full_freq_axis_sharded(spar_full):
    """The FREQUENCY axis is physically partitioned over "sp" (VERDICT
    r2 #6 / SURVEY §5.7): out-sharding introspection shows the nw axis
    split across devices, with 1e-10 parity vs the dp-only layout."""
    from jax.sharding import PartitionSpec as P

    model, evaluate = spar_full
    cases = _full_cases(8)
    mesh = make_mesh(8, axis_names=("dp", "sp"))  # (4, 2): nw split in 2
    out = sweep_cases_full(evaluate, cases, mesh=mesh, out_keys=("PSD",),
                           shard_freq=True)
    spec = out["PSD"].sharding.spec
    assert spec == P("dp", None, "sp"), spec
    # the frequency axis is REALLY partitioned: each device holds nw/2
    shard_shapes = {s.data.shape for s in out["PSD"].addressable_shards}
    nw = model.nw
    assert all(sh[2] == (nw + 1) // 2 or sh[2] == nw // 2 for sh in shard_shapes), \
        (shard_shapes, nw)
    ref = sweep_cases_full(evaluate, cases, mesh=make_mesh(8), out_keys=("PSD",))
    np.testing.assert_allclose(np.asarray(out["PSD"]), np.asarray(ref["PSD"]),
                               rtol=1e-10, atol=1e-12)


def test_checkpointed_full_sweep(spar_full, tmp_path):
    """Checkpointed FULL-physics sweep over a case dict, with resume."""
    model, evaluate = spar_full
    cases = _full_cases(12)
    mesh = make_mesh(8)
    out_dir = str(tmp_path / "fsweep")
    out1 = run_sweep_checkpointed_full(evaluate, cases, out_dir,
                                       shard_size=8, mesh=mesh)
    assert out1["PSD"].shape[0] == 12
    os.remove(os.path.join(out_dir, "shard_0001.npz"))
    out2 = run_sweep_checkpointed_full(evaluate, cases, out_dir,
                                       shard_size=8, mesh=mesh)
    np.testing.assert_allclose(out2["PSD"], out1["PSD"], rtol=1e-12)


def test_qtf_grid_sharded_parity():
    """Slender-QTF w1 x w2 pair axis physically partitioned over all 8
    devices, 1e-10 parity vs the unsharded kernel (VERDICT r2 #6)."""
    from raft_tpu.physics.qtf_slender import fowt_qtf_slender
    from raft_tpu.structure.schema import load_design

    design = load_design("/root/reference/examples/OC4semi-RAFT_QTF.yaml")
    # small 2nd-order grid for test runtime; keep the physics identical
    design["platform"]["min_freq2nd"] = 0.01
    design["platform"]["max_freq2nd"] = 0.05
    design["platform"]["df_freq2nd"] = 0.01
    model = raft_tpu.Model(design)
    case = dict(zip(model.design["cases"]["keys"],
                    model.design["cases"]["data"][0]))
    model.hydro[0].hydro_excitation(case)

    mesh = make_mesh(8)
    q_sh = qtf_slender_sharded(model, 0, Xi0=None, mesh=mesh)
    q_ref = np.asarray(fowt_qtf_slender(model, 0, Xi0=None))
    scale = np.max(np.abs(q_ref))
    np.testing.assert_allclose(q_sh, q_ref, atol=1e-10 * scale, rtol=0)
