"""The continuous-batching core of the evaluation service.

Socket-free and unit-testable: requests go in through :meth:`Batcher.
submit` (thread-safe, returns a ``concurrent.futures.Future``), pend in
one bounded admission queue, and each tick the dispatcher coalesces
the backlog — deduplicating identical in-flight cases, grouping the
rest by bucket signature so MIXED-TOPOLOGY tenants share one compiled
program, padding each group to the batch ladder — into the bucketed
evaluators, then fans the results back out per request.  This is
inference-server-style continuous batching over the *design* axis: the
batch dimension is "whichever tenants are waiting right now", not a
precomputed sweep.

The coalescing window is ADAPTIVE by default (ROADMAP item 5b,
``RAFT_TPU_SERVE_TICK_MODE``): it anchors on the oldest pending
request and scales with the recent per-tick row load between
``RAFT_TPU_SERVE_TICK_MIN_MS`` (near-empty queue — the whole window is
pure tail latency, so a lone light-load request waits ~the floor
instead of the full tick) and the ``RAFT_TPU_SERVE_TICK_MS`` ceiling
(sustained load — bigger batches amortize the wait), and a bucket
group filling a full top ladder rung dispatches speculatively early.
The PR-11 stage decomposition (queue_wait/tick_wait/dispatch/solve/
post) is computed from the same submit/tick/dispatch marks, so the
stages keep summing to the measured total by construction whatever the
window does.

Error semantics ride in-band: every row carries the int32 solver-health
``status`` word (:mod:`raft_tpu.utils.health`); SEVERE bits surface in
the result payload (the HTTP layer maps them to 422 with
``describe()`` text), and a request may opt into a quarantine-style
``f64_cpu`` re-solve (:func:`raft_tpu.serve.engine.escalate_row`) —
only a HEALTHY re-solve is adopted, mirroring the sweep quarantine's
adoption rule.

Healthy rows land in the content-addressed result cache
(:mod:`raft_tpu.serve.cache`); a submit-time hit resolves the future
without ever queueing.  Between miss and cache insert the case is
IN-FLIGHT: a duplicate submitted while its row is mid-solve joins the
solving tick's requester list (cross-tick joining,
``serve_inflight_joins``) instead of dispatching the same case again —
under a cold burst this removes the redundant re-solves that used to
stretch the tail (BENCH_r07 measured ~140 of 232 dispatched rows
redundant under the 200-client load).  Backpressure: per-client token buckets raise
:class:`QuotaExceeded` (→ 429), a full admission queue raises
:class:`QueueFull` (→ 503), a draining service raises
:class:`Draining` (→ 503).
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
import time
from collections import deque

import numpy as np

from raft_tpu.obs import metrics
from raft_tpu.obs.spans import span
from raft_tpu.serve import engine
from raft_tpu.serve.cache import ResultCache, result_cache_key
from raft_tpu.structure import bucketing
from raft_tpu.serve.quota import ClientQuotas
from raft_tpu.utils import config, health, structlog
from raft_tpu.utils.structlog import log_event


class RejectError(RuntimeError):
    """A request refused at admission (never queued)."""

    reason = "rejected"
    http_status = 503


class QuotaExceeded(RejectError):
    """Per-client token bucket dry — this client should slow down."""

    reason = "quota"
    http_status = 429

    def __init__(self, retry_after_s=0.0):
        super().__init__("client quota exceeded")
        self.retry_after_s = retry_after_s


class QueueFull(RejectError):
    """Admission queue at its bound — every client should back off."""

    reason = "queue_full"


class Draining(RejectError):
    """The service is draining (SIGTERM): in-flight work finishes, new
    work is refused."""

    reason = "draining"


class _Request:
    __slots__ = ("entry", "Hs", "Tp", "beta", "out_keys", "escalate_f64",
                 "client", "future", "t_submit", "cache_key", "trace_ctx",
                 "t_marks")

    def __init__(self, entry, Hs, Tp, beta, out_keys, escalate_f64, client,
                 cache_key, trace_ctx=None):
        self.entry = entry
        self.Hs, self.Tp, self.beta = Hs, Tp, beta
        self.out_keys = out_keys
        self.escalate_f64 = bool(escalate_f64)
        self.client = client
        self.future = concurrent.futures.Future()
        self.t_submit = time.perf_counter()
        self.cache_key = cache_key
        # (trace_id, span_id) of the request's serve_request span: the
        # tick span links to it, so one trace covers client -> queue ->
        # tick -> dispatch -> response across the thread boundary
        self.trace_ctx = trace_ctx
        # (tick_t0, dispatch_t0, dispatch_t1, solve_s, rows) stamped by
        # the tick that dispatched this request — the tail-attribution
        # stage decomposition and the latency exemplar read these at
        # resolve time (rows = unique dispatched rows in the group)
        self.t_marks = None


class Batcher:
    """Continuous batcher over a design :class:`~raft_tpu.serve.engine.
    Registry`.

    Construction resolves the mesh and the batch ladder but compiles
    nothing; the first tick (or :func:`raft_tpu.serve.engine.warm`)
    builds/loads the programs.  ``start()`` spawns the dispatcher
    thread; tests drive :meth:`run_tick` directly instead.
    """

    def __init__(self, registry, out_keys=None, mesh=None, tick_ms=None,
                 max_batch=None, cache=None, quotas=None, queue_bound=None,
                 replica_id=None):
        from raft_tpu.parallel.sweep import make_mesh

        self.registry = registry
        # stamped into latency exemplars so a /metrics scrape of a
        # fleet names WHICH replica served the p99 request
        self.replica_id = str(replica_id) if replica_id else (
            f"pid-{os.getpid()}")
        # status is non-optional: per-request error semantics read it
        self.out_keys = engine.normalize_out_keys(out_keys)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.sizes = engine.batch_ladder(self.mesh, max_batch)
        self.tick_s = (float(config.get("SERVE_TICK_MS"))
                       if tick_ms is None else float(tick_ms)) / 1e3
        # adaptive tick (ROADMAP item 5b): the coalescing window scales
        # between the floor and self.tick_s with the recent per-tick
        # row load, and a bucket group filling a top ladder rung
        # dispatches speculatively early — see _wake_in
        self.tick_mode = str(config.get("SERVE_TICK_MODE"))
        self.tick_floor_s = min(
            float(config.get("SERVE_TICK_MIN_MS")) / 1e3, self.tick_s)
        self.cache = cache if cache is not None else ResultCache(
            int(float(config.get("SERVE_CACHE_MB")) * 1e6))
        self.quotas = quotas if quotas is not None else ClientQuotas(
            config.get("SERVE_QPS"), config.get("SERVE_BURST"))
        self.queue_bound = (int(config.get("SERVE_QUEUE"))
                            if queue_bound is None else int(queue_bound))
        self._cond = threading.Condition()
        self._pending: deque[_Request] = deque()  # raft-lint: guarded-by=self._cond
        self._draining = False  # raft-lint: guarded-by=self._cond
        self._stop = False  # raft-lint: guarded-by=self._cond
        self._in_tick = False  # raft-lint: guarded-by=self._cond
        # adaptive-tick state: per-signature pending UNIQUE cache keys
        # (the full-rung early-dispatch trigger — duplicates of one
        # case dedupe to a single dispatched row, so counting requests
        # would collapse the window for a 1-row batch under a same-
        # corner burst), the oldest pending request's submit instant
        # (the window anchors on it), and an EMA of dispatched UNIQUE
        # rows per tick (the load signal the window scales with)
        self._sig_pending: dict = {}  # raft-lint: guarded-by=self._cond
        self._first_pending_t = None  # raft-lint: guarded-by=self._cond
        self._load_ema = 0.0  # raft-lint: guarded-by=self._cond
        # cross-tick in-flight joining: cache_key -> the requester list
        # of a row some tick is CURRENTLY solving.  A duplicate case
        # submitted mid-solve attaches to that list instead of queueing
        # a redundant dispatch row (the burst pattern: hundreds of
        # clients posting the same corner before the first result can
        # reach the cache) — the dispatching tick pops the (grown)
        # list when its row lands and fans out to every joiner.
        self._inflight: dict = {}  # raft-lint: guarded-by=self._cond
        self._thread = None

    # ------------------------------------------------------------ submit

    def submit(self, design, Hs, Tp, beta, out_keys=None, escalate_f64=False,
               client=None, trace_ctx=None):
        """Admit one evaluation request; returns a Future resolving to
        the result payload dict (``outputs``/``status``/``status_text``/
        ``cache_hit``/``escalated``).  Raises :class:`KeyError` for an
        unknown design name, :class:`ValueError` for out_keys outside
        the served set, and a :class:`RejectError` subclass on
        backpressure."""
        entry = (design if isinstance(design, engine.DesignEntry)
                 else self.registry.get(design))
        if entry is None:
            raise KeyError(f"unknown design {design!r}")
        requested = tuple(out_keys) if out_keys else self.out_keys
        extra_keys = set(requested) - set(self.out_keys)
        if extra_keys:
            raise ValueError(
                f"out_keys {sorted(extra_keys)} not served (this server "
                f"dispatches {list(self.out_keys)})")
        if self._draining:
            raise Draining("service is draining")
        bucket = self.quotas.bucket(client)
        if not bucket.acquire():
            metrics.counter("serve_rejected_quota").inc()
            log_event("serve_reject", reason="quota", client=str(client))
            raise QuotaExceeded(retry_after_s=bucket.retry_after_s())
        Hs, Tp, beta = float(Hs), float(Tp), float(beta)
        metrics.counter("serve_requests").inc()
        key = result_cache_key(
            entry.fingerprint, {"Hs": Hs, "Tp": Tp, "beta": beta},
            self.out_keys, extra=engine.flags_extra())
        req = _Request(entry, Hs, Tp, beta, requested, escalate_f64, client,
                       key, trace_ctx=trace_ctx)
        row = self.cache.get(key)
        if row is not None:
            # only HEALTHY rows are cached, so an opt-in escalation
            # never applies to a hit
            self._resolve(req, row, cache_hit=True)
            return req.future
        with self._cond:
            if self._draining:
                bucket.refund()   # rejected work must not eat quota
                raise Draining("service is draining")
            joined = self._inflight.get(key)
            if joined is not None:
                # the same case is mid-solve in an earlier tick: ride
                # its row instead of dispatching it again (cache-miss
                # only because the result is not back yet)
                joined.append(req)
                metrics.counter("serve_coalesced").inc()
                metrics.counter("serve_inflight_joins").inc()
                return req.future
            if len(self._pending) >= self.queue_bound:
                bucket.refund()
                metrics.counter("serve_rejected_queue").inc()
                log_event("serve_reject", reason="queue_full",
                          client=str(client))
                raise QueueFull(
                    f"admission queue full ({self.queue_bound} pending)")
            self._pending.append(req)
            metrics.gauge("serve_pending").set(len(self._pending))
            keys = self._sig_pending.setdefault(entry.sig, set())
            keys.add(key)
            n_sig = len(keys)
            if self._first_pending_t is None:
                self._first_pending_t = req.t_submit
            # the tick sleep IS the coalescing window, so a fixed-mode
            # submit never notifies (waking per submit would dispatch
            # every lull-time request as a batch of one — the PR-9
            # lesson).  Adaptive mode wakes the dispatcher only when
            # the window itself should move: the queue just went
            # empty->nonempty (the dispatcher may be parked on the
            # idle ceiling; _wake_in re-anchors on this request, so a
            # lone light-load request waits ~the floor, not the full
            # tick) or a bucket group just filled the top ladder rung
            # (a full batch gains nothing by waiting — dispatch NOW)
            if self.tick_mode == "adaptive" and (
                    len(self._pending) == 1 or n_sig >= self.sizes[-1]):
                self._cond.notify_all()
        return req.future

    # -------------------------------------------------------------- tick

    def run_tick(self):
        """Dispatch everything pending NOW (the dispatcher thread calls
        this once per tick; tests call it directly).  Returns the
        number of requests resolved."""
        with self._cond:
            batch = list(self._pending)
            self._pending.clear()
            self._sig_pending.clear()
            self._first_pending_t = None
            metrics.gauge("serve_pending").set(0)
            self._in_tick = True
        if not batch:
            with self._cond:
                # idle ticks decay the load signal so the first lone
                # request after a burst gets the floor window again
                self._load_ema *= 0.7
                self._in_tick = False
                self._cond.notify_all()
            return 0
        t0 = time.perf_counter()
        # dedupe identical in-flight cases: one dispatched row fans out
        # to every requester (sweeps and optimizer herds are full of
        # duplicate corners that miss the cache only because they are
        # simultaneous)
        unique: dict[str, list[_Request]] = {}
        for req in batch:
            unique.setdefault(req.cache_key, []).append(req)
        metrics.counter("serve_coalesced").inc(len(batch) - len(unique))
        with self._cond:
            # publish the requester lists for cross-tick joining: a
            # duplicate case submitted while its row is mid-solve
            # appends itself to the SAME list (under this lock) and is
            # fanned out when the dispatching chunk pops the key
            for key_, rl in unique.items():
                self._inflight[key_] = rl
            # load EMA over UNIQUE dispatched rows (0.3 smoothing):
            # a duplicate-heavy burst must not read as a full device
            self._load_ema += 0.3 * (len(unique) - self._load_ema)
        groups: dict = {}
        for reqs in unique.values():
            groups.setdefault(reqs[0].entry.sig, []).append(reqs)
        # the tick span LINKS to every coalesced request span (they live
        # in other tasks/threads, so they cannot be its tree children):
        # one trace then covers client -> admission queue -> tick ->
        # bucket dispatch -> response
        span_kw = {}
        if structlog.enabled():
            links = [{"trace_id": r.trace_ctx[0], "span_id": r.trace_ctx[1]}
                     for rl in unique.values() for r in rl if r.trace_ctx]
            if links:
                span_kw["links"] = links
        with span("serve_tick", rows=len(batch), unique=len(unique),
                  **span_kw):
            n_dispatch, deferred = self._dispatch_groups(groups, t0)
            # escalation re-solves run LAST (and still on this thread:
            # _rung_flags mutates process-wide env, so a parallel
            # escalation would leak f64 flags into a concurrent normal
            # dispatch) — every non-escalating requester already has its
            # result before anyone pays the solo re-solve, which on first
            # use may trace+compile the unwarmed f64_cpu program.  The
            # head-of-line cost that remains is the NEXT tick,
            # documented tradeoff.
            for rl, row in deferred:
                self._finalize(rl, row)
            wall = time.perf_counter() - t0
            metrics.histogram("serve_tick_s").observe(wall)
            log_event("serve_tick", rows=len(batch), unique=len(unique),
                      n_groups=len(groups), dispatches=n_dispatch,
                      wall_s=round(wall, 6))
        with self._cond:
            self._in_tick = False
            self._cond.notify_all()
        return len(batch)

    def _dispatch_groups(self, groups, tick_t0):
        """Dispatch every signature group of one tick; returns
        ``(n_dispatch, deferred)`` where ``deferred`` is the
        (reqs, row) list awaiting an f64 escalation re-solve.
        ``tick_t0`` is the tick's start instant: every dispatched
        request gets (tick_t0, dispatch window, solve wall) marks so
        resolve time can decompose its latency into stages."""
        n_dispatch = 0
        deferred = []
        for sig, reqlists in groups.items():
            cap = self.sizes[-1]
            for lo in range(0, len(reqlists), cap):
                chunk = reqlists[lo:lo + cap]
                firsts = [rl[0] for rl in chunk]
                t_d0 = time.perf_counter()
                timings = {}
                try:
                    out = engine.dispatch(
                        [r.entry for r in firsts],
                        [r.Hs for r in firsts], [r.Tp for r in firsts],
                        [r.beta for r in firsts],
                        out_keys=self.out_keys, mesh=self.mesh,
                        padded=engine.pick_padded(len(firsts), self.sizes),
                        timings=timings)
                    n_dispatch += 1
                except Exception as e:  # noqa: BLE001 — fan the failure out
                    log_event("serve_error", error=repr(e)[:300],
                              rows=len(chunk))
                    metrics.counter("serve_errors").inc()
                    # retire the in-flight keys FIRST so late joiners
                    # re-queue for a fresh tick instead of attaching to
                    # a list nobody will resolve again
                    with self._cond:
                        for rl in chunk:
                            self._inflight.pop(rl[0].cache_key, None)
                    for rl in chunk:
                        for req in rl:
                            if not req.future.set_running_or_notify_cancel():
                                continue
                            req.future.set_exception(e)
                    continue
                t_d1 = time.perf_counter()
                solve_s = min(timings.get("solve_s") or 0.0, t_d1 - t_d0)
                marks = (tick_t0, t_d0, t_d1, solve_s, len(firsts))
                for i, rl in enumerate(chunk):
                    row = {k: out[k][i] for k in self.out_keys}
                    # retire the in-flight key before fan-out: joiners
                    # appended up to this instant ride this row; later
                    # submits hit the result cache (or the next tick)
                    with self._cond:
                        self._inflight.pop(rl[0].cache_key, None)
                    for req in rl:
                        req.t_marks = marks
                    if self._needs_escalation(rl, row):
                        deferred.append((rl, row))
                    else:
                        self._finalize(rl, row)
        return n_dispatch, deferred

    @staticmethod
    def _needs_escalation(reqs, row):
        return (bool(health.any_bit(int(np.asarray(row["status"]))))
                and any(r.escalate_f64 for r in reqs))

    def _finalize(self, reqs, row):
        """Fan one dispatched row out to its (deduplicated) requesters:
        status checks, optional f64 escalation, cache insert."""
        status = int(np.asarray(row["status"]))
        severe = bool(health.any_bit(status))
        esc_row, esc_info = None, None
        if severe and any(r.escalate_f64 for r in reqs):
            try:
                retried, st2 = engine.escalate_row(
                    reqs[0].entry, reqs[0].Hs, reqs[0].Tp, reqs[0].beta,
                    out_keys=self.out_keys, mesh=self.mesh)
            except Exception as e:  # noqa: BLE001 — keep the base row
                esc_info = {"status_before": status, "status_after": None,
                            "resolved": False, "error": repr(e)[:200]}
            else:
                resolved = not bool(health.any_bit(st2))
                esc_info = {"status_before": status, "status_after": st2,
                            "resolved": resolved}
                if resolved:
                    esc_row = retried
                log_event("serve_escalate", status_before=status,
                          status_after=st2, resolved=resolved)
        if not severe:
            self.cache.put(reqs[0].cache_key, row)
        for req in reqs:
            use_esc = esc_row is not None and req.escalate_f64
            self._resolve(req, esc_row if use_esc else row, cache_hit=False,
                          escalated=esc_info if req.escalate_f64 else None)

    def _resolve(self, req, row, cache_hit, escalated=None):
        status = int(np.asarray(row["status"]))
        result = {
            "outputs": {k: row[k] for k in req.out_keys},
            "status": status,
            "status_text": health.describe(status),
            "severe": bool(health.any_bit(status)),
            "cache_hit": bool(cache_hit),
            "escalated": escalated,
        }
        if not req.future.set_running_or_notify_cancel():
            return  # requester went away (client timeout/cancel)
        wall = time.perf_counter() - req.t_submit
        # the exemplar carried by this observation if it lands in a
        # histogram's top-K: enough identity to reconstruct WHAT the
        # p99 request actually was (which design, which compiled
        # bucket, how many rows rode its dispatch, did the cache
        # answer, how did the solver judge it, on which replica) and
        # to join it back to its span tree via trace_id/span_id
        exemplar = {
            "design": req.entry.fingerprint,
            "sig": bucketing.signature_fingerprint(req.entry.sig),
            "cache_hit": int(bool(cache_hit)),
            "status": status,
            "replica": self.replica_id,
        }
        if req.trace_ctx is not None:
            exemplar["trace_id"], exemplar["span_id"] = req.trace_ctx
        if req.t_marks is not None:
            exemplar["rows"] = int(req.t_marks[4])
        metrics.histogram("serve_request_s").observe(wall, exemplar=exemplar)
        if req.t_marks is not None and not cache_hit \
                and req.t_submit <= req.t_marks[0]:
            # tail attribution: split this request's end-to-end latency
            # into named stages that sum to `wall` by construction —
            # queue_wait (pending until its tick began), tick_wait
            # (behind earlier groups inside the tick), dispatch
            # (pack/device_put overhead), solve (compiled program +
            # fetch), post (status fold / cache insert / escalation).
            # A cross-tick JOINER (submitted after its row's tick began)
            # is excluded: the tick-level stage windows started before
            # it existed, so they cannot decompose ITS wall
            tick_t0, d0, d1, solve_s = req.t_marks[:4]
            stages = {
                "queue_wait": max(tick_t0 - req.t_submit, 0.0),
                "tick_wait": max(d0 - tick_t0, 0.0),
                "dispatch": max((d1 - d0) - solve_s, 0.0),
                "solve": solve_s,
            }
            stages["post"] = max(wall - sum(stages.values()), 0.0)
            for name, v in stages.items():
                metrics.histogram(f"serve_stage_{name}_s").observe(v)
            if structlog.enabled():
                # stamp the REQUEST's ids explicitly (payload kwargs
                # override the ambient tick-span context), so `obs
                # report --tail` can join an exemplar's span_id
                # straight to this stage breakdown
                ids = req.trace_ctx or (None, None)
                log_event("serve_request_stages", wall_s=round(wall, 6),
                          escalated=escalated is not None,
                          trace_id=ids[0], span_id=ids[1],
                          **{f"{k}_s": round(v, 6)
                             for k, v in stages.items()})
        # the sliding-window twin of the lifetime histogram: /healthz
        # p50/p95-over-last-N-seconds and the SLO breach gate read this
        metrics.window("serve_request_window_s").observe(wall,
                                                         exemplar=exemplar)
        slo_ms = float(config.get("SERVE_SLO_MS") or 0)
        if slo_ms > 0 and wall * 1e3 > slo_ms:
            metrics.counter("serve_slo_breaches").inc()
            log_event("slo_breach", wall_s=round(wall, 6), slo_ms=slo_ms,
                      client=str(req.client), cache_hit=bool(cache_hit))
        req.future.set_result(result)

    # ------------------------------------------------------- tick thread

    def start(self):
        """Spawn the dispatcher thread (idempotent)."""
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="raft-serve-batcher")
        self._thread.start()
        return self

    def _loop(self):
        while True:
            t0 = time.perf_counter()
            self.run_tick()
            with self._cond:
                if self._stop and not self._pending:
                    return
                while not self._stop:
                    delay = self._wake_in(t0)
                    if delay <= 0:
                        break
                    # a submit may notify (adaptive wake conditions) —
                    # re-evaluate the window rather than trusting the
                    # original timeout
                    self._cond.wait(timeout=delay)

    def _wake_in(self, tick_t0):
        """Seconds until the next tick should run (call under _cond).

        Fixed mode: the constant ``SERVE_TICK_MS`` cadence.  Adaptive
        mode (ROADMAP item 5b): a bucket group at a full top ladder
        rung dispatches NOW (a full batch gains nothing by waiting);
        an empty queue parks on the ceiling (a submit notifies and
        re-anchors); otherwise the coalescing window anchors on the
        OLDEST pending request and scales with the recent per-tick row
        load between the floor (near-empty queue: the whole window is
        pure tail latency) and the ceiling (sustained load: bigger
        batches amortize the wait) — capped by the fixed cadence so a
        busy server never ticks slower than before."""
        now = time.perf_counter()
        deadline = tick_t0 + self.tick_s
        if self.tick_mode != "adaptive":
            return deadline - now
        if self._sig_pending and max(
                len(ks) for ks in self._sig_pending.values()) \
                >= self.sizes[-1]:
            return 0.0
        if self._first_pending_t is None:
            return deadline - now
        frac = min(1.0, self._load_ema / max(self.sizes[-1], 1))
        window = self.tick_floor_s + frac * (self.tick_s - self.tick_floor_s)
        return min(deadline, self._first_pending_t + window) - now

    def set_sizes(self, sizes):
        """Swap the batch ladder (post-warmup cost refinement,
        :func:`raft_tpu.serve.engine.refine_ladder`).  Every rung must
        already be warmed — the batcher only ever dispatches ladder
        sizes, so a pruned ladder keeps the compile-free contract."""
        sizes = tuple(sorted(int(s) for s in sizes))
        if not sizes:
            raise ValueError("empty batch ladder")
        with self._cond:
            self.sizes = sizes
        return self.sizes

    @property
    def draining(self):
        return self._draining

    def drain(self, timeout=None):
        """Graceful drain: refuse new work, finish every pending tick,
        stop the dispatcher.  Every already-accepted Future resolves
        before this returns (bounded by ``timeout``)."""
        t0 = time.perf_counter()
        pend0 = len(self._pending)
        with self._cond:
            self._draining = True
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            alive = self._thread.is_alive()
        else:
            # never started (socket-free/unit use): flush inline
            while self._pending or self._in_tick:
                self.run_tick()
            alive = False
        wall = round(time.perf_counter() - t0, 3)
        log_event("serve_drain", pending=pend0, wall_s=wall,
                  completed=not alive)
        return {"pending": pend0, "wall_s": wall, "completed": not alive}

    # -------------------------------------------------------------- misc

    def stats(self):
        return {
            "pending": len(self._pending),
            "inflight_rows": len(self._inflight),
            "draining": self._draining,
            "tick_ms": self.tick_s * 1e3,
            "tick_mode": self.tick_mode,
            "tick_floor_ms": self.tick_floor_s * 1e3,
            "load_ema_rows": round(self._load_ema, 2),
            "batch_sizes": list(self.sizes),
            "out_keys": list(self.out_keys),
            "designs": self.registry.names(),
            "cache": self.cache.stats(),
        }
