"""Subprocess body for the AOT program-bank tests (tests/test_aot.py).

Runs ONE sweep in a fresh JAX runtime and reports a JSON line on
stdout.  Everything is driven by environment variables so the parent
test composes scenarios without argument plumbing:

    RAFT_TPU_AOT / RAFT_TPU_AOT_DIR / RAFT_TPU_CACHE_DIR /
    RAFT_TPU_COMPILE_BUDGET      — the flags under test
    AOT_CHILD_OUT                — where to savez the sweep outputs
    AOT_CHILD_MODEL              — "spar": the bundled spar model via
                                   make_case_evaluator (the acceptance
                                   path); unset: a tiny deterministic
                                   closure (fast mechanics tests)
    AOT_CHILD_FAKE_CODE          — pretend the raft_tpu sources have a
                                   different content hash (simulates a
                                   code edit / jax upgrade: stored
                                   entries must MISS cleanly)

Not a pytest module (underscore name): executed via ``python -m`` from
test subprocesses only.
"""

import json
import os
import sys
import time

t_proc = time.perf_counter()

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from raft_tpu.analysis import recompile  # noqa: E402
from raft_tpu.aot import bank  # noqa: E402
from raft_tpu.obs import metrics  # noqa: E402
from raft_tpu.parallel.sweep import make_mesh, sweep_cases  # noqa: E402


def tiny_evaluator():
    def evaluate(h, t, b):
        w = jnp.linspace(0.1, 2.0, 16)
        psd = (h / t) ** 2 / ((w - 2 * np.pi / t) ** 2 + 0.01)
        return {"PSD": psd, "X0": jnp.stack([h * jnp.cos(b),
                                             h * jnp.sin(b)])}

    evaluate._raft_program_key = ("aot_child_tiny", 1)
    return evaluate, ("PSD", "X0")


def spar_evaluator():
    import raft_tpu
    from raft_tpu import api

    design = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "raft_tpu", "designs", "spar_demo.yaml")
    model = raft_tpu.Model(design)
    return api.make_case_evaluator(model), ("PSD", "X0", "status")


def main():
    if os.environ.get("AOT_CHILD_FAKE_CODE"):
        bank._CODE_FP_CACHE.clear()
        bank.code_fingerprint = lambda: os.environ["AOT_CHILD_FAKE_CODE"]

    if os.environ.get("AOT_CHILD_MODEL") == "spar":
        evaluate, out_keys = spar_evaluator()
    else:
        evaluate, out_keys = tiny_evaluator()
    build_done_s = time.perf_counter() - t_proc

    mesh = make_mesh(8)
    rng = np.random.default_rng(7)
    Hs = rng.uniform(2.0, 8.0, 8)
    Tp = rng.uniform(6.0, 14.0, 8)
    beta = rng.uniform(-0.5, 0.5, 8)

    with recompile.count_compilations() as log:
        out = sweep_cases(evaluate, Hs, Tp, beta, mesh=mesh,
                          out_keys=out_keys)
        jax.block_until_ready(out)
    cold_start_s = time.perf_counter() - t_proc

    out_path = os.environ.get("AOT_CHILD_OUT")
    if out_path:
        np.savez(out_path, **{k: np.asarray(v) for k, v in out.items()})

    c = metrics.snapshot()["counters"]
    print(json.dumps({
        "cold_start_s": round(cold_start_s, 2),
        "build_s": round(build_done_s, 2),
        "sweep_compile_events": log.count,
        "sweep_real_compiles": log.real_count,
        "process_real_compiles": recompile.PROCESS_LOG.real_count,
        "loaded": c.get("aot_programs_loaded", 0),
        "compiled": c.get("aot_programs_compiled", 0),
        "misses": c.get("aot_bank_misses", 0),
        "errors": c.get("aot_bank_errors", 0),
    }))


if __name__ == "__main__":
    main()
