"""Second-order wave kinematics helpers (jax).

Twins of the reference's second-order wave field functions
(``/root/reference/raft/helpers.py``: ``getWaveKin_grad_u1`` :239,
``getWaveKin_grad_dudt`` :280, ``getWaveKin_grad_pres1st`` :284,
``getWaveKin_axdivAcc`` :310, ``getWaveKin_pot2ndOrd`` :336), used by
the slender-body QTF computation.

NOTE on a replicated reference quirk: these helpers receive the wave
heading in RADIANS from the QTF call chain, but apply ``deg2rad`` to it
for the direction cosines while using the raw value inside the phase
factor (helpers.py:244-246 vs :260).  The golden QTF data encodes this
mixed-units behavior, so it is reproduced verbatim here; pass
``beta_rad`` exactly as the reference does.
"""

from __future__ import annotations

import jax.numpy as jnp

DEG2RAD = 0.017453292519943295


def _khz(k, h, z, denom="sinh"):
    """cosh/sinh(k(z+h)) / {sinh,cosh}(kh) with the reference's deep-water
    switch at k h >= 10 (helpers.py:250-257)."""
    kh = k * h
    deep = kh >= 10.0
    arg_zh = jnp.where(deep, 0.0, k * (z + h))
    arg_h = jnp.where(deep, 1.0, kh)
    den = jnp.sinh(arg_h) if denom == "sinh" else jnp.cosh(arg_h)
    khz_xy = jnp.where(deep, jnp.exp(k * z), jnp.cosh(arg_zh) / den)
    khz_z = jnp.where(deep, jnp.exp(k * z), jnp.sinh(arg_zh) / den)
    return khz_xy, khz_z


def grad_u1(w, k, beta_rad, h, r):
    """(3,3) complex gradient of first-order velocity at point r.

    helpers.py:239-277 — including the deg2rad-of-radians quirk."""
    x, y, z = r[0], r[1], r[2]
    cosB = jnp.cos(DEG2RAD * beta_rad)
    sinB = jnp.sin(DEG2RAD * beta_rad)
    khz_xy, khz_z = _khz(k, h, z, denom="sinh")
    active = (z <= 0) & (k > 0)

    phase = jnp.exp(-1j * (k * (jnp.cos(beta_rad) * x + jnp.sin(beta_rad) * y)))
    aux_x = w * cosB * phase
    aux_y = w * sinB * phase
    aux_z = 1j * w * phase

    g00 = -1j * aux_x * khz_xy * k * cosB
    g01 = -1j * aux_x * khz_xy * k * sinB
    g02 = aux_x * k * khz_z
    g11 = -1j * aux_y * khz_xy * k * sinB
    g12 = aux_y * k * khz_z
    g22 = aux_z * k * khz_xy
    G = jnp.array([
        [g00, g01, g02],
        [g01, g11, g12],
        [g02, g01, g22],  # reference sets grad[2,1] = grad[0,1] (:274)
    ])
    return jnp.where(active, G, 0.0)


def grad_dudt(w, k, beta_rad, h, r):
    return 1j * w * grad_u1(w, k, beta_rad, h, r)


def grad_pres1st(k, beta_rad, h, r, rho=1025.0, g=9.81):
    """(3,) complex gradient of first-order pressure; helpers.py:284-307."""
    x, y, z = r[0], r[1], r[2]
    cosB = jnp.cos(DEG2RAD * beta_rad)
    sinB = jnp.sin(DEG2RAD * beta_rad)
    khz_xy, khz_z = _khz(k, h, z, denom="cosh")
    active = (z <= 0) & (k > 0)
    phase = jnp.exp(-1j * (k * (cosB * x + sinB * y)))
    out = jnp.array([
        rho * g * khz_xy * phase * (-1j * k * cosB),
        rho * g * khz_xy * phase * (-1j * k * sinB),
        rho * g * khz_z * phase * k,
    ])
    return jnp.where(active, out, 0.0)


def _u_single(w, k, beta_rad, h, r, rho=1025.0, g=9.81):
    """First-order velocity amplitude for unit elevation at one (w, k);
    mirrors getWaveKin for a single component (helpers.py:188-236)."""
    x, y, z = r[0], r[1], r[2]
    zeta = jnp.exp(-1j * (k * (jnp.cos(beta_rad) * x + jnp.sin(beta_rad) * y)))
    kh = k * h
    deep = kh > 89.4
    kzero = k == 0.0
    arg_zh = jnp.where(deep | kzero, 0.0, k * (z + h))
    arg_h = jnp.where(deep | kzero, 1.0, kh)
    SINH = jnp.sinh(arg_zh) / jnp.sinh(arg_h)
    COSHs = jnp.cosh(arg_zh) / jnp.sinh(arg_h)
    ekz = jnp.exp(jnp.minimum(k * z, 0.0))
    SINH = jnp.where(deep, ekz, jnp.where(kzero, 1.0, SINH))
    COSHs = jnp.where(deep, ekz, jnp.where(kzero, 99999.0, COSHs))
    u = jnp.array([
        w * zeta * COSHs * jnp.cos(beta_rad),
        w * zeta * COSHs * jnp.sin(beta_rad),
        1j * w * zeta * SINH,
    ])
    return jnp.where(z <= 0, u, 0.0)


def axdiv_acc(w1, w2, k1, k2, beta_rad, h, r, vel1, vel2, q, g=9.81):
    """Rainey axial-divergence acceleration; helpers.py:310-333."""
    aux1 = grad_u1(w1, k1, beta_rad, h, r) @ q
    dwdz1 = jnp.dot(aux1, q)
    u1 = _u_single(w1, k1, beta_rad, h, r, g=g)
    aux2 = grad_u1(w2, k2, beta_rad, h, r) @ q
    dwdz2 = jnp.dot(aux2, q)
    u2 = _u_single(w2, k2, beta_rad, h, r, g=g)

    v1 = vel1 - jnp.dot(vel1, q) * q
    v2 = vel2 - jnp.dot(vel2, q) * q
    u1p = u1 - jnp.dot(u1, q) * q
    u2p = u2 - jnp.dot(u2, q) * q

    acc = 0.25 * (dwdz1 * jnp.conj(u2p - v2) + jnp.conj(dwdz2) * (u1p - v1))
    acc = acc - jnp.dot(acc, q) * q
    return acc


def pot_2nd_ord(w1, w2, k1, k2, beta_rad, h, r, g=9.81, rho=1025.0):
    """Difference-frequency second-order potential acceleration and
    pressure; helpers.py:336-373 (with the deg2rad quirk)."""
    b = DEG2RAD * beta_rad
    cosB, sinB = jnp.cos(b), jnp.sin(b)
    z = r[2]
    k1_k2 = jnp.array([k1 * cosB - k2 * cosB, k1 * sinB - k2 * sinB, 0.0])
    nk = jnp.linalg.norm(k1_k2)
    nk_safe = jnp.where(nk == 0, 1e-30, nk)

    dw2 = (w1 - w2) ** 2
    den1 = dw2 / g - nk * jnp.tanh(nk_safe * h)
    den1 = jnp.where(jnp.abs(den1) < 1e-30, 1e-30, den1)
    g12 = (-1j * g / (2 * w1)) * (
        k1**2 * (1 - jnp.tanh(k1 * h) ** 2)
        - 2 * k1 * k2 * (1 + jnp.tanh(k1 * h) * jnp.tanh(k2 * h))
    ) / den1
    g21 = (-1j * g / (2 * w2)) * (
        k2**2 * (1 - jnp.tanh(k2 * h) ** 2)
        - 2 * k2 * k1 * (1 + jnp.tanh(k2 * h) * jnp.tanh(k1 * h))
    ) / den1
    aux = 0.5 * (g21 + jnp.conj(g12))

    khz_xy = jnp.cosh(nk_safe * (z + h)) / jnp.cosh(nk_safe * h)
    khz_z = jnp.sinh(nk_safe * (z + h)) / jnp.cosh(nk_safe * h)
    phase = jnp.exp(-1j * jnp.dot(k1_k2, r))

    acc = jnp.array([
        aux * khz_xy * phase * (w1 - w2) * (k1 * cosB - k2 * cosB),
        aux * khz_xy * phase * (w1 - w2) * (k1 * sinB - k2 * sinB),
        aux * khz_z * phase * 1j * (w1 - w2) * nk,
    ])
    p = aux * khz_xy * phase * (-1j) * rho * (w1 - w2)

    active = (z <= 0) & (k1 > 0) & (k2 > 0) & (w1 != w2)
    return jnp.where(active, acc, 0.0), jnp.where(active, p, 0.0)
