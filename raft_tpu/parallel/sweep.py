"""Device-mesh sweep drivers: scale design/case evaluations over TPUs.

The reference sweeps designs with nested serial Python loops
(``/root/reference/raft/parametersweep.py:56-100``) and has no
distributed backend (SURVEY.md §2.1).  Here a sweep is one batched
tensor program laid out over a ``jax.sharding.Mesh``:

* the **batch** axis (designs x cases — embarrassingly parallel, each a
  ~6-DOF problem) shards over the ``dp`` mesh axis and rides ICI;
* the **frequency** axis — the workload's 'sequence' axis — can shard
  over ``sp``; the only cross-frequency couplings are the
  drag-linearisation RMS statistics and the convergence norm
  (raft_member.py:2084-2090), which XLA lowers to all-reduces when the
  sharded program is compiled (the moral equivalent of context
  parallelism for this physics).

Everything goes through GSPMD: we annotate in/out shardings and let the
compiler insert the collectives.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices=None, axis_names=("dp",)):
    devices = np.array(jax.devices()[: n_devices or len(jax.devices())])
    if len(axis_names) == 1:
        shape = (len(devices),)
    else:
        # split devices as evenly as possible over two axes
        n = len(devices)
        dp = max(d for d in range(1, n + 1) if n % d == 0 and d * d <= n)
        shape = (n // dp, dp)
    return Mesh(devices.reshape(shape), axis_names)


def sweep_cases(evaluate, Hs, Tp, beta, mesh=None, out_keys=("PSD", "X0")):
    """Evaluate a batch of sea states, sharded over the mesh's dp axis.

    evaluate : scalar-case function from :func:`raft_tpu.api.make_case_evaluator`
    Hs/Tp/beta : (N,) arrays (N divisible by the dp axis size)
    """
    if mesh is None:
        mesh = make_mesh()
    batched = jax.vmap(lambda h, t, b: {k: evaluate(h, t, b)[k] for k in out_keys})
    sharding = NamedSharding(mesh, P("dp"))
    fn = jax.jit(batched, in_shardings=(sharding, sharding, sharding))
    args = [jax.device_put(jnp.asarray(x), sharding) for x in (Hs, Tp, beta)]
    return fn(*args)
