"""Static equilibrium parity vs the reference's hardcoded mean offsets.

Targets from /root/reference/tests/test_model.py:75-123 (solveStatics
under wave-only and current-only loading; the wind cases additionally
need the aero module and are covered by the aero milestone).  The
catenary mooring module is exercised end-to-end here: matching these
equilibria requires the mooring force and tangent stiffness to agree
with MoorPy's.
"""

import os

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu

pytestmark = pytest.mark.slow

CASES = {
    "wave": {
        "wind_speed": 0, "wind_heading": 0, "turbulence": 0,
        "turbine_status": "operating", "yaw_misalign": 0,
        "wave_spectrum": "JONSWAP", "wave_period": 10, "wave_height": 4,
        "wave_heading": -30, "current_speed": 0, "current_heading": 0,
    },
    "current": {
        "wind_speed": 0, "wind_heading": 0, "turbulence": 0,
        "turbine_status": "operating", "yaw_misalign": 0,
        "wave_spectrum": "JONSWAP", "wave_period": 0, "wave_height": 0,
        "wave_heading": 0, "current_speed": 0.6, "current_heading": 15,
    },
    "wind": {
        "wind_speed": 8, "wind_heading": 30, "turbulence": 0,
        "turbine_status": "operating", "yaw_misalign": 0,
        "wave_spectrum": "JONSWAP", "wave_period": 0, "wave_height": 0,
        "wave_heading": 0, "current_speed": 0, "current_heading": 0,
    },
    "wind_wave_current": {
        "wind_speed": 8, "wind_heading": 30, "turbulence": 0,
        "turbine_status": "operating", "yaw_misalign": 0,
        "wave_spectrum": "JONSWAP", "wave_period": 10, "wave_height": 4,
        "wave_heading": -30, "current_speed": 0.6, "current_heading": 15,
    },
}

# desired_X0 rows from test_model.py for the designs we support so far
TARGETS = {
    "OC3spar.yaml": {
        "wave": [-1.64267049e-05, -2.83795893e-15, -6.65861624e-01,
                 3.88717546e-19, -5.94238978e-11, -4.02571352e-17],
        "current": [3.86072176e+00, 9.22694246e-01, -6.74898762e-01,
                    -2.64759824e-04, 9.82529767e-04, -1.03532699e-05],
    },
    "VolturnUS-S.yaml": {
        "wave": [4.27925162e-01, -9.00035158e-17, -4.51814991e-01,
                 -5.63389767e-18, -2.54250076e-02, -1.07219357e-22],
        "current": [3.46491856e+00, 8.10382757e-01, -4.53718903e-01,
                    6.48535991e-04, -2.79078335e-02, 3.71621922e-03],
    },
    "VolturnUS-S-pointInertia.yaml": {
        "wave": [4.34028448e-01, 1.29311805e-15, -4.66112782e-01,
                 8.09445578e-17, -2.58031212e-02, 1.54046523e-21],
        "current": [3.47177656e+00, 8.10749061e-01, -4.68029699e-01,
                    6.58432223e-04, -2.83226533e-02, 3.71570242e-03],
    },
    "OC4semi-WAMIT_Coefs.yaml": {
        "wave": [-1.72715184e-03, -1.57518810e-16, -1.94361922e-01,
                 1.07116427e-16, -7.10621656e-08, 1.63094600e-21],
        "current": [1.71117023e+00, 4.59025857e-01, -1.94362700e-01,
                    3.00965823e-04, -1.12322280e-03, 9.56379292e-08],
    },
}


# wind-case rows from /root/reference/tests/test_model.py desired_X0
# (indices 0 = OC3spar, 1 = VolturnUS-S, 4 = OC4semi-WAMIT_Coefs)
WIND_TARGETS = {
    "OC3spar.yaml": {
        "wind": [1.09516355e+01, 5.35255759e+00, -8.11412806e-01,
                 -2.20873760e-02, 4.01303217e-02, -5.01725650e-03],
        "wind_wave_current": [1.51631881e+01, 5.72634727e+00, -8.60169827e-01,
                              -2.23626764e-02, 4.10513406e-02, -1.30793500e-02],
    },
    "VolturnUS-S.yaml": {
        "wind": [1.31272840e+01, 1.07929704e+01, -5.25069310e-01,
                 -1.83674546e-02, 3.77423342e-02, -1.08655033e-03],
        "wind_wave_current": [1.53251788e+01, 1.20396365e+01, -5.38169903e-01,
                              -1.76586714e-02, 3.54288952e-02, 2.63027461e-03],
    },
    "OC4semi-WAMIT_Coefs.yaml": {
        "wind": [4.40156080e+00, 3.10317400e+00, -2.06683747e-01,
                 -1.45699889e-02, 2.77354876e-02, -8.23131250e-04],
        "wind_wave_current": [5.85516544e+00, 3.77367023e+00, -2.09149016e-01,
                              -1.42540233e-02, 2.66270816e-02, -8.22294356e-04],
    },
}


@pytest.mark.parametrize("design", list(TARGETS), ids=[d.split(".")[0] for d in TARGETS])
@pytest.mark.parametrize("case_name", ["wave", "current", "wind",
                                       "wind_wave_current"])
def test_solve_statics(design, case_name):
    path = ref_data(design)
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    if case_name in ("wind", "wind_wave_current") and design not in WIND_TARGETS:
        pytest.skip("no wind target stored for this design")
    model = raft_tpu.Model(path)
    X = np.asarray(model.solve_statics(CASES[case_name]))
    # The reference targets are *early-stopped* Newton iterates (dsolve2
    # stops at 0.05 m / 0.005 rad steps), so they are trajectory-dependent:
    # our exact mooring tangent stiffness (vs MoorPy's analytic assembly)
    # shifts the current-case iterates by O(1e-5 m).  The wave cases match
    # at the reference's own tolerance.
    if case_name == "current":
        assert_allclose(X, TARGETS[design][case_name], rtol=5e-4, atol=5e-5)
    elif case_name in ("wind", "wind_wave_current"):
        # the mean rotor thrust from our BEMT deviates from CCBlade by
        # up to ~1% (see test_aero), which carries into the offsets
        tgt = np.asarray(WIND_TARGETS[design][case_name])
        scale = np.max(np.abs(tgt))
        assert_allclose(X, tgt, atol=0.02 * scale, rtol=0)
    else:
        assert_allclose(X, TARGETS[design][case_name], rtol=1e-5, atol=1e-6)
