"""Panel mesh generation for circular members (member2pnl equivalent).

Generates quadrilateral panel meshes of the submerged portion of
cylindrical members for the potential-flow solver, mirroring the role
of the reference mesher (``/root/reference/raft/member2pnl.py``:
``meshMember`` :73, side/cap paneling with waterline clipping) with a
simpler regular discretisation, plus a writer for the HAMS ``.pnl``
interchange format the reference's BEM pipeline consumes.
"""

from __future__ import annotations

import numpy as np


def mesh_cylinder(stations, diameters, rA, q, n_az=18, dz_max=2.0):
    """Quad panel mesh of a (possibly tapered) circular member's wetted
    surface, clipped at z = 0; includes a bottom cap.

    stations : (n,) axial positions from end A; diameters : (n,);
    rA : (3,) end-A coordinates; q : (3,) axial unit vector.

    Returns (vertices (P,4,3), centroids (P,3), normals (P,3) outward,
    areas (P,)).
    """
    stations = np.asarray(stations, dtype=float)
    diameters = np.asarray(diameters, dtype=float)
    rA = np.asarray(rA, dtype=float)
    q = np.asarray(q, dtype=float)
    q = q / np.linalg.norm(q)

    # axial subdivision (finer than stations)
    s_grid = [stations[0]]
    for i in range(1, len(stations)):
        seg = stations[i] - stations[i - 1]
        if seg <= 0:
            continue
        nseg = max(1, int(np.ceil(seg / dz_max)))
        s_grid += list(stations[i - 1] + seg * (np.arange(1, nseg + 1) / nseg))
    s_grid = np.asarray(s_grid)
    d_grid = np.interp(s_grid, stations, diameters)

    # local transverse axes
    tmp = np.array([1.0, 0, 0]) if abs(q[2]) > 0.9 else np.array([0, 0, 1.0])
    p1 = np.cross(tmp, q)
    p1 /= np.linalg.norm(p1)
    p2 = np.cross(q, p1)

    th = np.linspace(0, 2 * np.pi, n_az + 1)
    verts, cents, norms, areas = [], [], [], []

    def ring(s, d):
        c = rA + q * s
        return c[None, :] + 0.5 * d * (
            np.cos(th)[:, None] * p1[None, :] + np.sin(th)[:, None] * p2[None, :]
        )

    for i in range(len(s_grid) - 1):
        zA = rA[2] + q[2] * s_grid[i]
        zB = rA[2] + q[2] * s_grid[i + 1]
        if zA >= 0 and zB >= 0:
            continue
        sA, dA = s_grid[i], d_grid[i]
        sB, dB = s_grid[i + 1], d_grid[i + 1]
        # clip the segment at the waterline
        if zB > 0:
            f = (0.0 - zA) / (zB - zA)
            sB = sA + f * (s_grid[i + 1] - s_grid[i])
            dB = dA + f * (d_grid[i + 1] - d_grid[i])
        elif zA > 0:
            f = (0.0 - zB) / (zA - zB)
            sA = sB + f * (s_grid[i] - s_grid[i + 1])
            dA = dB + f * (d_grid[i] - d_grid[i + 1])
        rA_ring = ring(sA, dA)
        rB_ring = ring(sB, dB)
        for k in range(n_az):
            vs = np.array([rA_ring[k], rA_ring[k + 1], rB_ring[k + 1], rB_ring[k]])
            c = vs.mean(axis=0)
            d1 = vs[2] - vs[0]
            d2 = vs[3] - vs[1]
            nvec = np.cross(d1, d2)
            a = 0.5 * np.linalg.norm(nvec)
            if a < 1e-10:
                continue
            nvec = nvec / (2 * a)
            # outward = away from member axis
            axis_pt = rA + q * np.dot(c - rA, q)
            if np.dot(nvec, c - axis_pt) < 0:
                nvec = -nvec
                vs = vs[::-1]
            verts.append(vs)
            cents.append(c)
            norms.append(nvec)
            areas.append(a)

    # bottom cap (triangle fan collapsed to quads), if submerged
    if rA[2] + q[2] * s_grid[0] < 0:
        d0 = d_grid[0]
        c0 = rA + q * s_grid[0]
        ring0 = ring(s_grid[0], d0)
        for k in range(n_az):
            vs = np.array([c0, ring0[k + 1], ring0[k], c0])
            d1 = vs[2] - vs[0]
            d2 = vs[1] - vs[0]
            nvec = np.cross(d1, d2)
            a = 0.5 * np.linalg.norm(nvec)
            if a < 1e-10:
                continue
            nvec = nvec / (2 * a)
            if np.dot(nvec, -q) < 0:  # cap normal points away from body (down)
                nvec = -nvec
                vs = vs[::-1]
            verts.append(vs)
            cents.append(vs.mean(axis=0))
            norms.append(nvec)
            areas.append(a)

    return (np.asarray(verts), np.asarray(cents), np.asarray(norms),
            np.asarray(areas))


def mesh_cylinder_capped(stations, diameters, rA, q, n_az=18, dz_max=2.0,
                         p1=None, p2=None):
    """:func:`mesh_cylinder` plus a top cap when the upper end is
    submerged (fully submerged members — pontoons, heave plates — need
    closed surfaces for the panel solver)."""
    verts, cents, norms, areas = mesh_cylinder(
        stations, diameters, rA, q, n_az=n_az, dz_max=dz_max)
    rA = np.asarray(rA, dtype=float)
    q = np.asarray(q, dtype=float) / np.linalg.norm(q)
    zTop = rA[2] + q[2] * stations[-1]
    if zTop < -1e-9 and len(verts):
        tmp = np.array([1.0, 0, 0]) if abs(q[2]) > 0.9 else np.array([0, 0, 1.0])
        p1v = np.cross(tmp, q)
        p1v /= np.linalg.norm(p1v)
        p2v = np.cross(q, p1v)
        th = np.linspace(0, 2 * np.pi, n_az + 1)
        c0 = rA + q * stations[-1]
        ring = c0[None, :] + 0.5 * diameters[-1] * (
            np.cos(th)[:, None] * p1v[None, :] + np.sin(th)[:, None] * p2v[None, :])
        vs_l, c_l, n_l, a_l = [], [], [], []
        for k in range(n_az):
            vs = np.array([c0, ring[k], ring[k + 1], c0])
            d1 = vs[2] - vs[0]
            d2 = vs[1] - vs[0]
            nvec = np.cross(d2, d1)
            a = 0.5 * np.linalg.norm(nvec)
            if a < 1e-10:
                continue
            nvec = nvec / (2 * a)
            if np.dot(nvec, q) < 0:  # top cap outward = +q
                nvec = -nvec
                vs = vs[::-1]
            vs_l.append(vs)
            c_l.append(vs.mean(axis=0))
            n_l.append(nvec)
            a_l.append(a)
        if vs_l:
            verts = np.concatenate([verts, np.asarray(vs_l)])
            cents = np.concatenate([cents, np.asarray(c_l)])
            norms = np.concatenate([norms, np.asarray(n_l)])
            areas = np.concatenate([areas, np.asarray(a_l)])
    return verts, cents, norms, areas


def mesh_rectangular(stations, sides, rA, q, p1, p2, dz_max=2.0, da_max=2.0):
    """Quad panel mesh of a (possibly tapered) rectangular member's
    wetted surface, clipped at z = 0, with end caps on submerged ends
    (the reference meshes rectangular members in
    member2pnl.meshRectangularMember:504-670).

    stations : (n,) axial positions; sides : (n,2) (p1-width, p2-width);
    rA : end-A coordinates; q, p1, p2 : member axes.
    """
    stations = np.asarray(stations, dtype=float)
    sides = np.asarray(sides, dtype=float)
    rA = np.asarray(rA, dtype=float)
    q = np.asarray(q, dtype=float) / np.linalg.norm(q)
    p1 = np.asarray(p1, dtype=float)
    p2 = np.asarray(p2, dtype=float)

    s_grid = [stations[0]]
    for i in range(1, len(stations)):
        seg = stations[i] - stations[i - 1]
        if seg <= 0:
            continue
        nseg = max(1, int(np.ceil(seg / dz_max)))
        s_grid += list(stations[i - 1] + seg * (np.arange(1, nseg + 1) / nseg))
    s_grid = np.asarray(s_grid)
    w1 = np.interp(s_grid, stations, sides[:, 0])
    w2 = np.interp(s_grid, stations, sides[:, 1])

    def perimeter(s, a, b, n_per_side):
        """Points around the rectangle boundary at axial position s."""
        c = rA + q * s
        n1, n2 = n_per_side
        # corners in (p1, p2) local coords, ccw
        u = np.concatenate([
            np.linspace(-a / 2, a / 2, n1 + 1)[:-1],
            np.full(n2, a / 2),
            np.linspace(a / 2, -a / 2, n1 + 1)[:-1],
            np.full(n2, -a / 2)])
        v = np.concatenate([
            np.full(n1, -b / 2),
            np.linspace(-b / 2, b / 2, n2 + 1)[:-1],
            np.full(n1, b / 2),
            np.linspace(b / 2, -b / 2, n2 + 1)[:-1]])
        pts = c[None, :] + u[:, None] * p1[None, :] + v[:, None] * p2[None, :]
        return np.vstack([pts, pts[:1]])

    n1 = max(2, int(np.ceil(np.max(w1) / da_max)))
    n2 = max(2, int(np.ceil(np.max(w2) / da_max)))
    nper = 2 * (n1 + n2)

    verts, cents, norms, areas = [], [], [], []

    def add_quad(vs, outward_hint):
        c = vs.mean(axis=0)
        d1 = vs[2] - vs[0]
        d2 = vs[3] - vs[1]
        nvec = np.cross(d1, d2)
        a = 0.5 * np.linalg.norm(nvec)
        if a < 1e-10:
            return
        nvec = nvec / (2 * a)
        if np.dot(nvec, outward_hint) < 0:
            nvec = -nvec
            vs = vs[::-1]
        verts.append(vs)
        cents.append(c)
        norms.append(nvec)
        areas.append(a)

    for i in range(len(s_grid) - 1):
        zA = rA[2] + q[2] * s_grid[i]
        zB = rA[2] + q[2] * s_grid[i + 1]
        if zA >= 0 and zB >= 0:
            continue
        sA, aA, bA = s_grid[i], w1[i], w2[i]
        sB, aB, bB = s_grid[i + 1], w1[i + 1], w2[i + 1]
        if zB > 0:
            f = (0.0 - zA) / (zB - zA)
            sB = sA + f * (s_grid[i + 1] - s_grid[i])
            aB = aA + f * (w1[i + 1] - w1[i])
            bB = bA + f * (w2[i + 1] - w2[i])
        elif zA > 0:
            f = (0.0 - zB) / (zA - zB)
            sA = sB + f * (s_grid[i] - s_grid[i + 1])
            aA = aB + f * (w1[i] - w1[i + 1])
            bA = bB + f * (w2[i] - w2[i + 1])
        ringA = perimeter(sA, aA, bA, (n1, n2))
        ringB = perimeter(sB, aB, bB, (n1, n2))
        cA = rA + q * sA
        for k in range(nper):
            vs = np.array([ringA[k], ringA[k + 1], ringB[k + 1], ringB[k]])
            hint = vs.mean(axis=0) - (cA + q * np.dot(vs.mean(axis=0) - cA, q))
            add_quad(vs, hint if np.linalg.norm(hint) > 1e-9 else p1)

    # end caps (regular grids) on submerged ends
    for end, sgn in ((0, -1.0), (-1, 1.0)):
        z_end = rA[2] + q[2] * s_grid[end]
        if z_end >= -1e-9:
            continue
        a, b = w1[end], w2[end]
        c0 = rA + q * s_grid[end]
        us = np.linspace(-a / 2, a / 2, n1 + 1)
        vsv = np.linspace(-b / 2, b / 2, n2 + 1)
        for iu in range(n1):
            for ivv in range(n2):
                quad = np.array([
                    c0 + us[iu] * p1 + vsv[ivv] * p2,
                    c0 + us[iu + 1] * p1 + vsv[ivv] * p2,
                    c0 + us[iu + 1] * p1 + vsv[ivv + 1] * p2,
                    c0 + us[iu] * p1 + vsv[ivv + 1] * p2,
                ])
                add_quad(quad, sgn * q)

    if not verts:
        return (np.zeros((0, 4, 3)), np.zeros((0, 3)), np.zeros((0, 3)),
                np.zeros(0))
    return (np.asarray(verts), np.asarray(cents), np.asarray(norms),
            np.asarray(areas))


def point_in_member(pts, mem, shrink=1e-3):
    """Boolean mask: points strictly inside a member's outer volume.

    Circular members: radial distance against the station-interpolated
    radius; rectangular: |p1|,|p2| components against half-sides.  The
    ``shrink`` margin keeps panels ON the surface classified outside.
    """
    pts = np.asarray(pts, dtype=float)
    rA = np.asarray(mem.rA0, dtype=float)
    q = np.asarray(mem.q0, dtype=float)
    s = (pts - rA) @ q
    inside_ax = (s > 1e-6) & (s < mem.stations[-1] - 1e-6)
    radial = pts - rA - s[:, None] * q[None, :]
    if mem.circular:
        r_at = np.interp(s, mem.stations, mem.d[:, 0] / 2.0)
        inside_r = np.linalg.norm(radial, axis=1) < r_at * (1 - shrink) - 1e-6
    else:
        p1 = np.asarray(mem.p10, dtype=float)
        p2 = np.asarray(mem.p20, dtype=float)
        a_at = np.interp(s, mem.stations, mem.d[:, 0] / 2.0)
        b_at = np.interp(s, mem.stations, mem.d[:, 1] / 2.0)
        inside_r = ((np.abs(radial @ p1) < a_at * (1 - shrink) - 1e-6)
                    & (np.abs(radial @ p2) < b_at * (1 - shrink) - 1e-6))
    return inside_ax & inside_r


def remove_interior_panels(verts, cents, norms, areas, members, owner):
    """Drop panels whose centroids lie inside ANOTHER member's volume.

    This is the functional effect of the reference's boolean-union
    intersection mesher (IntersectionMesh.py:139: pygmsh OCC union +
    clipping): interior surfaces where members overlap do not radiate
    and pollute the source-panel solve.  ``owner`` maps each panel to
    the member index that generated it.
    """
    keep = np.ones(len(areas), dtype=bool)
    for im, mem in enumerate(members):
        others = owner != im
        if not np.any(others):
            continue
        keep[others] &= ~point_in_member(cents[others], mem)
    return verts[keep], cents[keep], norms[keep], areas[keep]


def _point_in_any(pts, members, skip):
    """Inside-mask of ``pts`` against every member except index
    ``skip``."""
    inside = np.zeros(len(pts), dtype=bool)
    for jm, mem in enumerate(members):
        if jm == skip:
            continue
        inside |= point_in_member(pts, mem)
    return inside


def _subdivide_quad(q):
    """Split one quad (4,3) into 4 sub-quads via edge midpoints and the
    centroid (degenerate/triangle quads subdivide consistently)."""
    m01 = 0.5 * (q[0] + q[1])
    m12 = 0.5 * (q[1] + q[2])
    m23 = 0.5 * (q[2] + q[3])
    m30 = 0.5 * (q[3] + q[0])
    c = 0.25 * (q[0] + q[1] + q[2] + q[3])
    return np.stack([
        np.stack([q[0], m01, c, m30]),
        np.stack([m01, q[1], m12, c]),
        np.stack([c, m12, q[2], m23]),
        np.stack([m30, c, m23, q[3]]),
    ])


def clip_intersecting_panels(verts, norms, members, owner, max_depth=3):
    """Re-mesh panels that CROSS member-intersection curves — the
    functional core of the reference's boolean-union mesher
    (IntersectionMesh.py:139): a panel with vertices on both sides of
    another member's surface is recursively subdivided (midpoint
    4-split) down to ``max_depth``, and sub-panels whose centroids fall
    inside the other member are discarded.  The retained leaf panels
    track the true intersection curve to O(panel_size / 2^max_depth),
    eliminating both the double-counted interior portions and the
    surface holes that whole-panel removal leaves at junctions
    (e.g. OC4 column/pontoon joints, ``intersectMesh: 1`` designs).

    Normal orientation is inherited from the parent panel (the meshers'
    outward convention), not re-derived from winding.

    Returns (vertices (P',4,3), centroids, normals, areas).
    """
    verts = np.asarray(verts)
    P = len(verts)
    # batched prefilter: classify every parent panel in O(n_members)
    # vectorised passes (the remove_interior_panels pattern) so the
    # Python recursion below only touches the small set of panels that
    # genuinely cross another member's surface
    vin_all = np.zeros((P, 4), dtype=bool)
    cin_all = np.zeros(P, dtype=bool)
    cents0 = verts.mean(axis=1)
    for jm, mem in enumerate(members):
        rows = owner != jm
        if not np.any(rows):
            continue
        vin_all[rows] |= point_in_member(
            verts[rows].reshape(-1, 3), mem).reshape(-1, 4)
        cin_all[rows] |= point_in_member(cents0[rows], mem)
    keep_whole = ~vin_all.any(axis=1) & ~cin_all
    drop_whole = vin_all.all(axis=1) & cin_all
    crossing = ~keep_whole & ~drop_whole

    out = [verts[i] for i in np.nonzero(keep_whole)[0]]
    out_norm = list(np.nonzero(keep_whole)[0])
    for i in np.nonzero(crossing)[0]:
        im = int(owner[i])
        stack = [(sq, 1) for sq in _subdivide_quad(verts[i])] \
            if max_depth > 0 else [(verts[i], 0)]
        while stack:
            q, depth = stack.pop()
            vin = _point_in_any(q, members, im)
            cent = q.mean(axis=0)[None, :]
            cin = bool(_point_in_any(cent, members, im)[0])
            if not vin.any() and not cin:
                out.append(q)          # fully outside
                out_norm.append(i)
            elif vin.all() and cin:
                continue               # fully interior: drop
            elif depth >= max_depth:
                if not cin:
                    out.append(q)      # leaf: centroid rule
                    out_norm.append(i)
            else:
                stack.extend((sq, depth + 1) for sq in _subdivide_quad(q))
    if not out:
        z = np.zeros((0, 4, 3))
        return z, np.zeros((0, 3)), np.zeros((0, 3)), np.zeros(0)
    verts2 = np.stack(out)
    cents2, norms2, areas2 = _panel_geometry(verts2)
    # orient each leaf like its parent panel (winding-derived sign can
    # disagree with the mesher's outward normals)
    parent_n = np.asarray([norms[j] for j in out_norm])
    flip = np.sum(norms2 * parent_n, axis=1) < 0
    norms2[flip] *= -1.0
    return verts2, cents2, norms2, areas2


def mesh_fowt(fs, dz_max=None, n_az=18, da_max=None, intersect=True,
              clip_depth=3):
    """Combined wetted-surface panel mesh of a FOWT's potMod members at
    the reference pose (the calcBEM meshing stage,
    raft_fowt.py:1327-1344).  Members are meshed independently, as the
    reference's member2pnl does (no boolean union).

    ``intersect``: resolve member overlaps (the functional equivalent of
    the reference's boolean-union IntersectionMesh path,
    raft_fowt.py:1346-1402): whole panels inside other members are
    dropped AND panels crossing intersection curves are re-meshed by
    recursive subdivision-clipping (:func:`clip_intersecting_panels`)
    to ``clip_depth`` levels; ``clip_depth=0`` recovers the
    whole-panel-removal behaviour.

    Returns (vertices, centroids, normals, areas)."""
    vs, cs, ns, as_, owner = [], [], [], [], []
    for im, mem in enumerate(fs.members):
        if not mem.potMod:
            continue
        draft = -min(mem.rA0[2], mem.rB0[2])
        if draft <= 0:
            continue
        dz = dz_max or max(min(3.0, draft / 4.0), 0.5)
        da = da_max or dz
        if mem.circular:
            v, c, nr, a = mesh_cylinder_capped(
                mem.stations, mem.d[:, 0], mem.rA0, mem.q0,
                n_az=n_az, dz_max=dz)
        else:
            v, c, nr, a = mesh_rectangular(
                mem.stations, mem.d, mem.rA0, mem.q0, mem.p10, mem.p20,
                dz_max=dz, da_max=da)
        if len(a):
            vs.append(v)
            cs.append(c)
            ns.append(nr)
            as_.append(a)
            owner.append(np.full(len(a), im))
    if not vs:
        return (np.zeros((0, 4, 3)), np.zeros((0, 3)), np.zeros((0, 3)),
                np.zeros(0))
    verts = np.concatenate(vs)
    cents = np.concatenate(cs)
    norms = np.concatenate(ns)
    areas = np.concatenate(as_)
    if intersect:
        own = np.concatenate(owner)
        if clip_depth > 0:
            verts, cents, norms, areas = clip_intersecting_panels(
                verts, norms, fs.members, own, max_depth=clip_depth)
        else:
            verts, cents, norms, areas = remove_interior_panels(
                verts, cents, norms, areas, fs.members, own)
    return verts, cents, norms, areas


def _panel_geometry(verts):
    """(centroids, normals, areas) for quad panels (P,4,3) by the
    diagonal cross product; triangles are degenerate quads.  Normals
    follow the winding; callers flip if needed."""
    cents = verts.mean(axis=1)
    d1 = verts[:, 2] - verts[:, 0]
    d2 = verts[:, 3] - verts[:, 1]
    nvec = np.cross(d1, d2)
    areas = 0.5 * np.linalg.norm(nvec, axis=1)
    norms = nvec / np.maximum(2 * areas, 1e-12)[:, None]
    return cents, norms, areas


def read_pnl(path):
    """Read a HAMS .pnl mesh (node-list + panel-connectivity layout, as
    written by pyhams / the reference pipeline).

    Returns (vertices (P,4,3), centroids (P,3), normals (P,3), areas (P,)).
    Triangles are returned as degenerate quads (last vertex repeated).
    Normals follow the file's winding; callers flip if needed.
    """
    nodes = {}
    panels = []
    with open(path) as f:
        lines = f.read().splitlines()
    mode = None
    for ln in lines:
        s = ln.split()
        if not s:
            continue
        if ln.lstrip().startswith("#"):
            low = ln.lower()
            if "relation" in low or "elem" in low or "panel" in low:
                mode = "panels"
            elif "node" in low:
                mode = "nodes"
            else:
                mode = None
            continue
        if mode == "nodes" and len(s) == 4:
            try:
                nodes[int(s[0])] = [float(s[1]), float(s[2]), float(s[3])]
            except ValueError:
                pass
        elif mode == "panels" and len(s) >= 5:
            try:
                nv = int(s[1])
                idx = [int(v) for v in s[2:2 + nv]]
            except ValueError:
                continue
            if nv == 3:
                idx = idx + [idx[2]]
            panels.append(idx)
    verts = np.array([[nodes[i] for i in p] for p in panels])
    return (verts,) + _panel_geometry(verts)


def write_gdf(path, vertices, ulen=1.0, grav=9.8, isx=0, isy=0,
              clip_above_water=False, title="raft_tpu gdf mesh"):
    """Write panels in the WAMIT .gdf format
    (member2pnl.py:writeMeshToGDF:847-875 and the GDF variants at
    :314/:672): header, 'ULEN GRAV', 'ISX ISY' symmetry flags, panel
    count, then 4 vertex rows per panel.

    ``clip_above_water`` mirrors the reference's aboveWater=False
    branch: panels entirely above z = 0 are dropped and vertices above
    the waterline are moved down to z = 0."""
    vertices = np.asarray(vertices, dtype=float).reshape(-1, 4, 3)
    if clip_above_water:
        keep = np.any(vertices[:, :, 2] < -0.001, axis=1)
        vertices = vertices[keep].copy()
        vertices[:, :, 2] = np.minimum(vertices[:, :, 2], 0.0)
    with open(path, "w") as f:
        f.write(f"{title}\n")
        f.write(f"{ulen:.1f}   {grav:.1f}\n")
        f.write(f"{isx}, {isy}\n")
        f.write(f"{len(vertices)}\n")
        for quad in vertices:
            for v in quad:
                f.write(f"{v[0]:>10.3f} {v[1]:>10.3f} {v[2]:>10.3f}\n")


def read_gdf(path):
    """Read a WAMIT .gdf mesh -> (vertices (P,4,3), centroids, normals,
    areas) with the same conventions as :func:`read_pnl`."""
    with open(path) as f:
        lines = [ln for ln in f.read().splitlines() if ln.strip()]
    npan = int(lines[3].split()[0])
    coords = []
    for ln in lines[4:]:
        coords.extend(float(t) for t in ln.split())
    verts = np.asarray(coords, dtype=float).reshape(-1, 3)[:4 * npan]
    verts = verts.reshape(npan, 4, 3)
    return (verts,) + _panel_geometry(verts)


def write_pnl(path, vertices, title="raft_tpu panel mesh"):
    """Write panels in the HAMS .pnl format (member2pnl.writeMesh:280)."""
    n = len(vertices)
    with open(path, "w") as f:
        f.write(f"    --------------{title}-----------------\n")
        f.write("    Output the particulars of the panel mesh\n")
        f.write(f"    {n}    0    1    1\n\n")
        for i, quad in enumerate(vertices):
            f.write(f"    {i+1}  4 ")
            for v in quad:
                f.write(f"  {v[0]:.6e} {v[1]:.6e} {v[2]:.6e}")
            f.write("\n")
