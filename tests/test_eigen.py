"""Eigenanalysis parity vs the reference's hardcoded natural frequencies.

Targets from /root/reference/tests/test_model.py:155-175 (unloaded
cases: turbine idle, no environmental loads — the loaded cases need
exact-CCBlade aero for the equilibrium point and are deferred with it).
"""

import os

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu

pytestmark = pytest.mark.slow

UNLOADED_CASE = {
    "wind_speed": 0, "wind_heading": 0, "turbulence": 0,
    "turbine_status": "idle", "yaw_misalign": 0,
    "wave_spectrum": "JONSWAP", "wave_period": 0, "wave_height": 0,
    "wave_heading": 0, "current_speed": 0, "current_heading": 0,
}

TARGETS = {
    "OC3spar.yaml": [0.00796903, 0.00796903, 0.03245079, 0.03383781, 0.03384323, 0.15347415],
    "VolturnUS-S.yaml": [0.00782180, 0.00779927, 0.06073036, 0.03829455, 0.03823218, 0.01238992],
    "VolturnUS-S-pointInertia.yaml": [0.00782029, 0.00779718, 0.06072388, 0.03804270, 0.03797990, 0.01238741],
    "OC4semi-WAMIT_Coefs.yaml": [0.00884301, 0.00884300, 0.05733308, 0.04002449, 0.04003508, 0.01253087],
}


@pytest.mark.parametrize("design", list(TARGETS), ids=[d.split(".")[0] for d in TARGETS])
def test_solve_eigen_unloaded(design):
    path = ref_data(design)
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    model.solve_statics(UNLOADED_CASE)
    fns, modes = model.solve_eigen()
    assert_allclose(fns, TARGETS[design], rtol=1e-5, atol=1e-5)
