"""Second-order QTF reader and force-spectrum tests (OC4 .12d dataset)."""

import os

import numpy as np
import pytest

from tests.conftest import ref_data

from raft_tpu.ops.waves import jonswap
from raft_tpu.physics.secondorder import hydro_force_2nd, read_qtf_12d

QTF_PATH = ref_data("OC4semi-WAMIT_Coefs", "marin_semi.12d")


@pytest.fixture(scope="module")
def qtf():
    if not os.path.exists(QTF_PATH):
        pytest.skip("reference QTF data unavailable")
    return read_qtf_12d(QTF_PATH)


def test_qtf_hermitian(qtf):
    Q = qtf["qtf"]
    assert Q.shape[0] == Q.shape[1]
    # off-diagonal entries are hermitian-completed from the file's single
    # triangle; the diagonal can carry a (tiny) imaginary part from the
    # source data, so test hermitian symmetry off the diagonal only
    asym = Q - np.conj(np.transpose(Q, (1, 0, 2, 3)))
    off = asym - np.einsum("iihd->ihd", asym)[None] * np.eye(Q.shape[0])[:, :, None, None]
    assert np.max(np.abs(off)) < 1e-9 * np.max(np.abs(Q))
    assert len(qtf["w_2nd"]) > 2


def test_mean_drift_downwave(qtf):
    """Mean surge drift in head seas must push the platform downwave."""
    w = np.arange(0.005, 0.205, 0.005) * 2 * np.pi
    S0 = np.asarray(jonswap(w, 6.0, 12.0))
    beta = float(qtf["heads_rad"][0])
    f_mean, f = hydro_force_2nd(qtf, beta, S0, w)
    assert f_mean[0] > 0  # positive surge drift for ~0 deg heading
    assert f.shape == (6, len(w))
    assert np.all(f >= 0)
    assert f[0, :-1].max() > 0


@pytest.mark.slow
def test_oc4_model_runs_with_qtf():
    path = ref_data("OC4semi-WAMIT_Coefs.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    import raft_tpu

    m = raft_tpu.Model(path)
    assert m.qtf is not None
    case = dict(m.cases[0])
    Xi, info = m.solve_dynamics(case)
    assert np.isfinite(np.asarray(Xi)).all()
    # mean drift feedback shifts the equilibrium downwave
    X_drift = m.solve_statics(case, extra_force=np.sum(m._last_drift_mean, axis=0))
    X_plain = m.solve_statics(case)
    assert float(X_drift[0]) != float(X_plain[0])
