"""Morison strip-theory hydrodynamics (jax).

The reference computes strip hydrodynamics in triple Python loops
(members x strips x frequencies; ``/root/reference/raft/raft_member.py``
``calcHydroConstants`` :1261-1368, ``calcImat`` :1370-1448,
``calcHydroExcitation`` :1940-1992, ``calcHydroLinearization``
:1995-2126, ``calcDragExcitation`` :2128-2152, ``calcCurrentLoads``
:1793-1897, orchestrated by ``raft_fowt.py`` :1589-1625, :1732-1985).

Here all strips of all members are flattened into one ``StripSet`` of
static arrays at build time, and each physics stage is a single fused
jax expression over the ``(strip, heading, frequency)`` axes — the
shape XLA tiles well on TPU and the axes ``vmap`` extends to cases and
designs.  Submergence and strip-activity branches are where-masks.

MacCamy-Fuchs diffraction correction (raft_member.py:1451-1486): the
Hankel-function factor depends only on (k, strip radius), both static
per model, so the complex Cm(k) per strip is precomputed at build time
with scipy and enters the excitation as a constant tensor.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.ops import transforms as tf
from raft_tpu.ops import waves as wv


# ----------------------------------------------------------------- build

@dataclass
class StripSet:
    """Flattened strip arrays across all members of one FOWT."""

    node: np.ndarray      # (S,) structural node index of each strip
    mnode0: np.ndarray    # (S,) index of the strip's member's FIRST node
    ls: np.ndarray        # (S,) axial position along member
    dls: np.ndarray       # (S,)
    ds: np.ndarray        # (S,2)
    drs: np.ndarray       # (S,2)
    circ: np.ndarray      # (S,) bool
    active: np.ndarray    # (S,) bool — False for potMod members (no Morison)
    mcf: np.ndarray       # (S,) bool — MacCamy-Fuchs members
    q0: np.ndarray        # (S,3) member axes at reference pose
    p10: np.ndarray
    p20: np.ndarray
    Cd_q: np.ndarray
    Cd_p1: np.ndarray
    Cd_p2: np.ndarray
    Cd_End: np.ndarray
    Ca_q: np.ndarray
    Ca_p1: np.ndarray
    Ca_p2: np.ndarray
    Ca_End: np.ndarray
    Cm_p1_w: np.ndarray   # (S, nw) complex — (1+Ca_p1) or MCF-corrected
    Cm_p2_w: np.ndarray   # (S, nw) complex

    @property
    def S(self):
        return len(self.ls)


_MCF_TABLE = None
_MCF_KR_MAX = 80.0
_MCF_N = 1 << 15


def _mcf_table():
    """Cm(x) and its exact analytic derivative on the table grid.

    dCm/dx = (4i/pi) * (-2 x^-3 / H1' - x^-2 H1'' / H1'^2) with
    H1''(x) = H2(x)/x - H1(x) (Bessel recurrences), so the table
    supports cubic-HERMITE interpolation: ~7e-12 max relative error on
    the ramp-blended quantity over kR in [1e-4, 80] (measured; see
    test_ops), where the previous 2^20-point LINEAR table reached only
    ~2e-9 while embedding a 16 MB constant into every trace that used
    it."""
    global _MCF_TABLE
    if _MCF_TABLE is None:
        from scipy.special import hankel1

        x = np.linspace(0.0, _MCF_KR_MAX, _MCF_N)
        with np.errstate(all="ignore"):
            H1 = hankel1(1, x)
            H2 = hankel1(2, x)
            Hp1 = 0.5 * (hankel1(0, x) - H2)
            Cm = 4j / (np.pi * x**2 * Hp1)
            dCm = (4j / np.pi) * (-2.0 / (x**3 * Hp1)
                                  - (H2 / x - H1) / (x**2 * Hp1**2))
        # analytic x->0 limits (Cm -> 2, dCm -> 0); the raw expressions
        # are 0/0 at the first node
        Cm[0] = 2.0
        dCm[0] = 0.0
        _MCF_TABLE = (np.nan_to_num(Cm), np.nan_to_num(dCm))
    return _MCF_TABLE


def mcf_cm(kR):
    """MacCamy-Fuchs complex inertia coefficient Cm(kR) = 4i/(pi (kR)^2
    H1'(kR)) as a universal function of kR (raft_member.py:1467-1478).

    Evaluated through one compact cubic-Hermite table (~7e-12 relative
    error for kR <= 80; clamped beyond, where the factor is ~4e-4 from
    its asymptote) so the numpy build path and the traced geometry path
    (kR = k * R * d_scale) produce identical values.  Works on numpy or
    jax arrays of any shape.
    """
    Cm_t, dCm_t = _mcf_table()
    h = _MCF_KR_MAX / (_MCF_N - 1)
    xp = jnp if isinstance(kR, jnp.ndarray) else np
    xq = xp.clip(xp.asarray(kR, dtype=float), 0.0, _MCF_KR_MAX)
    i = xp.clip((xq / h).astype(int), 0, _MCF_N - 2)
    t = xq / h - i
    y0, y1 = xp.asarray(Cm_t)[i], xp.asarray(Cm_t)[i + 1]
    d0, d1 = xp.asarray(dCm_t)[i], xp.asarray(dCm_t)[i + 1]
    t2 = t * t
    t3 = t2 * t
    return ((2 * t3 - 3 * t2 + 1) * y0 + (t3 - 2 * t2 + t) * (h * d0)
            + (-2 * t3 + 3 * t2) * y1 + (t3 - t2) * (h * d1))


def mcf_blend(kR, Cm0_p1, Cm0_p2):
    """Blend the MCF Cm(kR) with the baseline (1+Ca) coefficients using
    the reference's long-wave ramp (raft_member.py:1479-1484); the ramp
    threshold k < pi/(5R) is kR < pi/5, so everything is a function of
    kR.  Returns (Cm_p1, Cm_p2) broadcast over kR's shape."""
    xp = jnp if isinstance(kR, jnp.ndarray) else np
    Cm = mcf_cm(kR)
    ramp = xp.where(kR < np.pi / 5, 0.5 * (1 - xp.cos(5 * kR)), 1.0)
    ramp = xp.where(kR <= 0, 0.0, ramp)
    return Cm * ramp + Cm0_p1 * (1 - ramp), Cm * ramp + Cm0_p2 * (1 - ramp)


def build_strips(fs, k_array=None):
    """Flatten all members' strips; optionally bake MCF Cm(k) factors.

    fs : FOWTStructure;  k_array : (nw,) wave numbers for MCF members.
    """
    cols = {f: [] for f in (
        "node mnode0 ls dls ds drs circ active mcf q0 p10 p20 "
        "Cd_q Cd_p1 Cd_p2 Cd_End Ca_q Ca_p1 Ca_p2 Ca_End".split()
    )}
    mcf_rows = []
    nw = len(k_array) if k_array is not None else 1
    for im, mem in enumerate(fs.members):
        ns = mem.ns
        if mem.mtype == "rigid":
            cols["node"] += [int(fs.member_node[im])] * ns
            cols["ls"] += list(mem.ls)  # axial offset from the member node
        else:
            # beam strips sit exactly at their own structural nodes
            cols["node"] += [int(fs.member_node[im]) + i for i in range(ns)]
            cols["ls"] += [0.0] * ns
        cols["mnode0"] += [int(fs.member_node[im])] * ns
        cols["dls"] += list(mem.dls)
        cols["ds"] += list(mem.ds)
        cols["drs"] += list(mem.drs)
        cols["circ"] += [mem.circular] * ns
        cols["active"] += [not mem.potMod] * ns
        cols["mcf"] += [bool(mem.MCF) and k_array is not None] * ns
        cols["q0"] += [mem.q0] * ns
        cols["p10"] += [mem.p10] * ns
        cols["p20"] += [mem.p20] * ns
        for cname in "Cd_q Cd_p1 Cd_p2 Cd_End Ca_q Ca_p1 Ca_p2 Ca_End".split():
            cols[cname] += list(getattr(mem, cname))
        # MCF complex inertia coefficient per frequency (raft_member.py:1467-1484)
        for il in range(ns):
            Cm0_p1 = 1.0 + mem.Ca_p1[il]
            Cm0_p2 = 1.0 + mem.Ca_p2[il]
            if mem.MCF and k_array is not None:
                R = mem.ds[il, 0] / 2.0
                k = np.asarray(k_array)
                Cm_p1, Cm_p2 = mcf_blend(k * R, Cm0_p1, Cm0_p2)
                mcf_rows.append((np.nan_to_num(Cm_p1), np.nan_to_num(Cm_p2)))
            else:
                mcf_rows.append(
                    (np.full(nw, Cm0_p1, dtype=np.complex128), np.full(nw, Cm0_p2, dtype=np.complex128))
                )

    out = {k2: np.asarray(v) for k2, v in cols.items()}
    out["Cm_p1_w"] = np.stack([r[0] for r in mcf_rows])
    out["Cm_p2_w"] = np.stack([r[1] for r in mcf_rows])
    return StripSet(**out)


# ------------------------------------------------------------- kinematics

def strip_frames(ss: StripSet, R_ptfm, r_nodes, node_rot=None):
    """Strip positions and member axes under the current pose.

    r_strip = r_node + q * ls (rigid members; raft_member.py:359-362).
    For general structures, each member rotates with its first node
    (member.setPosition uses nodeList[0].r[3:], raft_member.py:348-357):
    pass node_rot (N, 3) rotations and R_ptfm is ignored per strip.
    Returns (r (S,3), q, p1, p2 each (S,3)).
    """
    if node_rot is not None:
        th = node_rot[jnp.asarray(ss.mnode0)]  # (S, 3)
        R = tf.rotation_matrix(th[:, 0], th[:, 1], th[:, 2])  # (S,3,3)
        q = jnp.einsum("sij,sj->si", R, jnp.asarray(ss.q0))
        p1 = jnp.einsum("sij,sj->si", R, jnp.asarray(ss.p10))
        p2 = jnp.einsum("sij,sj->si", R, jnp.asarray(ss.p20))
    else:
        q = jnp.asarray(ss.q0) @ R_ptfm.T
        p1 = jnp.asarray(ss.p10) @ R_ptfm.T
        p2 = jnp.asarray(ss.p20) @ R_ptfm.T
    r = r_nodes[jnp.asarray(ss.node)] + q * jnp.asarray(ss.ls)[:, None]
    return r, q, p1, p2


def _areas(ss: StripSet):
    """Static per-strip volumes/areas used across the stages.

    v_side : transverse reference volume (circ: pi/4 d^2 dl)
    v_end  : tapered-end reference volume (sphere-equation based)
    a_end  : signed end area for dynamic pressure
    a_q / a_p1 / a_p2 : drag reference areas
    (raft_member.py:1324-1348, 1867-1889, 2070-2108)
    """
    ds = jnp.asarray(ss.ds)
    drs = jnp.asarray(ss.drs)
    dls = jnp.asarray(ss.dls)
    circ = jnp.asarray(ss.circ)

    v_side = jnp.where(
        circ, 0.25 * jnp.pi * ds[:, 0] ** 2 * dls, ds[:, 0] * ds[:, 1] * dls
    )
    v_end_c = jnp.pi / 12.0 * jnp.abs(
        (ds[:, 0] + drs[:, 0]) ** 3 - (ds[:, 0] - drs[:, 0]) ** 3
    )
    dmean_p = jnp.mean(ds + drs, axis=1)
    dmean_m = jnp.mean(ds - drs, axis=1)
    v_end_r = jnp.pi / 12.0 * (dmean_p**3 - dmean_m**3)
    v_end = jnp.where(circ, v_end_c, v_end_r)
    a_end = jnp.where(
        circ,
        jnp.pi * ds[:, 0] * drs[:, 0],
        (ds[:, 0] + drs[:, 0]) * (ds[:, 1] + drs[:, 1])
        - (ds[:, 0] - drs[:, 0]) * (ds[:, 1] - drs[:, 1]),
    )
    # drag areas; note the reference's ds[il,0]+ds[il,0] for rectangular
    # axial area (raft_member.py:1867,2070) is reproduced verbatim
    a_q = jnp.where(circ, jnp.pi * ds[:, 0] * dls, 2 * (ds[:, 0] + ds[:, 0]) * dls)
    a_p1 = jnp.where(circ, ds[:, 0] * dls, ds[:, 0] * dls)
    a_p2 = jnp.where(circ, ds[:, 0] * dls, ds[:, 1] * dls)
    return v_side, v_end, a_end, a_q, a_p1, a_p2


def _submerged_scale(ss, r, v_side):
    """Submergence mask and partial-emergence volume scaling
    (raft_member.py:1309, 1329-1330)."""
    dls = jnp.asarray(ss.dls)
    z = r[:, 2]
    sub = z < 0
    dls_safe = jnp.where(dls == 0, 1.0, dls)
    scale = jnp.where(
        z + 0.5 * dls > 0, (0.5 * dls - z) / dls_safe, 1.0
    )
    v = v_side * scale
    return sub, v


def _reduce_force(Tn, node_idx, F6, n_nodes):
    """Sum per-strip 6-force contributions at their nodes and reduce.

    F6: (..., S, 6) -> (..., nDOF) via segment-sum + T congruence."""
    Fn = jax.ops.segment_sum(
        jnp.moveaxis(F6, -2, 0), jnp.asarray(node_idx), num_segments=n_nodes
    )  # (N, ..., 6)
    return jnp.einsum("nia,n...i->...a", Tn, Fn)


def _reduce_matrix(Tn, node_idx, M3, r_off, n_nodes):
    """Sum per-strip 3x3 matrices translated to their nodes and reduce."""
    M6 = tf.translate_matrix_3to6(M3, r_off)  # (S,6,6)
    Mn = jax.ops.segment_sum(M6, jnp.asarray(node_idx), num_segments=n_nodes)
    return jnp.einsum("nia,nij,njb->ab", Tn, Mn, Tn)


# ------------------------------------------------------------- constants

def hydro_constants(fs, ss: StripSet, R_ptfm, r_nodes, Tn):
    """Added-mass matrix + per-strip inertial-excitation tensors.

    FOWT.calcHydroConstants (raft_fowt.py:1589-1625) + Member
    calcHydroConstants/calcImat (raft_member.py:1261-1448).

    Returns dict with A_hydro (nDOF,nDOF), Imat (S,3,3,nw) complex,
    Amat (S,3,3), a_i (S,), plus the strip frames.
    """
    rho = fs.rho_water
    r, q, p1, p2 = strip_frames(ss, R_ptfm, r_nodes)
    v_side, v_end, a_end, *_ = _areas(ss)
    sub, v_i = _submerged_scale(ss, r, v_side)
    active = sub & jnp.asarray(ss.active)

    qq = tf.vec_vec_trans(q)
    p1p1 = tf.vec_vec_trans(p1)
    p2p2 = tf.vec_vec_trans(p2)

    Amat = rho * v_i[:, None, None] * (
        jnp.asarray(ss.Ca_p1)[:, None, None] * p1p1
        + jnp.asarray(ss.Ca_p2)[:, None, None] * p2p2
    ) + rho * v_end[:, None, None] * jnp.asarray(ss.Ca_End)[:, None, None] * qq
    Amat = jnp.where(active[:, None, None], Amat, 0.0)

    # inertial excitation with (possibly frequency-dependent) Cm
    Imat = (
        rho * v_i[:, None, None, None]
        * (
            jnp.asarray(ss.Cm_p1_w)[:, None, None, :] * p1p1[..., None]
            + jnp.asarray(ss.Cm_p2_w)[:, None, None, :] * p2p2[..., None]
        )
        + (rho * v_end * jnp.asarray(ss.Ca_End))[:, None, None, None] * qq[..., None]
    )
    Imat = jnp.where(active[:, None, None, None], Imat, 0.0)

    a_i = jnp.where(active, a_end, 0.0)

    r_off = r - r_nodes[jnp.asarray(ss.node)]
    A_hydro = _reduce_matrix(Tn, ss.node, Amat, r_off, fs.n_nodes)
    return dict(
        A_hydro=A_hydro, Amat=Amat, Imat=Imat, a_i=a_i,
        r=r, q=q, p1=p1, p2=p2, sub=sub, active=active,
    )


# ------------------------------------------------------------ excitation

def wave_fields(ss, r, zeta, beta, w, k, depth, rho, g):
    """Wave kinematics at every strip for every heading.

    zeta: (nWaves, nw); beta: (nWaves,) [rad].
    Returns u, ud (nWaves, S, 3, nw), pDyn (nWaves, S, nw)."""

    def per_heading(zeta_h, beta_h):
        return wv.wave_kinematics(zeta_h[None, :], beta_h, w, k, depth, r, rho=rho, g=g)

    u, ud, p = jax.vmap(per_heading)(zeta, beta)
    return u, ud, p


def hydro_excitation(fs, ss, hc, zeta, beta, w, k, Tn, r_nodes):
    """Linear strip-theory wave excitation.

    F_strip = Imat @ ud + pDyn * a_i * q (raft_member.py:1988), masked to
    submerged, non-potMod strips, translated to nodes, T-reduced.

    Returns dict with F_hydro_iner (nWaves, nDOF, nw) and the wave
    kinematics (kept for the drag linearisation stage).
    """
    r, q = hc["r"], hc["q"]
    u, ud, pDyn = wave_fields(
        ss, r, zeta, beta, w, k, fs.depth, fs.rho_water, fs.g
    )
    # strips above water get zero kinematics already (z>0); excitation
    # additionally requires z<0 (strict; raft_member.py:1979)
    active = hc["active"]
    F3 = (
        jnp.einsum("sijw,hsjw->hsiw", hc["Imat"], ud)
        + pDyn[:, :, None, :] * (hc["a_i"][:, None] * q)[None, :, :, None]
    )
    F3 = jnp.where(active[None, :, None, None], F3, 0.0)

    r_off = r - r_nodes[jnp.asarray(ss.node)]
    mom = jnp.cross(
        jnp.broadcast_to(r_off[None, :, :, None], F3.shape),
        F3, axis=2,
    )
    F6 = jnp.concatenate([F3, mom], axis=2)  # (nWaves, S, 6, nw)
    Fn = jax.ops.segment_sum(
        jnp.moveaxis(F6, 1, 0), jnp.asarray(ss.node), num_segments=fs.n_nodes
    )  # (N, nWaves, 6, nw)
    F_red = jnp.einsum("nia,nhiw->haw", Tn, Fn)
    return dict(F_hydro_iner=F_red, u=u, ud=ud, pDyn=pDyn)


# --------------------------------------------------------- linearisation

def drag_lin_precompute(fs, ss, hc, u_ih, Tn, r_nodes, w, dtype=None):
    """Hoist everything Xi-independent out of the drag-linearisation
    fixed point.

    The fixed point re-linearises per iteration, but only the response
    ``Xi`` changes between iterations — strip areas, member-axis outer
    products, wave-velocity projections, lever arms and the node gather
    indices are all functions of geometry and sea state alone.
    Precomputing them leaves :func:`drag_lin_iter` with the minimum
    per-iteration work the math requires: the only remaining gather is
    of the (iteration-dependent) node response, never of geometry
    constants — guarded by tests/test_dynamics_hotpath.py.

    dtype : optional (real_dtype, complex_dtype) compute policy; the
    precomputed tensors are cast so the iteration runs entirely in that
    precision (see :mod:`raft_tpu.utils.dtypes`).
    """
    rho = fs.rho_water
    r, q, p1, p2 = hc["r"], hc["q"], hc["p1"], hc["p2"]
    _, _, a_end, a_q, a_p1, a_p2 = _areas(ss)
    a_end_abs = jnp.abs(a_end)
    circ = jnp.asarray(ss.circ)
    sub = hc["sub"]

    node_idx = jnp.asarray(ss.node)
    r_off = r - r_nodes[node_idx]             # (S, 3)

    c = jnp.sqrt(8.0 / jnp.pi) * 0.5 * rho
    pre = dict(
        q=q, p1=p1, p2=p2,
        qq=tf.vec_vec_trans(q),
        p1p1=tf.vec_vec_trans(p1),
        p2p2=tf.vec_vec_trans(p2),
        circ=circ, sub=sub,
        # drag-coefficient prefactors sqrt(8/pi) * 0.5 rho A Cd
        cq=c * a_q * jnp.asarray(ss.Cd_q),
        cp1=c * a_p1 * jnp.asarray(ss.Cd_p1),
        cp2=c * a_p2 * jnp.asarray(ss.Cd_p2),
        # end/axial drag uses |a_end| (raft_member.py:2104-2113)
        cEnd=c * a_end_abs * jnp.asarray(ss.Cd_End),
        Tn=jnp.asarray(Tn), node_gather=node_idx, r_off=r_off,
        H=tf.skew(r_off),
        u=u_ih, iw=1j * jnp.asarray(w),
    )
    # scatter targets: static numpy for build-time StripSets, traced for
    # bucketed designs where the strip->node map is itself a design
    # input (segment_sum takes either)
    pre["node_idx"] = (np.asarray(ss.node) if isinstance(ss.node, np.ndarray)
                       else jnp.asarray(ss.node))
    pre["n_nodes"] = fs.n_nodes

    # Bmat is LINEAR in the three per-strip RMS coefficients c_d, so
    # both node reductions fold into precomputed per-direction tensors
    # and the per-iteration reduction collapses to weighted sums:
    #
    # * drag force: F3 = sum_d c_d axis_d (axis_d . u) is SEPARABLE —
    #   the reduced 6-force direction e6_d = [axis_d, r_off x axis_d]
    #   is real and Xi-independent, so with T6_d[s, a] the T-reduction
    #   of e6_d and proj_d = axis_d . u_ih the per-strip projections,
    #   F_hydro_drag[a, w] = sum_s T6_d[s, a] (c_d[s] proj_d[s, w]).
    #   T6_d is (S, nDOF) — tiny at every nDOF, so this replaces the
    #   whole per-iteration Bmat @ u / moment / segment-sum chain
    #   unconditionally.
    # * damping matrix: G_d[s] = Tn_s^T translate(P_d, r_off) Tn_s with
    #   B_hydro_drag = sum_s c_d[s] G_d[s].  G_d is (S, nDOF, nDOF) —
    #   folded only for small reduced models (nDOF <= 12); the N-DOF
    #   flexible models keep the general segment-sum reduction, whose
    #   B-side has no frequency axis and stays cheap.
    nDOF = pre["Tn"].shape[-1]
    Tn_s = pre["Tn"][node_idx]                # (S, 6, nDOF)

    def reduce_force_dir(axis, proj):
        e6 = jnp.concatenate([axis, jnp.cross(r_off, axis)], axis=-1)
        T6 = jnp.einsum("sia,si->sa", Tn_s, e6)          # (S, nDOF)
        return jnp.where(sub[:, None], T6, 0.0), proj

    pre["T6q"], pre["uq"] = reduce_force_dir(
        q, jnp.einsum("siw,si->sw", u_ih, q))
    pre["T6p1"], pre["up1"] = reduce_force_dir(
        p1, jnp.einsum("siw,si->sw", u_ih, p1))
    pre["T6p2"], pre["up2"] = reduce_force_dir(
        p2, jnp.einsum("siw,si->sw", u_ih, p2))

    if nDOF <= 12:
        H = pre["H"]
        Ht = jnp.swapaxes(H, -1, -2)

        def reduce_dir(P):
            MH = P @ H
            M6 = jnp.concatenate([
                jnp.concatenate([P, MH], axis=-1),
                jnp.concatenate([jnp.swapaxes(MH, -1, -2), H @ P @ Ht],
                                axis=-1),
            ], axis=-2)
            G = jnp.einsum("sia,sij,sjb->sab", Tn_s, M6, Tn_s)
            return jnp.where(sub[:, None, None], G, 0.0)

        pre["Gq"] = reduce_dir(pre["qq"])
        pre["Gp1"] = reduce_dir(pre["p1p1"])
        pre["Gp2"] = reduce_dir(pre["p2p2"])
    if dtype is not None:
        rdt, cdt = dtype
        pre = {
            k2: (v.astype(cdt) if jnp.iscomplexobj(v)
                 else v.astype(rdt) if jnp.issubdtype(v.dtype, jnp.floating)
                 else v) if isinstance(v, jnp.ndarray) else v
            for k2, v in pre.items()
        }
    return pre


def drag_lin_iter(pre, Xi):
    """One drag-linearisation evaluation at response ``Xi`` (nDOF, nw).

    B' = sqrt(8/pi) * vRMS * 0.5 rho A Cd per strip/direction
    (raft_member.py:2039-2126); returns the reduced damping matrix,
    per-strip Bmat for the drag excitation, and F_hydro_drag — exactly
    :func:`hydro_linearization`'s outputs, from the hoisted state.

    Per-iteration work: the node responses (one small einsum + the one
    response gather), relative-velocity RMS statistics, and the two
    node reductions.  No geometry is rebuilt or re-gathered.
    """
    q, p1, p2 = pre["q"], pre["p1"], pre["p2"]
    sub = pre["sub"]

    # node motion at each strip: Xi at the strip's node + lever arm.
    # i w is applied at NODE level (N << S rows) before the gather, so
    # the strip-level work is one cross + one add (helpers.py:149-184
    # getKinematics semantics, i w distributed over the sum; a fully
    # folded (S, 3, nDOF) velocity operator measured SLOWER here — the
    # elementwise gather+cross chain fuses, the extra dot does not)
    Vn = pre["iw"] * jnp.einsum("nia,aw->niw", pre["Tn"], Xi)  # (N, 6, nw)
    Vs = Vn[pre["node_gather"]]                                # (S, 6, nw)
    rr = jnp.broadcast_to(pre["r_off"][:, :, None], Vs[:, 3:].shape)
    vnode = Vs[:, :3] + jnp.cross(Vs[:, 3:], rr, axis=1)

    # NOTE: projecting vrel (rather than precomputing the u_ih
    # projections and subtracting the vnode ones) measured faster —
    # the three dots fuse with the vrel construction, and it keeps the
    # reference's exact summation order
    vrel = pre["u"] - vnode
    vq_c = jnp.einsum("siw,si->sw", vrel, q)
    vp1_c = jnp.einsum("siw,si->sw", vrel, p1)
    vp2_c = jnp.einsum("siw,si->sw", vrel, p2)

    # |z|^2 as re^2 + im^2 (jnp.abs(z)**2 lowers to a hypot + square —
    # a per-element sqrt the statistics never needed), and the
    # transverse RMS through the orthogonal decomposition
    # sum|vrel_p|^2 = sum|vrel|^2 - sum|vq_c|^2 (q is a unit axis), so
    # the (S, 3, nw) vrel_p tensor is never materialised
    a2 = lambda z: jnp.real(z) ** 2 + jnp.imag(z) ** 2
    rms = lambda x: jnp.sqrt(0.5 * jnp.sum(a2(x), axis=-1))
    vq2 = jnp.sum(a2(vq_c), axis=-1)                    # (S,)
    vRMS_q = jnp.sqrt(0.5 * vq2)
    tot2 = jnp.sum(a2(vrel), axis=(1, 2))               # (S,)
    vRMS_p_tot = jnp.sqrt(0.5 * jnp.maximum(tot2 - vq2, 0.0))
    vRMS_p1 = jnp.where(pre["circ"], vRMS_p_tot, rms(vp1_c))
    vRMS_p2 = jnp.where(pre["circ"], vRMS_p_tot, rms(vp2_c))

    Bq = vRMS_q * pre["cq"]
    Bp1 = vRMS_p1 * pre["cp1"]
    Bp2 = vRMS_p2 * pre["cp2"]
    BEnd = vRMS_q * pre["cEnd"]

    Bmat = (
        (Bq + BEnd)[:, None, None] * pre["qq"]
        + Bp1[:, None, None] * pre["p1p1"]
        + Bp2[:, None, None] * pre["p2p2"]
    )
    Bmat = jnp.where(sub[:, None, None], Bmat, 0.0)

    # drag excitation through the separable fold (drag_lin_precompute):
    # three (S, nDOF) x (c_d * proj_d) contractions replace the
    # reference's Bmat @ u / moment / segment-sum chain
    # (raft_member.py:2128-2152)
    cq_ = Bq + BEnd
    F_drag = (jnp.einsum("sa,sw->aw", pre["T6q"], cq_[:, None] * pre["uq"])
              + jnp.einsum("sa,sw->aw", pre["T6p1"],
                           Bp1[:, None] * pre["up1"])
              + jnp.einsum("sa,sw->aw", pre["T6p2"],
                           Bp2[:, None] * pre["up2"]))

    if "Gq" in pre:
        # folded damping reduction: three weighted sums replace the
        # per-iteration translate + segment-sum + congruence chain
        B_red = jnp.sum(
            cq_[:, None, None] * pre["Gq"]
            + Bp1[:, None, None] * pre["Gp1"]
            + Bp2[:, None, None] * pre["Gp2"], axis=0)
        return dict(B_hydro_drag=B_red, Bmat=Bmat, F_hydro_drag=F_drag)

    # general (N-DOF) damping reduction with the precomputed lever-arm
    # alternators
    H = pre["H"]
    MH = Bmat @ H
    M6 = jnp.concatenate([
        jnp.concatenate([Bmat, MH], axis=-1),
        jnp.concatenate([jnp.swapaxes(MH, -1, -2),
                         H @ Bmat @ jnp.swapaxes(H, -1, -2)], axis=-1),
    ], axis=-2)
    Mn = jax.ops.segment_sum(M6, pre["node_idx"], num_segments=pre["n_nodes"])
    B_red = jnp.einsum("nia,nij,njb->ab", pre["Tn"], Mn, pre["Tn"])
    return dict(B_hydro_drag=B_red, Bmat=Bmat, F_hydro_drag=F_drag)


def hydro_linearization(fs, ss, hc, u_ih, Xi, w, Tn, r_nodes):
    """Stochastic drag linearisation for one sea state.

    One-shot convenience wrapper over :func:`drag_lin_precompute` +
    :func:`drag_lin_iter` (the fixed point in models/dynamics.py calls
    the two stages directly so the precompute runs once, not per
    iteration).

    u_ih : (S, 3, nw) wave velocity for the linearisation heading.
    Xi   : (nDOF, nw) response amplitudes in reduced DOFs.
    """
    pre = drag_lin_precompute(fs, ss, hc, u_ih, Tn, r_nodes, w)
    return drag_lin_iter(pre, Xi)


def drag_excitation(fs, ss, hc, Bmat, u_ih, Tn, r_nodes):
    """F = Bmat @ u per strip/frequency, reduced (raft_member.py:2128-2152)."""
    sub = hc["sub"]
    F3 = jnp.einsum("sij,sjw->siw", Bmat, u_ih)
    F3 = jnp.where(sub[:, None, None], F3, 0.0)
    r_off = hc["r"] - r_nodes[jnp.asarray(ss.node)]
    mom = jnp.cross(jnp.broadcast_to(r_off[:, :, None], F3.shape), F3, axis=1)
    F6 = jnp.concatenate([F3, mom], axis=1)  # (S, 6, nw)
    Fn = jax.ops.segment_sum(F6, jnp.asarray(ss.node), num_segments=fs.n_nodes)
    return jnp.einsum("nia,niw->aw", Tn, Fn)


# -------------------------------------------------------------- current

def current_loads(fs, ss, hc, speed, heading_deg, Zref, Tn, r_nodes):
    """Mean current drag loads (raft_member.py:1793-1897)."""
    rho = fs.rho_water
    r, q, p1, p2 = hc["r"], hc["q"], hc["p1"], hc["p2"]
    _, _, a_end, a_q, a_p1, a_p2 = _areas(ss)
    a_end_abs = jnp.abs(a_end)
    circ = jnp.asarray(ss.circ)
    sub = hc["sub"]

    z = r[:, 2]
    v = speed * ((fs.depth - jnp.abs(z)) / (fs.depth + Zref)) ** fs.shearExp_water
    hd = jnp.deg2rad(heading_deg)
    vcur = jnp.stack([v * jnp.cos(hd), v * jnp.sin(hd), jnp.zeros_like(v)], axis=-1)

    vq_c = jnp.einsum("si,si->s", vcur, q)
    vp1_c = jnp.einsum("si,si->s", vcur, p1)
    vp2_c = jnp.einsum("si,si->s", vcur, p2)
    vrel_q = vq_c[:, None] * q
    vrel_p = vcur - vrel_q
    vrel_p1 = vp1_c[:, None] * p1
    vrel_p2 = vp2_c[:, None] * p2

    nq = jnp.abs(vq_c)
    np_tot = jnp.linalg.norm(vrel_p, axis=1)
    np1 = jnp.where(circ, np_tot, jnp.linalg.norm(vrel_p1, axis=1))
    np2 = jnp.where(circ, np_tot, jnp.linalg.norm(vrel_p2, axis=1))

    D = (
        0.5 * rho * (a_q * jnp.asarray(ss.Cd_q) * nq)[:, None] * vrel_q
        + 0.5 * rho * (a_p1 * jnp.asarray(ss.Cd_p1) * np1)[:, None] * vrel_p1
        + 0.5 * rho * (a_p2 * jnp.asarray(ss.Cd_p2) * np2)[:, None] * vrel_p2
        + 0.5 * rho * (a_end_abs * jnp.asarray(ss.Cd_End) * nq)[:, None] * vrel_q
    )
    D = jnp.where(sub[:, None], D, 0.0)

    r_off = r - r_nodes[jnp.asarray(ss.node)]
    mom = jnp.cross(r_off, D)
    F6 = jnp.concatenate([D, mom], axis=1)
    Fn = jax.ops.segment_sum(F6, jnp.asarray(ss.node), num_segments=fs.n_nodes)
    return jnp.einsum("nia,ni->a", Tn, Fn)
