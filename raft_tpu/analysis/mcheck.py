"""Deterministic interleaving explorer for the fleet's fs protocols.

The static half of the protocol engine (``analysis/protocol.py``)
pins WHERE the shared-filesystem mutations are; this module checks
WHAT their composition guarantees.  It runs the *real* protocol
functions — :mod:`raft_tpu.parallel.fabric` lease primitives, the
:class:`~raft_tpu.serve.fleet.FleetLedger`, the
:mod:`~raft_tpu.aot.release` pointer, the rollout gate and the
router :class:`~raft_tpu.serve.router.Breaker` — against an
in-memory virtual filesystem implementing exactly the atomicity the
engines assume (atomic create-exclusive, atomic rename, atomic
replace; everything else interruptible), and enumerates EVERY
interleaving of 2–3 cooperative actors, plus crash injection at each
tmp-write → replace/rename boundary.

Global invariants asserted at every explored state:

* ``single-holder`` — at most one live lease holder per shard/rid: a
  non-seizer action never changes the token of an existing lease.
* ``current-verified`` — the ``current`` release pointer always
  resolves to a manifest that passes ``verify_manifest``, including
  with a promoter crashed mid-flip.
* ``rollout-recoverable`` — a crashed rollout always leaves the
  parent release promotable and the replica lease re-seizable.
* ``grave-not-resurrected`` — a stolen lease's grave name is never
  renamed back into (or created as) live state.
* ``gate-candidate-probed`` — the rollout gate only turns green after
  ``ROLLOUT_CANARY_PROBES`` canary observations of the replaced
  replica at its post-seize endpoint.
* ``breaker-liveness`` — the breaker never refuses traffic with the
  half-open trial slot held and zero trials outstanding.
* ``no-tmp-live`` — a leftover ``*.tmp*`` file is never treated as
  live membership or release state.

State-space control is canonicalization + memoized state hashing: a
state is the canonical virtual-fs image plus each actor's observation
history; alternatives of an already-expanded state are pruned.  The
protocols are small (bounded actors, bounded fs keys), so exploration
completes in seconds.  Everything here is jax-free: protocol modules
are imported as leaf modules without executing their package
``__init__`` (which would drag jax in).
"""
from __future__ import annotations

import importlib
import importlib.util
import json
import os
import sys
import threading
import time
import types
import uuid

from raft_tpu.utils import fsops

EPOCH = 1_700_000_000.0

#: invariant identifiers — pinned in protocol_baseline.json so adding
#: or dropping a checked invariant diffs against the baseline like a
#: mutation-site change does
INVARIANTS = (
    "breaker-liveness",
    "current-verified",
    "gate-candidate-probed",
    "grave-not-resurrected",
    "no-tmp-live",
    "rollout-recoverable",
    "single-holder",
)

_STEP_TIMEOUT_S = 20.0


class Violation(Exception):
    """A protocol invariant failed in some interleaving."""

    def __init__(self, invariant, detail, trace=()):
        super().__init__(f"[{invariant}] {detail}")
        self.invariant = invariant
        self.detail = detail
        self.trace = tuple(trace)


class EngineError(Exception):
    """The explorer itself broke (nondeterminism, deadlock, blowup) —
    distinct from a Violation: CI treats it as exit 2, not 1."""


class _Crash(BaseException):
    """Injected actor death.  BaseException so protocol ``except
    (OSError, ValueError)`` cleanup paths cannot swallow it — a dead
    process runs no cleanup."""


#: packages whose ``__init__`` imports the jax-heavy engines; leaf
#: modules under them (fabric, fleet, release, ...) are themselves
#: jax-free, and cross-imports between them (fleet → parallel.resilience)
#: mean EVERY heavy package must be stubbed, not just the target's chain
_HEAVY_PKGS = ("raft_tpu.parallel", "raft_tpu.serve", "raft_tpu.aot")


def _import_light(name):
    """Import a raft_tpu leaf module WITHOUT executing the jax-heavy
    package ``__init__``s (``raft_tpu.parallel``/``raft_tpu.serve``
    pull in the sweep/serve engines).  Registers stub package modules
    with only a ``__path__`` so the normal import machinery finds the
    leaf files; genuinely imported packages are left alone."""
    if name in sys.modules:
        return sys.modules[name]
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    parts = name.split(".")
    chains = [".".join(parts[:i]) for i in range(1, len(parts))]
    for pkg in list(_HEAVY_PKGS) + chains:
        if pkg not in sys.modules:
            mod = types.ModuleType(pkg)
            mod.__path__ = [os.path.join(root, *pkg.split("."))]
            sys.modules[pkg] = mod
    return importlib.import_module(name)


# ------------------------------------------------------------ virtual fs


class VirtualClock:
    """Deterministic stand-in for ``time.time``/``time.monotonic``:
    every read advances by 1µs (strictly monotonic, reproducible per
    choice sequence); scenarios advance whole seconds explicitly."""

    def __init__(self):
        self.wall = EPOCH
        self.mono = 1000.0

    def time(self):
        self.wall += 1e-6
        return self.wall

    def monotonic(self):
        self.mono += 1e-6
        return self.mono

    def advance(self, seconds):
        self.wall += float(seconds)
        self.mono += float(seconds)


class VirtualFS:
    """In-memory filesystem with the protocol's assumed atomicity.

    Every op is one atomic step; the scheduler interleaves actors
    BETWEEN ops (``checkpoint``), never inside one.  Mutations are
    logged (``oplog``) so invariants can attribute ownership changes
    to a sanctioned cause."""

    def __init__(self, sched):
        self.sched = sched
        self.files = {}   # path -> text
        self.mtimes = {}  # path -> virtual wall time
        self.dirs = set()
        #: mutation log: (actor, op, path, src, text_after) — invariants
        #: attribute every ownership change to its sanctioned primitive
        self.oplog = []
        self._seq = 0
        self._version = 0        # bumps on every mutation
        self._canon_memo = None  # (version, canon image)

    # -- bookkeeping

    def _log(self, op, path, src=""):
        self._version += 1
        self.oplog.append((self.sched.current_name(), op, path, src,
                           self.files.get(path)))

    def _mkparents(self, path):
        d = os.path.dirname(path)
        while d and d not in self.dirs:
            self.dirs.add(d)
            d = os.path.dirname(d)

    # -- mutations (checkpointed; rename/replace/unlink crashable)

    def create_exclusive(self, path, text):
        self.sched.checkpoint("create_excl", path)
        if path in self.files:
            raise FileExistsError(path)
        self.files[path] = text
        self.mtimes[path] = self.sched.clock.wall
        self._mkparents(path)
        self._log("create_excl", path)

    def write_text(self, path, text):
        self.sched.checkpoint("write", path)
        self.files[path] = text
        self.mtimes[path] = self.sched.clock.wall
        self._mkparents(path)
        self._log("write", path)

    def replace(self, src, dst):
        self.sched.checkpoint("replace", dst, crashable=True)
        if src not in self.files:
            raise FileNotFoundError(src)
        self.files[dst] = self.files.pop(src)
        self.mtimes[dst] = self.mtimes.pop(src)
        self._log("replace", dst, src=src)

    def rename(self, src, dst):
        self.sched.checkpoint("rename", src, crashable=True)
        if src not in self.files:
            raise FileNotFoundError(src)
        self.files[dst] = self.files.pop(src)
        self.mtimes[dst] = self.mtimes.pop(src)
        self._log("rename", dst, src=src)

    def unlink(self, path):
        self.sched.checkpoint("unlink", path, crashable=True)
        if path not in self.files:
            raise FileNotFoundError(path)
        del self.files[path]
        del self.mtimes[path]
        self._log("unlink", path)

    def utime(self, path):
        self.sched.checkpoint("utime", path)
        if path not in self.files:
            raise FileNotFoundError(path)
        self.mtimes[path] = self.sched.clock.wall
        self._log("utime", path)

    def makedirs(self, path, exist_ok=True):
        # not a coordination-relevant op: apply without a checkpoint
        if not exist_ok and path in self.dirs:
            raise FileExistsError(path)
        self.dirs.add(path)
        self._mkparents(os.path.join(path, "x"))

    # -- reads (checkpointed so read/write interleavings are explored;
    #    the observed VALUE joins the actor's history — two states only
    #    memo-merge when every actor has seen the same data, otherwise
    #    pruning could hide an interleaving the continuation depends on)

    def read_text(self, path):
        self.sched.checkpoint("read", path)
        if path not in self.files:
            self.sched.note(("read", path, None))
            raise FileNotFoundError(path)
        text = self.files[path]
        self.sched.note(("read", path, text))
        return text

    def exists(self, path):
        self.sched.checkpoint("exists", path)
        found = path in self.files or path in self.dirs
        self.sched.note(("exists", path, found))
        return found

    def listdir(self, path):
        self.sched.checkpoint("listdir", path)
        names = {os.path.basename(p) for p in self.files
                 if os.path.dirname(p) == path}
        self.sched.note(("listdir", path, tuple(sorted(names))))
        if not names and path not in self.dirs:
            raise FileNotFoundError(path)
        return sorted(names)

    def getmtime(self, path):
        self.sched.checkpoint("stat", path)
        if path not in self.files:
            self.sched.note(("stat", path, None))
            raise FileNotFoundError(path)
        mtime = self.mtimes[path]
        self.sched.note(("stat", path, mtime))
        return mtime

    # -- deterministic unique names (no checkpoint)

    def tmp_name(self, path):
        self._seq += 1
        return f"{path}.tmp.{self.sched.current_name()}.{self._seq}"

    def grave_name(self, path, tag):
        self._seq += 1
        return f"{path}.{tag}.{self.sched.current_name()}.{self._seq}"

    # -- canonical image for state hashing

    def canon(self, aliases):
        if self._canon_memo is not None \
                and self._canon_memo[0] == self._version:
            return self._canon_memo[1]
        out = tuple((path, _canon_text(self.files[path], aliases))
                    for path in sorted(self.files))
        self._canon_memo = (self._version, out)
        return out


_TIME_KEYS = {"claimed_t", "renewed_t", "t", "created", "t_unix"}


def _canon_value(key, val, aliases):
    if key == "token" and isinstance(val, str):
        return aliases.get(val, "?token")
    if key in _TIME_KEYS and isinstance(val, (int, float)):
        return int(val)
    if key in ("pid",):
        return 0
    if key in ("host",):
        return "h"
    if isinstance(val, dict):
        return {k: _canon_value(k, v, aliases) for k, v in sorted(
            val.items())}
    return val


#: (text, alias fingerprint) -> canonical form.  Tokens and graves are
#: deterministic per run (virtual uuid/clock), so the same texts recur
#: across thousands of replays — without this cache canonicalization
#: dominates exploration time.
_CANON_CACHE = {}


def _canon_text(text, aliases):
    key = (text, tuple(sorted(aliases.items())))
    hit = _CANON_CACHE.get(key)
    if hit is not None:
        return hit
    try:
        obj = json.loads(text)
    except ValueError:
        return text
    if not isinstance(obj, dict):
        return text
    out = json.dumps(
        {k: _canon_value(k, v, aliases) for k, v in sorted(obj.items())},
        sort_keys=True, default=str)
    if len(_CANON_CACHE) < 100_000:
        _CANON_CACHE[key] = out
    return out


# ------------------------------------------------------------- scheduler


class _FakeUUID:
    __slots__ = ("hex",)

    def __init__(self, hex_):
        self.hex = hex_

    def __str__(self):
        return self.hex


class _Actor:
    def __init__(self, name, fn):
        self.name = name
        self.fn = fn
        self.go = threading.Semaphore(0)
        self.thread = None
        self.started = False
        self.finished = False
        self.crashed = False
        self.error = None
        self.pending = None        # (kind, path, crashable) at a checkpoint
        self.history = []          # every checkpoint passed (its position)
        self.canon_hist = []       # canonical prefix of history
        self.choose_options = None
        self.choose_value = None


class Scheduler:
    """One deterministic execution of a scenario under a prescribed
    choice prefix; choices past the prefix default to the first
    enabled one, and every decision point is recorded for the
    explorer to branch on."""

    def __init__(self, scenario, prefix, max_crashes=1):
        self.scenario = scenario
        self.prefix = tuple(prefix)
        self.max_crashes = max_crashes
        self.clock = VirtualClock()
        self.fs = VirtualFS(self)
        self.back = threading.Semaphore(0)
        self.actors = {}
        self.current = None
        self.crashes = 0
        self.decisions = []
        self.applied = []
        self.ctx = None

    # -- actor-side API (called from actor threads via the vfs / ctx)

    def current_name(self):
        return self.current.name if self.current is not None else "_env"

    def checkpoint(self, kind, path, crashable=False):
        actor = self.current
        if actor is None or actor.thread is not threading.current_thread():
            return  # setup/finalize/invariant context: apply immediately
        if actor.crashed:
            raise _Crash()
        actor.pending = (kind, path, bool(crashable))
        self.back.release()
        actor.go.acquire()
        if actor.crashed:
            raise _Crash()
        actor.history.append((kind, path))
        actor.pending = None

    def note(self, observed):
        """Record a read's RESULT in the acting actor's history (no
        scheduling point — the value was determined by the checkpoint
        that admitted the read)."""
        actor = self.current
        if actor is not None and actor.thread is threading.current_thread():
            actor.history.append(("res",) + tuple(observed))

    def pause(self, label="pause"):
        self.checkpoint("pause", label)

    def choose(self, options):
        actor = self.current
        if actor is None or actor.thread is not threading.current_thread():
            return options[0]
        if actor.crashed:
            raise _Crash()
        actor.choose_options = tuple(options)
        self.back.release()
        actor.go.acquire()
        if actor.crashed:
            raise _Crash()
        value = actor.choose_value
        actor.choose_options = None
        actor.choose_value = None
        actor.history.append(("choose", value))
        return value

    # -- scheduler side

    def _actor_main(self, actor):
        actor.go.acquire()
        try:
            actor.fn(self.ctx)
        except _Crash:
            pass
        except Exception as e:                       # scenario bug
            actor.error = e
        finally:
            actor.finished = True
            self.back.release()

    def _step(self, actor):
        self.current = actor
        if not actor.started:
            actor.started = True
            actor.thread = threading.Thread(
                target=self._actor_main, args=(actor,), daemon=True)
            actor.thread.start()
        actor.go.release()
        if not self.back.acquire(timeout=_STEP_TIMEOUT_S):
            raise EngineError(
                f"actor {actor.name} never yielded (deadlock in "
                f"scenario {self.scenario.name})")
        self.current = None
        if actor.error is not None:
            raise EngineError(
                f"actor {actor.name} raised {actor.error!r} in scenario "
                f"{self.scenario.name}")

    def _crash(self, actor):
        actor.crashed = True
        self.crashes += 1
        self.applied.append(f"{actor.name}: CRASH before "
                            f"{(actor.pending or ('?',))[0]}")
        self._step(actor)  # unwind: checkpoints now raise _Crash

    def _enabled(self):
        out = []
        for name in sorted(self.actors):
            a = self.actors[name]
            if a.finished:
                continue
            if a.choose_options is not None:
                out.extend(("pick", name, i)
                           for i in range(len(a.choose_options)))
                continue
            out.append(("step", name))
            if (a.pending is not None and a.pending[2]
                    and self.crashes < self.max_crashes
                    and name in self.scenario.crashable):
                out.append(("crash", name))
        return tuple(out)

    def _canon_history(self, actor):
        # incremental: aliases are registered before their token can
        # reach the fs or a read result, so canonical prefixes never
        # go stale and only new entries need work
        aliases = self.ctx.aliases
        done = actor.canon_hist
        for entry in actor.history[len(done):]:
            if entry[0] == "res":
                _, kind, path, val = entry
                if isinstance(val, str):
                    val = _canon_text(val, aliases)
                elif isinstance(val, float):
                    val = int(val)
                done.append(("res", kind, path, val))
            else:
                done.append(entry)
        return tuple(done)

    def _state_key(self):
        actors = tuple(
            (a.name, a.started, a.finished, a.crashed,
             self._canon_history(a), a.pending, a.choose_options)
            for _, a in sorted(self.actors.items()))
        return (self.fs.canon(self.ctx.aliases), actors,
                self.scenario.digest(self.ctx))

    def _apply(self, choice):
        kind = choice[0]
        actor = self.actors[choice[1]]
        if kind == "crash":
            self._crash(actor)
            return
        if kind == "pick":
            actor.choose_value = actor.choose_options[choice[2]]
            self.applied.append(
                f"{actor.name}: choose {actor.choose_value}")
            self._step(actor)
            return
        if actor.pending is not None:
            self.applied.append(
                f"{actor.name}: {actor.pending[0]} {actor.pending[1]}")
        else:
            self.applied.append(f"{actor.name}: start")
        self._step(actor)

    def run(self):
        """Execute to completion; returns the decision list.  Raises
        Violation (with the interleaving trace attached) or
        EngineError."""
        self.ctx = Ctx(self)
        fsops.install(self.fs)
        saved = (time.time, time.monotonic, uuid.uuid4)
        time.time = self.clock.time
        time.monotonic = self.clock.monotonic
        uid = [0]

        def _uuid4():
            # deterministic tokens: replays reproduce them exactly and
            # identical lease texts recur across runs (canon cache)
            uid[0] += 1
            return _FakeUUID(f"{uid[0]:032x}")

        uuid.uuid4 = _uuid4
        try:
            self.scenario.setup(self.ctx)
            for name, fn in self.scenario.actors(self.ctx).items():
                self.actors[name] = _Actor(name, fn)
            self._check()
            step = 0
            while any(not a.finished for a in self.actors.values()):
                step += 1
                if step > 10_000:
                    raise EngineError(
                        f"scenario {self.scenario.name}: run did not "
                        "terminate")
                enabled = self._enabled()
                if not enabled:
                    break
                idx = len(self.decisions)
                if idx < len(self.prefix):
                    choice = self.prefix[idx]
                    if choice not in enabled:
                        raise EngineError(
                            f"nondeterministic replay in scenario "
                            f"{self.scenario.name}: prescribed {choice} "
                            f"not in {enabled}")
                else:
                    choice = enabled[0]
                self.decisions.append(
                    (self._state_key(), enabled, choice))
                self._apply(choice)
                self._check()
            self.scenario.finalize(self.ctx)
            return self.decisions
        except Violation as v:
            raise Violation(v.invariant, v.detail,
                            trace=tuple(self.applied)) from None
        finally:
            time.time, time.monotonic, uuid.uuid4 = saved
            fsops.uninstall()
            for a in self.actors.values():
                if a.started and not a.finished:
                    # violation unwound the run mid-flight: let the
                    # paused daemon threads die with the process
                    a.crashed = True
                    a.go.release()

    def _check(self):
        self.scenario.invariant(self.ctx)


class Ctx:
    """What scenario scripts and invariants see: the scheduler's
    cooperative API plus shared scenario state."""

    def __init__(self, sched):
        self.sched = sched
        self.fs = sched.fs
        self.clock = sched.clock
        self.shared = {}
        self.aliases = {}   # raw token -> stable actor alias

    def pause(self, label="pause"):
        self.sched.pause(label)

    def choose(self, options):
        return self.sched.choose(options)

    def alias(self, token, name):
        self.aliases[str(token)] = name


# -------------------------------------------------------------- explorer


def explore(scenario, max_crashes=1, max_runs=50_000):
    """Exhaustively explore every interleaving (modulo memoized-state
    pruning).  Returns ``(violation_or_None, stats)``."""
    expanded = set()
    stack = [()]
    runs = 0
    while stack:
        prefix = stack.pop()
        runs += 1
        if runs > max_runs:
            raise EngineError(
                f"scenario {scenario.name}: exceeded {max_runs} runs — "
                "state space blew up")
        try:
            decisions = Scheduler(scenario, prefix,
                                  max_crashes=max_crashes).run()
        except Violation as v:
            return v, {"runs": runs, "states": len(expanded)}
        for i, (key, enabled, chosen) in enumerate(decisions):
            if len(enabled) < 2 or key in expanded:
                continue
            expanded.add(key)
            base = tuple(d[2] for d in decisions[:i])
            for alt in enabled:
                if alt != chosen:
                    stack.append(base + (alt,))
    return None, {"runs": runs, "states": len(expanded)}


# ------------------------------------------------- invariant helpers


def _text_token(text):
    try:
        rec = json.loads(text) if text is not None else None
    except ValueError:
        return None
    return rec.get("token") if isinstance(rec, dict) else None


def check_lease_ownership(ctx, seizers=()):
    """``single-holder`` + ``grave-not-resurrected`` over the vfs
    mutation log.

    A lease file (shard/replica membership) may only ever be touched
    by the sanctioned ownership primitives:

    * ``create_excl`` — claim: the filesystem itself guarantees one
      winner, so it is always legal;
    * ``replace`` — rewrite: legal only when the installed token has
      held THIS lease before (a renewer refreshing its own record —
      including the accepted wedged-renewer lost-update) or the actor
      is a scenario-designated seizer (rolling-upgrade takeover);
    * ``rename``/``unlink`` away — steal/evict/release.

    A plain ``write`` to a lease path (torn-write channel), or a
    ``replace`` installing a never-before-seen token by a non-seizer,
    is a hijack: a live holder displaced without steal/evict/seize —
    exactly the pre-PR-13 claim-collision live-twin bug."""
    fs = ctx.fs
    book = ctx.shared.setdefault(
        "_ownership", {"idx": 0, "ever": {}})
    new_ops = fs.oplog[book["idx"]:]
    book["idx"] = len(fs.oplog)
    for actor, op, path, src, text in new_ops:
        if op in ("rename", "replace") and ".stolen." in src \
                and ".stolen." not in path:
            raise Violation(
                "grave-not-resurrected",
                f"{actor} renamed grave {src} back to live path {path}")
        base = os.path.basename(path)
        is_lease = base.endswith(".json") and (
            "/leases/" in path or "/replicas/" in path)
        if not is_lease:
            continue
        ever = book["ever"].setdefault(path, set())
        tok = _text_token(text)
        if op == "write":
            raise Violation(
                "single-holder",
                f"{actor} mutated lease {path} with a plain "
                "(interruptible) write — claims must be "
                "create-exclusive and rewrites tmp+replace")
        if op == "replace" and tok is not None and tok not in ever \
                and actor not in seizers:
            raise Violation(
                "single-holder",
                f"{actor} installed a brand-new token into live lease "
                f"{path} via replace without being a designated seizer "
                "(claim-collision hijack: the holder was displaced "
                "without steal/evict/seize)")
        if tok is not None:
            ever.add(tok)


def check_no_tmp_live(ctx, ledger=None):
    """Membership/release views must never surface tmp leftovers."""
    if ledger is not None:
        for rid in ledger.replicas():
            if ".tmp" in rid or ".stolen." in rid:
                raise Violation(
                    "no-tmp-live",
                    f"membership lists non-live file as replica {rid!r}")


# ------------------------------------------------------------- scenarios


class Scenario:
    """Base: subclasses define name/crashable/seizers, setup, actors,
    invariant, digest, finalize."""

    name = "?"
    crashable = frozenset()
    seizers = frozenset()
    max_crashes = 1

    def setup(self, ctx):
        pass

    def actors(self, ctx):
        return {}

    def invariant(self, ctx):
        pass

    def digest(self, ctx):
        return None

    def finalize(self, ctx):
        pass


class LeaseLedgerScenario(Scenario):
    """2 workers race claim/renew/steal/release on one fabric shard —
    the thief only steals when :meth:`Ledger.stealable` says the lease
    expired (TTL, advanced by a clock actor), with crash injection
    inside the renew rewrite, the release unlink and the steal's
    rename→unlink window."""

    name = "lease-ledger"
    crashable = frozenset({"A", "B"})

    OUT = "/proto/sweep"

    def setup(self, ctx):
        fabric = _import_light("raft_tpu.parallel.fabric")
        ctx.shared["fabric"] = fabric
        ctx.shared["LA"] = fabric.Ledger(self.OUT, 1, worker_id="wA")
        ctx.shared["LB"] = fabric.Ledger(self.OUT, 1, worker_id="wB")
        ctx.alias(ctx.shared["LA"].token, "A")
        ctx.alias(ctx.shared["LB"].token, "B")

    def actors(self, ctx):
        def actor_a(c):
            led = c.shared["LA"]
            if led.claim(0):
                led.renew(0)
                led.release(0)

        def actor_b(c):
            led = c.shared["LB"]
            if led.claim(0):
                return
            for attempt in (2, 3):
                c.pause("b-retry")
                reason, age, holder, _ = led.stealable(0)
                if reason:
                    if led.steal(0, reason, age, holder):
                        led.claim(0, attempt=attempt)
                    return
                if led.claim(0, attempt=attempt):
                    return

        def ticker(c):
            # one TTL expiry: everything after this sees A's unrenewed
            # lease as stealable (FABRIC_TTL_S defaults to 30s)
            c.pause("tick")
            c.clock.advance(31.0)

        return {"A": actor_a, "B": actor_b, "T": ticker}

    def invariant(self, ctx):
        check_lease_ownership(ctx)

    def finalize(self, ctx):
        # whatever happened (incl. crashes mid-rewrite / mid-steal), a
        # late worker must still be able to take the shard over
        fabric = ctx.shared["fabric"]
        ctx.clock.advance(31.0)
        led = fabric.Ledger(self.OUT, 1, worker_id="wC")
        ctx.alias(led.token, "C")
        if not led.claim(0):
            reason, age, holder, _ = led.stealable(0)
            if not reason:
                raise Violation(
                    "rollout-recoverable",
                    "shard lease held but not stealable a full TTL "
                    "after every worker stopped (wedged ledger)")
            if not led.steal(0, reason, age, holder):
                raise Violation(
                    "rollout-recoverable",
                    "expired shard lease could not be stolen")
            if not led.claim(0, attempt=9):
                raise Violation(
                    "rollout-recoverable",
                    "shard lease unclaimable after a winning steal")
        check_lease_ownership(ctx)


class ReleasePointerScenario(Scenario):
    """Concurrent promote(R2) vs rollback with crash injection at the
    pointer flip: ``current`` must resolve to a verified manifest at
    every state, including with the promoter dead mid-flip."""

    name = "release-pointer"
    crashable = frozenset({"P", "Q"})

    AOT = "/proto/aot"

    def setup(self, ctx):
        release = _import_light("raft_tpu.aot.release")
        release._PARITY_CACHE[:] = []  # run-scoped: don't leak clocks
        ctx.shared["release"] = release
        man1 = release.build_manifest({}, "code", "flags")
        man2 = release.build_manifest({}, "code", "flags",
                                      parent=man1["release"])
        for man in (man1, man2):
            fsops.makedirs(release.releases_dir(self.AOT))
            fsops.write_atomic(
                release.manifest_path(man["release"], self.AOT),
                json.dumps(man, sort_keys=True))
        ctx.shared["r1"] = man1["release"]
        ctx.shared["r2"] = man2["release"]
        release.promote(man1["release"], self.AOT)

    def actors(self, ctx):
        release = ctx.shared["release"]

        def promoter(c):
            release.promote(c.shared["r2"], self.AOT)

        def rollbacker(c):
            try:
                release.rollback(self.AOT)
            except (ValueError, FileNotFoundError):
                pass  # current had no parent yet: nothing to roll back

        return {"P": promoter, "Q": rollbacker}

    def invariant(self, ctx):
        release = ctx.shared["release"]
        rid, man = release.resolve(self.AOT)
        if rid is None:
            raise Violation("current-verified",
                            "current pointer lost (resolves to nothing)")
        if ".tmp" in rid:
            raise Violation("no-tmp-live",
                            f"current resolves through a tmp file: {rid}")
        if man is None:
            raise Violation("current-verified",
                            f"current names {rid} but its manifest is "
                            "missing/unreadable")
        problems = release.verify_manifest(man)
        if problems:
            raise Violation("current-verified",
                            f"current manifest {rid} fails verification: "
                            + "; ".join(problems))
        if rid not in (ctx.shared["r1"], ctx.shared["r2"]):
            raise Violation("current-verified",
                            f"current names a foreign release {rid}")

    def finalize(self, ctx):
        self.invariant(ctx)


class RolloutScenario(Scenario):
    """A rollout driver promotes, marks, and seizes against a renewing
    old replica; the driver is crashable at every flip.  After any
    crash the parent must be able to re-seize and re-promote."""

    name = "rollout-takeover"
    crashable = frozenset({"R"})
    seizers = frozenset({"R", "_env"})   # finalize recovery seizes too

    AOT = "/proto/aot"
    ROOT = "/proto/deploy"

    def setup(self, ctx):
        release = _import_light("raft_tpu.aot.release")
        fleet = _import_light("raft_tpu.serve.fleet")
        release._PARITY_CACHE[:] = []
        ctx.shared["release"] = release
        ctx.shared["fleet"] = fleet
        man1 = release.build_manifest({}, "code", "flags")
        man2 = release.build_manifest({}, "code", "flags",
                                      parent=man1["release"])
        for man in (man1, man2):
            fsops.makedirs(release.releases_dir(self.AOT))
            fsops.write_atomic(
                release.manifest_path(man["release"], self.AOT),
                json.dumps(man, sort_keys=True))
        ctx.shared["r1"] = man1["release"]
        ctx.shared["r2"] = man2["release"]
        release.promote(man1["release"], self.AOT)
        old = fleet.FleetLedger(self.ROOT, replica_id="r0")
        old.claim(7001)
        ctx.shared["old"] = old
        ctx.alias(old.token, "OLD")

    def actors(self, ctx):
        release = ctx.shared["release"]
        fleet = ctx.shared["fleet"]

        def rollout(c):
            cand = fleet.FleetLedger(self.ROOT, replica_id="r0")
            c.alias(cand.token, "CAND")
            c.shared["cand"] = cand
            release.promote(c.shared["r2"], self.AOT)
            release.write_rollout_marker(c.shared["r1"],
                                         c.shared["r2"], self.AOT)
            cand.seize(7002)
            release.clear_rollout_marker(self.AOT)

        def old_renewer(c):
            c.shared["old"].renew()

        return {"R": rollout, "O": old_renewer}

    def invariant(self, ctx):
        release = ctx.shared["release"]
        fleet = ctx.shared["fleet"]
        check_lease_ownership(ctx, seizers=self.seizers)
        rid, man = release.resolve(self.AOT)
        if rid is None or man is None or release.verify_manifest(man):
            raise Violation(
                "current-verified",
                f"current does not resolve to a verified manifest "
                f"mid-rollout (got {rid!r})")
        check_no_tmp_live(ctx, fleet.FleetLedger(self.ROOT))

    def finalize(self, ctx):
        # parent recovery after any outcome (incl. a crashed driver):
        # re-promote the parent and re-seize the replica
        release = ctx.shared["release"]
        fleet = ctx.shared["fleet"]
        try:
            release.promote(ctx.shared["r1"], self.AOT)
        except (OSError, ValueError) as e:
            raise Violation(
                "rollout-recoverable",
                f"parent release no longer promotable after rollout: {e}")
        parent = fleet.FleetLedger(self.ROOT, replica_id="r0")
        ctx.alias(parent.token, "PARENT")
        if not parent.seize(7001):
            raise Violation("rollout-recoverable",
                            "parent could not re-seize the replica lease")
        rec, _ = parent.read("r0")
        if not rec or rec.get("token") != parent.token:
            raise Violation("rollout-recoverable",
                            "parent seize did not take effect")
        release.clear_rollout_marker(self.AOT)
        self.invariant(ctx)


class RolloutGateScenario(Scenario):
    """The REAL per-replica rollout gate interleaved with the REAL
    canary observation runs across a same-rid takeover: green requires
    the candidate to have been probed at its post-seize endpoint."""

    name = "rollout-gate"
    seizers = frozenset({"C"})

    ROOT = "/proto/deploy"
    E_OLD = "127.0.0.1:7001"
    E_NEW = "127.0.0.1:7002"

    def setup(self, ctx):
        fleet = _import_light("raft_tpu.serve.fleet")
        canary = _import_light("raft_tpu.serve.canary")
        rollout = _import_light("raft_tpu.serve.rollout")
        release = _import_light("raft_tpu.aot.release")
        release._PARITY_CACHE[:] = []
        from raft_tpu.obs import metrics
        ctx.shared["fleet"] = fleet
        ctx.shared["rollout"] = rollout
        old = fleet.FleetLedger(self.ROOT, replica_id="r0")
        old.claim(7001)
        neighbor = fleet.FleetLedger(self.ROOT, replica_id="r1")
        neighbor.claim(7003)
        ctx.alias(old.token, "OLD")
        ctx.alias(neighbor.token, "NBR")
        ctx.shared["old"] = old
        state = canary.CanaryState()
        ctx.shared["canary"] = state
        ctx.shared["baseline"] = {
            "passes": metrics.counter("canary_pass").value,
            "fails": metrics.counter("canary_fail").value,
        }
        ctx.shared["need"] = 2
        ctx.shared["gate"] = None  # (verdict, reason) once decided

    def _observe(self, ctx, rid, endpoint):
        ctx.shared["canary"].observe(
            design="d", replica=rid, fingerprint="fp",
            case=(1.0, 2.0, 3.0), out_keys=("x",),
            outputs={"x": [1.0]}, status=0, endpoint=endpoint)

    def actors(self, ctx):
        rollout = ctx.shared["rollout"]
        fleet = ctx.shared["fleet"]

        def candidate(c):
            cand = fleet.FleetLedger(self.ROOT, replica_id="r0")
            c.alias(cand.token, "CAND")
            cand.seize(7002)

        def prober(c):
            # two passes over live membership, probing each replica
            # at whatever endpoint its lease names RIGHT NOW — exactly
            # what the router canary daemon does
            led = fleet.FleetLedger(self.ROOT)
            for _ in range(2):
                live = led.live()
                for rid in sorted(live):
                    rec = live[rid]
                    self._observe(
                        c, rid, f"{rec.get('addr')}:{rec.get('port')}")
                c.pause("probe-pass")

        def gate(c):
            for _ in range(4):
                c.pause("gate-poll")
                payload = {"canary": c.shared["canary"].summary(),
                           "active": []}
                verdict, reason = rollout.gate_decision(
                    payload, c.shared["baseline"], c.shared["need"],
                    replica="r0", endpoint=self.E_NEW)
                if verdict != "pending":
                    c.shared["gate"] = (verdict, reason)
                    return

        return {"C": candidate, "P": prober, "G": gate}

    def invariant(self, ctx):
        check_lease_ownership(ctx, seizers=self.seizers)
        gate = ctx.shared["gate"]
        if gate is None or gate[0] != "green":
            return
        state = ctx.shared["canary"]
        with state._lock:
            run = dict(state._probes.get("r0") or {})
        if run.get("endpoint") != self.E_NEW \
                or int(run.get("n") or 0) < ctx.shared["need"]:
            raise Violation(
                "gate-candidate-probed",
                "rollout gate turned green before the candidate was "
                f"probed {ctx.shared['need']}x at its post-seize "
                f"endpoint (observation run: {run or None}) — the "
                "pre-PR-16 fleet-wide-pass race")

    def digest(self, ctx):
        state = ctx.shared["canary"]
        with state._lock:
            probes = tuple(sorted(
                (rid, run.get("endpoint"), run.get("n"))
                for rid, run in state._probes.items()))
        return (probes, ctx.shared["gate"])


class BreakerScenario(Scenario):
    """Two requesters + a cooldown clock against the REAL router
    breaker: after any interleaving of admit/success/failure/cancel,
    the half-open trial slot is never left held with no trial
    outstanding (the wedged-breaker liveness bug)."""

    name = "breaker"

    def setup(self, ctx):
        router = _import_light("raft_tpu.serve.router")
        br = router.Breaker(fails=1, cooldown_s=5.0,
                            clock=ctx.clock.monotonic)
        ctx.shared["br"] = br
        ctx.shared["trials"] = set()

    def actors(self, ctx):
        br = ctx.shared["br"]
        trials = ctx.shared["trials"]

        def requester(name):
            def fn(c):
                for _ in range(2):
                    c.pause("request")
                    if not br.allow():
                        continue
                    if br._trial_inflight:
                        trials.add(name)
                    outcome = c.choose(["success", "failure", "cancel"])
                    if outcome == "success":
                        br.record_success()
                    elif outcome == "failure":
                        br.record_failure()
                    else:
                        br.release_trial()   # cancelled hedge loser
                    trials.discard(name)
            return fn

        def clock_actor(c):
            for _ in range(2):
                c.pause("tick")
                c.clock.advance(6.0)

        return {"U": requester("U"), "V": requester("V"),
                "T": clock_actor}

    def invariant(self, ctx):
        br = ctx.shared["br"]
        if br._trial_inflight and not ctx.shared["trials"]:
            raise Violation(
                "breaker-liveness",
                "breaker refuses traffic with the half-open trial slot "
                "held and zero trials outstanding (a cancelled attempt "
                "did not release_trial)")

    def digest(self, ctx):
        br = ctx.shared["br"]
        return (br._consecutive, br._trial_inflight,
                None if br._opened_t is None else round(br._opened_t),
                round(ctx.clock.mono), tuple(sorted(ctx.shared["trials"])))


SCENARIOS = (LeaseLedgerScenario, ReleasePointerScenario,
             RolloutScenario, RolloutGateScenario, BreakerScenario)


# ----------------------------------------------------------- entry points


def _apply_patches(patches):
    # import EVERY target module before patching ANY: fleet.py binds
    # the fabric lease primitives by value at import time, so patching
    # fabric first and importing fleet second would save the patched
    # function as fleet's "original" and restore the bug permanently
    specs = [(spec.split(":"), obj)
             for spec, obj in (patches or {}).items()]
    mods = {name: _import_light(name) for (name, _), _ in specs}
    saved = []
    for (mod_name, attr), obj in specs:
        mod = mods[mod_name]
        saved.append((mod, attr, getattr(mod, attr)))
        setattr(mod, attr, obj)
    return saved


def _restore_patches(saved):
    for mod, attr, obj in saved:
        setattr(mod, attr, obj)


def run_all(patches=None, scenarios=None):
    """Explore every scenario (optionally with fixture patches
    applied).  Returns ``(violations, stats)`` where violations is a
    list of Violation and stats maps scenario name -> counters."""
    saved = _apply_patches(patches)
    violations, stats = [], {}
    try:
        for cls in (scenarios or SCENARIOS):
            scen = cls()
            v, st = explore(scen, max_crashes=scen.max_crashes)
            stats[scen.name] = st
            if v is not None:
                violations.append((scen.name, v))
    finally:
        _restore_patches(saved)
    return violations, stats


def load_fixture(path):
    """Import a ``tests/fixtures/protocol/`` fixture module; its
    ``PATCHES`` dict maps ``"module.path:attr"`` to the reverted
    (historically-buggy) implementation to explore with."""
    name = "_raft_protocol_fixture_" + \
        os.path.basename(path).replace(".py", "")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    if not hasattr(mod, "PATCHES"):
        raise EngineError(f"fixture {path} defines no PATCHES dict")
    return mod
