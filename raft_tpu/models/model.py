"""Top-level Model: frequency grid, FOWTs, load-case analysis.

The array-level equivalent of the reference Model
(``/root/reference/raft/raft_model.py:27-2245``).  Round-1 scope:
single-FOWT construction, statics, Morison hydro and the dynamics
solve; arrays/farms and potential flow wired in later milestones.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu.models.fowt import FOWTStructure
from raft_tpu.models.hydro import FOWTHydro
from raft_tpu.models.statics_solve import solve_equilibrium
from raft_tpu.physics.mooring import build_mooring
from raft_tpu.physics.statics import calc_statics
from raft_tpu.structure.schema import coerce, frequency_grid, load_design, parse_cases
from raft_tpu.ops.waves import wave_number_ref


class Model:
    def __init__(self, design):
        design = load_design(design)
        self.design = design

        settings = design.get("settings", {}) or {}
        self.XiStart = coerce(settings, "XiStart", default=0.1)
        self.nIter = int(coerce(settings, "nIter", default=15, dtype=int))

        self.w = frequency_grid(design)
        self.nw = len(self.w)
        self.depth = float(coerce(design["site"], "water_depth"))
        # reference-compatible dispersion solve (loose 1e-3 iteration,
        # raft_model.py:63-65) so downstream values match golden data
        self.k = wave_number_ref(self.w, self.depth)

        self.cases = parse_cases(design)

        # single-FOWT mode (array mode in a later milestone)
        self.fowtList = [FOWTStructure(design, depth=self.depth)]
        self.nDOF = sum(f.nDOF for f in self.fowtList)

        # mooring system (jax catenary equivalent of the FOWT-level
        # MoorPy system, raft_fowt.py:346-372)
        fs = self.fowtList[0]
        if "mooring" in design and isinstance(design["mooring"], dict):
            self.ms = build_mooring(design["mooring"], rho_water=fs.rho_water, g=fs.g)
        else:
            self.ms = None

        self._hydro = None
        self._statics = None

    # ------------------------------------------------------------ lazy state
    @property
    def hydro(self):
        if self._hydro is None:
            self._hydro = [FOWTHydro(f, self.w, self.k) for f in self.fowtList]
        return self._hydro

    def statics(self, Xi0=None):
        """FOWT statics matrices (cached at the zero pose)."""
        if Xi0 is None:
            if self._statics is None:
                self._statics = calc_statics(self.fowtList[0])
            return self._statics
        return calc_statics(self.fowtList[0], Xi0)

    # --------------------------------------------------------------- statics
    def solve_statics(self, case=None):
        """Mean offsets for a load case (Model.solveStatics equivalent,
        raft_model.py:550-964; staticsMod=0 / forcingsMod=0 path).

        Returns the equilibrium pose X (nDOF,)."""
        fs = self.fowtList[0]
        stat = self.statics()
        K_h = stat["C_struc"] + stat["C_hydro"]
        F_und = stat["W_struc"] + stat["W_hydro"] + stat["f0_additional"]

        F_env = jnp.zeros(fs.nDOF)
        if case is not None:
            fh = self.hydro[0]
            F_env = F_env + fh.current_loads(case)
            F_env = F_env + self.aero_mean_force(case)

        X, Fres = solve_equilibrium(fs, self.ms, K_h, F_und, F_env)
        self.X0 = X
        return X

    def aero_mean_force(self, case):
        """Mean rotor force; zero until the BEMT aero module lands."""
        return jnp.zeros(self.fowtList[0].nDOF)
