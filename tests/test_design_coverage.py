"""Breadth coverage: every reference design and example YAML builds and
reaches an unloaded static equilibrium (loader/schema robustness across
the full design corpus, including legacy numeric member types and the
426-DOF flexible example)."""

import glob
import os

import numpy as np
import pytest

from tests.conftest import REFERENCE_DIR

import raft_tpu

pytestmark = pytest.mark.slow

DESIGNS = sorted(
    glob.glob(os.path.join(REFERENCE_DIR, "designs", "*.yaml"))
    + glob.glob(os.path.join(REFERENCE_DIR, "examples", "*.yaml"))
)
# the farm design needs its MoorDyn file path resolved relative to the
# tests dir in the reference; covered by test_farm via the test_data copy
SKIP = {"VolturnUS-S_farm.yaml"}


@pytest.mark.parametrize(
    "path", [p for p in DESIGNS if os.path.basename(p) not in SKIP],
    ids=[os.path.basename(p) for p in DESIGNS if os.path.basename(p) not in SKIP],
)
def test_design_builds_and_solves(path):
    model = raft_tpu.Model(path)
    X = np.asarray(model.solve_statics(None))
    assert np.isfinite(X).all()
    assert abs(X[2]) < 10.0  # unloaded heave within a sane band
