"""In-band solver-health status bits for traced solve paths.

The per-case pipeline (statics Newton -> drag-linearisation fixed
point -> complex impedance solve) can fail *finitely*: a Newton that
hits its iteration cap, a drag linearisation stopped by the reference
cap, an impedance matrix solved through near-singularity — all produce
numbers, not NaNs, and under ``vmap``/``pjit`` there is no host
exception to carry the bad news.  The status word is the in-band
replacement: a per-case **int32 bitmask** produced alongside the
physics by the solvers themselves, carried through every traced
evaluator as the ``"status"`` output, persisted into sweep shards, and
consumed by the escalation re-solver in
:mod:`raft_tpu.parallel.resilience`.

Contract (every future backend — pmap pods, native BEM — must
preserve it):

* the word is ``int32`` everywhere, including under the
  ``RAFT_TPU_DTYPE=float32`` policy (no 64-bit integers sneak in);
* all helpers are pure array ops (operator overloading only — they
  work identically on numpy values host-side and on traced jax values
  inside ``jit``/``vmap``), with **no host callbacks**;
* bit 0 means "this specific guard fired", absence of bits means "no
  guard fired" — it is NOT a proof of correctness, only of silence.

Bits are split into a SEVERE set (the result is suspect: escalation
re-solves these) and an informational set (a guard engaged but the
solve still met its stopping rule).  ``describe`` renders a host-side
human-readable reason for logs and ``quarantine.json``.

This module deliberately imports neither jax nor the flag registry:
it is loadable from host tooling (linter, CLI) without touching a
backend, and the helpers stay backend-agnostic.
"""

from __future__ import annotations

import numpy as np

# ------------------------------------------------------------- bit registry

# name -> mask.  Append-only: shard files and quarantine.json persist
# raw masks, so reassigning a bit silently re-labels historical data.
MASKS = {
    # statics Newton hit its iteration budget with the step-size
    # stopping rule unmet (solve_equilibrium_general)
    "STATICS_MAX_ITER": 1 << 0,
    # at least one applied Newton step saturated the per-DOF step cap
    # (the jnp.clip in the damped Newton) — informational: the
    # reference caps early steps routinely and still converges
    "STATICS_STEP_CAPPED": 1 << 1,
    # drag-linearisation fixed point stopped by the reference iteration
    # cap with the relative-change rule unmet (solve_dynamics_fowt)
    "DRAG_CAP_HIT": 1 << 2,
    # one-step Hager estimate of kappa_1(Z(w)) exceeded
    # RAFT_TPU_COND_THRESHOLD (gated by RAFT_TPU_COND_CHECK)
    "ILL_CONDITIONED_Z": 1 << 3,
    # a non-finite value in a solver output (X0 / Xi) — also
    # synthesized host-side for quarantined NaN rows
    "NONFINITE_INTERMEDIATE": 1 << 4,
    # an input was clamped to keep the physics defined (e.g. the
    # near-zero wind-speed floor in the aero constants) — informational
    "INPUT_CLIPPED": 1 << 5,
}

STATICS_MAX_ITER = MASKS["STATICS_MAX_ITER"]
STATICS_STEP_CAPPED = MASKS["STATICS_STEP_CAPPED"]
DRAG_CAP_HIT = MASKS["DRAG_CAP_HIT"]
ILL_CONDITIONED_Z = MASKS["ILL_CONDITIONED_Z"]
NONFINITE_INTERMEDIATE = MASKS["NONFINITE_INTERMEDIATE"]
INPUT_CLIPPED = MASKS["INPUT_CLIPPED"]

OK = 0

# bits that mean "the shipped numbers are suspect" — the escalation
# ladder re-solves rows carrying any of these
SEVERE = (STATICS_MAX_ITER | DRAG_CAP_HIT | ILL_CONDITIONED_Z
          | NONFINITE_INTERMEDIATE)
# guards that engaged without violating a stopping rule
INFORMATIONAL = STATICS_STEP_CAPPED | INPUT_CLIPPED


# ------------------------------------------------------------ pure helpers


def set_bit(status, mask, cond):
    """``status | mask`` where ``cond`` holds, ``status`` elsewhere.

    Pure operator-overloading arithmetic (``bool * int32`` promotes to
    int32 in both numpy and jax), so the same helper serves traced
    code under jit/vmap and host-side numpy post-processing.  ``mask``
    is a static Python int from this registry; ``cond`` broadcasts.
    """
    return status | (cond * np.int32(mask))


def any_bit(status, mask=SEVERE):
    """Boolean (array) — does ``status`` carry any bit of ``mask``?"""
    return (status & np.int32(mask)) != 0


def describe(status):
    """Human-readable reason string for one host-side status value.

    ``0`` renders as ``"ok"``; unknown (future) bits render as
    ``"bit<N>"`` so old tooling degrades readably on new data.
    """
    s = int(status)
    if s == 0:
        return "ok"
    names = [name for name, mask in MASKS.items() if s & mask]
    known = 0
    for mask in MASKS.values():
        known |= mask
    unknown = s & ~known
    bit = 0
    while unknown:
        if unknown & 1:
            names.append(f"bit{bit}")
        unknown >>= 1
        bit += 1
    return "|".join(names)
