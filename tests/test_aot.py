"""Tier-1 tests for the AOT program bank (:mod:`raft_tpu.aot`).

* mechanics, in-process: store/load round trip is bit-identical to the
  freshly-traced program; key misses are CLEAN (flag flip, code/jax
  fingerprint change, corrupted payload) — a stale or damaged entry can
  re-lower or fail loudly, never execute;
* the maintenance CLI (``list``/``verify``/``gc``) catches orphans and
  corruption and reclaims dead entries;
* the process-wide compile budget (``RAFT_TPU_COMPILE_BUDGET``)
  raises/warns at the dispatch that compiled;
* cross-process, fresh JAX runtime (the ISSUE acceptance): export in
  one process, load in a subprocess — bit-identical outputs with ZERO
  backend-compile events (sentinel-asserted), including the real spar
  model warmed through ``python -m raft_tpu.aot warmup``.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.analysis import recompile
from raft_tpu.aot import bank
from raft_tpu.aot.__main__ import main as aot_cli
from raft_tpu.obs import metrics
from raft_tpu.parallel.sweep import make_mesh, sweep_cases

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "_aot_child.py")
SPAR = os.path.join(REPO, "raft_tpu", "designs", "spar_demo.yaml")


def tiny_evaluator(stamp=("tiny", 1)):
    """Deterministic stamped closure (fresh object per call, so each
    test controls its own sweep memo)."""

    def evaluate(h, t, b):
        w = jnp.linspace(0.1, 2.0, 16)
        psd = (h / t) ** 2 / ((w - 2 * np.pi / t) ** 2 + 0.01)
        return {"PSD": psd, "X0": jnp.stack([h * jnp.cos(b),
                                             h * jnp.sin(b)])}

    if stamp is not None:
        evaluate._raft_program_key = stamp
    return evaluate


def run_sweep(evaluate, seed=3):
    rng = np.random.default_rng(seed)
    out = sweep_cases(evaluate, rng.uniform(2, 8, 8),
                      rng.uniform(6, 14, 8), rng.uniform(-0.5, 0.5, 8),
                      mesh=make_mesh(8))
    jax.block_until_ready(out)
    return {k: np.asarray(v) for k, v in out.items()}


def counters():
    return metrics.snapshot()["counters"]


@pytest.fixture
def bank_dir(tmp_path, monkeypatch):
    d = tmp_path / "aot_bank"
    monkeypatch.setenv("RAFT_TPU_AOT_DIR", str(d))
    return d


def bank_files(d, suffix=".json"):
    v = d / f"v{bank.BANK_FORMAT}"
    return sorted(p for p in v.iterdir() if p.name.endswith(suffix)) \
        if v.is_dir() else []


# ------------------------------------------------------------- mechanics

def test_roundtrip_bit_identical_vs_fresh_trace(bank_dir, monkeypatch):
    """load-mode export, then a fresh require-mode closure loads the
    banked executable — zero compile events, outputs exactly equal to
    the freshly-traced program's."""
    monkeypatch.setenv("RAFT_TPU_AOT", "load")
    c0 = counters()
    out_fresh = run_sweep(tiny_evaluator())   # traces, compiles, exports
    c1 = counters()
    assert c1.get("aot_programs_compiled", 0) - \
        c0.get("aot_programs_compiled", 0) == 1
    assert len(bank_files(bank_dir)) == 1

    monkeypatch.setenv("RAFT_TPU_AOT", "require")
    with recompile.assert_compile_budget(0, "bank-loaded sweep"):
        out_loaded = run_sweep(tiny_evaluator())  # new closure, same stamp
    c2 = counters()
    assert c2.get("aot_programs_loaded", 0) - \
        c1.get("aot_programs_loaded", 0) == 1
    for k in out_fresh:
        np.testing.assert_array_equal(out_fresh[k], out_loaded[k])

    # device-cost ledger: the sidecar carries a non-empty cost_analysis
    # block (acceptance: every banked program's sidecar does) and both
    # the export and the load registered the program in the ledger with
    # its dispatch stats
    with open(bank_files(bank_dir)[0]) as f:
        meta = json.load(f)
    cost = meta["cost_analysis"]
    assert cost and cost["flops"] > 0 and cost["arg_bytes"] > 0
    rows = {r["key"]: r for r in bank.ledger_summary()}
    assert meta["key"] in rows
    row = rows[meta["key"]]
    assert row["flops"] == cost["flops"]
    assert row["dispatches"] >= 2 and row["wall_s"] > 0
    assert row["gflops_s_mean"] > 0
    assert metrics.counter("program_dispatches").value >= 2


def test_flag_flip_is_a_miss(bank_dir, monkeypatch):
    """A trace-time flag flip changes the key: require mode fails
    loudly instead of serving the old-flag program."""
    monkeypatch.setenv("RAFT_TPU_AOT", "load")
    run_sweep(tiny_evaluator())
    monkeypatch.setenv("RAFT_TPU_AOT", "require")
    monkeypatch.setenv("RAFT_TPU_SOLVER", "lapack")
    with pytest.raises(bank.BankMissError, match="warmup"):
        run_sweep(tiny_evaluator())


def test_require_miss_can_fall_back_flag_controlled(bank_dir, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_AOT", "require")
    monkeypatch.setenv("RAFT_TPU_AOT_MISS", "compile")
    c0 = counters()
    out = run_sweep(tiny_evaluator())
    assert np.isfinite(out["PSD"]).all()
    c1 = counters()
    assert c1.get("aot_bank_misses", 0) - c0.get("aot_bank_misses", 0) == 1
    assert c1.get("aot_programs_compiled", 0) - \
        c0.get("aot_programs_compiled", 0) == 1


def test_stale_code_fingerprint_misses_cleanly(bank_dir, monkeypatch):
    """A source edit (simulated: different code fingerprint) never
    loads the old entry — require refuses, load re-lowers next to it."""
    monkeypatch.setenv("RAFT_TPU_AOT", "load")
    run_sweep(tiny_evaluator())
    assert len(bank_files(bank_dir)) == 1

    monkeypatch.setattr(bank, "code_fingerprint", lambda: "deadbeef" * 2)
    monkeypatch.setenv("RAFT_TPU_AOT", "require")
    with pytest.raises(bank.BankMissError):
        run_sweep(tiny_evaluator())
    monkeypatch.setenv("RAFT_TPU_AOT", "load")
    run_sweep(tiny_evaluator())              # clean re-lower, new entry
    assert len(bank_files(bank_dir)) == 2


def test_corrupt_payload_is_miss_not_crash(bank_dir, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_AOT", "load")
    run_sweep(tiny_evaluator())
    [bin_path] = bank_files(bank_dir, ".bin")
    bin_path.write_bytes(bin_path.read_bytes()[:64])   # truncate

    monkeypatch.setenv("RAFT_TPU_AOT", "require")
    c0 = counters()
    with pytest.raises(bank.BankMissError):
        run_sweep(tiny_evaluator())
    assert counters().get("aot_bank_errors", 0) - \
        c0.get("aot_bank_errors", 0) == 1

    monkeypatch.setenv("RAFT_TPU_AOT", "load")
    out = run_sweep(tiny_evaluator())        # re-compiles, heals the entry
    assert np.isfinite(out["PSD"]).all()
    monkeypatch.setenv("RAFT_TPU_AOT", "require")
    with recompile.assert_compile_budget(0, "healed entry"):
        run_sweep(tiny_evaluator())


def test_unstamped_closure_is_never_banked(bank_dir, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_AOT", "load")
    out = run_sweep(tiny_evaluator(stamp=None))
    assert np.isfinite(out["PSD"]).all()
    assert bank_files(bank_dir) == []        # nothing exported


def test_off_mode_touches_nothing(bank_dir, monkeypatch):
    monkeypatch.delenv("RAFT_TPU_AOT", raising=False)
    run_sweep(tiny_evaluator())
    assert not (bank_dir / f"v{bank.BANK_FORMAT}").exists()


# ------------------------------------------------------------------ CLI

def test_cli_list_verify_gc(bank_dir, monkeypatch, capsys):
    monkeypatch.setenv("RAFT_TPU_AOT", "load")
    run_sweep(tiny_evaluator())
    [meta_path] = bank_files(bank_dir)
    key = meta_path.name[:-5]

    assert aot_cli(["verify"]) == 0
    assert aot_cli(["list"]) == 0
    assert key in capsys.readouterr().out

    stray = meta_path.parent / (meta_path.name + ".tmp")
    stray.write_bytes(b"interrupted write")
    assert aot_cli(["verify"]) == 0          # note, not a CI failure
    assert "interrupted" in capsys.readouterr().out

    orphan = meta_path.parent / ("ff" * 12 + ".bin")
    orphan.write_bytes(b"not an executable")
    assert aot_cli(["verify"]) == 1
    assert "orphan" in capsys.readouterr().err

    meta_path.write_text("{not json")
    assert aot_cli(["verify"]) == 1

    assert aot_cli(["gc"]) == 0     # corrupt + orphan + .tmp reclaimed
    assert not stray.exists()
    assert aot_cli(["verify"]) == 0
    assert aot_cli(["gc", "--all"]) == 0
    assert bank_files(bank_dir) == [] and bank_files(bank_dir, ".bin") == []


def test_content_fingerprint_deterministic_and_content_blind_fallback():
    """Equal content hashes equal (incl. object arrays, which must
    recurse instead of hashing pointer bytes); distinct content hashes
    differently; non-coercible objects degrade to type identity."""
    a = np.array([{"Hs": 6.0}, [1, 2]], dtype=object)
    b = np.array([{"Hs": 6.0}, [1, 2]], dtype=object)
    assert bank.content_fingerprint(a) == bank.content_fingerprint(b)
    c = np.array([{"Hs": 7.0}, [1, 2]], dtype=object)
    assert bank.content_fingerprint(a) != bank.content_fingerprint(c)

    class Opaque:
        pass

    assert bank.content_fingerprint(Opaque()) == \
        bank.content_fingerprint(Opaque())


def test_warmup_rejects_unknown_kind():
    from raft_tpu.aot import warmup

    with pytest.raises(ValueError, match="unknown warmup kind"):
        warmup.warmup_model(kinds=("case",))   # typo: singular


# -------------------------------------------------------- compile budget

def test_compile_budget_enforced_and_warn(monkeypatch):
    recompile.install()
    # the persistent cache would classify a repeat program as a disk
    # hit (budget-exempt); force real compiles for determinism
    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        monkeypatch.setenv("RAFT_TPU_COMPILE_BUDGET",
                           str(recompile.PROCESS_LOG.real_count))
        with pytest.raises(recompile.RecompilationError,
                           match="RAFT_TPU_COMPILE_BUDGET"):
            jax.jit(lambda x: x * 1.618 + 0.577)(
                jnp.ones(5)).block_until_ready()

        monkeypatch.setenv("RAFT_TPU_COMPILE_BUDGET_ACTION", "warn")
        monkeypatch.setenv("RAFT_TPU_COMPILE_BUDGET",
                           str(recompile.PROCESS_LOG.real_count))
        c0 = counters().get("compile_budget_exceeded", 0)
        jax.jit(lambda x: x * 2.718 - 1.414)(
            jnp.ones(5)).block_until_ready()   # logs, does not raise
        assert counters()["compile_budget_exceeded"] > c0
    finally:
        jax.config.update("jax_enable_compilation_cache", old)


# -------------------------------------------- cross-process (fresh runtime)

def _run_child(env_overrides, out_path=None):
    env = {k: v for k, v in os.environ.items()
           if not (k.startswith("RAFT_TPU_") or k.startswith("AOT_CHILD"))}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.update(env_overrides)
    if out_path:
        env["AOT_CHILD_OUT"] = str(out_path)
    p = subprocess.run([sys.executable, CHILD], env=env, timeout=600,
                       capture_output=True, text=True)
    assert p.returncode == 0, f"child failed:\n{p.stderr[-2000:]}"
    return json.loads(p.stdout.strip().splitlines()[-1])


def test_subprocess_export_then_fresh_runtime_loads(tmp_path):
    """The acceptance mechanics end to end: export in process A, load
    in process B with a FRESH JAX runtime — xla_compiles == 0
    (sentinel-asserted in the child) and bit-identical outputs vs the
    freshly-traced program."""
    base = {"RAFT_TPU_AOT_DIR": str(tmp_path / "bank"),
            "RAFT_TPU_CACHE_DIR": str(tmp_path / "xla")}
    r1 = _run_child({**base, "RAFT_TPU_AOT": "load"},
                    tmp_path / "a.npz")
    assert r1["compiled"] == 1 and r1["loaded"] == 0

    r2 = _run_child({**base, "RAFT_TPU_AOT": "require",
                     "RAFT_TPU_COMPILE_BUDGET": "0"},
                    tmp_path / "b.npz")
    assert r2["loaded"] == 1 and r2["compiled"] == 0
    assert r2["sweep_compile_events"] == 0
    assert r2["process_real_compiles"] == 0

    a = np.load(tmp_path / "a.npz")
    b = np.load(tmp_path / "b.npz")
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])


def test_subprocess_stale_code_relowers_not_crashes(tmp_path):
    """Process B pretends the sources changed (new code fingerprint):
    the stored entry must MISS and re-lower cleanly — same results,
    one fresh compile, two entries on disk."""
    base = {"RAFT_TPU_AOT_DIR": str(tmp_path / "bank"),
            "RAFT_TPU_CACHE_DIR": str(tmp_path / "xla")}
    r1 = _run_child({**base, "RAFT_TPU_AOT": "load"}, tmp_path / "a.npz")
    assert r1["compiled"] == 1

    r2 = _run_child({**base, "RAFT_TPU_AOT": "load",
                     "AOT_CHILD_FAKE_CODE": "0123456789abcdef"},
                    tmp_path / "b.npz")
    assert r2["compiled"] == 1 and r2["loaded"] == 0 and r2["misses"] == 1

    a, b = np.load(tmp_path / "a.npz"), np.load(tmp_path / "b.npz")
    for k in a.files:
        np.testing.assert_array_equal(a[k], b[k])
    v = tmp_path / "bank" / f"v{bank.BANK_FORMAT}"
    assert len([p for p in v.iterdir() if p.name.endswith(".json")]) == 2


def test_spar_warmup_then_fresh_process_is_compile_free(tmp_path):
    """The ISSUE acceptance on the real model: `python -m raft_tpu.aot
    warmup` exports the spar case-evaluator sweep program; a fresh
    process then answers the same sweep under RAFT_TPU_AOT=require +
    RAFT_TPU_COMPILE_BUDGET=0 — 1 bank load, 0 compiles, and a cold
    start far below the trace+compile cost it replaced (~25s on this
    host)."""
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("RAFT_TPU_")}
    env.update(XLA_FLAGS="--xla_force_host_platform_device_count=8",
               RAFT_TPU_AOT_DIR=str(tmp_path / "bank"),
               RAFT_TPU_CACHE_DIR=str(tmp_path / "xla"))
    # --x64 matches the child runtime (the parity suite runs x64; x64
    # is part of the bank's environment fingerprint)
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.aot", "warmup",
         "--design", SPAR, "--kinds", "cases", "--n", "8", "--x64"],
        env=env, timeout=600, capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0, f"warmup failed:\n{p.stderr[-2000:]}"
    assert "compiled 1 program(s)" in p.stdout

    r = _run_child({"RAFT_TPU_AOT_DIR": str(tmp_path / "bank"),
                    "RAFT_TPU_CACHE_DIR": str(tmp_path / "xla"),
                    "RAFT_TPU_AOT": "require",
                    "RAFT_TPU_COMPILE_BUDGET": "0",
                    "AOT_CHILD_MODEL": "spar"},
                   tmp_path / "spar.npz")
    assert r["loaded"] == 1 and r["compiled"] == 0
    assert r["sweep_compile_events"] == 0
    assert r["process_real_compiles"] == 0
    # trace+compile alone costs ~25s here; a bank hit must stay well
    # under that even with wall-clock noise
    assert r["cold_start_s"] < 20.0
    out = np.load(tmp_path / "spar.npz")
    assert np.isfinite(out["PSD"]).all()
