"""Hierarchical telemetry spans over the structured-log stream.

A span is a timed region of host-side work — a driver run, one statics
solve, one sweep shard, one retry attempt, one escalation rung.  Spans
emit paired ``span_begin``/``span_end`` JSONL events carrying
``trace_id`` (shared by a whole nested tree), ``span_id`` and the
parent's id, propagated through a :mod:`contextvars` variable so
nesting works across function boundaries (and stays correctly scoped
per thread/async task).  Every other ``log_event`` fired inside a span
automatically carries the enclosing trace/span ids, which is what lets
``python -m raft_tpu.obs report`` attribute a ``shard_retry`` to the
shard (and sweep) it happened in.

Overhead discipline: with ``RAFT_TPU_LOG`` unset, a span is a sink
check, a clock read and one histogram observe (a few microseconds) —
no ids are generated, no contextvar is touched, nothing is emitted;
the ``span_<name>_s`` wall-time histograms stay on either way, so a
Prometheus scrape (``RAFT_TPU_METRICS``) carries per-stage timings
even when the event stream is off.  All instrumentation is host-side
only: spans never run under a jax trace, so the jaxpr contract suite
sees zero new primitives.

Device-trace alignment: when ``RAFT_TPU_PROFILE`` is set, each span
also enters a ``jax.profiler.TraceAnnotation`` of the same name, so
the host span shows up on the profiler timeline next to the XLA device
slices it caused (the ``named_scope`` annotations inside the sweep's
traced programs carry the same names down onto device ops).
"""

from __future__ import annotations

import time
import uuid

from raft_tpu.obs import metrics
from raft_tpu.utils import config, structlog


def _new_id():
    return uuid.uuid4().hex[:16]


def current_ids():
    """(trace_id, span_id) of the innermost active span, or None."""
    return structlog.SPAN_CTX.get()


class span:
    """Context manager for one telemetry span::

        with obs.span("shard", shard=3, rows=256):
            ...

    Emits ``span_begin``/``span_end`` (the latter with ``wall_s``,
    ``ok`` and a truncated ``error`` on failure) and observes the wall
    time into the ``span_<name>_s`` histogram of the metrics registry.
    Exceptions always propagate."""

    __slots__ = ("name", "attrs", "trace_id", "span_id",
                 "_token", "_t0", "_ann")

    def __init__(self, name, **attrs):
        self.name = name
        self.attrs = attrs
        self.trace_id = None
        self.span_id = None
        self._token = None
        self._t0 = None
        self._ann = None

    def __enter__(self):
        if config.raw("PROFILE"):
            # mirror the span onto the jax profiler timeline; must not
            # be able to break the instrumented computation
            try:
                from jax.profiler import TraceAnnotation

                self._ann = TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        if not structlog.enabled():
            return self  # fast path: no ids, no contextvar, no event
        parent = structlog.SPAN_CTX.get()
        self.trace_id = parent[0] if parent else _new_id()
        self.span_id = _new_id()
        self._token = structlog.SPAN_CTX.set((self.trace_id, self.span_id))
        structlog.log_event(
            "span_begin", name=self.name,
            parent_id=parent[1] if parent else None, **self.attrs)
        return self

    def __exit__(self, exc_type, exc, tb):
        wall = time.perf_counter() - self._t0
        # the wall-time histogram feeds unconditionally (metrics exist
        # without the event stream); events only when the sink is live
        metrics.histogram(f"span_{self.name}_s").observe(wall)
        if self._token is not None:
            kw = {}
            if exc_type is not None:
                kw["error"] = repr(exc)[:200]
            structlog.log_event(
                "span_end", name=self.name, wall_s=round(wall, 6),
                ok=exc_type is None, **kw)
            structlog.SPAN_CTX.reset(self._token)
            self._token = None
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._ann = None
        return False
