"""Warm the AOT program bank before anyone is waiting on it.

Builds a model once and dispatches each requested sweep kind through
the SAME funnel production uses (:mod:`raft_tpu.parallel.sweep`), with
``RAFT_TPU_AOT`` forced to at least ``load`` — so every program the
sweep memo would build is lowered, compiled and exported now, and a
fresh serving/worker process later answers its first sweep from the
bank in seconds.

The warmed kinds map onto the four traced entry points the jaxpr
contract suite guards (:mod:`raft_tpu.analysis.jaxpr_contracts`):

* ``cases``  — :func:`raft_tpu.api.make_case_evaluator` through
  :func:`~raft_tpu.parallel.sweep.sweep_cases` (the spar-dynamics
  chain: statics, excitation, drag fixed point, impedance solves);
* ``full``   — :func:`raft_tpu.api.make_full_evaluator` through
  :func:`~raft_tpu.parallel.sweep.sweep_cases_full` (full physics,
  operating turbine);
* ``design`` — :func:`raft_tpu.api.make_design_evaluator` through
  ``sweep_cases_full`` (the design-sweep axis);
* the solver-health status fold rides along in every kind: ``status``
  is warmed as a first-class out_key (default out_keys include it).

Batch sizes are per-program: a 10k/512 sweep dispatches a 512-row
program plus one padded tail, so warm the sizes you will serve
(``--n 512,8``).  Custom closures (e.g. ``sweep_10k.py``'s per-design
summary evaluator) self-warm instead: their first ``RAFT_TPU_AOT=load``
run exports, every later process loads.

Fabric workers (:mod:`raft_tpu.parallel.fabric`) call
:func:`warmup_model` before their FIRST shard claim when the sweep
spec names a warmup block and ``RAFT_TPU_AOT`` is armed — a worker
joining mid-sweep answers its first shard from the bank (its
``fabric_worker_start`` event reports ``programs_compiled=0`` on a
warmed bank) instead of stalling the ledger behind a 25s+ trace.
"""

from __future__ import annotations

import contextlib
import os
import time

import numpy as np

DEFAULT_OUT_KEYS = ("PSD", "X0", "status")
DEFAULT_KINDS = ("cases", "full", "design")
#: ``bucketed`` warms the shape-bucketed heterogeneous-design programs
#: (raft_tpu.structure.bucketing) over the BUNDLED design trio — one
#: program per bucket signature, shared by every design in the bucket —
#: so a fresh process answers a mixed-topology sweep with zero compiles.
#: ``serve`` warms the evaluation service's bucketed single-case
#: programs at the batcher's CANDIDATE batch-size ladder
#: (dp,2*dp,..,RAFT_TPU_SERVE_MAX_BATCH — raft_tpu.serve.engine;
#: under RAFT_TPU_SERVE_LADDER=cost the server prunes flat rungs after
#: its own warmup, always to a SUBSET of these), so a fresh server
#: answers its first request with zero compiles; ``--n`` is ignored
#: for this kind, set RAFT_TPU_SERVE_MAX_BATCH (and --out-keys/--x64
#: and RAFT_TPU_BUCKET_STEPS — the pad ladder is part of the bucket
#: signature) to EXACTLY what the server will run
ALL_KINDS = DEFAULT_KINDS + ("bucketed", "serve")

_DESIGNS_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "designs")
BUCKET_WARMUP_DESIGNS = tuple(
    os.path.join(_DESIGNS_DIR, f) for f in
    ("spar_demo.yaml", "semi_demo.yaml", "mhk_demo.yaml"))


@contextlib.contextmanager
def _force_load_mode():
    """Ensure the bank is armed for the duration of the warmup (a
    warmup under ``RAFT_TPU_AOT=off`` would compile and export
    nothing; ``require`` would refuse the very misses it exists to
    fill)."""
    from raft_tpu.utils import config

    env = config.env_name("AOT")
    old = os.environ.get(env)
    if config.get("AOT") != "load":
        os.environ[env] = "load"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop(env, None)
        else:
            os.environ[env] = old


def _round_up(n, multiple):
    return int(-(-n // multiple) * multiple)


def warmup_model(design=None, sizes=(8,), kinds=DEFAULT_KINDS,
                 out_keys=DEFAULT_OUT_KEYS, mesh=None, designs=None):
    """Warm the bank for one design (``designs`` — a list of YAML
    paths — warms the ``serve`` kind for SEVERAL, deduplicated by
    bucket signature: the one-warmup-for-N-replicas recipe of the
    serving fleet, where the coordinator pays the compile bill once
    and every replica starts under ``RAFT_TPU_AOT=require``).
    Returns a list of per-program report dicts (kind, rows,
    loaded/compiled, seconds)."""
    import jax

    import raft_tpu
    from raft_tpu import api
    from raft_tpu.obs import metrics
    from raft_tpu.parallel.sweep import make_mesh, sweep_cases, \
        sweep_cases_full
    from raft_tpu.utils.devices import enable_compile_cache
    from raft_tpu.utils.structlog import log_event

    unknown = set(kinds) - set(ALL_KINDS)
    if unknown:
        # a typo'd kind must not report a successful no-op warmup — the
        # serving replica would discover the cold bank as BankMissError
        raise ValueError(f"unknown warmup kind(s) {sorted(unknown)}; "
                         f"choose from {list(ALL_KINDS)}")
    enable_compile_cache()
    if mesh is None:
        mesh = make_mesh()
    dp = mesh.shape.get("dp", mesh.devices.size)

    # the single-design model only feeds the non-bucketed sweep kinds
    # (cases/full/design) — and the serve kind only when no explicit
    # `designs` list supplies its entries: a bucketed-only or
    # designs-driven serve warmup must not pay a YAML load + host
    # build it never uses
    evaluators = {}
    model = None
    need_model = bool(set(kinds) - {"bucketed", "serve"}) \
        or ("serve" in kinds and not designs)
    if need_model:
        if design is None:
            design = os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "designs", "spar_demo.yaml")
        model = raft_tpu.Model(design)
        if "cases" in kinds:
            evaluators["cases"] = api.make_case_evaluator(model)
        if "full" in kinds:
            evaluators["full"] = api.make_full_evaluator(model)
        if "design" in kinds:
            evaluators["design"] = api.make_design_evaluator(model)

    reports = []
    with _force_load_mode():
        for kind, evaluate in evaluators.items():
            for n in sizes:
                rows = _round_up(int(n), dp)
                rng = np.random.default_rng(0)
                Hs = rng.uniform(2.0, 8.0, rows)
                Tp = rng.uniform(6.0, 14.0, rows)
                beta = rng.uniform(-0.5, 0.5, rows)
                c0 = {k: metrics.counter(k).value for k in
                      ("aot_programs_loaded", "aot_programs_compiled")}
                t0 = time.perf_counter()
                if kind == "cases":
                    out = sweep_cases(evaluate, Hs, Tp, beta, mesh=mesh,
                                      out_keys=out_keys)
                elif kind == "full":
                    out = sweep_cases_full(
                        evaluate,
                        {"wind_speed": rng.uniform(4.0, 24.0, rows),
                         "Hs": Hs, "Tp": Tp, "beta_deg": beta * 57.3},
                        mesh=mesh, out_keys=out_keys)
                else:  # design
                    out = sweep_cases_full(
                        evaluate,
                        {"Hs": Hs, "Tp": Tp, "beta": beta,
                         "Cd_scale": rng.uniform(0.9, 1.1, rows)},
                        mesh=mesh, out_keys=out_keys)
                jax.block_until_ready(out)
                wall = time.perf_counter() - t0
                rep = dict(
                    kind=kind, rows=rows, wall_s=round(wall, 2),
                    loaded=metrics.counter("aot_programs_loaded").value
                    - c0["aot_programs_loaded"],
                    compiled=metrics.counter("aot_programs_compiled").value
                    - c0["aot_programs_compiled"])
                log_event("aot_warmup", kind=kind, n=rows,
                          loaded=rep["loaded"], compiled=rep["compiled"],
                          wall_s=rep["wall_s"])
                reports.append(rep)

        if "bucketed" in kinds:
            # heterogeneous shape buckets over the bundled design trio.
            # ``--n`` stays PER-PROGRAM like every other kind: the bank
            # keys on input avals, so each bucket must be warmed at the
            # per-bucket group size a production mixed sweep will
            # dispatch — n rows of EVERY bundled bucket signature, not
            # n rows split ~n/n_buckets ways across them
            from raft_tpu.parallel.sweep import sweep_heterogeneous
            from raft_tpu.structure import bucketing

            bmodels = [raft_tpu.Model(p) for p in BUCKET_WARMUP_DESIGNS]
            by_sig = {}
            for bm in bmodels:
                by_sig.setdefault(bucketing.bucket_signature(bm),
                                  []).append(bm)
            for n in sizes:
                rows = _round_up(int(n), dp)
                models_row = []
                for group in by_sig.values():
                    models_row += [group[i % len(group)]
                                   for i in range(rows)]
                total = len(models_row)
                rng = np.random.default_rng(0)
                c0 = {k: metrics.counter(k).value for k in
                      ("aot_programs_loaded", "aot_programs_compiled")}
                t0 = time.perf_counter()
                out = sweep_heterogeneous(
                    models_row, rng.uniform(2.0, 8.0, total),
                    rng.uniform(6.0, 14.0, total),
                    rng.uniform(-0.5, 0.5, total), mesh=mesh,
                    out_keys=out_keys)
                jax.block_until_ready(out)
                wall = time.perf_counter() - t0
                rep = dict(
                    kind="bucketed", rows=rows, n_buckets=len(by_sig),
                    wall_s=round(wall, 2),
                    loaded=metrics.counter("aot_programs_loaded").value
                    - c0["aot_programs_loaded"],
                    compiled=metrics.counter("aot_programs_compiled").value
                    - c0["aot_programs_compiled"])
                log_event("aot_warmup", kind="bucketed", n=rows,
                          n_buckets=len(by_sig), loaded=rep["loaded"],
                          compiled=rep["compiled"], wall_s=rep["wall_s"])
                reports.append(rep)

        if "serve" in kinds:
            # the evaluation service's programs: each design's bucketed
            # single-case evaluator at every padded batch size of the
            # batcher's ladder — sizes come from RAFT_TPU_SERVE_MAX_BATCH
            # (NOT --n), because the bank keys on input avals and the
            # server dispatches exactly these ladder rungs.  `designs`
            # warms a whole fleet's design set in one pass (engine.warm
            # groups entries by bucket signature, so N same-bucket
            # designs still compile each ladder rung exactly once)
            from raft_tpu.serve import engine as serve_engine

            entries = []
            if designs:
                for i, path in enumerate(designs):
                    # a mixed-kind warmup already built `model` for the
                    # first design — reuse it, don't pay a second YAML
                    # load + host build
                    m = (model if model is not None and path == design
                         else raft_tpu.Model(path))
                    entries.append(serve_engine.DesignEntry(
                        f"warmup{i}", m))
            else:
                entries.append(serve_engine.DesignEntry("warmup", model))
            reports += serve_engine.warm(entries, mesh=mesh,
                                         out_keys=tuple(out_keys))
    return reports
