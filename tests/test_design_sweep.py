"""Design-axis sweep and differentiability tests.

The north-star use case: batch *design variants* (not just sea states)
through one compiled program and differentiate response metrics with
respect to design parameters.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture(scope="module")
def evaluator():
    import raft_tpu
    from raft_tpu.api import make_design_evaluator

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "raft_tpu", "designs", "spar_demo.yaml")
    model = raft_tpu.Model(path)
    return make_design_evaluator(model)


def test_design_vmap(evaluator):
    """8 design variants in one vmapped program."""
    f = jax.jit(jax.vmap(lambda cd: evaluator(
        {"Hs": 6.0, "Tp": 12.0, "beta": 0.0, "Cd_scale": cd})["PSD"]))
    cds = jnp.linspace(0.5, 2.0, 8)
    psd = np.asarray(f(cds))
    assert psd.shape[0] == 8
    assert np.isfinite(psd).all()
    # more drag -> more damping -> smaller resonant response
    peak = psd[:, 0, :].max(axis=1)
    assert peak[0] > peak[-1]


@pytest.mark.slow
def test_design_gradient(evaluator):
    """Exact gradient of a response metric wrt a design parameter."""

    def metric(L_scale):
        out = evaluator({"Hs": 6.0, "Tp": 12.0, "beta": 0.0,
                         "L_moor_scale": L_scale})
        return jnp.sum(out["PSD"][0])  # integrated surge PSD

    # forward-mode (the fixed-point solves are lax.while_loops, which
    # support jvp but not reverse-mode; one design scalar -> jacfwd)
    g = jax.jacfwd(metric)(jnp.asarray(1.0))
    assert np.isfinite(float(g))
    # check against finite difference
    eps = 1e-4
    fd = (float(metric(1.0 + eps)) - float(metric(1.0 - eps))) / (2 * eps)
    assert abs(float(g) - fd) / (abs(fd) + 1e-9) < 5e-2


def test_reverse_mode_gradient(evaluator):
    """jax.grad (reverse mode) through the full evaluation: the statics
    Newton and drag-linearisation fixed points are wrapped in
    lax.custom_root (implicit differentiation), so gradients of response
    metrics wrt design parameters work in BOTH modes and agree with
    finite differences (the gradient-based L6 design-optimization
    story, SURVEY.md §7.1)."""
    import jax

    evaluate = evaluator

    def metric(Ls):
        out = evaluate(dict(Hs=6.0, Tp=12.0, beta=0.3, L_moor_scale=Ls))
        return jnp.sum(jnp.abs(out["Xi"][0]) ** 2) + jnp.sum(out["X0"] ** 2)

    g_rev = float(jax.grad(metric)(1.0))
    g_fwd = float(jax.jacfwd(metric)(1.0))
    assert g_rev == pytest.approx(g_fwd, rel=1e-10)
    eps = 1e-4
    fd = float((metric(1.0 + eps) - metric(1.0 - eps)) / (2 * eps))
    assert g_rev == pytest.approx(fd, rel=2e-3)
