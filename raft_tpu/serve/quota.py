"""Per-client quotas for the evaluation service.

Backpressure has two layers with distinct HTTP semantics:

* **quota** (this module): a per-client token bucket — sustained rate
  ``RAFT_TPU_SERVE_QPS`` with burst capacity ``RAFT_TPU_SERVE_BURST``.
  A client over its bucket gets **429** (its problem: slow down); other
  clients are unaffected.
* **admission queue** (:mod:`raft_tpu.serve.batcher`): one bounded
  pending queue for the whole service.  A full queue gets **503** (the
  server's problem: every client should back off) — the queue bound is
  what keeps a load spike from growing an unbounded backlog of
  accepted-but-unserved work.

Pure stdlib, no jax.  The clock is injectable for tests.
"""

from __future__ import annotations

import threading
import time


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second refill, ``burst``
    capacity, one token per request.  ``rate <= 0`` disables the
    bucket (every acquire succeeds)."""

    __slots__ = ("rate", "burst", "_tokens", "_t", "_lock", "_clock")

    def __init__(self, rate, burst, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self._lock = threading.Lock()
        self._tokens = self.burst  # raft-lint: guarded-by=self._lock
        self._clock = clock
        self._t = clock()  # raft-lint: guarded-by=self._lock

    def acquire(self, n=1):
        """Take ``n`` tokens; False when the bucket is dry."""
        if self.rate <= 0:
            return True
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def refund(self, n=1):
        """Return ``n`` tokens (clamped to the burst capacity): a
        request rejected AFTER its quota debit — admission queue full,
        service draining — must not also eat the client's budget."""
        if self.rate <= 0:
            return
        with self._lock:
            self._tokens = min(self.burst, self._tokens + n)

    def retry_after_s(self):
        """Suggested client back-off (the ``Retry-After`` header):
        time until one token refills."""
        if self.rate <= 0:
            return 0.0
        with self._lock:
            missing = max(0.0, 1.0 - self._tokens)
        return missing / self.rate


class ClientQuotas:
    """Lazily-created per-client token buckets keyed by client id (the
    ``X-Client`` header when present, else the peer address).  Client
    maps are bounded: the least-recently-seen bucket is dropped past
    ``max_clients`` — a full bucket is the steady state for an absent
    client anyway, so re-creating it later is semantically free."""

    def __init__(self, rate, burst, max_clients=4096, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._max = int(max_clients)
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}  # raft-lint: guarded-by=self._lock

    def bucket(self, client):
        client = str(client or "anonymous")
        with self._lock:
            b = self._buckets.get(client)
            if b is None:
                if len(self._buckets) >= self._max:
                    self._buckets.pop(next(iter(self._buckets)))
                b = self._buckets[client] = TokenBucket(
                    self.rate, self.burst, clock=self._clock)
            else:
                # refresh recency (plain dicts iterate in insert order)
                self._buckets.pop(client)
                self._buckets[client] = b
            return b

    def acquire(self, client):
        return self.bucket(client).acquire()
