"""Every violation here carries a suppression: the file must lint clean.

# raft-lint: disable-file=env-read
"""

import os

import jax.numpy as jnp
import numpy as np


def audited(nw, x):
    a = jnp.zeros(nw, dtype=complex)  # raft-lint: disable=dtype-literal
    # raft-lint: disable=dtype-literal
    b = np.zeros(nw, dtype=complex)
    y = jnp.sum(x)
    # raft-lint: disable=host-coercion
    v = float(y)
    flag = os.environ.get("RAFT_TPU_SOLVER")  # file-level suppression
    return a, b, v, flag
