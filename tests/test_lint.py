"""Unit tests for the trace-hygiene AST linter
(:mod:`raft_tpu.analysis.lint`): every rule on seeded good/bad fixture
snippets, the suppression syntax, the CLI exit codes, and the CI gate
itself (the repo must lint clean).

Pure-AST: no jax import, no backend.
"""

import os
import subprocess
import sys

import pytest

from raft_tpu.analysis import lint

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "fixtures", "lint")
ALL_RULES = set(lint.RULES)


def run_fixture(name):
    return lint.lint_file(os.path.join(FIXTURES, name), rules=ALL_RULES)


def rules_by_line(findings):
    return {(f.line, f.rule) for f in findings}


def test_bad_dtype_fixture():
    found = run_fixture("bad_dtype.py")
    assert {f.rule for f in found} == {"dtype-literal"}
    assert {f.line for f in found} == {9, 10, 11, 12, 13, 18}


def test_good_dtype_fixture_clean():
    assert run_fixture("good_dtype.py") == []


def test_bad_coercion_fixture():
    found = run_fixture("bad_coercion.py")
    assert {f.rule for f in found} == {"host-coercion"}
    # shape/len metadata access must NOT be flagged (lines 24-25)
    assert {f.line for f in found} == {10, 11, 17, 18}


def test_bad_env_fixture():
    found = run_fixture("bad_env.py")
    assert {f.rule for f in found} == {"env-read"}
    assert {f.line for f in found} == {8, 9, 10}


def test_bad_jit_fixture():
    found = run_fixture("bad_jit.py")
    assert {f.rule for f in found} == {"jit-static"}
    assert {f.line for f in found} == {15, 16}
    assert any("out_keys" in f.message for f in found)
    assert any("mode" in f.message for f in found)


def test_bad_event_fixture():
    found = run_fixture("bad_event.py")
    assert {f.rule for f in found} == {"event-name"}
    # registered names (8, 12) and the dynamic name (11) are clean
    assert {f.line for f in found} == {9, 10}
    assert any("shard_don" in f.message for f in found)


def test_bad_span_fixture():
    found = run_fixture("bad_span.py")
    assert {f.rule for f in found} == {"span-name"}
    # registered name (13) and the dynamic name (15) are clean
    assert {f.line for f in found} == {9, 11}
    assert any("shrad" in f.message for f in found)
    assert any("sweep_dispach" in f.message for f in found)


def test_span_registry_covers_runtime_emitters():
    """Every literal span name in the scanned tree is registered in the
    SPANS table, and the table describes each name."""
    from raft_tpu.obs import events

    findings = [f for f in lint.lint_paths() if f.rule == "span-name"]
    assert findings == [], "\n".join(f.format() for f in findings)
    for name, help_ in events.describe_spans():
        assert help_, name


def test_event_registry_covers_runtime_emitters():
    """Every literal log_event name in the scanned tree is registered
    (the CI-gate property the rule exists for), and the registry itself
    describes fields for each name."""
    from raft_tpu.obs import events

    findings = [f for f in lint.lint_paths() if f.rule == "event-name"]
    assert findings == [], "\n".join(f.format() for f in findings)
    for name, fields, help_ in events.describe():
        assert fields and help_, name


def test_suppressions_silence_findings():
    assert run_fixture("suppressed.py") == []


def test_finding_format_is_file_line_col():
    f = run_fixture("bad_env.py")[0]
    path, line, col, rest = f.format().split(":", 3)
    assert path.endswith("bad_env.py")
    assert int(line) == f.line and int(col) == f.col
    assert "[env-read]" in rest


def test_repo_lints_clean():
    """The CI gate: the default scan set has zero findings."""
    findings = lint.lint_paths()
    assert findings == [], "\n".join(f.format() for f in findings)


def test_env_read_allowed_in_registry():
    """The registry module itself is the sanctioned reader."""
    cfg = os.path.join(lint.repo_root(), "raft_tpu", "utils", "config.py")
    assert lint.lint_file(cfg) == []


@pytest.mark.parametrize("args,expected", [
    ([], 0),                                           # repo clean
    ([os.path.join(FIXTURES, "bad_env.py")], 1),       # findings -> 1
])
def test_cli_exit_codes(args, expected):
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", "lint", *args],
        capture_output=True, text=True,
        cwd=os.path.dirname(HERE))
    assert p.returncode == expected, p.stdout + p.stderr
    if expected == 1:
        # file:line findings on stdout
        assert "bad_env.py:8" in p.stdout
