"""Linear (Airy) wave kernels: dispersion, spectra, kinematics.

jax.numpy re-derivations of the reference wave layer
(``/root/reference/raft/helpers.py``: ``waveNumber`` :377,
``JONSWAP`` :703, ``getWaveKin`` :188, ``getRMS`` :678,
``getPSD`` :687, ``getRAO`` :762, ``getKinematics`` :149).

Design notes (TPU-first):
* The dispersion solve is a *fixed-iteration* self-consistent update
  (the reference iterates to a 1e-3 relative tolerance; 30 fixed
  iterations of the same update map is far past that tolerance for any
  physical (omega, h) and keeps the op trace-static so it fuses under
  ``vmap`` over the frequency axis).
* The branchy deep/shallow-water guards of ``getWaveKin``
  (helpers.py:211-222) become ``jnp.where`` ladders with operands
  sanitised before ``sinh``/``cosh`` so no overflow occurs on the
  untaken branch.
* Everything broadcasts: kinematics evaluate at arbitrary batches of
  points x frequencies in one fused expression.
"""

from __future__ import annotations

import jax.numpy as jnp


def wave_number(w, h, g=9.81, n_iter=12):
    """Solve the linear dispersion relation w^2 = g k tanh(k h).

    helpers.py:377-392 uses the fixed-point map ``k <- w^2/(g tanh(k h))``
    to a 1e-3 tolerance, which oscillates without converging in shallow
    water.  We instead run a fixed ``n_iter`` Newton iterations on
    ``f(k) = g k tanh(k h) - w^2`` from the Eckart seed — this converges
    to double precision for any physical (w, h) (same root the reference
    finds where its map converges) and is shape-static for jit/vmap.

    w : (...,) angular frequency [rad/s]; h : depth [m].
    """
    w = jnp.asarray(w)
    w2g = w * w / g
    # Eckart (1952) approximation as the Newton seed
    arg = jnp.maximum(w2g * h, 1e-30)
    k = w2g / jnp.sqrt(jnp.tanh(arg))

    for _ in range(n_iter):
        kh = jnp.minimum(k * h, 50.0)  # tanh saturates; keep sech2 stable
        th = jnp.tanh(kh)
        sech2 = 1.0 - th * th
        f = k * th - w2g
        fp = th + kh * sech2
        fp = jnp.where(fp == 0, 1.0, fp)
        k = jnp.maximum(k - f / fp, 0.0)
    return k


def wave_number_ref(w, h, g=9.81, e=0.001):
    """Build-time (numpy) twin of the reference's dispersion iteration,
    including its loose 1e-3 stopping rule (helpers.py:377-392) — used
    for the model frequency grid so golden values match bit-for-bit.
    The traced kernels use :func:`wave_number` (full precision)."""
    import numpy as np

    w = np.atleast_1d(np.asarray(w, dtype=float))
    k = np.zeros_like(w)
    for i, wi in enumerate(w):
        k1 = wi * wi / g
        if k1 == 0.0:
            k[i] = 0.0
            continue
        k2 = wi * wi / (np.tanh(k1 * h) * g)
        while abs(k2 - k1) / k1 > e:
            k1 = k2
            k2 = wi * wi / (np.tanh(k1 * h) * g)
        k[i] = k2
    return k


def jonswap(ws, Hs, Tp, gamma=None):
    """One-sided JONSWAP spectrum S(w) [m^2/(rad/s)]; helpers.py:703-760.

    ``gamma=None`` (or 0) applies the IEC 61400-3 default as a function
    of Tp/sqrt(Hs); gamma=1 gives Pierson-Moskowitz.
    """
    ws = jnp.asarray(ws)
    if gamma is None:
        gamma = 0.0
    gamma = jnp.asarray(gamma, dtype=ws.dtype)
    # IEC default when gamma falsy (reference treats `not Gamma` i.e. 0/None)
    TpOvrSqrtHs = Tp / jnp.sqrt(Hs)
    gamma_iec = jnp.where(
        TpOvrSqrtHs <= 3.6,
        5.0,
        jnp.where(TpOvrSqrtHs >= 5.0, 1.0, jnp.exp(5.75 - 1.15 * TpOvrSqrtHs)),
    )
    gamma = jnp.where(gamma == 0, gamma_iec, gamma)

    f = 0.5 / jnp.pi * ws
    fpOvrf4 = (Tp * f) ** -4.0
    C = 1.0 - 0.287 * jnp.log(gamma)
    sigma = jnp.where(f <= 1.0 / Tp, 0.07, 0.09)
    alpha = jnp.exp(-0.5 * ((f * Tp - 1.0) / sigma) ** 2)
    return 0.5 / jnp.pi * C * 0.3125 * Hs * Hs * fpOvrf4 / f * jnp.exp(-1.25 * fpOvrf4) * gamma**alpha


def _kh_ratios(k, h, z):
    """The three transfer ratios of getWaveKin (helpers.py:209-222):

    sinh(k(z+h))/sinh(kh), cosh(k(z+h))/sinh(kh), cosh(k(z+h))/cosh(kh)

    with the reference's guards: k==0 -> (1, 99999, 99999); k*h > 89.4 ->
    numerically-stable deep-water forms.  Arguments are clamped before
    sinh/cosh so the untaken branch cannot overflow.
    """
    kh = k * h
    deep = kh > 89.4
    kzero = k == 0.0

    arg_zh = jnp.where(deep | kzero, 0.0, k * (z + h))
    arg_h = jnp.where(deep | kzero, 1.0, kh)
    sinh_den = jnp.sinh(arg_h)
    SINH = jnp.sinh(arg_zh) / sinh_den
    COSHs = jnp.cosh(arg_zh) / sinh_den
    COSHc = jnp.cosh(arg_zh) / jnp.cosh(arg_h)

    ekz = jnp.exp(jnp.minimum(k * z, 0.0))
    # deep-water forms (helpers.py:215-218)
    SINH = jnp.where(deep, ekz, SINH)
    COSHs = jnp.where(deep, ekz, COSHs)
    COSHc = jnp.where(deep, ekz + jnp.exp(jnp.minimum(-k * (z + 2.0 * h), 0.0)), COSHc)
    # k == 0 (helpers.py:211-214)
    SINH = jnp.where(kzero, 1.0, SINH)
    COSHs = jnp.where(kzero, 99999.0, COSHs)
    COSHc = jnp.where(kzero, 99999.0, COSHc)
    return SINH, COSHs, COSHc


def wave_kinematics(zeta0, beta, w, k, h, r, rho=1025.0, g=9.81):
    """Complex amplitudes of wave velocity, acceleration and dynamic
    pressure at point(s) ``r``; helpers.py:188-236 ``getWaveKin``.

    Parameters
    ----------
    zeta0 : (..., nw) complex or real — wave elevation amplitude per freq
    beta  : scalar wave heading [rad]
    w, k  : (nw,) frequency [rad/s] and wave number [1/m]
    h     : depth [m]
    r     : (..., 3) evaluation point(s) (z <= 0 submerged)

    Returns
    -------
    u  : (..., 3, nw) complex velocity
    ud : (..., 3, nw) complex acceleration
    p  : (..., nw) complex dynamic pressure

    Points above the waterline (z > 0) get zero kinematics, matching the
    reference's ``if z <= 0`` guard (helpers.py:207).  Note the reference
    *does* phase-shift the local elevation for all points; only u/ud/p
    are zeroed.
    """
    r = jnp.asarray(r)
    x, y, z = r[..., 0:1], r[..., 1:2], r[..., 2:3]  # keep last dim for ω broadcast
    cosb, sinb = jnp.cos(beta), jnp.sin(beta)
    zeta = zeta0 * jnp.exp(-1j * (k * (cosb * x + sinb * y)))

    SINH, COSHs, COSHc = _kh_ratios(k, h, z)
    sub = z <= 0

    u_x = w * zeta * COSHs * cosb
    u_y = w * zeta * COSHs * sinb
    u_z = 1j * w * zeta * SINH
    u = jnp.stack([u_x, u_y, u_z], axis=-2)  # (..., 3, nw)
    u = jnp.where(sub[..., None, :], u, 0.0)
    ud = 1j * w * u
    p = jnp.where(sub, rho * g * zeta * COSHc, 0.0)
    return u, ud, p


def get_kinematics(r, Xi, w):
    """Node displacement/velocity/acceleration amplitudes from platform
    6-DOF motion amplitudes; helpers.py:149-184 ``getKinematics``.

    r : (..., 3) point relative to reference; Xi : (..., 6, nw); w: (nw,).
    Returns (dr, v, a) each (..., 3, nw).
    """
    th = Xi[..., 3:, :]  # (..., 3, nw)
    # th x r  per frequency: cross with r broadcast on the ω axis
    rr = jnp.broadcast_to(r[..., :, None], th.shape)
    rot = jnp.cross(th, rr, axis=-2)
    dr = Xi[..., :3, :] + rot
    v = 1j * w * dr
    a = 1j * w * v
    return dr, v, a


def get_rms(xi):
    """sqrt(0.5 * sum |xi|^2) over all axes; helpers.py:678-684."""
    return jnp.sqrt(0.5 * jnp.sum(jnp.abs(xi) ** 2))


def get_psd(xi, dw, axis=None):
    """Response PSD 0.5|xi|^2/dw, summed across excitation sources if a
    leading axis is given; helpers.py:687-700."""
    psd = 0.5 * jnp.abs(xi) ** 2 / dw
    if axis is not None:
        psd = jnp.sum(psd, axis=axis)
    return psd


def get_rao(Xi, zeta, eps=1e-6):
    """Response per unit wave amplitude with a small-amplitude guard;
    helpers.py:762-784."""
    ok = jnp.abs(zeta) > eps
    zsafe = jnp.where(ok, zeta, 1.0)
    return jnp.where(ok, Xi / zsafe, 0.0)
