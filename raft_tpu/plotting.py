"""Visualisation: system geometry and response spectra.

Equivalent of the reference's plotting layer (``/root/reference/raft/
raft_model.py``: ``plot`` :1532, ``plot2d`` :1599, ``plotResponses``
:1363; member/mooring renderers in the component classes).  Matplotlib
is imported lazily so headless/batch runs never pay for it.
"""

from __future__ import annotations

import numpy as np


def plot_system(model, ax=None, color="k", n_theta=12):
    """3-D render of members (as surface meshes), mooring lines
    (catenary profiles) and anchors for every FOWT."""
    import matplotlib.pyplot as plt

    if ax is None:
        fig = plt.figure(figsize=(9, 7))
        ax = fig.add_subplot(111, projection="3d")

    for i, fs in enumerate(model.fowtList):
        off = np.array([fs.x_ref, fs.y_ref, 0.0])
        for mem in fs.members:
            if mem.part_of == "nacelle":
                continue
            _plot_member(ax, mem, off, color=color, n_theta=n_theta)
        ms = model.ms_list[i]
        if ms is not None:
            for il in range(ms.n_lines):
                _plot_line(ax, ms.r_anchor[il], off + ms.r_fair0[il],
                           ms.L[il], ms.w[il], ms.EA[il])
    if model.ms_array is not None:
        net = model.ms_array
        import jax.numpy as jnp

        r6 = np.stack([[f.x_ref, f.y_ref, 0, 0, 0, 0] for f in model.fowtList])
        _, info = net.body_forces(jnp.asarray(r6, dtype=float))
        pos = np.asarray(net._point_positions(jnp.asarray(r6, dtype=float),
                                              info["r_free"]))
        for (a, b), L, w_l, EA in zip(net.l_ends, net.l_L, net.l_w, net.l_EA):
            _plot_line(ax, pos[a], pos[b], L, w_l, EA)

    ax.set_xlabel("x [m]")
    ax.set_ylabel("y [m]")
    ax.set_zlabel("z [m]")
    try:
        ax.set_box_aspect((1, 1, 0.5))
    except AttributeError:
        pass
    return ax


def _member_polylines(mem, off, n_theta=12):
    """Station rings + longitudinal lines of one member as 3-D
    polylines (shared by the 3-D renderer and the projected 2-D view)."""
    th = np.linspace(0, 2 * np.pi, n_theta + 1)
    rings = []
    for i in range(len(mem.stations)):
        c = off + np.asarray(mem.rA0) + np.asarray(mem.q0) * mem.stations[i]
        d = np.atleast_1d(np.asarray(mem.d[i], dtype=float))
        d = d if d.size == 2 else np.r_[d, d]
        rings.append(c[None, :]
                     + 0.5 * d[0] * np.cos(th)[:, None] * np.asarray(mem.p10)[None, :]
                     + 0.5 * d[1] * np.sin(th)[:, None] * np.asarray(mem.p20)[None, :])
    lines = [np.stack([r[k] for r in rings])
             for k in range(0, n_theta + 1, max(1, n_theta // 4))]
    return rings + lines


def _plot_member(ax, mem, off, color="k", n_theta=12):
    for pts in _member_polylines(mem, off, n_theta=n_theta):
        ax.plot(pts[:, 0], pts[:, 1], pts[:, 2], color=color, lw=0.5)


def _plot_line(ax, rA, rB, L, w_line, EA, n=30):
    """Catenary profile between two points (for rendering only)."""
    pts = _catenary_points(rA, rB, L, w_line, EA, n=n)
    ax.plot(pts[:, 0], pts[:, 1], pts[:, 2], color="tab:blue", lw=0.8)


def plot_responses(model, channels=("surge", "heave", "pitch"), ifowt=0):
    """Response PSDs per case (plotResponses equivalent)."""
    import matplotlib.pyplot as plt

    fig, axs = plt.subplots(len(channels), 1, sharex=True, figsize=(8, 2.5 * len(channels)))
    axs = np.atleast_1d(axs)
    f_hz = model.w / (2 * np.pi)
    for iCase, per_fowt in model.results["case_metrics"].items():
        m = per_fowt[ifowt]
        for ax, ch in zip(axs, channels):
            # rad/s-density PSDs on a Hz axis need the 2 pi conversion
            # (reference plotResponses, raft_model.py:1363); per-rotor
            # channels plot rotor 0
            psd = np.asarray(m[f"{ch}_PSD"])
            psd = psd[:, 0] if psd.ndim == 2 else psd
            ax.plot(f_hz, 2 * np.pi * psd, label=f"case {iCase + 1}")
            ax.set_ylabel(f"{ch} PSD")
    axs[0].legend()
    axs[-1].set_xlabel("frequency [Hz]")
    return fig, axs


def plot2d(model, ax=None, color="k", Xuvec=(1, 0, 0), Yuvec=(0, 0, 1),
           figsize=(6, 4), n_theta=12):
    """2-D projection of the whole system — member outlines and mooring
    catenary profiles projected onto the plane spanned by ``Xuvec`` /
    ``Yuvec`` (``Model.plot2d`` equivalent, raft_model.py:1599-1630;
    the default is the x-z side view)."""
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots(1, 1, figsize=figsize)
    else:
        fig = ax.get_figure()
    Xu = np.asarray(Xuvec, dtype=float)
    Yu = np.asarray(Yuvec, dtype=float)

    proj = lambda pts: (pts @ Xu, pts @ Yu)

    for i, fs in enumerate(model.fowtList):
        off = np.array([fs.x_ref, fs.y_ref, 0.0])
        for mem in fs.members:
            if mem.part_of == "nacelle":
                continue
            for pts in _member_polylines(mem, off, n_theta=n_theta):
                x, y = proj(pts)
                ax.plot(x, y, color=color, lw=0.5)
        ms = model.ms_list[i]
        if ms is not None:
            for il in range(ms.n_lines):
                pts = _catenary_points(ms.r_anchor[il], off + ms.r_fair0[il],
                                       ms.L[il], ms.w[il], ms.EA[il])
                x, y = proj(pts)
                ax.plot(x, y, color="tab:blue", lw=0.8)
    # shared-mooring network lines (arrays), as in plot_system
    if model.ms_array is not None:
        import jax.numpy as jnp

        net = model.ms_array
        r6 = np.stack([[f.x_ref, f.y_ref, 0, 0, 0, 0] for f in model.fowtList])
        _, info = net.body_forces(jnp.asarray(r6, dtype=float))
        pos = np.asarray(net._point_positions(jnp.asarray(r6, dtype=float),
                                              info["r_free"]))
        for (a, b), L, w_l, EA in zip(net.l_ends, net.l_L, net.l_w, net.l_EA):
            pts = _catenary_points(pos[a], pos[b], L, w_l, EA)
            x, y = proj(pts)
            ax.plot(x, y, color="tab:blue", lw=0.8)
    ax.axis("equal")
    ax.set_xlabel("[m]")
    ax.set_ylabel("[m]")
    return fig, ax


def _catenary_points(rA, rB, L, w_line, EA, n=30):
    """Catenary profile polyline between two points (shared by the 3-D
    and 2-D renderers)."""
    import jax.numpy as jnp

    from raft_tpu.physics.mooring import _profile, solve_catenary

    lo, hi = (rA, rB) if rA[2] <= rB[2] else (rB, rA)
    dv = np.asarray(hi) - np.asarray(lo)
    XF = max(np.hypot(dv[0], dv[1]), 1e-6)
    uh = dv[:2] / XF
    HF, VF, _, _ = solve_catenary(
        jnp.asarray(XF), jnp.asarray(dv[2]), jnp.asarray(float(L)),
        jnp.asarray(float(w_line)), jnp.asarray(float(EA)))
    s = np.linspace(0, float(L), n)
    xs, zs = [], []
    for si in s:
        VFs = float(VF) - float(w_line) * (float(L) - si)
        x, z = _profile(jnp.asarray(float(HF)), jnp.asarray(max(VFs, 0.0)),
                        jnp.asarray(si), jnp.asarray(float(w_line)),
                        jnp.asarray(float(EA)))
        xs.append(float(x))
        zs.append(float(z))
    xs = np.clip(np.asarray(xs), 0, XF)
    zs = np.asarray(zs)
    return np.stack([np.asarray(lo)[0] + uh[0] * xs,
                     np.asarray(lo)[1] + uh[1] * xs,
                     np.asarray(lo)[2] + zs], axis=1)


def plot_responses_extended(model, ifowt=0):
    """9-panel PSD figure of the standard response channels per case
    (``Model.plotResponses_extended`` equivalent,
    raft_model.py:1463-1530)."""
    return plot_responses(
        model,
        channels=("surge", "sway", "heave", "pitch", "roll", "yaw", "AxRNA",
                  "Mbase", "wave"),
        ifowt=ifowt)
