"""Full-physics traced evaluator parity vs the orchestrated Model path.

``api.make_full_evaluator`` folds the entire per-case chain — aero-servo
constants, potential-flow A/B/X, multi-heading Morison excitation,
external-QTF 2nd-order forces, equilibrium with environmental mean
loads, drag-linearised impedance solve, multi-source response — into
one jit.  These tests assert it reproduces the (golden-validated)
orchestrated ``Model.solve_statics``/``solve_dynamics`` results on the
north-star workloads:

* VolturnUS-S example, operating turbine in wind (aero path),
* OC4semi with WAMIT coefficients (potential-flow path),
* OC4semi external .12d QTF (2nd-order path), multi-heading.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import raft_tpu
from raft_tpu.api import case_to_traced as traced_case
from raft_tpu.api import make_full_evaluator

EXAMPLES = "/root/reference/examples"


def assert_parity(model, case, nWaves=1, rtol=1e-9):
    X0_o = model.solve_statics(case)
    Xi_o, info = model.solve_dynamics(case, X0=X0_o)
    if model.qtf is not None:
        X0_o = model.solve_statics(
            case, extra_force=np.sum(model._last_drift_mean, axis=0))
    evaluate = jax.jit(make_full_evaluator(model, nWaves=nWaves))
    out = evaluate(traced_case(case, nWaves))
    scale_X = np.max(np.abs(np.asarray(X0_o))) + 1e-12
    np.testing.assert_allclose(np.asarray(out["X0"]), np.asarray(X0_o),
                               atol=rtol * scale_X, rtol=0)
    Xi_o = np.asarray(Xi_o)
    Xi_t = np.asarray(out["Xi"])
    scale = np.max(np.abs(Xi_o))
    np.testing.assert_allclose(Xi_t, Xi_o, atol=rtol * scale, rtol=0)
    return out


@pytest.mark.slow
def test_volturn_wind_case():
    """Operating turbine in turbulent wind: the aero constants
    (A/B_aero, gyroscopics, mean thrust into the equilibrium) flow
    through the traced path identically."""
    model = raft_tpu.Model(os.path.join(EXAMPLES, "VolturnUS-S_example.yaml"))
    case = dict(model.cases[0])
    case.update(wind_speed=16.0, turbulence=0.1, wave_heading=30.0,
                wave_height=6.0, wave_period=12.0)
    assert_parity(model, case)


@pytest.mark.slow
def test_oc4_wamit_case():
    """potModMaster=3 with WAMIT .1/.3 coefficients + external .12d QTF:
    BEM excitation w/ heading interpolation and the 2nd-order force
    realization run in-trace."""
    model = raft_tpu.Model(os.path.join(EXAMPLES, "OC4semi-WAMIT_Coefs.yaml"))
    case = dict(model.cases[0])
    out = assert_parity(model, case)
    # the 2nd-order path must actually be active
    assert model.qtf is not None
    assert float(np.max(np.abs(np.asarray(out["F_2nd_mean"])))) > 0


@pytest.mark.slow
def test_oc4_wamit_multiheading():
    """Two wave headings: per-heading excitation/response parity."""
    model = raft_tpu.Model(os.path.join(EXAMPLES, "OC4semi-WAMIT_Coefs.yaml"))
    case = dict(model.cases[0])
    case.update(wave_heading=[0.0, 45.0], wave_height=[6.0, 3.0],
                wave_period=[12.0, 9.0], wave_spectrum=["JONSWAP", "JONSWAP"])
    assert_parity(model, case, nWaves=2)


def test_spar_jit_and_vmap():
    """The full evaluator jits once and vmaps over a case batch."""
    here = os.path.dirname(os.path.abspath(__file__))
    model = raft_tpu.Model(os.path.join(here, "..", "raft_tpu", "designs",
                                        "spar_demo.yaml"))
    evaluate = make_full_evaluator(model)
    fn = jax.jit(jax.vmap(lambda h, t, b: evaluate(
        dict(Hs=h, Tp=t, beta_deg=b))["PSD"]))
    B = 4
    out = fn(jnp.linspace(2, 8, B), jnp.linspace(8, 16, B), jnp.zeros(B))
    assert out.shape == (B, 6, model.nw)
    assert bool(jnp.all(jnp.isfinite(out)))
