"""Intersection-curve re-meshing (the functional core of the
reference's boolean-union mesher, IntersectionMesh.py:139).

Geometry under test: a vertical column (R = 5 m) pierced by a
horizontal pontoon (R = 2 m) — the OC4-style column/pontoon junction.
The wetted surface of the union is known semi-analytically by dense
surface sampling (independent of the mesher), so the clipped mesh's
total area quantifies junction accuracy directly:

* whole-panel removal (clip_depth = 0, the round-4 stand-in) leaves
  panel-sized holes/overlaps along the intersection curve;
* recursive subdivision-clipping converges the area to the true union
  as depth grows.
"""

from types import SimpleNamespace

import numpy as np
import pytest

from raft_tpu.io.panels import (_point_in_any, clip_intersecting_panels,
                                mesh_cylinder, point_in_member)

R_COL, R_PON = 5.0, 2.0
Z_PON = -8.0


def _column():
    return SimpleNamespace(
        rA0=np.array([0.0, 0.0, -12.0]), rB0=np.array([0.0, 0.0, 0.0]),
        q0=np.array([0.0, 0.0, 1.0]), circular=True,
        stations=np.array([0.0, 12.0]),
        d=np.full((2, 2), 2 * R_COL),
        p10=np.array([1.0, 0.0, 0.0]), p20=np.array([0.0, 1.0, 0.0]))


def _pontoon():
    return SimpleNamespace(
        rA0=np.array([-15.0, 0.0, Z_PON]), rB0=np.array([15.0, 0.0, Z_PON]),
        q0=np.array([1.0, 0.0, 0.0]), circular=True,
        stations=np.array([0.0, 30.0]),
        d=np.full((2, 2), 2 * R_PON),
        p10=np.array([0.0, 1.0, 0.0]), p20=np.array([0.0, 0.0, 1.0]))


def _meshes(n_az=24, dz=1.0):
    members = [_column(), _pontoon()]
    vs, ns_, owner = [], [], []
    for im, m in enumerate(members):
        v, c, n, a = mesh_cylinder(
            stations=m.stations, diameters=m.d[:, 0], rA=m.rA0, q=m.q0,
            n_az=n_az, dz_max=dz)
        vs.append(np.asarray(v))
        ns_.append(np.asarray(n))
        owner.append(np.full(len(a), im))
    return (members, np.concatenate(vs), np.concatenate(ns_),
            np.concatenate(owner))


def _reference_area(members, verts, owner, n_sub=24):
    """EXACT clipping of the faceted surface by dense per-panel
    bilinear subdivision (n_sub x n_sub sub-cells, outside-fraction by
    sub-cell centers) — the limit the recursive clipping must converge
    to, free of cylinder-faceting bias."""
    u = (np.arange(n_sub + 1)) / n_sub
    total = 0.0
    for i in range(len(verts)):
        q = verts[i]
        # bilinear surface P(u,v)
        P = ((1 - u)[:, None, None] * (1 - u)[None, :, None] * q[0]
             + u[:, None, None] * (1 - u)[None, :, None] * q[1]
             + u[:, None, None] * u[None, :, None] * q[2]
             + (1 - u)[:, None, None] * u[None, :, None] * q[3])
        d1 = P[1:, 1:] - P[:-1, :-1]
        d2 = P[:-1, 1:] - P[1:, :-1]
        cell_a = 0.5 * np.linalg.norm(np.cross(d1, d2), axis=-1)
        centers = 0.25 * (P[1:, 1:] + P[:-1, :-1] + P[:-1, 1:] + P[1:, :-1])
        outside = ~_point_in_any(centers.reshape(-1, 3), members,
                                 int(owner[i]))
        total += float(np.sum(cell_a.reshape(-1) * outside))
    return total


def test_junction_area_converges_to_union():
    members, verts, norms, owner = _meshes()
    ref = _reference_area(members, verts, owner)
    errs = []
    for depth in (0, 1, 2, 3):
        _, _, _, areas = clip_intersecting_panels(
            verts, norms, members, owner, max_depth=depth)
        errs.append(abs(float(np.sum(areas)) - ref) / ref)
    # subdivision-clipping must land within 0.5% of the exact-clipping
    # limit (measured: ~5e-4 at depth >= 1) and beat whole-panel
    # removal (depth 0) at every depth; the error is not strictly
    # monotone in depth because it crosses zero as the staircase
    # approximation straddles the true curve
    assert errs[3] < 5e-3, errs
    assert errs[0] > 2 * errs[3], errs
    assert all(e < errs[0] for e in errs[1:]), errs


def test_clipped_mesh_has_no_interior_centroids():
    """No retained centroid may lie strictly inside BOTH members — that
    region is the interior of the intersection volume, which the
    boolean union removes.  (A centroid may register as inside its OWN
    member: sub-panel centroids of a curved surface sit on chords
    slightly below the true radius.)"""
    members, verts, norms, owner = _meshes()
    v2, c2, n2, a2 = clip_intersecting_panels(
        verts, norms, members, owner, max_depth=3)
    inside0 = point_in_member(c2, members[0])
    inside1 = point_in_member(c2, members[1])
    assert len(c2) > len(verts) * 0.5
    assert not np.any(inside0 & inside1)


@pytest.mark.slow
def test_junction_added_mass_mesh_convergence():
    """Quantified hydro agreement at the junction: infinite-frequency
    added mass from the native panel solver on the clipped union mesh
    converges under clip-depth refinement, and whole-panel removal
    (depth 0) sits further from the converged value than depth 2 —
    the potential-flow solution near member junctions is validated by
    mesh convergence (VERDICT r4 missing #1)."""
    import shutil

    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")
    from raft_tpu.native import radiation_added_mass

    members, verts, norms, owner = _meshes(n_az=20, dz=1.2)

    def A33(depth):
        v2, c2, n2, a2 = clip_intersecting_panels(
            verts, norms, members, owner, max_depth=depth)
        A = radiation_added_mass(v2, c2, n2, a2, mirror=-1)
        return float(np.asarray(A)[2, 2])

    a0, a2, a3 = A33(0), A33(2), A33(3)
    assert a3 > 0
    assert abs(a2 - a3) / a3 < 0.02, (a0, a2, a3)
    assert abs(a0 - a3) > abs(a2 - a3), (a0, a2, a3)


def test_normals_inherited_outward():
    """Leaf panels keep the parent's outward orientation."""
    members, verts, norms, owner = _meshes()
    v2, c2, n2, a2 = clip_intersecting_panels(
        verts, norms, members, owner, max_depth=2)
    # outwardness proxy: for the column, radial component of the normal
    # is positive for its side panels (centroid x,y direction)
    col = np.abs(c2[:, 2] - Z_PON) > R_PON + 0.5  # away from the junction
    col &= np.hypot(c2[:, 0], c2[:, 1]) > 0.9 * R_COL
    rad = c2[col][:, :2] / np.linalg.norm(c2[col][:, :2], axis=1)[:, None]
    dots = np.sum(n2[col][:, :2] * rad, axis=1)
    assert np.all(dots > 0.5)
