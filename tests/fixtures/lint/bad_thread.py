"""Seeded negatives for the ``thread-hygiene`` concurrency rule."""

import threading


def spawn_anonymous(fn):
    t = threading.Thread(target=fn)     # no daemon, no name, no join
    t.start()
    return t


class NoStopSampler(threading.Thread):  # no stop/join path
    def __init__(self):
        super().__init__()              # and no daemon/name either

    def run(self):
        pass


class GoodSampler(threading.Thread):
    def __init__(self):
        super().__init__(name="fixture-sampler", daemon=True)
        self._stop_evt = threading.Event()

    def run(self):
        self._stop_evt.wait()

    def stop(self):
        self._stop_evt.set()
        self.join(timeout=2.0)


def spawn_joined(fn):
    worker = threading.Thread(target=fn, daemon=True, name="fixture-w")
    worker.start()
    worker.join(timeout=1.0)
    return worker
