"""Top-level Model: frequency grid, FOWTs, load-case analysis.

The array-level equivalent of the reference Model
(``/root/reference/raft/raft_model.py:27-2245``).  Round-1 scope:
single-FOWT construction, statics, Morison hydro and the dynamics
solve; arrays/farms and potential flow wired in later milestones.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from raft_tpu.models.fowt import FOWTStructure
from raft_tpu.models.hydro import FOWTHydro
from raft_tpu.models.statics_solve import solve_equilibrium
from raft_tpu.physics.mooring import build_mooring
from raft_tpu.physics.statics import calc_statics
from raft_tpu.structure.schema import coerce, frequency_grid, load_design, parse_cases
from raft_tpu.ops.waves import wave_number_ref


class Model:
    def __init__(self, design, base_dir=None):
        """``base_dir``: directory for resolving relative data paths
        (MoorDyn files, WAMIT coefficients) when ``design`` is an
        already-loaded dict; inferred from the file location when
        ``design`` is a path."""
        self.base_dir = base_dir
        if isinstance(design, str):
            import os

            self.base_dir = os.path.dirname(os.path.abspath(design))
        design = load_design(design)
        self.design = design

        settings = design.get("settings", {}) or {}
        self.XiStart = coerce(settings, "XiStart", default=0.1)
        self.nIter = int(coerce(settings, "nIter", default=15, dtype=int))
        # optional extra under-relaxed iterations past the reference cap,
        # taken only when unconverged (golden parity needs the default 0;
        # see models/dynamics.py solve_dynamics_fowt)
        self.nIterExtra = int(coerce(settings, "nIterExtra", default=0,
                                     dtype=int))

        self.w = frequency_grid(design)
        self.nw = len(self.w)
        self.depth = float(coerce(design["site"], "water_depth"))
        # reference-compatible dispersion solve (loose 1e-3 iteration,
        # raft_model.py:63-65) so downstream values match golden data
        self.k = wave_number_ref(self.w, self.depth)

        # second-order QTF frequency grid (raft_fowt.py:410-425)
        platform0 = design.get("platform") or (design.get("platforms") or [{}])[0]
        # QTF/RAO checkpoint folder (raft_fowt.py:434-436): when set,
        # computed slender-body QTFs are persisted as WAMIT .12d and the
        # converged motion RAOs as .4 next to them
        self.out_folder_qtf = platform0.get("outFolderQTF")
        if "min_freq2nd" in platform0 and "max_freq2nd" in platform0:
            mf2 = platform0["min_freq2nd"]
            Mf2 = platform0["max_freq2nd"]
            df2 = platform0.get("df_freq2nd", mf2)
            self.w1_2nd = np.arange(mf2, Mf2 + 0.5 * mf2, df2) * 2 * np.pi
            self.k1_2nd = wave_number_ref(self.w1_2nd, self.depth)
        else:
            self.w1_2nd = None
            self.k1_2nd = None

        self.cases = parse_cases(design)

        # ---- FOWT list: single-unit or array mode (raft_model.py:67-162)
        self.fowtList = []
        self.ms_list = []
        self.ms_array = None
        if "array" in design:
            if "turbine" in design and "turbines" not in design:
                design["turbines"] = [design["turbine"]]
            if "platform" in design and "platforms" not in design:
                design["platforms"] = [design["platform"]]
            if "mooring" in design and "moorings" not in design:
                design["moorings"] = [design["mooring"]]
            fowtInfo = [dict(zip(design["array"]["keys"], row))
                        for row in design["array"]["data"]]
            for info in fowtInfo:
                design_i = {"site": design["site"],
                            "settings": design.get("settings", {})}
                if info["turbineID"] != 0:
                    design_i["turbine"] = design["turbines"][info["turbineID"] - 1]
                design_i["platform"] = design["platforms"][info["platformID"] - 1]
                if info["mooringID"] != 0:
                    design_i["mooring"] = design["moorings"][info["mooringID"] - 1]
                fs = FOWTStructure(
                    design_i, depth=self.depth,
                    x_ref=info["x_location"], y_ref=info["y_location"],
                    heading_adjust=info.get("heading_adjust", 0),
                )
                self.fowtList.append(fs)
                if "mooring" in design_i and isinstance(design_i["mooring"], dict):
                    self.ms_list.append(build_mooring(
                        design_i["mooring"], rho_water=fs.rho_water, g=fs.g,
                        x_ref=info["x_location"], y_ref=info["y_location"],
                        heading_adjust=info.get("heading_adjust", 0)))
                else:
                    self.ms_list.append(None)
            if "array_mooring" in design and design["array_mooring"].get("file"):
                import os

                from raft_tpu.physics.mooring import parse_moordyn

                fpath = self._resolve_data_path(design["array_mooring"]["file"])
                bpath = design["array_mooring"].get("bathymetry")
                if bpath:
                    bpath = self._resolve_data_path(bpath)
                self.ms_array = parse_moordyn(
                    fpath, self.depth, rho=self.fowtList[0].rho_water,
                    g=self.fowtList[0].g, bathymetry=bpath)
        else:
            self.fowtList.append(FOWTStructure(design, depth=self.depth))
            fs = self.fowtList[0]
            if "mooring" in design and isinstance(design["mooring"], dict):
                mo = design["mooring"]
                if "file" in mo and "lines" not in mo:
                    # MoorDyn-file mooring: simple vessel-anchor files
                    # become a full MooringSystem (all moorMod levels,
                    # incl. the lumped-mass tension/impedance paths);
                    # files with free/shared points take the
                    # quasi-static network treatment
                    import os

                    from raft_tpu.physics.mooring import (parse_moordyn,
                                                          parse_moordyn_system)

                    fpath = self._resolve_data_path(mo["file"])
                    depth_mo = coerce(mo, "water_depth", default=self.depth)
                    try:
                        self.ms_list.append(parse_moordyn_system(
                            fpath, depth_mo, rho=fs.rho_water, g=fs.g,
                            moorMod=coerce(mo, "moorMod", default=0,
                                           dtype=int)))
                    except ValueError:
                        self.ms_list.append(parse_moordyn(
                            fpath, depth_mo, rho=fs.rho_water, g=fs.g))
                else:
                    self.ms_list.append(
                        build_mooring(mo, rho_water=fs.rho_water, g=fs.g))
            else:
                self.ms_list.append(None)

        self.nFOWT = len(self.fowtList)
        self.nDOF = sum(f.nDOF for f in self.fowtList)
        self.dof_offsets = np.cumsum([0] + [f.nDOF for f in self.fowtList])
        self.ms = self.ms_list[0]  # single-FOWT convenience alias

        self._hydro = None
        self._statics = None
        self._rotor_aero = None
        self._aero_cache = {}

    # ------------------------------------------------------------ lazy state
    @property
    def hydro(self):
        if self._hydro is None:
            self._hydro = [FOWTHydro(f, self.w, self.k) for f in self.fowtList]
        return self._hydro

    def statics(self, ifowt=0, Xi0=None):
        """FOWT statics matrices (cached at the zero pose; eager build
        work pinned to the host backend)."""
        from raft_tpu.utils.devices import on_cpu, to_host

        if Xi0 is None:
            if self._statics is None:
                with on_cpu():
                    self._statics = [
                        to_host(calc_statics(f)) for f in self.fowtList
                    ]
            return self._statics[ifowt]
        return calc_statics(self.fowtList[ifowt], Xi0)

    def _mooring_closures(self):
        """Total mooring force/stiffness over all FOWTs + shared lines."""
        from raft_tpu.physics.mooring import mooring_force, mooring_stiffness

        offs = self.dof_offsets

        def force(X):
            F = jnp.zeros(self.nDOF)
            for i, ms in enumerate(self.ms_list):
                if ms is not None:
                    Fm, _ = mooring_force(ms, X[offs[i]:offs[i] + 6])
                    F = F.at[offs[i]:offs[i] + 6].add(Fm)
            if self.ms_array is not None:
                r6_all = jnp.stack(
                    [X[offs[i]:offs[i] + 6] for i in range(self.nFOWT)]
                )
                Fa, _ = self.ms_array.body_forces(r6_all)
                for i in range(self.nFOWT):
                    F = F.at[offs[i]:offs[i] + 6].add(Fa[i])
            return F

        def stiff(X):
            K = jnp.zeros((self.nDOF, self.nDOF))
            for i, ms in enumerate(self.ms_list):
                if ms is not None:
                    K = K.at[offs[i]:offs[i] + 6, offs[i]:offs[i] + 6].add(
                        mooring_stiffness(ms, X[offs[i]:offs[i] + 6])
                    )
            if self.ms_array is not None:
                r6_all = jnp.stack(
                    [X[offs[i]:offs[i] + 6] for i in range(self.nFOWT)]
                )
                Ka = self.ms_array.stiffness(r6_all)
                for i in range(self.nFOWT):
                    for j in range(self.nFOWT):
                        K = K.at[offs[i]:offs[i] + 6, offs[j]:offs[j] + 6].add(
                            Ka[6 * i:6 * i + 6, 6 * j:6 * j + 6]
                        )
            return K

        return force, stiff

    # --------------------------------------------------------------- statics
    def solve_statics(self, case=None, extra_force=None):
        """Mean offsets for a load case (Model.solveStatics equivalent,
        raft_model.py:550-964; staticsMod=0 / forcingsMod=0 path).

        extra_force: additional constant force (e.g. wave mean drift fed
        back after the dynamics solve, raft_model.py:316-328).
        Returns the equilibrium pose X (nDOF,) over all FOWTs."""
        from raft_tpu.models.statics_solve import (
            make_tolerances, solve_equilibrium_general)

        import scipy.linalg

        K_blocks, F_und_parts, F_env_parts = [], [], []
        C_elast_blocks = []
        for i, fs in enumerate(self.fowtList):
            # reset to the undisplaced pose at case start, as the
            # reference does before computing turbine constants and
            # current loads (raft_model.py:599-621) — without this, pose
            # state left by a previous case's solve_dynamics leaks into
            # this case's mean environmental loads (order-dependence)
            self.hydro[i].set_position(np.zeros(fs.nDOF))
            stat = self.statics(i)
            K_blocks.append(np.asarray(stat["C_struc"] + stat["C_hydro"]))
            C_elast_blocks.append(np.asarray(stat["C_elast"]))
            F_und_parts.append(
                np.asarray(stat["W_struc"] + stat["W_hydro"] + stat["f0_additional"]))
            F_env = jnp.zeros(fs.nDOF)
            if case is not None:
                F_env = F_env + self.hydro[i].current_loads(case)
                F_env = F_env + self.aero_mean_force(case, i)
            F_env_parts.append(np.asarray(F_env))

        K_h = jnp.asarray(scipy.linalg.block_diag(*K_blocks))
        F_und = jnp.asarray(np.concatenate(F_und_parts))
        F_env = jnp.asarray(np.concatenate(F_env_parts))
        if extra_force is not None:
            F_env = F_env + jnp.asarray(extra_force)

        C_elast = jnp.asarray(scipy.linalg.block_diag(*C_elast_blocks))
        tol_vec, caps, refs = make_tolerances(self.fowtList)
        force, stiff = self._mooring_closures()
        X, Fres, n_iter, converged, st_status = solve_equilibrium_general(
            K_h, F_und, F_env, force, stiff, tol_vec, caps, refs,
            C_elast=C_elast)
        self.statics_status = int(st_status)
        if not bool(converged):
            # mirror the drag-linearisation warning in solve_dynamics:
            # the Newton budget struck with the step rule unmet, so the
            # reported equilibrium is the capped iterate
            import warnings

            from raft_tpu.obs import metrics
            from raft_tpu.utils import health
            from raft_tpu.utils.structlog import log_event

            warnings.warn(
                "solveStatics Newton did not converge within "
                f"{int(n_iter)} iterations "
                f"(status: {health.describe(int(st_status))})")
            metrics.counter("statics_unconverged").inc()
            log_event("statics_unconverged", n_iter=int(n_iter),
                      status=int(st_status),
                      reason=health.describe(int(st_status)))
        self.X0 = X
        return X

    def _resolve_data_path(self, path, suffixes=("",)):
        """Resolve a design-file-relative data path.  The reference
        resolves such paths against the CWD it is launched from (repo
        root for the shipped examples); here we try the design file's
        directory and its parent so the shipped YAMLs work in place."""
        import os

        if os.path.isabs(path) or self.base_dir is None:
            return path
        for base in (self.base_dir,
                     os.path.normpath(os.path.join(self.base_dir, ".."))):
            cand = os.path.normpath(os.path.join(base, path))
            if any(os.path.exists(cand + s) for s in suffixes):
                return cand
        return os.path.join(self.base_dir, path)

    @property
    def qtf(self):
        """Lazy difference-frequency QTF data (potSecOrder == 2 path)."""
        if not hasattr(self, "_qtf"):
            self._qtf = None
            fs = self.fowtList[0]
            if fs.potSecOrder == 2 and fs.hydroPath:
                import os

                from raft_tpu.physics.secondorder import read_qtf_12d

                path = self._resolve_data_path(fs.hydroPath, (".12d",)) + ".12d"
                if os.path.exists(path):
                    self._qtf = read_qtf_12d(path, rho=fs.rho_water, g=fs.g)
        return self._qtf

    @property
    def rotor_aero(self):
        """Lazy per-rotor BEMT models (CCBlade-equivalent)."""
        if self._rotor_aero is None:
            from raft_tpu.physics.aero import build_rotor_aero

            self._rotor_aero = []
            turbine = self.design.get("turbine")
            if turbine is not None and "wt_ops" in turbine:
                t = dict(turbine)
                site = self.design.get("site", {})
                t.setdefault("nrotors", self.fowtList[0].nrotors)
                t.setdefault("rho_air", coerce(site, "rho_air", default=1.225))
                t.setdefault("mu_air", coerce(site, "mu_air", default=1.81e-5))
                t.setdefault(
                    "shearExp_air",
                    coerce(site, "shearExp_air",
                           default=coerce(site, "shearExp", default=0.12)),
                )
                for ir in range(self.fowtList[0].nrotors):
                    submerged = self.fowtList[0].rotors[ir].Zhub < 0
                    self._rotor_aero.append(
                        build_rotor_aero(t, ir, submerged=submerged))
        return self._rotor_aero

    def turbine_constants(self, case, ifowt=0):
        """Aero-servo added mass/damping/excitation + gyroscopics in the
        reduced DOFs (FOWT.calcTurbineConstants equivalent,
        raft_fowt.py:1514-1586).  Cached per case."""
        from raft_tpu.physics.aero import calc_aero, operating_point
        from raft_tpu.ops import transforms as tf

        fs = self.fowtList[ifowt]
        nDOF, nw = fs.nDOF, self.nw
        out = dict(
            f_aero0=np.zeros((nDOF, max(fs.nrotors, 1))),
            A_aero=np.zeros((nDOF, nDOF, nw)),
            B_aero=np.zeros((nDOF, nDOF, nw)),
            f_aero=np.zeros((nDOF, nw), dtype=np.complex128),
            B_gyro=np.zeros((nDOF, nDOF)),
            A00=np.zeros((nw, max(fs.nrotors, 1))),
            B00=np.zeros((nw, max(fs.nrotors, 1))),
            rotor_info=[None] * max(fs.nrotors, 1),
        )
        status = str(case.get("turbine_status", "operating"))
        if status != "operating" or not self.rotor_aero:
            return out
        key = (ifowt,) + tuple(sorted(
            (k, str(v)) for k, v in case.items()
            if k in ("wind_speed", "wind_heading", "turbulence",
                     "yaw_misalign", "turbine_heading",
                     "current_speed", "current_heading", "turbine_status")))
        if key in self._aero_cache:
            return self._aero_cache[key]

        fh = self.hydro[ifowt]
        for ir, rot in enumerate(self.rotor_aero):
            rprops = fs.rotors[ir]
            current = rprops.Zhub < 0  # submerged rotor -> current-driven
            if current:
                speed = float(coerce(case, "current_speed", shape=0, default=1.0))
            elif isinstance(case.get("wind_speed"), (list, tuple, np.ndarray)):
                # per-FOWT waked wind speeds from the farm wake coupling
                # (raft_model.py:646-648)
                speed = float(np.asarray(case["wind_speed"], dtype=float)[
                    min(ifowt, len(case["wind_speed"]) - 1)])
            else:
                speed = float(coerce(case, "wind_speed", shape=0, default=10))
            if rprops.aeroServoMod <= 0 or speed <= 0:
                continue
            f0, f, a, b, info = calc_aero(rot, rprops, case, self.w, speed=speed, current=current)
            node = int(fs.rotor_node[ir])
            Tn = np.asarray(fh.Tn[node])  # (6, nDOF)
            out["f_aero0"][:, ir] = Tn.T @ f0
            out["f_aero"] += Tn.T @ f
            out["A_aero"] += np.einsum("ia,ijw,jb->abw", Tn, a, Tn)
            out["B_aero"] += np.einsum("ia,ijw,jb->abw", Tn, b, Tn)
            out["A00"][:, ir] = a[0, 0, :]
            out["B00"][:, ir] = b[0, 0, :]
            out["rotor_info"][ir] = dict(
                info, speed=speed, aeroServoMod=rprops.aeroServoMod,
                Ng=rot.Ng)
            if current and rot.cpmin is not None:
                from raft_tpu.physics.aero import calc_cavitation

                out["rotor_info"][ir]["cavitation"] = calc_cavitation(
                    rot, rprops, case, rho=fs.rho_water, g=fs.g)
            # gyroscopic damping (raft_fowt.py:1569-1581)
            Om_rpm = float(operating_point(rot, speed)[0])
            IO = info["q"] * (rprops.I_drivetrain * Om_rpm * 2 * np.pi / 60)
            G = np.zeros((6, 6))
            G[3:, 3:] = np.asarray(tf.skew(jnp.asarray(IO)))
            out["B_gyro"] += Tn.T @ G @ Tn
        self._aero_cache[key] = out
        return out

    def aero_mean_force(self, case, ifowt=0):
        """Sum of mean rotor forces in reduced DOFs."""
        tc = self.turbine_constants(case, ifowt)
        return jnp.asarray(np.sum(tc["f_aero0"], axis=1))

    def qtf_slender(self, waveHeadInd=0, Xi0=None, ifowt=0):
        """Slender-body QTF dispatcher for the potSecOrder == 1 flow:
        the (w1 x w2) pair axis is physically partitioned over the
        device mesh whenever more than one device is visible (the
        sharded path is bitwise-compatible with the host path,
        tests/test_qtf_slender.py), so large min_freq2nd grids scale
        across chips transparently."""
        import jax

        if len(jax.devices()) > 1:
            from raft_tpu.parallel.sweep import qtf_slender_sharded

            return qtf_slender_sharded(
                self, waveHeadInd, Xi0=Xi0, ifowt=ifowt)
        from raft_tpu.physics.qtf_slender import fowt_qtf_slender

        return fowt_qtf_slender(self, waveHeadInd, Xi0=Xi0, ifowt=ifowt)

    # -------------------------------------------------------------- dynamics
    def solve_dynamics(self, case, X0=None):
        """Iterative linearised dynamics for one case
        (Model.solveDynamics equivalent, raft_model.py:966-1255).

        Per-FOWT impedances converge independently (raft_model.py:994),
        then the system impedance couples them through shared mooring
        stiffness (:1164-1182) and the response is solved per heading.

        Returns (Xi (nWaves+1, nDOF_total, nw), diagnostics dict)."""
        from raft_tpu.models.dynamics import solve_dynamics_fowt, system_response
        from raft_tpu.physics.mooring import mooring_stiffness

        if X0 is None:
            X0 = self.solve_statics(case)
        offs = self.dof_offsets
        nw = self.nw

        Z_blocks, Bmats, infos = [], [], []
        F_2nd_mean = None
        nWaves = 1 if np.isscalar(case.get("wave_heading", 0)) else len(case["wave_heading"])
        F_waves = [[] for _ in range(nWaves)]

        for i, fs in enumerate(self.fowtList):
            fh = self.hydro[i]
            fh.set_position(X0[offs[i]:offs[i + 1]])
            stat = self.statics(i)
            exc = fh.hydro_excitation(case)
            nDOF = fs.nDOF

            A_BEM, B_BEM = self.bem_matrices(i)
            F_BEM = self.bem_excitation(case, fh, i)
            tc = self.turbine_constants(case, i)

            M_lin = (
                jnp.asarray(tc["A_aero"])
                + stat["M_struc"][:, :, None] + fh.hc0["A_hydro"][:, :, None] + A_BEM
            )
            B_lin = (
                jnp.asarray(tc["B_aero"]) + B_BEM
                + jnp.asarray(tc["B_gyro"])[:, :, None]
            )
            # mooring reaction: quasi-static stiffness (moorMod 0/1) or
            # the frequency-dependent lumped-mass impedance (moorMod 2,
            # raft_model.py:1020-1031)
            C_moor = jnp.zeros((nDOF, nDOF))
            Z_moor = None
            ms_i = self.ms_list[i]
            if ms_i is not None:
                if getattr(ms_i, "moorMod", 0) == 2 and getattr(ms_i, "m_lin", None) is not None:
                    from raft_tpu.physics.mooring_dynamics import fowt_mooring_impedance

                    Z6 = fowt_mooring_impedance(
                        ms_i, np.asarray(X0[offs[i]:offs[i] + 6]),
                        self.w, self.k, fh.S[0], fh.beta[0], self.depth,
                        rho=fs.rho_water, g=fs.g)
                    Z_moor = jnp.zeros((nw, nDOF, nDOF),
                                       dtype=jnp.asarray(Z6).dtype)
                    Z_moor = Z_moor.at[:, :6, :6].set(Z6)
                else:
                    C_moor = C_moor.at[:6, :6].add(
                        mooring_stiffness(ms_i, X0[offs[i]:offs[i] + 6]))
            C_lin = stat["C_struc"] + stat["C_hydro"] + C_moor + stat["C_elast"]
            F_lin = F_BEM[0] + exc["F_hydro_iner"][0]

            # second-order (difference-frequency) forces from external QTFs
            F_2nd = jnp.zeros((nWaves, nDOF, nw), dtype=F_lin.dtype)
            if F_2nd_mean is None:
                F_2nd_mean = np.zeros((nWaves, self.nDOF))
            if self.qtf is not None and i == 0:
                from raft_tpu.physics.secondorder import hydro_force_2nd

                for ih in range(nWaves):
                    fm, f2 = hydro_force_2nd(self.qtf, fh.beta[ih], fh.S[ih], self.w)
                    F_2nd = F_2nd.at[ih, :6, :].add(jnp.asarray(f2[:6]))
                    F_2nd_mean[ih, offs[i]:offs[i] + 6] = fm[:6]
                F_lin = F_lin + F_2nd[0]

            Z_i, Xi_i, Bmat, dyn_diag = solve_dynamics_fowt(
                fs, fh.strips, fh.hc, fh.u[0], M_lin, B_lin, C_lin, F_lin,
                jnp.asarray(self.w), fh.Tn, fh.r_nodes,
                n_iter=self.nIter, Xi_start=self.XiStart, Z_extra=Z_moor,
                n_iter_extra=self.nIterExtra,
            )

            # internally-computed slender-body QTFs (potSecOrder == 1):
            # converge first order, compute QTFs from the motion RAOs,
            # then re-linearise with the 2nd-order forces included
            # (raft_model.py:1108-1131)
            if fs.potSecOrder == 1 and self.w1_2nd is not None:
                from raft_tpu.ops.waves import get_rao
                from raft_tpu.physics.secondorder import hydro_force_2nd

                RAO = np.asarray(get_rao(Xi_i[:6], jnp.asarray(fh.zeta[0])))
                qtf = self.qtf_slender(0, Xi0=RAO, ifowt=i)
                qtf_data = dict(w_2nd=self.w1_2nd,
                                heads_rad=np.asarray([fh.beta[0]]), qtf=qtf)
                if self.out_folder_qtf:
                    # persist in the reference's checkpoint formats
                    # (raft_fowt.py:2027-2041 .4, :2072-2078 .12d); the
                    # case index keeps multi-case runs from overwriting
                    # each other (case-specific drag linearisation makes
                    # the RAOs, hence the QTF, case-dependent)
                    import os

                    from raft_tpu.io.wamit import write_rao_4
                    from raft_tpu.physics.secondorder import write_qtf_12d

                    os.makedirs(self.out_folder_qtf, exist_ok=True)
                    whead = float(np.degrees(fh.beta[0]))
                    iCase = getattr(self, "_current_case_index", None)
                    tag = (f"Head{whead:.0f}_WT{i}" if iCase is None
                           else f"Head{whead:.0f}_Case{iCase + 1}_WT{i}")
                    write_rao_4(os.path.join(
                        self.out_folder_qtf, f"raos-slender_body_{tag}.4"),
                        self.w, RAO, beta_deg=whead)
                    write_qtf_12d(os.path.join(
                        self.out_folder_qtf,
                        f"qtf-slender_body-total_{tag}.12d"),
                        np.asarray(qtf), self.w1_2nd,
                        np.asarray([fh.beta[0]]),
                        rho=fs.rho_water, g=fs.g)
                for ih in range(nWaves):
                    fm, f2 = hydro_force_2nd(qtf_data, fh.beta[ih], fh.S[ih], self.w)
                    F_2nd = F_2nd.at[ih, :6, :].add(jnp.asarray(f2[:6]))
                    F_2nd_mean[ih, offs[i]:offs[i] + 6] += fm[:6]
                F_lin = F_lin + F_2nd[0]
                Z_i, Xi_i, Bmat, dyn_diag = solve_dynamics_fowt(
                    fs, fh.strips, fh.hc, fh.u[0], M_lin, B_lin, C_lin, F_lin,
                    jnp.asarray(self.w), fh.Tn, fh.r_nodes,
                    n_iter=self.nIter, Xi_start=self.XiStart, Z_extra=Z_moor,
                n_iter_extra=self.nIterExtra,
                )
            Z_blocks.append(Z_i)
            Bmats.append(Bmat)
            if not bool(dyn_diag["drag_converged"]):
                import warnings

                warnings.warn(
                    "solveDynamics drag linearisation did not converge to "
                    f"tolerance (residual {float(dyn_diag['drag_resid']):.2e}) "
                    "for FOWT %d" % i)
            infos.append(dict(S=fh.S, zeta=fh.zeta, exc=exc, tc=tc,
                              dyn_diag=dyn_diag))
            for ih in range(nWaves):
                F_drag = fh.drag_excitation(Bmat, ih)
                F_waves[ih].append(
                    F_BEM[ih] + exc["F_hydro_iner"][ih] + F_drag + F_2nd[ih])
        self._last_drift_mean = F_2nd_mean

        # ---- system impedance: block-diagonal FOWT impedances + shared
        # mooring stiffness (raft_model.py:1164-1182)
        Z_sys = jnp.zeros((nw, self.nDOF, self.nDOF),
                          dtype=Z_blocks[0].dtype)
        for i in range(self.nFOWT):
            Z_sys = Z_sys.at[:, offs[i]:offs[i + 1], offs[i]:offs[i + 1]].add(
                Z_blocks[i])
        if self.ms_array is not None:
            r6_all = jnp.stack(
                [X0[offs[i]:offs[i] + 6] for i in range(self.nFOWT)])
            Ka = self.ms_array.stiffness(r6_all)
            for i in range(self.nFOWT):
                for j in range(self.nFOWT):
                    Z_sys = Z_sys.at[:, offs[i]:offs[i] + 6,
                                     offs[j]:offs[j] + 6].add(
                        Ka[6 * i:6 * i + 6, 6 * j:6 * j + 6][None])

        F_sys = jnp.stack([jnp.concatenate(Fw, axis=0) for Fw in F_waves])
        Xi = system_response(Z_sys, F_sys)
        # numerical sanity guard (raft_model.py:1098-1099)
        if not bool(jnp.all(jnp.isfinite(jnp.abs(Xi)))):
            raise RuntimeError(
                "NaN detected in response vector Xi (solveDynamics guard)")
        Xi = jnp.concatenate(
            [Xi, jnp.zeros((1, self.nDOF, nw), dtype=Xi.dtype)], axis=0)
        info0 = infos[0]
        return Xi, dict(
            Z=Z_sys, Bmat=Bmats[0], S=info0["S"], zeta=info0["zeta"],
            exc=info0["exc"], tc=info0["tc"], infos=infos,
        )

    @property
    def bem(self):
        """First FOWT's potential-flow coefficients (see bem_list)."""
        return self.bem_list[0]

    @property
    def bem_list(self):
        """Per-FOWT potential-flow coefficients: WAMIT-format files when
        the design points at them (readHydro equivalent,
        raft_fowt.py:1444-1509), otherwise the NATIVE panel solver runs
        on the potMod members (calcBEM equivalent, :1288-1442)."""
        if not hasattr(self, "_bem_list"):
            self._bem_list = []
            for i, fs in enumerate(self.fowtList):
                bem_i = None
                if fs.potFirstOrder == 1 and fs.hydroPath:
                    from raft_tpu.io.wamit import load_bem_coefficients

                    path = self._resolve_data_path(fs.hydroPath, (".1", ".3"))
                    bem_i = load_bem_coefficients(
                        path, self.w, fs.rho_water, fs.g,
                        r_ref=fs.node_r0[fs.root_id],
                    )
                    for key in ("A_BEM", "B_BEM", "X_BEM"):
                        if not np.all(np.isfinite(bem_i[key])):
                            raise RuntimeError(
                                f"non-finite {key} coefficients loaded from "
                                f"{path} (raft_fowt.py:1503-1509 guard)")
                elif any(m.potMod for m in fs.members):
                    bem_i = self.run_bem(ifowt=i)
                self._bem_list.append(bem_i)
        return self._bem_list

    def run_bem(self, ifowt=0, w_bem=None, headings=None, save_dir=None,
                n_az=None, dz_max=None, force=False, workers=None,
                d_scale=1.0):
        """Run the native free-surface panel solver on the FOWT's potMod
        members and read the coefficients back through the WAMIT
        interchange files (mirrors the reference's HAMS round trip:
        mesh -> run -> write .1/.3 -> readHydro, raft_fowt.py:1288-1509).

        Results are cached in ``save_dir`` (default
        ``./_bem_cache/<design name>``); pass force=True to re-run.
        ``d_scale`` uniformly scales the potMod members' diameters/side
        lengths before meshing (the geometry design axis; the cache key
        includes the scaled mesh, so each scale gets its own entry).
        Returns the same dict structure as WAMIT-file loading.
        """
        import os

        from raft_tpu.io.panels import mesh_fowt
        from raft_tpu.io.wamit import (load_bem_coefficients, write_wamit1,
                                       write_wamit3)

        fs = self.fowtList[ifowt]
        settings = self.design.get("settings", {}) or {}
        name = str(self.design.get("name", "design")).replace(" ", "_")[:40]
        if save_dir is None:
            from raft_tpu.utils import config

            save_dir = config.get("BEM_DIR")
        os.makedirs(save_dir, exist_ok=True)

        if w_bem is None:
            dw = float(coerce(settings, "dw_BEM", default=0.0) or 0.0)
            wMax = float(coerce(settings, "wMax_BEM", default=0.0) or 0.0)
            if dw <= 0:
                dw = max((self.w[-1] - self.w[0]) / 24.0, 1e-3)
            if wMax <= 0:
                wMax = float(self.w[-1])
            w_bem = np.arange(dw, wMax + 0.5 * dw, dw)
        if headings is None:
            headings = np.arange(0.0, 360.0, 45.0)

        # mesh first (cheap host work), then key the cache by the panel
        # geometry + solver inputs: same-named designs with different
        # geometry (scaled members, per-FOWT differences in an array,
        # different frequency grids/depths) get distinct entries
        import hashlib

        n_az_v = n_az or int(coerce(settings, "nAz_BEM", default=18, dtype=int))
        dz_v = dz_max or (coerce(settings, "dz_BEM", default=0.0) or None)
        fs_mesh = fs
        if abs(float(d_scale) - 1.0) > 1e-12:
            import copy as _copy
            import dataclasses as _dc

            fs_mesh = _copy.copy(fs)
            fs_mesh.members = [
                _dc.replace(m, d=np.asarray(m.d) * float(d_scale))
                if m.potMod else m
                for m in fs.members
            ]
        v, c, nrm, a = mesh_fowt(fs_mesh, dz_max=dz_v, n_az=n_az_v)
        if len(a) == 0:
            return None
        hsh = hashlib.sha256()
        # kernel-version token: cache entries from older solver kernels
        # (e.g. pre-finite-depth) must not be served for the same inputs
        hsh.update(b"panel_bem-v2-fd")
        for arr in (v, a, np.asarray(w_bem, float),
                    np.asarray(headings, float),
                    np.asarray([self.depth, fs.rho_water, fs.g], float)):
            hsh.update(np.ascontiguousarray(
                np.asarray(arr, dtype=np.float64)).tobytes())
        prefix = os.path.join(
            save_dir, f"{name}_f{ifowt}_{hsh.hexdigest()[:12]}")

        if force or not os.path.exists(prefix + ".1"):
            from raft_tpu.native import solve_bem

            A, B, X = solve_bem(v, c, nrm, a, w_bem, headings_deg=headings,
                                depth=self.depth, rho=fs.rho_water, g=fs.g,
                                ref=(0.0, 0.0, 0.0), workers=workers)
            write_wamit1(prefix + ".1", w_bem, A, B, rho=fs.rho_water)
            write_wamit3(prefix + ".3", w_bem, headings, X,
                         rho=fs.rho_water, g=fs.g)
        return load_bem_coefficients(
            prefix, self.w, fs.rho_water, fs.g, r_ref=fs.node_r0[fs.root_id])

    def bem_matrices(self, ifowt=0):
        """Potential-flow added mass / radiation damping on the model
        grid (zero when no coefficient files are configured)."""
        nDOF, nw = self.fowtList[ifowt].nDOF, self.nw
        A = np.zeros((nDOF, nDOF, nw))
        B = np.zeros((nDOF, nDOF, nw))
        bem = self.bem_list[ifowt]
        if bem is not None:
            A[:6, :6, :] = bem["A_BEM"]
            B[:6, :6, :] = bem["B_BEM"]
        return jnp.asarray(A), jnp.asarray(B)

    def bem_excitation(self, case, fh, ifowt=0):
        """F_BEM per wave heading: heading-interpolated excitation
        coefficients x component amplitudes, with the array phase offset
        exp(-i k (x cos b + y sin b)) (raft_fowt.py:1793-1849)."""
        from raft_tpu.io.wamit import interp_heading
        from raft_tpu.models.hydro import make_sea_state

        fs = self.fowtList[ifowt]
        nDOF, nw = fs.nDOF, self.nw
        nWaves = 1 if np.isscalar(case.get("wave_heading", 0)) else len(case["wave_heading"])
        F = np.zeros((nWaves, nDOF, nw), dtype=np.complex128)
        bem = self.bem_list[ifowt]
        if bem is not None and np.any(np.abs(bem["X_BEM"]) > 0):
            S, zeta, beta = make_sea_state(case, self.w)
            heading = np.atleast_1d(np.degrees(beta))
            for ih in range(nWaves):
                phase = np.exp(-1j * self.k * (
                    fs.x_ref * np.cos(np.radians(heading[ih]))
                    + fs.y_ref * np.sin(np.radians(heading[ih]))))
                X = interp_heading(
                    bem["X_BEM"], bem["headings"],
                    (heading[ih] - fs.heading_adjust) % 360)
                # interp_heading rotates by the BEM-frame heading; global
                # rotation uses the absolute heading
                F[ih, :6, :] = X * zeta[ih] * phase
        return jnp.asarray(F)

    # --------------------------------------------------------------- eigen
    def solve_eigen(self, case=None):
        """Natural frequencies and modes (Model.solveEigen equivalent,
        raft_model.py:436-547).  Call after solve_statics for a loaded
        state (the mooring stiffness tracks the mean offsets).

        Returns (fns [Hz], modes) with the reference's DOF-claiming
        mode sort for rigid systems."""
        X0 = getattr(self, "X0", None)
        if X0 is None:
            X0 = self.solve_statics(case)
        offs = self.dof_offsets
        M_tot = np.zeros((self.nDOF, self.nDOF))
        C_tot = np.zeros((self.nDOF, self.nDOF))
        for i, fs in enumerate(self.fowtList):
            stat = self.statics(i)
            A_BEM, _ = self.bem_matrices(i)
            sl = slice(offs[i], offs[i + 1])
            M_tot[sl, sl] += (
                np.asarray(stat["M_struc"])
                + np.asarray(self.hydro[i].hc0["A_hydro"])
                + np.asarray(A_BEM[:, :, 0])
            )
            C_tot[sl, sl] += (
                np.asarray(stat["C_struc"]) + np.asarray(stat["C_hydro"])
                + np.asarray(stat["C_elast"])
            )
            C_tot[offs[i] + 5, offs[i] + 5] += fs.yaw_stiffness
        _, stiff = self._mooring_closures()
        C_tot += np.asarray(stiff(jnp.asarray(X0)))

        eigenvals, eigenvectors = np.linalg.eig(np.linalg.solve(M_tot, C_tot))
        if np.any(eigenvals.real <= 0.0):
            raise RuntimeError("zero or negative system eigenvalues detected")

        # flexible/multibody systems: ascending sort (raft_model.py:518-527)
        if not all(f.nDOF == 6 for f in self.fowtList):
            order = np.argsort(eigenvals.real)
            fns = np.sqrt(eigenvals[order].real) / 2.0 / np.pi
            return fns, eigenvectors[:, order]

        nDOF = self.nDOF
        # DOF-claiming sort (raft_model.py:499-516)
        ind_list = []
        for i in range(nDOF - 1, -1, -1):
            vec = np.abs(eigenvectors[i, :]).copy()
            for _ in range(nDOF):
                ind = int(np.argmax(vec))
                if ind in ind_list:
                    vec[ind] = 0.0
                else:
                    ind_list.append(ind)
                    break
        ind_list.reverse()
        fns = np.sqrt(eigenvals[ind_list].real) / 2.0 / np.pi
        modes = eigenvectors[:, ind_list]
        return fns, modes

    def calc_outputs(self):
        """System-property and eigen outputs (Model.calcOutputs
        equivalent, raft_model.py:1319-1360): fills
        ``results['properties']`` and ``results['eigen']`` and returns
        the results dict."""
        from raft_tpu.ops import transforms as tf

        fs = self.fowtList[0]
        stat = self.statics(0)
        if not hasattr(self, "results"):
            self.results = {}
        props = self.results.setdefault("properties", {})

        X0_unloaded = np.asarray(self.solve_statics(None))
        force, stiff = self._mooring_closures()
        F_moor0 = np.asarray(force(jnp.asarray(X0_unloaded)))[:6]
        C_moor0 = np.asarray(stiff(jnp.asarray(X0_unloaded)))[:6, :6]

        m_shell = float(sum(m.mshell for m in fs.members
                            if m.part_of == "platform"))
        props["tower mass"] = np.asarray(stat["mtower"])
        props["tower CG"] = np.asarray(stat["rCG_tow"])
        props["substructure mass"] = float(stat["m_sub"])
        props["substructure CG"] = np.asarray(stat["rCG_sub"])
        props["shell mass"] = m_shell
        props["ballast mass"] = np.asarray(stat["m_ballast"])
        props["ballast densities"] = np.asarray(stat["pb"])
        props["total mass"] = float(np.asarray(stat["M_struc"])[0, 0])
        props["total CG"] = np.asarray(stat["rCG"])
        # substructure inertias about its own CG (raft_model.py:1338-1340)
        M_subCG = np.asarray(tf.translate_matrix_6to6(
            jnp.asarray(stat["M_sub6"]), -jnp.asarray(stat["rCG_sub"])))
        props["roll inertia at subCG"] = M_subCG[3, 3]
        props["pitch inertia at subCG"] = M_subCG[4, 4]
        props["yaw inertia at subCG"] = M_subCG[5, 5]
        props["buoyancy (pgV)"] = fs.rho_water * fs.g * float(stat["V"])
        props["center of buoyancy"] = np.asarray(stat["rCB"])
        props["C hydrostatic"] = np.asarray(stat["C_hydro"])[:6, :6]
        props["C system"] = (
            np.asarray(stat["C_struc"] + stat["C_hydro"]
                       + stat["C_elast"])[:6, :6] + C_moor0)
        props["F_lines0"] = F_moor0
        props["C_lines0"] = C_moor0
        props["M support structure"] = np.asarray(stat["M_struc_sub"])[:6, :6]
        A_BEM, _ = self.bem_matrices(0)
        props["A support structure"] = np.asarray(
            self.hydro[0].hc0["A_hydro"])[:6, :6] + np.asarray(A_BEM[:6, :6, -1])
        props["C support structure"] = (
            np.asarray(stat["C_struc_sub"] + stat["C_hydro"])[:6, :6] + C_moor0)

        fns, modes = self.solve_eigen()
        self.results["eigen"] = {"frequencies": np.asarray(fns),
                                 "modes": np.asarray(modes)}
        return self.results

    def write_modes_json(self, filename, fns=None, modes=None, ifowt=0):
        """Write eigenmodes in the viz3Danim JSON layout
        (FOWT.write_modes_json equivalent, raft_fowt.py:2889-3070)."""
        from raft_tpu.models.outputs import write_modes_json

        if fns is None or modes is None:
            fns, modes = self.solve_eigen()
        write_modes_json(self, filename, np.asarray(fns), np.asarray(modes),
                         ifowt=ifowt)

    def wake_coupling(self, u_grid=None):
        """Set up farm wake coupling (florisCoupling equivalent,
        raft_model.py:1956-2053) using the built-in Gaussian wake model
        and this model's own BEMT power/thrust curves.  Returns the
        WakeCoupling driver (find_equilibrium / calc_aep)."""
        from raft_tpu.physics.wake import WakeCoupling

        self.wake = WakeCoupling(self, u_grid=u_grid)
        return self.wake

    # ---------------------------------------------------------- case driver
    def analyze_cases(self):
        """Run every case in the design's case table and collect channel
        statistics (Model.analyzeCases equivalent, raft_model.py:264-433)."""
        from raft_tpu.models.outputs import turbine_outputs

        self.results = {
            "freq_rad": self.w,
            "case_metrics": {},
            "mean_offsets": [],
        }
        from raft_tpu.obs import metrics, span
        from raft_tpu.utils.structlog import log_event

        for iCase, case in enumerate(self.cases):
            self._current_case_index = iCase   # QTF checkpoint filenames
            # telemetry spans (host-side only): statics + dynamics wall
            # times per case land in the span tree / span_*_s histograms
            with span("solve_statics", case=iCase):
                X0 = self.solve_statics(case)
            with span("solve_dynamics", case=iCase):
                Xi, info = self.solve_dynamics(case, X0=X0)
            metrics.counter("cases_done").inc()
            for i, inf in enumerate(info.get("infos", [])):
                dd = inf.get("dyn_diag")
                if dd is not None:
                    from raft_tpu.utils import health
                    st = int(dd["status"])
                    metrics.histogram("drag_iterations").observe(
                        int(dd["n_iter_drag"]))
                    if st & int(health.SEVERE):
                        metrics.counter("cases_flagged").inc()
                    log_event("drag_linearisation", case=iCase, fowt=i,
                              resid=float(dd["drag_resid"]),
                              converged=bool(dd["drag_converged"]),
                              n_iter=int(dd["n_iter_drag"]),
                              status=st, reason=health.describe(st))
            # feed mean drift back into the equilibrium for ANY 2nd-order
            # configuration — the reference re-runs solveStatics with
            # Fhydro_2nd_mean whenever potSecOrder > 0, slender-body QTFs
            # included, and its golden means reflect that drift-included
            # pose (raft_model.py:316-328, :625-628)
            if self.qtf is not None or (self.w1_2nd is not None and any(
                    fs.potSecOrder == 1 for fs in self.fowtList)):
                X0 = self.solve_statics(
                    case, extra_force=np.sum(self._last_drift_mean, axis=0)
                )
            self.results["mean_offsets"].append(np.asarray(X0))
            self.results["case_metrics"][iCase] = {}
            offs = self.dof_offsets
            for i in range(self.nFOWT):
                tc_i = info["infos"][i]["tc"]
                metrics = turbine_outputs(
                    self, case, X0[offs[i]:offs[i + 1]],
                    Xi[:, offs[i]:offs[i + 1], :],
                    info["infos"][i]["S"], info["infos"][i]["zeta"],
                    A_aero=tc_i["A00"].T, B_aero=tc_i["B00"].T,
                    f_aero0=tc_i["f_aero0"], ifowt=i,
                    rotor_info=tc_i.get("rotor_info"),
                )
                self.results["case_metrics"][iCase][i] = metrics
        self._current_case_index = None
        return self.results
