"""Declarative alert-rule engine over the metrics registry.

PRs 5/10/11/13 built the fleet's signals — counters, gauges,
histograms, sliding SLO windows, run records — but every one of them is
*passive*: a human must run ``obs report`` or ``obs runs regress``
after the fact to notice that p95 doubled, a breaker is flapping or a
replica fleet is shedding leases.  This module is the active layer:
**rules** declare conditions over the live registry (in the SAME
namespaced metric form the run store flattens to — ``counter:<name>``,
``gauge:<name>:value``, ``hist:<name>:p95``, ``window:<name>:p95``,
``waste:<axis>``, ``derived:<name>`` — so one selector grammar spans
live processes and stored run records), a named daemon thread
evaluates them every ``RAFT_TPU_ALERT_EVAL_S`` seconds against
:func:`raft_tpu.obs.metrics.snapshot`, and firing/resolving

* emits the registered ``alert_fire`` / ``alert_resolve`` events,
* maintains the ``alerts_active`` gauge (+ ``alerts_fired`` /
  ``alerts_resolved`` counters),
* appends one JSON record per transition to the ``RAFT_TPU_ALERTS``
  JSONL sink (single-line ``"a"``-mode append under the engine lock —
  the same bounded-append contract as the structlog sink),
* is served live at ``GET /alerts`` on both the replica server and the
  fleet router.

Rule grammar (one dict per rule, YAML or JSON)::

    name:       breaker-storm            # unique id
    metric:     counter:router_breaker_opens
    predicate:  rate_above               # above | below | rate_above
                                         # | absent
    threshold:  0.0                      # per-second for rate_above
    for_s:      0.0                      # condition must hold this long
    clear_s:    10.0                     # resolve hysteresis: condition
                                         # must stay clean this long
    severity:   critical                 # info | warning | critical
    replay_above: 0.0                    # `eval --record` total-value
                                         # threshold for rate rules
    context:    canary_parity            # context-registry key attached
                                         # to the fire payload

``rate_above`` compares the metric's per-second rate of increase
between consecutive evaluations (counter resets are treated as
no-rate, never a negative spike); ``absent`` fires when the metric is
missing from the snapshot.  A rule whose metric is absent (other than
``absent`` rules) simply does not apply that tick — a cold process
must not page about metrics it has not minted yet.

The default rule pack (:func:`default_rules`) covers the fleet's known
failure classes — SLO-breach storms, breaker-open storms, membership
lease churn, result-cache hit-rate collapse, compile-budget burn and
canary failures/parity splits — and is loadable/overridable from a
rule file (``RAFT_TPU_ALERT_RULES``): same-name rules replace pack
entries, ``disabled: true`` removes one, ``default_pack: false`` at
the top level starts from empty.

``python -m raft_tpu.obs alerts {list,check,eval}`` are the offline
verbs; ``eval --record <run-record>`` replays the rules against a
stored PR-11 run record (rate rules gate on their cumulative total vs
``replay_above``) so the lint gate needs NO live fleet and NO jax
import.

This module also owns the **provenance wire format** (:func:`
format_provenance` / :func:`parse_provenance` for the
``x-raft-provenance`` response header): every consumer that parses it
— the serve client, ``obs report``'s consistency line, the router
canary — must work without a backend, so the codec lives here in the
jax-free obs layer rather than under ``raft_tpu.serve``.

Pure stdlib; zero overhead when ``RAFT_TPU_ALERT_EVAL_S`` is unset (no
thread, no state, :func:`maybe_start` returns None).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from dataclasses import dataclass

from raft_tpu.obs import metrics
from raft_tpu.utils import config
from raft_tpu.utils.structlog import log_event

SEVERITIES = ("info", "warning", "critical")

PREDICATES = ("above", "below", "rate_above", "absent")

#: selector prefixes of the flattened metric namespace (the PR-11 run
#: store's :func:`raft_tpu.obs.runs.flatten` names, plus the live-only
#: ``gauge:<name>:value`` and the counter-ratio ``derived:`` family)
_SELECTOR_RE = re.compile(
    r"^(counter|gauge|hist|window|stage|waste|derived|extra):.+|^wall_s$")


# ------------------------------------------------------------------- rules


@dataclass(frozen=True)
class Rule:
    """One declarative alert rule (see the module docstring grammar)."""

    name: str
    metric: str
    predicate: str
    threshold: float = 0.0
    for_s: float = 0.0
    clear_s: float = 0.0
    severity: str = "warning"
    replay_above: float = 0.0
    context: str | None = None
    help: str = ""


def parse_rule(obj):
    """Validate one rule dict into a :class:`Rule`; raises
    ``ValueError`` naming the offending field (the ``alerts check``
    CLI surfaces these)."""
    if not isinstance(obj, dict):
        raise ValueError(f"rule must be a mapping, got {type(obj).__name__}")
    name = obj.get("name")
    if not name or not isinstance(name, str):
        raise ValueError("rule needs a non-empty string 'name'")
    metric = obj.get("metric")
    if not isinstance(metric, str) or not _SELECTOR_RE.match(metric or ""):
        raise ValueError(
            f"rule {name!r}: 'metric' must be a namespaced selector "
            "(counter:<n> | gauge:<n>:value|max | hist:<n>:p50|p95|mean | "
            f"window:<n>:p50|p95 | waste:<axis> | derived:<n> | "
            f"extra:<path> | wall_s), got {metric!r}")
    predicate = obj.get("predicate")
    if predicate not in PREDICATES:
        raise ValueError(f"rule {name!r}: predicate must be one of "
                         f"{'/'.join(PREDICATES)}, got {predicate!r}")
    severity = obj.get("severity", "warning")
    if severity not in SEVERITIES:
        raise ValueError(f"rule {name!r}: severity must be one of "
                         f"{'/'.join(SEVERITIES)}, got {severity!r}")
    unknown = set(obj) - {"name", "metric", "predicate", "threshold",
                          "for_s", "clear_s", "severity", "replay_above",
                          "context", "help", "disabled"}
    if unknown:
        raise ValueError(f"rule {name!r}: unknown field(s) "
                         f"{sorted(unknown)}")

    def num(field, default=0.0, lo=None):
        v = obj.get(field, default)
        try:
            v = float(v)
        except (TypeError, ValueError):
            raise ValueError(f"rule {name!r}: {field!r} must be a number, "
                             f"got {obj.get(field)!r}")
        if lo is not None and v < lo:
            raise ValueError(f"rule {name!r}: {field!r} must be >= {lo}")
        return v

    return Rule(name=name, metric=metric, predicate=predicate,
                threshold=num("threshold"),
                for_s=num("for_s", lo=0.0), clear_s=num("clear_s", lo=0.0),
                severity=severity,
                replay_above=num("replay_above"),
                context=obj.get("context") or None,
                help=str(obj.get("help") or ""))


def default_rules():
    """The default rule pack: the fleet's known failure classes, each
    grounded in a metric an earlier PR already records.  Thresholds
    are deliberately conservative — steady state on a healthy fleet
    fires nothing (drill-asserted).  Re-checked against the adaptive
    serve tick + cost-pruned batch ladder: light-load requests now
    dispatch solo (lower occupancy, MORE dispatches), which moves no
    rule input — cache keys, compile counts and breaker/lease signals
    are all window-independent, and `cache-hit-collapse` gates on the
    0.05 floor precisely so legitimate low-dup workloads (every light-
    load probe is a distinct case) cannot page anyone."""
    return [
        Rule("slo-breach", "counter:serve_slo_breaches", "rate_above",
             threshold=0.1, for_s=5.0, clear_s=30.0, severity="warning",
             help="sustained RAFT_TPU_SERVE_SLO_MS breaches (>0.1/s for "
                  "5s) — the PR-10 sliding-window SLO is being missed"),
        Rule("breaker-storm", "counter:router_breaker_opens", "rate_above",
             threshold=0.0, clear_s=10.0, severity="critical",
             help="router circuit breakers are opening: a replica is "
                  "dead, hung or erroring (the kill-a-replica signal)"),
        Rule("lease-churn", "counter:fleet_evictions", "rate_above",
             threshold=0.0, clear_s=30.0, severity="warning",
             help="fleet membership leases are expiring and being "
                  "evicted — replicas are dying faster than they renew"),
        Rule("cache-hit-collapse", "derived:serve_cache_hit_rate", "below",
             threshold=0.05, for_s=30.0, clear_s=30.0, severity="warning",
             help="the content-addressed result cache stopped hitting "
                  "(routing affinity broken, or a flag flip changed "
                  "every cache key)"),
        Rule("compile-budget-burn", "counter:xla_real_compiles",
             "rate_above", threshold=0.0, clear_s=60.0, severity="critical",
             help="REAL XLA compilations at steady state — the AOT "
                  "bank/warmup contract (0 steady-state compiles) is "
                  "broken"),
        Rule("canary-failure", "counter:canary_fail", "rate_above",
             threshold=0.0, clear_s=60.0, severity="critical",
             context="canary_parity",
             help="golden-answer canary probes are failing: a replica "
                  "returns numbers that differ from the captured golden"),
        Rule("canary-parity", "gauge:canary_parity_ok:value", "below",
             threshold=1.0, clear_s=5.0, severity="critical",
             context="canary_parity",
             help="cross-replica parity is broken: replicas disagree on "
                  "golden outputs or serve from divergent provenance "
                  "(stale bank, env skew, flag divergence)"),
    ]


def load_rules(path=None):
    """The effective rule pack: :func:`default_rules`, overridden and
    extended by the YAML/JSON rule file at ``path`` (same-name rules
    replace, ``disabled: true`` removes, top-level ``default_pack:
    false`` starts from empty).  ``path=None`` returns the default
    pack."""
    rules = {r.name: r for r in default_rules()}
    if not path:
        return list(rules.values())
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        data = json.loads(text)
    except ValueError:
        try:
            import yaml
        except ImportError:
            raise ValueError(
                f"{path}: not JSON and pyyaml is unavailable for YAML")
        data = yaml.safe_load(text)
    if isinstance(data, dict):
        if data.get("default_pack") is False:
            rules = {}
        entries = data.get("rules")
        if not isinstance(entries, list):
            raise ValueError(f"{path}: expected a top-level 'rules' list")
        extra_top = set(data) - {"rules", "default_pack"}
        if extra_top:
            raise ValueError(f"{path}: unknown top-level field(s) "
                             f"{sorted(extra_top)}")
    elif isinstance(data, list):
        entries = data
    else:
        raise ValueError(f"{path}: rule file must be a list of rules or a "
                         "mapping with a 'rules' list")
    for e in entries:
        if isinstance(e, dict) and e.get("disabled"):
            name = e.get("name")
            if not name:
                raise ValueError(f"{path}: 'disabled' entry needs a 'name'")
            rules.pop(name, None)
            continue
        r = parse_rule(e)
        rules[r.name] = r
    return list(rules.values())


# -------------------------------------------------- flattened metric view


def _num(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and v == v


def _derived_metrics(counters):
    """Counter-ratio metrics rules can gate on directly: cache hit
    rates from the exact hit/miss counter pairs every
    :class:`~raft_tpu.serve.cache.ResultCache` maintains."""
    out = {}
    for name in counters or {}:
        m = re.fullmatch(r"(\w+)_hits", name)
        if not m:
            continue
        prefix = m.group(1)
        hits = counters.get(f"{prefix}_hits", 0)
        misses = counters.get(f"{prefix}_misses")
        if misses is None:
            continue
        total = hits + misses
        if total:
            out[f"derived:{prefix}_hit_rate"] = hits / total
    return out


def flatten_record(record):
    """One flat ``{selector: float}`` view of a run record — the rule
    evaluation domain.  Delegates to the PR-11 store's
    :func:`raft_tpu.obs.runs.flatten` (so rule selectors and ``obs
    runs regress`` watch patterns share one namespace) and adds the
    alerting extras: current gauge values (``gauge:<name>:value``),
    the ``derived:`` counter ratios, and the record's recompile-
    sentinel counts (``compiles.xla_compiles/xla_real_compiles`` live
    OUTSIDE the metrics snapshot — folding them in as ``counter:`` is
    what lets ``compile-budget-burn`` fire at all)."""
    from raft_tpu.obs import runs

    flat = runs.flatten(record)
    snap = record.get("snapshot") or {}
    for name, g in (snap.get("gauges") or {}).items():
        if isinstance(g, dict) and _num(g.get("value")):
            flat[f"gauge:{name}:value"] = float(g["value"])
    flat.update(_derived_metrics(snap.get("counters") or {}))
    for name, v in (record.get("compiles") or {}).items():
        if _num(v):
            flat.setdefault(f"counter:{name}", float(v))
    return flat


def flatten_snapshot(snap):
    """Flatten a live :func:`raft_tpu.obs.metrics.snapshot` (what the
    evaluator daemon feeds the engine every tick).  The recompile
    sentinel's real-vs-total compile counts ride along (same source
    the run store records), so the ``compile-budget-burn`` rule sees
    live steady-state compiles too."""
    from raft_tpu.obs import runs

    return flatten_record({"snapshot": snap,
                           "compiles": runs._compile_counts()})


# -------------------------------------------------------- context registry

_CONTEXT_LOCK = threading.Lock()
#: rule-context payloads published by subsystems (e.g. the canary's
#: offending-provenance detail) and attached to fire records/events of
#: rules declaring ``context: <key>``
_CONTEXT: dict[str, dict] = {}  # raft-lint: guarded-by=_CONTEXT_LOCK


def set_context(key, payload):
    """Publish the detail payload a firing rule should carry (the
    canary names the offending replica/provenance here)."""
    with _CONTEXT_LOCK:
        if payload is None:
            _CONTEXT.pop(key, None)
        else:
            _CONTEXT[key] = dict(payload)


def get_context(key):
    if key is None:
        return None
    with _CONTEXT_LOCK:
        payload = _CONTEXT.get(key)
        return dict(payload) if payload else None


# ------------------------------------------------------------------ engine


class _RuleState:
    __slots__ = ("pending_since", "firing_since", "clear_since",
                 "last_value", "last_t", "fires", "value")

    def __init__(self):
        self.pending_since = None
        self.firing_since = None
        self.clear_since = None
        self.last_value = None   # rate_above: previous sample
        self.last_t = None
        self.fires = 0
        self.value = None        # last evaluated metric value


class AlertEngine:
    """Evaluates a rule pack against flattened metric views and owns
    the fire/resolve lifecycle (for-duration, resolve hysteresis,
    events, counters, the ``alerts_active`` gauge and the
    ``RAFT_TPU_ALERTS`` JSONL sink).

    ``clock`` is injectable (monotonic seconds) so the for-duration /
    hysteresis state machine is deterministic under test."""

    def __init__(self, rules=None, sink_path=None, clock=time.monotonic):
        self.rules = list(rules if rules is not None else default_rules())
        self.sink_path = sink_path
        self._clock = clock
        self._lock = threading.Lock()
        self._states = {r.name: _RuleState()
                        for r in self.rules}  # raft-lint: guarded-by=self._lock
        #: end time of the previous evaluate() pass (None before the
        #: first): lets a counter MINTED mid-life — breaker opens,
        #: evictions, canary failures all create their counter on
        #: first increment — register as a rate from 0 instead of
        #: silently becoming the baseline
        self._last_eval_t = None  # raft-lint: guarded-by=self._lock

    # ---------------------------------------------------------- evaluate

    def _condition(self, rule, st, flat, now):
        """(applies, condition) of one rule this tick.  A metric absent
        from the view makes every predicate but ``absent`` not-apply."""
        v = flat.get(rule.metric)
        st.value = v
        if rule.predicate == "absent":
            return True, v is None
        if v is None:
            return False, False
        if rule.predicate == "above":
            return True, v > rule.threshold
        if rule.predicate == "below":
            return True, v < rule.threshold
        # rate_above: per-second increase between consecutive samples.
        # A counter reset (value went DOWN: process restart) re-
        # baselines without firing.  A metric first seen AFTER the
        # engine's first pass was MINTED mid-life (counters are created
        # on their first increment — breaker opens, evictions, canary
        # failures), so it counts as a rate from 0 since the previous
        # pass; on the engine's first pass everything baselines
        # silently (pre-existing totals, e.g. warmup compiles, are not
        # a storm).
        prev_v, prev_t = st.last_value, st.last_t
        if prev_v is None and self._last_eval_t is not None:
            prev_v, prev_t = 0.0, self._last_eval_t
        st.last_value, st.last_t = v, now
        if prev_v is None or prev_t is None or now <= prev_t or v < prev_v:
            return True, False
        rate = (v - prev_v) / (now - prev_t)
        return True, rate > rule.threshold

    def evaluate(self, flat, now=None):
        """One evaluation pass over a flattened metric view; returns
        the list of transition records (fires + resolves) this pass
        produced.  Thread-safe: the daemon and ad-hoc callers share
        the engine."""
        now = self._clock() if now is None else float(now)
        transitions = []
        with self._lock:
            for rule in self.rules:
                st = self._states[rule.name]
                applies, cond = self._condition(rule, st, flat, now)
                if not applies:
                    continue
                if cond:
                    st.clear_since = None
                    if st.firing_since is not None:
                        continue
                    if st.pending_since is None:
                        st.pending_since = now
                    if now - st.pending_since >= rule.for_s:
                        st.firing_since = now
                        st.pending_since = None
                        st.fires += 1
                        transitions.append(self._record(
                            "fire", rule, st.value,
                            context=get_context(rule.context)))
                else:
                    st.pending_since = None
                    if st.firing_since is None:
                        continue
                    if st.clear_since is None:
                        st.clear_since = now
                    if now - st.clear_since >= rule.clear_s:
                        duration = round(now - st.firing_since, 3)
                        st.firing_since = None
                        st.clear_since = None
                        transitions.append(self._record(
                            "resolve", rule, st.value, duration_s=duration))
            n_active = sum(1 for s in self._states.values()
                           if s.firing_since is not None)
            for rec in transitions:
                self._sink_write(rec)
            self._last_eval_t = now
        metrics.gauge("alerts_active").set(n_active)
        for rec in transitions:
            if rec["kind"] == "fire":
                metrics.counter("alerts_fired").inc()
                log_event("alert_fire", rule=rec["rule"],
                          severity=rec["severity"], metric=rec["metric"],
                          value=rec["value"], threshold=rec["threshold"],
                          context=rec["context"])
                # black-box the moments before the fire: the triggering
                # rule lands in the dump filename (alert-<rule>), so a
                # fleet's FLIGHT_DIR reads as a postmortem index
                from raft_tpu.obs import flight

                flight.dump(trigger=f"alert-{rec['rule']}")
            else:
                metrics.counter("alerts_resolved").inc()
                log_event("alert_resolve", rule=rec["rule"],
                          severity=rec["severity"], metric=rec["metric"],
                          duration_s=rec["duration_s"], value=rec["value"])
        return transitions

    def _record(self, kind, rule, value, duration_s=None, context=None):
        """One sink/transition record (the ``alert-record`` schema
        family — every key below is written unconditionally)."""
        return {
            "t_unix": round(time.time(), 3),
            "kind": kind,
            "rule": rule.name,
            "severity": rule.severity,
            "metric": rule.metric,
            "predicate": rule.predicate,
            "threshold": rule.threshold,
            "value": value,
            "for_s": rule.for_s,
            "duration_s": duration_s,
            "context": context,
            "pid": os.getpid(),
        }

    def _sink_write(self, rec):
        """Append one record to the ``RAFT_TPU_ALERTS`` JSONL sink:
        a single-line ``"a"``-mode append (one write syscall) under
        the engine lock — the bounded-append idiom the structlog sink
        established; a torn multi-process interleave cannot occur
        inside one line."""
        path = self.sink_path
        if not path:
            return
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec, default=str) + "\n")
        except OSError:
            pass  # the sink is telemetry; losing a line must not crash

    # ----------------------------------------------------------- queries

    def active(self):
        """Currently-firing rules: ``[{rule, severity, metric, since_s,
        value}]``."""
        now = self._clock()
        out = []
        with self._lock:
            for rule in self.rules:
                st = self._states[rule.name]
                if st.firing_since is None:
                    continue
                out.append({"rule": rule.name, "severity": rule.severity,
                            "metric": rule.metric, "value": st.value,
                            "since_s": round(now - st.firing_since, 3),
                            "context": get_context(rule.context)})
        return out

    def summary(self):
        """JSON-ready engine state (the ``GET /alerts`` payload body)."""
        with self._lock:
            rules = [{"name": r.name, "metric": r.metric,
                      "predicate": r.predicate, "threshold": r.threshold,
                      "for_s": r.for_s, "clear_s": r.clear_s,
                      "severity": r.severity,
                      "firing": self._states[r.name].firing_since
                      is not None,
                      "fires": self._states[r.name].fires,
                      "value": self._states[r.name].value}
                     for r in self.rules]
        return {"rules": rules, "active": self.active()}


# ------------------------------------------------------------------ replay


def replay_rules(rules, record):
    """Replay a rule pack against one stored run record (``alerts eval
    --record``): no daemon, no live registry, no jax.

    A record is ONE snapshot, so time-domain semantics collapse:
    ``for_s`` is ignored, and ``rate_above`` rules gate on the
    counter's cumulative TOTAL exceeding the rule's ``replay_above``
    (a whole-session total of zero breaches/opens/compiles is the
    clean bar the fixtures pin).  Returns ``(fired, checked)``."""
    flat = flatten_record(record)
    fired, checked = [], 0
    for rule in rules:
        v = flat.get(rule.metric)
        if rule.predicate == "absent":
            checked += 1
            cond = v is None
        elif v is None:
            continue
        elif rule.predicate == "above":
            checked += 1
            cond = v > rule.threshold
        elif rule.predicate == "below":
            checked += 1
            cond = v < rule.threshold
        else:  # rate_above
            checked += 1
            cond = v > rule.replay_above
        if cond:
            fired.append({"rule": rule.name, "severity": rule.severity,
                          "metric": rule.metric, "value": v,
                          "threshold": (rule.replay_above
                                        if rule.predicate == "rate_above"
                                        else rule.threshold),
                          "help": rule.help})
    return fired, checked


# -------------------------------------------------------------- sink reads


def read_sink(path):
    """Parse one ``RAFT_TPU_ALERTS`` JSONL sink; returns ``(records,
    n_bad_lines)`` — damaged lines counted, never fatal."""
    records, bad = [], 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            # both keys the renderer hard-subscripts must be present —
            # a foreign/truncated record counts as unparseable, it
            # must not crash `alerts list --sink`
            if isinstance(rec, dict) and "rule" in rec and "kind" in rec:
                records.append(rec)
            else:
                bad += 1
    return records, bad


def render_sink_summary(records):
    """Human-readable lines over parsed sink records (``alerts list
    --sink``)."""
    out = []
    for rec in records:
        t = time.strftime("%Y-%m-%d %H:%M:%S",
                          time.localtime(rec.get("t_unix") or 0))
        line = (f"{t}  {rec['kind']:8s} {rec['rule']:24s} "
                f"[{rec.get('severity') or '?'}] "
                f"{rec.get('metric') or '?'} = {rec.get('value')}")
        if rec.get("duration_s") is not None:
            line += f" (fired {rec.get('duration_s')}s)"
        if rec.get("context"):
            line += f"  context={json.dumps(rec.get('context'), default=str)}"
        out.append(line)
    return out


# ----------------------------------------------------- provenance codec

#: field order of the ``x-raft-provenance`` header (fixed, so the
#: header is byte-stable for a given provenance dict); ``release`` is
#: last — pre-release replicas simply omit it and old parsers ignore it
PROVENANCE_FIELDS = ("bank_key", "bank_sha", "code", "flags", "replica",
                     "release")


def format_provenance(prov):
    """``x-raft-provenance`` header value from a provenance dict:
    ``bank_key=..;bank_sha=..;code=..;flags=..;replica=..`` (known
    fields in fixed order; values sanitized to header-safe chars)."""
    parts = []
    for k in PROVENANCE_FIELDS:
        v = prov.get(k)
        if v is None:
            continue
        v = re.sub(r"[;=\s]", "_", str(v))
        parts.append(f"{k}={v}")
    return ";".join(parts)


def parse_provenance(value):
    """Parse an ``x-raft-provenance`` header into a dict, or None when
    the value is empty/garbled (a consumer must never crash on a
    foreign header)."""
    if not value or not isinstance(value, str):
        return None
    out = {}
    for part in value.split(";"):
        k, sep, v = part.partition("=")
        if sep and k.strip():
            out[k.strip()] = v.strip()
    return out or None


def provenance_consistency(by_design, releases=None):
    """Cross-replica provenance verdict over ``{design: {replica:
    prov_dict}}``: two replicas serving the SAME design must agree on
    the bank payload sha, bank key, code hash and flags key (replica
    id legitimately differs).  Returns ``{"consistent": bool,
    "splits": [{design, field, values: {replica: value}}]}`` — the
    canary feeds this into the ``canary_parity`` rule context so the
    alert payload names the offending provenance.

    ``releases`` makes the verdict VERSION-AWARE (:func:`raft_tpu.
    aot.release.parity_context`): ``{"allowed": [release ids
    legitimately in the fleet], "entries": {release_id: [16-char
    payload sha prefixes]}}``.  A mid-rollout fleet spans two release
    ids, so cross-replica comparison happens *within* a release group
    (mixed-version skew is expected, not an alarm), while a replica
    stamping an id outside ``allowed`` — or a bank sha its own
    release's manifest never shipped — is a genuine skew even when it
    is the lone replica on that release (the seeded
    ``provenance_skew`` drill).  ``releases=None`` is exactly the
    pre-release behavior."""
    allowed = set((releases or {}).get("allowed") or ())
    manifest_shas = {rid: set(shas) for rid, shas in
                     ((releases or {}).get("entries") or {}).items()}
    splits = []
    for design in sorted(by_design or {}):
        provs = {rid: p for rid, p in (by_design[design] or {}).items()
                 if p}
        if releases:
            groups = {}
            for rid, p in provs.items():
                rel = p.get("release") or "none"
                if allowed and rel not in allowed:
                    splits.append({"design": design, "field": "release",
                                   "values": {rid: rel}})
                    continue
                sha = p.get("bank_sha") or "none"
                shipped = manifest_shas.get(rel)
                if shipped is not None and sha not in shipped \
                        and sha != "none":
                    splits.append({"design": design, "field": "bank_sha",
                                   "values": {rid: sha}})
                    continue
                groups.setdefault(rel, {})[rid] = p
        else:
            groups = {None: provs}
        for group in groups.values():
            if len(group) < 2:
                continue
            for field in ("bank_sha", "bank_key", "code", "flags"):
                values = {rid: (p.get(field) or "none")
                          for rid, p in group.items()}
                if len(set(values.values())) > 1:
                    splits.append({"design": design, "field": field,
                                   "values": dict(sorted(values.items()))})
    return {"consistent": not splits, "splits": splits}


# ------------------------------------------------------ process lifecycle


class AlertDaemon(threading.Thread):
    """Daemon thread evaluating the engine against the live metrics
    registry every ``interval_s`` seconds (``RAFT_TPU_ALERT_EVAL_S``)."""

    def __init__(self, engine, interval_s=None):
        super().__init__(name="raft-alert-eval", daemon=True)
        self.engine = engine
        self.interval_s = float(interval_s if interval_s is not None
                                else config.get("ALERT_EVAL_S"))
        self._stop_evt = threading.Event()

    def run(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.engine.evaluate(flatten_snapshot(metrics.snapshot()))
            except Exception:
                pass  # a bad pass must never kill alerting

    def stop(self):
        self._stop_evt.set()
        self.join(timeout=2.0)


_INSTALL_LOCK = threading.Lock()
#: the process-wide (engine, daemon) pair, installed at most once
_INSTALLED: list = []  # raft-lint: guarded-by=_INSTALL_LOCK


def maybe_start():
    """Start the in-process alert evaluator when
    ``RAFT_TPU_ALERT_EVAL_S`` > 0 (idempotent; returns the daemon or
    None).  With the flag unset this is a float compare and nothing
    else — the zero-overhead invariant."""
    interval = float(config.get("ALERT_EVAL_S") or 0)
    if interval <= 0:
        return None
    with _INSTALL_LOCK:
        if _INSTALLED:
            return _INSTALLED[1]
        rules = load_rules(config.get("ALERT_RULES") or None)
        engine = AlertEngine(rules, sink_path=config.get("ALERTS") or None)
        daemon = AlertDaemon(engine, interval)
        daemon.start()
        _INSTALLED[:] = [engine, daemon]
    return daemon


def installed_engine():
    with _INSTALL_LOCK:
        return _INSTALLED[0] if _INSTALLED else None


def stop():
    """Stop + uninstall the process evaluator (idempotent)."""
    with _INSTALL_LOCK:
        if not _INSTALLED:
            return
        _engine, daemon = _INSTALLED
        _INSTALLED[:] = []
    daemon.stop()


def endpoint_payload():
    """The ``GET /alerts`` body: engine state when the evaluator is
    installed, an explicit ``enabled: false`` otherwise."""
    engine = installed_engine()
    if engine is None:
        return {"ok": True, "enabled": False, "active": [], "rules": []}
    return {"ok": True, "enabled": True, **engine.summary()}
