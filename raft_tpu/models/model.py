"""Top-level Model: frequency grid, FOWTs, load-case analysis.

The array-level equivalent of the reference Model
(``/root/reference/raft/raft_model.py:27-2245``).  Round-1 scope:
single-FOWT construction, statics, Morison hydro and the dynamics
solve; arrays/farms and potential flow wired in later milestones.
"""

from __future__ import annotations

import numpy as np

from raft_tpu.models.fowt import FOWTStructure
from raft_tpu.structure.schema import coerce, frequency_grid, load_design, parse_cases
from raft_tpu.ops.waves import wave_number_ref


class Model:
    def __init__(self, design):
        design = load_design(design)
        self.design = design

        settings = design.get("settings", {}) or {}
        self.XiStart = coerce(settings, "XiStart", default=0.1)
        self.nIter = int(coerce(settings, "nIter", default=15, dtype=int))

        self.w = frequency_grid(design)
        self.nw = len(self.w)
        self.depth = float(coerce(design["site"], "water_depth"))
        # reference-compatible dispersion solve (loose 1e-3 iteration,
        # raft_model.py:63-65) so downstream values match golden data
        self.k = wave_number_ref(self.w, self.depth)

        self.cases = parse_cases(design)

        # single-FOWT mode (array mode in a later milestone)
        self.fowtList = [FOWTStructure(design, depth=self.depth)]
        self.nDOF = sum(f.nDOF for f in self.fowtList)
