"""Design-optimization API: the WEIS/OpenMDAO-facing surface.

Equivalent of the reference's ``omdao_raft.RAFT_OMDAO``
(``/root/reference/raft/omdao_raft.py``: inputs :26-343, compute
:343-818, output mapping :820-887): one ``compute`` call is one design
evaluation — build the model, solve statics/dynamics over the case
table, and return flat outputs (platform properties, response
statistics, natural periods, WEIS aggregates).

Because the heavy path here is jit-compiled jax, an optimizer loop
amortizes compilation across iterations, and gradient-based optimizers
can switch to the differentiable design axis in
:func:`raft_tpu.api.make_design_evaluator` instead of finite
differences.

The OpenMDAO ``ExplicitComponent`` subclass is provided when openmdao
is importable (it is not part of this image); the dict-based
``DesignEvaluation`` below carries the same contract without the
dependency.
"""

from __future__ import annotations

import copy

import numpy as np


class DesignEvaluation:
    """One-design-in, flat-metrics-out evaluation for optimizer loops."""

    def __init__(self, base_design):
        from raft_tpu.structure.schema import load_design

        self.base_design = load_design(base_design)

    def compute(self, overrides=None):
        """Evaluate a design variant.

        overrides: dict of dotted design-path -> value, e.g.
        ``{"platform.members.0.d": [...], "mooring.lines.0.length": 870}``.
        Returns flat outputs (properties_*, per-case stats_*, periods,
        WEIS aggregates Max_Offset / Max_PtfmPitch).
        """
        import raft_tpu

        design = copy.deepcopy(self.base_design)
        for path, value in (overrides or {}).items():
            node = design
            keys = path.split(".")
            for k in keys[:-1]:
                node = node[int(k)] if isinstance(node, list) else node[k]
            k = keys[-1]
            if isinstance(node, list):
                node[int(k)] = value
            else:
                node[k] = value

        model = raft_tpu.Model(design)
        model.analyze_cases()
        stat = model.statics(0)

        out = {
            # platform properties (omdao_raft.py:253-273)
            "properties_substructure_mass": float(stat["m_sub"]),
            "properties_total_mass": float(stat["m"]),
            "properties_displacement": float(stat["V"]),
            "properties_AWP": float(stat["AWP"]),
            "properties_center_of_mass": np.asarray(stat["rCG"]),
            "properties_center_of_buoyancy": np.asarray(stat["rCB"]),
            "properties_metacentric_height": float(stat["rM"][2] - stat["rCG"][2]),
        }

        # natural periods (omdao_raft.py:858-866)
        fns, _ = model.solve_eigen()
        out["rigid_body_periods"] = 1.0 / np.maximum(np.asarray(fns), 1e-12)

        # per-case statistics + WEIS aggregates (omdao_raft.py:275-336)
        max_offset = 0.0
        max_pitch = 0.0
        for iCase, per_fowt in model.results["case_metrics"].items():
            for ifowt, m in per_fowt.items():
                for ch in ("surge", "sway", "heave", "roll", "pitch", "yaw"):
                    for s in ("avg", "std", "max"):
                        out[f"stats_{ch}_{s}_case{iCase}_fowt{ifowt}"] = float(
                            m[f"{ch}_{s}"])
                off = np.hypot(float(m["surge_max"]), float(m["sway_max"]))
                max_offset = max(max_offset, off)
                max_pitch = max(max_pitch, abs(float(m["pitch_max"])))
                if "Tmoor_avg" in m:
                    out[f"stats_Tmoor_max_case{iCase}_fowt{ifowt}"] = float(
                        np.max(np.asarray(m["Tmoor_max"])))
        out["Max_Offset"] = max_offset
        out["Max_PtfmPitch"] = max_pitch
        return out


try:  # OpenMDAO component wrapper (optional dependency)
    import openmdao.api as om

    class RAFT_TPU_Component(om.ExplicitComponent):
        """ExplicitComponent exposing DesignEvaluation to WEIS-style
        optimization problems (omdao_raft.RAFT_OMDAO analog)."""

        def initialize(self):
            self.options.declare("base_design")
            self.options.declare("design_vars", types=dict,
                                 desc="input name -> dotted design path")
            self.options.declare("outputs", types=list)

        def setup(self):
            self._eval = DesignEvaluation(self.options["base_design"])
            for name in self.options["design_vars"]:
                self.add_input(name)
            for name in self.options["outputs"]:
                self.add_output(name)

        def compute(self, inputs, outputs):
            overrides = {
                path: float(inputs[name])
                for name, path in self.options["design_vars"].items()
            }
            res = self._eval.compute(overrides)
            for name in self.options["outputs"]:
                outputs[name] = res[name]

except ImportError:  # pragma: no cover - openmdao absent in this image
    RAFT_TPU_Component = None
