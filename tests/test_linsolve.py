"""Parity and flag-gating tests for the batched small-N complex solver
(:mod:`raft_tpu.ops.linsolve`) against the generic ``jnp.linalg.solve``
LAPACK path, on real impedance matrices from the bundled designs and on
synthetic systems across the supported size range."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.ops import linsolve

EXAMPLES = "/root/reference/examples"
BUNDLED = [
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "raft_tpu", "designs", "spar_demo.yaml"),
    # reference example designs carry full aero chains — slow tier
    pytest.param(os.path.join(EXAMPLES, "VolturnUS-S_example.yaml"),
                 marks=pytest.mark.slow),
    pytest.param(os.path.join(EXAMPLES, "OC3spar.yaml"),
                 marks=pytest.mark.slow),
]


def _impedance_from_design(path):
    import raft_tpu

    model = raft_tpu.Model(path)
    case = dict(model.cases[0]) if model.cases else {
        "wave_spectrum": "JONSWAP", "wave_height": 4.0, "wave_period": 10.0,
        "wave_heading": 0.0, "wind_speed": 0.0, "turbulence": 0.0,
        "turbine_status": "operating", "yaw_misalign": 0.0,
        "current_speed": 0.0, "current_heading": 0.0}
    _, info = model.solve_dynamics(case)
    return np.asarray(info["Z"])


@pytest.mark.parametrize("path", BUNDLED)
def test_native_matches_lapack_on_design_impedances(path):
    """Native solver vs jnp.linalg.solve <= 1e-10 on the converged
    impedance tensors of every bundled design (the tentpole's parity
    gate)."""
    if not os.path.exists(path):
        pytest.skip("design unavailable in this container")
    Z = _impedance_from_design(path)  # (nw, nDOF, nDOF) complex
    nDOF = Z.shape[-1]
    if nDOF > linsolve.MAX_NATIVE_N:
        pytest.skip("native kernel only specialises N <= 12")
    rng = np.random.default_rng(7)
    F = (rng.normal(size=Z.shape[:-1]) + 1j * rng.normal(size=Z.shape[:-1]))
    x_ref = np.asarray(linsolve.solve(jnp.asarray(Z), jnp.asarray(F),
                                      path="lapack"))
    x_nat = np.asarray(linsolve.solve(jnp.asarray(Z), jnp.asarray(F),
                                      path="native"))
    scale = np.max(np.abs(x_ref))
    assert np.max(np.abs(x_nat - x_ref)) <= 1e-10 * scale


@pytest.mark.parametrize("N", [1, 2, 3, 6, 9, 12])
def test_native_synthetic_sizes(N):
    """Impedance-structured random systems across the specialised size
    range, with RHS batch broadcasting (the system_response layout)."""
    rng = np.random.default_rng(N)
    nw, nH = 17, 3
    M = rng.normal(size=(N, N))
    M = M @ M.T + N * np.eye(N)
    C = rng.normal(size=(N, N))
    C = C @ C.T + N * np.eye(N)
    B = rng.normal(size=(N, N))
    B = 0.05 * B @ B.T + 0.1 * np.eye(N)
    w = np.linspace(0.01, 2.0, nw)
    Z = -(w**2)[:, None, None] * M + 1j * w[:, None, None] * B + C
    F = rng.normal(size=(nH, nw, N)) + 1j * rng.normal(size=(nH, nw, N))
    x_ref = np.linalg.solve(Z[None], F[..., None])[..., 0]
    x_nat = np.asarray(linsolve.solve(jnp.asarray(Z), jnp.asarray(F),
                                      path="native"))
    assert x_nat.shape == x_ref.shape
    assert np.max(np.abs(x_nat - x_ref)) <= 1e-10 * np.max(np.abs(x_ref))


def test_solver_flag(monkeypatch):
    """RAFT_TPU_SOLVER gating: default native, explicit lapack, large-N
    fallback, loud failure on typos."""
    monkeypatch.delenv("RAFT_TPU_SOLVER", raising=False)
    assert linsolve.solver_path(6) == "native"
    assert linsolve.solver_path(linsolve.MAX_NATIVE_N + 1) == "lapack"
    monkeypatch.setenv("RAFT_TPU_SOLVER", "lapack")
    assert linsolve.solver_path(6) == "lapack"
    monkeypatch.setenv("RAFT_TPU_SOLVER", "numpy")
    with pytest.raises(ValueError):
        linsolve.solver_path(6)


@pytest.mark.slow
def test_pallas_prototype_parity():
    """The Pallas block-GE kernel (RAFT_TPU_SOLVER=pallas) in interpret
    mode on CPU: same algebra as the native SSA elimination, validated
    on impedance-structured systems incl. RHS broadcasting and a
    non-multiple-of-block batch (edge-replicated pad lanes dropped).
    Slow tier: interpret-mode pallas_call + the reference solves
    compile (house rule: anything that compiles is slow-marked — the
    tier-1 wall budget has ~1 min of slack)."""
    rng = np.random.default_rng(3)
    N, nw, nH = 6, 17, 2
    M = rng.normal(size=(N, N))
    M = M @ M.T + N * np.eye(N)
    B = rng.normal(size=(N, N))
    B = 0.05 * B @ B.T + 0.1 * np.eye(N)
    C = rng.normal(size=(N, N))
    C = C @ C.T + N * np.eye(N)
    w = np.linspace(0.01, 2.0, nw)
    Z = -(w**2)[:, None, None] * M + 1j * w[:, None, None] * B + C
    F = rng.normal(size=(nH, nw, N)) + 1j * rng.normal(size=(nH, nw, N))
    x_ref = np.linalg.solve(Z[None], F[..., None])[..., 0]
    x_pal = np.asarray(linsolve.solve(jnp.asarray(Z), jnp.asarray(F),
                                      path="pallas"))
    assert x_pal.shape == x_ref.shape
    scale = np.max(np.abs(x_ref))
    assert np.max(np.abs(x_pal - x_ref)) <= 1e-10 * scale
    # bit-level agreement with the native kernel is NOT promised (lane
    # layout differs) but the elimination is the same algebra
    x_nat = np.asarray(linsolve.solve(jnp.asarray(Z), jnp.asarray(F),
                                      path="native"))
    assert np.max(np.abs(x_pal - x_nat)) <= 1e-12 * scale
    # cond_estimate rides the flagged path too
    k_pal = np.asarray(linsolve.cond_estimate(jnp.asarray(Z),
                                              path="pallas"))
    k_nat = np.asarray(linsolve.cond_estimate(jnp.asarray(Z),
                                              path="native"))
    np.testing.assert_allclose(k_pal, k_nat, rtol=1e-10)


def test_pallas_flag_gates(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_SOLVER", "pallas")
    assert linsolve.solver_path(6) == "pallas"
    # oversized systems still fall back to lapack under the flag
    assert linsolve.solver_path(linsolve.MAX_NATIVE_N + 1) == "lapack"


@pytest.mark.slow
def test_pallas_under_jit_small_block():
    """The kernel inside jit with a sub-batch block size (grid > 1):
    interpret-mode lowering composes with jit/XLA on CPU."""
    import jax

    rng = np.random.default_rng(5)
    N, B_ = 4, 11
    Z = rng.normal(size=(B_, N, N)) + 1j * rng.normal(size=(B_, N, N)) \
        + 4j * np.eye(N)
    F = rng.normal(size=(B_, N)) + 1j * rng.normal(size=(B_, N))

    fn = jax.jit(lambda z, f: linsolve._pallas_solve(z, f, block=4))
    x = np.asarray(fn(jnp.asarray(Z), jnp.asarray(F)))
    x_ref = np.linalg.solve(Z, F[..., None])[..., 0]
    assert np.max(np.abs(x - x_ref)) <= 1e-10 * np.max(np.abs(x_ref))


def test_large_n_takes_lapack_even_when_forced(monkeypatch):
    """A 16-DOF system routed with path='native' must still fall back —
    the unrolled kernel is only generated for N <= MAX_NATIVE_N."""
    rng = np.random.default_rng(0)
    N = linsolve.MAX_NATIVE_N + 4
    A = rng.normal(size=(N, N)) + 1j * rng.normal(size=(N, N)) + 3 * np.eye(N)
    b = rng.normal(size=(N,)) + 0j
    x = np.asarray(linsolve.solve(jnp.asarray(A), jnp.asarray(b),
                                  path="native"))
    assert np.allclose(A @ x, b, atol=1e-10)
