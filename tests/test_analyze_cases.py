"""End-to-end analyzeCases parity vs reference goldens.

Exercises the full chain: statics -> mooring equilibrium -> (aero-servo
constants) -> wave excitation -> iterative drag linearisation ->
impedance solve -> response statistics, against
*_true_analyzeCases.pkl.

Tolerances: the no-wind case matches at golden tolerance (1e-5); the
WIND case carries the ~1% BEMT-vs-CCBlade load/derivative deviation
through the aero damping and mean thrust, so motion PSDs are gated at
1.5e-2 relative to the spectral peak.

Known golden anomalies (measured, documented rather than hidden):

* The OC3 wind-case ``Tmoor_PSD`` golden — RESOLVED as a golden-side
  Jacobian artifact (round 5, test_oc3_wind_tmoor_decomposition):
  fitting a CONSTANT per-line-end tension Jacobian to the golden PSDs
  using the golden's own stored motion RAs reproduces them to ~1e-14
  relative, so the golden contains NO frequency-dependent (line-
  dynamics) content at all — round 4's "line-inertia-like" reading was
  wrong.  The fitted Jacobian's translational columns match our 0.1-m
  central-secant catenary Jacobian to ~1e-4, but its roll/pitch
  columns are 0.086-0.10x the true rotational derivative of the SAME
  catenary tension function that reproduces the golden's mean tensions
  to 1e-3 — an effective fairlead lever arm of ~7 m where the OC3
  fairleads sit 70 m below the rotation point, inconsistent with any
  rotation point of the platform (best-fit z* still leaves 92% error)
  and with any finite-difference step size of the true catenary.  The
  golden inherits this from the MoorPy build that generated it
  (getCoupledStiffness(tensions=True) rotational columns; MoorPy is
  not in this image to pin the exact defect).  Our rotational columns
  are the physically-correct ones (the lumped-mass line dynamics
  reduces to this same Jacobian at w -> 0,
  tests/test_mooring_dynamics.py::test_quasi_static_tension_limit), so
  the production path keeps them; the wind-case tension-spectrum gate
  stays loose only because pitch response is significant there (the
  no-wind case, where rotational contributions are negligible, matches
  at 3e-5).
* RESOLVED (round 4): the VolturnUS-S goldens' ~1.2e5 N mean surge
  force in the no-wind case is the slender-body-QTF mean drift fed back
  into the equilibrium — the reference re-runs solveStatics with
  Fhydro_2nd_mean for ANY potSecOrder > 0 (raft_model.py:316-328), and
  with the same feedback our means match at ~1%
  (test_analyze_cases_volturn_meandrift).  The VolturnUS WIND case
  remains off in the low-frequency 2nd-order band (motion-dependent
  QTF terms with wind-included RAOs; deviations up to ~0.9 of the tiny
  yaw channel) and stays out of the gated set for now.
"""

import os
import pickle

import numpy as np
import pytest
from numpy.testing import assert_allclose

from tests.conftest import ref_data

import raft_tpu

pytestmark = pytest.mark.slow

METRICS = [
    "wave_PSD", "surge_PSD", "sway_PSD", "heave_PSD", "roll_PSD",
    "pitch_PSD", "yaw_PSD", "AxRNA_PSD", "Mbase_PSD", "Tmoor_PSD",
]


def test_analyze_cases_oc3_nowind():
    path = ref_data("OC3spar.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    res = model.analyze_cases()
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)

    # case 0 has wind_speed == 0 (no aero); golden-tolerance parity
    iCase = 0
    assert model.cases[iCase]["wind_speed"] == 0
    for metric in METRICS:
        a = np.asarray(res["case_metrics"][iCase][0][metric])
        b = np.asarray(true["case_metrics"][iCase][0][metric])
        if metric == "Tmoor_PSD":
            # the reference's tension spectra inherit MoorPy's coarse
            # 0.1-step finite-difference tension Jacobian (including a
            # 0.1 *rad* rotational step); we replicate the secant but
            # small catenary-model differences remain visible at ~3e-5
            assert_allclose(a, b, rtol=3e-5, atol=1e-3, err_msg=metric)
        else:
            assert_allclose(a, b, rtol=1e-5, atol=1e-3, err_msg=metric)

    # ---- WIND case (case 1, 10 m/s operating): full aero-servo chain.
    iCase = 1
    assert model.cases[iCase]["wind_speed"] > 0
    mc = res["case_metrics"][iCase][0]
    gc = true["case_metrics"][iCase][0]
    # mean offsets carry the mean rotor thrust through the equilibrium;
    # gate covers the reference's own 0.05 m solveStatics tolerance on
    # the ~28 m offset (turbine constants at the case-start zero pose,
    # raft_model.py:602, shift the converged mean by ~7 mm)
    assert_allclose(float(np.asarray(mc["surge_avg"])),
                    float(np.asarray(gc["surge_avg"])), rtol=2e-3)
    assert_allclose(float(np.asarray(mc["pitch_avg"])),
                    float(np.asarray(gc["pitch_avg"])), rtol=2e-3)
    # motion spectra: the deviations are budgeted to the single
    # mean-rotor-load path at ~0.2-0.3% effective load deviation
    # (test_oc3_wind_error_budget); gates at ~1.5x measured
    for metric, gate in (("wave_PSD", 1.2e-2), ("surge_PSD", 1.0e-2),
                         ("heave_PSD", 1.0e-2), ("pitch_PSD", 1.2e-2),
                         ("yaw_PSD", 1.0e-2), ("AxRNA_PSD", 1.5e-2),
                         ("Mbase_PSD", 1.5e-2)):
        a = np.asarray(mc[metric])
        b = np.asarray(gc[metric])
        scale = np.max(np.abs(b)) + 1e-12
        assert np.max(np.abs(a - b)) / scale < gate, metric
    # mean tensions at the wind-loaded offset
    assert_allclose(np.asarray(mc["Tmoor_avg"]), np.asarray(gc["Tmoor_avg"]),
                    rtol=1e-3)
    # tension spectra: loose gate only (see module docstring)
    a = np.asarray(mc["Tmoor_PSD"])
    b = np.asarray(gc["Tmoor_PSD"])
    assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < 0.5


def test_oc3_wind_tmoor_decomposition():
    """Quantified resolution of the OC3 wind-case Tmoor anomaly (see
    module docstring).  Asserts, from the golden's own data:

    1. the golden tension PSDs are EXACTLY a constant-Jacobian
       realization of the golden's stored motion RAs (fit residual
       < 1e-8 relative) — no frequency-dependent line-dynamics content;
    2. the fitted Jacobian's translational columns match our catenary
       tension Jacobian (same 0.1 central secant as MoorPy) to 5e-4;
    3. the fitted rotational (roll/pitch) columns are 0.07-0.11x ours —
       the golden-side artifact — while our mean tensions match the
       golden to 1e-3, pinning our catenary as consistent with the
       golden's own means.
    """
    from scipy.optimize import least_squares

    import jax.numpy as jnp
    from raft_tpu.models.outputs import mooring_tension_vector

    path = ref_data("OC3spar.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)
    gc = true["case_metrics"][1][0]
    RAD = np.pi / 180.0
    RA = np.stack(
        [np.asarray(gc["surge_RA"]), np.asarray(gc["sway_RA"]),
         np.asarray(gc["heave_RA"]), RAD * np.asarray(gc["roll_RA"]),
         RAD * np.asarray(gc["pitch_RA"]), RAD * np.asarray(gc["yaw_RA"])],
        axis=1)  # (nsources, 6, nw)
    X0 = np.array(
        [float(np.asarray(gc[c + "_avg"])) for c in ("surge", "sway", "heave")]
        + [RAD * float(np.asarray(gc[c + "_avg"]))
           for c in ("roll", "pitch", "yaw")])

    model = raft_tpu.Model(path)
    ms = model.ms_list[0]
    dw = model.w[1] - model.w[0]
    gpsd = np.asarray(gc["Tmoor_PSD"])

    f = lambda x: np.asarray(mooring_tension_vector(ms, jnp.asarray(x)))
    # (3) our catenary reproduces the golden mean tensions
    np.testing.assert_allclose(f(X0), np.asarray(gc["Tmoor_avg"]), rtol=1e-3)

    dx = 0.1
    J = np.stack([(f(X0 + np.eye(6)[j] * dx) - f(X0 - np.eye(6)[j] * dx))
                  / (2 * dx) for j in range(6)], axis=1)

    def predict(Jt):
        amps = np.einsum("j,hjw->hw", Jt, RA)
        return np.sum(0.5 * np.abs(amps) ** 2 / dw, axis=0)

    for iT in range(gpsd.shape[0]):
        sol = least_squares(
            lambda Jt: (predict(Jt) - gpsd[iT]) / gpsd[iT].max(),
            J[iT], method="lm", max_nfev=20000)
        # (1) constant Jacobian reproduces the golden exactly
        assert np.abs(sol.fun).max() < 1e-8, iT
        # (2) translational columns agree
        np.testing.assert_allclose(sol.x[:3], J[iT, :3], rtol=5e-4,
                                   err_msg=f"end {iT} translational")
        # (3) rotational columns are the golden-side ~0.1x artifact
        for j in (3, 4):
            if abs(J[iT, j]) > 1e4:
                ratio = sol.x[j] / J[iT, j]
                assert 0.07 < ratio < 0.11, (iT, j, ratio)


def test_oc3_wind_error_budget():
    """Error budget for the wind-case PSD gates (VERDICT r4 Weak #3):
    decomposes the 1e-2-level deviations into their aero sources by
    direct sensitivity measurement (perturb one turbine-constant group
    by +1%, re-solve, measure the PSD shift).

    Measured on this host (f64 CPU), deviation and sensitivity both
    relative to the golden/base spectral peak:

    channel    | dev vs golden | sens/+1% f_aero0 | sens/+1% B_aero | implied mean-load dev
    surge_PSD  |   5.7e-3      |   2.6e-2         |   2.2e-3        |   0.22%
    pitch_PSD  |   8.2e-3      |   4.0e-2         |   2.4e-3        |   0.20%
    heave_PSD  |   3.7e-4      |   1.6e-3         |   1.0e-4        |   0.23%
    yaw_PSD    |   3.8e-3      |   3.0e-2         |   8.0e-4        |   0.13%
    AxRNA_PSD  |   1.1e-2      |   3.7e-2         |   2.0e-3        |   0.30%

    (f_aero turbulence excitation and A_aero have ZERO motion-PSD
    sensitivity: the rotor excitation source row is zero by reference
    convention — the block is commented out at raft_model.py:1238-1247.)

    Every channel implies the SAME ~0.2-0.3% effective mean-rotor-load
    deviation, i.e. the whole wind-case gap is the single mean-load
    path (BEMT vs CCBlade at this operating point), matching the
    independently-gated 2e-3 mean-offset agreement.  The aero-damping
    path contributes <1e-3 at the known ~1% derivative agreement.  The
    1.5e-2 gates are therefore budgeted, not hopeful; this test pins
    the attribution so a regression in a DIFFERENT path (excitation,
    damping sign, equilibrium) cannot hide inside the gate.
    """
    path = ref_data("OC3spar.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    from raft_tpu.models.outputs import turbine_outputs

    model = raft_tpu.Model(path)
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)
    case = model.cases[1]
    gc = true["case_metrics"][1][0]
    channels = ("surge_PSD", "pitch_PSD", "heave_PSD", "yaw_PSD",
                "AxRNA_PSD")

    def run(scale=None):
        orig = model.turbine_constants
        model._aero_cache = {}
        if scale:
            def patched(c, ifowt=0):
                out = dict(orig(c, ifowt))
                for k, f in scale.items():
                    out[k] = out[k] * f
                return out
            model.turbine_constants = patched
        try:
            X0 = model.solve_statics(case)
            Xi, info = model.solve_dynamics(case, X0=X0)
        finally:
            model.turbine_constants = orig
            model._aero_cache = {}
        tc = model.turbine_constants(case)
        return turbine_outputs(
            model, case, np.asarray(X0), np.asarray(Xi),
            info["infos"][0]["S"], info["infos"][0]["zeta"],
            A_aero=np.asarray(tc["A00"]).T, B_aero=np.asarray(tc["B00"]).T,
            f_aero0=tc["f_aero0"], ifowt=0, rotor_info=tc.get("rotor_info"))

    base = run()
    pert = run({"f_aero0": 1.01})

    implied = {}
    for met in channels:
        a = np.asarray(base[met])
        b = np.asarray(gc[met])
        dev = np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12)
        p = np.asarray(pert[met])
        sens = np.max(np.abs(p - a)) / (np.max(np.abs(a)) + 1e-12)
        implied[met] = dev / max(sens, 1e-12)  # percent of mean load
    # single-cause attribution: every channel's deviation corresponds to
    # the same small effective mean-load deviation
    vals = np.array(list(implied.values()))
    assert np.all(vals < 0.45), implied      # < 0.45% mean-load dev
    assert np.all(vals > 0.05), implied      # and not accidentally zero
    assert vals.max() / vals.min() < 4.0, implied  # consistent across ch.


def test_analyze_cases_flexible_wind():
    """VolturnUS-S-flexible analyzeCases parity — BOTH cases, including
    the 10 m/s operating-turbine case through the aero-servo chain on a
    flexible-tower (multibody) model.

    Measured deviations (f64 CPU): case 0 motion PSDs ~2e-10 (golden
    level), Tmoor 1.2e-4; case 1 motion PSDs 4-5e-3 (the ~1% BEMT
    derivative deviation through the aero damping), AxRNA 1.1e-2,
    Tmoor 2e-2.  Gates at ~1.5x measured.  Mbase (FE tower-base moment)
    is gated loosely: the load recovery -Kf @ Xi is a near-cancellation
    that amplifies the small flexible-DOF response deviations (case 0
    3.4e-2 with motions at 1e-10; case 1 ~0.53 via the wind-band
    flexible response — the aero damping's effect on the tower-mode
    rows, invisible in the platform-motion channels).
    """
    path = ref_data("VolturnUS-S-flexible.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    res = model.analyze_cases()
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)

    mc = res["case_metrics"][0][0]
    gc = true["case_metrics"][0][0]
    for metric in ("surge_PSD", "heave_PSD", "pitch_PSD", "yaw_PSD",
                   "AxRNA_PSD"):
        a, b = np.asarray(mc[metric]), np.asarray(gc[metric])
        assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < 1e-8, metric
    a, b = np.asarray(mc["Tmoor_PSD"]), np.asarray(gc["Tmoor_PSD"])
    assert np.max(np.abs(a - b)) / np.max(np.abs(b)) < 1e-3
    a, b = np.asarray(mc["Mbase_PSD"]), np.asarray(gc["Mbase_PSD"])
    assert np.max(np.abs(a - b)) / np.max(np.abs(b)) < 8e-2

    mc = res["case_metrics"][1][0]
    gc = true["case_metrics"][1][0]
    assert model.cases[1]["wind_speed"] > 0
    assert_allclose(float(np.asarray(mc["surge_avg"])),
                    float(np.asarray(gc["surge_avg"])), rtol=1e-2)
    assert_allclose(float(np.asarray(mc["pitch_avg"])),
                    float(np.asarray(gc["pitch_avg"])), rtol=5e-2)
    for metric, gate in (("surge_PSD", 1e-2), ("heave_PSD", 1e-2),
                         ("pitch_PSD", 1e-2), ("AxRNA_PSD", 2e-2),
                         ("Tmoor_PSD", 3e-2), ("Mbase_PSD", 0.6)):
        a, b = np.asarray(mc[metric]), np.asarray(gc[metric])
        assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < gate, metric


def test_analyze_cases_farm_wind():
    """2-unit VolturnUS-S farm analyzeCases parity at 10.5 m/s operating
    wind — the coupled array chain (shared-mooring equilibrium, per-unit
    aero + excitation, block system impedance) against the farm golden.

    Measured deviations (f64 CPU): motion PSDs 1e-4..1.6e-2 per unit,
    Mbase 2.1-3.8e-2, surge_avg 4e-3.  Gates at ~1.5x measured.
    """
    path = ref_data("VolturnUS-S_farm.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    res = model.analyze_cases()
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)
    assert np.asarray(model.cases[0]["wind_speed"]).max() > 0
    for ifowt in range(2):
        mc = res["case_metrics"][0][ifowt]
        gc = true["case_metrics"][0][ifowt]
        assert_allclose(float(np.asarray(mc["surge_avg"])),
                        float(np.asarray(gc["surge_avg"])), rtol=1e-2)
        for metric, gate in (("surge_PSD", 3e-3), ("heave_PSD", 1e-3),
                             ("pitch_PSD", 2.5e-2), ("AxRNA_PSD", 2e-2),
                             ("Mbase_PSD", 6e-2)):
            a, b = np.asarray(mc[metric]), np.asarray(gc[metric])
            assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < gate, \
                (ifowt, metric)


def test_analyze_cases_volturn_meandrift():
    """VolturnUS-S analyzeCases no-wind case with the slender-QTF mean
    drift fed back into the equilibrium (raft_model.py:316-328): the
    golden's 1.61 m mean surge — formerly documented as an anomaly — is
    the drift-included pose.  Motion/tension PSDs include the 2nd-order
    response realisation (measured 1.2-2.6e-2)."""
    path = ref_data("VolturnUS-S.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    model = raft_tpu.Model(path)
    res = model.analyze_cases()
    with open(path.replace(".yaml", "_true_analyzeCases.pkl"), "rb") as f:
        true = pickle.load(f)
    mc = res["case_metrics"][0][0]
    gc = true["case_metrics"][0][0]
    assert model.cases[0]["wind_speed"] == 0
    assert_allclose(float(np.asarray(mc["surge_avg"])),
                    float(np.asarray(gc["surge_avg"])), rtol=2e-2)
    assert_allclose(float(np.asarray(mc["pitch_avg"])),
                    float(np.asarray(gc["pitch_avg"])), rtol=1e-2)
    for metric, gate in (("surge_PSD", 2e-2), ("heave_PSD", 2e-2),
                         ("pitch_PSD", 4e-2), ("AxRNA_PSD", 2e-2),
                         ("Mbase_PSD", 3e-2), ("Tmoor_PSD", 2e-2)):
        a, b = np.asarray(mc[metric]), np.asarray(gc[metric])
        assert np.max(np.abs(a - b)) / (np.max(np.abs(b)) + 1e-12) < gate, metric
