"""CLI for the AOT program bank.

    python -m raft_tpu.aot warmup [--design YAML] [--n 512,8]
                                  [--kinds cases,full,design]
                                  [--out-keys PSD,X0,status]
    python -m raft_tpu.aot list
    python -m raft_tpu.aot verify
    python -m raft_tpu.aot gc [--max-age-days D] [--all] [--dry-run]
    python -m raft_tpu.aot release {cut,list,verify,promote,rollback}

Exit codes: 0 clean, 1 problems (verify) / failed warmup, 2 usage.

``list``/``verify``/``gc`` never initialize a jax backend (version
fingerprints come from package metadata), so they are safe in CI and
on hosts with a dead accelerator tunnel.  ``warmup`` runs real
compilations: it pins the platform from ``RAFT_TPU_CLI_PLATFORM``
(default cpu) unless ``--platform`` overrides it, and leaves x64 OFF
by default — matching how the sweep consumers (bench, sweep_10k,
serving workers) run; pass ``--x64`` only when the consumers enable
x64 too (e.g. the parity test suite).  Warm with EXACTLY the
platform, x64 mode, dtype policy and flags the serving process will
run — all of them are part of the bank key, so a mismatch is a clean
but total miss.
"""

from __future__ import annotations

import argparse
import sys
import time


def _cmd_warmup(args):
    from raft_tpu.utils import config

    platform = (args.platform if args.platform is not None
                else config.get("CLI_PLATFORM"))
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    if args.x64:
        import jax

        jax.config.update("jax_enable_x64", True)

    from raft_tpu.aot import warmup

    sizes = [int(s) for s in args.n.split(",") if s.strip()]
    kinds = [k.strip() for k in args.kinds.split(",") if k.strip()]
    out_keys = tuple(k.strip() for k in args.out_keys.split(",") if k.strip())
    designs = list(args.design or ())
    try:
        reports = warmup.warmup_model(
            design=designs[0] if designs else None, sizes=sizes,
            kinds=kinds, out_keys=out_keys,
            designs=designs if len(designs) > 1 else None)
    except ValueError as e:   # e.g. a typo'd --kinds entry
        print(str(e), file=sys.stderr)
        return 2
    for r in reports:
        how = ("already banked" if r["loaded"] and not r["compiled"]
               else f"compiled {r['compiled']} program(s)")
        print(f"warmup {r['kind']:<7} rows={r['rows']:<6} {how} "
              f"in {r['wall_s']}s")
    from raft_tpu.aot import bank

    print(f"bank: {bank.bank_dir()}")
    return 0


def _fmt_age(created):
    if not created:
        return "?"
    days = (time.time() - created) / 86400.0
    return f"{days:.1f}d"


def _cmd_list(_args):
    from raft_tpu.aot import bank

    rows = []
    for key, meta, _mp, bin_path in bank.scan():
        if meta is None:
            rows.append((key, "?", "?", "?", "?", "CORRUPT/ORPHAN"))
            continue
        env = meta.get("environment") or {}
        state = "stale" if bank.is_stale(meta) else "ok"
        rows.append((key, meta.get("kind", "?"),
                     f"{env.get('platform')}x{env.get('n_devices')}"
                     + ("/x64" if env.get("x64") else ""),
                     f"{(meta.get('payload_bytes') or 0) / 1e6:.1f}MB",
                     _fmt_age(meta.get("created")), state))
    if not rows:
        print(f"bank empty: {bank.bank_dir()}")
        return 0
    widths = [max(len(str(r[i])) for r in rows) for i in range(6)]
    hdr = ("key", "kind", "env", "size", "age", "state")
    for r in (hdr,) + tuple(rows):
        print("  ".join(str(v).ljust(w) for v, w in zip(r, widths)))
    print(f"{len(rows)} entr{'y' if len(rows) == 1 else 'ies'} in "
          f"{bank.bank_dir()}")
    return 0


def _cmd_verify(_args):
    from raft_tpu.aot import bank

    problems, notes, n = bank.verify_bank()
    for note in notes:
        print(f"note: {note}")
    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        print(f"aot verify: {len(problems)} problem(s) across {n} "
              f"entr{'y' if n == 1 else 'ies'}.", file=sys.stderr)
        return 1
    print(f"aot bank verified: {n} entr{'y' if n == 1 else 'ies'}, "
          f"{len(notes)} stale, 0 problems ({bank.bank_dir()}).")
    return 0


def _cmd_gc(args):
    from raft_tpu.aot import bank

    s = bank.gc_bank(max_age_days=args.max_age_days,
                     remove_all=args.all, dry_run=args.dry_run)
    verb = "would remove" if args.dry_run else "removed"
    print(f"aot gc: {verb} {s['removed']} entr"
          f"{'y' if s['removed'] == 1 else 'ies'} "
          f"({s['bytes_freed'] / 1e6:.1f}MB), kept {s['kept']}.")
    return 0


def _pin_backend(platform, x64):
    """The warmup-style jax pins for commands that compute live
    program identities (cut / verify --against-designs)."""
    from raft_tpu.utils import config

    platform = platform if platform is not None \
        else config.get("CLI_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    if x64:
        import jax

        jax.config.update("jax_enable_x64", True)


def _cmd_release(args):
    from raft_tpu.aot import release

    if args.release_cmd == "cut":
        _pin_backend(args.platform, args.x64)
        man = release.cut(label=args.label, promote_after=args.promote)
        state = "promoted" if args.promote else "cut"
        print(f"release {man['release']} {state}: {man['n_entries']} "
              f"entr{'y' if man['n_entries'] == 1 else 'ies'}, parent "
              f"{man['parent'] or 'none'} ({release.releases_dir()})")
        return 0

    if args.release_cmd == "list":
        cur = release.current_release()
        mans = release.list_releases()
        if not mans:
            print(f"no releases under {release.releases_dir()}")
            return 0
        for man in mans:
            mark = "*" if man["release"] == cur else " "
            print(f"{mark} {man['release']}  entries={man['n_entries']:<4}"
                  f" parent={man.get('parent') or '-':<12}"
                  f" age={_fmt_age(man.get('created'))}"
                  f"  {man.get('label') or ''}")
        print(f"{len(mans)} release(s); * = current")
        return 0

    if args.release_cmd == "verify":
        return _cmd_release_verify(args)

    if args.release_cmd == "promote":
        previous = release.promote(args.release)
        print(f"current -> {args.release} (was {previous or 'unset'})")
        return 0

    if args.release_cmd == "rollback":
        rid, parent = release.rollback()
        print(f"rolled back: current {rid} -> parent {parent}")
        return 0
    return 2


def _cmd_release_verify(args):
    """Integrity (+ optionally bank / live-design) check of one
    release.  ``--manifest PATH`` is a pure file check — no bank, no
    jax (the lint.sh fixture gate); the default target is the
    ``current`` release."""
    from raft_tpu.aot import release

    if args.manifest:
        man = release.load_manifest(args.manifest)
        if man is None:
            print(f"PROBLEM: unreadable manifest {args.manifest}",
                  file=sys.stderr)
            return 1
        problems = release.verify_manifest(man)
    else:
        rid = args.release or release.current_release()
        if rid is None:
            print("no --release/--manifest given and no current "
                  "release pointer", file=sys.stderr)
            return 2
        man = release.load_release(rid)
        if man is None:
            print(f"PROBLEM: no release {rid} under "
                  f"{release.releases_dir()}", file=sys.stderr)
            return 1
        problems = release.verify_manifest(man)
        if not problems:
            problems = release.verify_against_bank(man)
    for p in problems:
        print(f"PROBLEM: {p}", file=sys.stderr)
    if problems:
        print(f"release verify: {len(problems)} problem(s).",
              file=sys.stderr)
        return 1
    if args.against_designs:
        _pin_backend(args.platform, args.x64)
        from raft_tpu.serve import engine

        reg = engine.Registry()
        paths = []
        for spec in args.against_designs:
            name, _, path = spec.rpartition("=")
            path = path or spec
            reg.register(name or f"design-{len(paths)}", path)
            paths.append(path)
        entries = [reg.get(n) for n in reg.names()]
        report = release.diagnose(entries, manifest=man)
        if report["unwarmed"]:
            for line in release.format_diagnosis(report, paths,
                                                 x64=args.x64):
                print(line, file=sys.stderr)
            return 1
        print(f"release {man['release']}: all {report['total']} serve "
              "program(s) warmed for the given designs.")
        return 0
    print(f"release {man['release']} verified: {man['n_entries']} "
          f"entr{'y' if man['n_entries'] == 1 else 'ies'}, 0 problems.")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.aot")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("warmup", help="lower+compile+export the sweep "
                                      "programs for a design")
    p.add_argument("--design", action="append", default=None,
                   help="design YAML (default: bundled spar_demo); "
                        "repeatable — several designs warm the serve "
                        "kind's whole fleet design set in one pass "
                        "(deduplicated by bucket signature)")
    p.add_argument("--n", default="8",
                   help="comma list of batch sizes to warm (rounded up "
                        "to the dp mesh-axis size)")
    p.add_argument("--kinds", default=",".join(
        ("cases", "full", "design")),
        help="comma list of sweep kinds: cases,full,design,bucketed,"
             "serve (bucketed warms the shape-bucketed heterogeneous-"
             "design programs over the bundled design trio; serve "
             "warms the evaluation service's single-case programs at "
             "the RAFT_TPU_SERVE_MAX_BATCH batch ladder — --n is "
             "ignored for it)")
    p.add_argument("--out-keys", default="PSD,X0,status",
                   help="out_keys of the warmed programs (include "
                        "'status' to warm the health fold)")
    p.add_argument("--platform", default=None,
                   help="jax platform pin (default: RAFT_TPU_CLI_PLATFORM)")
    p.add_argument("--x64", action="store_true",
                   help="warm under jax_enable_x64 (only when the "
                        "serving/sweep processes enable it too — x64 "
                        "is part of the bank key)")

    sub.add_parser("list", help="table of bank entries")
    sub.add_parser("verify", help="integrity-check the bank (CI gate)")

    p = sub.add_parser("gc", help="remove stale/orphaned/corrupt entries")
    p.add_argument("--max-age-days", type=float, default=None)
    p.add_argument("--all", action="store_true",
                   help="empty the bank entirely")
    p.add_argument("--dry-run", action="store_true")

    p = sub.add_parser("release", help="immutable, content-addressed "
                                       "releases of the bank")
    rsub = p.add_subparsers(dest="release_cmd", required=True)
    rp = rsub.add_parser("cut", help="snapshot the warmed bank into a "
                                     "signed release manifest")
    rp.add_argument("--label", default=None,
                    help="free-form annotation (not part of the id)")
    rp.add_argument("--promote", action="store_true",
                    help="flip the current pointer to the new release")
    rp.add_argument("--platform", default=None)
    rp.add_argument("--x64", action="store_true")
    rsub.add_parser("list", help="table of releases (* = current)")
    rp = rsub.add_parser("verify", help="integrity-check a release "
                                        "(CI gate)")
    rp.add_argument("--release", default=None,
                    help="release id (default: the current pointer)")
    rp.add_argument("--manifest", default=None,
                    help="verify ONE manifest file in isolation "
                         "(pure integrity; no bank, no jax)")
    rp.add_argument("--against-designs", action="append", default=None,
                    metavar="[NAME=]YAML",
                    help="also preflight the live designs' program "
                         "identities against the manifest and name the "
                         "mismatch class (code/flags/ladder/avals); "
                         "repeatable")
    rp.add_argument("--platform", default=None)
    rp.add_argument("--x64", action="store_true")
    rp = rsub.add_parser("promote", help="point current at a release "
                                         "(atomic rename)")
    rp.add_argument("release")
    rsub.add_parser("rollback", help="re-point current at its parent")

    args = ap.parse_args(argv)
    cmd = {"warmup": _cmd_warmup, "list": _cmd_list,
           "verify": _cmd_verify, "gc": _cmd_gc,
           "release": _cmd_release}[args.cmd]
    return cmd(args)


if __name__ == "__main__":
    sys.exit(main())
