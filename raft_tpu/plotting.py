"""Visualisation: system geometry and response spectra.

Equivalent of the reference's plotting layer (``/root/reference/raft/
raft_model.py``: ``plot`` :1532, ``plot2d`` :1599, ``plotResponses``
:1363; member/mooring renderers in the component classes).  Matplotlib
is imported lazily so headless/batch runs never pay for it.
"""

from __future__ import annotations

import numpy as np


def plot_system(model, ax=None, color="k", n_theta=12):
    """3-D render of members (as surface meshes), mooring lines
    (catenary profiles) and anchors for every FOWT."""
    import matplotlib.pyplot as plt

    if ax is None:
        fig = plt.figure(figsize=(9, 7))
        ax = fig.add_subplot(111, projection="3d")

    for i, fs in enumerate(model.fowtList):
        off = np.array([fs.x_ref, fs.y_ref, 0.0])
        for mem in fs.members:
            if mem.part_of == "nacelle":
                continue
            _plot_member(ax, mem, off, color=color, n_theta=n_theta)
        ms = model.ms_list[i]
        if ms is not None:
            for il in range(ms.n_lines):
                _plot_line(ax, ms.r_anchor[il], off + ms.r_fair0[il],
                           ms.L[il], ms.w[il], ms.EA[il])
    if model.ms_array is not None:
        net = model.ms_array
        import jax.numpy as jnp

        r6 = np.stack([[f.x_ref, f.y_ref, 0, 0, 0, 0] for f in model.fowtList])
        _, info = net.body_forces(jnp.asarray(r6, dtype=float))
        pos = np.asarray(net._point_positions(jnp.asarray(r6, dtype=float),
                                              info["r_free"]))
        for (a, b), L, w_l, EA in zip(net.l_ends, net.l_L, net.l_w, net.l_EA):
            _plot_line(ax, pos[a], pos[b], L, w_l, EA)

    ax.set_xlabel("x [m]")
    ax.set_ylabel("y [m]")
    ax.set_zlabel("z [m]")
    try:
        ax.set_box_aspect((1, 1, 0.5))
    except AttributeError:
        pass
    return ax


def _plot_member(ax, mem, off, color="k", n_theta=12):
    th = np.linspace(0, 2 * np.pi, n_theta + 1)
    pts_a, pts_b = [], []
    for i in range(len(mem.stations)):
        c = off + mem.rA0 + mem.q0 * mem.stations[i]
        d = mem.d[i]
        ring = c[None, :] + 0.5 * d[0] * np.cos(th)[:, None] * mem.p10[None, :] \
            + 0.5 * d[1] * np.sin(th)[:, None] * mem.p20[None, :]
        ax.plot(ring[:, 0], ring[:, 1], ring[:, 2], color=color, lw=0.5)
        pts_a.append(ring)
    for k in range(0, n_theta + 1, max(1, n_theta // 4)):
        line = np.stack([r[k] for r in pts_a])
        ax.plot(line[:, 0], line[:, 1], line[:, 2], color=color, lw=0.5)


def _plot_line(ax, rA, rB, L, w_line, EA, n=30):
    """Catenary profile between two points (for rendering only)."""
    import jax.numpy as jnp

    from raft_tpu.physics.mooring import solve_catenary, _profile

    lo, hi = (rA, rB) if rA[2] <= rB[2] else (rB, rA)
    dv = np.asarray(hi) - np.asarray(lo)
    XF = max(np.hypot(dv[0], dv[1]), 1e-6)
    uh = dv[:2] / XF
    HF, VF, _, _ = solve_catenary(jnp.asarray(XF), jnp.asarray(dv[2]),
                                  jnp.asarray(float(L)), jnp.asarray(float(w_line)),
                                  jnp.asarray(float(EA)))
    s = np.linspace(0, float(L), n)
    xs, zs = [], []
    for si in s:
        VFs = float(VF) - float(w_line) * (float(L) - si)
        x, z = _profile(jnp.asarray(float(HF)), jnp.asarray(max(VFs, 0.0) if VFs < 0 else VFs),
                        jnp.asarray(si), jnp.asarray(float(w_line)), jnp.asarray(float(EA)))
        xs.append(float(x))
        zs.append(float(z))
    xs = np.clip(np.asarray(xs), 0, XF)
    zs = np.asarray(zs)
    pts = np.stack([np.asarray(lo)[0] + uh[0] * xs,
                    np.asarray(lo)[1] + uh[1] * xs,
                    np.asarray(lo)[2] + zs], axis=1)
    ax.plot(pts[:, 0], pts[:, 1], pts[:, 2], color="tab:blue", lw=0.8)


def plot_responses(model, channels=("surge", "heave", "pitch"), ifowt=0):
    """Response PSDs per case (plotResponses equivalent)."""
    import matplotlib.pyplot as plt

    fig, axs = plt.subplots(len(channels), 1, sharex=True, figsize=(8, 2.5 * len(channels)))
    axs = np.atleast_1d(axs)
    f_hz = model.w / (2 * np.pi)
    for iCase, per_fowt in model.results["case_metrics"].items():
        m = per_fowt[ifowt]
        for ax, ch in zip(axs, channels):
            ax.plot(f_hz, np.asarray(m[f"{ch}_PSD"]), label=f"case {iCase + 1}")
            ax.set_ylabel(f"{ch} PSD")
    axs[0].legend()
    axs[-1].set_xlabel("frequency [Hz]")
    return fig, axs
