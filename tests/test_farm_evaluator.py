"""Traced farm evaluator parity vs the orchestrated array path
(VERDICT r2 #3): ``api.make_farm_evaluator`` folds the coupled
multi-FOWT chain — shared-mooring equilibrium, per-unit excitation with
array wave phases, per-unit drag-linearised impedances, block system
impedance + shared-mooring stiffness (raft_model.py:1164-1236) — into
one jit, reproducing ``Model.solve_statics``/``solve_dynamics`` on the
2-unit VolturnUS-S farm at 1e-9.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tests.conftest import ref_data

import raft_tpu
from raft_tpu.api import make_farm_evaluator

pytestmark = pytest.mark.slow

WAVE_CASE = {
    "wind_speed": 0, "wind_heading": 0, "turbulence": 0,
    "turbine_status": "operating", "yaw_misalign": 0,
    "wave_spectrum": "JONSWAP", "wave_period": 10, "wave_height": 4,
    "wave_heading": -30, "current_speed": 0, "current_heading": 0,
}


@pytest.fixture(scope="module")
def farm_model():
    path = ref_data("VolturnUS-S_farm.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    return raft_tpu.Model(path)


def _parity(model, case, traced_case, rtol=1e-9):
    X0_o = model.solve_statics(case)
    Xi_o, info = model.solve_dynamics(case, X0=X0_o)
    evaluate = jax.jit(make_farm_evaluator(model))
    out = evaluate(traced_case)
    scale_X = np.max(np.abs(np.asarray(X0_o)))
    np.testing.assert_allclose(np.asarray(out["X0"]), np.asarray(X0_o),
                               atol=rtol * scale_X, rtol=0)
    Xi_o = np.asarray(Xi_o)
    Xi_t = np.asarray(out["Xi"])
    scale = np.max(np.abs(Xi_o))
    np.testing.assert_allclose(Xi_t, Xi_o, atol=rtol * scale, rtol=0)
    return out


def test_farm_evaluator_wave_parity(farm_model):
    out = _parity(farm_model, WAVE_CASE, dict(
        wind_speed=0.0, Hs=4.0, Tp=10.0, beta_deg=-30.0))
    # both units respond, with array phase differences
    PSD = np.asarray(out["PSD"])
    assert PSD.shape == (12, farm_model.nw)
    assert not np.allclose(PSD[0], PSD[6])


def test_farm_evaluator_wind_parity(farm_model):
    """Per-FOWT (waked) wind speeds through the traced chain
    (raft_model.py:646-648 wind-speed lists)."""
    case = dict(WAVE_CASE, wind_speed=[10.0, 8.5], turbulence=0.1)
    _parity(farm_model, case, dict(
        wind_speed=jnp.asarray([10.0, 8.5]), TI=0.1,
        Hs=4.0, Tp=10.0, beta_deg=-30.0))


def test_farm_evaluator_vmaps(farm_model):
    """The farm evaluator vmaps over a case batch (the sweep axis)."""
    evaluate = make_farm_evaluator(farm_model)
    fn = jax.jit(jax.vmap(lambda h, t, b: evaluate(
        dict(Hs=h, Tp=t, beta_deg=b))["PSD"]))
    B = 3
    out = fn(jnp.linspace(2, 6, B), jnp.linspace(8, 14, B), jnp.zeros(B))
    assert out.shape == (B, 12, farm_model.nw)
    assert bool(jnp.all(jnp.isfinite(out)))
