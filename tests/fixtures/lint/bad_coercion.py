"""Seeded violations for the host-coercion rule: host Python pulls of
values that dataflow from jnp expressions."""

import jax.numpy as jnp
import numpy as np


def resid_to_python(x):
    r = jnp.max(jnp.abs(x))
    flag = bool(r < 1e-3)           # line 10: bool() on traced value
    val = float(r)                  # line 11: float() on traced value
    return flag, val


def pull_to_numpy(x):
    y = jnp.fft.rfft(x)
    scalar = y.sum().item()         # line 17: .item() via tainted name
    host = np.asarray(y)            # line 18: host pull mid-pipeline
    return scalar, host


def shape_access_is_fine(x):
    y = jnp.abs(x)
    n = int(y.shape[0])             # host metadata: NOT flagged
    return n, len(np.asarray(y.shape))
