// raft_tpu native panel-method kernel.
//
// First-order potential-flow boundary-element solver core: constant-
// strength source panels (Hess & Smith) with a flat free surface
// handled by the method of images.  This is the native-code foundation
// of the HAMS-equivalent solver the reference delegates to an external
// Fortran package (pyHAMS; /root/reference/raft/raft_fowt.py:1288-1442)
// — here the influence-matrix assembly and dense solve live in C++
// behind a C ABI consumed through ctypes.
//
// Current scope: frequency-limit radiation problems.
//   mirror = -1 : high-frequency free-surface condition (phi = 0 on
//                 z = 0, negative image)  -> A(w -> inf)
//   mirror = +1 : rigid-lid condition (dphi/dz = 0, positive image)
//                 -> A(w -> 0)
// The finite-frequency wave Green function slots into the same
// assembly (influence() below) as a follow-up.
//
// Numerics: panel integrals by centroid collocation with 2x2 Gauss
// refinement for near-field pairs and an analytic equivalent-disk self
// term; dense partial-pivot LU for the source strengths.

#include <cmath>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct V3 {
  double x, y, z;
};

inline V3 sub(const V3& a, const V3& b) { return {a.x - b.x, a.y - b.y, a.z - b.z}; }
inline double dot(const V3& a, const V3& b) { return a.x * b.x + a.y * b.y + a.z * b.z; }
inline double norm(const V3& a) { return std::sqrt(dot(a, a)); }

// potential at p of a unit-strength source distribution (1/(4 pi r)
// kernel) over a quad panel given by 4 vertices, by Gauss quadrature
double quad_potential(const V3* verts, double area, const V3& p) {
  // bilinear map with 2x2 Gauss points
  static const double gp[2] = {-0.5773502691896257, 0.5773502691896257};
  double phi = 0.0;
  for (int iu = 0; iu < 2; ++iu) {
    for (int iv = 0; iv < 2; ++iv) {
      double u = 0.5 * (1 + gp[iu]);
      double v = 0.5 * (1 + gp[iv]);
      V3 q{
          (1 - u) * (1 - v) * verts[0].x + u * (1 - v) * verts[1].x +
              u * v * verts[2].x + (1 - u) * v * verts[3].x,
          (1 - u) * (1 - v) * verts[0].y + u * (1 - v) * verts[1].y +
              u * v * verts[2].y + (1 - u) * v * verts[3].y,
          (1 - u) * (1 - v) * verts[0].z + u * (1 - v) * verts[1].z +
              u * v * verts[2].z + (1 - u) * v * verts[3].z,
      };
      double r = norm(sub(p, q));
      phi += 0.25 * area / (4.0 * M_PI * (r > 1e-12 ? r : 1e-12));
    }
  }
  return phi;
}

// velocity (gradient of potential) at p from a quad source panel
V3 quad_velocity(const V3* verts, double area, const V3& p) {
  static const double gp[2] = {-0.5773502691896257, 0.5773502691896257};
  V3 vel{0, 0, 0};
  for (int iu = 0; iu < 2; ++iu) {
    for (int iv = 0; iv < 2; ++iv) {
      double u = 0.5 * (1 + gp[iu]);
      double v = 0.5 * (1 + gp[iv]);
      V3 q{
          (1 - u) * (1 - v) * verts[0].x + u * (1 - v) * verts[1].x +
              u * v * verts[2].x + (1 - u) * v * verts[3].x,
          (1 - u) * (1 - v) * verts[0].y + u * (1 - v) * verts[1].y +
              u * v * verts[2].y + (1 - u) * v * verts[3].y,
          (1 - u) * (1 - v) * verts[0].z + u * (1 - v) * verts[1].z +
              u * v * verts[2].z + (1 - u) * v * verts[3].z,
      };
      V3 d = sub(p, q);
      double r = norm(d);
      double r3 = (r > 1e-9 ? r * r * r : 1e-27);
      double c = 0.25 * area / (4.0 * M_PI * r3);
      vel.x += c * d.x;
      vel.y += c * d.y;
      vel.z += c * d.z;
    }
  }
  return vel;
}

// dense partial-pivot LU solve: A (n x n, row major) x = b, overwrites
int lu_solve(std::vector<double>& A, std::vector<double>& b, int n) {
  std::vector<int> piv(n);
  for (int i = 0; i < n; ++i) piv[i] = i;
  for (int k = 0; k < n; ++k) {
    int pk = k;
    double amax = std::fabs(A[k * n + k]);
    for (int i = k + 1; i < n; ++i) {
      double a = std::fabs(A[i * n + k]);
      if (a > amax) {
        amax = a;
        pk = i;
      }
    }
    if (amax < 1e-30) return 1;
    if (pk != k) {
      for (int j = 0; j < n; ++j) std::swap(A[k * n + j], A[pk * n + j]);
      std::swap(b[k], b[pk]);
    }
    double inv = 1.0 / A[k * n + k];
    for (int i = k + 1; i < n; ++i) {
      double f = A[i * n + k] * inv;
      if (f == 0.0) continue;
      A[i * n + k] = f;
      for (int j = k + 1; j < n; ++j) A[i * n + j] -= f * A[k * n + j];
      b[i] -= f * b[k];
    }
  }
  for (int i = n - 1; i >= 0; --i) {
    double s = b[i];
    for (int j = i + 1; j < n; ++j) s -= A[i * n + j] * b[j];
    b[i] = s / A[i * n + i];
  }
  return 0;
}

}  // namespace

extern "C" {

// Solve the radiation problem for all 6 rigid-body modes.
//
// vertices : (n, 4, 3) panel corner coordinates (below the waterline)
// centroid : (n, 3); normal : (n, 3) body-outward unit normals;
// area     : (n,)
// mirror   : -1 (phi=0 free surface, w->inf) or +1 (rigid lid, w->0)
// rho      : fluid density
// ref      : (3,) reference point for the rotational modes
// A_out    : (6, 6) added-mass matrix, row major
//
// Returns 0 on success.
int panel_radiation_added_mass(int n, const double* vertices,
                               const double* centroid, const double* normal,
                               const double* area, int mirror, double rho,
                               const double* ref, double* A_out) {
  const V3* verts = reinterpret_cast<const V3*>(vertices);
  const V3* cen = reinterpret_cast<const V3*>(centroid);
  const V3* nor = reinterpret_cast<const V3*>(normal);
  const V3 r0{ref[0], ref[1], ref[2]};

  // ---- influence matrix: normal velocity at panel i from unit source
  // on panel j (+ mirrored image panel)
  std::vector<double> G(static_cast<size_t>(n) * n);
  std::vector<double> P(static_cast<size_t>(n) * n);  // potentials
  for (int i = 0; i < n; ++i) {
    for (int j = 0; j < n; ++j) {
      if (i == j) {
        // analytic self terms: half-space velocity jump + equivalent disk
        G[i * n + j] = 0.5;  // (sigma/2) outward normal velocity
        double a_eq = std::sqrt(area[j] / M_PI);
        P[i * n + j] = 0.5 * a_eq;  // disk potential a/2 for 1/(4 pi r)
      } else {
        V3 vel = quad_velocity(&verts[4 * j], area[j], cen[i]);
        G[i * n + j] = dot(vel, nor[i]);
        P[i * n + j] = quad_potential(&verts[4 * j], area[j], cen[i]);
      }
      // mirrored image above z = 0
      V3 iv[4];
      for (int k = 0; k < 4; ++k) {
        iv[k] = verts[4 * j + k];
        iv[k].z = -iv[k].z;
      }
      V3 velm = quad_velocity(iv, area[j], cen[i]);
      double phim = quad_potential(iv, area[j], cen[i]);
      G[i * n + j] += mirror * dot(velm, nor[i]);
      P[i * n + j] += mirror * phim;
    }
  }

  // ---- modes: rigid-body normal velocities
  // translations: n_k ; rotations: ((r - r0) x n)_k
  std::vector<double> phi(static_cast<size_t>(6) * n);  // panel potentials per mode
  std::vector<double> nmode(static_cast<size_t>(6) * n);
  for (int i = 0; i < n; ++i) {
    V3 rr = sub(cen[i], r0);
    double nm[6] = {nor[i].x,
                    nor[i].y,
                    nor[i].z,
                    rr.y * nor[i].z - rr.z * nor[i].y,
                    rr.z * nor[i].x - rr.x * nor[i].z,
                    rr.x * nor[i].y - rr.y * nor[i].x};
    for (int m = 0; m < 6; ++m) nmode[m * n + i] = nm[m];
  }

  for (int m = 0; m < 6; ++m) {
    std::vector<double> Gc(G);  // LU destroys the matrix
    std::vector<double> rhs(nmode.begin() + m * n, nmode.begin() + (m + 1) * n);
    if (lu_solve(Gc, rhs, n)) return 1;
    // potentials phi_m(i) = sum_j P(i,j) sigma_j
    for (int i = 0; i < n; ++i) {
      double s = 0.0;
      for (int j = 0; j < n; ++j) s += P[i * n + j] * rhs[j];
      phi[m * n + i] = s;
    }
  }

  // ---- added mass A_km = rho * sum_i phi_m(i) n_k(i) dS_i
  for (int k = 0; k < 6; ++k) {
    for (int m = 0; m < 6; ++m) {
      double s = 0.0;
      for (int i = 0; i < n; ++i) s += phi[m * n + i] * nmode[k * n + i] * area[i];
      A_out[k * 6 + m] = rho * s;
    }
  }
  return 0;
}

}  // extern "C"
