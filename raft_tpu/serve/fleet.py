"""Replica membership ledger of the horizontal serving fleet.

ROADMAP item 4: one asyncio server became N **replica** processes, each
running the existing :mod:`raft_tpu.serve` service warmed from the SAME
immutable AOT bank, fronted by the consistent-hash failover router
(:mod:`raft_tpu.serve.router`).  Membership/liveness is the fabric's
lease model verbatim — this module reuses the atomic primitives the
sweep fabric trusts (:func:`raft_tpu.parallel.fabric.lease_claim` /
``lease_rewrite`` / ``lease_remove``):

* **claim = join** — a replica that bound its socket (and finished its
  bank warmup) claims ``<root>/_fleet/replicas/<rid>.json`` with
  ``O_CREAT|O_EXCL``; the lease body carries its port, the bucket
  signatures + design content fingerprints it serves (the router's
  hash-ring routing keys) and a small health snapshot;
* **renewed lease = alive** — a daemon renewer rewrites the lease
  (tmp + ``os.replace``) every ``ttl/3``, refreshing ``renewed_t`` and
  the health snapshot;
* **expired lease = dead** — a replica that stops renewing (SIGKILL,
  OOM, wedged host) simply ages out: the router evicts the lease
  (atomic rename — exactly one evictor wins) and drops the replica
  from its ring;
* **drain = release** — graceful shutdown releases the lease at drain
  START (``POST /drain`` / SIGTERM), so the router stops routing new
  work to a draining replica while it finishes the accepted work.

The ledger needs a shared filesystem and nothing else — the same
requirement the AOT bank and the sweep fabric already have, so a
multi-host fleet is "point ``--fleet-dir`` at the shared mount".

``run_fleet`` is the local coordinator (``python -m raft_tpu.serve
fleet --replicas N``): optionally warm the shared bank once, spawn N
replica server subprocesses, wait for their leases, forward SIGTERM.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import uuid

from raft_tpu.obs import metrics
from raft_tpu.parallel import resilience
from raft_tpu.parallel.fabric import (lease_claim, lease_read,
                                      lease_remove, lease_rewrite)
from raft_tpu.utils import config, fsops
from raft_tpu.utils.structlog import log_event

FLEET_DIRNAME = "_fleet"

#: fault kinds targeted at one replica (stripped from the rest by the
#: coordinator, like the fabric's worker_kill forwarding).
#: ``provenance_skew`` perturbs the replica's reported bank/code
#: identity at startup — the canary drill's deterministic stand-in for
#: a stale-banked or env-skewed replica
REPLICA_FAULT_KINDS = ("replica_kill", "replica_hang", "replica_5xx",
                       "provenance_skew")


def fleet_dir(root):
    return os.path.join(root, FLEET_DIRNAME)


def _replicas_dir(root):
    return os.path.join(fleet_dir(root), "replicas")


def _lease_path(root, rid):
    return os.path.join(_replicas_dir(root), f"{rid}.json")


def router_record_path(root):
    return os.path.join(fleet_dir(root), "router.json")


def read_router_record(root):
    """The router's last published membership record, or None."""
    try:
        return json.loads(fsops.read_text(router_record_path(root)))
    except (OSError, ValueError):
        return None


class FleetLedger:
    """The replica-membership ledger rooted at ``root`` (usually the
    serving deploy directory next to the AOT bank).  Replica-side
    methods (:meth:`claim`/:meth:`renew`/:meth:`release`) are
    token-guarded like fabric shard leases; observer-side methods
    (:meth:`replicas`/:meth:`live`/:meth:`expired`/:meth:`evict`) are
    what the router's membership prober runs."""

    def __init__(self, root, replica_id=None):
        self.root = root
        self.replica_id = replica_id
        self.token = uuid.uuid4().hex
        # NO mkdir here: read-side users (fleet --status, the router's
        # prober) must not conjure a ledger tree under a typo'd path —
        # the write path (claim) creates it

    # ------------------------------------------------------ replica side

    def claim(self, port, host="127.0.0.1", designs=None, buckets=None,
              healthz=None, out_keys=None):
        """Join the fleet: exclusive lease creation for this replica id.
        ``designs`` maps served design name -> {"sig": bucket-signature
        fingerprint, "fingerprint": design content hash} (the router
        hashes these into its ring keys); ``buckets`` is the distinct
        signature fingerprint list; ``out_keys`` is the out_keys tuple
        this replica dispatches (the router canary intersects its probe
        keys with this — a probe asking for an unserved key is a 400)."""
        fsops.makedirs(_replicas_dir(self.root), exist_ok=True)
        now = time.time()
        rec = {
            "replica": self.replica_id,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "addr": str(host),
            "port": int(port),
            "claimed_t": now,
            "renewed_t": now,
            "ttl_s": float(config.get("FLEET_TTL_S")),
            "designs": dict(designs or {}),
            "buckets": list(buckets or ()),
            "out_keys": list(out_keys or ()),
            "healthz": dict(healthz or {}),
            "token": self.token,
        }
        if not lease_claim(_lease_path(self.root, self.replica_id), rec):
            return False
        metrics.counter("fleet_joins").inc()
        log_event("replica_join", replica=self.replica_id, port=int(port),
                  designs=sorted(rec["designs"]), root=self.root)
        return True

    def seize(self, port, host="127.0.0.1", designs=None, buckets=None,
              healthz=None, out_keys=None):
        """TAKE OVER an existing lease: unconditionally rewrite the
        replica id's lease with THIS process's record + token (one
        atomic rename — readers see the old owner or the new one,
        never a gap in membership).  The rolling-upgrade replacement
        path: the upgraded process warms + binds first, seizes the
        SAME rid (same ring vnodes — zero key movement), and only then
        drains the old process; the old owner's renew/release no-op on
        the token mismatch.  Outside a rollout, prefer :meth:`claim` —
        seizing a healthy stranger's lease is an operator error this
        method will happily commit."""
        fsops.makedirs(_replicas_dir(self.root), exist_ok=True)
        prev, _ = self.read(self.replica_id)
        now = time.time()
        rec = {
            "replica": self.replica_id,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "addr": str(host),
            "port": int(port),
            "claimed_t": now,
            "renewed_t": now,
            "ttl_s": float(config.get("FLEET_TTL_S")),
            "designs": dict(designs or {}),
            "buckets": list(buckets or ()),
            "out_keys": list(out_keys or ()),
            "healthz": dict(healthz or {}),
            "token": self.token,
        }
        lease_rewrite(_lease_path(self.root, self.replica_id), rec)
        metrics.counter("fleet_takeovers").inc()
        log_event("replica_takeover", replica=self.replica_id,
                  port=int(port),
                  prev_port=(prev or {}).get("port"), root=self.root)
        return True

    def renew(self, healthz=None):
        """Refresh ``renewed_t`` (+ the health snapshot); False when
        the lease is no longer this replica's (evicted or released) —
        the renewer does NOT re-claim: an evicted replica rejoining
        must go through the explicit join path."""
        rec, _ = self.read(self.replica_id)
        if not rec or rec.get("token") != self.token:
            return False
        rec["renewed_t"] = time.time()
        if healthz is not None:
            rec["healthz"] = dict(healthz)
        lease_rewrite(_lease_path(self.root, self.replica_id), rec)
        return True

    def release(self, reason="drain"):
        """Leave the fleet (drain start / clean exit).  True when this
        call removed the lease."""
        rec, _ = self.read(self.replica_id)
        if not rec or rec.get("token") != self.token:
            return False
        removed = lease_remove(_lease_path(self.root, self.replica_id))
        if removed:
            log_event("replica_drain", replica=self.replica_id,
                      reason=str(reason), root=self.root)
        return removed

    # ----------------------------------------------------- observer side

    def read(self, rid):
        return lease_read(_lease_path(self.root, rid))

    def replicas(self):
        """Every readable lease: ``{replica_id: (record, mtime)}``."""
        out = {}
        try:
            names = fsops.listdir(_replicas_dir(self.root))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            rec, mtime = lease_read(os.path.join(_replicas_dir(self.root),
                                                 name))
            if rec is not None:
                out[name[:-5]] = (rec, mtime)
        return out

    @staticmethod
    def lease_age(rec, mtime, now=None):
        """Seconds since the lease was last renewed."""
        now = time.time() if now is None else now
        return now - float(rec.get("renewed_t") or mtime or now)

    def live(self, now=None):
        """``{replica_id: record}`` of every lease renewed within its
        TTL — the router's ring membership source."""
        now = time.time() if now is None else now
        out = {}
        for rid, (rec, mtime) in self.replicas().items():
            ttl = float(rec.get("ttl_s") or config.get("FLEET_TTL_S"))
            if self.lease_age(rec, mtime, now) <= ttl:
                out[rid] = rec
        return out

    def expired(self, now=None):
        """``{replica_id: (record, age_s)}`` of leases past their TTL
        (dead replicas awaiting eviction)."""
        now = time.time() if now is None else now
        out = {}
        for rid, (rec, mtime) in self.replicas().items():
            ttl = float(rec.get("ttl_s") or config.get("FLEET_TTL_S"))
            age = self.lease_age(rec, mtime, now)
            if age > ttl:
                out[rid] = (rec, age)
        return out

    def evict(self, rid, reason="expired", age_s=None):
        """Atomically remove a dead replica's lease (router-side).
        True when THIS caller won the removal race."""
        if not lease_remove(_lease_path(self.root, rid)):
            return False
        metrics.counter("fleet_evictions").inc()
        log_event("replica_evict", replica=rid, reason=str(reason),
                  age_s=round(float(age_s), 3) if age_s is not None
                  else None, root=self.root)
        return True

    def summary(self, now=None):
        """Ledger snapshot for the ``fleet --status`` CLI / tests."""
        now = time.time() if now is None else now
        reps = {}
        for rid, (rec, mtime) in self.replicas().items():
            ttl = float(rec.get("ttl_s") or config.get("FLEET_TTL_S"))
            age = self.lease_age(rec, mtime, now)
            reps[rid] = {
                "port": rec.get("port"),
                "pid": rec.get("pid"),
                "designs": sorted(rec.get("designs") or ()),
                "age_s": round(age, 3),
                "live": age <= ttl,
            }
        router = read_router_record(self.root)
        out = {
            "root": self.root,
            "replicas": reps,
            "n_live": sum(1 for r in reps.values() if r["live"]),
            "router": None,
        }
        if router:
            out["router"] = {
                "t": router.get("t"),
                "pid": router.get("pid"),
                "n_replicas": router.get("n_replicas"),
                "replicas": sorted(router.get("replicas") or ()),
            }
        return out


class LeaseRenewer(threading.Thread):
    """Daemon thread renewing a replica's fleet lease every ``ttl/3``
    (the fabric ``_Renewer`` pattern).  ``healthz`` is an optional
    callable returning the snapshot dict to refresh in the lease body
    — it runs on THIS thread, off the server's event loop."""

    def __init__(self, ledger, healthz=None):
        super().__init__(name=f"raft-fleet-lease-{ledger.replica_id}",
                         daemon=True)
        self.ledger = ledger
        self.healthz = healthz
        ttl = float(config.get("FLEET_TTL_S"))
        self.interval_s = max(ttl / 3.0, 0.05)
        self._stop_evt = threading.Event()

    def run(self):
        while not self._stop_evt.wait(self.interval_s):
            try:
                snap = self.healthz() if self.healthz is not None else None
                self.ledger.renew(healthz=snap)
            except Exception:
                pass  # renewal must never kill the server

    def stop(self):
        self._stop_evt.set()
        self.join(timeout=2.0)


# ------------------------------------------------------- local coordinator


def _strip_replica_faults(env, index):
    """Forward the replica-targeted fault kinds to exactly ONE spawned
    replica (``RAFT_TPU_FLEET_FAULT_REPLICA``), stripping them from the
    rest — every replica arming ``replica_kill`` from a shared
    environment would kill the whole fleet once each."""
    fspecs = env.get(config.env_name("FAULTS"), "")
    if fspecs and index != int(config.get("FLEET_FAULT_REPLICA")):
        kept = [s for s in fspecs.split(",") if s.strip()
                and s.strip().split(":")[0] not in REPLICA_FAULT_KINDS]
        env[config.env_name("FAULTS")] = ",".join(kept)
    return env


def spawn_replica(root, designs_spec, index=0, replica_id=None,
                  host="127.0.0.1", env=None, extra_args=()):
    """Spawn one replica server subprocess against the fleet ledger at
    ``root`` (ephemeral port; the lease carries the real one).
    stdout/stderr land in ``_fleet/replicas/<rid>.log``.  Returns
    ``(Popen, replica_id)``."""
    rid = replica_id or f"r{index}-{uuid.uuid4().hex[:6]}"
    wenv = dict(os.environ)
    wenv.update(env or {})
    _strip_replica_faults(wenv, index)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    old_pp = wenv.get("PYTHONPATH", "")
    wenv["PYTHONPATH"] = repo + (os.pathsep + old_pp if old_pp else "")
    # every fleet replica leaves a black box: unless the operator
    # pointed the flight recorder elsewhere, its dumps land next to
    # the replica logs — a SIGKILLed replica's last seconds are then
    # one `obs trace --merge` away from its survivors' story
    wenv.setdefault(
        config.env_name("FLIGHT_DIR"),
        os.path.abspath(os.path.join(_replicas_dir(root), "flight")))
    fsops.makedirs(_replicas_dir(root), exist_ok=True)
    logf = open(os.path.join(_replicas_dir(root), f"{rid}.log"), "ab")
    argv = [sys.executable, "-m", "raft_tpu.serve"]
    for spec in designs_spec:
        argv += ["--designs", spec]
    argv += ["--host", host, "--port", "0",
             "--fleet-dir", os.path.abspath(root), "--replica-id", rid]
    argv += list(extra_args)
    try:
        proc = subprocess.Popen(argv, env=wenv, stdout=logf,
                                stderr=subprocess.STDOUT, cwd=repo)
    finally:
        logf.close()  # the child keeps its own handle
    log_event("fleet_spawn", root=root, replica=rid, pid=proc.pid)
    return proc, rid


def run_fleet(root, replicas, designs_spec, host="127.0.0.1",
              extra_args=(), warm_bank=False, join_timeout_s=600.0,
              on_ready=None):
    """Local fleet coordinator: optionally warm the shared AOT bank
    ONCE, spawn ``replicas`` server subprocesses, wait for their
    membership leases, then babysit until SIGTERM/SIGINT (forwarded to
    every replica, which drains gracefully).  Returns 0 on clean
    shutdown.

    The one-warmup-for-N-replicas recipe is the whole point of the
    shared bank: the coordinator pays the trace+compile bill once
    (``RAFT_TPU_AOT=load``) and every replica then starts under
    ``RAFT_TPU_AOT=require`` with zero backend compiles — the bank
    directory is the deploy artifact."""
    if warm_bank:
        # in-process warmup through the SAME serve funnel the replicas
        # dispatch (bucket signature x batch ladder, out_keys default)
        from raft_tpu.aot.warmup import warmup_model

        paths = [s.split("=", 1)[1] if "=" in s else s
                 for spec in designs_spec for s in spec.split(",") if s]
        warmup_model(design=paths[0], kinds=("serve",), designs=paths)
    ledger = FleetLedger(root)
    # install the stop signal BEFORE spawning: a SIGTERM during the
    # join window must drain the replicas already spawned, not orphan
    # them behind a dead coordinator
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    procs = []
    try:
        # append as we go: if spawn k fails, the finally block must
        # still drain replicas 0..k-1 (a comprehension would discard
        # them with its exception)
        for i in range(int(replicas)):
            procs.append(spawn_replica(root, designs_spec, index=i,
                                       host=host, extra_args=extra_args))
        my_rids = {rid for _p, rid in procs}
        deadline = time.time() + float(join_timeout_s)
        while not stop.is_set():
            # only THIS coordinator's replicas count toward readiness —
            # a predecessor fleet's not-yet-expired leases in the same
            # --fleet-dir must not fake a ready fleet of dead ports
            live = ledger.live()
            if my_rids <= set(live):
                break
            dead = [rid for p, rid in procs if p.poll() is not None]
            if dead:
                raise RuntimeError(
                    f"replica(s) {dead} exited before joining the fleet "
                    f"(see {_replicas_dir(root)}/<rid>.log)")
            if time.time() > deadline:
                raise RuntimeError(
                    f"fleet join timed out: "
                    f"{len(my_rids & set(live))}/{len(procs)} leases "
                    f"after {join_timeout_s}s")
            time.sleep(0.25)
        if not stop.is_set():
            live = ledger.live()
            ports = {rid: live[rid].get("port")
                     for rid in sorted(my_rids) if rid in live}
            if on_ready is not None:
                on_ready(ports)
            stop.wait()
    finally:
        # every exit path — clean SIGTERM, join failure, timeout,
        # KeyboardInterrupt — drains the replicas it spawned
        rcs = _shutdown_replicas(procs)
    return 0 if all(rc == 0 for rc in rcs.values()) else 1


def _shutdown_replicas(procs):
    """SIGTERM every live replica, wait out the drain window, SIGKILL
    stragglers.  Returns {replica_id: returncode}."""
    for p, _rid in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    rcs = {}
    drain_s = float(config.get("SERVE_DRAIN_S"))
    for p, rid in procs:
        try:
            rcs[rid] = p.wait(timeout=drain_s + 30.0)
        except subprocess.TimeoutExpired:
            p.kill()
            rcs[rid] = p.wait(timeout=10.0)
    return rcs


def publish_router_record(root, rec):
    """Atomic write of the router's membership view (``router.json``)
    — the second `_fleet/` record family, read by ``fleet --status``
    and the drill assertions."""
    fsops.makedirs(fleet_dir(root), exist_ok=True)
    resilience._atomic_json(router_record_path(root), rec)
