"""Fixture: event-name rule — unregistered literal event names are
flagged; registered names and dynamic names pass."""
from raft_tpu.utils import structlog
from raft_tpu.utils.structlog import log_event


def emit(name):
    log_event("shard_done", shard=1, rows=4)      # registered: clean
    log_event("shard_don", shard=1)               # typo -> flagged
    structlog.log_event("my_custom_event", x=2)   # unregistered -> flagged
    log_event(name, x=3)                          # dynamic: not checkable
    log_event("heartbeat", devices=[])            # registered: clean
