"""Fabric entry callables for tests/test_fabric.py worker subprocesses.

Not a pytest module (underscore name): fabric workers import these by
FILE PATH (``.../tests/_fabric_entry.py:toy_entry``), so the toy
evaluators need no installable package.  The computes are cheap,
deterministic pure functions of the case arrays — identical results on
any mesh / any worker count, which is exactly what the bit-identical
acceptance tests compare against the serial runner.
"""

import time

import jax.numpy as jnp
import numpy as np


def _toy_full(c):
    """Same math as tests/test_resilience.py::toy_full."""
    return {"PSD": jnp.stack([c["Hs"], c["Tp"], c["Hs"] * c["Tp"]]),
            "X0": c["Hs"] - c["Tp"]}


def toy_entry(out_keys=("PSD", "X0"), **_):
    """Plain toy entry: dict-case evaluator via the shared
    full_compute path."""
    from raft_tpu.parallel.sweep import full_compute

    return full_compute(_toy_full, out_keys=tuple(out_keys))


def slow_toy_entry(out_keys=("PSD", "X0"), delay_s=0.3, **_):
    """Toy entry whose every shard takes ``delay_s`` of wall time —
    long enough that several workers demonstrably interleave (and that
    a mid-sweep joiner finds shards left to claim).  The sleep wraps
    the shard compute on the HOST (a sleep inside the traced evaluator
    would only run at trace time)."""
    from raft_tpu.parallel.sweep import full_compute

    inner = full_compute(_toy_full, out_keys=tuple(out_keys))

    def compute(chunk, mesh):
        time.sleep(float(delay_s))
        return inner(chunk, mesh)

    return compute


def toy_with_cases_entry(n=12, out_keys=("PSD", "X0"), **_):
    """Entry that also supplies its case batch (the pure-CLI path)."""
    rng = np.random.default_rng(3)
    return {
        "compute": toy_entry(out_keys=out_keys),
        "cases": {"Hs": 2.0 + 6.0 * rng.random(int(n)),
                  "Tp": 8.0 + 8.0 * rng.random(int(n))},
    }


def not_an_entry(**_):
    """Returns neither a compute callable nor a compute dict —
    resolve_entry must reject it loudly."""
    return {"nope": 1}


def stamped_toy_evaluator():
    """A toy evaluator carrying the fabric entry stamp — what a real
    evaluator factory does so RAFT_TPU_FABRIC_WORKERS can route the
    checkpointed drivers through the fabric."""
    here = __file__

    def evaluate(c):
        return _toy_full(c)

    evaluate._raft_fabric_entry = {"entry": f"{here}:toy_entry",
                                   "kwargs": {}}
    return evaluate
