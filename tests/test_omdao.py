"""Optimizer-facing design-evaluation API tests (openmdao-free path)."""

import pytest

import os

import numpy as np


pytestmark = pytest.mark.slow

def test_design_evaluation_compute():
    from raft_tpu.omdao import DesignEvaluation

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "raft_tpu", "designs", "spar_demo.yaml")
    ev = DesignEvaluation(path)
    out = ev.compute()
    assert out["properties_total_mass"] > 1e6
    assert out["properties_displacement"] > 1e3
    assert len(out["rigid_body_periods"]) == 6
    assert out["Max_Offset"] >= 0
    assert "stats_pitch_std_case0_fowt0" in out

    # an override must change the result (longer mooring -> softer surge)
    out2 = ev.compute({"mooring.lines.0.length": 920.0})
    assert out2["stats_surge_max_case0_fowt0"] != out["stats_surge_max_case0_fowt0"]
