"""Solver-health status word: registry, guarded numerics, escalation.

* bit-registry + helper semantics (pure, int32, vmap-safe);
* the statics Newton's new ``(X, F_resid, n_iter, converged, status)``
  return: max-iter and step-cap bits on seeded synthetic systems, the
  ``RAFT_TPU_ITER_SCALE`` escalation knob, gradients still flowing
  through ``lax.custom_root``;
* the drag fixed point's ``DRAG_CAP_HIT`` and the gated Hager
  condition estimate (``RAFT_TPU_COND_CHECK``) on the bundled spar;
* the status-assembly trace: no gathers/host callbacks, nothing
  64-bit — the word stays int32 (the jaxpr contract engine carries the
  same guard as entry ``health_status``);
* the acceptance scenario end-to-end: a seeded unconverged-but-FINITE
  statics case (float32 Newton stalled at roundoff, no NaN anywhere)
  flows through ``sweep_cases_full`` -> checkpoint shard -> resume with
  the right bits, is listed in ``quarantine.json`` with a
  human-readable reason, and is resolved by the ``f64_cpu`` escalation
  rung (retol's larger budget alone cannot fix a roundoff stall, so
  the ladder order is exercised for real).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import raft_tpu
from raft_tpu.models.statics_solve import solve_equilibrium_general
from raft_tpu.parallel import resilience
from raft_tpu.parallel.sweep import (
    make_mesh, run_sweep_checkpointed_full, sweep_cases_full)
from raft_tpu.utils import health
from raft_tpu.utils.dtypes import compute_dtypes

SPAR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "raft_tpu", "designs", "spar_demo.yaml")

SPAR_CASE = {
    "wind_speed": 0, "wind_heading": 0, "turbulence": 0,
    "turbine_status": "operating", "yaw_misalign": 0,
    "wave_spectrum": "JONSWAP", "wave_period": 12, "wave_height": 6,
    "wave_heading": 0, "current_speed": 0, "current_heading": 0,
}


def _events(path, name=None):
    with open(path) as f:
        evs = [json.loads(line) for line in f if line.strip()]
    return [e for e in evs if name is None or e["event"] == name]


@pytest.fixture
def log_path(tmp_path, monkeypatch):
    p = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", p)
    return p


MESH = None


def mesh2():
    global MESH
    if MESH is None:
        MESH = make_mesh(2)
    return MESH


# ------------------------------------------------------------ bit registry


def test_bits_are_disjoint_single_bits():
    masks = list(health.MASKS.values())
    assert len(set(masks)) == len(masks)
    assert all(m & (m - 1) == 0 for m in masks)  # one bit each
    assert health.SEVERE & health.INFORMATIONAL == 0
    combined = 0
    for m in masks:
        combined |= m
    assert combined == health.SEVERE | health.INFORMATIONAL


def test_describe_human_readable():
    assert health.describe(health.OK) == "ok"
    s = health.STATICS_MAX_ITER | health.DRAG_CAP_HIT
    d = health.describe(s)
    assert "STATICS_MAX_ITER" in d and "DRAG_CAP_HIT" in d
    # future/unknown bits degrade readably instead of crashing tooling
    assert "bit30" in health.describe(1 << 30)
    assert health.any_bit(np.int32(health.DRAG_CAP_HIT))
    assert not health.any_bit(np.int32(health.STATICS_STEP_CAPPED))
    assert health.any_bit(np.int32(health.STATICS_STEP_CAPPED),
                          mask=health.INFORMATIONAL)


def test_set_bit_int32_under_jit_and_vmap():
    def fold(cond):
        st = health.set_bit(jnp.zeros((), jnp.int32),
                            health.DRAG_CAP_HIT, cond)
        return health.set_bit(st, health.INPUT_CLIPPED, ~cond)

    out = jax.jit(fold)(jnp.asarray(True))
    assert out.dtype == jnp.int32 and int(out) == health.DRAG_CAP_HIT
    outs = jax.vmap(fold)(jnp.asarray([True, False]))
    assert outs.dtype == jnp.int32
    assert list(map(int, outs)) == [health.DRAG_CAP_HIT,
                                    health.INPUT_CLIPPED]


def _dtypes_produced(jaxpr):
    """Set of dtype names produced by any equation, recursively."""
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    seen = set()
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            dt = getattr(getattr(v, "aval", None), "dtype", None)
            if dt is not None:
                seen.add(str(dt))
        for val in eqn.params.values():
            vs = val if isinstance(val, (list, tuple)) else (val,)
            for x in vs:
                inner = getattr(x, "jaxpr", x)
                if hasattr(inner, "eqns"):
                    seen |= _dtypes_produced(inner)
    return seen


def test_status_fold_trace_clean_int32_float32():
    """The satellite contract: the status path adds no gathers or host
    callbacks and stays int32/float32 under the f32 policy — checked on
    the same fold the jaxpr contract engine traces (entry
    ``health_status``)."""
    from raft_tpu.analysis import jaxpr_contracts as jc

    def fold(st_statics, drag_converged, cond_Z, X0, Xi):
        status = health.set_bit(st_statics, health.DRAG_CAP_HIT,
                                ~drag_converged)
        status = health.set_bit(status, health.ILL_CONDITIONED_Z,
                                cond_Z > 1e7)
        status = health.set_bit(
            status, health.NONFINITE_INTERMEDIATE,
            ~(jnp.all(jnp.isfinite(X0)) & jnp.all(jnp.isfinite(Xi))))
        return jnp.asarray(status, dtype=jnp.int32)

    jaxpr = jax.make_jaxpr(fold)(
        jnp.zeros((), jnp.int32), jnp.asarray(False),
        jnp.zeros((), jnp.float32), jnp.zeros(6, jnp.float32),
        jnp.zeros((6, 10), jnp.complex64))
    assert jc.check_structure("health_status", "float32", jaxpr) == []
    produced = _dtypes_produced(jaxpr)
    assert not produced & {"int64", "float64", "complex128"}, produced
    assert "health_status" in jc.CONTRACTS  # engine carries the guard


# ------------------------------------------------- statics Newton status


def _toy_system(rdt=None):
    rdt = rdt or jnp.zeros(()).dtype
    K = jnp.eye(2, dtype=rdt) * jnp.asarray(100.0, rdt)

    def force(X):
        return jnp.asarray(-5.0, rdt) * X ** 3

    def stiff(X):
        return jnp.diag(jnp.asarray(15.0, rdt) * X ** 2)

    tol = jnp.full(2, 1e-8, rdt)
    caps = jnp.full(2, 50.0, rdt)
    refs = jnp.zeros(2, rdt)
    return K, force, stiff, tol, caps, refs


def _solve_toy(F, max_iter=30, cap=None, rdt=None):
    K, force, stiff, tol, caps, refs = _toy_system(rdt)
    if cap is not None:
        caps = jnp.full(2, cap, caps.dtype)
    return solve_equilibrium_general(
        K, jnp.asarray(F, K.dtype), jnp.zeros(2, K.dtype), force, stiff,
        tol, caps, refs, max_iter=max_iter)


def test_statics_converged_clean_status():
    X, Fres, n_iter, converged, status = _solve_toy([1000.0, -500.0])
    assert bool(converged)
    assert int(status) == health.OK
    assert 1 <= int(n_iter) < 30
    assert float(jnp.max(jnp.abs(Fres))) < 1e-6


def test_statics_max_iter_bit_finite_result():
    X, Fres, n_iter, converged, status = _solve_toy([1000.0, -500.0],
                                                    max_iter=2)
    assert not bool(converged)
    assert int(n_iter) == 2
    assert bool(health.any_bit(int(status)))
    assert int(status) & health.STATICS_MAX_ITER
    # the failure is FINITE — exactly the class NaN-quarantine misses
    assert bool(jnp.all(jnp.isfinite(X)))


def test_statics_step_cap_bit_informational():
    X, _, n_iter, converged, status = _solve_toy([1000.0, -500.0],
                                                 max_iter=40, cap=1.0)
    assert bool(converged)
    assert int(status) & health.STATICS_STEP_CAPPED
    # cap saturation alone is not severe: no escalation for it
    assert not bool(health.any_bit(int(status)))


def test_iter_scale_flag_escalates_budget(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_ITER_SCALE", "8")
    X, _, n_iter, converged, status = _solve_toy([1000.0, -500.0],
                                                 max_iter=2)
    assert bool(converged)
    assert int(status) == health.OK
    assert 2 < int(n_iter) <= 16


def test_statics_gradient_still_flows():
    def head(f0):
        X, *_ = _solve_toy(jnp.stack([f0, -500.0]))
        return X[0]

    g = jax.grad(head)(1000.0)
    # implicit-function-theorem gradient: dX/dF = 1/(K + 15 X^2) at eq
    X0 = float(head(1000.0))
    assert np.isfinite(float(g))
    assert float(g) == pytest.approx(1.0 / (100.0 + 15.0 * X0 ** 2),
                                     rel=1e-6)


def test_statics_status_vmappable():
    f = jax.vmap(lambda f0: _solve_toy(jnp.stack([f0, -f0]), max_iter=2)[4])
    st = f(jnp.asarray([0.0, 1000.0]))
    assert st.dtype == jnp.int32
    assert int(st[0]) == health.OK          # zero force: converges at once
    assert int(st[1]) & health.STATICS_MAX_ITER


# -------------------------------------------------- condition estimate


def test_cond_estimate_bounds_and_detects():
    from raft_tpu.ops import linsolve

    rng = np.random.default_rng(3)
    A = rng.normal(size=(4, 6, 6)) + 1j * rng.normal(size=(4, 6, 6))
    A = A + 6 * np.eye(6)  # well-conditioned batch
    est = np.asarray(linsolve.cond_estimate(jnp.asarray(A)))
    exact = np.array([np.linalg.cond(a, 1) for a in A])
    # one Hager step lower-bounds ||Z^-1||_1: never above the truth
    assert np.all(est <= exact * (1 + 1e-9))
    assert np.all(est >= 1.0)
    # a genuinely ill-conditioned matrix is detected loudly
    B = np.asarray(A[0])
    B[:, 0] = B[:, 1] * (1 + 1e-12)
    est_bad = float(linsolve.cond_estimate(jnp.asarray(B)))
    assert est_bad > 1e8
    # f32 policy: the estimate stays in the 32-bit pair path
    est32 = linsolve.cond_estimate(jnp.asarray(A, dtype=jnp.complex64))
    assert est32.dtype == jnp.float32


# ------------------------------------------- drag/dynamics status (spar)


@pytest.fixture(scope="module")
def spar_model():
    return raft_tpu.Model(SPAR)


def test_drag_converged_spar_status_ok(spar_model):
    _, info = spar_model.solve_dynamics(SPAR_CASE)
    dd = info["infos"][0]["dyn_diag"]
    assert bool(dd["drag_converged"])
    assert int(dd["status"]) == health.OK
    assert float(dd["cond_Z"]) == 0.0  # COND_CHECK off: gated out


def test_drag_cap_hit_bit(spar_model):
    old = spar_model.nIter
    try:
        spar_model.nIter = 0  # cap=1: the first linearisation is kept
        _, info = spar_model.solve_dynamics(SPAR_CASE)
    finally:
        spar_model.nIter = old
    dd = info["infos"][0]["dyn_diag"]
    assert not bool(dd["drag_converged"])
    assert int(dd["status"]) & health.DRAG_CAP_HIT
    assert bool(health.any_bit(int(dd["status"])))


def test_cond_check_gated_bit(spar_model, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_COND_CHECK", "1")
    monkeypatch.setenv("RAFT_TPU_COND_THRESHOLD", "1.0")
    _, info = spar_model.solve_dynamics(SPAR_CASE)
    dd = info["infos"][0]["dyn_diag"]
    assert float(dd["cond_Z"]) > 1.0
    assert int(dd["status"]) & health.ILL_CONDITIONED_Z
    monkeypatch.setenv("RAFT_TPU_COND_THRESHOLD", "1e30")
    _, info = spar_model.solve_dynamics(SPAR_CASE)
    dd = info["infos"][0]["dyn_diag"]
    assert float(dd["cond_Z"]) > 1.0
    assert not int(dd["status"]) & health.ILL_CONDITIONED_Z


def test_case_evaluator_emits_status(spar_model):
    from raft_tpu.api import make_case_evaluator

    ev = make_case_evaluator(spar_model)
    out = ev(6.0, 12.0, 0.0)
    assert out["status"].dtype == jnp.int32
    assert int(out["status"]) == health.OK


# --------------------------------------- end-to-end escalation acceptance


def make_stiff_evaluator():
    """REAL statics Newton on a cubic-spring system whose float32 solve
    stalls at roundoff (finite, no NaN: the residual-driven step bottoms
    out near X*eps32 ~ 4e-7, above the 1e-8 tolerance) while the same
    system under float64 converges in ~8 iterations.  ``F0 = 0`` rows
    converge immediately even in f32, giving each shard a healthy and a
    flagged row."""

    def evaluate(case):
        rdt = compute_dtypes()[0]  # honours RAFT_TPU_DTYPE at trace time
        K, force, stiff, tol, caps, refs = _toy_system(rdt)
        F = jnp.stack([case["F0"], -case["F0"]]).astype(rdt)
        X, Fres, n_iter, conv, st = solve_equilibrium_general(
            K, F, jnp.zeros(2, rdt), force, stiff, tol, caps, refs,
            max_iter=12)
        st = health.set_bit(st, health.NONFINITE_INTERMEDIATE,
                            ~jnp.all(jnp.isfinite(X)))
        return {"X0": X, "resid": Fres,
                "status": jnp.asarray(st, jnp.int32)}

    return evaluate


CASES_F0 = np.asarray([0.0, 1000.0, 0.0, 1000.0])


def test_unconverged_finite_flagged_through_sweep(monkeypatch):
    """sweep_cases_full carries the status column: the f32-stalled rows
    are flagged severe, the healthy rows clean, nothing is NaN."""
    monkeypatch.setenv("RAFT_TPU_DTYPE", "float32")
    out = sweep_cases_full(make_stiff_evaluator(), {"F0": CASES_F0},
                           mesh=mesh2(), out_keys=("X0", "status"))
    st = np.asarray(out["status"])
    assert st.dtype == np.int32
    assert list(st) == [health.OK, health.STATICS_MAX_ITER,
                        health.OK, health.STATICS_MAX_ITER]
    assert np.isfinite(np.asarray(out["X0"])).all()  # no NaNs anywhere


def test_flagged_rows_recorded_without_escalation(tmp_path, log_path,
                                                  monkeypatch):
    """RAFT_TPU_ESCALATE=off: flagged-but-finite rows are listed in
    quarantine.json with a human-readable reason, values untouched,
    bits persisted into the shard and counted in the manifest."""
    monkeypatch.setenv("RAFT_TPU_DTYPE", "float32")
    out_dir = str(tmp_path / "sweep")
    out = run_sweep_checkpointed_full(
        make_stiff_evaluator(), {"F0": CASES_F0}, out_dir, shard_size=2,
        mesh=mesh2(), out_keys=("X0", "status"))
    st = np.asarray(out["status"])
    assert list(st) == [health.OK, health.STATICS_MAX_ITER,
                        health.OK, health.STATICS_MAX_ITER]
    assert np.isfinite(np.asarray(out["X0"])).all()

    entries = resilience.load_quarantine(out_dir)
    assert [e["index"] for e in entries] == [1, 3]
    for e in entries:
        assert e["status"] == health.STATICS_MAX_ITER
        assert e["reason"] == "STATICS_MAX_ITER"
        assert e["resolved"] is False
        assert e["keys_nonfinite"] == []       # the silent-failure class
        assert "escalation" not in e
    with open(os.path.join(out_dir, "quarantine.json")) as f:
        assert json.load(f)["version"] == 2
    with open(os.path.join(out_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert all(manifest["shards"][str(s)]["flagged"] == 1 for s in (0, 1))
    done = _events(log_path, "sweep_done")
    assert done and done[-1]["n_flagged"] == 2

    # resume: bits and quarantine survive untouched, shards not re-run
    out2 = run_sweep_checkpointed_full(
        make_stiff_evaluator(), {"F0": CASES_F0}, out_dir, shard_size=2,
        mesh=mesh2(), out_keys=("X0", "status"))
    assert np.array_equal(np.asarray(out2["status"]), st)
    assert len(resilience.load_quarantine(out_dir)) == 2
    assert len(_events(log_path, "shard_resume")) == 2


def test_escalation_f64_cpu_resolves_and_clears_bits(tmp_path, log_path,
                                                     monkeypatch):
    """The acceptance scenario end-to-end: the seeded
    unconverged-but-finite case climbs the ladder — retol (4x budget,
    still f32) cannot fix a roundoff stall, f64_cpu converges — and the
    shard ships the escalated finite result with bits cleared, with the
    whole story in quarantine.json."""
    monkeypatch.setenv("RAFT_TPU_DTYPE", "float32")
    monkeypatch.setenv("RAFT_TPU_ESCALATE", "f64_cpu")
    out_dir = str(tmp_path / "sweep")
    out = run_sweep_checkpointed_full(
        make_stiff_evaluator(), {"F0": CASES_F0}, out_dir, shard_size=2,
        mesh=mesh2(), out_keys=("X0", "status"))

    st = np.asarray(out["status"])
    assert list(st) == [health.OK] * 4                 # bits cleared
    X0 = np.asarray(out["X0"])
    assert np.isfinite(X0).all()
    # the escalated rows carry the true (f64-converged) equilibrium:
    # 100 x + 5 x^3 = 1000  ->  x = 4.72513...
    np.testing.assert_allclose(X0[1], [4.7251313, -4.7251313], rtol=1e-5)

    entries = resilience.load_quarantine(out_dir)
    assert [e["index"] for e in entries] == [1, 3]
    for e in entries:
        assert e["status"] == health.STATICS_MAX_ITER
        assert e["reason"] == "STATICS_MAX_ITER"
        assert e["resolved"] is True
        assert e["status_after"] == health.OK
        assert e["reason_after"] == "ok"
        esc = e["escalation"]
        assert esc["mode"] == "f64_cpu"
        assert esc["rungs_tried"] == ["retol", "f64_cpu"]
        assert esc["resolved_by"] == "f64_cpu"
        assert esc["result_delta"]["X0"] is not None  # original-vs-escalated
    # ladder order visible in the event log: retol fails, f64_cpu heals
    esc_evs = _events(log_path, "shard_escalate")
    assert [(e["rung"], e["resolved"]) for e in esc_evs] \
        == [("retol", False), ("f64_cpu", True)] * 2
    with open(os.path.join(out_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert all(manifest["shards"][str(s)]["flagged"] == 0 for s in (0, 1))
    assert all(manifest["shards"][str(s)]["quarantined"] == 0
               for s in (0, 1))
    done = _events(log_path, "sweep_done")
    assert done and done[-1]["n_flagged"] == 0 \
        and done[-1]["n_quarantined"] == 0

    # resume is quiet: escalated shards are valid on disk
    out2 = run_sweep_checkpointed_full(
        make_stiff_evaluator(), {"F0": CASES_F0}, out_dir, shard_size=2,
        mesh=mesh2(), out_keys=("X0", "status"))
    assert np.array_equal(np.asarray(out2["X0"]), X0)
    assert len(resilience.load_quarantine(out_dir)) == 2


def test_retol_rung_sufficient_when_budget_is_the_problem(tmp_path,
                                                          monkeypatch):
    """A case that is merely budget-starved (f64, max_iter too small)
    is healed by the FIRST rung — f64_cpu is never tried."""

    def evaluate(case):
        rdt = compute_dtypes()[0]
        K, force, stiff, tol, caps, refs = _toy_system(rdt)
        F = jnp.stack([case["F0"], -case["F0"]]).astype(rdt)
        X, Fres, n_iter, conv, st = solve_equilibrium_general(
            K, F, jnp.zeros(2, rdt), force, stiff, tol, caps, refs,
            max_iter=2)
        return {"X0": X, "status": jnp.asarray(st, jnp.int32)}

    monkeypatch.setenv("RAFT_TPU_ESCALATE", "retol")
    out_dir = str(tmp_path / "sweep")
    out = run_sweep_checkpointed_full(
        evaluate, {"F0": CASES_F0[:2]}, out_dir, shard_size=2,
        mesh=mesh2(), out_keys=("X0", "status"))
    assert list(np.asarray(out["status"])) == [health.OK, health.OK]
    (entry,) = resilience.load_quarantine(out_dir)
    assert entry["escalation"]["resolved_by"] == "retol"
    assert entry["escalation"]["rungs_tried"] == ["retol"]
    np.testing.assert_allclose(np.asarray(out["X0"])[1],
                               [4.7251313, -4.7251313], rtol=1e-6)


def test_nan_rows_climb_the_ladder_too(tmp_path, monkeypatch):
    """With escalation active, NaN rows take the ladder instead of the
    legacy solo retry — and a deterministic pathology that persists
    through every rung stays quarantined, original NaNs intact."""

    def evaluate(case):
        bad = case["F0"] < 0
        x = jnp.where(bad, jnp.nan, case["F0"])
        return {"X0": jnp.stack([x, -x]),
                "status": jnp.zeros((), jnp.int32)}

    monkeypatch.setenv("RAFT_TPU_ESCALATE", "f64_cpu")
    out_dir = str(tmp_path / "sweep")
    out = run_sweep_checkpointed_full(
        evaluate, {"F0": np.asarray([1.0, -1.0])}, out_dir, shard_size=2,
        mesh=mesh2(), out_keys=("X0", "status"))
    assert np.isnan(np.asarray(out["X0"])[1]).all()
    (entry,) = resilience.load_quarantine(out_dir)
    assert entry["resolved"] is False
    assert entry["status"] & health.NONFINITE_INTERMEDIATE
    assert "NONFINITE_INTERMEDIATE" in entry["reason"]
    assert entry["escalation"]["resolved_by"] is None
    assert entry["escalation"]["rungs_tried"] == ["retol", "f64_cpu"]
    assert entry["keys_nonfinite"] == ["X0"]
