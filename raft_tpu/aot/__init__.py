"""Ahead-of-time program bank: exported, versioned compiled programs.

BENCH_r05 measured ``compile_s: 33.65`` against ``full_pipeline_s:
2.21`` — every fresh process pays ~15x the work it compiles before the
first sweep answers.  This package kills that cold start: compiled XLA
executables are serialized into a versioned on-disk bank
(:mod:`raft_tpu.aot.bank`), keyed so that a stale entry can never be
executed, and loaded by the sweep funnel
(:func:`raft_tpu.parallel.sweep._cached_jit`) *before* tracing — a
warmed fresh process answers its first sweep in seconds with ZERO
backend compilations (sentinel-verified,
:mod:`raft_tpu.analysis.recompile`).

``python -m raft_tpu.aot {warmup,list,verify,gc}`` is the operator
surface; see the README "AOT program bank & warmup" section.
"""

from raft_tpu.aot.bank import (BankMissError, BankedProgram,  # noqa: F401
                               compile_or_load)
