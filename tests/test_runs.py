"""Run-record store + perf-regression sentinel tests
(:mod:`raft_tpu.obs.runs`).

Fast tier, toy evaluators on a small CPU mesh:

* a checkpointed sweep with ``RAFT_TPU_RUNS_DIR`` set appends one
  schema-versioned record (env fingerprint, metrics snapshot, git SHA);
* the acceptance drill: clean back-to-back runs regress-clean (exit 0),
  a faults-injected delayed dispatch is caught (exit 1) with the
  regressed metric named;
* env-fingerprint mismatch downgrades failures to warnings;
* baseline pinning + newest-record resolution in the CLI;
* ``runs ingest`` imports the historical BENCH artifacts (including the
  early driver-wrapper schema and the timed-out r03 round);
* ``obs report --format json`` / ``runs record --events`` speak the
  same machine-readable section schema.
"""

import json
import os
import re
import shutil
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.obs import metrics, runs
from raft_tpu.parallel.sweep import make_mesh, run_sweep_checkpointed_full
from raft_tpu.utils import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "runs")


def toy_full(c):
    return {"PSD": jnp.stack([c["Hs"], c["Tp"], c["Hs"] * c["Tp"]]),
            "X0": c["Hs"] - c["Tp"]}


def _cases(n, seed=0):
    rng = np.random.default_rng(seed)
    return dict(Hs=2.0 + 6.0 * rng.random(n), Tp=8.0 + 8.0 * rng.random(n))


MESH = None


def mesh2():
    global MESH
    if MESH is None:
        MESH = make_mesh(2)
    return MESH


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


@pytest.fixture
def store(tmp_path, monkeypatch):
    d = str(tmp_path / "runs")
    os.makedirs(d)
    monkeypatch.setenv("RAFT_TPU_RUNS_DIR", d)
    return d


def _sweep(tmp_path, name, n=8, seed=0):
    out = run_sweep_checkpointed_full(
        toy_full, _cases(n, seed), str(tmp_path / name), shard_size=4,
        mesh=mesh2())
    return out


def _cli(*args, env=None):
    e = dict(os.environ)
    if env:
        e.update(env)
    return subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "runs", *args],
        capture_output=True, text=True, cwd=REPO, env=e)


# ------------------------------------------------------------- the store


def test_sweep_appends_schema_versioned_record(store, tmp_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_RUN_ID", "runstore01")
    _sweep(tmp_path, "s1")
    records = runs.list_records(store)
    assert len(records) == 1
    path, rec = records[0]
    assert rec["schema"] == runs.SCHEMA_VERSION
    assert rec["kind"] == "sweep" and rec["label"] == "s1"
    assert rec["run_id"] == "runstore01"
    assert rec["wall_s"] > 0
    # env fingerprint: host + toolchain + backend, all comparison keys
    env = rec["env"]
    for k in runs.ENV_COMPARE_KEYS:
        assert env.get(k) is not None, k
    assert env["platform"] == "cpu" and env["n_devices"] == 8
    assert re.fullmatch(r"[0-9a-f]{16}", env["code"])
    # git SHA of this checkout rides along
    assert rec["git_sha"] is None or re.fullmatch(r"[0-9a-f]{40}",
                                                  rec["git_sha"])
    flat = runs.flatten(rec)
    assert flat["counter:shards_done"] == 2
    assert flat["counter:rows_evaluated"] == 8
    assert flat["hist:shard_wall_s:p95"] > 0
    assert flat["extra:n_cases"] == 8
    # unset store = disabled recording, not an error
    monkeypatch.delenv("RAFT_TPU_RUNS_DIR")
    assert runs.maybe_record("sweep") is None


def test_regress_clean_then_catches_injected_slowdown(store, tmp_path):
    """The acceptance drill: same-host clean back-to-back runs pass
    (exit 0, noise thresholds hold); a deliberately slowed dispatch
    (delay fault at shard_eval) is caught with exit 1 and the regressed
    metric named."""
    _sweep(tmp_path, "base", seed=1)
    metrics.reset()
    _sweep(tmp_path, "clean", seed=1)
    metrics.reset()
    with faults.inject("delay:shard_eval:8"):
        _sweep(tmp_path, "slow", seed=1)
    records = runs.list_records(store)
    assert [r["label"] for _, r in records] == ["base", "clean", "slow"]
    (p_base, base), (p_clean, clean), (p_slow, slow) = records

    verdict = runs.regress_records(clean, base)
    assert verdict["comparable"] and verdict["ok"]
    assert verdict["checked"] > 0 and not verdict["regressions"]

    verdict = runs.regress_records(slow, base)
    assert not verdict["ok"]
    regressed = {r["metric"] for r in verdict["regressions"]}
    assert "hist:shard_wall_s:p95" in regressed

    # the CLI contract: exit 0 clean, exit 1 naming the metric
    p = _cli("regress", p_clean, "--baseline", p_base, "--check")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "no regressions" in p.stdout
    p = _cli("regress", p_slow, "--baseline", p_base, "--check")
    assert p.returncode == 1, p.stdout + p.stderr
    assert "hist:shard_wall_s:p95" in p.stdout
    assert "REGRESSION" in p.stdout


def test_env_mismatch_downgrades_to_warning(store, tmp_path):
    _sweep(tmp_path, "a", seed=2)
    metrics.reset()
    with faults.inject("delay:shard_eval:8"):
        _sweep(tmp_path, "b", seed=2)
    (p_a, a), (p_b, b) = runs.list_records(store)
    assert not runs.regress_records(b, a)["ok"]  # same env: caught
    # different host fingerprint: the SAME slowdown only warns
    b2 = json.loads(json.dumps(b))
    b2["env"]["host"] = "some-other-box"
    verdict = runs.regress_records(b2, a)
    assert verdict["env_mismatch"] == ["host"]
    assert verdict["regressions"] and verdict["ok"]


def test_pin_and_newest_resolution(store):
    for day, name in enumerate(("baseline", "clean", "regressed"), 1):
        shutil.copy(
            os.path.join(FIXTURES, f"{name}.json"),
            os.path.join(store,
                         f"run-2025010{day}T000000-1-{name[:6]}.json"))
    records = runs.list_records(store)
    assert len(records) == 3
    runs.pin_baseline(records[0][0], store)
    assert os.path.samefile(runs.pinned_baseline(store), records[0][0])
    # default resolution: newest record (the regressed fixture) vs pin
    p = _cli("regress", "--dir", store)
    assert p.returncode == 1, p.stdout + p.stderr
    assert "hist:shard_wall_s:p95" in p.stdout
    # list marks the pinned baseline
    p = _cli("list", "--dir", store)
    assert p.returncode == 0 and "baseline:" in p.stdout


def test_ingest_bench_artifacts(store):
    # modern artifact (r07: serve bench), early wrapper (r01), and the
    # timed-out round (r03: rc 124, parsed null) — all seven real
    # BENCH_rNN.json shapes are covered by these three
    r07 = runs.ingest_bench(os.path.join(REPO, "BENCH_r07.json"))
    assert r07["label"] == "r07" and r07["env"]["ingested"]
    assert r07["extra"]["evals_per_s"] == pytest.approx(679.98)
    assert r07["extra"]["breakdown.serve.load.p95_ms"] == pytest.approx(690.2)
    r01 = runs.ingest_bench(os.path.join(REPO, "BENCH_r01.json"))
    assert r01["label"] == "r01"
    assert r01["extra"]["value"] == pytest.approx(1351.8)
    r03 = runs.ingest_bench(os.path.join(REPO, "BENCH_r03.json"))
    assert r03["label"] == "r03" and r03["headline"]["failed"]
    # ingested records only ever WARN under regress (no env fingerprint)
    verdict = runs.regress_records(r07, r01)
    assert verdict["env_mismatch"] == ["ingested"] and verdict["ok"]
    # the CLI imports every artifact in one call
    p = _cli("ingest", *(os.path.join(REPO, f"BENCH_r0{i}.json")
                         for i in range(1, 8)), "--dir", store)
    assert p.returncode == 0, p.stdout + p.stderr
    assert len(runs.list_records(store)) == 7
    labels = {r["label"] for _, r in runs.list_records(store)}
    assert labels == {f"r0{i}" for i in range(1, 8)}


def test_watch_rules_directions():
    # latency percentiles: rel_tol 1.0 absorbs the log-bucket
    # quantization step (~1.78x) a clean rerun can take
    assert runs.watch_rule("hist:shard_wall_s:p95") == ("lower", 1.0, 0.05)
    assert runs.watch_rule("hist:serve_stage_solve_s:p50")[0] == "lower"
    # achieved rates end in _s but are higher-is-better — rule order
    # (and tighter rel_tol: 1.0 could never gate a rate)
    assert runs.watch_rule("hist:program_gflops_s:p50")[:2] == \
        ("higher", 0.5)
    assert runs.watch_rule("extra:design_evals_per_s")[0] == "higher"
    assert runs.watch_rule("waste:strips")[0] == "lower"
    assert runs.watch_rule("counter:rows_quarantined")[0] == "lower"
    # workload-shaped metrics are informational, never gated
    assert runs.watch_rule("counter:rows_evaluated") is None
    assert runs.watch_rule("hist:drag_iterations:p95") is None
    # one quantization bucket up passes, two fail (the real-model
    # clean-rerun noise model)
    base = {"schema": 1, "kind": "t", "env": {}, "snapshot": {
        "counters": {}, "gauges": {},
        "histograms": {"shard_wall_s": {
            "count": 4, "mean": 0.032, "min": 0.03, "max": 0.04,
            "sum": 0.13, "p50": 0.031623, "p95": 0.031623}}}}
    import copy as _copy

    bump1 = _copy.deepcopy(base)
    bump1["snapshot"]["histograms"]["shard_wall_s"].update(
        p50=0.056234, p95=0.056234, mean=0.056)
    bump2 = _copy.deepcopy(base)
    bump2["snapshot"]["histograms"]["shard_wall_s"].update(
        p50=0.1, p95=0.1, mean=0.1)
    assert runs.regress_records(bump1, base)["ok"]
    assert not runs.regress_records(bump2, base)["ok"]


def test_report_json_and_record_events_cli(store, tmp_path):
    """`obs report --format json` exposes every section machine-
    readably, and `runs record --events` embeds exactly those sections
    in the record instead of re-parsing rendered text."""
    cap = tmp_path / "cap.jsonl"
    events = [
        {"t": 0.0, "event": "proc_start", "pid": 1, "run_id": "r",
         "unix_t": 1700000000.0},
        {"t": 0.1, "event": "serve_request_stages", "pid": 1, "run_id": "r",
         "wall_s": 0.02, "queue_wait_s": 0.005, "tick_wait_s": 0.001,
         "dispatch_s": 0.002, "solve_s": 0.011, "post_s": 0.001,
         "escalated": False},
        {"t": 0.2, "event": "metrics_snapshot", "pid": 1, "run_id": "r",
         "snapshot": {"counters": {"pad_valid_strips": 9,
                                   "pad_total_strips": 16}}},
    ]
    cap.write_text("".join(json.dumps(e) + "\n" for e in events))
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "report", str(cap),
         "--format", "json"], capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0, p.stdout + p.stderr
    data = json.loads(p.stdout)
    assert data["serve_stages"]["n_requests"] == 1
    assert data["serve_stages"]["p95"]["stages_sum_s"] == pytest.approx(
        0.02)
    assert data["waste"]["axes"]["strips"]["waste_frac"] == pytest.approx(
        1 - 9 / 16)
    assert data["meta"]["events"] == 3 and data["event_counts"]

    p = _cli("record", "--kind", "capture", "--label", "t",
             "--events", str(cap), "--dir", store,
             "--extra-json", '{"evals_per_s": 123.0}')
    assert p.returncode == 0, p.stdout + p.stderr
    ((path, rec),) = runs.list_records(store)
    assert rec["report"]["serve_stages"]["n_requests"] == 1
    flat = runs.flatten(rec)
    assert flat["stage:solve:p95"] == pytest.approx(0.011)
    assert flat["waste:strips"] == pytest.approx(1 - 9 / 16)
    assert flat["extra:evals_per_s"] == 123.0
