"""Tier-1 tests for the jaxpr contract engine and the recompilation
sentinel (:mod:`raft_tpu.analysis`).

* the declarative contracts + primitive-count baseline hold on the
  bundled spar design under BOTH ``RAFT_TPU_DTYPE`` modes and BOTH
  fixed-point drivers (trace-only — nothing is compiled or executed);
* the contracts are non-vacuous: a seeded re-gather regression and a
  seeded host callback are caught;
* the recompilation sentinel counts real backend compiles, and a
  second identical sweep invocation is compile-free (the steady-state
  invariant reported by bench.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.analysis import jaxpr_contracts as jc
from raft_tpu.analysis import recompile


@pytest.fixture(scope="module")
def tracer():
    """One spar model build shared by every contract check."""
    return jc.EntryPointTracer()


def test_contracts_and_budgets_all_entries(tracer):
    """The acceptance gate: every entry point, both dtype modes, both
    fixed-point drivers — structural contracts AND the checked-in
    primitive-count baseline."""
    report = jc.run_checks(tracer=tracer)
    assert report["violations"] == [], "\n".join(report["violations"])
    # sanity: all four entries actually traced, in every variant
    assert set(report["counts"]) == set(jc.CONTRACTS)
    assert set(report["counts"]["solve_dynamics_fowt"]) == {
        "float64+while", "float64+scan", "float32+while", "float32+scan"}


def test_budget_catches_bloat(tracer):
    """A grown jaxpr fails the budget with a primitive-level diff."""
    jaxpr = tracer.trace("drag_lin_iter", "float64")
    counts = jc.count_primitives(jaxpr)
    counts["gather"] = counts.get("gather", 0) + 50   # simulated re-gather loop
    counts["mul"] = counts.get("mul", 0) * 3          # simulated unroll bloat
    viols = jc.check_budget("drag_lin_iter", "float64", counts,
                            jc.load_baseline())
    assert any("gather" in v for v in viols)


def test_missing_baseline_entry_is_loud():
    viols = jc.check_budget("drag_lin_iter", "float99", {"add": 1}, {})
    assert viols and "baseline" in viols[0]


def test_contract_catches_seeded_regather(tracer):
    """Non-vacuous: an Xi-dependent geometry-style lookup added to the
    iteration body violates the gather cap."""
    from raft_tpu.physics import morison

    model, fs, fh = tracer.model, tracer.fs, tracer.fh
    pre = morison.drag_lin_precompute(
        fs, fh.strips, fh.hc, fh.u[0], fh.Tn, fh.r_nodes,
        jnp.asarray(model.w))
    idx = jnp.arange(fs.nDOF)

    def regressed_iter(Xi):
        out = morison.drag_lin_iter(pre, Xi)
        # the PR-2 bug class: re-gathering per iteration
        return out["B_hydro_drag"] + Xi.real[idx, :].sum() * jnp.eye(fs.nDOF)

    Xi0 = jnp.full((fs.nDOF, model.nw), 0.1 + 0j)
    jaxpr = jax.make_jaxpr(regressed_iter)(Xi0)
    viols = jc.check_structure("drag_lin_iter", "float64", jaxpr)
    assert any("gather" in v for v in viols)


def test_contract_catches_host_callback(tracer):
    def leaky(x):
        jax.debug.callback(lambda v: None, x[0])
        return x * 2.0

    jaxpr = jax.make_jaxpr(leaky)(jnp.zeros(4))
    viols = jc.check_structure("system_response", "float64", jaxpr)
    assert any("callback" in v for v in viols)


def test_dtype64_leak_detected_in_loop_body():
    """A float64 op inside a while body is caught by the loop-scoped
    float32 contract (the build prefix stays exempt)."""
    big = jnp.asarray(np.ones(4), dtype=jnp.float64)

    def f(x):
        staged = (big * 2.0).astype(jnp.float32)  # build prefix: allowed

        def body(c):
            return c + (big.sum() / 4.0).astype(jnp.float32)  # leak: f64 sum per trip

        return jax.lax.while_loop(lambda c: c.sum() < 10.0, body,
                                  x + staged)

    jaxpr = jax.make_jaxpr(f)(jnp.zeros(4, jnp.float32))
    hits = jc.produced_64bit_in_loops(jaxpr)
    assert hits, "seeded f64 loop-body op not detected"
    c = jc.CONTRACTS["solve_dynamics_fowt"]
    assert c.dtype_clean == "loops"
    viols = jc.check_structure("solve_dynamics_fowt", "float32+while", jaxpr)
    assert any("64-bit" in v for v in viols)


# ------------------------------------------------------- recompile sentinel

def test_sentinel_counts_compiles():
    f = jax.jit(lambda x: x * 2.0 + 1.0)
    x3, x4 = jnp.ones(3), jnp.ones(4)
    f(x3).block_until_ready()  # warm

    with recompile.count_compilations() as log:
        f(x3).block_until_ready()          # cache hit
    assert log.count == 0

    with recompile.count_compilations() as log:
        f(x4).block_until_ready()          # new shape -> compile
    assert log.count >= 1
    assert log.total_seconds > 0

    with pytest.raises(recompile.RecompilationError, match="budget 0"):
        with recompile.assert_compile_budget(0):
            jax.jit(lambda x: x - 5.0)(x3).block_until_ready()


def test_second_identical_sweep_is_compile_free():
    """The steady-state invariant on the real sweep driver: the jitted
    batched program is memoized per evaluator, so a second identical
    ``sweep_cases`` invocation triggers ZERO backend compilations."""
    from raft_tpu.parallel.sweep import make_mesh, sweep_cases

    def evaluate(h, t, b):
        w = jnp.linspace(0.1, 2.0, 16)
        psd = (h / t) ** 2 / ((w - 2 * np.pi / t) ** 2 + 0.01)
        return {"PSD": psd, "X0": jnp.stack([h * jnp.cos(b),
                                             h * jnp.sin(b)])}

    mesh = make_mesh(8)
    Hs = np.linspace(1.0, 8.0, 8)
    Tp = np.linspace(6.0, 14.0, 8)
    beta = np.zeros(8)

    with recompile.count_compilations() as first:
        out1 = sweep_cases(evaluate, Hs, Tp, beta, mesh=mesh)
        jax.block_until_ready(out1)
    assert first.count >= 1  # the warm run really compiled something

    with recompile.assert_compile_budget(0, "second identical sweep"):
        out2 = sweep_cases(evaluate, Hs, Tp, beta, mesh=mesh)
        jax.block_until_ready(out2)
    np.testing.assert_array_equal(np.asarray(out1["PSD"]),
                                  np.asarray(out2["PSD"]))
