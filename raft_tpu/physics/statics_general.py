"""General statics for mixed rigid/flexible FOWTs (numpy, build time).

The jax fast path (:mod:`raft_tpu.physics.statics`) covers
single-rigid-body FOWTs; this module is the faithful general
implementation of ``FOWT.calcStatics`` for structures with flexible
(beam) members (``/root/reference/raft/raft_fowt.py:811-1285`` with the
beam branches of ``raft_member.py``: ``getInertia`` :542-657,
``getWeight`` :1183-1258, ``getHydrostatics`` :1008-1146).  It runs
once per design at the reference pose and its reduced matrices enter
the traced solves as constants.
"""

from __future__ import annotations

import numpy as np

from raft_tpu.physics.beams import fe_inertia, fe_stiffness, mass_and_center
from raft_tpu.structure.members import _frustum_vcv


def _getH(r):
    return np.array([[0.0, r[2], -r[1]], [-r[2], 0.0, r[0]], [r[1], -r[0], 0.0]])


def _translate6(M, r):
    H = _getH(r)
    out = np.zeros((6, 6))
    m = M[:3, :3]
    out[:3, :3] = m
    out[:3, 3:] = m @ H + M[:3, 3:]
    out[3:, :3] = out[:3, 3:].T
    out[3:, 3:] = H @ m @ H.T + M[3:, :3] @ H + H.T @ M[:3, 3:] + M[3:, 3:]
    return out


def _force3to6(F, r):
    return np.concatenate([F, np.cross(r, F)])


def _weight_point(mass, dR, g):
    W = _force3to6(np.array([0.0, 0.0, -g * mass]), dR)
    C = np.zeros((6, 6))
    C[3, 3] = -mass * g * dR[2]
    C[4, 4] = -mass * g * dR[2]
    return W, C


def _beam_member_arrays(mem, node_r, g):
    """Per-node inertia/weight/stiffness of a beam member at ref pose.

    Returns dict with M (6n,6n), W (6n,), C_struc (6n,6n), Kf (6n,6n),
    mass, center, mshell."""
    n = mem.ns
    M = fe_inertia(mem, node_r)
    Kf = fe_stiffness(mem, node_r)
    mass_fe, _ = mass_and_center(M, node_r)

    # lumped ballast + caps into diagonal blocks (raft_member.py:550-657)
    for i in range(n):
        for m_l, c_l, I_l in (
            (mem.node_ballast_mass[i], mem.node_ballast_center[i], mem.node_ballast_I[i]),
            (mem.node_cap_mass[i], mem.node_cap_center[i], mem.node_cap_I[i]),
        ):
            if m_l <= 0:
                continue
            Mm = np.diag([m_l, m_l, m_l, 0, 0, 0]).astype(float)
            T = mem.R0.T
            Mm[3:, 3:] = T.T @ np.diag(I_l) @ T
            M[6 * i:6 * i + 6, 6 * i:6 * i + 6] += _translate6(Mm, c_l - node_r[i])

    mass, center = mass_and_center(M, node_r)

    # ---- weight vector + per-node weight stiffness (getWeight beam branch)
    W = np.zeros(6 * n)
    C_struc = np.zeros((6 * n, 6 * n))
    mass_node = np.zeros(n)
    m_center_sum = np.zeros((n, 3))
    Dc = np.column_stack((mem.p10, mem.p20, mem.q0))
    for i in range(n - 1):
        L = np.linalg.norm(node_r[i + 1] - node_r[i])
        if mem.circular:
            Do = 0.5 * (mem.dorsl_node_ext[i, 0] + mem.dorsl_node_ext[i + 1, 0])
            Di = 0.5 * (mem.dorsl_node_int[i, 0] + mem.dorsl_node_int[i + 1, 0])
            A = np.pi * (Do**2 - Di**2) / 4
        else:
            Lo = 0.5 * (mem.dorsl_node_ext[i] + mem.dorsl_node_ext[i + 1])
            Li = 0.5 * (mem.dorsl_node_int[i] + mem.dorsl_node_int[i + 1])
            A = Lo[0] * Lo[1] - Li[0] * Li[1]
        W[6 * i:6 * i + 6] += mem.rho_shell * A * g * np.array(
            [0, 0, -L / 2, -L**2 / 12 * Dc[1, 2], L**2 / 12 * Dc[0, 2], 0])
        W[6 * (i + 1):6 * (i + 1) + 6] += mem.rho_shell * A * g * np.array(
            [0, 0, -L / 2, L**2 / 12 * Dc[1, 2], -L**2 / 12 * Dc[0, 2], 0])
        mass_node[i] += mem.rho_shell * A * L / 2
        mass_node[i + 1] += mem.rho_shell * A * L / 2
        m_center_sum[i] += mem.rho_shell * A * L / 2 * (node_r[i] + L / 4 * mem.q0)
        m_center_sum[i + 1] += mem.rho_shell * A * L / 2 * (node_r[i + 1] - L / 4 * mem.q0)

    for i in range(n):
        for m_l, c_l in ((mem.node_ballast_mass[i], mem.node_ballast_center[i]),
                         (mem.node_cap_mass[i], mem.node_cap_center[i])):
            f = m_l * g * np.array([0.0, 0, -1, 0, 0, 0])
            off = c_l - node_r[i]
            f6 = f.copy()
            f6[3:] += np.cross(off, f[:3])
            W[6 * i:6 * i + 6] += f6
            mass_node[i] += m_l
            m_center_sum[i] += m_l * c_l
        cmn = m_center_sum[i] / mass_node[i] if mass_node[i] > 0 else np.zeros(3)
        W_own, C_own = _weight_point(mass_node[i], cmn - node_r[i], g)
        C_struc[6 * i:6 * i + 6, 6 * i:6 * i + 6] = C_own

    return dict(M=M, W=W, C_struc=C_struc, Kf=Kf, mass=mass, center=center,
                mshell=mass_fe + float(mem.node_cap_mass.sum()))


def _beam_hydrostatics(mem, node_r, rho, g):
    """Beam branch of getHydrostatics (raft_member.py:1008-1146)."""
    n = mem.ns
    Fvec = np.zeros(6 * n)
    Cmat = np.zeros((6 * n, 6 * n))
    V_UW = 0.0
    r_centerV = np.zeros(3)
    AWP = IWP = xWP = yWP = 0.0

    q = mem.q0
    beta = np.arctan2(q[1], q[0])
    phi = np.arctan2(np.hypot(q[0], q[1]), q[2])
    cosPhi, sinPhi = np.cos(phi), np.sin(phi)
    tanPhi = np.tan(phi)
    cosBeta, sinBeta = np.cos(beta), np.sin(beta)

    nodes_z = node_r[:, 2]
    nodes_s = np.linalg.norm(node_r - node_r[0], axis=1)
    dist_p = np.diff(nodes_s, prepend=0)
    dist_n = np.diff(nodes_s, append=nodes_s[-1])

    waterline_node = None
    for i in range(n - 1):
        if nodes_z[i] * nodes_z[i + 1] < 0:
            waterline_node = i if abs(nodes_z[i]) < abs(nodes_z[i + 1]) else i + 1
            break

    for i in range(1, len(mem.stations)):
        lsec = mem.stations[i] - mem.stations[i - 1]
        if lsec <= 0:
            continue
        for inode in range(n):
            sA = max(nodes_s[inode] - dist_p[inode] / 2, mem.stations[i - 1])
            sB = min(nodes_s[inode] + dist_n[inode] / 2, mem.stations[i])
            l_node = sB - sA
            if l_node <= 0:
                continue
            if inode == 0:
                rA = node_r[0]
            else:
                rA = node_r[inode - 1] + (node_r[inode] - node_r[inode - 1]) * (
                    (sA - nodes_s[inode - 1]) / (nodes_s[inode] - nodes_s[inode - 1]))
            if inode == n - 1:
                rB = node_r[-1]
            else:
                rB = node_r[inode] + (node_r[inode + 1] - node_r[inode]) * (
                    (sB - nodes_s[inode]) / (nodes_s[inode + 1] - nodes_s[inode]))

            def shape_at(s):
                if mem.circular:
                    dA_st, dB_st = mem.d[i - 1, 0], mem.d[i, 0]
                    return (dB_st - dA_st) * ((s - mem.stations[i - 1]) / lsec) + dA_st
                slA_st, slB_st = mem.d[i - 1], mem.d[i]
                return (slB_st - slA_st) * ((s - mem.stations[i - 1]) / lsec) + slA_st

            if rA[2] < 0 and rB[2] < 0:
                V_sub, hc = _frustum_vcv(shape_at(sA), shape_at(sB), l_node)
                r_center = rA + (rB - rA) * (hc / l_node)
                r_rel = r_center - node_r[inode]
                Fvec[6 * inode:6 * inode + 6] += _force3to6(
                    np.array([0, 0, rho * g * V_sub]), r_rel)
                Cmat[6 * inode + 3, 6 * inode + 3] += rho * g * V_sub * r_rel[2]
                Cmat[6 * inode + 4, 6 * inode + 4] += rho * g * V_sub * r_rel[2]
                Cmat[6 * inode + 3, 6 * inode + 5] += -rho * g * V_sub * r_rel[0]
                Cmat[6 * inode + 4, 6 * inode + 5] += -rho * g * V_sub * r_rel[1]
                V_UW += V_sub
                r_centerV += r_center * V_sub
            elif rA[2] * rB[2] < 0:
                frac = abs(rA[2] / (rA[2] - rB[2]))
                rWP = rA + frac * (rB - rA)
                sWP = sA + frac * (sB - sA)
                wet = np.linalg.norm(rWP - rA)
                V_sub, hc = _frustum_vcv(shape_at(sA), shape_at(sWP), wet)
                r_center = rA + (rWP - rA) * (hc / wet)
                r_rel = r_center - node_r[inode]
                Fvec[6 * inode:6 * inode + 6] += _force3to6(
                    np.array([0, 0, rho * g * V_sub]), r_rel)
                Cmat[6 * inode + 3, 6 * inode + 3] += rho * g * V_sub * r_rel[2]
                Cmat[6 * inode + 4, 6 * inode + 4] += rho * g * V_sub * r_rel[2]
                Cmat[6 * inode + 3, 6 * inode + 5] += -rho * g * V_sub * r_rel[0]
                Cmat[6 * inode + 4, 6 * inode + 5] += -rho * g * V_sub * r_rel[1]
                V_UW += V_sub
                r_centerV += r_center * V_sub

                if inode == waterline_node:
                    M = 0.0
                    if mem.circular:
                        dWP = shape_at(sWP)
                        AWP = np.pi / 4 * dWP**2
                        IWP = np.pi / 64 * dWP**4
                        IxWP = IyWP = IWP
                        M = -rho * g * np.pi * (
                            dWP**2 / 32 * (2.0 + tanPhi**2)
                            + 0.5 * (rA[2] / cosPhi) ** 2) * sinPhi
                    else:
                        slWP = shape_at(sWP)
                        AWP = slWP[0] * slWP[1]
                        IxWP_l = slWP[0] * slWP[1] ** 3 / 12
                        IyWP_l = slWP[0] ** 3 * slWP[1] / 12
                        I_rot = mem.R0 @ np.diag([IxWP_l, IyWP_l, 0]) @ mem.R0.T
                        IxWP, IyWP = I_rot[0, 0], I_rot[1, 1]
                    Fvec[6 * inode + 3] += -sinBeta * M
                    Fvec[6 * inode + 4] += M * cosBeta
                    xWP, yWP = rWP[0], rWP[1]
                    b = 6 * inode
                    Cmat[b + 2, b + 2] += rho * g * AWP / cosPhi
                    Cmat[b + 2, b + 3] += rho * g * (-AWP * yWP)
                    Cmat[b + 2, b + 4] += rho * g * (AWP * xWP)
                    Cmat[b + 3, b + 2] += rho * g * (-AWP * yWP)
                    Cmat[b + 3, b + 3] += rho * g * (IxWP + AWP * yWP**2)
                    Cmat[b + 3, b + 4] += rho * g * (AWP * xWP * yWP)
                    Cmat[b + 4, b + 2] += rho * g * (AWP * xWP)
                    Cmat[b + 4, b + 3] += rho * g * (AWP * xWP * yWP)
                    Cmat[b + 4, b + 4] += rho * g * (IyWP + AWP * xWP**2)

    rCB = r_centerV / V_UW if V_UW > 0 else np.zeros(3)
    return dict(Fvec=Fvec, Cmat=Cmat, V_UW=V_UW, r_centerV=r_centerV, rCB=rCB,
                AWP=AWP, IWP=IWP, xWP=xWP, yWP=yWP)


def _rotated_member(mem, th):
    """Shallow member copy with axes rotated by the (finite) node
    rotation vector th — member.setPosition tracking nodeList[0].r[3:]
    (raft_member.py:348-357)."""
    import dataclasses

    if th is None or not np.any(th):
        return mem
    from raft_tpu.ops import transforms as tf

    R = np.asarray(tf.rotation_matrix(th[0], th[1], th[2]))
    return dataclasses.replace(
        mem, q0=R @ mem.q0, p10=R @ mem.p10, p20=R @ mem.p20,
        R0=R @ mem.R0)


def calc_statics_general(fs, Xi0=None):
    """FOWT.calcStatics equivalent for mixed rigid/flexible structures
    (raft_fowt.py:811-1285), optionally at a DISPLACED mean pose: node
    positions from the nonlinear rigid-link/beam kinematics
    (setNodesPosition, raft_fowt.py:669-752), member axes rotated with
    their nodes, and T rebuilt at the displaced positions.  Returns the
    same dict as the jax fast path (numpy values)."""
    import jax.numpy as jnp

    from raft_tpu.physics.statics import member_hydrostatics, member_inertia

    rho, g = fs.rho_water, fs.g
    N = fs.n_nodes
    nF = 6 * N
    T = fs.T
    dT = fs.dT
    node_r = fs.node_r0
    node_rot = None
    if Xi0 is not None and np.any(np.asarray(Xi0)):
        # self-consistent displaced-pose kinematics (see
        # Topology.self_consistent_displacements)
        disp, T = fs.topology.self_consistent_displacements(
            fs.T, fs.reducedDOF, fs.root_id, np.asarray(Xi0, dtype=float))
        node_r = fs.node_r0 + disp[:, :3]
        node_rot = disp[:, 3:]

    M_full = np.zeros((nF, nF))
    Msub_full = np.zeros((nF, nF))
    Cs_full = np.zeros((nF, nF))
    Cssub_full = np.zeros((nF, nF))
    Ch_full = np.zeros((nF, nF))
    Ce_full = np.zeros((nF, nF))
    W_full = np.zeros(nF)
    Wsub_full = np.zeros(nF)
    Wh_full = np.zeros(nF)
    f0_full = np.zeros(nF)
    Wint_s_full = np.zeros(nF)
    Wint_h_full = np.zeros(nF)

    m_center_sum = np.zeros(3)
    m_sub_sum = np.zeros(3)
    m_sub = 0.0
    VTOT = AWP_TOT = IWPx = IWPy = 0.0
    Sum_V_rCB = np.zeros(3)
    mtower, rCG_tow = [], []
    mem_info = []

    claimed = set(d[0] for d in fs.reducedDOF)

    for im, mem in enumerate(fs.members):
        n0 = int(fs.member_node[im])
        if node_rot is not None:
            mem = _rotated_member(mem, node_rot[n0])
        if mem.mtype == "rigid":
            nn = 1
            r_n = node_r[n0]
            if mem.part_of != "nacelle":
                M6, mass, s_bar, _ = member_inertia(
                    mem, jnp.asarray(mem.R0), jnp.asarray(mem.q0))
                M6 = np.asarray(M6)
                mass = float(mass)
                dCG = np.asarray(mem.q0) * float(s_bar)
                W6, C6 = _weight_point(mass, dCG, g)
                sl = slice(6 * n0, 6 * n0 + 6)
                M_full[sl, sl] += M6
                W_full[sl.start:sl.stop] += W6
                Cs_full[sl, sl] += C6
                center = dCG + node_r[n0]
                m_center_sum += center * mass
                if mem.part_of == "tower":
                    mtower.append(mass)
                    rCG_tow.append(center)
                else:
                    Msub_full[sl, sl] += M6
                    Cssub_full[sl, sl] += C6
                    Wsub_full[sl.start:sl.stop] += W6
                    m_sub += mass
                    m_sub_sum += center * mass
                mem_info.append(dict(mass=mass, center=center, V=None))
            elif mem.name != "nacelle":
                mem_info.append(dict(mass=0.0, center=np.zeros(3), V=None))
                continue
            else:
                mem_info.append(dict(mass=0.0, center=np.zeros(3), V=None))
            hs = member_hydrostatics(
                mem, jnp.asarray(mem.q0), jnp.asarray(mem.p10),
                jnp.asarray(mem.p20), jnp.asarray(mem.R0),
                jnp.asarray(r_n), rho, g)
            sl = slice(6 * n0, 6 * n0 + 6)
            Wh_full[sl.start:sl.stop] += np.asarray(hs["Fvec"])
            Ch_full[sl, sl] += np.asarray(hs["Cmat"])
            V = float(hs["V_UW"])
            rCB_m = (np.asarray(hs["r_centerV"]) / V - r_n) if V > 0 else np.zeros(3)
            xWP = float(hs["xWP"]) - r_n[0] + node_r[n0][0]
            yWP = float(hs["yWP"]) - r_n[1] + node_r[n0][1]
            VTOT += V
            AWP_TOT += float(hs["AWP"])
            IWPx += float(hs["IWP"]) + float(hs["AWP"]) * yWP**2
            IWPy += float(hs["IWP"]) + float(hs["AWP"]) * xWP**2
            Sum_V_rCB += (rCB_m + node_r[n0]) * V
            mem_info[-1]["V"] = V
            mem_info[-1]["rCB"] = rCB_m + node_r[n0]
        else:  # beam
            nn = mem.ns
            sl = slice(6 * n0, 6 * (n0 + nn))
            r_nodes_m = node_r[n0:n0 + nn]
            arr = _beam_member_arrays(mem, r_nodes_m, g)
            M_full[sl, sl] += arr["M"]
            W_full[sl.start:sl.stop] += arr["W"]
            Cs_full[sl, sl] += arr["C_struc"]
            Ce_full[sl, sl] += arr["Kf"]
            m_center_sum += arr["center"] * arr["mass"]
            if mem.part_of == "tower":
                mtower.append(arr["mass"])
                rCG_tow.append(arr["center"])
            else:
                Msub_full[sl, sl] += arr["M"]
                Cssub_full[sl, sl] += arr["C_struc"]
                Wsub_full[sl.start:sl.stop] += arr["W"]
                m_sub += arr["mass"]
                m_sub_sum += arr["center"] * arr["mass"]
            hs = _beam_hydrostatics(mem, r_nodes_m, rho, g)
            Wh_full[sl.start:sl.stop] += hs["Fvec"]
            Ch_full[sl, sl] += hs["Cmat"]
            V = hs["V_UW"]
            VTOT += V
            AWP_TOT += hs["AWP"]
            IWPx += hs["IWP"] + hs["AWP"] * hs["yWP"] ** 2
            IWPy += hs["IWP"] + hs["AWP"] * hs["xWP"] ** 2
            Sum_V_rCB += hs["r_centerV"]
            mem_info.append(dict(mass=arr["mass"], center=arr["center"],
                                 V=V, rCB=hs["rCB"]))

            # internal loads at beam end nodes (raft_fowt.py:1088-1115)
            endA, endB = n0, n0 + nn - 1
            incA = endA not in claimed
            incB = endB not in claimed
            FwA = FwB = np.zeros(6)
            FbA = FbB = np.zeros(6)
            if incA and incB:
                FwA, _ = _weight_point(arr["mass"] / 2, arr["center"] - node_r[endA], g)
                FwB, _ = _weight_point(arr["mass"] / 2, arr["center"] - node_r[endB], g)
                FbA = _force3to6(np.array([0, 0, rho * g * V / 2]), hs["rCB"] - node_r[endA])
                FbB = _force3to6(np.array([0, 0, rho * g * V / 2]), hs["rCB"] - node_r[endB])
            elif incA:
                FwA, _ = _weight_point(arr["mass"], arr["center"] - node_r[endA], g)
                FbA = _force3to6(np.array([0, 0, rho * g * V]), hs["rCB"] - node_r[endA])
            elif incB:
                FwB, _ = _weight_point(arr["mass"], arr["center"] - node_r[endB], g)
                FbB = _force3to6(np.array([0, 0, rho * g * V]), hs["rCB"] - node_r[endB])
            Wint_s_full[6 * endA:6 * endA + 6] += FwA
            Wint_s_full[6 * endB:6 * endB + 6] += FwB
            Wint_h_full[6 * endA:6 * endA + 6] += FbA
            Wint_h_full[6 * endB:6 * endB + 6] += FbB

    # ---- RNA (raft_fowt.py:1033-1052)
    from raft_tpu.ops import transforms as tf
    import jax.numpy as jnp2

    for ir, rot in enumerate(fs.rotors):
        node = int(fs.rotor_node[ir])
        Rn = np.eye(3)
        if node_rot is not None and np.any(node_rot[node]):
            Rn = np.asarray(tf.rotation_matrix(*node_rot[node]))
        Mm = np.diag([rot.mRNA, rot.mRNA, rot.mRNA, rot.IxRNA, rot.IrRNA, rot.IrRNA])
        Mm = np.asarray(tf.rotate_matrix_6(jnp2.asarray(Mm), jnp2.asarray(Rn @ rot.R_q0)))
        dCG = (Rn @ rot.q_rel) * rot.xCG_RNA
        W6, C6 = _weight_point(rot.mRNA, dCG, g)
        sl = slice(6 * node, 6 * node + 6)
        W_full[sl.start:sl.stop] += W6
        M_full[sl, sl] += _translate6(Mm, dCG)
        Cs_full[sl, sl] += C6
        m_center_sum += (rot.r_rel + dCG) * rot.mRNA

    # ---- point inertias / loads
    for pi_ in fs.pointInertias:
        node = int(np.argmin(np.linalg.norm(node_r - np.asarray(pi_["r"]), axis=1)))
        dR = np.asarray(pi_["r"]) - node_r[node]
        W6, C6 = _weight_point(pi_["m"], dR, g)
        M6 = _translate6(np.asarray(pi_["inertia"], dtype=float), dR)
        sl = slice(6 * node, 6 * node + 6)
        W_full[sl.start:sl.stop] += W6
        M_full[sl, sl] += M6
        Cs_full[sl, sl] += C6
        Msub_full[sl, sl] += M6
        Cssub_full[sl, sl] += C6
        Wsub_full[sl.start:sl.stop] += W6
        m_sub += pi_["m"]
        m_sub_sum += np.asarray(pi_["r"]) * pi_["m"]
        m_center_sum += np.asarray(pi_["r"]) * pi_["m"]
    for pl in fs.pointLoads:
        node = int(np.argmin(np.linalg.norm(node_r - np.asarray(pl["r"]), axis=1)))
        f6 = np.asarray(pl["f"], dtype=float).copy()
        f6[3:] += np.cross(np.asarray(pl["r"]) - node_r[node], f6[:3])
        f0_full[6 * node:6 * node + 6] += f6

    # ---- reduce (raft_fowt.py:1118-1128)
    M_struc = T.T @ M_full @ T
    M_struc_sub = T.T @ Msub_full @ T
    C_hydro = T.T @ Ch_full @ T
    C_struc = T.T @ Cs_full @ T
    C_struc_sub = T.T @ Cssub_full @ T
    C_elast = T.T @ Ce_full @ T
    W_struc = T.T @ W_full
    W_hydro = T.T @ Wh_full
    f0_add = T.T @ f0_full
    W_int_s = T.T @ Wint_s_full
    W_int_h = T.T @ Wint_h_full

    # ---- geometric stiffness of flexible members (raft_fowt.py:1131-1180)
    def geom_stiffness(mem, n0, force_red):
        nn = mem.ns
        Wnodes = np.zeros((nn, 6))
        for i in range(nn):
            Wnodes[i] = T[6 * (n0 + i):6 * (n0 + i) + 6, :] @ force_red
        Kg = np.zeros((6 * nn, 6 * nn))
        for i in range(nn):
            W_after = np.sum(Wnodes[i + 1:], axis=0)
            W_before = -W_after - Wnodes[i]
            r_b = np.zeros(3)
            r_a = np.zeros(3)
            if i != 0:
                r_b = (node_r[n0 + i] + node_r[n0 + i - 1]) / 2 - node_r[n0 + i]
            if i != nn - 1:
                r_a = (node_r[n0 + i] + node_r[n0 + i + 1]) / 2 - node_r[n0 + i]
            Kn = np.zeros((6, 6))
            Kn[3, 3] = (W_after[2] * r_a[2] + W_before[2] * r_b[2]) + (W_after[1] * r_a[1] + W_before[1] * r_b[1])
            Kn[4, 4] = (W_after[2] * r_a[2] + W_before[2] * r_b[2]) + (W_after[0] * r_a[0] + W_before[0] * r_b[0])
            Kn[5, 5] = (W_after[1] * r_a[1] + W_before[1] * r_b[1]) + (W_after[0] * r_a[0] + W_before[0] * r_b[0])
            Kn[3, 4] = -W_after[1] * r_a[0] - W_before[1] * r_b[0]
            Kn[3, 5] = -W_after[2] * r_a[0] - W_before[2] * r_b[0]
            Kn[4, 5] = -W_after[2] * r_a[1] - W_before[2] * r_b[1]
            Kn[4, 3] = -W_after[0] * r_a[1] - W_before[0] * r_b[1]
            Kn[5, 4] = -W_after[0] * r_a[2] - W_before[0] * r_b[2]
            Kn[5, 3] = -W_after[1] * r_a[2] - W_before[1] * r_b[2]
            Kg[6 * i:6 * i + 6, 6 * i:6 * i + 6] = Kn
        return Kg

    Kg_s_full = np.zeros((nF, nF))
    Kg_h_full = np.zeros((nF, nF))
    for im, mem in enumerate(fs.members):
        if mem.mtype == "beam":
            n0 = int(fs.member_node[im])
            sl = slice(6 * n0, 6 * (n0 + mem.ns))
            Kg_s_full[sl, sl] = geom_stiffness(mem, n0, W_struc + W_int_s)
            Kg_h_full[sl, sl] = geom_stiffness(mem, n0, W_hydro + W_int_h)
    C_struc = C_struc + T.T @ Kg_s_full @ T
    C_hydro = C_hydro + T.T @ Kg_h_full @ T

    # ---- dT geometric terms (raft_fowt.py:1182-1194)
    nD = fs.nDOF
    Cg_h = -np.einsum("fij,f->ij", dT, Wh_full + Wint_h_full)
    Cg_s = -np.einsum("fij,f->ij", dT, W_full + Wint_s_full)
    Cg_ss = -np.einsum("fij,f->ij", dT, Wsub_full)
    C_hydro = C_hydro + Cg_h
    C_struc = C_struc + Cg_s
    C_struc_sub = C_struc_sub + Cg_ss

    sym = lambda A: 0.5 * (A + A.T)
    M_struc, M_struc_sub = sym(M_struc), sym(M_struc_sub)
    C_hydro, C_struc, C_struc_sub = sym(C_hydro), sym(C_struc), sym(C_struc_sub)
    C_elast = sym(C_elast)

    # ---- totals (raft_fowt.py:1206-1285)
    Xh = np.array([1.0 if d[1] == 0 else 0.0 for d in fs.reducedDOF])
    m_all = float(np.sum((M_struc @ Xh) * Xh))
    rCG = m_center_sum / m_all
    rCG_sub = m_sub_sum / m_sub if m_sub > 0 else np.zeros(3)
    rCB = Sum_V_rCB / VTOT if VTOT > 0 else np.zeros(3)
    zMeta = rCB[2] + IWPx / VTOT if VTOT > 0 else 0.0

    M_sub6 = _translate6(M_struc_sub[:6, :6], -rCG_sub)
    M_all6 = _translate6(M_struc[:6, :6], -rCG)

    pb, m_ballast = [], []
    for mem in fs.members:
        if mem.part_of == "nacelle":
            continue
        for p in mem.pfill:
            if p != 0 and p not in pb:
                pb.append(p)
    m_ballast = np.zeros(len(pb))
    for mem in fs.members:
        if mem.part_of == "nacelle":
            continue
        for mf, p in zip(mem.mfill, mem.pfill):
            if p != 0:
                m_ballast[pb.index(p)] += mf

    return dict(
        M_struc=M_struc, M_struc_sub=M_struc_sub, C_struc=C_struc,
        C_struc_sub=C_struc_sub, C_hydro=C_hydro, C_elast=C_elast,
        W_struc=W_struc, W_hydro=W_hydro, f0_additional=f0_add,
        rCG=rCG, rCG_sub=rCG_sub, rCB=rCB, m=m_all, m_sub=m_sub,
        V=VTOT, AWP=AWP_TOT, rM=np.array([rCB[0], rCB[1], zMeta]),
        m_ballast=m_ballast, pb=pb, mtower=mtower, rCG_tow=rCG_tow,
        M_all6=M_all6, M_sub6=M_sub6, r_nodes=node_r,
        R_ptfm=np.eye(3), Tn=None,
    )
