"""Canary-gated rolling upgrade of the serving fleet to a cut release.

The zero-downtime recipe, replica by replica ("surf replacement" — the
fleet never shrinks, a key never moves):

1. **resolve + preflight** — the candidate release manifest must
   verify (signature, content address, every entry present in the
   shared bank with its exact sha).  The compile bill was paid at
   warmup/cut time; a rollout never compiles.
2. **promote + mark** — flip ``releases/current.json`` to the
   candidate and write ``releases/rollout.json`` (``from``/``to``):
   from here until the marker clears, BOTH release ids are legitimate
   fleet members — the router canary's provenance-consistency check
   reads exactly this window (:func:`raft_tpu.aot.release.
   parity_context`), so a mixed-version fleet mid-rollout is expected
   state, not an alarm.
3. **per replica: spawn → seize → drain → canary** — spawn the
   upgraded process under the manifest's captured flag environment
   (``--takeover``): it warms from the bank, binds, then atomically
   SEIZES the same replica id's lease (same ring vnodes — the router
   sees one endpoint change, no key movement) and only then is the
   old process drained (in-flight work finishes behind the failover
   ladder).  The step passes once the router's canary has probed the
   mixed fleet green (``ROLLOUT_CANARY_PROBES`` fresh passes, zero
   fresh fails, parity ok) with no firing alert.
4. **automatic rollback** — ANY step failure (join timeout, red
   canary, firing alert) re-points ``current`` at the parent release
   and rolls the already-upgraded replicas back the same seize-and-
   drain way.  No operator input; the run record names the aborted
   release.

The whole rollout emits one ``rollout`` span (steps as child spans,
spawned replicas stitched in via traceparent propagation), a
``rollout_*`` event stream, and one ``rollout`` run record
(:mod:`raft_tpu.obs.runs`) — the ``rollout-record`` schema family.

``FleetOps`` isolates every side effect (ledger reads, process spawn,
drain POSTs, canary verdicts) behind one injectable seam, so the
state machine is unit-testable without sockets or subprocesses.
"""

from __future__ import annotations

import http.client
import json
import time

from raft_tpu.obs import metrics, runs
from raft_tpu.obs.spans import propagation_env, span
from raft_tpu.serve import fleet
from raft_tpu.utils import config
from raft_tpu.utils.structlog import log_event


def _http_get_json(url, path, timeout_s=5.0):
    """Blocking GET of ``{url}{path}``; parsed body or None."""
    base = url.split("//", 1)[-1].rstrip("/")
    host, _, port = base.partition(":")
    conn = http.client.HTTPConnection(host, int(port or 80),
                                      timeout=timeout_s)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            return None
        return json.loads(body)
    except (OSError, http.client.HTTPException, ValueError):
        return None
    finally:
        conn.close()


def _http_drain(addr, port, timeout_s=5.0):
    """POST /drain to one replica endpoint (loopback admin); True when
    the replica acknowledged (202/200)."""
    conn = http.client.HTTPConnection(addr, int(port), timeout=timeout_s)
    try:
        conn.request("POST", "/drain", body=b"{}",
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        return resp.status in (200, 202)
    except (OSError, http.client.HTTPException):
        return False
    finally:
        conn.close()


def gate_decision(payload, baseline, need, replica=None, endpoint=None):
    """Pure per-poll gate decision for one rollout step from one
    ``/alerts`` payload snapshot: ``("green"|"red"|"pending",
    reason)``.  Green requires ``need`` observations of the replaced
    ``replica`` at its post-seize ``endpoint`` specifically (the
    canary's per-replica observation run restarts on an endpoint
    change, so its count is the new process's probe count); without a
    replica/endpoint the gate falls back to fleet-wide fresh passes.
    Factored out of :meth:`FleetOps.canary_verdict` so the protocol
    model checker can interleave the REAL gate against the canary
    state machine — the pre-PR-16 fleet-wide-pass race lives exactly
    here."""
    can = (payload or {}).get("canary")
    if not can:
        return "pending", "no-canary"
    fails = int(can.get("fails") or 0) - baseline["fails"]
    if fails > 0:
        return "red", "canary-fail"
    if not can.get("parity_ok", True):
        return "red", "canary-parity"
    active = (payload or {}).get("active") or []
    if active:
        names = sorted(a.get("rule") or "?" for a in active)
        return "red", "alert:" + ",".join(names)
    if replica is not None and endpoint:
        run = (can.get("probes") or {}).get(str(replica)) or {}
        fresh = (int(run.get("n") or 0)
                 if run.get("endpoint") == str(endpoint) else 0)
    else:
        fresh = int(can.get("passes") or 0) - baseline["passes"]
    if fresh >= need:
        return "green", f"canary-green({fresh})"
    return "pending", "waiting"


class FleetOps:
    """The rollout driver's side-effect seam against a real fleet:
    ledger reads, takeover spawns, drain POSTs and router-canary
    verdicts.  Tests inject a fake with the same five methods."""

    def __init__(self, root, designs_spec, router_url=None):
        self.root = root
        self.designs_spec = list(designs_spec)
        self.router_url = router_url
        self.ledger = fleet.FleetLedger(root)
        self.poll_s = float(config.get("ROLLOUT_POLL_S"))

    def live(self):
        return self.ledger.live()

    def spawn_takeover(self, rid, env):
        """Spawn the upgraded replica under the release's captured
        flag environment; it seizes ``rid``'s lease after warm+bind.
        The traceparent env stitches it into the rollout trace."""
        wenv = dict(env or {})
        wenv.update(propagation_env())
        proc, _rid = fleet.spawn_replica(
            self.root, self.designs_spec, replica_id=rid, env=wenv,
            extra_args=["--takeover"])
        return proc

    def wait_takeover(self, rid, prev_rec, timeout_s, proc=None):
        """Block until ``rid``'s lease changed hands (token differs
        from the previous owner's) and is live; the new lease record,
        or None on timeout / candidate death."""
        deadline = time.monotonic() + float(timeout_s)
        prev_token = (prev_rec or {}).get("token")
        while time.monotonic() < deadline:
            if proc is not None and proc.poll() is not None:
                return None  # candidate died before seizing (see log)
            rec = self.live().get(rid)
            if rec is not None and rec.get("token") != prev_token:
                return rec
            time.sleep(self.poll_s)
        return None

    def drain(self, rec):
        """Drain the PREVIOUS owner of a seized lease at its old
        endpoint (the lease already names the new one)."""
        if not rec or not rec.get("port"):
            return False
        return _http_drain(rec.get("addr") or "127.0.0.1", rec["port"])

    def canary_baseline(self):
        """Pass/fail counters before a step, so the gate only credits
        FRESH probe results; None when no router canary is attached."""
        if not self.router_url:
            return None
        payload = _http_get_json(self.router_url, "/alerts")
        can = (payload or {}).get("canary")
        if not can:
            return None
        return {"passes": int(can.get("passes") or 0),
                "fails": int(can.get("fails") or 0)}

    def canary_verdict(self, baseline, timeout_s, replica=None,
                       endpoint=None):
        """Gate one step on the live router canary: green needs
        ``ROLLOUT_CANARY_PROBES`` probes **of the replaced replica at
        its post-seize endpoint** (the canary's per-replica observation
        run restarts when the probed endpoint changes, so its count IS
        the new process's probe count — fleet-wide passes from healthy
        neighbors, and probes of the OLD process still answering its
        drain window, can never green the gate before the candidate
        was observed), with zero fresh fails anywhere, parity ok, and
        no active alert.  Returns ``(ok, reason)``; skipped (no
        router/canary attached) counts as green — standalone fleets
        can still roll.  Without ``replica``/``endpoint`` the gate
        falls back to fleet-wide fresh passes."""
        if not self.router_url or baseline is None:
            return True, "canary-skipped"
        need = int(config.get("ROLLOUT_CANARY_PROBES"))
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            payload = _http_get_json(self.router_url, "/alerts")
            verdict, reason = gate_decision(payload, baseline, need,
                                            replica=replica,
                                            endpoint=endpoint)
            if verdict != "pending":
                return verdict == "green", reason
            time.sleep(self.poll_s)
        return False, "canary-timeout"


def _upgrade_one(ops, rid, prev_rec, env, timeout_s):
    """One surf replacement: spawn under ``env``, wait for the seize,
    drain the old owner, gate on the canary.  ``(ok, reason)``."""
    baseline = ops.canary_baseline()
    proc = ops.spawn_takeover(rid, env)
    rec = ops.wait_takeover(rid, prev_rec, timeout_s, proc=proc)
    if rec is None:
        return False, "join-timeout"
    ops.drain(prev_rec)
    endpoint = f"{rec.get('addr') or '127.0.0.1'}:{rec.get('port')}"
    return ops.canary_verdict(baseline, timeout_s, replica=rid,
                              endpoint=endpoint)


def build_record(to_release, from_release, ok, replaced, rolled_back,
                 reason, steps, wall_s):
    """The ``rollout-record`` payload embedded in the run record's
    ``extra`` block — names the candidate (and, on rollback, the
    ABORTED release sha the postmortem greps for)."""
    record = {
        "to": to_release,
        "from": from_release,
        "ok": bool(ok),
        "replaced": list(replaced),
        "rolled_back": bool(rolled_back),
        "aborted": (to_release if rolled_back else None),
        "reason": reason,
        "steps": list(steps),
        "wall_s": round(float(wall_s), 3),
    }
    return record


def summarize_record(record):
    """One console line from a rollout record (CLI footer + drill
    assertions)."""
    verb = ("rolled back" if record.get("rolled_back")
            else "upgraded" if record["ok"] else "failed")
    n = len(record.get("replaced") or ())
    return (f"rollout {record['to']}: {verb} ({n} replaced, "
            f"reason={record.get('reason') or 'clean'}, "
            f"{record.get('wall_s')}s)")


def run_rollout(root, to_release, designs_spec, router_url=None,
                ops=None):
    """Drive one canary-gated rolling upgrade of the fleet at ``root``
    to ``to_release``; returns the rollout record (see
    :func:`build_record`).  Exceptions before the promote leave the
    fleet untouched; any failure after it triggers automatic
    rollback."""
    from raft_tpu.aot import release

    t0 = time.monotonic()
    man = release.load_release(to_release)
    if man is None:
        raise FileNotFoundError(
            f"no release {to_release!r} under {release.releases_dir()} "
            "(cut + verify it first)")
    problems = release.verify_manifest(man) \
        or release.verify_against_bank(man)
    if problems:
        raise ValueError(f"refusing to roll out {to_release}: "
                         + "; ".join(problems))
    from_release = release.current_release()
    parent_man = release.load_release(from_release) \
        if from_release else None
    ops = ops if ops is not None else FleetOps(root, designs_spec,
                                               router_url=router_url)
    timeout_s = float(config.get("ROLLOUT_HEALTH_TIMEOUT_S"))
    fleet_now = ops.live()
    order = sorted(fleet_now)
    steps, upgraded = [], []
    ok, reason = True, None
    with span("rollout", to=to_release):
        log_event("rollout_start", to=to_release,
                  **{"from": from_release}, replicas=order, root=root)
        release.promote(to_release)
        release.write_rollout_marker(from_release, to_release)
        try:
            for rid in order:
                st = time.monotonic()
                with span("rollout_step", replica=rid):
                    step_ok, why = _upgrade_one(
                        ops, rid, fleet_now[rid], man.get("env") or {},
                        timeout_s)
                wall = round(time.monotonic() - st, 3)
                log_event("rollout_step", replica=rid, phase="upgrade",
                          ok=step_ok, wall_s=wall)
                steps.append({"replica": rid, "phase": "upgrade",
                              "ok": step_ok, "reason": why,
                              "wall_s": wall})
                # the seize may have landed even when the canary then
                # failed — the candidate owns the lease and must be
                # rolled back with the green ones
                upgraded.append(rid)
                if not step_ok:
                    ok, reason = False, why
                    break
            if not ok and from_release:
                metrics.counter("rollout_rollbacks").inc()
                log_event("rollout_rollback", to=from_release,
                          reason=reason, aborted=to_release)
                release.promote(from_release)
                for rid in upgraded:
                    st = time.monotonic()
                    prev = ops.live().get(rid) or fleet_now.get(rid)
                    with span("rollout_step", replica=rid,
                              phase="rollback"):
                        back_ok, back_why = _upgrade_one(
                            ops, rid, prev,
                            (parent_man or {}).get("env") or {},
                            timeout_s)
                    wall = round(time.monotonic() - st, 3)
                    log_event("rollout_step", replica=rid,
                              phase="rollback", ok=back_ok, wall_s=wall)
                    steps.append({"replica": rid, "phase": "rollback",
                                  "ok": back_ok, "reason": back_why,
                                  "wall_s": wall})
        finally:
            release.clear_rollout_marker()
        wall_s = time.monotonic() - t0
        log_event("rollout_done", to=to_release, ok=ok,
                  replaced=len(upgraded if ok else ()),
                  rolled_back=not ok, wall_s=round(wall_s, 3))
    record = build_record(to_release, from_release, ok,
                          upgraded if ok else [], not ok, reason,
                          steps, wall_s)
    runs.maybe_record("rollout", label=to_release, wall_s=wall_s,
                      extra=record)
    return record
