"""Benchmark: full design evaluations per second per chip on the
north-star workload (BASELINE.md): the IEA-15MW VolturnUS-S semi at
100 frequency bins x 12 load cases with an OPERATING TURBINE — i.e.
each case evaluation includes BEMT aero-servo constants, mean
thrust/current in the equilibrium, strip-theory excitation, iterative
stochastic drag linearisation and the per-frequency 6-DOF complex
impedance solves (the chain of raft_model.py:966-1255).

* raft_tpu path: ``api.make_full_evaluator`` — the whole chain as one
  jit — vmapped over (designs x cases) on this chip.
* baseline: a serial NumPy twin of the same math structured the way the
  reference is (per-strip/per-frequency Python loops,
  raft_model.py:1084-1089, raft_member.py:1965-2124), with rotor aero
  from a serial blade-element solve (scipy brentq per element, central
  finite differences for the load derivatives).  Measured here because
  the reference publishes no numbers and cannot run in this image (its
  moorpy/ccblade deps are absent; see BASELINE.md).

One design evaluation = the full 12-case table.  Prints ONE JSON line:
{"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import time

import numpy as np

from raft_tpu.utils import config

VOLTURN = "/root/reference/examples/VolturnUS-S_example.yaml"

# 12-case table: operating turbine across the schedule, varied seas
CASES = [
    # (wind_speed, wind_heading, TI, Hs, Tp, wave_heading)
    (4.0, 0.0, 0.12, 1.5, 7.0, 0.0),
    (6.0, 0.0, 0.12, 1.8, 7.5, 0.0),
    (8.0, 10.0, 0.12, 2.2, 8.0, 10.0),
    (10.0, 0.0, 0.14, 2.8, 9.0, 0.0),
    (10.6, 0.0, 0.14, 3.0, 9.5, 20.0),
    (12.0, -10.0, 0.14, 3.4, 10.0, 0.0),
    (14.0, 0.0, 0.14, 4.0, 10.5, 0.0),
    (16.0, 0.0, 0.16, 4.6, 11.0, 30.0),
    (18.0, 20.0, 0.16, 5.2, 11.5, 0.0),
    (20.0, 0.0, 0.16, 5.8, 12.0, 0.0),
    (22.0, 0.0, 0.16, 6.5, 12.5, -20.0),
    (24.0, 0.0, 0.18, 7.2, 13.0, 0.0),
]


def build():
    import raft_tpu
    from raft_tpu.api import make_full_evaluator
    from raft_tpu.structure.schema import load_design

    design = load_design(VOLTURN)
    design["settings"]["min_freq"] = 0.002   # 100 w-bins (0.002..0.2 Hz)
    design["settings"]["max_freq"] = 0.2
    model = raft_tpu.Model(design)
    assert model.nw == 100
    # geometry=True: every design in the sweep is a DISTINCT geometry
    # (member d/t, ballast fill, mooring length — the WEIS design
    # variables, parametersweep.py:56-100) through one compilation
    return model, make_full_evaluator(model, geometry=True)


def sample_geometry(B, seed=0):
    """B distinct design-geometry parameter sets, parametersweep-style
    ranges (+/-8% member diameter/thickness, +/-10% ballast fill,
    +/-3% mooring length)."""
    rng = np.random.default_rng(seed)
    return np.stack([
        rng.uniform(0.92, 1.08, B),   # d_scale
        rng.uniform(0.92, 1.08, B),   # t_scale
        rng.uniform(0.90, 1.10, B),   # fill_scale
        rng.uniform(0.97, 1.03, B),   # L_moor_scale
    ], axis=1)


# ---------------------------------------------------- NumPy baseline: aero

def _np_solve_phi(Vx, Vy, sigma_p, theta, lc_tip, lc_hub, cl_tab, cd_tab,
                  aoa_rad):
    """Serial inflow-angle solve (Ning 2014 residual, brentq bracket)."""
    from scipy.optimize import brentq

    def induction(phi):
        sphi, cphi = np.sin(phi), np.cos(phi)
        sphi_s = np.sign(sphi) * max(abs(sphi), 1e-9) if sphi != 0 else 1e-9
        alpha = phi - theta
        cl = np.interp(alpha, aoa_rad, cl_tab)
        cd = np.interp(alpha, aoa_rad, cd_tab)
        cn = cl * cphi + cd * sphi
        ct = cl * sphi - cd * cphi
        Ftip = 2 / np.pi * np.arccos(np.clip(np.exp(-lc_tip / abs(sphi_s)), 0, 1))
        Fhub = 2 / np.pi * np.arccos(np.clip(np.exp(-lc_hub / abs(sphi_s)), 0, 1))
        F = max(Ftip * Fhub, 1e-6)
        kk = sigma_p * cn / (4.0 * F * sphi_s**2)
        kp = sigma_p * ct / (4.0 * F * sphi_s * cphi)
        g1 = 2 * F * kk - (10.0 / 9 - F)
        g2 = max(2 * F * kk - F * (4.0 / 3 - F), 1e-12)
        g3 = 2 * F * kk - (25.0 / 9 - 2 * F)
        if phi > 0:
            if kk <= 2.0 / 3:
                a = kk / (1.0 + kk) if abs(1 + kk) > 1e-12 else 0.0
            elif abs(g3) < 1e-6:
                a = 1.0 - 1.0 / (2.0 * np.sqrt(g2))
            else:
                a = (g1 - np.sqrt(g2)) / g3
        else:
            a = kk / (kk - 1.0) if kk > 1.0 else 0.0
        ap = kp / (1.0 - kp) if abs(1 - kp) > 1e-12 else 0.0
        return a, ap

    def residual(phi):
        a, ap = induction(phi)
        sphi, cphi = np.sin(phi), np.cos(phi)
        return sphi / max(1.0 - a, 1e-12) - Vx / Vy * cphi / max(1.0 + ap, 1e-12)

    eps = 1e-6
    try:
        if residual(eps) * residual(np.pi / 2) <= 0:
            phi = brentq(residual, eps, np.pi / 2, xtol=1e-10)
        else:
            phi = brentq(residual, np.pi / 2, np.pi - eps, xtol=1e-10)
    except ValueError:
        phi = eps
    a, ap = induction(phi)
    return phi, a, ap


def numpy_rotor_loads(rm, Uinf, Om_rpm, pitch_deg, tilt, yaw):
    """Azimuthally averaged hub loads, serial loops (baseline twin of
    the traced BEMT in raft_tpu.physics.aero)."""
    from raft_tpu.physics.aero import _curvature

    x_az, y_az, z_az, cone, _ = _curvature(rm.r, rm.precurve, rm.presweep, rm.precone)
    rfull = np.r_[rm.Rhub, rm.r, rm.Rtip]
    cvfull = np.r_[0.0, rm.precurve, rm.precurveTip]
    swfull = np.r_[0.0, rm.presweep, rm.presweepTip]
    xf, yf, zf, conef, sf = _curvature(rfull, cvfull, swfull, rm.precone)

    Omega = Om_rpm * np.pi / 30.0
    theta_r = np.deg2rad(rm.theta_deg + pitch_deg)
    sigma_p = rm.B * rm.chord / (2.0 * np.pi * rm.r)
    lc_tip = rm.B / 2.0 * (rm.Rtip - rm.r) / rm.r
    lc_hub = rm.B / 2.0 * (rm.r - rm.Rhub) / rm.Rhub
    aoa_rad = np.deg2rad(rm.aoa_deg)
    nr = len(rm.r)

    F_sum = np.zeros(3)
    M_sum = np.zeros(3)
    for isec in range(rm.nSector):
        az = isec * 2 * np.pi / rm.nSector
        sy, cy = np.sin(yaw), np.cos(yaw)
        st, ct = np.sin(tilt), np.cos(tilt)
        sa, ca = np.sin(az), np.cos(az)
        sc, cc = np.sin(cone), np.cos(cone)
        height = (y_az * sa + z_az * ca) * ct - x_az * st
        V = Uinf * (1.0 + height / rm.hubHt) ** rm.shearExp
        Vx = V * ((cy * st * ca + sy * sa) * sc + cy * ct * cc) - Omega * y_az * sc
        Vy = V * (cy * st * sa - sy * ca) + Omega * z_az

        Np = np.zeros(nr)
        Tp = np.zeros(nr)
        for ie in range(nr):  # serial element loop, as CCBlade does
            phi, a, ap = _np_solve_phi(
                Vx[ie], Vy[ie], sigma_p[ie], theta_r[ie], lc_tip[ie],
                lc_hub[ie], rm.cl[ie], rm.cd[ie], aoa_rad)
            sphi, cphi = np.sin(phi), np.cos(phi)
            alpha = phi - theta_r[ie]
            cl = np.interp(alpha, aoa_rad, rm.cl[ie])
            cd = np.interp(alpha, aoa_rad, rm.cd[ie])
            cn = cl * cphi + cd * sphi
            ctv = cl * sphi - cd * cphi
            W2 = (Vx[ie] * (1 - a)) ** 2 + (Vy[ie] * (1 + ap)) ** 2
            qd = 0.5 * rm.rho * W2 * rm.chord[ie]
            Np[ie] = cn * qd
            Tp[ie] = ctv * qd

        Npf = np.r_[0.0, Np, 0.0]
        Tpf = np.r_[0.0, Tp, 0.0]
        fx = Npf * np.cos(conef)
        fy = Tpf
        fz = Npf * np.sin(conef)
        Fx, Fy, Fz = (np.trapezoid(v, sf) for v in (fx, fy, fz))
        mx = yf * fz - zf * fy
        my = zf * fx - xf * fz
        mz = xf * fy - yf * fx
        Mx, My, Mz = (np.trapezoid(v, sf) for v in (mx, my, mz))
        F_sum += [Fx, ca * Fy - sa * Fz, sa * Fy + ca * Fz]
        M_sum += [Mx, ca * My - sa * Mz, sa * My + ca * Mz]

    F = rm.B * F_sum / rm.nSector
    M = rm.B * M_sum / rm.nSector
    return np.array([F[0], F[1], F[2], -M[0], M[1], M[2]])


def numpy_turbine_constants(model, case, w):
    """Mean rotor force + aero damping/added-mass + gyroscopics
    (baseline twin of FOWT.calcTurbineConstants with serial BEMT and
    finite-difference load derivatives)."""
    from raft_tpu.physics.aero import RPM2RADPS, kaimal_rot_psd

    fs = model.fowtList[0]
    nw = len(w)
    out = dict(f0=np.zeros(6), A=np.zeros((6, 6, nw)), B=np.zeros((6, 6, nw)),
               B_gyro=np.zeros((6, 6)))
    speed = float(case.get("wind_speed", 0.0))
    if not model.rotor_aero or speed <= 0:
        return out
    for ir, rm in enumerate(model.rotor_aero):
        rp = fs.rotors[ir]
        heading = np.radians(float(case.get("wind_heading", 0.0)))
        yaw = heading + np.radians(float(case.get("yaw_misalign", 0.0)))
        R_q = _rotmat(0.0, -rp.shaft_tilt, rp.shaft_toe + yaw)
        q = R_q @ np.array([1.0, 0.0, 0.0])
        yaw_mis = np.arctan2(q[1], q[0]) - heading
        tilt = np.arctan2(q[2], np.hypot(q[0], q[1]))
        Om = np.interp(speed, rm.U_sched, rm.Omega_sched)
        pit = np.interp(speed, rm.U_sched, rm.pitch_sched)

        loads = numpy_rotor_loads(rm, speed, Om, pit, -tilt, yaw_mis)
        # central finite differences for the load derivatives
        dU, dOm, dPi = 0.1, 0.05, 0.05
        dT_dU = (numpy_rotor_loads(rm, speed + dU, Om, pit, -tilt, yaw_mis)[0]
                 - numpy_rotor_loads(rm, speed - dU, Om, pit, -tilt, yaw_mis)[0]) / (2 * dU)

        f0 = np.zeros(6)
        f0[:3] = R_q @ loads[:3]
        f0[3:] = R_q @ loads[3:]
        r_off = q * rp.overhang
        f0[3:] += np.cross(r_off, f0[:3])
        out["f0"] += f0

        # aeroServoMod 1: fore-aft damping dT/dU only (raft_rotor.py:880-900)
        qq = np.outer(q, q)
        B6 = np.zeros((6, 6))
        B6[:3, :3] = dT_dU * qq
        H = _skew(r_off)
        B6t = np.zeros((6, 6))
        B6t[:3, :3] = B6[:3, :3]
        B6t[:3, 3:] = B6[:3, :3] @ H
        B6t[3:, :3] = B6t[:3, 3:].T
        B6t[3:, 3:] = H @ B6[:3, :3] @ H.T
        out["B"] += B6t[:, :, None]

        # Kaimal spectrum (scipy special functions) for the excitation path
        kaimal_rot_psd(w, speed, float(case.get("turbulence", 0.1)),
                       rp.Zhub, rm.Rtip)

        IO = q * (rp.I_drivetrain * Om * 2 * np.pi / 60)
        G = np.zeros((6, 6))
        G[3:, 3:] = _skew(IO)
        out["B_gyro"] += G
    return out


# ------------------------------------------------- NumPy baseline: case

def numpy_eval_case(model, case):
    """Serial NumPy twin of one FULL case evaluation (reference-style
    loops): turbine constants -> equilibrium -> excitation -> drag
    linearisation -> per-frequency solves -> response spectra."""
    fs = model.fowtList[0]
    fh = model.hydro[0]
    ss = fh.strips
    w = model.w
    k = model.k
    nw = len(w)
    dw = w[1] - w[0]
    rho, g, depth = fs.rho_water, fs.g, fs.depth

    Hs = float(case["wave_height"])
    Tp = float(case["wave_period"])
    beta = np.radians(float(case["wave_heading"]))

    stat = model.statics()
    K_h = np.asarray(stat["C_struc"] + stat["C_hydro"])
    F_und = np.asarray(stat["W_struc"] + stat["W_hydro"])
    Imat = np.asarray(fh.hc0["Imat"])  # (S,3,3,nw)
    a_i = np.asarray(fh.hc0["a_i"])
    ms = model.ms

    # --- aero-servo constants (serial BEMT + FD derivatives)
    tc = numpy_turbine_constants(model, case, w)
    M = np.asarray(stat["M_struc"]) + np.asarray(fh.hc0["A_hydro"])

    # --- catenary mooring (serial per line, Newton)
    def line_force(r6):
        R = _rotmat(r6[3], r6[4], r6[5])
        F = np.zeros(6)
        for iL in range(ms.n_lines):
            rf = r6[:3] + R @ ms.r_fair0[iL]
            dv = rf - ms.r_anchor[iL]
            XF, ZF = np.hypot(dv[0], dv[1]), dv[2]
            HF, VF = _catenary_np(XF, ZF, ms.L[iL], ms.w[iL], ms.EA[iL])
            uh = dv[:2] / max(XF, 1e-9)
            f3 = np.array([-HF * uh[0], -HF * uh[1], -VF])
            F[:3] += f3
            F[3:] += np.cross(rf - r6[:3], f3)
        return F

    def line_stiffness(r6, dx=1e-4):
        K = np.zeros((6, 6))
        for j in range(6):
            e = np.zeros(6)
            e[j] = dx
            K[:, j] = -(line_force(r6 + e) - line_force(r6 - e)) / (2 * dx)
        return K

    # --- static equilibrium with environmental mean loads
    X = np.zeros(6)
    tols = np.array([0.05, 0.05, 0.05, 0.005, 0.005, 0.005])
    for _ in range(30):
        F = F_und - K_h @ X + tc["f0"] + line_force(X)
        K = K_h + line_stiffness(X)
        dX = np.linalg.solve(K, F)
        if np.all(np.abs(dX) < tols):
            break
        X += dX

    # --- strip frames at the mean offset
    Rp = _rotmat(X[3], X[4], X[5])
    r0n = fs.node_r0
    d = r0n - r0n[fs.root_id]
    r_nodes = r0n + X[:3] + (d @ Rp.T - d)
    q = ss.q0 @ Rp.T
    p1 = ss.p10 @ Rp.T
    p2 = ss.p20 @ Rp.T
    r = r_nodes[ss.node] + q * ss.ls[:, None]
    sub = r[:, 2] < 0
    active = sub & ss.active

    # --- sea state + per-strip wave kinematics & excitation (strip loop)
    S = _jonswap_np(w, Hs, Tp)
    zeta = np.sqrt(2 * S * dw).astype(np.complex128)
    Fexc = np.zeros((6, nw), dtype=np.complex128)
    u_all = np.zeros((ss.S, 3, nw), dtype=np.complex128)
    for s in range(ss.S):
        u, ud, pd = _wavekin_np(zeta, beta, w, k, depth, r[s], rho, g)
        u_all[s] = u
        if not active[s]:
            continue
        F3 = np.einsum("ijw,jw->iw", Imat[s], ud) + pd[None, :] * (a_i[s] * q[s])[:, None]
        lever = r[s] - r_nodes[fs.root_id]
        Fexc[:3] += F3
        Fexc[3:] += np.cross(np.broadcast_to(lever[:, None], F3.shape), F3, axis=0)

    C = K_h + line_stiffness(X)
    B_const = tc["B"] + tc["B_gyro"][:, :, None]

    # --- drag linearisation iterations + per-frequency solves
    a_q = np.where(ss.circ, np.pi * ss.ds[:, 0] * ss.dls, 2 * (ss.ds[:, 0] + ss.ds[:, 0]) * ss.dls)
    a_p1 = np.where(ss.circ, ss.ds[:, 0] * ss.dls, ss.ds[:, 0] * ss.dls)
    a_p2 = np.where(ss.circ, ss.ds[:, 0] * ss.dls, ss.ds[:, 1] * ss.dls)
    a_end = np.abs(np.where(
        ss.circ, np.pi * ss.ds[:, 0] * ss.drs[:, 0],
        (ss.ds[:, 0] + ss.drs[:, 0]) * (ss.ds[:, 1] + ss.drs[:, 1])
        - (ss.ds[:, 0] - ss.drs[:, 0]) * (ss.ds[:, 1] - ss.drs[:, 1])))

    XiLast = np.zeros((6, nw), dtype=np.complex128)
    Xi = XiLast
    for _ in range(model.nIter + 1):
        B6 = np.zeros((6, 6))
        Fdrag = np.zeros((6, nw), dtype=np.complex128)
        for s in range(ss.S):  # strip loop, as the reference does
            if not sub[s]:
                continue
            lever = r[s] - r_nodes[fs.root_id]
            th = XiLast[3:]
            vnode = 1j * w * (XiLast[:3] + np.cross(th, np.broadcast_to(lever[:, None], th.shape), axis=0))
            vrel = u_all[s] - vnode
            vq = q[s] @ vrel
            vp1 = p1[s] @ vrel
            vp2 = p2[s] @ vrel
            vrel_p = vrel - vq[None, :] * q[s][:, None]
            rms = lambda x: np.sqrt(0.5 * np.sum(np.abs(x) ** 2))
            vq_r = rms(vq)
            vp_r = rms(vrel_p)
            c = np.sqrt(8 / np.pi) * 0.5 * rho
            Bq = c * vq_r * a_q[s] * ss.Cd_q[s] + c * vq_r * a_end[s] * ss.Cd_End[s]
            Bp1 = c * (vp_r if ss.circ[s] else rms(vp1)) * a_p1[s] * ss.Cd_p1[s]
            Bp2 = c * (vp_r if ss.circ[s] else rms(vp2)) * a_p2[s] * ss.Cd_p2[s]
            Bm = (Bq * np.outer(q[s], q[s]) + Bp1 * np.outer(p1[s], p1[s])
                  + Bp2 * np.outer(p2[s], p2[s]))
            H = _skew(lever)
            B6[:3, :3] += Bm
            B6[:3, 3:] += Bm @ H
            B6[3:, :3] += (Bm @ H).T
            B6[3:, 3:] += H @ Bm @ H.T
            F3 = Bm @ u_all[s]
            Fdrag[:3] += F3
            Fdrag[3:] += np.cross(np.broadcast_to(lever[:, None], F3.shape), F3, axis=0)

        Xi = np.zeros((6, nw), dtype=np.complex128)
        for i in range(nw):  # frequency loop, as the reference does
            Z = -w[i] ** 2 * M + 1j * w[i] * (B6 + B_const[:, :, i]) + C
            Xi[:, i] = np.linalg.solve(Z, Fexc[:, i] + Fdrag[:, i])
        tolCheck = np.abs(Xi - XiLast) / (np.abs(Xi) + 0.01)
        if np.all(tolCheck < 0.01):
            break
        XiLast = 0.2 * XiLast + 0.8 * Xi

    return 0.5 * np.abs(Xi) ** 2 / dw


def _rotmat(x3, x2, x1):
    s1, c1, s2, c2, s3, c3 = np.sin(x1), np.cos(x1), np.sin(x2), np.cos(x2), np.sin(x3), np.cos(x3)
    return np.array([
        [c1 * c2, c1 * s2 * s3 - c3 * s1, s1 * s3 + c1 * c3 * s2],
        [c2 * s1, c1 * c3 + s1 * s2 * s3, c3 * s1 * s2 - c1 * s3],
        [-s2, c2 * s3, c2 * c3]])


def _skew(r):
    return np.array([[0, r[2], -r[1]], [-r[2], 0, r[0]], [r[1], -r[0], 0]])


def _jonswap_np(ws, Hs, Tp):
    TpOvrSqrtHs = Tp / np.sqrt(Hs)
    gamma = 5.0 if TpOvrSqrtHs <= 3.6 else 1.0 if TpOvrSqrtHs >= 5.0 else np.exp(5.75 - 1.15 * TpOvrSqrtHs)
    f = 0.5 / np.pi * ws
    fp4 = (Tp * f) ** -4.0
    C = 1.0 - 0.287 * np.log(gamma)
    sig = np.where(f <= 1.0 / Tp, 0.07, 0.09)
    alpha = np.exp(-0.5 * ((f * Tp - 1.0) / sig) ** 2)
    return 0.5 / np.pi * C * 0.3125 * Hs * Hs * fp4 / f * np.exp(-1.25 * fp4) * gamma**alpha


def _wavekin_np(zeta, beta, w, k, h, r, rho, g):
    x, y, z = r
    ze = zeta * np.exp(-1j * k * (np.cos(beta) * x + np.sin(beta) * y))
    if z > 0:
        nw = len(w)
        return (np.zeros((3, nw), np.complex128),
                np.zeros((3, nw), np.complex128),
                np.zeros(nw, np.complex128))
    kh = k * h
    deep = kh > 89.4
    with np.errstate(over="ignore"):
        SINH = np.where(deep, np.exp(k * z), np.sinh(np.where(deep, 0, k * (z + h))) / np.sinh(np.where(deep, 1, kh)))
        COSHs = np.where(deep, np.exp(k * z), np.cosh(np.where(deep, 0, k * (z + h))) / np.sinh(np.where(deep, 1, kh)))
        COSHc = np.where(deep, np.exp(k * z), np.cosh(np.where(deep, 0, k * (z + h))) / np.cosh(np.where(deep, 1, kh)))
    u = np.stack([w * ze * COSHs * np.cos(beta), w * ze * COSHs * np.sin(beta), 1j * w * ze * SINH])
    return u, 1j * w * u, rho * g * ze * COSHc


def _catenary_np(XF, ZF, L, w_line, EA, n_iter=60):
    lr = np.hypot(XF, ZF)
    lam = 0.2 if L <= lr else np.sqrt(max(3 * ((L**2 - ZF**2) / XF**2 - 1), 1e-12))
    HF = max(abs(0.5 * w_line * XF / lam), 1e-3)
    VF = 0.5 * w_line * (ZF / np.tanh(lam) + L)
    for _ in range(n_iter):
        def prof(HF, VF):
            t1 = VF / HF
            s1 = np.sqrt(1 + t1 * t1)
            if VF < w_line * L:  # grounded
                LB = L - VF / w_line
                X = LB + HF / w_line * np.log(t1 + s1) + HF * L / EA
                Z = HF / w_line * (s1 - 1) + VF**2 / (2 * EA * w_line)
            else:
                VA = VF - w_line * L
                t2 = VA / HF
                s2 = np.sqrt(1 + t2 * t2)
                X = HF / w_line * (np.log(t1 + s1) - np.log(t2 + s2)) + HF * L / EA
                Z = HF / w_line * (s1 - s2) + (VF * L - 0.5 * w_line * L**2) / EA
            return X, Z
        X0, Z0 = prof(HF, VF)
        dh = max(1e-4 * HF, 1.0)
        dv = max(1e-4 * abs(VF), 1.0)
        Xh, Zh = prof(HF + dh, VF)
        Xv, Zv = prof(HF, VF + dv)
        J = np.array([[(Xh - X0) / dh, (Xv - X0) / dv], [(Zh - Z0) / dh, (Zv - Z0) / dv]])
        rvec = np.array([X0 - XF, Z0 - ZF])
        try:
            dHV = np.linalg.solve(J, -rvec)
        except np.linalg.LinAlgError:
            break
        HF = max(HF + np.clip(dHV[0], -0.5 * (abs(HF) + abs(VF) + 1), 0.5 * (abs(HF) + abs(VF) + 1)), 1e-6)
        VF = VF + np.clip(dHV[1], -0.5 * (abs(HF) + abs(VF) + 1), 0.5 * (abs(HF) + abs(VF) + 1))
        if np.hypot(*rvec) < 1e-8 * max(XF, 1.0):
            break
    return HF, VF


# ------------------------------------------------------------------- main

def _enable_compile_cache():
    """Persistent XLA compilation cache: repeated bench runs (driver
    retries, round reruns) skip recompilation entirely.  The mechanism
    lives in ``raft_tpu.utils.devices.enable_compile_cache`` (shared
    with the drivers and sweep runtimes); the bench keeps its own
    repo-local cache directory and the RAFT_TPU_BENCH_PLATFORM pin."""
    from raft_tpu.utils.devices import enable_compile_cache

    enable_compile_cache(
        cache_dir=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "_jax_cache"),
        platform=config.get("BENCH_PLATFORM") or None)


BASELINE_ARTIFACT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BASELINE_MEASURED.json")


def _load_or_measure_baseline(max_measure_s):
    """Seconds per FULL design evaluation (12-case table) for the serial
    NumPy twin.  The round-3/4 timeouts were budgeting failures: the
    ~28.5 s/case baseline was re-measured *inside* every timed attempt
    (12 cases = ~342 s of a 513 s deadline re-measuring a constant).
    Now the measured value is persisted as a repo artifact
    (BASELINE_MEASURED.json, value + host fingerprint) and reused; a
    fresh measurement only happens if the artifact is missing, runs in
    the parent *before* any attempt deadline, uses NBASE=1 by default,
    and refreshes the artifact for next time."""
    import platform
    import socket

    fingerprint = dict(host=socket.gethostname(),
                       cpu=platform.processor() or platform.machine(),
                       cpu_count=os.cpu_count())
    try:
        with open(BASELINE_ARTIFACT) as f:
            art = json.load(f)
        # the artifact is only valid on the machine that measured it —
        # reusing a baseline from a different machine would make
        # vs_baseline a cross-host ratio.  Hostname alone is a weak
        # fingerprint (generic names like 'vm'), so the cpu fields
        # must match too.
        if all(art.get(k) == v for k, v in fingerprint.items()):
            return (float(art["design_eval_s"]),
                    art.get("host", "?") + " (artifact)")
    except Exception:
        pass

    n_base = config.get("BENCH_NBASE")
    model = _baseline_model()
    cases = [dict(wind_speed=c[0], wind_heading=c[1], turbulence=c[2],
                  wave_height=c[3], wave_period=c[4], wave_heading=c[5])
             for c in CASES]
    numpy_eval_case(model, cases[0])  # one-time statics JIT warmup
    times = []
    t_all0 = time.perf_counter()
    for i in range(n_base):
        t0 = time.perf_counter()
        numpy_eval_case(model, cases[i % len(cases)])
        times.append(time.perf_counter() - t0)
        if time.perf_counter() - t_all0 > max_measure_s:
            break
    design_eval_s = float(np.mean(times)) * len(CASES)
    try:
        with open(BASELINE_ARTIFACT, "w") as f:
            json.dump(dict(design_eval_s=design_eval_s,
                           case_s_mean=float(np.mean(times)),
                           n_measured=len(times), **fingerprint,
                           workload="VolturnUS-S 100w x 12 cases, serial "
                                    "NumPy twin (bench.numpy_eval_case)"), f)
    except Exception:
        pass
    return design_eval_s, fingerprint["host"]


def _baseline_model():
    import raft_tpu
    from raft_tpu.structure.schema import load_design

    design = load_design(VOLTURN)
    design["settings"]["min_freq"] = 0.002
    design["settings"]["max_freq"] = 0.2
    return raft_tpu.Model(design)


def main():
    """Driver entry.  Budget discipline (the round-4 lesson):

    1. the NumPy baseline is resolved FIRST, outside any attempt
       deadline, from the persisted artifact (free) or a single-case
       measurement (~30 s);
    2. the PROVEN configuration (flat: one baked geometry, (B*12,)
       case batch — the round-2 config that produced 28.35 evals/s)
       runs first under a bounded deadline, so a number is banked
       early;
    3. the geometry-DoE configuration gets the remainder; if it
       succeeds its (strictly harder) number is reported, otherwise
       the banked flat number is.
    Each attempt runs in a subprocess so an accelerator-compiler
    blowup cannot take down the whole bench."""
    import subprocess
    import sys

    mode = config.get("BENCH_MODE")
    if mode:
        run_mode(mode)
        return

    budget = config.get("BENCH_BUDGET_S")
    t_start = time.perf_counter()
    base_eval_s, base_host = _load_or_measure_baseline(
        max_measure_s=min(120.0, 0.15 * budget))

    # backend health probe: a dead accelerator tunnel hangs jax init
    # until killed (observed with the axon plugin), which would burn
    # every attempt's deadline before the CPU fallback gets a turn.
    # One tiny matmul in a subprocess with a generous timeout settles it
    # up front (shared with the sweep runtime's CPU-fallback logic).
    device_ok = True
    if not config.get("BENCH_PLATFORM"):
        from raft_tpu.utils.devices import probe_backend

        device_ok = probe_backend(timeout_s=config.get("BENCH_PROBE_S"))

    # mixed (shape-bucketed heterogeneous topologies) is the headline
    # workload; flat banks a proven number early; geom gets the rest
    attempts = ([("mixed", 0.3), ("flat", 0.45), ("geom", 0.8)]
                if device_ok else [])
    results = {}
    last_err = ("" if device_ok
                else "accelerator backend unavailable (health probe failed)")
    for mode, share in attempts:
        remaining = budget - (time.perf_counter() - t_start) - 10.0
        deadline = max(60.0, remaining * share)
        env = dict(os.environ, RAFT_TPU_BENCH_MODE=mode,
                   RAFT_TPU_BENCH_BASE_EVAL_S=repr(base_eval_s),
                   RAFT_TPU_BENCH_BASE_HOST=base_host,
                   RAFT_TPU_BENCH_DEADLINE_S=repr(deadline))
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, timeout=deadline, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            last_err = f"mode={mode} exceeded {deadline:.0f}s"
            continue
        for line in reversed((p.stdout or "").strip().splitlines()):
            try:
                parsed = json.loads(line)
            except Exception:
                continue
            if not (isinstance(parsed, dict) and "metric" in parsed):
                continue  # stray JSON-ish stdout line, not the result
            results[mode] = line
            break
        else:
            tail = (p.stderr or "").strip().splitlines()[-3:]
            last_err = f"mode={mode} rc={p.returncode}: " + " | ".join(tail)
    # mixed-topology (distinct_geometries in the strong sense) is the
    # headline when it finished; then the geometry-DoE; flat is the bank
    for mode in ("mixed", "geom", "flat"):
        if mode in results:
            # leftover budget buys the fabric scaling block (1/2/4
            # workers over the same sweep) in the headline breakdown
            print(_attach_fabric(results[mode], budget, t_start))
            return

    # last resort: the accelerator backend may be unreachable (observed:
    # axon tunnel down -> 'UNAVAILABLE: TPU backend setup/compile
    # error' at init).  A CPU number explicitly labelled as such beats
    # a third consecutive value=0 round; device_kind in the breakdown
    # plus the note keep it honest.
    if not config.get("BENCH_PLATFORM"):
        remaining = budget - (time.perf_counter() - t_start) - 10.0
        env = dict(os.environ, RAFT_TPU_BENCH_MODE="flat",
                   RAFT_TPU_BENCH_PLATFORM="cpu",
                   RAFT_TPU_BENCH_BASE_EVAL_S=repr(base_eval_s),
                   RAFT_TPU_BENCH_BASE_HOST=base_host,
                   RAFT_TPU_BENCH_DEADLINE_S=repr(max(60.0, remaining)))
        try:
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                timeout=max(60.0, remaining), capture_output=True, text=True)
            for line in reversed((p.stdout or "").strip().splitlines()):
                try:
                    parsed = json.loads(line)
                except Exception:
                    continue
                if isinstance(parsed, dict) and "metric" in parsed:
                    parsed["note"] = f"{last_err}; CPU-host fallback"
                    print(json.dumps(parsed))
                    return
        except subprocess.TimeoutExpired:
            pass
    print(json.dumps({
        "metric": "design-evals/sec/chip (VolturnUS-S, 100w x 12 cases)",
        "value": 0.0, "unit": "design-evals/s", "vs_baseline": 0.0,
        "error": last_err,
    }))


_SRC_FP = [None]


def _src_fingerprint():
    """Content hash of THIS file: the bank's code fingerprint covers
    ``raft_tpu/**`` only, but the bench's traced wrappers (eval_case,
    the case table plumbing) live here — an edit to bench.py must miss
    the bank, never load pre-edit physics."""
    if _SRC_FP[0] is None:
        from raft_tpu.aot.bank import file_fingerprint

        _SRC_FP[0] = file_fingerprint(os.path.abspath(__file__))
    return _SRC_FP[0]


def _aot_memo(evaluate):
    from raft_tpu.aot import bank
    from raft_tpu.parallel.sweep import _flags_key

    return (_flags_key(), ("program", bank.program_key(evaluate)),
            ("cases", bank.content_fingerprint(CASES)),
            ("src", _src_fingerprint()))


def _aot_compile(fn, args, kind, evaluate=None):
    """AOT-compile a bench program through the program bank
    (:mod:`raft_tpu.aot.bank`): with ``RAFT_TPU_AOT=load`` a warmed
    bank answers in deserialize time instead of the 33s trace+compile
    the r05 breakdown measured, and a miss exports the program for the
    next round.  The memo key carries the evaluator's design-content
    stamp, the case table and this file's source hash (bench programs
    bake all three in); an unstamped evaluator compiles outside the
    bank.  Returns ``(compiled, loaded, seconds)``."""
    from raft_tpu.aot import bank

    pk = bank.program_key(evaluate)
    return bank.compile_or_load(fn, args, kind, _aot_memo(evaluate),
                                bankable=pk is not None)


def _aot_banked(kind, evaluate, args):
    """True when the bank already holds this program (metadata-only
    check — no deserialization): lets the breakdown heuristics tell a
    free bank load from a 25-33s compile they may not have budget for."""
    from raft_tpu.aot import bank

    if bank.program_key(evaluate) is None or bank.mode() == "off":
        return False
    return bank.peek(kind, _aot_memo(evaluate), args) is not None


def _timed_reps(compiled, args, reps):
    """Steady-state timing under the recompilation sentinel: warm up
    first (first-dispatch helper compiles are not steady state), then
    average ``reps`` executions, counting backend compiles inside them
    (any nonzero count means the headline number includes XLA work)."""
    import jax

    from raft_tpu.analysis.recompile import count_compilations

    jax.block_until_ready(compiled(*args))
    with count_compilations() as clog:
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(compiled(*args))
        dt = (time.perf_counter() - t0) / reps
    return dt, clog.count


def _deadline_remaining(t_start):
    """Seconds left before the parent kills this attempt (None if run
    standalone)."""
    d = config.get("BENCH_DEADLINE_S")
    if d is None:
        return None
    return d - (time.perf_counter() - t_start)


def _program_cost(kind_str, evaluate, args, compile_est):
    """Expected wall cost of materializing one more bank-fronted
    program: ~0 when the bank already holds it, else the full compile
    estimate.  ``compile_est`` must be the REAL compile scale even
    when the headline was a bank load (a 0.1s load time as the
    estimate would green-light a 30s compile the deadline cannot
    absorb — the pre-bank failure mode in reverse)."""
    if _aot_banked(kind_str, evaluate, args):
        return 0.0
    return max(compile_est, 5.0)


def _stage_times(jit_builder, args, reps, compile_est, dt, t_start,
                 kind="bench", evaluate=None):
    """Stage attribution by dead-code elimination: jitting a function
    that returns only (a scalar reduction of) an intermediate lets XLA
    prune everything downstream of it, so the timing isolates the
    pipeline prefix without output-transfer skew.  On by default
    (RAFT_TPU_BENCH_BREAKDOWN=0 to skip), but each stage variant is a
    separate program, so it only runs when the attempt deadline leaves
    room for the ones the bank does NOT already hold (banked stages
    cost a deserialize, not a compile).
    ``jit_builder(key)`` -> jitted pruned pipeline.

    Returns (t_stat, t_dyn): raw per-executable times of the
    statics+equilibrium prefix and the through-drag-solve prefix, or
    (None, None) when skipped/failed."""
    import jax

    remaining = _deadline_remaining(t_start)
    est = sum(_program_cost(f"{kind}:stage:{key}", evaluate, args,
                            compile_est) for key in ("X0", "Z"))
    room = remaining is None or remaining > 1.2 * est + 8 * dt + 2.0
    if not config.get("BENCH_BREAKDOWN") or not room:
        return None, None
    try:
        def timed(key):
            f, _, _ = _aot_compile(jit_builder(key), args,
                                   f"{kind}:stage:{key}", evaluate=evaluate)
            jax.block_until_ready(f(*args))
            t0 = time.perf_counter()
            for _ in range(reps):
                jax.block_until_ready(f(*args))
            return (time.perf_counter() - t0) / reps

        t_stat = timed("X0")  # geometry+statics+aero+equilib.
        t_dyn = timed("Z")    # + excitation + drag-lin solve
        return t_stat, t_dyn
    except Exception:
        return None, None


def _pruned_probe(jit_raw_builder, key, args, compile_est, t_dyn, t_start,
                  kind="bench", evaluate=None):
    """Fetch one diagnostic output across the batch via a pipeline
    pruned to ``key`` (XLA dead-code-eliminates everything downstream).
    One extra program per probe (bank-fronted), so only taken when the
    attempt deadline leaves room for it; None when skipped/failed."""
    remaining = _deadline_remaining(t_start)
    cost = _program_cost(f"{kind}:probe:{key}", evaluate, args, compile_est)
    if t_dyn is None or (remaining is not None
                         and remaining < 1.3 * cost + 4 * t_dyn + 1.0):
        return None
    try:
        f, _, _ = _aot_compile(jit_raw_builder(key), args,
                               f"{kind}:probe:{key}", evaluate=evaluate)
        return np.asarray(f(*args))
    except Exception:
        return None


def _drag_iters(jit_raw_builder, args, compile_est, t_dyn, t_start,
                kind="bench", evaluate=None):
    """Realized drag-linearisation iteration counts across the batch
    (the fixed point reports how many masked scan trips did real work)."""
    return _pruned_probe(jit_raw_builder, "n_iter_drag", args,
                         compile_est, t_dyn, t_start, kind=kind,
                         evaluate=evaluate)


def _flagged_fraction(jit_raw_builder, args, compile_est, t_dyn, t_start,
                      kind="bench", evaluate=None):
    """Fraction of evaluated cases whose solver-health status word
    carries SEVERE bits (unconverged statics/drag, ill-conditioned Z,
    non-finite output — see raft_tpu.utils.health)."""
    from raft_tpu.utils import health

    st = _pruned_probe(jit_raw_builder, "status", args,
                       compile_est, t_dyn, t_start, kind=kind,
                       evaluate=evaluate)
    if st is None:
        return None
    return float(((st & np.int32(health.SEVERE)) != 0).mean())


def _finish_breakdown(breakdown, t_compile, dt, t_stat, t_dyn,
                      base_per_sec, batch_designs, distinct_geometries,
                      iters=None, ndof=6, recompiles=None, flagged=None,
                      cold_start_s=None):
    """Shared breakdown block.  Stage prefixes are reported as RAW
    times of their own executables (differences between separately
    compiled programs can be negative and misattribute time); derived
    splits are clamped at zero."""
    from raft_tpu.models.dynamics import fixed_point_mode
    from raft_tpu.obs import metrics as _metrics
    from raft_tpu.ops.linsolve import solver_path
    from raft_tpu.utils.dtypes import policy_name

    drag_s = (max(t_dyn - t_stat, 0.0) if t_dyn and t_stat else None)
    it_mean = float(iters.mean()) if iters is not None else None
    breakdown.update(
        solver_path=solver_path(ndof),
        fixed_point=fixed_point_mode(),
        dtype_policy=policy_name() or "derived",
        drag_iterations_mean=(round(it_mean, 2) if it_mean is not None
                              else None),
        drag_iterations_max=int(iters.max()) if iters is not None else None,
        per_drag_iteration_s=(round(drag_s / it_mean, 5)
                              if drag_s is not None and it_mean else None),
        # recompilation sentinel (raft_tpu.analysis.recompile): backend
        # compiles observed during the steady-state timing reps — any
        # nonzero value means the headline number includes XLA work
        steady_state_recompiles=recompiles,
        # solver-health probe: severe-bit fraction across the batch
        flagged_fraction=(round(flagged, 4) if flagged is not None
                          else None),
    )
    # cold-start attribution (the r05 finding: compile_s 33.65 vs
    # full_pipeline_s 2.21): compile_s is the headline program's
    # lower+compile (or bank-load) time; cold_start_s is wall time from
    # attempt start to the first completed evaluation — the number a
    # serving process actually waits.  programs_loaded/compiled split
    # the process's AOT-layer programs into bank hits vs fresh
    # compiles: a warmed round reads "N loaded, 0 compiled".
    aot_counters = _metrics.snapshot()["counters"]
    breakdown.update(
        compile_s=round(t_compile, 2),
        cold_start_s=(round(cold_start_s, 2) if cold_start_s is not None
                      else None),
        programs_loaded=aot_counters.get("aot_programs_loaded", 0),
        programs_compiled=aot_counters.get("aot_programs_compiled", 0),
        aot_mode=config.get("AOT"),
        full_pipeline_s=round(dt, 4),
        prefix_statics_equilibrium_s=round(t_stat, 4) if t_stat else None,
        prefix_through_drag_solve_s=round(t_dyn, 4) if t_dyn else None,
        drag_solve_minus_statics_s=(round(max(t_dyn - t_stat, 0.0), 4)
                                    if t_dyn and t_stat else None),
        psd_tail_s=round(max(dt - t_dyn, 0.0), 4) if t_dyn else None,
        baseline_design_eval_s=round(1.0 / base_per_sec, 2),
        baseline_host=config.get("BENCH_BASE_HOST") or None,
        batch_designs=batch_designs,
        distinct_geometries=distinct_geometries,
    )
    breakdown["telemetry"] = _telemetry_block()
    return breakdown


def _telemetry_block():
    """Fold the obs metrics snapshot into the bench breakdown: total
    XLA compiles (recompile-sentinel feed), sweep-runtime reliability
    counters, and the heartbeat gauges' high watermarks — so the
    BENCH_rNN.json artifact carries the telemetry trajectory alongside
    the timings."""
    from raft_tpu.obs import heartbeat as hb_mod
    from raft_tpu.obs import metrics

    if config.get("HEARTBEAT_S"):
        # one synchronous sample so the block reflects the END of the
        # run even when the bench finished inside the first interval
        try:
            hb_mod.Heartbeat(0.0).beat()
        except Exception:
            pass
    snap = metrics.snapshot()
    c, g = snap["counters"], snap["gauges"]

    def gmax(name):
        v = (g.get(name) or {}).get("max")
        return int(v) if v is not None else None

    return dict(
        xla_compiles=c.get("xla_compiles", 0),
        shard_retries=c.get("shard_retries", 0),
        shard_oom_splits=c.get("shard_oom_splits", 0),
        escalation_rungs=c.get("escalation_rungs", 0),
        escalations_resolved=c.get("escalations_resolved", 0),
        rows_quarantined=c.get("rows_quarantined", 0),
        cases_flagged=c.get("cases_flagged", 0),
        heartbeat_max_device_bytes=gmax("device_bytes_in_use"),
        heartbeat_max_live_arrays=gmax("live_arrays"),
    )


def run_mode(mode):
    t_start = time.perf_counter()
    if mode == "fabric":
        # no _enable_compile_cache here: the fabric coordinator never
        # touches jax — compile caching happens inside the workers
        run_fabric_bench(t_start)
    elif mode == "serve":
        run_serve_bench(t_start)
    else:
        _enable_compile_cache()
        from raft_tpu.obs.heartbeat import maybe_heartbeat

        with maybe_heartbeat():
            if mode == "flat":
                run_flat(t_start)
            elif mode == "mixed":
                run_mixed(t_start)
            else:
                _run_geom(t_start)
    # longitudinal perf trajectory (RAFT_TPU_RUNS_DIR): a COMPLETED
    # bench mode child appends its metrics-registry picture — compile
    # counts, stage/waste histograms, cost ledger — as a run record.
    # Reached only on success: a crashed mode must not enter the
    # regression-gating store looking like a complete run (the sweep
    # and serve recorders have the same completion semantics)
    from raft_tpu.obs import runs as obs_runs

    obs_runs.maybe_record("bench", label=mode,
                          wall_s=time.perf_counter() - t_start)


def fabric_bench_cases(n, seed=17):
    """The bench fabric sweep's case batch: ``n`` DISTINCT designs
    (per-row Cd_scale around the bundled spar) under varied sea states
    — pure numpy so the coordinator never builds a model."""
    rng = np.random.default_rng(seed)
    n = int(n)
    return {
        "Hs": rng.uniform(2.0, 8.0, n),
        "Tp": rng.uniform(6.0, 14.0, n),
        "beta": rng.uniform(-0.5, 0.5, n),
        "Cd_scale": rng.uniform(0.9, 1.1, n),
    }


def fabric_bench_entry(out_keys=("PSD", "X0", "status"), n=1024, seed=17,
                       **_):
    """Fabric worker entry for the bench scaling block: the bundled
    spar's DESIGN evaluator (per-row drag-coefficient designs) through
    the standard full_compute shard path.  Runs without
    /root/reference."""
    import raft_tpu
    from raft_tpu import api
    from raft_tpu.parallel.sweep import full_compute

    design = os.path.join(os.path.dirname(os.path.abspath(
        raft_tpu.__file__)), "designs", "spar_demo.yaml")
    model = raft_tpu.Model(design)
    evaluate = api.make_design_evaluator(model)
    return {"compute": full_compute(evaluate, out_keys=tuple(out_keys)),
            "cases": fabric_bench_cases(n, seed)}


def run_fabric_bench(t_start=None):
    """Measure the elastic fabric's design-evals/s scaling: the SAME
    ≥256-design sweep at 1/2/4 local workers, each config a fresh
    ledger + fresh worker subprocesses (ROADMAP item 2 acceptance).

    Rates are reported over the sweep WINDOW (first shard start to
    last shard completion, from the ledger's done records) so worker
    cold start is visible separately (``wall_s``) instead of polluting
    the throughput ratio.  Prints one JSON line ``{"fabric": block}``
    that the parent bench folds into the headline breakdown."""
    import shutil
    import tempfile

    from raft_tpu.parallel import fabric

    t_start = t_start if t_start is not None else time.perf_counter()
    n = int(config.get("BENCH_FABRIC_N"))
    shard = int(config.get("BENCH_FABRIC_SHARD"))
    counts = [int(w) for w in
              str(config.get("BENCH_FABRIC_WORKERS")).split(",")
              if w.strip()]
    deadline = config.get("BENCH_DEADLINE_S")
    out_keys = ("PSD", "X0", "status")
    cases = fabric_bench_cases(n)
    n_shards = (n + shard - 1) // shard
    base = tempfile.mkdtemp(prefix="raft_fabric_bench_")
    runs = {}
    note = None
    try:
        # warmup pass (discarded): exports the shard program into the
        # AOT bank / XLA disk cache so every measured config is equally
        # warm — otherwise the 1-worker run eats the one-time compile
        # and the multi-worker speedup is a cold-start artifact
        fabric.run_fabric(
            os.path.join(base, "warm"), workers=1,
            entry="bench:fabric_bench_entry",
            entry_kwargs={"n": 2 * shard, "out_keys": list(out_keys)},
            cases=fabric_bench_cases(2 * shard), out_keys=out_keys,
            shard_size=shard,
            worker_env={"RAFT_TPU_AOT":
                        config.raw("AOT") or "load"})
        shutil.rmtree(os.path.join(base, "warm"), ignore_errors=True)
        for w in counts:
            if deadline and runs and \
                    time.perf_counter() - t_start > 0.7 * deadline:
                note = (f"budget exhausted after "
                        f"{sorted(runs)} worker configs")
                break
            out_dir = os.path.join(base, f"w{w}")
            t0 = time.perf_counter()
            fabric.run_fabric(
                out_dir, workers=w, entry="bench:fabric_bench_entry",
                entry_kwargs={"n": n, "out_keys": list(out_keys)},
                cases=cases, out_keys=out_keys, shard_size=shard,
                worker_env={"RAFT_TPU_AOT":
                            config.raw("AOT") or "load"})
            wall = time.perf_counter() - t0
            ledger = fabric.Ledger(out_dir, n_shards)
            recs = [ledger.read_done(s) for s in range(n_shards)]
            starts = [r["t"] - r.get("wall_s", 0.0) for r in recs if r]
            ends = [r["t"] for r in recs if r]
            window = max(1e-9, max(ends) - min(starts))
            states = ledger.worker_states()

            def csum(key):
                return sum((st.get("counters") or {}).get(key, 0)
                           for st in states.values())

            from raft_tpu.aot import bank

            pooled = ledger.pooled_walls()
            runs[str(w)] = dict(
                wall_s=round(wall, 2),
                window_s=round(window, 2),
                evals_per_s=round(n / window, 3),
                evals_per_s_incl_startup=round(n / wall, 3),
                shard_wall_p50_s=(round(pooled.percentile(0.50), 3)
                                  if pooled.count else None),
                shard_wall_p95_s=(round(pooled.percentile(0.95), 3)
                                  if pooled.count else None),
                steals=csum("shards_stolen"),
                shard_retries=csum("shard_retries"),
                programs_loaded=sum(st.get("programs_loaded") or 0
                                    for st in states.values()),
                programs_compiled=sum(st.get("programs_compiled") or 0
                                      for st in states.values()),
                # fleet-merged device-cost ledger: per-program flops and
                # the achieved GFLOP/s across this config's workers
                programs=bank.merge_ledgers(
                    [st.get("programs") for st in states.values()]),
            )
            shutil.rmtree(out_dir, ignore_errors=True)
    finally:
        shutil.rmtree(base, ignore_errors=True)

    block = dict(
        workload=f"spar_demo design sweep: {n} distinct Cd_scale "
                 f"designs, shard {shard}",
        n_designs=n, shard_size=shard, host_cores=os.cpu_count(),
        workers=runs,
    )
    r1 = runs.get("1")
    if r1:
        block["speedup_vs_1"] = {
            w: round(r["evals_per_s"] / r1["evals_per_s"], 2)
            for w, r in runs.items()}
        block["scaling_efficiency"] = {
            w: round(r["evals_per_s"] / (int(w) * r1["evals_per_s"]), 2)
            for w, r in runs.items()}
    cores = os.cpu_count() or 1
    if counts and cores < max(counts):
        note = ((note + "; ") if note else "") + (
            f"host exposes {cores} physical core(s): XLA-bound work "
            f"cannot exceed ~1x process-level scaling here; the "
            f"fabric's speedup needs >=1 core (or device) per worker")
    if note:
        block["note"] = note
    print(json.dumps({"fabric": block}))
    return block


def serve_bench_pool(n, seed=23):
    """The load test's case pool: ``n`` distinct (Hs, Tp, beta) corners
    the synthetic clients draw from with repetition — duplicate corners
    are the point (they exercise the result cache and the in-flight
    coalescer, like real sweep/optimizer traffic)."""
    rng = np.random.default_rng(seed)
    return [(round(h, 3), round(t, 3), round(b, 3)) for h, t, b in zip(
        rng.uniform(2.0, 8.0, n), rng.uniform(6.0, 14.0, n),
        rng.uniform(-0.5, 0.5, n))]


def run_serve_bench(t_start=None):
    """The evaluation-service load test (``RAFT_TPU_BENCH_MODE=serve``,
    ROADMAP item 3 acceptance): warm the AOT bank with the ``serve``
    kind, start a server subprocess under the STRICT serving config
    (``RAFT_TPU_AOT=require`` + ``RAFT_TPU_COMPILE_BUDGET=0`` — any
    real XLA compile after warmup raises inside the server), then hit
    it with hundreds of concurrent synthetic clients.  Reports
    p50/p95 latency, evals/s, batch occupancy and cache hit rate, plus
    a per-request parity block against solo evaluator calls and a
    SIGTERM drain check.  Runs under x64 end to end so the parity
    gates are float64-meaningful (x64 is part of the bank key — the
    warmup, the server and the solo oracle all pin it).

    Prints one JSON result line; the harness persists it as
    BENCH_r07.json."""
    import shutil
    import signal as _signal
    import subprocess
    import sys
    import tempfile
    import threading

    t_start = t_start if t_start is not None else time.perf_counter()
    n_clients = int(config.get("BENCH_SERVE_CLIENTS"))
    n_reqs = int(config.get("BENCH_SERVE_REQS"))
    pool = serve_bench_pool(int(config.get("BENCH_SERVE_POOL")))
    design = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "raft_tpu", "designs", "spar_demo.yaml")
    base = tempfile.mkdtemp(prefix="raft_serve_bench_")
    aot_dir = os.path.join(base, "aot_bank")
    cache_dir = os.path.join(base, "jax_cache")
    metrics_path = os.path.join(base, "serve_metrics.prom")
    common = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "RAFT_TPU_AOT_DIR": aot_dir,
        "RAFT_TPU_CACHE_DIR": cache_dir,
        "RAFT_TPU_SERVE_MAX_BATCH": "64",
        "RAFT_TPU_SERVE_TICK_MS": "20",
    }
    block = {"workload": f"spar_demo single-case serving: {n_clients} "
                         f"concurrent clients x {n_reqs} requests, "
                         f"{len(pool)}-case pool",
             "host_cores": os.cpu_count()}
    proc = None
    stderr_f = None
    try:
        # ---- 1. fill the bank: the serve warmup kind at the ladder
        t0 = time.perf_counter()
        p = subprocess.run(
            [sys.executable, "-m", "raft_tpu.aot", "warmup",
             "--kinds", "serve", "--design", design, "--x64"],
            env=dict(os.environ, **common, RAFT_TPU_AOT="load"),
            capture_output=True, text=True, timeout=1800)
        if p.returncode != 0:
            raise RuntimeError(f"serve warmup failed: "
                               f"{(p.stderr or '')[-800:]}")
        block["warmup_s"] = round(time.perf_counter() - t0, 2)
        block["warmup_programs"] = sum(
            1 for line in p.stdout.splitlines()
            if line.startswith("warmup serve"))

        # ---- 2. the server, strict mode: a compile-free cold start is
        # enforced, not hoped for
        stderr_f = open(os.path.join(base, "server_stderr.txt"), "w")
        t0 = time.perf_counter()
        proc = subprocess.Popen(
            [sys.executable, "-m", "raft_tpu.serve",
             "--designs", f"spar={design}", "--port", "0", "--x64"],
            env=dict(os.environ, **common,
                     RAFT_TPU_AOT="require", RAFT_TPU_AOT_MISS="error",
                     RAFT_TPU_COMPILE_BUDGET="0",
                     RAFT_TPU_METRICS=metrics_path),
            stdout=subprocess.PIPE, stderr=stderr_f, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        port = None
        for line in proc.stdout:
            if "serving" in line and "http://" in line:
                port = int(line.split("http://", 1)[1].split()[0]
                           .rsplit(":", 1)[1])
                break
        if port is None:
            raise RuntimeError("server never became ready (see "
                               f"{base}/server_stderr.txt)")
        block["cold_start_s"] = round(time.perf_counter() - t0, 2)

        # ---- 3. the load: N concurrent keep-alive clients drawing
        # duplicate corners from the shared pool
        from raft_tpu.serve.client import ServeClient

        latencies, codes = [], []
        sample: dict[int, dict] = {}
        lock = threading.Lock()

        def client(ci):
            rng = np.random.default_rng(1000 + ci)
            c = ServeClient("127.0.0.1", port, client_id=f"bench{ci}",
                            timeout=600)
            try:
                for _ in range(n_reqs):
                    pi = int(rng.integers(len(pool)))
                    h, t, b = pool[pi]
                    t0 = time.perf_counter()
                    code, body = c.evaluate("spar", h, t, b)
                    dt = time.perf_counter() - t0
                    with lock:
                        latencies.append(dt)
                        codes.append(code)
                        if code == 200 and pi not in sample:
                            sample[pi] = body
            finally:
                c.close()

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        load_wall = time.perf_counter() - t0
        if not latencies:
            # every client died before recording a response (server
            # crash mid-load): fail with the server's own words, not an
            # IndexError — base/ is cleaned up in the finally
            with open(os.path.join(base, "server_stderr.txt")) as f:
                tail = f.read()[-1500:]
            raise RuntimeError(
                f"serve load phase recorded no responses; server stderr "
                f"tail:\n{tail}")
        lat = np.sort(np.asarray(latencies))
        n_ok = sum(1 for c in codes if c == 200)
        p50 = float(lat[len(lat) // 2])
        p95 = float(lat[int(len(lat) * 0.95)])
        block["load"] = dict(
            clients=n_clients, requests=len(codes), ok=n_ok,
            non_200=sorted({c for c in codes if c != 200}),
            wall_s=round(load_wall, 2),
            evals_per_s=round(n_ok / load_wall, 2),
            p50_ms=round(p50 * 1e3, 1),
            p95_ms=round(p95 * 1e3, 1),
            # the tail-attribution acceptance ratio (ROADMAP item 5b):
            # BENCH_r07's fixed 20ms tick measured 4.5x
            p95_over_p50=round(p95 / p50, 2) if p50 > 0 else None,
            max_ms=round(float(lat[-1]) * 1e3, 1),
        )

        # ---- 3b. light load: sequential UNIQUE requests against the
        # now-idle server.  Each dispatches solo, so the latency is
        # ~(adaptive tick floor + dispatch + solve); the fixed window
        # paid ~U(0, SERVE_TICK_MS) extra here — the light-load
        # acceptance number of the adaptive tick
        from raft_tpu.serve.client import ServeClient as _SC

        lc = _SC("127.0.0.1", port, client_id="bench-light", timeout=600)
        light = []
        try:
            for i in range(12):
                t0 = time.perf_counter()
                code, _body = lc.evaluate("spar", 3.1 + 0.01 * i,
                                          8.3 + 0.05 * i, 0.21)
                if code == 200:
                    light.append(time.perf_counter() - t0)
                time.sleep(0.05)   # let the queue drain to empty
        finally:
            lc.close()
        if light:
            ls = np.sort(np.asarray(light))
            block["light_load"] = dict(
                requests=len(light),
                p50_ms=round(float(ls[len(ls) // 2]) * 1e3, 1),
                max_ms=round(float(ls[-1]) * 1e3, 1),
            )

        # ---- 4. server-side provenance: 0 real compiles, occupancy,
        # cache hit rate
        c = ServeClient("127.0.0.1", port)
        _, health = c.healthz()
        occ = health.get("batch_occupancy") or {}
        win = health.get("window") or {}
        block["server"] = dict(
            programs_loaded=health.get("aot_programs_loaded"),
            programs_compiled=health.get("aot_programs_compiled"),
            # the adaptive-tick + cost-ladder configuration actually
            # serving (the ladder may be a pruned subset of the warmed
            # candidates under RAFT_TPU_SERVE_LADDER=cost)
            tick_mode=health.get("tick_mode"),
            tick_ms=health.get("tick_ms"),
            tick_floor_ms=health.get("tick_floor_ms"),
            batch_sizes=health.get("batch_sizes"),
            xla_real_compiles=health.get("xla_real_compiles"),
            dispatches=health.get("serve_dispatches"),
            rows_dispatched=health.get("serve_rows_dispatched"),
            coalesced_requests=health.get("serve_coalesced"),
            batch_occupancy_mean=occ.get("mean"),
            batch_occupancy_p95=occ.get("p95"),
            cache=health.get("cache"),
            # the server's own sliding-window latency view (last
            # RAFT_TPU_SERVE_WINDOW_S seconds) next to the client-side
            # lifetime percentiles above, plus SLO breach accounting
            window_p50_ms=(round(win["p50"] * 1e3, 1)
                           if win.get("p50") is not None else None),
            window_p95_ms=(round(win["p95"] * 1e3, 1)
                           if win.get("p95") is not None else None),
            window_rate_per_s=win.get("rate_per_s"),
            slo=health.get("slo"),
            # tail attribution: per-stage latency histograms of every
            # dispatched request — where the p95-vs-p50 gap lives
            # (queue-wait / tick-wait / dispatch / solve / post)
            request_stages=health.get("request_stages"),
            # device-cost ledger: per-program flops / dispatches /
            # achieved GFLOP/s from the warmed bank's sidecars
            cost_ledger=health.get("cost_ledger"),
        )
        c.close()

        # ---- 5. parity: the served rows against solo evaluator calls
        # in THIS process, through the same warmed bank
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_enable_x64", True)
        for k, v in common.items():
            os.environ[k] = v
        # parity must read the SAME warmed bank the server used
        os.environ[config.env_name("AOT")] = "load"
        _enable_compile_cache()
        import raft_tpu
        from raft_tpu import api
        from raft_tpu.parallel.sweep import make_mesh
        from raft_tpu.serve import engine

        model = raft_tpu.Model(design)
        entry = engine.DesignEntry("spar", model)
        mesh = make_mesh(1)
        solo_jit = jax.jit(api.make_case_evaluator(model))
        checked = 0
        status_equal = x0_bits = engine_bits = True
        psd_delta = solo_delta = 0.0
        for pi, body in sorted(sample.items())[:12]:
            h, t, b = pool[pi]
            got = {k: np.asarray(v) for k, v in body["outputs"].items()}
            se = engine.dispatch([entry], [h], [t], [b], mesh=mesh,
                                 padded=1)
            so = solo_jit(h, t, b)
            status_equal &= (int(np.asarray(so["status"]))
                             == int(body["status"]))
            x0_bits &= np.array_equal(got["X0"],
                                      np.asarray(se["X0"][0]))
            engine_bits &= all(np.array_equal(got[k],
                                              np.asarray(se[k][0]))
                               for k in ("PSD", "X0", "status"))
            psd_delta = max(psd_delta, float(np.max(np.abs(
                got["PSD"] - np.asarray(se["PSD"][0])))))
            solo_delta = max(solo_delta, max(
                float(np.max(np.abs(got[k] - np.asarray(so[k]))))
                for k in ("PSD", "X0")))
            checked += 1
        block["parity"] = dict(
            cases_checked=checked,
            status_bit_equal=bool(status_equal),
            x0_bit_identical_vs_solo_dispatch=bool(x0_bits),
            all_keys_bit_identical_vs_solo_dispatch=bool(engine_bits),
            max_abs_delta_vs_solo_dispatch=psd_delta,
            max_abs_delta_vs_solo_case_evaluator=solo_delta,
        )

        # ---- 6. SIGTERM drain under fire: every accepted request must
        # get its response
        drain_codes, drain_errors = [], []

        def drain_client(ci):
            dc = ServeClient("127.0.0.1", port, client_id=f"drain{ci}",
                             timeout=600)
            try:
                h, t, b = pool[ci % len(pool)]
                code, body = dc.evaluate("spar", h, t, b + 0.001 * ci)
                # a 200 without its outputs payload IS a dropped
                # response; rejects (503) legitimately carry none
                ok_payload = (code != 200) or (
                    isinstance(body, dict) and "outputs" in body)
                drain_codes.append((code, ok_payload))
            except (ConnectionError, OSError):
                drain_codes.append(("refused", True))
            except Exception as e:  # noqa: BLE001
                drain_errors.append(repr(e))
            finally:
                dc.close()

        threads = [threading.Thread(target=drain_client, args=(i,))
                   for i in range(32)]
        for th in threads:
            th.start()
        time.sleep(0.05)
        proc.send_signal(_signal.SIGTERM)
        for th in threads:
            th.join(timeout=600)
        rc = proc.wait(timeout=300)
        accepted = [c for c, _ in drain_codes if c == 200]
        bad_payloads = sum(1 for _, okp in drain_codes if not okp)
        block["drain"] = dict(
            burst=32, accepted=len(accepted),
            rejected_or_refused=len(drain_codes) - len(accepted),
            dropped_responses=len(drain_errors) + bad_payloads,
            server_rc=rc,
            metrics_flushed=os.path.exists(metrics_path),
        )
        ok = (rc == 0 and block["drain"]["dropped_responses"] == 0
              and block["server"]["xla_real_compiles"] == 0
              and status_equal and n_ok == len(codes))
        result = {
            "metric": f"serve evals/s (spar_demo, {n_clients} concurrent "
                      f"clients, warmed AOT bank, x64)",
            "value": block["load"]["evals_per_s"],
            "unit": "evals/s",
            "ok": bool(ok),
            "breakdown": {"serve": block},
        }
        print(json.dumps(result))
        return result
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        if stderr_f is not None:
            stderr_f.close()
        shutil.rmtree(base, ignore_errors=True)


def _attach_fabric(line, budget, t_start):
    """Run the fabric scaling mode in a subprocess with the leftover
    budget and fold its block into the headline JSON line."""
    import subprocess
    import sys

    if not config.get("BENCH_FABRIC"):
        return line
    remaining = budget - (time.perf_counter() - t_start) - 10.0
    if remaining < 120.0:
        return line
    env = dict(os.environ, RAFT_TPU_BENCH_MODE="fabric",
               RAFT_TPU_BENCH_DEADLINE_S=repr(remaining))
    try:
        p = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            timeout=remaining, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return line
    block = None
    for out_line in reversed((p.stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(out_line)
        except Exception:
            continue
        if isinstance(parsed, dict) and "fabric" in parsed:
            block = parsed["fabric"]
            break
    if block is None:
        return line
    result = json.loads(line)
    result.setdefault("breakdown", {})["fabric"] = block
    return json.dumps(result)


def run_mixed(t_start):
    """Mixed-TOPOLOGY headline: the bundled spar/semi/MHK design trio
    (three genuinely different member layouts, node counts and mooring
    line counts) swept in ONE batch through the shape-bucketed
    heterogeneous dispatcher (raft_tpu.structure.bucketing +
    parallel.sweep.sweep_heterogeneous).  ``distinct_geometries`` is
    finally True in the strong sense — distinct *topologies*, not
    coefficient scales on one layout — and the breakdown reports the
    bucket count and the measured padding waste the static program
    shapes cost.  Uses the bundled designs, so this mode runs without
    the /root/reference checkout."""
    import jax

    import raft_tpu
    from raft_tpu.analysis.recompile import count_compilations
    from raft_tpu.parallel.sweep import make_mesh, sweep_heterogeneous
    from raft_tpu.structure import bucketing

    designs_dir = os.path.join(
        os.path.dirname(os.path.abspath(raft_tpu.__file__)), "designs")
    models = [raft_tpu.Model(os.path.join(designs_dir, f)) for f in
              ("spar_demo.yaml", "semi_demo.yaml", "mhk_demo.yaml")]
    # signatures only: the sweep packs each design once internally —
    # a second pack_design pass here would duplicate the packing work
    # inside the deadline-bounded attempt.  The lazy statics builds ARE
    # forced now (they run eager host-side jax ops) so the cold-start
    # window below counts DISPATCH compiles, not build ops.
    sigs = [bucketing.bucket_signature(m) for m in models]
    for m in models:
        m.statics()
    n_buckets = len(set(sigs))

    B = config.get("BENCH_DESIGNS")
    reps = config.get("BENCH_REPS")
    arr = np.array(CASES)
    models_row = [models[i % len(models)] for i in range(B)]
    Hs = arr[np.arange(B) % len(CASES), 3]
    Tp = arr[np.arange(B) % len(CASES), 4]
    beta = np.deg2rad(arr[np.arange(B) % len(CASES), 5])
    mesh = make_mesh()
    out_keys = ("PSD", "X0", "status")

    t0 = time.perf_counter()
    with count_compilations() as clog_cold:
        out = sweep_heterogeneous(models_row, Hs, Tp, beta, mesh=mesh,
                                  out_keys=out_keys)
    t_compile = time.perf_counter() - t0
    cold_start = time.perf_counter() - t_start

    with count_compilations() as clog:
        t0 = time.perf_counter()
        for _ in range(reps):
            out = sweep_heterogeneous(models_row, Hs, Tp, beta, mesh=mesh,
                                      out_keys=out_keys)
        dt = (time.perf_counter() - t0) / reps
    evals_per_sec = B / dt

    from raft_tpu.utils import health

    flagged = float(((np.asarray(out["status"])
                      & np.int32(health.SEVERE)) != 0).mean())
    # serial-twin baseline: DESIGN-eval rate feeds the breakdown (so
    # baseline_design_eval_s stays comparable across bench modes), the
    # per-CASE rate feeds this mode's case-evals/s ratio.  The numpy
    # twin needs a buildable model; without it fall back to a unit
    # ratio with a note.
    note = None
    try:
        base_design_per_sec = _numpy_baseline(models[0])
        base_per_sec = base_design_per_sec * len(CASES)
    except Exception as e:
        base_design_per_sec = base_per_sec = evals_per_sec
        note = f"numpy baseline unavailable ({type(e).__name__}); ratio=1"
    breakdown = dict(device_kind=jax.devices()[0].device_kind)
    breakdown = _finish_breakdown(
        breakdown, t_compile, dt, None, None, base_design_per_sec, B, True,
        ndof=6, recompiles=clog.count, flagged=flagged,
        cold_start_s=cold_start)
    # padding waste over the DISPATCHED rows, from strip counts + the
    # bucket signatures (no second pack_design pass needed)
    s_real = sum(m.hydro[0].strips.S for m in models_row)
    s_pad = sum(bucketing.signature_meta(
        sigs[models.index(m)])["S"] for m in models_row)
    breakdown.update(
        n_buckets=n_buckets,
        n_topologies=len(models),
        cold_start_compiles=clog_cold.real_count,
        padding_waste_frac=round(1.0 - s_real / s_pad, 4),
        # per-axis decomposition under the ACTIVE pad ladder
        # (RAFT_TPU_BUCKET_STEPS) — the strips row reproduces
        # padding_waste_frac, nodes/lines name the rest of the budget
        waste_by_axis=bucketing.waste_by_axis(
            [bucketing.axis_counts(m, sigs[models.index(m)])
             for m in models_row]),
        bucket_steps=config.get("BUCKET_STEPS"),
    )
    # achieved-GFLOP/s per banked program (populated when the AOT bank
    # is armed — run the mode child under RAFT_TPU_AOT=load for the
    # ledger-backed before/after)
    from raft_tpu.aot import bank as _bank

    ledger = _bank.ledger_summary()
    if ledger:
        breakdown["cost_ledger"] = ledger
    result = {
        "metric": "case-evals/sec/chip (mixed spar+semi+MHK topologies, "
                  "shape-bucketed, 40w)",
        "value": round(evals_per_sec, 3),
        "unit": "case-evals/s",
        "vs_baseline": round(evals_per_sec / base_per_sec, 2),
        "breakdown": breakdown,
    }
    if note:
        result["note"] = note
    print(json.dumps(result))


def _run_geom(t_start):
    import jax
    import jax.numpy as jnp

    model, evaluate = build()
    n_cases = len(CASES)
    arr = np.array(CASES)

    case_cols = jnp.asarray(arr, dtype=jnp.float32)   # (12, 6) case table

    def design_eval(g4, key="PSD"):
        """One FULL design evaluation: the geometry stage once, then the
        12-case table through the traced chain (inner vmap)."""
        gc = evaluate.geometry_constants(dict(
            d_scale=g4[0], t_scale=g4[1], fill_scale=g4[2],
            L_moor_scale=g4[3]))

        def one_case(c6):
            return evaluate(dict(
                wind_speed=c6[0], wind_heading_deg=c6[1], TI=c6[2],
                Hs=c6[3], Tp=c6[4], beta_deg=c6[5], geom_const=gc))[key]

        return jax.vmap(one_case)(case_cols)

    def eval_case(g4, key="PSD"):
        return design_eval(g4, key=key)

    # batch of B DISTINCT design geometries x the 12-case table
    B = config.get("BENCH_DESIGNS")
    reps = config.get("BENCH_REPS")
    args = [jnp.asarray(sample_geometry(B), dtype=jnp.float32)]  # (B, 4)

    fn = jax.jit(jax.vmap(eval_case))
    # AOT-compile through the program bank: a warmed bank answers in
    # deserialize time; a miss lowers+compiles AND exports for the next
    # round.  The executable is timed directly — calling fn(*args)
    # would trigger a second, redundant compilation (lower().compile()
    # does not populate the jit cache).
    compiled, _bank_hit, t_compile = _aot_compile(fn, args, "bench:geom",
                                                  evaluate=evaluate)
    jax.block_until_ready(compiled(*args))
    cold_start = time.perf_counter() - t_start
    # breakdown budgeting needs the REAL compile scale: when the
    # headline was a bank load, t_compile is deserialize time — use
    # the compile_s its exporter recorded instead
    compile_est = t_compile
    if _bank_hit:
        from raft_tpu.aot import bank as _bank

        _meta = _bank.peek("bench:geom", _aot_memo(evaluate), args)
        compile_est = float((_meta or {}).get("compile_s") or 33.0)

    dt, n_recompiles = _timed_reps(compiled, args, reps)
    design_evals_per_sec = B / dt

    t_stat, t_dyn = _stage_times(
        lambda key: jax.jit(jax.vmap(
            lambda *a: jnp.sum(jnp.abs(eval_case(*a, key=key))))),
        args, reps, compile_est, dt, t_start, kind="bench:geom",
        evaluate=evaluate)
    raw_builder = lambda key: jax.jit(
        jax.vmap(lambda *a: eval_case(*a, key=key)))
    iters = _drag_iters(raw_builder, args, compile_est, t_dyn, t_start,
                        kind="bench:geom", evaluate=evaluate)
    flagged = _flagged_fraction(raw_builder, args, compile_est, t_dyn,
                                t_start, kind="bench:geom",
                                evaluate=evaluate)

    # optional profiler capture (point RAFT_TPU_PROFILE at a directory
    # and open the trace in TensorBoard / Perfetto)
    prof_dir = config.get("PROFILE")
    if prof_dir:
        with jax.profiler.trace(prof_dir):
            jax.block_until_ready(compiled(*args))

    base_design_evals_per_sec = _numpy_baseline(model)
    breakdown = _finish_breakdown(
        _flops_breakdown(compiled, dt), t_compile, dt, t_stat, t_dyn,
        base_design_evals_per_sec, B, True, iters=iters,
        ndof=model.fowtList[0].nDOF, recompiles=n_recompiles,
        flagged=flagged, cold_start_s=cold_start)
    print(json.dumps({
        "metric": "design-evals/sec/chip (VolturnUS-S geometry DoE, 100w x 12 cases, operating turbine)",
        "value": round(design_evals_per_sec, 3),
        "unit": "design-evals/s",
        "vs_baseline": round(design_evals_per_sec / base_design_evals_per_sec, 2),
        "breakdown": breakdown,
    }))


def _flops_breakdown(compiled, dt):
    """Achieved FLOP rate from XLA's own cost model + an MFU estimate
    against the env-provided peak (default 90 TF/s f32-class; set
    RAFT_TPU_PEAK_TFLOPS for the actual part).  Emits null (not NaN)
    when cost analysis is unavailable so the JSON stays standard."""
    import jax

    try:
        flops = float(compiled.cost_analysis()["flops"])
    except Exception:
        flops = None
    peak_tf = config.get("PEAK_TFLOPS")
    tflops = flops / dt / 1e12 if flops is not None else None
    return dict(
        xla_flops_per_batch=flops,
        tflops_achieved=round(tflops, 4) if tflops is not None else None,
        mfu_vs_peak=round(tflops / peak_tf, 6) if tflops is not None else None,
        peak_tflops_assumed=peak_tf,
        device_kind=jax.devices()[0].device_kind,
    )


def _numpy_baseline(model):
    """Serial NumPy twin: design evaluations (12-case tables) per
    second.  Inside a bench attempt the parent has already resolved the
    value (artifact or one bounded measurement) and passes it via env —
    measuring here would burn the attempt's deadline on a constant
    (the round-3/4 failure mode)."""
    env_s = config.get("BENCH_BASE_EVAL_S")
    if env_s:
        return 1.0 / env_s
    n_cases = len(CASES)
    n_base = config.get("BENCH_NBASE")
    cases = [dict(wind_speed=c[0], wind_heading=c[1], turbulence=c[2],
                  wave_height=c[3], wave_period=c[4], wave_heading=c[5])
             for c in CASES]
    numpy_eval_case(model, cases[0])  # one-time statics JIT warmup
    t0 = time.perf_counter()
    for i in range(n_base):
        numpy_eval_case(model, cases[i % n_cases])
    base_case_dt = (time.perf_counter() - t0) / n_base
    return 1.0 / (n_cases * base_case_dt)


def run_flat(t_start=None):
    """Banked configuration (round-2 proven): ONE baked geometry,
    flat (B*12,) case batch through the geometry=False evaluator."""
    import jax
    import jax.numpy as jnp

    import raft_tpu
    from raft_tpu.api import make_full_evaluator
    from raft_tpu.structure.schema import load_design

    if t_start is None:
        t_start = time.perf_counter()
    design = load_design(VOLTURN)
    design["settings"]["min_freq"] = 0.002
    design["settings"]["max_freq"] = 0.2
    model = raft_tpu.Model(design)
    evaluate = make_full_evaluator(model)

    def eval_case(ws, wh, ti, hs, tp, bd, key="PSD"):
        return evaluate(dict(wind_speed=ws, wind_heading_deg=wh, TI=ti,
                             Hs=hs, Tp=tp, beta_deg=bd))[key]

    n_cases = len(CASES)
    arr = np.array(CASES)
    B = config.get("BENCH_DESIGNS")
    reps = config.get("BENCH_REPS")
    tiled = np.tile(arr, (B, 1))
    args = [jnp.asarray(tiled[:, j], dtype=jnp.float32) for j in range(6)]

    fn = jax.jit(jax.vmap(eval_case))
    compiled, _bank_hit, t_compile = _aot_compile(fn, args, "bench:flat",
                                                  evaluate=evaluate)
    jax.block_until_ready(compiled(*args))
    cold_start = time.perf_counter() - t_start
    # breakdown budgeting needs the REAL compile scale: when the
    # headline was a bank load, t_compile is deserialize time — use
    # the compile_s its exporter recorded instead
    compile_est = t_compile
    if _bank_hit:
        from raft_tpu.aot import bank as _bank

        _meta = _bank.peek("bench:flat", _aot_memo(evaluate), args)
        compile_est = float((_meta or {}).get("compile_s") or 33.0)
    dt, n_recompiles = _timed_reps(compiled, args, reps)
    design_evals_per_sec = B / dt

    t_stat, t_dyn = _stage_times(
        lambda key: jax.jit(jax.vmap(
            lambda *a: jnp.sum(jnp.abs(eval_case(*a, key=key))))),
        args, reps, compile_est, dt, t_start, kind="bench:flat",
        evaluate=evaluate)
    raw_builder = lambda key: jax.jit(
        jax.vmap(lambda *a: eval_case(*a, key=key)))
    iters = _drag_iters(raw_builder, args, compile_est, t_dyn, t_start,
                        kind="bench:flat", evaluate=evaluate)
    flagged = _flagged_fraction(raw_builder, args, compile_est, t_dyn,
                                t_start, kind="bench:flat",
                                evaluate=evaluate)

    base = _numpy_baseline(model)
    breakdown = _finish_breakdown(
        _flops_breakdown(compiled, dt), t_compile, dt, t_stat, t_dyn,
        base, B, False, iters=iters, ndof=model.fowtList[0].nDOF,
        recompiles=n_recompiles, flagged=flagged, cold_start_s=cold_start)
    print(json.dumps({
        "metric": "design-evals/sec/chip (VolturnUS-S, 100w x 12 cases, operating turbine)",
        "value": round(design_evals_per_sec, 3),
        "unit": "design-evals/s",
        "vs_baseline": round(design_evals_per_sec / base, 2),
        "breakdown": breakdown,
    }))


if __name__ == "__main__":
    main()
