"""Seeded violations for the dtype-literal rule (every flagged line is
a real instance of the PR-2 silent-upcast bug class)."""

import jax.numpy as jnp
import numpy as np


def traced_allocations(nw):
    a = jnp.zeros(nw, dtype=complex)            # line 9: bare complex
    b = jnp.ones((3, nw), dtype=jnp.complex128)  # line 10: pinned 64-bit
    c = jnp.full(nw, 1.0, dtype="float64")       # line 11: string literal
    d = a.astype(complex)                        # line 12: astype literal
    e = jnp.zeros((3, nw), complex)              # line 13: positional dtype
    return a, b, c, d, e


def host_allocation(nw):
    return np.zeros(nw, dtype=complex)           # line 18: ambiguous width
