"""Static protocol extraction over the fleet's filesystem state machines.

The shared-filesystem coordination protocol (leases, release pointers,
rollout markers, canary gates) lives in six modules.  Every mutation of
the shared tree is a **protocol action**: it either goes through one of
the sanctioned atomic channels (``utils.fsops``, the lease primitives,
``resilience._atomic_*``) inside a function the model checker knows
about, or it is *unmodeled* — a write the interleaving explorer in
:mod:`raft_tpu.analysis.mcheck` never exercises, and therefore a hole
in every safety argument the checker makes.

This engine walks the AST of each protocol module, finds every
mutation site, classifies it into a named action, and pins the result
in ``analysis/protocol_baseline.json``.  ``protocol check`` fails when

* a mutation site appears that the baseline has never seen (new or
  reshaped protocol surface → re-derive the model, then re-pin), or
* a site bypasses the sanctioned channels entirely (raw ``os.rename``
  / bare ``open(..., "w")`` → unmodeled mutation), or
* the explorer itself finds an interleaving/crash schedule that breaks
  an invariant (see ``mcheck.INVARIANTS``).

Like the rest of the analysis package this module must import without
jax so it can run as a pre-commit/CI gate.
"""

from __future__ import annotations

import ast
import json
import os

from raft_tpu.analysis.lint import Finding, repo_root

BASELINE_SCHEMA = "protocol-baseline/v1"
BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "protocol_baseline.json")

#: The protocol surface: every module whose writes coordinate the fleet
#: through the shared filesystem.  Keys are short names used in site
#: keys; values are repo-relative paths.
MODULES = {
    "fabric": "raft_tpu/parallel/fabric.py",
    "fleet": "raft_tpu/serve/fleet.py",
    "release": "raft_tpu/aot/release.py",
    "rollout": "raft_tpu/serve/rollout.py",
    "router": "raft_tpu/serve/router.py",
    "canary": "raft_tpu/serve/canary.py",
}

#: Sanctioned mutating entry points of the fsops seam.
_FSOPS_MUTATORS = frozenset({
    "create_exclusive", "write_text", "write_atomic", "replace",
    "rename", "unlink", "utime", "makedirs",
})

#: Lease primitives (imported by value into fleet.py, hence bare names).
_LEASE_PRIMS = frozenset({"lease_claim", "lease_rewrite", "lease_remove"})

#: Sanctioned atomic writers living outside fsops (tmp+replace inside).
_ATOMIC_HELPERS = frozenset({
    "_atomic_write", "_atomic_json", "atomic_savez", "init_manifest",
})

#: Raw os-level mutators.  ``makedirs``/``mkdir`` are idempotent
#: directory scaffolding (ensure-dir); everything else raw is unmodeled.
_OS_MUTATORS = frozenset({
    "rename", "replace", "unlink", "remove", "rmdir", "removedirs",
    "renames", "makedirs", "mkdir", "link", "symlink", "truncate",
    "write",
})

_SHUTIL_MUTATORS = frozenset({
    "rmtree", "move", "copy", "copy2", "copyfile", "copytree",
})

#: Enclosing protocol function (simple name) -> action, for mutations
#: that go through the core fsops/lease channels.  A core-channel write
#: inside a function NOT listed here is an unmodeled finding: the model
#: checker does not know that state machine.
ACTION_BY_FUNC = {
    # fabric lease primitives + sweep ledger
    "lease_claim": "claim",
    "lease_rewrite": "renew",
    "lease_remove": "steal",
    "claim": "claim",
    "renew": "renew",
    "release": "release",
    "steal": "steal",
    # fleet replica lifecycle
    "seize": "seize",
    "evict": "evict",
    # release pointer machine
    "promote": "promote",
    "cut": "record",
    "write_rollout_marker": "marker",
    "clear_rollout_marker": "unmark",
    # worker recovery
    "_eval_shard": "requeue",
}

#: Every action name the model may emit (kept sorted for the baseline).
ACTIONS = tuple(sorted(set(ACTION_BY_FUNC.values())
                       | {"ensure-dir", "heartbeat", "append-log",
                          "record"}))


class Site(object):
    """One static mutation site inside a protocol module."""

    __slots__ = ("module", "path", "qualname", "callee", "line", "col",
                 "action")

    def __init__(self, module, path, qualname, callee, line, col, action):
        self.module = module
        self.path = path
        self.qualname = qualname
        self.callee = callee
        self.line = line
        self.col = col
        self.action = action

    @property
    def key(self):
        return "%s::%s::%s" % (self.module, self.qualname, self.callee)

    @property
    def modeled(self):
        return self.action is not None


def _dotted(node):
    """Dotted name of a call target, or None (subscripts, lambdas...)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _open_mode(call):
    """Literal mode string of an ``open()`` call, or None."""
    args = call.args
    if len(args) >= 2 and isinstance(args[1], ast.Constant) \
            and isinstance(args[1].value, str):
        return args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


class _SiteVisitor(ast.NodeVisitor):
    def __init__(self, module, path):
        self.module = module
        self.path = path
        self.stack = []
        self.sites = []

    # -- scope tracking -------------------------------------------------
    def _scoped(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped
    visit_ClassDef = _scoped

    @property
    def qualname(self):
        return ".".join(self.stack) if self.stack else "<module>"

    @property
    def func(self):
        """Innermost plain-function name (classes excluded by usage)."""
        return self.stack[-1] if self.stack else "<module>"

    # -- call classification --------------------------------------------
    def _add(self, call, callee, action):
        self.sites.append(Site(
            self.module, self.path, self.qualname, callee,
            call.lineno, call.col_offset, action))

    def visit_Call(self, node):
        name = _dotted(node.func)
        if name is not None:
            head, _, tail = name.rpartition(".")
            if head == "fsops" and tail in _FSOPS_MUTATORS:
                self._classify_core(node, name, tail)
            elif head == "" and tail in _LEASE_PRIMS:
                self._classify_core(node, name, tail)
            elif tail in _ATOMIC_HELPERS and head in (
                    "resilience", "bank", ""):
                self._add(node, name, "record")
            elif head in ("os", "os.path") and tail in _OS_MUTATORS:
                action = "ensure-dir" if tail in ("makedirs", "mkdir") \
                    else None
                self._add(node, name, action)
            elif head == "shutil" and tail in _SHUTIL_MUTATORS:
                self._add(node, name, None)
            elif name == "open" or name.endswith(".open"):
                mode = _open_mode(node)
                if mode is not None and ("a" in mode):
                    self._add(node, "open[%s]" % mode, "append-log")
                elif mode is not None and any(
                        c in mode for c in "wx+"):
                    self._add(node, "open[%s]" % mode, None)
        self.generic_visit(node)

    def _classify_core(self, node, name, tail):
        if tail == "makedirs":
            self._add(node, name, "ensure-dir")
        elif tail == "utime":
            self._add(node, name, "heartbeat")
        else:
            action = ACTION_BY_FUNC.get(self.func)
            if action is None and self.func in (
                    "write_done", "write_worker_status", "init_sweep",
                    "publish_router_record", "spawn_worker",
                    "spawn_replica"):
                action = "record"
            self._add(node, name, action)


def extract_module(module, path):
    """All mutation sites in one protocol module (repo-relative path)."""
    full = path if os.path.isabs(path) else os.path.join(repo_root(), path)
    with open(full) as f:
        tree = ast.parse(f.read(), filename=path)
    v = _SiteVisitor(module, path)
    v.visit(tree)
    return v.sites


def extract_all(modules=None):
    """Extract every module; returns ``(sites, unmodeled)`` lists."""
    sites = []
    for module, path in sorted((modules or MODULES).items()):
        sites.extend(extract_module(module, path))
    unmodeled = [s for s in sites if not s.modeled]
    return sites, unmodeled


def sites_to_model(sites):
    """Collapse modeled sites into the baseline mapping: site key ->
    ``{"action": ..., "count": n}`` (count disambiguates repeated calls
    of the same channel inside one function)."""
    model = {}
    for s in sites:
        if not s.modeled:
            continue
        ent = model.setdefault(s.key, {"action": s.action, "count": 0})
        ent["count"] += 1
        if ent["action"] != s.action:
            # same key, conflicting classification: surface as drift by
            # recording the lexically-last action (diff will flag it).
            ent["action"] = s.action
    return model


def load_baseline(path=BASELINE_PATH):
    with open(path) as f:
        data = json.load(f)
    if data.get("schema") != BASELINE_SCHEMA:
        raise ValueError("unexpected baseline schema: %r"
                         % data.get("schema"))
    return data


def write_baseline(path=BASELINE_PATH):
    """Re-pin the baseline.  Refuses while unmodeled sites exist — an
    unmodeled mutation must be routed through fsops (and given an
    action) before it can be pinned, otherwise the pin would bless a
    write the explorer never exercises."""
    from raft_tpu.analysis import mcheck

    sites, unmodeled = extract_all()
    if unmodeled:
        raise ValueError(
            "refusing to pin baseline over %d unmodeled mutation "
            "site(s); run `protocol extract` and route them through "
            "utils.fsops first" % len(unmodeled))
    data = {
        "schema": BASELINE_SCHEMA,
        "invariants": list(mcheck.INVARIANTS),
        "sites": {k: dict(v) for k, v in
                  sorted(sites_to_model(sites).items())},
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


def _site_finding(site, rule, message):
    return Finding(path=site.path, line=site.line, col=site.col,
                   rule=rule, message=message)


def diff_against_baseline(sites, unmodeled, baseline):
    """Static findings: unmodeled sites + drift vs the pinned model."""
    findings = []
    for s in unmodeled:
        sanctioned = (s.callee.startswith("fsops.")
                      or s.callee.split(".")[-1] in _LEASE_PRIMS)
        hint = ("teach ACTION_BY_FUNC the enclosing protocol action"
                if sanctioned else "route through utils.fsops and "
                "classify it") + " (then re-pin the baseline)"
        findings.append(_site_finding(
            s, "protocol-unmodeled",
            "unmodeled fs mutation %s in %s::%s — %s"
            % (s.callee, s.module, s.qualname, hint)))

    model = sites_to_model(sites)
    pinned = baseline.get("sites", {})
    first_by_key = {}
    for s in sites:
        if s.modeled:
            first_by_key.setdefault(s.key, s)

    for key in sorted(set(model) - set(pinned)):
        s = first_by_key[key]
        findings.append(_site_finding(
            s, "protocol-drift",
            "new protocol mutation site %s (action %s) not in "
            "baseline — extend the mcheck model, then re-pin with "
            "`protocol baseline --write`" % (key, model[key]["action"])))
    for key in sorted(set(pinned) - set(model)):
        ent = pinned[key]
        mod = key.split("::", 1)[0]
        findings.append(Finding(
            path=MODULES.get(mod, "raft_tpu/analysis/protocol_baseline.json"),
            line=1, col=0, rule="protocol-drift",
            message="pinned mutation site %s (action %s) vanished — "
                    "the protocol surface shrank; re-pin the baseline"
                    % (key, ent.get("action"))))
    for key in sorted(set(model) & set(pinned)):
        got, want = model[key], pinned[key]
        if (got["action"], got["count"]) != (
                want.get("action"), want.get("count")):
            s = first_by_key[key]
            findings.append(_site_finding(
                s, "protocol-drift",
                "mutation site %s reshaped: baseline pinned action=%s "
                "count=%s, extraction found action=%s count=%s"
                % (key, want.get("action"), want.get("count"),
                   got["action"], got["count"])))
    return findings


def explorer_findings(patches=None, scenarios=None):
    """Run the interleaving explorer; map violations to findings."""
    from raft_tpu.analysis import mcheck

    violations, stats = mcheck.run_all(patches=patches,
                                       scenarios=scenarios)
    findings = []
    for name, v in violations:
        findings.append(Finding(
            path="raft_tpu/analysis/mcheck.py", line=1, col=0,
            rule="protocol-" + v.invariant,
            message="scenario %s: %s | trace: %s"
                    % (name, v.detail, " -> ".join(v.trace[-12:]))))
    return findings, stats


def check(baseline_path=BASELINE_PATH, explore=True, scenarios=None):
    """Full gate: extraction diff + invariant check of the baseline
    + (optionally) the exhaustive interleaving exploration."""
    from raft_tpu.analysis import mcheck

    sites, unmodeled = extract_all()
    try:
        baseline = load_baseline(baseline_path)
    except (OSError, ValueError) as e:
        findings = [Finding(
            path="raft_tpu/analysis/protocol_baseline.json", line=1,
            col=0, rule="protocol-baseline",
            message="cannot load protocol baseline (%s); pin it with "
                    "`python -m raft_tpu.analysis protocol baseline "
                    "--write`" % e)]
        return findings, {}
    findings = diff_against_baseline(sites, unmodeled, baseline)

    if sorted(baseline.get("invariants", [])) != sorted(mcheck.INVARIANTS):
        findings.append(Finding(
            path="raft_tpu/analysis/protocol_baseline.json", line=1,
            col=0, rule="protocol-drift",
            message="invariant set drifted from baseline: pinned %s vs "
                    "mcheck %s — re-pin after reviewing"
                    % (sorted(baseline.get("invariants", [])),
                       sorted(mcheck.INVARIANTS))))

    stats = {}
    if explore and not findings:
        more, stats = explorer_findings(scenarios=scenarios)
        findings.extend(more)
    return findings, stats


def _static_fixture_module(src, path):
    """``PROTOCOL_MODULE = "name"`` constant from a static fixture's
    AST, or None.  Read without executing: static fixtures deliberately
    contain raw mutation calls and must only ever be *scanned*."""
    for node in ast.parse(src, filename=path).body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "PROTOCOL_MODULE" \
                and isinstance(node.value, ast.Constant):
            return node.value.value
    return None


def run_fixture(path):
    """Drive the engines against a seeded-bug fixture module.

    Two fixture shapes are supported:

    * ``PATCHES`` — a dict ``"module.path:attr" -> replacement``; the
      explorer re-runs with the buggy behaviour patched over the real
      protocol functions (optionally restricted via ``SCENARIOS``).
    * ``PROTOCOL_MODULE`` — the static engine scans the fixture file
      itself as if it were that protocol module, diffing its mutation
      sites against the pinned baseline.
    """
    from raft_tpu.analysis import mcheck

    src = open(path).read()
    module = _static_fixture_module(src, path)
    if module is not None:
        modules = dict(MODULES)
        modules[module] = os.path.abspath(path)
        sites, unmodeled = extract_all(modules)
        baseline = load_baseline()
        return diff_against_baseline(sites, unmodeled, baseline), {}

    mod = mcheck.load_fixture(path)
    names = getattr(mod, "SCENARIOS", None)
    scenarios = None
    if names:
        scenarios = [s for s in mcheck.SCENARIOS if s.name in names]
    return explorer_findings(patches=mod.PATCHES, scenarios=scenarios)
