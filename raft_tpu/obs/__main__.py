"""CLI for the telemetry subsystem (pure stdlib, no jax).

    python -m raft_tpu.obs report <run.jsonl> [--format json]
    python -m raft_tpu.obs report --merge <capture-dir | shard.jsonl ...>
    python -m raft_tpu.obs trace  <run.jsonl> -o trace.json
    python -m raft_tpu.obs trace  --merge <capture-dir | shards...> -o t.json
    python -m raft_tpu.obs events
    python -m raft_tpu.obs spans
    python -m raft_tpu.obs report <capture> --tail [RANK]
    python -m raft_tpu.obs runs   {record,list,compare,regress,ingest,pin}
    python -m raft_tpu.obs alerts {list,check,eval}
    python -m raft_tpu.obs flight {dump,show}

``report`` prints the per-stage wall-time tree, counter table, program
cost ledger, serve tail-attribution and padding-waste tables and the
reliability summary of one ``RAFT_TPU_LOG`` capture (``--format json``
emits the same sections machine-readably); ``trace`` exports it as
Chrome/Perfetto trace-event JSON (load in ``chrome://tracing`` or
https://ui.perfetto.dev).  ``--merge`` accepts several per-process
capture shards (or a directory of ``trace-<pid>.jsonl`` files, the
``RAFT_TPU_LOG=<dir>`` layout) and assembles coordinator + workers +
server onto ONE wall-clock timeline using the per-process
``proc_start`` clock anchors; ``--check`` (trace) additionally exits 1
when the merged capture has unmatched span begins or orphan spans (a
parent id resolving to no span) — the cross-process propagation
acceptance gate.  ``events``/``spans`` list the registered schemas.
``report --tail`` renders THE request at a latency rank (default p95)
— its exemplar identity, stage decomposition and full span tree;
``flight`` dumps/validates the black-box recorder's shards
(:mod:`raft_tpu.obs.flight` — a damaged shard exits 1).

``alerts`` is the live fleet-health layer's offline face
(:mod:`raft_tpu.obs.alerts`): ``list`` prints the effective rule pack
(default + ``RAFT_TPU_ALERT_RULES``/``--rules``, optionally
summarizing a ``RAFT_TPU_ALERTS`` sink), ``check`` validates it (the
lint.sh gate), and ``eval --record`` replays the rules against a
stored run record — rate rules gate on their cumulative totals — so
CI can gate alerting with no live fleet and no jax import.

``runs`` is the longitudinal perf store (:mod:`raft_tpu.obs.runs`,
``RAFT_TPU_RUNS_DIR``): ``record`` appends a run record from the
current process/capture, ``list`` shows the trajectory, ``compare``
prints per-metric deltas between two records, ``regress`` gates the
newest record against the pinned baseline (exit 1 on regression,
env-fingerprint mismatch downgrades to warnings), ``ingest`` imports
``BENCH_rNN.json`` artifacts, ``pin`` chooses the baseline.

Exit codes: 0 ok, 1 check/regress failed, 2 usage/input error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load(paths, merge):
    from raft_tpu.obs import report

    try:
        if merge:
            events, bad, info = report.merge_captures(paths)
        else:
            if len(paths) != 1:
                print("multiple captures need --merge", file=sys.stderr)
                raise SystemExit(2)
            events, bad = report.read_events(paths[0])
            info = None
    except OSError as e:
        print(f"cannot read {getattr(e, 'filename', None) or paths}: {e}",
              file=sys.stderr)
        raise SystemExit(2)
    if not events:
        print(f"{', '.join(paths)}: no parseable events (was RAFT_TPU_LOG "
              "pointed here during the run?)", file=sys.stderr)
        raise SystemExit(2)
    return events, bad, info


def _cmd_report(args):
    from raft_tpu.obs import report

    events, bad, _ = _load(args.jsonl, args.merge)
    if args.tail is not None:
        source = ", ".join(args.jsonl)
        if args.format == "json":
            json.dump(report.tail_view(events, rank=args.tail),
                      sys.stdout, indent=1, default=str)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(report.render_tail(events, rank=args.tail,
                                                source=source))
        return 0
    if args.format == "json":
        json.dump(report.report_data(events, bad,
                                     source=", ".join(args.jsonl)),
                  sys.stdout, indent=1, default=str)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(report.render_report(
            events, bad, source=", ".join(args.jsonl)))
    return 0


def _cmd_trace(args):
    from raft_tpu.obs import report

    events, bad, info = _load(args.jsonl, args.merge)
    trace = report.chrome_trace(events, merged=args.merge)
    with open(args.output, "w") as f:
        json.dump(trace, f)
    meta = trace["otherData"]
    print(f"{args.output}: {len(trace['traceEvents'])} trace events "
          f"({meta['spans_matched']} spans across {meta['pids']} "
          f"process(es), {meta['traces']} trace id(s)"
          + (f", {meta['spans_unmatched']} unmatched" if
             meta["spans_unmatched"] else "")
          + (f", {meta['spans_orphaned']} orphaned" if
             meta["spans_orphaned"] else "")
          + (f"; {info['unanchored_files']} unanchored shard(s)"
             if info and info.get("unanchored_files") else "")
          + (f"; {bad} unparseable lines skipped" if bad else "")
          + ") — open in chrome://tracing or ui.perfetto.dev")
    if args.check and (meta["spans_unmatched"] or meta["spans_orphaned"]):
        print(f"check FAILED: {meta['spans_unmatched']} unmatched begin(s), "
              f"{meta['spans_orphaned']} orphan span(s) — cross-process "
              "propagation is broken somewhere", file=sys.stderr)
        return 1
    return 0


def _cmd_events(_args):
    from raft_tpu.obs import events as ev

    for name, fields, help_ in ev.describe():
        print(f"{name:32s} {', '.join(fields):56s} {help_}")
    return 0


def _cmd_spans(_args):
    from raft_tpu.obs import events as ev

    for name, help_ in ev.describe_spans():
        print(f"{name:32s} {help_}")
    return 0


# ----------------------------------------------------------- flight verbs


def _cmd_flight_dump(args):
    """Persist THIS process's flight ring as one shard.  Mostly useful
    in-process (the ring is per-process); from the CLI it documents the
    dump format and gives scripts a deterministic writer."""
    from raft_tpu.obs import flight

    path = flight.dump(trigger=args.trigger, path=args.output)
    if path is None:
        print("flight dump: nowhere to write — pass -o PATH or set "
              "RAFT_TPU_FLIGHT_DIR (and RAFT_TPU_FLIGHT_RING > 0)",
              file=sys.stderr)
        return 2
    print(f"{path}: flight shard written (trigger={args.trigger})")
    return 0


def _cmd_flight_show(args):
    """Validate + summarize one dump shard; exit 1 on a damaged or
    newer-schema shard (the lint.sh gate)."""
    from raft_tpu.obs import flight

    return flight.show(args.shard)


# ----------------------------------------------------------- alerts verbs


def _alert_rules(args):
    from raft_tpu.obs import alerts
    from raft_tpu.utils import config

    path = getattr(args, "rules", None) or config.get("ALERT_RULES") or None
    return alerts.load_rules(path), path


def _cmd_alerts_list(args):
    from raft_tpu.obs import alerts

    try:
        rules, path = _alert_rules(args)
    except (OSError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    print(f"{len(rules)} rule(s)"
          + (f" (default pack + {path})" if path else " (default pack)"))
    print(f"  {'name':24s} {'severity':10s} {'predicate':12s} "
          f"{'threshold':>10s} {'for_s':>7s} {'clear_s':>7s}  metric")
    for r in sorted(rules, key=lambda r: r.name):
        print(f"  {r.name:24s} {r.severity:10s} {r.predicate:12s} "
              f"{r.threshold:10.4g} {r.for_s:7.1f} {r.clear_s:7.1f}  "
              f"{r.metric}")
    if args.sink:
        try:
            records, bad = alerts.read_sink(args.sink)
        except OSError as e:
            print(f"cannot read sink {args.sink}: {e}", file=sys.stderr)
            return 2
        print(f"\nsink {args.sink}: {len(records)} record(s)"
              + (f" ({bad} unparseable)" if bad else ""))
        for line in alerts.render_sink_summary(records):
            print(f"  {line}")
    return 0


def _cmd_alerts_check(args):
    """Rule-pack validation (the lint.sh gate): the default pack plus
    the given/flagged rule file must parse and validate.  Exit 0 ok,
    1 invalid."""
    try:
        rules, path = _alert_rules(args)
    except (OSError, ValueError) as e:
        print(f"alerts check FAILED: {e}", file=sys.stderr)
        return 1
    print(f"ok: {len(rules)} rule(s) valid"
          + (f" (default pack + {path})" if path else " (default pack)"))
    return 0


def _cmd_alerts_eval(args):
    """Replay the rule pack against a stored run record — no live
    fleet, no jax import.  Exit 0 clean, 1 when any rule fires."""
    from raft_tpu.obs import alerts, runs

    try:
        rules, _path = _alert_rules(args)
        record = runs.load_record(args.record)
    except (OSError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    fired, checked = alerts.replay_rules(rules, record)
    name = os.path.basename(args.record)
    print(f"alerts eval {name}: {checked}/{len(rules)} rule(s) "
          "applicable")
    for f in fired:
        print(f"  FIRED: {f['rule']} [{f['severity']}] {f['metric']} = "
              f"{f['value']:.6g} (replay threshold {f['threshold']:.6g})"
              + (f" — {f['help']}" if f.get("help") else ""))
    if fired:
        print(f"  FAILED: {len(fired)} rule(s) fired", file=sys.stderr)
        return 1
    print("  ok: no rules fired")
    return 0


# ------------------------------------------------------------- runs verbs


def _runs_store(args, need=True):
    from raft_tpu.obs import runs

    d = getattr(args, "dir", None) or runs.runs_dir()
    if d is None and need:
        print("no run store: set RAFT_TPU_RUNS_DIR or pass --dir",
              file=sys.stderr)
        raise SystemExit(2)
    return d


def _cmd_runs_record(args):
    from raft_tpu.obs import runs

    events = None
    if args.events:
        merge = len(args.events) > 1 or os.path.isdir(args.events[0])
        events, _bad, _ = _load(args.events, merge=merge)
    extra = {}
    if args.extra_json:
        try:
            extra = json.loads(args.extra_json)
            if not isinstance(extra, dict):
                raise ValueError("must be a JSON object")
        except ValueError as e:
            print(f"--extra-json: {e}", file=sys.stderr)
            return 2
    d = _runs_store(args)
    record = runs.build_record(args.kind, label=args.label, extra=extra,
                               events=events)
    path = runs.write_record(record, d)
    n = len(runs.flatten(record))
    print(f"{path}: recorded kind={args.kind} ({n} metrics)")
    return 0


def _cmd_runs_list(args):
    from raft_tpu.obs import runs

    d = _runs_store(args)
    records = runs.list_records(d)
    if not records:
        print(f"{d}: no run records")
        return 0
    pinned = runs.pinned_baseline(d)
    import time as _time

    for path, rec in records:
        mark = "*" if pinned and os.path.samefile(path, pinned) else " "
        t = _time.strftime("%Y-%m-%d %H:%M:%S",
                           _time.localtime(rec.get("t_unix") or 0))
        env = rec.get("env") or {}
        where = ("ingested" if env.get("ingested")
                 else f"{env.get('platform', '?')}x"
                      f"{env.get('n_devices', '?')}")
        print(f"{mark} {os.path.basename(path):44s} {t}  "
              f"{rec.get('kind', '?'):12s} {str(rec.get('label') or '-'):16s} "
              f"{where:10s} {len(runs.flatten(rec)):4d} metrics")
    if pinned:
        print(f"baseline: {os.path.basename(pinned)}")
    return 0


def _fmt_v(v):
    return "—" if v is None else (f"{v:.6g}" if isinstance(v, float) else v)


def _cmd_runs_compare(args):
    from raft_tpu.obs import runs

    try:
        a = runs.load_record(args.new)
        b = runs.load_record(args.baseline)
    except (OSError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    rows = runs.compare_records(a, b)
    mismatch = runs.env_mismatch(a, b)
    print(f"compare {os.path.basename(args.new)} vs "
          f"{os.path.basename(args.baseline)}"
          + (f"  [env mismatch: {', '.join(mismatch)} — numbers not "
             "directly comparable]" if mismatch else ""))
    print(f"  {'metric':44s} {'baseline':>12s} {'new':>12s} "
          f"{'delta':>12s} {'pct':>8s}")
    for r in rows:
        # keep one-sided rows (metric present in only one record):
        # a metric DISAPPEARING is the biggest change of all
        if args.changed_only and r.get("delta") == 0:
            continue
        pct = r.get("pct")
        print(f"  {r['metric']:44s} {_fmt_v(r['base']):>12s} "
              f"{_fmt_v(r['new']):>12s} {_fmt_v(r.get('delta')):>12s} "
              + (f"{pct:+7.1f}%" if pct is not None else "       —"))
    return 0


def _cmd_runs_regress(args):
    from raft_tpu.obs import runs
    from raft_tpu.utils.structlog import log_event

    d = getattr(args, "dir", None) or runs.runs_dir()
    new_path = args.record
    base_path = args.baseline
    if new_path is None:
        records = runs.list_records(d) if d else []
        if not records:
            print("regress: no record given and no records in the store",
                  file=sys.stderr)
            return 2
        new_path = records[-1][0]
        # newest-vs-baseline: never judge the baseline against itself —
        # whether it came from the pin file or --baseline (a self-
        # compare trivially passes and the gate would check nothing)
        base = base_path or (runs.pinned_baseline(d) if d else None)
        if base and os.path.exists(base) \
                and os.path.samefile(new_path, base) and len(records) > 1:
            new_path = records[-2][0]
    if base_path is None:
        base_path = runs.pinned_baseline(d) if d else None
        if base_path is None:
            print("regress: no baseline pinned (obs runs pin <record>) "
                  "and no --baseline given", file=sys.stderr)
            return 2
    try:
        new = runs.load_record(new_path)
        base = runs.load_record(base_path)
    except (OSError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    verdict = runs.regress_records(new, base, rel_tol=args.rel_tol)
    name_new, name_base = (os.path.basename(new_path),
                           os.path.basename(base_path))
    print(f"regress {name_new} vs baseline {name_base}: "
          f"{verdict['checked']} watched metrics checked")
    if verdict["env_mismatch"]:
        print(f"  WARNING: environment mismatch on "
              f"{', '.join(verdict['env_mismatch'])} — numbers are not "
              "comparable across hosts/backends; regressions downgraded "
              "to warnings")
    if verdict.get("kind_mismatch"):
        print(f"  note: comparing kind={new.get('kind')!r} against "
              f"kind={base.get('kind')!r} — only their shared metrics "
              "are checked")
    for r in verdict["regressions"]:
        tag = "warning" if verdict["env_mismatch"] else "REGRESSION"
        arrow = "↑" if r["better"] == "lower" else "↓"
        print(f"  {tag}: {r['metric']} {arrow} {_fmt_v(r['base'])} -> "
              f"{_fmt_v(r['new'])} (worse by {_fmt_v(r['worsening'])}, "
              f"threshold {_fmt_v(r['threshold'])})")
        if not verdict["env_mismatch"]:
            log_event("regression_detected", metric=r["metric"],
                      base=r["base"], new=r["new"],
                      threshold=r["threshold"], baseline=name_base,
                      record=name_new)
    for r in verdict["improvements"]:
        print(f"  improved: {r['metric']} {_fmt_v(r['base'])} -> "
              f"{_fmt_v(r['new'])}")
    if verdict["ok"]:
        print("  ok: no regressions"
              + (" gated (env mismatch)" if verdict["env_mismatch"]
                 and verdict["regressions"] else ""))
        return 0
    print(f"  FAILED: {len(verdict['regressions'])} regression(s)",
          file=sys.stderr)
    return 1


def _cmd_runs_ingest(args):
    from raft_tpu.obs import runs

    d = _runs_store(args)
    n = 0
    for path in args.files:
        try:
            record = runs.ingest_bench(path)
        except (OSError, ValueError) as e:
            print(f"skip {path}: {e}", file=sys.stderr)
            continue
        out = runs.write_record(record, d)
        print(f"{os.path.basename(path)} -> {os.path.basename(out)} "
              f"({len(runs.flatten(record))} metrics)")
        n += 1
    return 0 if n or not args.files else 2


def _cmd_runs_pin(args):
    from raft_tpu.obs import runs

    d = _runs_store(args)
    try:
        pin = runs.pin_baseline(args.record, d)
    except (OSError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    print(f"pinned {os.path.basename(args.record)} as baseline ({pin})")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("report", help="summarize one RAFT_TPU_LOG capture")
    p.add_argument("jsonl", nargs="+",
                   help="captured JSONL stream(s), or a capture directory "
                        "with --merge")
    p.add_argument("--merge", action="store_true",
                   help="assemble several per-process shards onto one "
                        "wall-clock timeline (proc_start anchors)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="'json' emits every report section machine-"
                        "readably (the run-record 'report' payload)")
    p.add_argument("--tail", nargs="?", const=0.95, default=None,
                   type=float, metavar="RANK",
                   help="render THE request at this latency rank "
                        "(default p95): its exemplar identity, stage "
                        "decomposition and full span tree")

    p = sub.add_parser("trace",
                       help="export a capture as Chrome trace events")
    p.add_argument("jsonl", nargs="+",
                   help="captured JSONL stream(s), or a capture directory "
                        "with --merge")
    p.add_argument("-o", "--output", default="trace.json",
                   help="output path (default trace.json)")
    p.add_argument("--merge", action="store_true",
                   help="assemble several per-process shards onto one "
                        "wall-clock timeline (proc_start anchors)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 on unmatched or orphan spans (CI gate "
                        "for cross-process trace propagation)")

    sub.add_parser("events", help="list the registered event schema")
    sub.add_parser("spans", help="list the registered span names")

    p = sub.add_parser("flight",
                       help="black-box flight recorder: dump this "
                            "process's ring, validate/summarize shards "
                            "(raft_tpu.obs.flight)")
    fsub = p.add_subparsers(dest="flight_cmd", required=True)

    f = fsub.add_parser("dump", help="persist the in-process ring as "
                                     "one schema-versioned JSONL shard")
    f.add_argument("-o", "--output", default=None,
                   help="output path (default: RAFT_TPU_FLIGHT_DIR/"
                        "flight-<pid>-<trigger>.jsonl)")
    f.add_argument("--trigger", default="manual",
                   help="trigger tag for the header + filename "
                        "(default manual)")

    f = fsub.add_parser("show",
                        help="strictly validate + summarize one dump "
                             "shard (exit 1 on a damaged/truncated/"
                             "newer-schema shard — the lint.sh gate)")
    f.add_argument("shard", help="a flight-*.jsonl dump shard")

    p = sub.add_parser("alerts",
                       help="alert-rule engine: list/check the rule "
                            "pack, replay it against stored run "
                            "records (raft_tpu.obs.alerts)")
    asub = p.add_subparsers(dest="alerts_cmd", required=True)

    a = asub.add_parser("list", help="print the effective rule pack "
                                     "(default + RAFT_TPU_ALERT_RULES/"
                                     "--rules)")
    a.add_argument("--rules", default=None,
                   help="YAML/JSON rule file over the default pack")
    a.add_argument("--sink", default=None,
                   help="also summarize a RAFT_TPU_ALERTS JSONL sink")

    a = asub.add_parser("check", help="validate the rule pack "
                                      "(exit 1 on an invalid rule — "
                                      "the lint.sh gate)")
    a.add_argument("--rules", default=None)

    a = asub.add_parser(
        "eval",
        help="replay the rule pack against a stored run record (no "
             "jax, no live fleet; exit 1 when any rule fires)")
    a.add_argument("--record", required=True,
                   help="a run-record .json from the RAFT_TPU_RUNS_DIR "
                        "store (or a checked-in fixture)")
    a.add_argument("--rules", default=None)

    p = sub.add_parser("runs",
                       help="longitudinal run-record store + regression "
                            "sentinel (RAFT_TPU_RUNS_DIR)")
    rsub = p.add_subparsers(dest="runs_cmd", required=True)

    r = rsub.add_parser("record", help="append one run record from the "
                                       "current process state")
    r.add_argument("--kind", default="manual")
    r.add_argument("--label", default=None)
    r.add_argument("--events", nargs="+", default=None,
                   help="fold a RAFT_TPU_LOG capture's report sections "
                        "into the record (machine-readable obs report)")
    r.add_argument("--extra-json", default=None,
                   help="JSON object of extra scalar metrics")
    r.add_argument("--dir", default=None)

    r = rsub.add_parser("list", help="list the stored run trajectory")
    r.add_argument("--dir", default=None)

    r = rsub.add_parser("compare", help="per-metric deltas of two records")
    r.add_argument("new")
    r.add_argument("baseline")
    r.add_argument("--changed-only", action="store_true")

    r = rsub.add_parser(
        "regress",
        help="gate a record against the pinned baseline (exit 1 on "
             "regression; env mismatch downgrades to warnings)")
    r.add_argument("record", nargs="?", default=None,
                   help="record to judge (default: newest in the store)")
    r.add_argument("--baseline", default=None,
                   help="baseline record (default: the pinned one)")
    r.add_argument("--dir", default=None)
    r.add_argument("--rel-tol", type=float, default=None,
                   help="override RAFT_TPU_RUNS_REL_TOL")
    r.add_argument("--check", action="store_true",
                   help="CI alias: identical gating, spelled explicitly "
                        "in lint.sh")

    r = rsub.add_parser("ingest",
                        help="import BENCH_rNN.json artifacts as records")
    r.add_argument("files", nargs="+")
    r.add_argument("--dir", default=None)

    r = rsub.add_parser("pin", help="pin one record as THE baseline")
    r.add_argument("record")
    r.add_argument("--dir", default=None)

    args = ap.parse_args(argv)
    if args.cmd == "runs":
        return {"record": _cmd_runs_record, "list": _cmd_runs_list,
                "compare": _cmd_runs_compare, "regress": _cmd_runs_regress,
                "ingest": _cmd_runs_ingest,
                "pin": _cmd_runs_pin}[args.runs_cmd](args)
    if args.cmd == "alerts":
        return {"list": _cmd_alerts_list, "check": _cmd_alerts_check,
                "eval": _cmd_alerts_eval}[args.alerts_cmd](args)
    if args.cmd == "flight":
        return {"dump": _cmd_flight_dump,
                "show": _cmd_flight_show}[args.flight_cmd](args)
    return {"report": _cmd_report, "trace": _cmd_trace,
            "events": _cmd_events, "spans": _cmd_spans}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
