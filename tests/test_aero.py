"""Rotor BEMT validation vs the reference's CCBlade golden values.

The reference's aero comes from CCBlade (Fortran BEM with hand-coded
adjoints); ours is an independent jax BEMT using the same Ning (2014)
residual formulation, CCBlade's load-integration scheme (trapezoid over
the element stations, no end padding) and cubic-equivalent polar
interpolation.  Agreement on thrust/torque is ~1% across the operating
schedule including +/-45 deg yaw misalignment (the residual is the
Fortran solver's internals, not reachable without CCBlade in-image);
this test gates at 2% with a scale-aware denominator so the feathered
near-zero-torque cut-out cases are included rather than excluded.
"""

import os
import pickle

import numpy as np
import pytest
import yaml

from tests.conftest import ref_data

import jax.numpy as jnp
from raft_tpu.ops import transforms as tf
from raft_tpu.physics.aero import build_rotor_aero, operating_point, rotor_loads


@pytest.fixture(scope="module")
def rotor_and_golden():
    path = ref_data("IEA15MW.yaml")
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    d = yaml.safe_load(open(path))
    t = d["turbine"]
    t["nrotors"] = 1
    t["rho_air"] = d["site"]["rho_air"]
    t["mu_air"] = d["site"]["mu_air"]
    t["shearExp_air"] = d["site"].get("shearExp_air", d["site"].get("shearExp", 0.12))
    rot = build_rotor_aero(t)
    with open(ref_data("IEA15MW_true_calcAero-yaw_mode0.pkl"), "rb") as f:
        true = pickle.load(f)
    return rot, true


@pytest.mark.slow
def test_hub_loads_vs_ccblade(rotor_and_golden):
    rot, true = rotor_and_golden
    tilt = -6 * np.pi / 180
    overhang = -12.0313

    # all TI=0 yaw_mode-0 cases: sorted speeds x headings (test_rotor.py:102-127)
    speeds = sorted([5, 15, 25, 10.59])
    headings = [-45, 0, 45]
    idx = 0
    worst = 0.0
    for ws in speeds:
        for wh in headings:
            for ti in [0, 0.5]:
                case = true[idx]["case"]
                assert case["wind_speed"] == ws and case["wind_heading"] == wh
                if ti == 0:
                    yaw = np.radians(wh)
                    R = np.asarray(tf.rotation_matrix(0.0, -tilt, yaw))
                    q = R @ np.array([1.0, 0, 0])
                    yaw_mis = np.arctan2(q[1], q[0]) - np.radians(wh)
                    tt = np.arctan2(q[2], np.hypot(q[0], q[1]))
                    Om, pit = operating_point(rot, ws)
                    loads = np.asarray(
                        rotor_loads(rot, float(ws), float(Om), float(pit),
                                    -float(tt), float(yaw_mis))
                    )
                    f0 = np.zeros(6)
                    f0[:3] = R @ loads[:3]
                    f0[3:] = R @ loads[3:]
                    f0 = np.asarray(
                        tf.transform_force_6(jnp.asarray(f0), jnp.asarray(q * overhang))
                    )
                    g = true[idx]["f_aero0"]
                    # dominant channels: thrust-driven forces + shaft torque,
                    # with a scale-aware denominator (torque crosses zero
                    # near feather at high yaw, where rel error diverges)
                    scale = 0.02 * np.max(np.abs(g))
                    for comp in (0, 3):
                        rel = abs(f0[comp] - g[comp]) / (abs(g[comp]) + scale)
                        worst = max(worst, rel)
                        assert rel < 0.03, (ws, wh, comp, rel, f0[comp], g[comp])
                idx += 1
    print(f"worst thrust/torque relative deviation vs CCBlade: {worst:.3f}")
