"""Seeded violations for the jit-static rule: config-like parameters
traced instead of declared static."""

import jax
import jax.numpy as jnp


def evaluate(x, mode, n_iter):
    y = jnp.sin(x)
    for _ in range(3):
        y = y + x
    return y


bad = jax.jit(lambda x, out_keys: x)                   # line 15: lambda
bad_named = jax.jit(evaluate)                          # line 16: named def
good = jax.jit(evaluate, static_argnames=("mode", "n_iter"))
good_arrays = jax.jit(lambda x, y: x + y)
