"""CLI for the telemetry subsystem (pure stdlib, no jax).

    python -m raft_tpu.obs report <run.jsonl>
    python -m raft_tpu.obs trace  <run.jsonl> -o trace.json
    python -m raft_tpu.obs events

``report`` prints the per-stage wall-time tree, counter table and
reliability summary of one ``RAFT_TPU_LOG`` capture; ``trace`` exports
it as Chrome/Perfetto trace-event JSON (load in ``chrome://tracing``
or https://ui.perfetto.dev); ``events`` lists the registered event
schema.  Exit codes: 0 ok, 2 usage/input error.
"""

from __future__ import annotations

import argparse
import json
import sys


def _load(path):
    from raft_tpu.obs import report

    try:
        events, bad = report.read_events(path)
    except OSError as e:
        print(f"cannot read {path}: {e}", file=sys.stderr)
        raise SystemExit(2)
    if not events:
        print(f"{path}: no parseable events (was RAFT_TPU_LOG pointed "
              "here during the run?)", file=sys.stderr)
        raise SystemExit(2)
    return events, bad


def _cmd_report(args):
    from raft_tpu.obs import report

    events, bad = _load(args.jsonl)
    sys.stdout.write(report.render_report(events, bad, source=args.jsonl))
    return 0


def _cmd_trace(args):
    from raft_tpu.obs import report

    events, bad = _load(args.jsonl)
    trace = report.chrome_trace(events)
    with open(args.output, "w") as f:
        json.dump(trace, f)
    meta = trace["otherData"]
    print(f"{args.output}: {len(trace['traceEvents'])} trace events "
          f"({meta['spans_matched']} spans"
          + (f", {meta['spans_unmatched']} unmatched" if
             meta["spans_unmatched"] else "")
          + (f"; {bad} unparseable lines skipped" if bad else "")
          + ") — open in chrome://tracing or ui.perfetto.dev")
    return 0


def _cmd_events(_args):
    from raft_tpu.obs import events as ev

    for name, fields, help_ in ev.describe():
        print(f"{name:32s} {', '.join(fields):56s} {help_}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.obs")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("report", help="summarize one RAFT_TPU_LOG capture")
    p.add_argument("jsonl", help="path to the captured JSONL event stream")

    p = sub.add_parser("trace",
                       help="export a capture as Chrome trace events")
    p.add_argument("jsonl", help="path to the captured JSONL event stream")
    p.add_argument("-o", "--output", default="trace.json",
                   help="output path (default trace.json)")

    sub.add_parser("events", help="list the registered event schema")

    args = ap.parse_args(argv)
    return {"report": _cmd_report, "trace": _cmd_trace,
            "events": _cmd_events}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
