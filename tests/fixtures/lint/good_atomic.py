"""Positives for the ``atomic-write`` rule: every sanctioned idiom."""

import json
import os

import numpy as np


def tmp_replace(path, rec):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:           # tmp + os.replace in-function
        json.dump(rec, f)
    os.replace(tmp, path)


def excl_claim(path, rec):
    fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    with os.fdopen(fd, "w") as f:       # O_CREAT|O_EXCL claim
        json.dump(rec, f)


def _atomic_write(path, writer):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:          # the sanctioned helper itself
        writer(f)
    os.replace(tmp, path)


def delegated(path, arrays):
    _atomic_write(path, lambda f: np.savez(f, **arrays))  # via helper


def append_log(path, line):
    with open(path, "a") as f:          # append: the audited exception
        f.write(line)
