"""Optimizer-facing design-evaluation API tests (openmdao-free path)."""

import pytest

import os

import numpy as np


pytestmark = pytest.mark.slow

def test_design_evaluation_compute():
    from raft_tpu.omdao import DesignEvaluation

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "raft_tpu", "designs", "spar_demo.yaml")
    ev = DesignEvaluation(path)
    out = ev.compute()
    assert out["properties_total_mass"] > 1e6
    assert out["properties_displacement"] > 1e3
    assert len(out["rigid_body_periods"]) == 6
    assert out["Max_Offset"] >= 0
    assert "stats_pitch_std_case0_fowt0" in out

    # an override must change the result (longer mooring -> softer surge)
    out2 = ev.compute({"mooring.lines.0.length": 920.0})
    assert out2["stats_surge_max_case0_fowt0"] != out["stats_surge_max_case0_fowt0"]


def test_design_evaluation_farm_traced_routing():
    """Farm designs route through make_farm_evaluator (the evaluator's
    own 1e-9 Xi parity vs the host path is pinned in
    test_farm_evaluator.py; this covers the DesignEvaluation glue:
    per-FOWT slicing + per-FOWT turbine constants into the shared
    stats pipeline)."""
    from raft_tpu.omdao import DesignEvaluation

    path = "/root/reference/tests/test_data/VolturnUS-S_farm.yaml"
    if not os.path.exists(path):
        pytest.skip("reference data unavailable")
    ev = DesignEvaluation(path)
    out = ev.compute()
    assert ev._fast[1] is not None, "farm traced path must engage"
    # both units produce stats; they differ (different positions/moorings)
    a = out["stats_surge_std_case0_fowt0"]
    b = out["stats_surge_std_case0_fowt1"]
    assert np.isfinite(a) and np.isfinite(b) and a > 0
    assert out["Max_Offset"] > 0


def test_design_evaluation_traced_parity_and_speed():
    """The traced fast path (VERDICT r4 #7): DesignEvaluation.compute
    routes repeat calls through api.make_full_evaluator.  Pins

    * metric parity vs the orchestrated host path (the oracle) at
      evaluator-parity level, and
    * repeat-call latency >= 10x faster than the host path's
      analyze_cases.
    """
    import time

    from raft_tpu.omdao import DesignEvaluation

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "raft_tpu", "designs", "spar_demo.yaml")

    ev_fast = DesignEvaluation(path, use_traced=True)
    ev_host = DesignEvaluation(path, use_traced=False)
    out_f = ev_fast.compute()     # includes jit compile
    assert ev_fast._fast[1] is not None, "traced path must engage"
    out_h = ev_host.compute()

    for key, vh in out_h.items():
        vf = out_f[key]
        scale = np.max(np.abs(np.asarray(vh))) + 1e-9
        np.testing.assert_allclose(
            np.asarray(vf), np.asarray(vh), atol=5e-6 * scale, rtol=0,
            err_msg=key)

    # repeat-call latency: traced path is compiled now; host path pays
    # the orchestrated per-case chain every call
    t0 = time.perf_counter()
    ev_fast.compute()
    t_fast = time.perf_counter() - t0
    t0 = time.perf_counter()
    ev_host.compute(overrides={"settings.nIter": ev_host.base_design[
        "settings"].get("nIter", 15)})  # force a host-path re-evaluation
    t_host = time.perf_counter() - t0
    assert t_host / max(t_fast, 1e-9) > 10, (t_fast, t_host)
