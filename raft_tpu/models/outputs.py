"""Response post-processing: per-case channel statistics.

Equivalent of ``FOWT.saveTurbineOutputs``
(``/root/reference/raft/raft_fowt.py:2291-2744``) for rigid FOWTs:
platform motion statistics, nacelle accelerations, rigid-tower base
bending moment, mooring tension spectra, and the wave reference PSD.
Statistics RMS-sum across excitation sources (wave headings + rotor).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.ops import transforms as tf
from raft_tpu.ops.waves import get_psd, get_rms
from raft_tpu.physics.mooring import mooring_force
from raft_tpu.physics.statics import member_inertia

RAD2DEG = 57.29577951308232


def _chan(results, name, avg, amps, dw):
    std = get_rms(amps)
    results[f"{name}_avg"] = avg
    results[f"{name}_std"] = std
    results[f"{name}_max"] = avg + 3 * std
    results[f"{name}_min"] = avg - 3 * std
    results[f"{name}_PSD"] = get_psd(amps, dw, axis=0)
    results[f"{name}_RA"] = amps


def mooring_tension_vector(ms, r6):
    """[T_endA..., T_endB...] per line — MoorPy getTensions layout
    (end A = anchor for the supported designs)."""
    _, info = mooring_force(ms, r6)
    TA = jnp.sqrt(info["HA"] ** 2 + info["VA"] ** 2)
    TB = jnp.sqrt(info["HF"] ** 2 + info["VF"] ** 2)
    return jnp.concatenate([TA, TB])


def _tension_stats_fn(ms, dx=0.1):
    """Jitted (T_mean, J) of the platform pose for one MooringSystem,
    built once and cached on the system: the 13 catenary evaluations of
    the secant Jacobian re-traced on every output call otherwise
    (~1.6 s/trace on CPU — it dominated turbine_outputs).

    The Jacobian uses the same 0.1-step central secant as MoorPy's
    getCoupledStiffness(tensions=True) (including the 0.1-*rad*
    rotational step), replicated for parity."""
    fn = getattr(ms, "_tension_stats_jit", None)
    if fn is None:
        def tension_and_jacobian(x6):
            f = lambda x: mooring_tension_vector(ms, x)
            eye = jnp.eye(6) * dx
            J = jnp.stack(
                [(f(x6 + eye[j]) - f(x6 - eye[j])) / (2 * dx)
                 for j in range(6)], axis=1)
            return f(x6), J

        fn = jax.jit(tension_and_jacobian)
        ms._tension_stats_jit = fn
    return fn


def write_modes_json(model, filename, fns, modes, ifowt=0):
    """Eigenmode JSON for viz3Danim (FOWT.write_modes_json equivalent,
    raft_fowt.py:2889-3070): real structural nodes plus virtual nodes
    along rigid members' stations, element connectivity with diameters,
    and per-mode nodal displacements mapped through the reduction T."""
    import json

    fs = model.fowtList[ifowt]
    T = np.asarray(fs.T)
    node_r0 = np.asarray(fs.node_r0)

    nodes = [list(map(float, r)) for r in node_r0]
    connectivity = []
    elem_props = []
    virtual = {}  # virtual node idx -> (real node id, offset)

    for im, mem in enumerate(fs.members):
        n0 = int(fs.member_node[im])
        if mem.mtype == "rigid":
            stations_r = [mem.rA0 + mem.q0 * s for s in mem.stations]
            prev = n0
            for i in range(len(mem.stations) - 1):
                rB = stations_r[i + 1]
                nodes.append(list(map(float, rB)))
                n2 = len(nodes) - 1
                virtual[n2] = (n0, rB - node_r0[n0])
                if i == 0:
                    n1 = n0
                else:
                    n1 = prev
                connectivity.append([int(n1), int(n2)])
                d = 0.5 * (np.max(mem.d[i]) + np.max(mem.d[i + 1]))
                elem_props.append({"shape": "cylinder", "type": 1,
                                   "Diam": float(d)})
                prev = n2
        else:  # beam: strip nodes are real structural nodes
            for i in range(mem.ns - 1):
                connectivity.append([n0 + i, n0 + i + 1])
                if mem.dorsl_node_ext is not None:
                    d = 0.5 * (np.max(mem.dorsl_node_ext[i])
                               + np.max(mem.dorsl_node_ext[i + 1]))
                else:
                    d = float(np.max(mem.d))
                elem_props.append({"shape": "cylinder", "type": 1,
                                   "Diam": float(d)})

    modes_list = []
    for i in range(modes.shape[1]):
        full = T @ np.asarray(modes[:, i])
        displ = []
        for idx in range(len(nodes)):
            if idx < len(node_r0):
                displ.append([float(full[6 * idx + k]) for k in range(3)])
            else:
                nid, off = virtual[idx]
                t = full[6 * nid:6 * nid + 3]
                rot = full[6 * nid + 3:6 * nid + 6]
                displ.append(list(map(float, t + np.cross(rot, off))))
        modes_list.append({"name": f"FEM{i+1}",
                           "frequency": float(fns[i]),
                           "omega": float(fns[i] * 2 * np.pi),
                           "Displ": displ})

    doc = {
        "writer": "raft_tpu",
        "fileKind": "Modes",
        "groundLevel": float(fs.depth),
        "Connectivity": connectivity,
        "Nodes": nodes,
        "ElemProps": elem_props,
        "Modes": modes_list,
    }
    with open(filename, "w") as f:
        json.dump(doc, f)


def turbine_outputs(model, case, X0, Xi, S, zeta, A_aero=None, B_aero=None,
                    f_aero0=None, ifowt=0, rotor_info=None):
    """Channel statistics for one case and one FOWT.

    Xi : (nWaves+1, nDOF, nw) response amplitudes of THIS FOWT (last
    row = rotor excitation source); X0 : (nDOF,) its mean offsets.
    """
    fs = model.fowtList[ifowt]
    w = jnp.asarray(model.w)
    dw = float(model.w[1] - model.w[0])
    results = {}

    Xi = jnp.asarray(Xi)
    X0 = jnp.asarray(X0)

    # PRP motions: the root node sits at the origin for the supported
    # topologies, so reduced DOFs are PRP motions directly; mean offsets
    # are relative to the FOWT's array reference position
    Xi_PRP = Xi
    ref = jnp.zeros(X0.shape[0]).at[0].set(fs.x_ref).at[1].set(fs.y_ref)
    Xi0_PRP = X0 - ref

    _chan(results, "surge", Xi0_PRP[0], Xi_PRP[:, 0, :], dw)
    _chan(results, "sway", Xi0_PRP[1], Xi_PRP[:, 1, :], dw)
    _chan(results, "heave", Xi0_PRP[2], Xi_PRP[:, 2, :], dw)
    _chan(results, "roll", RAD2DEG * Xi0_PRP[3], RAD2DEG * Xi_PRP[:, 3, :], dw)
    _chan(results, "pitch", RAD2DEG * Xi0_PRP[4], RAD2DEG * Xi_PRP[:, 4, :], dw)
    _chan(results, "yaw", RAD2DEG * Xi0_PRP[5], RAD2DEG * Xi_PRP[:, 5, :], dw)

    # ----- mooring tensions (raft_fowt.py:2356-2399): quasi-static
    # tension Jacobian for moorMod 0, lumped-mass line dynamics for
    # moorMod 1/2
    ms = model.ms_list[ifowt]
    if ms is not None and getattr(ms, "moorMod", 0) >= 1 \
            and getattr(ms, "m_lin", None) is not None:
        from raft_tpu.physics.mooring_dynamics import fowt_line_tension_amps

        T_mean = mooring_tension_vector(ms, X0[:6])
        nL = ms.n_lines
        nWp1 = Xi.shape[0]
        T_amps = np.zeros((nWp1, 2 * nL, model.nw), dtype=np.complex128)
        beta = np.atleast_1d(np.deg2rad(np.asarray(
            case.get("wave_heading", 0.0), dtype=float)))
        S_arr = np.atleast_2d(np.asarray(S))
        for ih in range(nWp1 - 1):   # wave sources only (reference parity)
            T_amps[ih] = fowt_line_tension_amps(
                ms, np.asarray(X0[:6]), np.asarray(Xi[ih, :6, :]),
                model.w, model.k, S_arr[min(ih, len(S_arr) - 1)],
                float(beta[min(ih, len(beta) - 1)]), model.depth,
                rho=fs.rho_water, g=fs.g)
        T_std = np.sqrt(0.5 * np.sum(np.abs(T_amps) ** 2, axis=(0, 2)))
        results["Tmoor_avg"] = T_mean
        results["Tmoor_std"] = jnp.asarray(T_std)
        results["Tmoor_max"] = T_mean + 3 * T_std
        results["Tmoor_min"] = T_mean - 3 * T_std
        dwf = float(model.w[1] - model.w[0])
        results["Tmoor_PSD"] = jnp.asarray(
            np.sum(0.5 * np.abs(T_amps) ** 2 / dwf, axis=0))
    elif ms is not None:
        T_mean, J = _tension_stats_fn(ms)(X0[:6])
        T_amps = jnp.einsum("tj,hjw->htw", J, Xi_PRP[:, :6, :])
        T_std = jnp.sqrt(0.5 * jnp.sum(jnp.abs(T_amps) ** 2, axis=(0, 2)))
        results["Tmoor_avg"] = T_mean
        results["Tmoor_std"] = T_std
        results["Tmoor_max"] = T_mean + 3 * T_std
        results["Tmoor_min"] = T_mean - 3 * T_std
        results["Tmoor_PSD"] = jnp.sum(0.5 * jnp.abs(T_amps) ** 2 / dw, axis=0)

    # ----- nacelle accelerations (raft_fowt.py:2401-2444)
    nrot = fs.nrotors
    for key in ("AxRNA", "AyRNA", "AzRNA"):
        for suf in ("std", "avg", "max", "min"):
            results[f"{key}_{suf}"] = np.zeros(nrot)
        results[f"{key}_PSD"] = np.zeros((model.nw, nrot))
    results["Mbase_avg"] = np.zeros(nrot)
    results["Mbase_std"] = np.zeros(nrot)
    results["Mbase_PSD"] = np.zeros((model.nw, nrot))
    results["Mbase_max"] = np.zeros(nrot)
    results["Mbase_min"] = np.zeros(nrot)

    stat = model.statics(ifowt)
    g = fs.g
    for ir in range(nrot):
        rot = fs.rotors[ir]
        node = int(fs.rotor_node[ir])
        # hub motion from the rigid-body transform of the rotor node
        d = jnp.asarray(fs.node_r0[node])  # reference lever (zero pose)
        H = tf.skew(d + Xi0_PRP[:3] * 0)   # reference uses current r; equal here
        XiHub = jnp.einsum("ia,haw->hiw", model.hydro[ifowt].Tn[node], Xi_PRP)

        for ax, key in enumerate(("AxRNA", "AyRNA", "AzRNA")):
            amps = XiHub[:, ax, :] * w**2
            results[f"{key}_std"] = results[f"{key}_std"].copy()
            results[f"{key}_std"][ir] = float(get_rms(amps))
            results[f"{key}_PSD"][:, ir] = np.asarray(get_psd(amps, dw, axis=0))
            if key == "AxRNA":
                avg = abs(float(jnp.sin(X0[4])) * g)
            elif key == "AyRNA":
                avg = abs(float(jnp.sin(X0[3])) * g)
            else:
                avg = abs(g)
            results[f"{key}_avg"][ir] = avg
            results[f"{key}_max"][ir] = avg + 3 * results[f"{key}_std"][ir]
            results[f"{key}_min"][ir] = avg - 3 * results[f"{key}_std"][ir]

        # ----- rigid tower base bending moment (raft_fowt.py:2504-2538)
        tower_idx = [i for i, m in enumerate(fs.members) if m.part_of == "tower"]
        if not tower_idx:
            continue
        mem_tower = fs.members[tower_idx[ir]]
        if mem_tower.mtype != "rigid":
            # flexible towers: internal loads at the base node from the
            # FE stiffness matrix (raft_fowt.py:2541-2604); Mbase_* is
            # aliased to the fore-aft component MbaseY (:2599-2604)
            from raft_tpu.physics.beams import fe_stiffness

            n0 = int(fs.member_node[tower_idx[ir]])
            nn = mem_tower.ns
            r_tow = np.asarray(model.hydro[ifowt].r_nodes)[n0:n0 + nn]
            Kf = fe_stiffness(mem_tower, r_tow)
            Tn_tow = np.asarray(model.hydro[ifowt].Tn)[n0:n0 + nn].reshape(6 * nn, -1)
            Xi0_int = Tn_tow @ np.asarray(X0)
            Xi_int = np.einsum("fa,haw->hfw", Tn_tow, np.asarray(Xi))
            Fi0 = -Kf @ Xi0_int
            Fi = -np.einsum("fe,hew->hfw", Kf, Xi_int)
            base = slice(0, 6) if r_tow[0, 2] <= r_tow[-1, 2] else slice(6 * nn - 6, 6 * nn)
            Fi0_b = Fi0[base]
            Fi_b = Fi[:, base, :]
            names = ["FbaseX", "FbaseY", "FbaseZ", "MbaseX", "MbaseY", "MbaseZ"]
            for c, nm in enumerate(names):
                std = float(get_rms(Fi_b[:, c, :]))
                results.setdefault(f"{nm}_avg", np.zeros(nrot))
                results.setdefault(f"{nm}_std", np.zeros(nrot))
                results.setdefault(f"{nm}_PSD", np.zeros((model.nw, nrot)))
                results.setdefault(f"{nm}_max", np.zeros(nrot))
                results.setdefault(f"{nm}_min", np.zeros(nrot))
                results[f"{nm}_avg"][ir] = Fi0_b[c]
                results[f"{nm}_std"][ir] = std
                results[f"{nm}_PSD"][:, ir] = np.asarray(get_psd(Fi_b[:, c, :], dw, axis=0))
                results[f"{nm}_max"][ir] = Fi0_b[c] + 3 * std
                results[f"{nm}_min"][ir] = Fi0_b[c] - 3 * std
            for suf in ("avg", "std", "max", "min"):
                results[f"Mbase_{suf}"][ir] = results[f"MbaseY_{suf}"][ir]
            results["Mbase_PSD"][:, ir] = results["MbaseY_PSD"][:, ir]
            continue
        mtower = float(stat["mtower"][ir])
        rCG_tow = np.asarray(stat["rCG_tow"][ir])
        m_turb = mtower + rot.mRNA
        zCG = (rCG_tow[2] * mtower + rot.r_rel[2] * rot.mRNA) / m_turb
        # tower base elevation at the DISPLACED pose (reference uses
        # mem.rA which tracks the mean offset, raft_fowt.py:2512)
        zBase = float(model.hydro[ifowt].r_nodes[int(fs.member_node[tower_idx[ir]])][2])
        hArm = zCG - zBase

        M6_tow, _, _, _ = member_inertia(
            mem_tower, jnp.asarray(mem_tower.R0), jnp.asarray(mem_tower.q0)
        )
        node_tow = int(fs.member_node[tower_idx[ir]])
        ICG = float(
            tf.translate_matrix_6to6(
                M6_tow, jnp.asarray(fs.node_r0[node_tow] - np.array([0, 0, zCG]))
            )[4, 4]
        ) + rot.mRNA * (rot.r_rel[2] - zCG) ** 2 + rot.IrRNA

        aCG = -(w**2) * (Xi_PRP[:, 0, :] + zCG * Xi_PRP[:, 4, :])
        M_I = -m_turb * aCG * hArm - ICG * (-(w**2) * Xi_PRP[:, 4, :])
        M_w = m_turb * g * hArm * Xi_PRP[:, 4, :]
        if A_aero is not None:
            # A_aero/B_aero: (nrotors, nw) fore-aft coefficients at the hub
            M_X_aero = -(
                -(w**2) * jnp.asarray(A_aero[ir]) + 1j * w * jnp.asarray(B_aero[ir])
            ) * (rot.r_rel[2] - zBase) ** 2 * Xi_PRP[:, 4, :]
        else:
            M_X_aero = 0.0
        dyn_moment = M_I + M_w + M_X_aero
        Mrms = float(get_rms(dyn_moment))
        Mavg = m_turb * g * hArm * float(jnp.sin(X0[4]))
        if f_aero0 is not None:
            # reduced mean rotor force mapped back to the rotor node
            # (raft_fowt.py:2533-2534 uses node.T @ f_aero0)
            f6 = np.asarray(model.hydro[ifowt].Tn[node]) @ np.asarray(f_aero0)[:, ir]
            Mavg += float(
                tf.transform_force_6(jnp.asarray(f6), jnp.asarray([0.0, 0.0, -hArm]))[4]
            )
        results["Mbase_avg"][ir] = Mavg
        results["Mbase_std"][ir] = Mrms
        results["Mbase_PSD"][:, ir] = np.asarray(get_psd(dyn_moment, dw, axis=0))
        results["Mbase_max"][ir] = Mavg + 3 * Mrms
        results["Mbase_min"][ir] = Mavg - 3 * Mrms

    # wave elevation PSD (raft_fowt.py:2608)
    results["wave_PSD"] = get_psd(jnp.asarray(zeta), dw, axis=0)

    # ----- rotor response channels (raft_fowt.py:2609-2688): rotor
    # azimuth/speed/torque/blade-pitch spectra through the control
    # transfer function C applied to the hub fore-aft motion, with the
    # turbulence inflow V_w driving the rotor-excitation source row
    for key in ("omega", "torque", "bPitch"):
        for suf in ("avg", "std", "max", "min"):
            results[f"{key}_{suf}"] = np.zeros(nrot)
        results[f"{key}_PSD"] = np.zeros((model.nw, nrot))
    results["power_avg"] = np.zeros(nrot)
    # per-rotor columns (the reference overwrites one (nw,) array per
    # rotor, raft_fowt.py:2679, losing all but the last rotor)
    results["wind_PSD"] = np.zeros((model.nw, nrot))
    RADPS2RPM = 60.0 / (2 * np.pi)
    for ir in range(nrot):
        ri = rotor_info[ir] if rotor_info else None
        if ri is not None and ri.get("cavitation") is not None:
            # per-(blade, element) cavitation margins; negative =
            # cavitation occurs (raft_fowt.py:2680-2683)
            results["cavitation"] = np.asarray(ri["cavitation"])
        if ri is None or ri.get("aeroServoMod", 0) <= 1 or ri.get("speed", 0) <= 0:
            continue
        node = int(fs.rotor_node[ir])
        XiHub = jnp.einsum("ia,haw->hiw", model.hydro[ifowt].Tn[node], Xi)[:, 0, :]
        C = jnp.asarray(ri["C"])  # (nw,)
        V_w = jnp.asarray(ri["V_w"])
        phi_w = C[None, :] * XiHub
        phi_w = phi_w.at[-1].set(C * (XiHub[-1] - V_w / (1j * w)))
        omega_w = 1j * w * phi_w
        torque_w = (1j * w * ri["kp_tau"] + ri["ki_tau"]) * phi_w
        bPitch_w = (1j * w * ri["kp_beta"] + ri["ki_beta"]) * phi_w

        results["omega_avg"][ir] = ri["Omega_rpm"]
        results["omega_std"][ir] = RADPS2RPM * float(get_rms(omega_w))
        # note the reference's 2-sigma band for rotor speed (raft_fowt.py:2656)
        results["omega_max"][ir] = results["omega_avg"][ir] + 2 * results["omega_std"][ir]
        results["omega_min"][ir] = results["omega_avg"][ir] - 2 * results["omega_std"][ir]
        results["omega_PSD"][:, ir] = RADPS2RPM**2 * np.asarray(
            get_psd(omega_w, dw, axis=0))

        Ng = ri.get("Ng", 1.0) or 1.0
        results["torque_avg"][ir] = ri["aero_torque"] / Ng
        results["torque_std"][ir] = float(get_rms(torque_w))
        results["torque_PSD"][:, ir] = np.asarray(get_psd(torque_w, dw, axis=0))

        results["power_avg"][ir] = ri["aero_power"]

        results["bPitch_avg"][ir] = ri["pitch_deg"]
        results["bPitch_std"][ir] = RAD2DEG * float(get_rms(bPitch_w))
        results["bPitch_PSD"][:, ir] = RAD2DEG**2 * np.asarray(
            get_psd(bPitch_w, dw, axis=0))

        results["wind_PSD"][:, ir] = np.asarray(get_psd(V_w, dw))
    return results
