"""Unit tests for the concurrency-invariant analyzer
(:mod:`raft_tpu.analysis.concurrency`) and the cross-process schema
contract engine (:mod:`raft_tpu.analysis.schemas`): every rule on
seeded good/bad fixtures, the repo-clean CI gates, the checked-in
schema baseline round-trip, and the CLI exit codes.

Pure host-side AST — no jax import, no backend, no compiles.
"""

import ast
import json
import os
import subprocess
import sys

import pytest

from raft_tpu.analysis import concurrency, lint, schemas

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
FIXTURES = os.path.join(HERE, "fixtures", "lint")


def run_fixture(name):
    return concurrency.analyze_paths([os.path.join(FIXTURES, name)])


# ------------------------------------------------------------ atomic-write


def test_bad_atomic_fixture():
    found = run_fixture("bad_atomic.py")
    assert {f.rule for f in found} == {"atomic-write"}
    assert {f.line for f in found} == {9, 14, 18}
    assert any("np.save" in f.message for f in found)


def test_good_atomic_fixture_clean():
    """tmp+os.replace, O_CREAT|O_EXCL, delegation to a sanctioned
    atomic writer and append-mode sinks are all exempt."""
    assert run_fixture("good_atomic.py") == []


# ---------------------------------------------------------- async-blocking


def test_bad_async_fixture():
    found = run_fixture("bad_async.py")
    by_rule = {f.rule for f in found}
    assert by_rule == {"async-blocking"}
    # six direct primitives + the taint through the sync helper; the
    # `clean` coroutine (asyncio.sleep, bounded acquire, str.join,
    # run_in_executor handoff) contributes nothing
    assert {f.line for f in found} == {10, 11, 12, 13, 14, 15, 23}
    transitive = [f for f in found if f.line == 23]
    assert "_blocking_helper" in transitive[0].message
    assert "time.sleep" in transitive[0].message


def test_async_fixture_suppression_covers_other_rules():
    """The fixture's open() carries a disable=atomic-write suppression:
    the shared suppression syntax works across the new engine too."""
    found = run_fixture("bad_async.py")
    assert not [f for f in found if f.rule == "atomic-write"]


# ---------------------------------------------------------- lock-discipline


def test_bad_lock_fixture():
    found = run_fixture("bad_lock.py")
    assert {f.rule for f in found} == {"lock-discipline"}
    # module-global item write + mutator call, instance item write +
    # augmented assign; the with-lock twins and the read are clean
    assert {f.line for f in found} == {15, 16, 35, 36}
    assert any("REGISTRY.pop" in f.message for f in found)
    assert any("self._bytes" in f.message for f in found)


def test_guard_annotations_parsed_from_runtime_modules():
    """The real shared-state modules declare their guards inline; the
    analyzer must pick them up (metrics registry + cache shown here)."""
    info = concurrency._load_module(
        os.path.join(REPO, "raft_tpu", "obs", "metrics.py"))
    assert info.module_guards.get("_REGISTRY")[0] == "_REGISTRY_LOCK"
    assert info.instance_guards.get(("Histogram", "count"))[0] \
        == "self._lock"
    info = concurrency._load_module(
        os.path.join(REPO, "raft_tpu", "serve", "batcher.py"))
    assert info.instance_guards.get(("Batcher", "_pending"))[0] \
        == "self._cond"


def test_lock_exemption_is_per_target(tmp_path):
    """An annotation for one name must not excuse unlocked mutations of
    a DIFFERENT guarded name in the same function (review finding)."""
    p = tmp_path / "percy.py"
    p.write_text(
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.hits = 0  # raft-lint: guarded-by=self._lock\n"
        "    def reset(self):\n"
        "        self._items = {}  # raft-lint: guarded-by=self._lock\n"
        "        self.hits = 0\n")
    found = concurrency.analyze_paths([str(p)])
    locks = [f for f in found if f.rule == "lock-discipline"]
    # reset()'s own annotation exempts _items, NOT hits
    assert [f.line for f in locks] == [8], "\n".join(
        f.format() for f in found)


def test_atomic_exemption_ignores_nested_defs(tmp_path):
    """An os.replace inside a nested (never-run-here) helper must not
    excuse a torn write in the enclosing function (review finding)."""
    p = tmp_path / "torn.py"
    p.write_text(
        "import json, os\n"
        "def outer(path, rec):\n"
        "    with open(path, 'w') as f:\n"
        "        json.dump(rec, f)\n"
        "    def helper(a, b):\n"
        "        os.replace(a, b)\n"
        "    return helper\n")
    found = concurrency.analyze_paths([str(p)])
    assert [(f.rule, f.line) for f in found] == [("atomic-write", 3)], \
        "\n".join(f.format() for f in found)


# ---------------------------------------------------------- thread-hygiene


def test_bad_thread_fixture():
    found = run_fixture("bad_thread.py")
    assert {f.rule for f in found} == {"thread-hygiene"}
    assert {f.line for f in found} == {7, 12, 14}
    # the anonymous spawn trips daemon, name AND join-path
    assert sum(1 for f in found if f.line == 7) == 3
    assert any("no stop/join path" in f.message for f in found)
    # GoodSampler and spawn_joined are hygienic — no findings past 14
    assert max(f.line for f in found) == 14


# ------------------------------------------------------------ repo CI gates


def test_repo_concurrency_clean():
    """The CI gate: the audited tree has zero concurrency findings
    (every historical hit — torn metrics export, blocking serve
    shutdown — is fixed, not suppressed)."""
    found = concurrency.analyze_paths()
    assert found == [], "\n".join(f.format() for f in found)


def test_no_blanket_suppressions_in_runtime_modules():
    """Acceptance: the gates land green without file-level disables of
    the new rules anywhere in the runtime package."""
    new_rules = set(concurrency.RULES)
    for path in lint.default_paths():
        with open(path, encoding="utf-8") as f:
            sup = lint._Suppressions(f.read())
        hit = sup.file_level & (new_rules | {"all"})
        assert not hit, f"{path}: file-level suppression of {hit}"


def test_blocking_taint_reaches_through_helpers():
    """metrics.export does file IO; the propagation must classify it
    blocking so async callers are caught (the PR's real finding)."""
    modules = {}
    for p in lint.default_paths():
        info = concurrency._load_module(p)
        modules[info.display] = info
    blocking, funcs = concurrency._propagate_blocking(modules)
    assert ("raft_tpu/obs/metrics.py", "export") in blocking
    assert ("raft_tpu/obs/runs.py", "maybe_record") in blocking
    # structlog is the audited allowlisted exception
    assert ("raft_tpu/utils/structlog.py", "log_event") not in blocking


# ------------------------------------------------------- schema contracts


def test_schema_repo_contracts_clean():
    violations, contracts = schemas.run_checks()
    assert violations == [], "\n".join(violations)
    assert set(contracts) == {f.name for f in schemas.FAMILIES}


def test_schema_lease_contract_content():
    """Spot-check the extraction against known fabric.py ground truth."""
    fam = next(f for f in schemas.FAMILIES if f.name == "lease")
    contract = schemas.extract_family(fam)
    assert contract["written"]["renewed_t"] == "always"
    assert contract["written"]["token"] == "always"
    # trace ids only ride along inside an active span
    assert contract["written"]["trace_id"] == "conditional"
    # every lease read is .get-defaulted (steals must survive a
    # half-written lease)
    assert set(contract["read"].values()) == {"optional"}


def test_schema_kwargs_writer_call_sites():
    """done-record keys come from write_done call sites: `rows` is at
    every site (always), `wall_s` only on the computed path."""
    fam = next(f for f in schemas.FAMILIES if f.name == "done-record")
    contract = schemas.extract_family(fam)
    assert contract["written"]["rows"] == "always"
    assert contract["written"]["wall_s"] == "conditional"
    assert contract["written"]["worker"] == "always"  # setdefault


def test_schema_required_vs_guarded_subscript():
    """run-record: load_record hard-requires `schema`; flatten's
    `record["wall_s"]` is presence-guarded and must stay optional."""
    fam = next(f for f in schemas.FAMILIES if f.name == "run-record")
    contract = schemas.extract_family(fam)
    assert contract["read"]["schema"] == "required"
    assert contract["written"]["schema"] == "always"
    assert contract["read"]["wall_s"] == "optional"


def test_schema_fixture_catches_drift():
    violations, _ = schemas.run_fixture_checks()
    assert len(violations) == 2, violations
    assert any("read-never-written" in v and "renewed_t" in v
               for v in violations)
    assert any("required-but-conditional" in v and "ttl_s" in v
               for v in violations)


def test_schema_baseline_roundtrip(tmp_path):
    """--write regenerates a baseline identical to the checked-in one,
    and a mutated contract is caught as baseline drift."""
    contracts = schemas.extract_all()
    p = tmp_path / "schema_baseline.json"
    schemas.write_baseline(contracts, str(p))
    assert json.loads(p.read_text()) == schemas.load_baseline()
    assert schemas.baseline_violations(contracts,
                                       schemas.load_baseline()) == []
    mutated = json.loads(json.dumps(contracts))
    mutated["lease"]["written"].pop("renewed_t")
    drift = schemas.baseline_violations(mutated, schemas.load_baseline())
    assert any("renewed_t" in v for v in drift)


# --------------------------------------------------- registered-unused


def test_registered_unused_repo_clean():
    found = lint.registered_unused()
    assert found == [], "\n".join(f.format() for f in found)


def test_usage_collector_literals():
    src = (
        'log_event("shard_done", shard=1)\n'
        'with span("shard", shard=1):\n'
        '    pass\n'
        'rec = {"event": "proc_start", "t": 0}\n'
        'config.get("FABRIC_TTL_S")\n'
        'config.env_name("WORKER_ID")\n'
    )
    c = lint._UsageCollector()
    c.visit(ast.parse(src))
    assert c.events == {"shard_done", "proc_start"}
    assert c.spans == {"shard"}
    assert c.flags == {"FABRIC_TTL_S", "WORKER_ID"}


# ------------------------------------------------------------- CLI gates


# NB: the exit-0 clean-tree CLI paths are exercised by lint.sh (and
# in-process above); only the exit-1 negatives need a subprocess here —
# tier-1 wall is within ~20s of its budget, every second counts.
@pytest.mark.parametrize("args,expected", [
    (["concurrency", os.path.join(FIXTURES, "bad_lock.py")], 1),
    (["schemas", "--fixture"], 1),                           # drift drill
])
def test_cli_exit_codes(args, expected):
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == expected, p.stdout + p.stderr
    if args == ["schemas", "--fixture"]:
        assert "renewed_t" in p.stdout
