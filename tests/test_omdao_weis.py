"""WEIS flat-I/O contract test: replay the EXACT option/input set WEIS
hands RAFT (captured by the reference's DEBUG_OMDAO dump into
weis_options.yaml / weis_inputs.yaml) through the openmdao-free
RAFT_OMDAO_Core and check the flat outputs.

This is the reference's own test_omdao_VolturnUS-S.py scenario without
the openmdao dependency (absent in this image): the ~150 flat inputs ->
nested design rebuild -> analyze -> flat outputs chain is identical.
"""

import os

import numpy as np
import pytest
import yaml

from tests.conftest import ref_data

from raft_tpu.omdao import RAFT_OMDAO_Core

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def weis_fixture():
    opt_path = ref_data("weis_options.yaml")
    in_path = ref_data("weis_inputs.yaml")
    if not (os.path.exists(opt_path) and os.path.exists(in_path)):
        pytest.skip("WEIS captured fixtures unavailable")
    opt = yaml.load(open(opt_path), Loader=yaml.FullLoader)
    inputs = yaml.load(open(in_path), Loader=yaml.FullLoader)
    return opt, inputs


def test_weis_replay(weis_fixture):
    opt, inputs = weis_fixture
    core = RAFT_OMDAO_Core(
        modeling_options=opt["modeling_options"],
        analysis_options=opt["analysis_options"],
        turbine_options=opt["turbine_options"],
        mooring_options=opt["mooring_options"],
        member_options=opt["member_options"])

    design = core.build_design(inputs)
    # the rebuilt nested design mirrors the VolturnUS-S yaml family
    assert len(design["platform"]["members"]) == opt["member_options"]["nmembers"]
    assert len(design["mooring"]["lines"]) == opt["mooring_options"]["nlines"]
    assert design["turbine"]["nBlades"] == 3
    assert len(design["cases"]["data"]) >= 1

    outputs = core.compute(inputs)

    # platform properties in the VolturnUS-S ballpark (15MW semi)
    assert 1e7 < outputs["properties_substructure mass"] < 2e7
    assert outputs["Max_Offset"] > 0
    assert 0 < outputs["Max_PtfmPitch"] < 15
    assert outputs["rigid_body_periods"].shape == (6,)
    assert outputs["surge_period"] > outputs["heave_period"]  # soft surge
    assert np.all(np.isfinite(outputs["platform_I_total"]))
    assert outputs["stats_pitch_std"].size == len(design["cases"]["data"])
    # rotor speed channels: the WEIS flat contract carries no
    # aeroServoMod switch, so the rebuilt design uses the default
    # (mod 1, no control TFs) exactly like the reference -> omega std 0
    # and rotor_overspeed == -1.0 by the aggregate formula
    if "rotor_overspeed" in outputs:
        assert outputs["rotor_overspeed"] >= -1.0
