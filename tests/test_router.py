"""Serving-fleet tests: fast socket-free units for the hash ring,
breaker state machine, retry/hedge decision logic, fleet lease ledger
and client backoff (tier-1), plus the slow-tier subprocess drills —
kill-a-replica under live load with zero dropped responses, and the
join/drain ladder."""

import asyncio
import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DESIGNS = os.path.join(ROOT, "raft_tpu", "designs")


# ------------------------------------------------------------- hash ring


def test_hash_ring_stability_add_remove_moves_only_own_keys():
    from raft_tpu.serve.router import HashRing

    ring = HashRing(vnodes=64)
    for rid in ("r0", "r1", "r2"):
        ring.add(rid)
    keys = [f"sig{i % 3}|design:{i}" for i in range(200)]
    before = {k: ring.owners(k)[0] for k in keys}
    # removing r1 must not move any key r1 did not own
    ring.remove("r1")
    after = {k: ring.owners(k)[0] for k in keys}
    assert len(ring) == 2 and "r1" not in ring
    for k in keys:
        if before[k] != "r1":
            assert after[k] == before[k], k
        else:
            assert after[k] in ("r0", "r2")
    # re-adding restores exactly the old ownership (hash positions are
    # content-derived, not arrival-ordered)
    ring.add("r1")
    assert {k: ring.owners(k)[0] for k in keys} == before
    # owner order is distinct and covers the membership
    owners = ring.owners(keys[0])
    assert sorted(owners) == ["r0", "r1", "r2"]
    assert len(set(owners)) == 3


def test_hash_ring_distribution_and_empty():
    from raft_tpu.serve.router import HashRing

    ring = HashRing(vnodes=64)
    assert ring.owners("anything") == []
    ring.add("a")
    ring.add("b")
    counts = {"a": 0, "b": 0}
    for i in range(400):
        counts[ring.owners(f"key{i}")[0]] += 1
    # vnodes keep the split sane (not 95/5)
    assert min(counts.values()) > 80, counts


def test_routing_key_sig_and_content_hash():
    from raft_tpu.serve.router import routing_key

    designs = {"spar": {"sig": "abc123", "fingerprint": "fp-spar"}}
    k1 = routing_key({"design": "spar", "Hs": 4.0}, designs)
    k2 = routing_key({"design": "spar", "Hs": 9.0}, designs)
    assert k1 == k2 == "abc123|fp-spar"  # case scalars don't move keys
    # unknown design still routes deterministically (replica 404s)
    assert routing_key({"design": "nope"}, designs) == "|design:nope"
    # inline designs route by content hash: same body = same replica
    d = {"type": "spar", "depth": 200.0}
    ka = routing_key({"design_inline": d}, designs)
    kb = routing_key({"design_inline": dict(d)}, designs)
    kc = routing_key({"design_inline": {**d, "depth": 210.0}}, designs)
    assert ka == kb and ka != kc and ka.startswith("|inline:")


# -------------------------------------------------------------- breaker


def test_breaker_state_machine():
    from raft_tpu.serve.router import Breaker

    clock = [0.0]
    b = Breaker(fails=3, cooldown_s=5.0, clock=lambda: clock[0])
    assert b.state == "closed" and b.allow()
    assert b.record_failure() is None
    assert b.record_failure() is None
    assert b.state == "closed"
    assert b.record_failure() == "open"      # 3rd consecutive opens
    assert b.state == "open" and not b.allow()
    assert 0 < b.retry_after_s() <= 5.0
    clock[0] += 5.0
    assert b.state == "half_open"
    assert b.allow()                          # ONE half-open trial
    assert not b.allow()                      # second trial refused
    assert b.record_failure() == "open"       # failed trial re-opens
    assert b.state == "open"
    clock[0] += 5.0
    assert b.allow()
    assert b.record_success() == "close"      # trial success closes
    assert b.state == "closed" and b.retry_after_s() == 0.0
    # a success resets the consecutive-failure count
    b.record_failure()
    b.record_failure()
    assert b.record_success() is None
    assert b.record_failure() is None and b.state == "closed"


def test_breaker_release_trial_returns_half_open_slot():
    from raft_tpu.serve.router import Breaker

    clock = [0.0]
    b = Breaker(fails=1, cooldown_s=1.0, clock=lambda: clock[0])
    b.record_failure()
    clock[0] += 1.0
    assert b.state == "half_open" and b.allow() and not b.allow()
    # a cancelled attempt (hedge loser) gives the trial slot back
    # without recording an outcome — the breaker must not wedge
    b.release_trial()
    assert b.allow()
    assert b.record_success() == "close"


def test_failover_retry_after_only_for_same_replica(tmp_path, monkeypatch):
    """A draining replica's Retry-After must not stall the failover to
    a DIFFERENT healthy replica — only a same-replica re-try honors
    it."""
    router = _mk_router(tmp_path, monkeypatch,
                        RAFT_TPU_ROUTER_RETRIES="3",
                        RAFT_TPU_ROUTER_BACKOFF_MS="10",
                        RAFT_TPU_ROUTER_BACKOFF_CAP_MS="1000",
                        RAFT_TPU_ROUTER_BREAKER_FAILS="10")
    _join_all(router, ["r0", "r1"])
    key = "k"
    owner = router.state.owners(key)[0]
    delays = []

    async def send(rid):
        if rid == owner:
            return 503, {"retry-after": "5"}, {"ok": False}
        return 200, {}, {"ok": True}

    async def record_sleep(d):
        delays.append(d)

    rid, tried, _h, status, _hdrs, _b = asyncio.run(
        router.failover(key, send, sleep=record_sleep))
    assert status == 200 and rid != owner and tried == 2
    # the one backoff before the OTHER replica uses the exponential
    # base (10ms), not the drainer's 5s window
    assert delays == [0.01]


def test_breaker_success_while_closed_no_transition():
    from raft_tpu.serve.router import Breaker

    b = Breaker(fails=2, cooldown_s=1.0, clock=lambda: 0.0)
    assert b.record_success() is None


# ------------------------------------------------------ backoff schedule


def test_backoff_delay_schedule_deterministic():
    from raft_tpu.serve.client import backoff_delay

    # capped exponential, no jitter: exact schedule
    sched = [backoff_delay(a, base_s=0.05, cap_s=2.0) for a in range(8)]
    assert sched == [0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 2.0, 2.0]
    # an explicit server Retry-After wins over the curve (even past cap)
    assert backoff_delay(0, 0.05, 2.0, retry_after_s=3.0) == 3.0
    assert backoff_delay(6, 0.05, 2.0, retry_after_s=0.5) == 2.0
    # jitter scales up to +100%, never below the base delay
    lo = backoff_delay(2, 0.05, 2.0, jitter=lambda: 0.0)
    hi = backoff_delay(2, 0.05, 2.0, jitter=lambda: 0.999)
    assert lo == 0.2 and 0.2 < hi < 0.4


def test_client_retries_honor_retry_after(monkeypatch):
    from raft_tpu.serve.client import ServeClient

    sleeps = []
    c = ServeClient("127.0.0.1", 1, retries=3, backoff_base_s=0.05,
                    backoff_cap_s=2.0, jitter=False,
                    sleep=sleeps.append)
    responses = [(429, {"ok": False, "retry_after_s": 0.7}),
                 (503, {"ok": False}),
                 (200, {"ok": True})]
    calls = []

    def fake_round_trip(method, path, payload=None, headers=None):
        calls.append((method, path))
        return responses[len(calls) - 1]

    monkeypatch.setattr(c, "_round_trip", fake_round_trip)
    code, body = c.request("POST", "/evaluate", {"design": "spar"})
    assert code == 200 and body["ok"]
    assert len(calls) == 3
    # first delay honored the 429's retry_after_s, second fell back to
    # the exponential curve
    assert sleeps == [0.7, 0.1]


def test_client_retries_exhausted_returns_last_reject(monkeypatch):
    from raft_tpu.serve.client import ServeClient

    c = ServeClient("127.0.0.1", 1, retries=2, jitter=False,
                    sleep=lambda _s: None)
    monkeypatch.setattr(c, "_round_trip",
                        lambda *a, **k: (503, {"ok": False}))
    code, _body = c.request("POST", "/evaluate", {})
    assert code == 503
    # retries=0 (the default flag value) never sleeps
    c0 = ServeClient("127.0.0.1", 1, retries=0,
                     sleep=lambda _s: pytest.fail("slept with retries=0"))
    monkeypatch.setattr(c0, "_round_trip",
                        lambda *a, **k: (429, {"ok": False}))
    assert c0.request("GET", "/healthz")[0] == 429


# ------------------------------------------------- failover ladder (async)


def _mk_router(tmp_path, monkeypatch, **flags):
    """A Router wired to a tmp fleet dir with deterministic flags and
    no real sockets (tests drive `failover` with injected send fns)."""
    from raft_tpu.serve.router import Router

    defaults = {"RAFT_TPU_ROUTER_RETRIES": "3",
                "RAFT_TPU_ROUTER_BACKOFF_MS": "1",
                "RAFT_TPU_ROUTER_BACKOFF_CAP_MS": "4",
                "RAFT_TPU_ROUTER_BREAKER_FAILS": "2",
                "RAFT_TPU_ROUTER_BREAKER_COOLDOWN_S": "30",
                "RAFT_TPU_ROUTER_HEDGE_MS": "0"}
    defaults.update(flags)
    for k, v in defaults.items():
        monkeypatch.setenv(k, v)
    router = Router(str(tmp_path), probe_http=False)
    return router


def _join(router, rid, port=1000):
    router.state.apply_membership({rid: {"addr": "127.0.0.1",
                                         "port": port, "designs": {}}})


def _join_all(router, rids):
    router.state.apply_membership(
        {rid: {"addr": "127.0.0.1", "port": 1000 + i, "designs": {}}
         for i, rid in enumerate(rids)})


def test_failover_retries_onto_next_replica(tmp_path, monkeypatch):
    from raft_tpu.serve import wire

    router = _mk_router(tmp_path, monkeypatch)
    _join_all(router, ["r0", "r1", "r2"])
    key = "sig|fp"
    owner = router.state.owners(key)[0]
    attempts = []

    async def send(rid):
        attempts.append(rid)
        if rid == owner:
            raise wire.UpstreamError("connect", "refused")
        return 200, {}, {"ok": True}

    async def no_sleep(_d):
        return None

    rid, tried, hedged, status, _h, body = asyncio.run(
        router.failover(key, send, sleep=no_sleep))
    assert status == 200 and body["ok"] and not hedged
    assert tried == 2
    assert attempts[0] == owner          # affinity owner tried first
    assert rid == attempts[1] != owner   # failover in ring order


def test_failover_5xx_retryable_and_breaker_opens(tmp_path, monkeypatch):
    router = _mk_router(tmp_path, monkeypatch,
                        RAFT_TPU_ROUTER_RETRIES="5")
    _join_all(router, ["r0", "r1"])
    key = "k"
    owner = router.state.owners(key)[0]
    calls = {"r0": 0, "r1": 0}

    async def send(rid):
        calls[rid] += 1
        if rid == owner:
            return 500, {}, {"ok": False}
        return 200, {}, {"ok": True}

    async def no_sleep(_d):
        return None

    rid, _tried, _h, status, _hdrs, _b = asyncio.run(
        router.failover(key, send, sleep=no_sleep))
    assert status == 200 and rid != owner
    # drive the owner's breaker open with a second request (FAILS=2)
    asyncio.run(router.failover(key, send, sleep=no_sleep))
    assert router.state.breaker_states()[owner] == "open"
    # breaker-open owner is skipped entirely now: one attempt, no retry
    calls[owner] = 0
    rid, tried, _h, status, _hdrs, _b = asyncio.run(
        router.failover(key, send, sleep=no_sleep))
    assert status == 200 and tried == 1 and calls[owner] == 0


def test_failover_all_dead_is_503_with_retry_after(tmp_path, monkeypatch):
    from raft_tpu.serve import wire

    router = _mk_router(tmp_path, monkeypatch,
                        RAFT_TPU_ROUTER_RETRIES="2",
                        RAFT_TPU_ROUTER_BREAKER_FAILS="1")

    async def send(rid):
        raise wire.UpstreamError("connect", "refused")

    async def no_sleep(_d):
        return None

    # empty ring: immediate graceful 503
    rid, tried, _h, status, _hdrs, body = asyncio.run(
        router.failover("k", send, sleep=no_sleep))
    assert rid is None and status == 503 and tried == 0
    assert body["reason"] == "no_replicas"
    assert body["retry_after_s"] >= 1.0
    # both replicas dead: ladder exhausts, breakers open, reject
    _join_all(router, ["r0", "r1"])
    rid, tried, _h, status, _hdrs, body = asyncio.run(
        router.failover("k", send, sleep=no_sleep))
    assert rid is None and status == 503 and tried >= 1
    # now every breaker is open -> all_breakers_open without attempts
    rid, tried, _h, status, _hdrs, body = asyncio.run(
        router.failover("k", send, sleep=no_sleep))
    assert rid is None and status == 503 and tried == 0
    assert body["reason"] == "all_breakers_open"


def test_failover_backoff_delays_and_retry_after(tmp_path, monkeypatch):
    router = _mk_router(tmp_path, monkeypatch,
                        RAFT_TPU_ROUTER_RETRIES="3",
                        RAFT_TPU_ROUTER_BACKOFF_MS="100",
                        RAFT_TPU_ROUTER_BACKOFF_CAP_MS="1000",
                        RAFT_TPU_ROUTER_BREAKER_FAILS="10")
    _join(router, "r0")
    delays = []
    n = {"v": 0}

    async def send(rid):
        n["v"] += 1
        if n["v"] < 4:
            # a draining replica: 503 with an explicit Retry-After
            return 503, {"retry-after": "1"}, {"ok": False}
        return 200, {}, {"ok": True}

    async def record_sleep(d):
        delays.append(round(d, 4))

    rid, tried, _h, status, _hdrs, _b = asyncio.run(
        router.failover("k", send, sleep=record_sleep))
    assert status == 200 and tried == 4
    # Retry-After=1s outranks the 0.1/0.2/0.4 exponential curve
    assert delays == [1.0, 1.0, 1.0]


def test_hedge_fires_after_delay_first_good_wins(tmp_path, monkeypatch):
    router = _mk_router(tmp_path, monkeypatch,
                        RAFT_TPU_ROUTER_HEDGE_MS="10")
    _join_all(router, ["r0", "r1"])
    key = "k"
    owner = router.state.owners(key)[0]
    started = []

    async def send(rid):
        started.append(rid)
        if rid == owner:
            await asyncio.sleep(5.0)        # the p99 straggler
            return 200, {}, {"ok": True, "from": "straggler"}
        return 200, {}, {"ok": True, "from": "hedge"}

    t0 = time.monotonic()
    rid, tried, hedged, status, _hdrs, body = asyncio.run(
        router.failover(key, send))
    assert time.monotonic() - t0 < 2.0      # did not wait for straggler
    assert status == 200 and hedged and tried == 1
    assert rid != owner and body["from"] == "hedge"
    assert started == [owner, rid]          # hedge fired second


def test_hedge_not_fired_when_primary_fast(tmp_path, monkeypatch):
    router = _mk_router(tmp_path, monkeypatch,
                        RAFT_TPU_ROUTER_HEDGE_MS="5000")
    _join_all(router, ["r0", "r1"])

    async def send(rid):
        return 200, {}, {"ok": True}

    rid, tried, hedged, status, _hdrs, _b = asyncio.run(
        router.failover("k", send))
    assert status == 200 and not hedged and tried == 1


# ------------------------------------------------------ fleet lease ledger


def test_fleet_lease_claim_renew_expire_evict(tmp_path, monkeypatch):
    from raft_tpu.serve.fleet import FleetLedger

    monkeypatch.setenv("RAFT_TPU_FLEET_TTL_S", "0.4")
    root = str(tmp_path)
    a = FleetLedger(root, replica_id="ra")
    assert a.claim(8001, designs={"spar": {"sig": "s", "fingerprint": "f"}},
                   buckets=["s"], healthz={"draining": False})
    # claim is exclusive per replica id
    a2 = FleetLedger(root, replica_id="ra")
    assert not a2.claim(8002)
    b = FleetLedger(root, replica_id="rb")
    assert b.claim(8003)
    obs = FleetLedger(root)
    assert set(obs.live()) == {"ra", "rb"}
    assert obs.live()["ra"]["port"] == 8001
    assert obs.live()["ra"]["designs"]["spar"]["sig"] == "s"
    # renew keeps a lease alive past its TTL; a silent replica expires
    time.sleep(0.25)
    assert a.renew(healthz={"draining": False, "pending": 0})
    time.sleep(0.25)
    live, expired = obs.live(), obs.expired()
    assert "ra" in live and "rb" not in live
    assert "rb" in expired and expired["rb"][1] > 0.4
    # eviction: exactly one winner, and the loser sees False
    assert obs.evict("rb", reason="expired", age_s=expired["rb"][1])
    assert not obs.evict("rb")
    assert set(obs.replicas()) == {"ra"}
    # release at drain start: lease gone while the process still runs
    assert a.release(reason="drain")
    assert obs.replicas() == {}
    assert not a.renew()      # a released lease is NOT silently re-claimed
    # token guard: a stranger never releases someone else's lease
    assert b.claim(8004)
    stranger = FleetLedger(root, replica_id="rb")
    assert not stranger.release()
    assert set(obs.replicas()) == {"rb"}


def test_fleet_summary_and_router_record(tmp_path, monkeypatch):
    from raft_tpu.serve import fleet

    monkeypatch.setenv("RAFT_TPU_FLEET_TTL_S", "30")
    root = str(tmp_path)
    led = fleet.FleetLedger(root, replica_id="r0")
    led.claim(9000, designs={"spar": {"sig": "s", "fingerprint": "f"}})
    s = led.summary()
    assert s["n_live"] == 1 and s["replicas"]["r0"]["port"] == 9000
    assert s["router"] is None
    fleet.publish_router_record(root, {
        "version": 1, "t": time.time(), "pid": os.getpid(),
        "n_replicas": 1,
        "replicas": {"r0": {"addr": "127.0.0.1", "port": 9000,
                            "designs": ["spar"], "breaker": "closed"}},
        "designs": {"spar": "s"}})
    s = led.summary()
    assert s["router"]["n_replicas"] == 1
    assert s["router"]["replicas"] == ["r0"]


def test_prober_membership_reconciliation(tmp_path, monkeypatch):
    """Socket-free prober pass: joins admit, expiry evicts, the ring
    updates, router.json is published."""
    from raft_tpu.serve import fleet
    from raft_tpu.serve.router import LedgerProber, RouterState

    monkeypatch.setenv("RAFT_TPU_FLEET_TTL_S", "0.3")
    root = str(tmp_path)
    state = RouterState(vnodes=16)
    prober = LedgerProber(root, state, probe_http=False)
    a = fleet.FleetLedger(root, replica_id="ra")
    b = fleet.FleetLedger(root, replica_id="rb")
    a.claim(8001)
    b.claim(8002)
    added, removed = prober.probe_once()
    assert sorted(added) == ["ra", "rb"] and not removed
    assert state.snapshot()["n_replicas"] == 2
    assert state.endpoint("ra") == ("127.0.0.1", 8001)
    rec = fleet.read_router_record(root)
    assert rec["n_replicas"] == 2 and set(rec["replicas"]) == {"ra", "rb"}
    # rb dies (stops renewing): next pass evicts it from ledger + ring
    t0 = time.time()
    while time.time() - t0 < 2.0:
        a.renew()
        if fleet.FleetLedger(root).expired():
            break
        time.sleep(0.05)
    added, removed = prober.probe_once()
    assert removed == ["rb"] and state.snapshot()["n_replicas"] == 1
    assert "rb" not in fleet.FleetLedger(root).replicas()  # evicted
    # a drained replica leaves the ring without eviction machinery
    a.release()
    _added, removed = prober.probe_once()
    assert removed == ["ra"] and state.snapshot()["n_replicas"] == 0


# ------------------------------------------------------ subprocess drills
#
# Everything below spawns real replica servers + the router (slow
# tier).  One module-scoped bank warmup is shared: the fleet contract
# is N replicas from ONE immutable bank, so the tests prove exactly
# that — replicas run RAFT_TPU_AOT=require + RAFT_TPU_COMPILE_BUDGET=0.

SPAR = os.path.join(DESIGNS, "spar_demo.yaml")
MHK = os.path.join(DESIGNS, "mhk_demo.yaml")
#: per-design case pools, small on purpose: repeats are what prove
#: cache affinity under the router
CASES = {
    "spar": [(4.0, 9.0, 0.0), (5.0, 10.0, 0.1), (6.0, 11.0, 0.0),
             (4.5, 9.5, -0.1)],
    "mhk": [(2.0, 7.0, 0.0), (2.5, 8.0, 0.1), (3.0, 9.0, 0.0),
            (3.5, 8.5, -0.1)],
}


@pytest.fixture(scope="module")
def warm_bank(tmp_path_factory):
    """Warm the serve programs for spar+mhk ONCE (ladder 1,2 on one
    device) into a module-shared bank — the fleet deploy artifact."""
    base = tmp_path_factory.mktemp("fleet_bank")
    bank, cache = str(base / "bank"), str(base / "jax_cache")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               RAFT_TPU_SERVE_MAX_BATCH="2",
               RAFT_TPU_AOT="load", RAFT_TPU_AOT_DIR=bank,
               RAFT_TPU_CACHE_DIR=cache)
    for drop in ("RAFT_TPU_LOG", "RAFT_TPU_FAULTS", "RAFT_TPU_AOT_MISS",
                 "RAFT_TPU_COMPILE_BUDGET"):
        env.pop(drop, None)
    proc = subprocess.run(
        [sys.executable, "-m", "raft_tpu.aot", "warmup", "--kinds",
         "serve", "--design", SPAR, "--design", MHK],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    return {"bank": bank, "cache": cache}


def _fleet_env(warm, logdir, extra=None):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               RAFT_TPU_SERVE_TICK_MS="10",
               RAFT_TPU_SERVE_MAX_BATCH="2",
               RAFT_TPU_SERVE_DRAIN_S="20",
               RAFT_TPU_FLEET_TTL_S="2.5",
               RAFT_TPU_AOT="require",
               RAFT_TPU_COMPILE_BUDGET="0",
               RAFT_TPU_AOT_DIR=warm["bank"],
               RAFT_TPU_CACHE_DIR=warm["cache"],
               RAFT_TPU_LOG=str(logdir) + os.sep)
    env.pop("RAFT_TPU_FAULTS", None)
    env.update(extra or {})
    return env


def _spawn_replica(root, rid, env, out_path):
    with open(out_path, "ab") as logf:
        return subprocess.Popen(
            [sys.executable, "-m", "raft_tpu.serve",
             "--designs", f"spar={SPAR}", "--designs", f"mhk={MHK}",
             "--port", "0", "--fleet-dir", str(root),
             "--replica-id", rid],
            cwd=ROOT, env=env, stdout=logf, stderr=subprocess.STDOUT)


def _wait_live(root, rids, procs, deadline_s):
    from raft_tpu.serve.fleet import FleetLedger

    ledger = FleetLedger(str(root))
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        live = ledger.live()
        if set(rids) <= set(live):
            return live
        for rid, p in procs.items():
            if rid in rids and p.poll() is not None:
                raise AssertionError(
                    f"replica {rid} exited rc={p.returncode} before "
                    "joining the fleet")
        time.sleep(0.3)
    raise AssertionError(f"replicas {rids} never all joined: "
                         f"{sorted(ledger.live())}")


def _spawn_router(root, env, extra=None):
    renv = dict(env)
    renv.update({"RAFT_TPU_ROUTER_PROBE_S": "0.4",
                 "RAFT_TPU_ROUTER_RETRIES": "5",
                 "RAFT_TPU_ROUTER_BACKOFF_MS": "25",
                 "RAFT_TPU_ROUTER_BACKOFF_CAP_MS": "400",
                 "RAFT_TPU_ROUTER_TIMEOUT_S": "120",
                 "RAFT_TPU_ROUTER_BREAKER_FAILS": "2",
                 "RAFT_TPU_ROUTER_BREAKER_COOLDOWN_S": "1"})
    renv.update(extra or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "raft_tpu.serve", "router",
         "--fleet-dir", str(root), "--port", "0"],
        cwd=ROOT, env=renv, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    t0 = time.monotonic()
    for line in proc.stdout:
        if "routing" in line and "http://" in line:
            port = int(line.split("http://", 1)[1].split()[0]
                       .rsplit(":", 1)[1])
            return proc, port
        if time.monotonic() - t0 > 120:
            break
    raise AssertionError("router never printed its ready line")


def _wait_router_replicas(port, n, deadline_s=60):
    from raft_tpu.serve.client import ServeClient

    c = ServeClient("127.0.0.1", port, timeout=30)
    t0 = time.monotonic()
    try:
        while time.monotonic() - t0 < deadline_s:
            code, h = c.healthz()
            if code == 200 and h["n_replicas"] == n:
                return h
            time.sleep(0.4)
    finally:
        c.close()
    raise AssertionError(f"router never converged to {n} replicas: {h}")


def _read_fleet_events(logdir):
    events = []
    for name in os.listdir(logdir):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(logdir, name)) as f:
            for line in f:
                try:
                    events.append(json.loads(line))
                except ValueError:
                    pass
    return events


def _terminate_all(procs, timeout=60):
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    rcs = []
    for p in procs:
        try:
            rcs.append(p.wait(timeout=timeout))
        except subprocess.TimeoutExpired:
            p.kill()
            rcs.append(p.wait(timeout=10))
    return rcs


@pytest.mark.slow
def test_kill_a_replica_drill_zero_dropped(warm_bank, tmp_path):
    """THE acceptance drill: 2 replicas under 64 concurrent in-flight
    requests, SIGKILL one mid-burst — every accepted request resolves
    200/422 (zero dropped responses), the dead lease expires and is
    evicted from the ring, a replacement joins from the warm bank with
    zero backend compiles, a drain re-routes mid-flight work, and the
    whole session merges onto one trace with 0 orphan spans.

    PR 14 rides the live fleet-health layer along: the router runs the
    alert engine (RAFT_TPU_ALERT_EVAL_S) + golden canary
    (RAFT_TPU_CANARY_S) — the SIGKILL must fire the breaker-storm
    alert within an eval interval and resolve it after the storm ends,
    the canary must pass on the bank-consistent fleet, and the steady
    phase must fire ZERO alerts (no false positives)."""
    from raft_tpu.serve.client import ServeClient
    from raft_tpu.serve.fleet import FleetLedger
    from raft_tpu.serve.router import HashRing, routing_key

    logdir = tmp_path / "logs"
    logdir.mkdir()
    root = tmp_path / "deploy"
    alert_sink = tmp_path / "alerts.jsonl"
    # canary on fleet-wide: replicas capture their warmup goldens, the
    # router probes; the alert engine runs on the ROUTER only (its
    # registry carries the breaker/eviction counters the pack watches)
    env = _fleet_env(warm_bank, logdir,
                     extra={"RAFT_TPU_CANARY_S": "0.5"})
    router_alert_env = {"RAFT_TPU_ALERT_EVAL_S": "0.25",
                        "RAFT_TPU_ALERTS": str(alert_sink)}
    procs = {}
    results, errors = [], []
    t_kill = None
    try:
        procs["rA"] = _spawn_replica(root, "rA", env,
                                     tmp_path / "rA.out")
        _wait_live(root, {"rA"}, procs, 300)
        # pick the second replica's id so the two designs get DISTINCT
        # ring owners (the test reproduces the router's hash math —
        # both replicas then carry live traffic, and the kill target
        # is deterministic, not a coin flip)
        designs_meta = FleetLedger(str(root)).live()["rA"]["designs"]
        spar_key = routing_key({"design": "spar"}, designs_meta)
        mhk_key = routing_key({"design": "mhk"}, designs_meta)
        victim = None
        for i in range(128):
            trial = HashRing()
            trial.add("rA")
            trial.add(f"rB{i}")
            if trial.owners(spar_key)[0] == f"rB{i}" \
                    and trial.owners(mhk_key)[0] == "rA":
                victim = f"rB{i}"
                break
        assert victim is not None
        survivor = "rA"
        procs[victim] = _spawn_replica(root, victim, env,
                                       tmp_path / "rB.out")
        _wait_live(root, {"rA", victim}, procs, 300)
        router_proc, port = _spawn_router(root, env,
                                          extra=router_alert_env)
        procs["router"] = router_proc
        _wait_router_replicas(port, 2, 60)
        probe = ServeClient("127.0.0.1", port, timeout=60)
        ring = probe.request("GET", "/ring")[1]["ring"]
        assert ring["spar"][0] == victim and ring["mhk"][0] == survivor

        def pool_case(i, j):
            design = ("spar", "mhk")[(i + j) % 2]
            return design, CASES[design][(i * 7 + j) % len(CASES[design])]

        def fresh_case(phase):
            # phase-unique NEVER-cached cases: the kill/drain phases
            # must interrupt REAL in-flight dispatches, not resolve
            # from the result cache before the fault even lands
            def gen(i, j):
                if (i + j) % 2:
                    return "spar", (4.0 + 0.003 * (phase * 1000 + i * 8 + j),
                                    9.0 + 0.001 * i, 0.0)
                return "mhk", (2.0 + 0.003 * (phase * 1000 + i * 8 + j),
                               7.5 + 0.001 * i, 0.0)
            return gen

        def worker(i, n, phase, case_fn):
            cl = ServeClient("127.0.0.1", port, client_id=f"c{phase}-{i}",
                            timeout=300)
            try:
                for j in range(n):
                    design, case = case_fn(i, j)
                    code, body = cl.evaluate(design, *case)
                    results.append(
                        (phase, design, code,
                         bool(isinstance(body, dict)
                              and body.get("cache_hit")),
                         cl.last_headers.get("x-raft-replica")))
            except Exception as e:  # noqa: BLE001 — asserted below
                errors.append((phase, i, repr(e)))
            finally:
                cl.close()

        def run_phase(phase, n_threads, reqs, case_fn=pool_case,
                      kill_after_s=None, kill_proc=None, drain_port=None):
            threads = [threading.Thread(target=worker,
                                        args=(i, reqs, phase, case_fn))
                       for i in range(n_threads)]
            for t in threads:
                t.start()
            if kill_after_s is not None:
                time.sleep(kill_after_s)
                if kill_proc is not None:
                    kill_proc.kill()          # SIGKILL, mid-burst
                if drain_port is not None:
                    dc = ServeClient("127.0.0.1", drain_port, timeout=30)
                    assert dc.request("POST", "/drain")[0] == 202
                    dc.close()
            for t in threads:
                t.join(timeout=600)
            assert not any(t.is_alive() for t in threads)

        # ---- phase 1: steady state — affinity + cache hit rate
        run_phase(1, 16, 6)
        assert not errors, errors
        p1 = [r for r in results if r[0] == 1]
        assert len(p1) == 96
        assert all(code == 200 for (_, _, code, _, _) in p1), \
            [r for r in p1 if r[2] != 200][:3]
        for design in ("spar", "mhk"):
            answered = {r[4] for r in p1 if r[1] == design}
            # affinity: every steady-state request for a design landed
            # on its ring owner — replica caches stay hot
            assert answered == {ring[design][0]}, (design, answered, ring)
        hit_rate = sum(1 for r in p1 if r[3]) / len(p1)
        # the acceptance bar: within 10% of single-server BENCH_r07's
        # 0.72 — the pool engineers ~0.85 ideal; in-tick coalescing of
        # simultaneous duplicates is the honest slack
        assert hit_rate >= 0.648, hit_rate

        # ---- phase 2: SIGKILL the spar owner under 64 in-flight
        # requests — all fresh cases, so every one is a REAL dispatch
        # (a cached row would resolve before the kill even lands)
        t_kill = time.time()   # steady state before this must be alert-free
        run_phase(2, 64, 1, case_fn=fresh_case(2), kill_after_s=0.25,
                  kill_proc=procs[victim])
        assert not errors, errors
        p2 = [r for r in results if r[0] == 2]
        assert len(p2) == 64
        # ZERO dropped responses: every accepted request resolved
        # 200/422 (the router retried the in-flight ones onto the
        # survivor; duplicate dispatch is benign by construction)
        assert all(code in (200, 422) for (_, _, code, _, _) in p2), \
            sorted({code for (_, _, code, _, _) in p2})
        assert procs[victim].wait(timeout=10) == -signal.SIGKILL
        # the dead replica's lease expires (TTL 2.5s) and is evicted
        h = _wait_router_replicas(port, 1, 30)
        assert victim not in h["replicas"]
        assert victim not in FleetLedger(str(root)).replicas()

        # ---- phase 3: replacement joins from the warm bank, zero
        # compiles, zero router restarts
        procs["rC"] = _spawn_replica(root, "rC", env, tmp_path / "rC.out")
        live = _wait_live(root, {"rC"}, procs, 300)
        _wait_router_replicas(port, 2, 60)
        hc = ServeClient("127.0.0.1", live["rC"]["port"], timeout=60)
        code, health = hc.healthz()
        hc.close()
        assert code == 200
        assert health["xla_real_compiles"] == 0
        assert health["aot_programs_compiled"] == 0
        assert health["aot_programs_loaded"] >= 4  # 2 buckets x ladder(1,2)
        run_phase(3, 16, 2)
        assert not errors, errors
        p3 = [r for r in results if r[0] == 3]
        assert all(code in (200, 422) for (_, _, code, _, _) in p3)

        # ---- phase 4: graceful drain of a replica under load — lease
        # released at drain start, router re-routes, accepted work
        # finishes, process exits 0
        drain_port = FleetLedger(str(root)).live()[survivor]["port"]
        run_phase(4, 16, 2, case_fn=fresh_case(4), kill_after_s=0.1,
                  drain_port=drain_port)
        assert not errors, errors
        p4 = [r for r in results if r[0] == 4]
        assert all(code in (200, 422) for (_, _, code, _, _) in p4)
        assert procs[survivor].wait(timeout=60) == 0
        _wait_router_replicas(port, 1, 30)
        probe.close()

        # ---- teardown: SIGTERM the rest; clean exits
        rcs = _terminate_all([procs["rC"], procs["router"]])
        assert rcs == [0, 0], rcs
    finally:
        _terminate_all([p for p in procs.values() if p.poll() is None],
                       timeout=30)

    # ---- capture assertions: the ladder was exercised and registered
    events = _read_fleet_events(logdir)
    names = [e.get("event") for e in events]
    assert names.count("replica_join") >= 3          # rA, rB, rC
    assert names.count("replica_drain") >= 1         # phase-4 drain
    assert names.count("replica_evict") >= 1         # the SIGKILL victim
    assert names.count("router_retry") >= 1
    assert names.count("breaker_open") >= 1
    retries = [e for e in events if e.get("event") == "router_retry"]
    known = {"connect", "dropped", "closed", "timeout", "gone",
             "protocol", "http_500", "http_502", "http_503"}
    assert retries and all(e.get("reason") in known for e in retries), \
        sorted({e.get("reason") for e in retries})
    # ---- live fleet health: the SIGKILL fired breaker-storm (within
    # an eval interval of the first breaker open), the storm RESOLVED
    # once the replacement fleet went quiet, the steady phase fired
    # ZERO false alerts, and the bank-consistent canary stayed green
    from raft_tpu.obs.alerts import read_sink

    records, bad = read_sink(str(alert_sink))
    assert bad == 0
    fires = [r for r in records if r["kind"] == "fire"]
    assert fires, "no alert ever fired across the kill drill"
    assert min(r["t_unix"] for r in fires) >= t_kill - 0.5, \
        ("an alert fired during the steady phase", t_kill, fires)
    assert {r["rule"] for r in fires} <= {"breaker-storm", "lease-churn"}, \
        fires
    storm = [r for r in records if r["rule"] == "breaker-storm"]
    storm_fires = [r["t_unix"] for r in storm if r["kind"] == "fire"]
    storm_resolves = [r["t_unix"] for r in storm if r["kind"] == "resolve"]
    assert storm_fires, "breaker-storm never fired on the SIGKILL"
    assert storm_resolves and min(storm_resolves) > min(storm_fires), \
        ("breaker-storm never resolved after the replacement joined",
         storm)
    assert names.count("alert_fire") >= 1
    assert names.count("alert_resolve") >= 1
    # canary: replicas captured warmup goldens, the router probed every
    # (replica, design) pair, and the bank-consistent fleet never
    # tripped a parity/golden failure
    checks = [e for e in events if e.get("event") == "canary_check"]
    assert checks, "router canary never probed"
    assert all(c.get("ok") for c in checks), \
        [c for c in checks if not c.get("ok")][:3]
    assert names.count("canary_golden") >= 2
    # provenance stamped end to end: routed responses carried the
    # replica's x-raft-provenance, and every stamp agreed on bank+code
    provs = [e for e in events if e.get("event") == "router_request"
             and e.get("provenance")]
    assert provs, "no routed response carried a provenance stamp"
    from raft_tpu.obs.alerts import parse_provenance

    stamped = {(parse_provenance(e["provenance"]) or {}).get("bank_sha")
               for e in provs if e.get("design") == "spar"}
    assert len(stamped) == 1 and "none" not in stamped, stamped
    # ---- one merged timeline, zero orphan spans.  The SIGKILLed
    # victim's shard legitimately carries unmatched span BEGINS (it
    # died mid-span — that is the drill), so the strict balanced-spans
    # --check runs over the surviving processes' shards; the full
    # merge must still resolve every cross-process parent (0 orphans).
    merged = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "trace", "--merge",
         str(logdir), "-o", str(tmp_path / "merged.json")],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert merged.returncode == 0, merged.stdout + merged.stderr
    meta = json.loads((tmp_path / "merged.json").read_text())["otherData"]
    assert meta["spans_orphaned"] == 0, meta
    assert meta["pids"] >= 4, meta          # router + rA/victim/rC
    survivors_dir = tmp_path / "logs_survivors"
    survivors_dir.mkdir()
    victim_shard = f"trace-{procs[victim].pid}.jsonl"
    for name in os.listdir(logdir):
        if name.endswith(".jsonl") and name != victim_shard:
            (survivors_dir / name).write_bytes(
                (logdir / name).read_bytes())
    checked = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "trace", "--merge",
         str(survivors_dir), "-o", str(tmp_path / "merged_ok.json"),
         "--check"],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert checked.returncode == 0, checked.stdout + checked.stderr


@pytest.mark.slow
def test_replica_fault_kinds_drive_failover(warm_bank, tmp_path):
    """The three replica fault kinds drive the ladder deterministically:
    replica_hang -> per-attempt timeout + retry; replica_5xx ->
    retryable 500 + retry; replica_kill -> SIGKILL mid-request +
    failover to the survivor, all invisible to the client."""
    from raft_tpu.serve.client import ServeClient
    from raft_tpu.serve.fleet import FleetLedger
    from raft_tpu.serve.router import HashRing, routing_key

    logdir = tmp_path / "logs"
    logdir.mkdir()
    root = tmp_path / "deploy"
    base_env = _fleet_env(warm_bank, logdir,
                          extra={"RAFT_TPU_SERVE_TIMEOUT_S": "30"})
    procs = []
    try:
        # rF alone owns everything: its armed faults fire in a known
        # order (hang first, then two 5xx) on the first client request
        envF = dict(base_env)
        envF["RAFT_TPU_FAULTS"] = ("replica_hang:serve_evaluate:1,"
                                   "replica_5xx:serve_evaluate:2")
        pF = _spawn_replica(root, "rF", envF, tmp_path / "rF.out")
        procs.append(pF)
        _wait_live(root, {"rF"}, {"rF": pF}, 300)
        router_proc, port = _spawn_router(
            root, base_env, extra={"RAFT_TPU_ROUTER_TIMEOUT_S": "4",
                                   "RAFT_TPU_ROUTER_BREAKER_FAILS": "10"})
        procs.append(router_proc)
        _wait_router_replicas(port, 1, 60)

        c = ServeClient("127.0.0.1", port, client_id="fault", timeout=120)
        code, body = c.evaluate("spar", *CASES["spar"][0])
        # hang (timeout) -> 500 -> 500 -> success, all on one request
        assert code == 200 and body["ok"], (code, body)
        _code, h = c.healthz()
        assert h["router_retries"] >= 3
        assert h["router_upstream_errors"] >= 3

        # ---- replica_kill: pick the joiner's id so it OWNS spar (the
        # test reproduces the router's ring math, so the kill target is
        # deterministic, not a coin flip)
        lease = FleetLedger(str(root)).live()["rF"]
        spar_key = routing_key({"design": "spar"}, lease["designs"])
        kill_id = None
        for i in range(64):
            ring = HashRing()
            ring.add("rF")
            ring.add(f"rK{i}")
            if ring.owners(spar_key)[0] == f"rK{i}":
                kill_id = f"rK{i}"
                break
        assert kill_id is not None
        envK = dict(base_env)
        envK["RAFT_TPU_FAULTS"] = "replica_kill:serve_evaluate:1"
        pK = _spawn_replica(root, kill_id, envK, tmp_path / "rK.out")
        procs.append(pK)
        _wait_live(root, {kill_id}, {kill_id: pK}, 300)
        _wait_router_replicas(port, 2, 60)
        # this request routes to the armed owner, which SIGKILLs itself
        # mid-request; the router fails it over to rF — still a 200
        code, body = c.evaluate("spar", *CASES["spar"][1])
        assert code == 200 and body["ok"], (code, body)
        assert pK.wait(timeout=30) == -signal.SIGKILL
        assert c.last_headers.get("x-raft-replica") == "rF"
        _wait_router_replicas(port, 1, 30)   # lease expired + evicted
        c.close()
    finally:
        _terminate_all([p for p in procs if p.poll() is None],
                       timeout=30)


@pytest.mark.slow
def test_canary_catches_stale_bank_provenance(warm_bank, tmp_path):
    """A provenance-divergent replica trips ``canary_parity`` with the
    offending provenance named in the alert payload.  The divergence
    is injected with the deterministic ``provenance_skew`` fault (the
    drill's stand-in for a genuinely stale-banked / env-skewed
    replica — same bank bytes, skewed reported identity): both
    replicas answer identical NUMBERS, so health bits, breakers and
    the golden compare all stay green — only the cross-replica
    provenance consistency check can see it."""
    import urllib.request

    from raft_tpu.obs.alerts import read_sink
    from raft_tpu.serve.client import ServeClient

    logdir = tmp_path / "logs"
    logdir.mkdir()
    root = tmp_path / "deploy"
    alert_sink = tmp_path / "alerts.jsonl"
    env = _fleet_env(warm_bank, logdir)
    envB = dict(env)
    envB["RAFT_TPU_FAULTS"] = "provenance_skew:serve_provenance:1"
    procs = []
    try:
        pA = _spawn_replica(root, "rA", env, tmp_path / "rA.out")
        procs.append(pA)
        pB = _spawn_replica(root, "rB", envB, tmp_path / "rB.out")
        procs.append(pB)
        _wait_live(root, {"rA", "rB"}, {"rA": pA, "rB": pB}, 300)
        router_proc, port = _spawn_router(
            root, env, extra={"RAFT_TPU_CANARY_S": "0.5",
                              "RAFT_TPU_ALERT_EVAL_S": "0.25",
                              "RAFT_TPU_ALERTS": str(alert_sink)})
        procs.append(router_proc)
        _wait_router_replicas(port, 2, 60)

        # the canary probes both replicas directly; within a few probe
        # + eval periods the provenance split must be firing at /alerts
        deadline = time.monotonic() + 60
        payload = None
        while time.monotonic() - deadline < 0:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/alerts", timeout=10) as r:
                payload = json.loads(r.read())
            active = {a["rule"] for a in payload.get("active") or ()}
            if "canary-parity" in active:
                break
            time.sleep(0.5)
        active = {a["rule"]: a for a in payload.get("active") or ()}
        assert "canary-parity" in active, payload
        # the alert payload names the offending provenance: the split
        # field(s) and the per-replica values, rB carrying the skew
        ctx = active["canary-parity"]["context"]
        splits = ctx["provenance"]["splits"]
        assert splits, ctx
        by_field = {s["field"]: s for s in splits}
        assert "bank_sha" in by_field, by_field
        assert by_field["bank_sha"]["values"]["rB"].startswith("skew"), \
            by_field
        assert not by_field["bank_sha"]["values"]["rA"].startswith("skew")
        # canary summary at the endpoint agrees
        assert payload["canary"]["parity_ok"] is False
        assert payload["canary"]["fails"] >= 1
        # the sink recorded the fire with the same context
        records, bad = read_sink(str(alert_sink))
        assert bad == 0
        parity = [r for r in records
                  if r["rule"] == "canary-parity" and r["kind"] == "fire"]
        assert parity and parity[0]["context"]["provenance"]["splits"]

        # client-visible provenance through the router: the response
        # stamp parses into last_provenance and names the replica that
        # answered (satellite: serve/client.py last_provenance)
        c = ServeClient("127.0.0.1", port, client_id="prov", timeout=120)
        code, _body = c.evaluate("spar", *CASES["spar"][0])
        assert code in (200, 422)
        assert c.last_provenance is not None, c.last_headers
        assert c.last_provenance["replica"] == \
            c.last_headers.get("x-raft-replica")
        assert {"bank_key", "bank_sha", "code", "flags"} <= \
            set(c.last_provenance)
        c.close()
    finally:
        _terminate_all([p for p in procs if p.poll() is None],
                       timeout=30)
    # the merged capture's report renders the INCONSISTENT provenance
    # line and the alerts section (canary failures included)
    events = _read_fleet_events(logdir)
    from raft_tpu.obs.report import report_data

    data = report_data(events)
    assert data["alerts"] is not None
    assert data["alerts"]["canary"]["provenance_failures"] >= 1
    prov = (data["router"] or {}).get("provenance")
    if prov is not None:
        # the router section's consistency verdict (needs routed
        # traffic from BOTH replicas to see the split; the canary
        # section above is the authoritative detector)
        assert "splits" in prov


def test_report_router_section():
    """The obs-report fleet-router table renders from router events."""
    from raft_tpu.obs.report import render_report, report_data

    events = [{"t": 0.0, "event": "proc_start", "unix_t": 0.0,
               "argv0": "x", "pid": 1}]
    for i in range(6):
        events.append({"t": 0.1 * i, "pid": 1, "event": "router_request",
                       "replica": "r0" if i % 2 else "r1", "code": 200,
                       "attempts": 1 + (i == 5), "hedged": False,
                       "design": "spar", "wall_s": 0.01 * (i + 1)})
    events.append({"t": 1.0, "pid": 1, "event": "router_retry",
                   "replica": "r0", "attempt": 1, "reason": "connect",
                   "delay_s": 0.05})
    events.append({"t": 1.1, "pid": 1, "event": "breaker_open",
                   "replica": "r1", "reason": "connect", "fails": 3,
                   "cooldown_s": 5.0})
    events.append({"t": 1.2, "pid": 1, "event": "replica_evict",
                   "replica": "r1", "reason": "expired", "age_s": 2.0,
                   "root": "/tmp/f"})
    data = report_data(events)
    router = data["router"]
    assert router["router_retry"] == 1
    assert router["breaker_open"] == 1 and router["replica_evict"] == 1
    rows = {(r["replica"], r["code"]): r for r in router["replicas"]}
    assert rows[("r0", 200)]["requests"] == 3
    assert rows[("r1", 200)]["requests"] == 3
    text = render_report(events)
    assert "fleet router" in text and "breakers 1 opened" in text
    # no router events -> no section
    assert report_data(events[:1])["router"] is None
