"""Panel mesh generation for circular members (member2pnl equivalent).

Generates quadrilateral panel meshes of the submerged portion of
cylindrical members for the potential-flow solver, mirroring the role
of the reference mesher (``/root/reference/raft/member2pnl.py``:
``meshMember`` :73, side/cap paneling with waterline clipping) with a
simpler regular discretisation, plus a writer for the HAMS ``.pnl``
interchange format the reference's BEM pipeline consumes.
"""

from __future__ import annotations

import numpy as np


def mesh_cylinder(stations, diameters, rA, q, n_az=18, dz_max=2.0):
    """Quad panel mesh of a (possibly tapered) circular member's wetted
    surface, clipped at z = 0; includes a bottom cap.

    stations : (n,) axial positions from end A; diameters : (n,);
    rA : (3,) end-A coordinates; q : (3,) axial unit vector.

    Returns (vertices (P,4,3), centroids (P,3), normals (P,3) outward,
    areas (P,)).
    """
    stations = np.asarray(stations, dtype=float)
    diameters = np.asarray(diameters, dtype=float)
    rA = np.asarray(rA, dtype=float)
    q = np.asarray(q, dtype=float)
    q = q / np.linalg.norm(q)

    # axial subdivision (finer than stations)
    s_grid = [stations[0]]
    for i in range(1, len(stations)):
        seg = stations[i] - stations[i - 1]
        if seg <= 0:
            continue
        nseg = max(1, int(np.ceil(seg / dz_max)))
        s_grid += list(stations[i - 1] + seg * (np.arange(1, nseg + 1) / nseg))
    s_grid = np.asarray(s_grid)
    d_grid = np.interp(s_grid, stations, diameters)

    # local transverse axes
    tmp = np.array([1.0, 0, 0]) if abs(q[2]) > 0.9 else np.array([0, 0, 1.0])
    p1 = np.cross(tmp, q)
    p1 /= np.linalg.norm(p1)
    p2 = np.cross(q, p1)

    th = np.linspace(0, 2 * np.pi, n_az + 1)
    verts, cents, norms, areas = [], [], [], []

    def ring(s, d):
        c = rA + q * s
        return c[None, :] + 0.5 * d * (
            np.cos(th)[:, None] * p1[None, :] + np.sin(th)[:, None] * p2[None, :]
        )

    for i in range(len(s_grid) - 1):
        zA = rA[2] + q[2] * s_grid[i]
        zB = rA[2] + q[2] * s_grid[i + 1]
        if zA >= 0 and zB >= 0:
            continue
        sA, dA = s_grid[i], d_grid[i]
        sB, dB = s_grid[i + 1], d_grid[i + 1]
        # clip the segment at the waterline
        if zB > 0:
            f = (0.0 - zA) / (zB - zA)
            sB = sA + f * (s_grid[i + 1] - s_grid[i])
            dB = dA + f * (d_grid[i + 1] - d_grid[i])
        elif zA > 0:
            f = (0.0 - zB) / (zA - zB)
            sA = sB + f * (s_grid[i] - s_grid[i + 1])
            dA = dB + f * (d_grid[i] - d_grid[i + 1])
        rA_ring = ring(sA, dA)
        rB_ring = ring(sB, dB)
        for k in range(n_az):
            vs = np.array([rA_ring[k], rA_ring[k + 1], rB_ring[k + 1], rB_ring[k]])
            c = vs.mean(axis=0)
            d1 = vs[2] - vs[0]
            d2 = vs[3] - vs[1]
            nvec = np.cross(d1, d2)
            a = 0.5 * np.linalg.norm(nvec)
            if a < 1e-10:
                continue
            nvec = nvec / (2 * a)
            # outward = away from member axis
            axis_pt = rA + q * np.dot(c - rA, q)
            if np.dot(nvec, c - axis_pt) < 0:
                nvec = -nvec
                vs = vs[::-1]
            verts.append(vs)
            cents.append(c)
            norms.append(nvec)
            areas.append(a)

    # bottom cap (triangle fan collapsed to quads), if submerged
    if rA[2] + q[2] * s_grid[0] < 0:
        d0 = d_grid[0]
        c0 = rA + q * s_grid[0]
        ring0 = ring(s_grid[0], d0)
        for k in range(n_az):
            vs = np.array([c0, ring0[k + 1], ring0[k], c0])
            d1 = vs[2] - vs[0]
            d2 = vs[1] - vs[0]
            nvec = np.cross(d1, d2)
            a = 0.5 * np.linalg.norm(nvec)
            if a < 1e-10:
                continue
            nvec = nvec / (2 * a)
            if np.dot(nvec, -q) < 0:  # cap normal points away from body (down)
                nvec = -nvec
                vs = vs[::-1]
            verts.append(vs)
            cents.append(vs.mean(axis=0))
            norms.append(nvec)
            areas.append(a)

    return (np.asarray(verts), np.asarray(cents), np.asarray(norms),
            np.asarray(areas))


def write_pnl(path, vertices, title="raft_tpu panel mesh"):
    """Write panels in the HAMS .pnl format (member2pnl.writeMesh:280)."""
    n = len(vertices)
    with open(path, "w") as f:
        f.write(f"    --------------{title}-----------------\n")
        f.write("    Output the particulars of the panel mesh\n")
        f.write(f"    {n}    0    1    1\n\n")
        for i, quad in enumerate(vertices):
            f.write(f"    {i+1}  4 ")
            for v in quad:
                f.write(f"  {v[0]:.6e} {v[1]:.6e} {v[2]:.6e}")
            f.write("\n")
