"""Telemetry-subsystem tests (:mod:`raft_tpu.obs`).

Fast tier, toy evaluators on a small CPU mesh (no model build):

* span nesting / parent-id propagation, including across the
  checkpointed-sweep path with a resume (pinned ``RAFT_TPU_RUN_ID``
  keeps both runs' events linkable);
* the zero-overhead fast path with ``RAFT_TPU_LOG`` unset;
* metrics-registry thread safety and histogram percentile estimates;
* the metrics snapshot landing in ``metrics.json`` + the sweep
  manifest, and the Prometheus text export;
* Chrome-trace export round-trip (valid JSON, balanced spans) and the
  report CLI on a capture with injected faults;
* the device heartbeat sampler.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.obs import current_ids, metrics, span
from raft_tpu.obs import report as obs_report
from raft_tpu.obs.heartbeat import Heartbeat
from raft_tpu.parallel.sweep import make_mesh, run_sweep_checkpointed_full
from raft_tpu.utils import faults, structlog

from _obs_helpers import read_events as _helper_read_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def toy_full(c):
    return {"PSD": jnp.stack([c["Hs"], c["Tp"], c["Hs"] * c["Tp"]]),
            "X0": c["Hs"] - c["Tp"]}


def _cases(n, seed=0):
    rng = np.random.default_rng(seed)
    return dict(Hs=2.0 + 6.0 * rng.random(n), Tp=8.0 + 8.0 * rng.random(n))


def _events(path, name=None, skip_anchor=True):
    # the shared anchor-aware capture reader (tests/_obs_helpers.py):
    # the proc_start clock anchor every sink opens with is skipped by
    # default; this module's historical call order is (path, name)
    return _helper_read_events(path, skip_anchor=skip_anchor, name=name)


MESH = None


def mesh2():
    global MESH
    if MESH is None:
        MESH = make_mesh(2)
    return MESH


@pytest.fixture
def log_path(tmp_path, monkeypatch):
    p = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", p)
    return p


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


# ------------------------------------------------------------------ spans


def test_span_nesting_and_parent_ids(log_path):
    with span("outer", job=1) as outer:
        with span("inner") as inner:
            structlog.log_event("drag_linearisation", case=0, fowt=0,
                                resid=1e-3, converged=True, n_iter=3,
                                status=0, reason="")
        with span("inner") as inner2:
            pass
    begins = {e["span_id"]: e for e in _events(log_path, "span_begin")}
    assert len(begins) == 3
    bo = begins[outer.span_id]
    bi, bi2 = begins[inner.span_id], begins[inner2.span_id]
    assert bo["parent_id"] is None and bo["name"] == "outer" and bo["job"] == 1
    assert bi["parent_id"] == outer.span_id
    assert bi2["parent_id"] == outer.span_id
    # one trace id for the whole tree, stamped on every record inside
    assert bo["trace_id"] == bi["trace_id"] == bi2["trace_id"]
    (free_ev,) = _events(log_path, "drag_linearisation")
    assert free_ev["span_id"] == inner.span_id
    assert free_ev["trace_id"] == outer.trace_id
    ends = _events(log_path, "span_end")
    assert len(ends) == 3 and all(e["ok"] and "wall_s" in e for e in ends)
    # pid + run_id are stamped on every record
    for e in _events(log_path):
        assert e["pid"] == os.getpid() and e["run_id"]
    # the context is fully unwound
    assert current_ids() is None


def test_span_failure_records_error_and_reraises(log_path):
    with pytest.raises(ValueError, match="boom"):
        with span("failing"):
            raise ValueError("boom")
    (end,) = _events(log_path, "span_end")
    assert end["ok"] is False and "ValueError" in end["error"]
    assert current_ids() is None


def test_zero_overhead_fast_path_when_log_unset(monkeypatch):
    from raft_tpu.analysis import recompile
    from raft_tpu.obs import flight

    monkeypatch.delenv("RAFT_TPU_LOG", raising=False)
    monkeypatch.delenv("RAFT_TPU_PROFILE", raising=False)
    monkeypatch.delenv("RAFT_TPU_FLIGHT_RING", raising=False)
    monkeypatch.delenv("RAFT_TPU_FLIGHT_DIR", raising=False)
    # the propagation path must ride the same fast path: an inherited
    # traceparent is only parsed/adopted when the sink is live
    monkeypatch.setenv("RAFT_TPU_TRACEPARENT",
                       "00-" + "a" * 32 + "-" + "b" * 16 + "-01")
    flight.reset()
    try:
        with span("quiet", x=1) as s:
            # no ids generated, no contextvar touched, nothing emitted
            assert s.span_id is None and current_ids() is None
        assert not structlog.enabled()
        # the wall-time histogram still feeds (metrics are independent
        # of the event stream) — but no event was produced anywhere
        assert metrics.histogram("span_quiet_s").count == 1
        # the always-on flight ring (default size) captured the pair
        # without turning the span path on
        assert [r[0] for r in flight.ring_records()] == ["sb", "se"]
        # and the recorder keeps the fast path µs-cheap and compile-
        # free: a span begin/end pair is two deque appends, no jax
        n = 2000
        with recompile.count_compilations() as clog:
            t0 = time.perf_counter()
            for _ in range(n):
                with span("quiet"):
                    pass
            per_span = (time.perf_counter() - t0) / n
        assert clog.count == 0
        assert per_span < 100e-6, f"span pair cost {per_span * 1e6:.1f}µs"
    finally:
        flight.reset()


# ----------------------------------------------- cross-process propagation


def test_traceparent_parse_format_roundtrip():
    from raft_tpu.obs import spans

    tp = spans.format_traceparent("a" * 16, "b" * 16)
    assert tp == "00-" + "0" * 16 + "a" * 16 + "-" + "b" * 16 + "-01"
    assert spans.parse_traceparent(tp) == ("a" * 16, "b" * 16)
    # foreign 32-hex trace ids keep their full width
    full = "1234567890abcdef" * 2
    assert spans.parse_traceparent(f"00-{full}-{'c' * 16}-01") == \
        (full, "c" * 16)
    # garbage / all-zero ids are "no context", never an exception
    assert spans.parse_traceparent(None) is None
    assert spans.parse_traceparent("nonsense") is None
    assert spans.parse_traceparent(
        "00-" + "0" * 32 + "-" + "b" * 16 + "-01") is None
    assert spans.format_traceparent() is None  # no active span


def test_root_span_adopts_env_traceparent(log_path, monkeypatch):
    from raft_tpu.obs import spans

    monkeypatch.setenv("RAFT_TPU_TRACEPARENT",
                       spans.format_traceparent("feed" * 4, "beef" * 4))
    with span("sweep") as outer:
        assert outer.trace_id == "feed" * 4
        with span("shard") as inner:
            pass
    begins = {e["name"]: e for e in _events(log_path, "span_begin")}
    # the root joined the inherited trace with the remote span as parent
    assert begins["sweep"]["parent_id"] == "beef" * 4
    assert begins["sweep"]["remote_parent"] is True
    # nesting below the root is untouched
    assert begins["shard"]["parent_id"] == outer.span_id
    assert begins["shard"]["trace_id"] == "feed" * 4
    assert "remote_parent" not in begins["shard"]


def test_propagation_env_and_ambient_ids(log_path, monkeypatch):
    from raft_tpu.obs import spans

    monkeypatch.delenv("RAFT_TPU_TRACEPARENT", raising=False)
    monkeypatch.setenv("RAFT_TPU_RUN_ID", "prop01")
    assert spans.ambient_ids() is None
    with span("sweep") as s:
        env = spans.propagation_env()
        assert env["RAFT_TPU_RUN_ID"] == "prop01"
        assert spans.parse_traceparent(env["RAFT_TPU_TRACEPARENT"]) == \
            (s.trace_id, s.span_id)
        assert spans.ambient_ids() == (s.trace_id, s.span_id)
    # outside a span: run id still pinned, inherited context chains
    monkeypatch.setenv("RAFT_TPU_TRACEPARENT",
                       spans.format_traceparent("c" * 16, "d" * 16))
    env = spans.propagation_env()
    assert spans.parse_traceparent(env["RAFT_TPU_TRACEPARENT"]) == \
        ("c" * 16, "d" * 16)
    assert spans.ambient_ids() == ("c" * 16, "d" * 16)


def test_log_directory_shards_per_process(tmp_path, monkeypatch):
    d = tmp_path / "capture"
    monkeypatch.setenv("RAFT_TPU_LOG", str(d) + os.sep)
    structlog.log_event("shard_start", shard=0, rows=4)
    shard_file = d / f"trace-{os.getpid()}.jsonl"
    assert shard_file.exists()
    evs = _events(str(shard_file), skip_anchor=False)
    # the shard opens with the proc_start clock anchor
    assert evs[0]["event"] == "proc_start"
    assert evs[0]["unix_t"] > 1e9 and "argv0" in evs[0]
    assert evs[1]["event"] == "shard_start"


FIXTURE_DIR = os.path.join(REPO, "tests", "fixtures", "obs")


def test_merge_captures_normalizes_clocks():
    evs, bad, info = obs_report.merge_captures([FIXTURE_DIR])
    assert bad == 0 and info["files"] == 2 and not info["unanchored_files"]
    # worker events land ~1.2s after the coordinator on the SHARED
    # clock (unix anchors 1700000000.0 vs 1700000001.2)
    by = {(e["pid"], e["event"]): e["t"] for e in evs}
    assert abs(by[(202, "proc_start")] - 1.2) < 1e-6
    assert by[(101, "span_begin")] < by[(202, "fabric_worker_start")]
    # t is sorted and zero-based
    assert evs[0]["t"] == 0.0
    assert all(a["t"] <= b["t"] for a, b in zip(evs, evs[1:]))
    trace = obs_report.chrome_trace(evs, merged=True)
    meta = trace["otherData"]
    assert meta["spans_matched"] == 4 and meta["spans_unmatched"] == 0
    # the acceptance property: every worker span resolves to its
    # coordinator parent after the merge — no orphans, ONE trace
    assert meta["spans_orphaned"] == 0 and meta["traces"] == 1
    assert meta["pids"] == 2 and meta["run_ids"] == ["fixture01"]


def test_externally_traced_request_is_not_an_orphan():
    """A serve request adopting an HTTP client's traceparent has a
    parent span living in the CLIENT's tracer — --check must not flag
    it.  But the same shape across two captured processes (a worker
    whose coordinator parent SHOULD be in the capture) stays an
    orphan."""
    def span_pair(pid, trace, sid, parent, remote):
        b = {"t": 0.1, "event": "span_begin", "pid": pid, "run_id": "r",
             "trace_id": trace, "span_id": sid, "name": "shard",
             "parent_id": parent}
        if remote:
            b["remote_parent"] = True
        e = {"t": 0.2, "event": "span_end", "pid": pid, "run_id": "r",
             "trace_id": trace, "span_id": sid, "name": "shard",
             "wall_s": 0.1, "ok": True}
        return [b, e]

    # single process, remote parent outside the capture: clean
    evs = span_pair(1, "t1", "s1", "client-span", remote=True)
    assert obs_report.chrome_trace(evs)["otherData"]["spans_orphaned"] == 0
    # two processes share the trace but the parent is missing: orphan
    evs = (span_pair(1, "t1", "s1", "lost-parent", remote=True)
           + span_pair(2, "t1", "s2", "s1", remote=False))
    assert obs_report.chrome_trace(evs)["otherData"]["spans_orphaned"] == 1
    # non-remote dangling parent is always an orphan
    evs = span_pair(1, "t1", "s1", "gone", remote=False)
    assert obs_report.chrome_trace(evs)["otherData"]["spans_orphaned"] == 1


def test_merge_cli_check_gates_orphans(tmp_path):
    out = str(tmp_path / "t.json")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "trace", "--merge",
         FIXTURE_DIR, "-o", out, "--check"],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0, p.stdout + p.stderr
    # a capture whose span parent resolves nowhere must FAIL the check
    broken = tmp_path / "trace-9.jsonl"
    broken.write_text(
        '{"t": 0.0, "event": "proc_start", "pid": 9, "run_id": "r",'
        ' "unix_t": 1700000000.0}\n'
        '{"t": 0.1, "event": "span_begin", "pid": 9, "run_id": "r",'
        ' "trace_id": "t9", "span_id": "s9", "name": "shard",'
        ' "parent_id": "gone"}\n'
        '{"t": 0.2, "event": "span_end", "pid": 9, "run_id": "r",'
        ' "trace_id": "t9", "span_id": "s9", "name": "shard",'
        ' "wall_s": 0.1, "ok": true}\n')
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "trace", "--merge",
         str(broken), "-o", out, "--check"],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 1
    assert "orphan" in p.stderr


def test_sweep_spans_and_run_id_survive_resume(tmp_path, monkeypatch):
    p = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("RAFT_TPU_LOG", p)
    monkeypatch.setenv("RAFT_TPU_RUN_ID", "linkage01")
    cases = _cases(8, seed=1)
    out_dir = str(tmp_path / "sweep")
    out1 = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    faults.truncate_file(os.path.join(out_dir, "shard_0001.npz"))
    out2 = run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                       shard_size=4, mesh=mesh2())
    for k in out1:
        assert np.array_equal(out1[k], out2[k])
    evs = _events(p)
    # both runs share the pinned run id on EVERY record
    assert {e["run_id"] for e in evs} == {"linkage01"}
    spans, unmatched = obs_report.collect_spans(evs)
    assert unmatched == []
    paths, _ = obs_report.span_paths(spans)
    # two sweep roots (run + resume), shards + attempts nested beneath
    assert len(paths[("sweep",)]) == 2
    assert len(paths[("sweep", "shard")]) == 3  # 2 fresh + 1 recomputed
    assert ("sweep", "shard", "shard_attempt") in paths
    # shard events carry the enclosing shard span's ids
    by_id = {s["span_id"]: s for s in spans}
    for e in _events(p, "shard_done"):
        assert by_id[e["span_id"]]["name"] == "shard"


# ---------------------------------------------------------------- metrics


def test_metrics_registry_thread_safety():
    c = metrics.counter("t_conc")
    h = metrics.histogram("t_conc_h")

    def work():
        for i in range(2000):
            c.inc()
            h.observe(i % 7 + 0.5)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8 * 2000
    assert h.count == 8 * 2000
    assert h.min == 0.5 and h.max == 6.5


def test_histogram_percentiles_and_snapshot():
    h = metrics.histogram("t_hist")
    for v in [0.01] * 50 + [0.1] * 45 + [10.0] * 5:
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["min"] == 0.01 and snap["max"] == 10.0
    # log-bucket estimates: p50 lands in the 0.01 bucket, p95 well
    # below the 10.0 outliers' bucket ceiling
    assert snap["p50"] <= 0.02
    assert 0.05 <= snap["p95"] <= 0.2
    assert metrics.histogram("t_empty").snapshot() == {"count": 0}
    assert metrics.histogram("t_empty").percentile(0.5) is None


def test_kind_collision_is_loud():
    metrics.counter("t_kind")
    with pytest.raises(TypeError, match="already registered"):
        metrics.gauge("t_kind")


def test_window_percentiles_and_expiry():
    w = metrics.window("t_win")
    # empty window: None percentiles, zero-count snapshot
    assert w.percentile(0.5) is None
    assert w.snapshot()["count"] == 0
    now = time.perf_counter()
    w.observe(99.0, t=now - 120.0)       # outside the 60s window
    for v in (1.0, 2.0, 3.0, 4.0):
        w.observe(v, t=now - 1.0)
    snap = w.snapshot(now=now)
    assert snap["count"] == 4 and snap["total"] == 5
    assert snap["p50"] in (2.0, 3.0) and snap["p95"] == 4.0
    assert snap["max"] == 4.0            # the expired 99.0 is gone
    # a custom window length re-admits the old sample
    assert w.percentile(1.0, window_s=300.0, now=now) == 99.0
    # everything aged out -> empty again (no stale percentiles)
    assert w.percentile(0.5, window_s=0.5, now=now) is None


def test_window_ring_wraparound():
    w = metrics.Window("t_ring", maxlen=8)
    now = time.perf_counter()
    for i in range(100):
        w.observe(float(i), t=now)
    snap = w.snapshot(now=now)
    # the ring keeps only the newest maxlen samples
    assert snap["count"] == 8 and snap["total"] == 100
    assert w.values(now=now) == [float(i) for i in range(92, 100)]


def test_window_in_snapshot_and_prometheus():
    metrics.window("t_win_prom").observe(0.25)
    snap = metrics.snapshot()
    assert snap["windows"]["t_win_prom"]["count"] == 1
    text = metrics.to_prometheus()
    assert "raft_tpu_t_win_prom_p95 0.25" in text
    assert "raft_tpu_t_win_prom_count 1" in text
    # non-serving processes keep the old snapshot schema
    metrics.reset()
    metrics.counter("t_plain").inc()
    assert "windows" not in metrics.snapshot()


def test_merge_states_edge_cases():
    h = metrics.Histogram("a")
    for v in (0.1, 0.2, 0.4, 3.0):
        h.observe(v)
    st = h.state()
    # empty / garbled states are ignored, not poison
    pooled = metrics.merge_states([None, {}, {"count": 0}, "garbage", st])
    assert pooled.count == 4
    assert pooled.min == 0.1 and pooled.max == 3.0
    # merge-with-self: counts add exactly, extrema/percentile stable
    twice = metrics.merge_states([st, st])
    assert twice.count == 8 and twice.sum == pytest.approx(2 * h.sum)
    assert twice.min == h.min and twice.max == h.max
    assert twice.percentile(0.5) == h.percentile(0.5)
    # disjoint bucket layouts: both contributions survive the pool
    lo = metrics.Histogram("lo")
    hi = metrics.Histogram("hi")
    lo.observe(1e-5)
    hi.observe(1e4)
    pooled = metrics.merge_states([lo.state(), hi.state()])
    assert pooled.count == 2
    assert pooled.min == 1e-5 and pooled.max == 1e4
    assert pooled.percentile(0.99) == pytest.approx(1e4)


def test_prometheus_export(tmp_path):
    metrics.counter("t_prom").inc(4)
    metrics.gauge("t_gauge").set(7.0)
    metrics.gauge("t_gauge").set(3.0)
    metrics.histogram("t_ph").observe(0.5)
    text = metrics.to_prometheus()
    assert "# TYPE raft_tpu_t_prom counter\nraft_tpu_t_prom 4" in text
    assert "raft_tpu_t_gauge 3.0" in text and "raft_tpu_t_gauge_max 7.0" in text
    assert 'raft_tpu_t_ph_bucket{le="+Inf"} 1' in text
    assert "raft_tpu_t_ph_count 1" in text
    path = tmp_path / "m.prom"
    assert metrics.export(str(path))
    assert path.read_text() == text


def test_sweep_dumps_metrics_snapshot(tmp_path, log_path, monkeypatch):
    prom = str(tmp_path / "scrape.prom")
    monkeypatch.setenv("RAFT_TPU_METRICS", prom)
    cases = _cases(8, seed=2)
    out_dir = str(tmp_path / "sweep")
    with faults.inject("transient:shard_eval:1"):
        run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                    shard_size=4, mesh=mesh2(),
                                    backoff_s=0.01)
    with open(os.path.join(out_dir, "metrics.json")) as f:
        snap = json.load(f)
    assert snap["counters"]["shards_done"] == 2
    assert snap["counters"]["shard_retries"] == 1
    assert snap["counters"]["rows_evaluated"] == 8
    # the same snapshot is embedded in the manifest...
    with open(os.path.join(out_dir, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["metrics"]["counters"] == snap["counters"]
    # ...emitted as an event...
    (ev,) = _events(log_path, "metrics_snapshot")
    assert ev["snapshot"]["counters"]["shards_done"] == 2
    # ...and exported as Prometheus text
    with open(prom) as f:
        text = f.read()
    assert "raft_tpu_shards_done 2" in text
    assert "raft_tpu_shard_retries 1" in text


def test_resumed_quarantined_rows_counted(tmp_path, log_path):
    """A resumed run must not report rows_quarantined=0 while the
    resumed shards still carry NaN-poisoned rows."""
    def toy_nan(c):
        bad = c["Hs"] < 0
        return {"PSD": jnp.where(bad, jnp.nan,
                                 jnp.stack([c["Hs"], c["Tp"], c["Hs"]])),
                "X0": jnp.where(bad, jnp.nan, c["Hs"] - c["Tp"])}

    cases = _cases(8, seed=6)
    cases["Hs"][5] = -1.0
    out_dir = str(tmp_path / "sweep")
    run_sweep_checkpointed_full(toy_nan, cases, out_dir, shard_size=4,
                                mesh=mesh2(), quarantine_retry=False)
    assert metrics.counter("rows_quarantined").value == 1
    metrics.reset()
    # full resume: every shard loads from disk, the poison persists
    run_sweep_checkpointed_full(toy_nan, cases, out_dir, shard_size=4,
                                mesh=mesh2(), quarantine_retry=False)
    assert metrics.counter("rows_quarantined").value == 1
    done = _events(log_path, "sweep_done")
    assert [e["n_quarantined"] for e in done] == [1, 1]


# ------------------------------------------------------------- CLI tooling


def _run_faulty_sweep(tmp_path, log_path):
    """One checkpointed sweep with a retried transient fault AND a
    quarantined NaN row — the acceptance capture."""
    cases = _cases(8, seed=3)
    out_dir = str(tmp_path / "sweep")
    with faults.inject("transient:shard_eval:1", "nan:shard_result:1"):
        run_sweep_checkpointed_full(toy_full, cases, out_dir,
                                    shard_size=4, mesh=mesh2(),
                                    backoff_s=0.01, quarantine_retry=False)
    assert len(_events(log_path, "shard_retry")) == 1
    assert len(_events(log_path, "shard_quarantine")) == 1
    return out_dir


def test_chrome_trace_roundtrip(tmp_path, log_path):
    _run_faulty_sweep(tmp_path, log_path)
    out = str(tmp_path / "trace.json")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "trace", log_path, "-o", out],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0, p.stdout + p.stderr
    with open(out) as f:
        trace = json.load(f)  # valid JSON round-trip
    evs = trace["traceEvents"]
    slices = [e for e in evs if e["ph"] == "X"]
    # every span begin matched an end (balanced), none dropped
    assert trace["otherData"]["spans_unmatched"] == 0
    assert len(slices) == trace["otherData"]["spans_matched"] > 0
    assert {s["name"] for s in slices} >= {"sweep", "shard", "shard_attempt"}
    for e in evs:
        assert {"name", "ph", "ts", "pid", "tid"} <= set(e)
    for s in slices:
        assert s["dur"] >= 0
    # the failed attempt slice carries the error
    fails = [s for s in slices if s["args"].get("error")]
    assert len(fails) == 1 and "Transient" in fails[0]["args"]["error"]
    # instant events for the non-span stream
    assert any(e["ph"] == "i" and e["name"] == "shard_retry" for e in evs)


def test_report_cli_smoke(tmp_path, log_path):
    _run_faulty_sweep(tmp_path, log_path)
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "report", log_path],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0, p.stdout + p.stderr
    out = p.stdout
    assert "span wall-time tree" in out
    assert "sweep" in out and "shard_attempt" in out
    assert "counters (final metrics snapshot)" in out
    assert "shard_retries" in out
    assert "reliability summary" in out
    assert "retries: 1" in out
    assert "quarantine judgements: 1" in out
    # empty/garbage input exits 2, not a traceback
    bad = tmp_path / "empty.jsonl"
    bad.write_text("not json\n")
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "report", str(bad)],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 2


def test_events_cli_lists_registry():
    p = subprocess.run(
        [sys.executable, "-m", "raft_tpu.obs", "events"],
        capture_output=True, text=True, cwd=REPO)
    assert p.returncode == 0
    assert "span_begin" in p.stdout and "heartbeat" in p.stdout


@pytest.mark.slow
def test_fleet_trace_merge_e2e(tmp_path, monkeypatch):
    """The acceptance drill: a 2-worker fabric sweep plus one served
    request, captured as per-process shards, merge into a SINGLE
    Perfetto timeline — the serve dispatch span resolves to its tick,
    both workers' shard spans resolve to the coordinator's sweep span,
    no orphan spans, one run_id."""
    capture = str(tmp_path / "capture") + os.sep
    monkeypatch.setenv("RAFT_TPU_LOG", capture)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv("RAFT_TPU_FABRIC_TTL_S", "2.0")
    monkeypatch.setenv("RAFT_TPU_FABRIC_POLL_S", "0.1")
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import _fabric_entry
    from raft_tpu.parallel import fabric

    entry_file = os.path.abspath(_fabric_entry.__file__)
    cases = _cases(16, seed=9)
    fabric.run_fabric(
        str(tmp_path / "fab"), workers=2,
        entry=f"{entry_file}:slow_toy_entry",
        entry_kwargs={"delay_s": 0.2}, cases=cases,
        out_keys=("PSD", "X0"), shard_size=4)

    # one served request through the batcher, client-traced: the
    # request span is open at submit (as the HTTP layer does), the tick
    # runs afterwards on its own root (as the dispatcher thread does)
    from raft_tpu.serve.batcher import Batcher
    from raft_tpu.serve.engine import Registry

    registry = Registry()
    registry.register("spar", os.path.join(
        REPO, "raft_tpu", "designs", "spar_demo.yaml"))
    from raft_tpu.parallel.sweep import make_mesh

    batcher = Batcher(registry, mesh=make_mesh(1), tick_ms=5, max_batch=2)
    with span("serve_request", endpoint="/evaluate") as req:
        fut = batcher.submit("spar", 6.0, 11.0, 0.125,
                             trace_ctx=current_ids())
    batcher.run_tick()
    fut.result(timeout=120)

    events, bad, info = obs_report.merge_captures([capture])
    assert bad == 0
    assert info["files"] == 3          # pytest process + 2 workers
    assert not info["unanchored_files"]
    assert len({e["run_id"] for e in events}) == 1
    trace = obs_report.chrome_trace(events, merged=True)
    meta = trace["otherData"]
    assert meta["pids"] == 3
    assert meta["spans_unmatched"] == 0
    assert meta["spans_orphaned"] == 0     # every parent id resolves
    spans, _ = obs_report.collect_spans(events)
    by_id = {s["span_id"]: s for s in spans}
    sweep = [s for s in spans if s["name"] == "sweep"][-1]
    shards = [s for s in spans if s["name"] == "shard"]
    assert {s["pid"] for s in shards} and all(
        s["pid"] != os.getpid() for s in shards)
    assert len({s["pid"] for s in shards}) == 2    # both workers spoke
    for s in shards:
        assert s["trace_id"] == sweep["trace_id"]
        assert by_id[s["parent_id"]] is sweep
    # the serve side: dispatch -> tick (tree), tick -> request (link)
    tick = [s for s in spans if s["name"] == "serve_tick"][-1]
    dispatch = [s for s in spans if s["name"] == "sweep_dispatch"
                and s["trace_id"] == tick["trace_id"]][-1]
    assert by_id[dispatch["parent_id"]] is tick
    assert {(l["trace_id"], l["span_id"])
            for l in tick["attrs"]["links"]} == \
        {(req.trace_id, req.span_id)}


# -------------------------------------------------------------- heartbeat


def test_report_renders_program_cost_table():
    evs = [
        {"t": 0.0, "event": "program_cost", "pid": 1, "run_id": "r",
         "kind": "cases", "key": "k1", "source": "load",
         "flops": 2.0e9, "arg_bytes": 4096},
        {"t": 0.1, "event": "program_dispatch", "pid": 1, "run_id": "r",
         "key": "k1", "kind": "cases", "wall_s": 0.5, "gflops_s": 4.0},
        {"t": 0.2, "event": "program_dispatch", "pid": 1, "run_id": "r",
         "key": "k1", "kind": "cases", "wall_s": 0.5, "gflops_s": 4.0},
        {"t": 0.3, "event": "bucket_sweep", "pid": 1, "run_id": "r",
         "rows": 8, "n_buckets": 1, "n_designs": 2,
         "padding_waste_frac": 0.25},
    ]
    txt = obs_report.render_report(evs)
    assert "program cost ledger" in txt
    # 2 dispatches of a 2-GFLOP program over 1.0s total -> 4 GFLOP/s,
    # padding-adjusted by the 0.75 occupancy
    assert "k1" in txt and "4.00" in txt and "3.00" in txt
    assert "occupancy 0.750" in txt


def test_heartbeat_samples_devices_and_progress(log_path):
    progress = {"shards_done": 0, "n_shards": 2}
    hb = Heartbeat(0.02, progress=progress)
    hb.beat()  # deterministic single sample (no thread timing)
    progress["shards_done"] = 1
    hb.beat()
    evs = _events(log_path, "heartbeat")
    assert len(evs) == 2
    assert evs[0]["devices"] and "kind" in evs[0]["devices"][0]
    assert evs[0]["live_arrays"] is not None
    assert [e["progress"]["shards_done"] for e in evs] == [0, 1]
    assert metrics.gauge("live_arrays").value is not None


def test_heartbeat_thread_lifecycle(log_path, monkeypatch):
    monkeypatch.setenv("RAFT_TPU_HEARTBEAT_S", "0.02")
    from raft_tpu.obs.heartbeat import maybe_heartbeat

    with maybe_heartbeat(progress={"stage": "x"}) as hb:
        assert hb is not None and hb.is_alive()
        time.sleep(0.1)
    assert not hb.is_alive()
    # sampled while running, plus the final beat on stop
    assert len(_events(log_path, "heartbeat")) >= 2


def test_heartbeat_carries_window_snapshots(log_path):
    metrics.window("t_hb_win").observe(0.125)
    hb = Heartbeat(0.02)
    hb.beat()
    (ev,) = _events(log_path, "heartbeat")
    assert ev["windows"]["t_hb_win"]["count"] == 1
    assert ev["windows"]["t_hb_win"]["p95"] == 0.125


def test_heartbeat_disabled_by_default(monkeypatch):
    monkeypatch.delenv("RAFT_TPU_HEARTBEAT_S", raising=False)
    from raft_tpu.obs.heartbeat import maybe_heartbeat

    with maybe_heartbeat() as hb:
        assert hb is None


def test_heartbeat_samples_host_rss(log_path):
    """Each beat carries the host process RSS/high-watermark from
    /proc/self/status (no psutil), and the gauges' watermarks survive
    into the metrics snapshot for run records."""
    from raft_tpu.obs.heartbeat import sample_host_rss

    rss, hwm = sample_host_rss()
    if rss is None:
        pytest.skip("no /proc/self/status on this platform")
    assert rss > 1024 ** 2          # a live jax process holds > 1 MiB
    assert hwm is None or hwm >= rss
    hb = Heartbeat(0.02)
    hb.beat()
    (ev,) = _events(log_path, "heartbeat")
    assert ev["host_rss_bytes"] > 1024 ** 2
    assert metrics.gauge("host_rss_bytes").max >= ev["host_rss_bytes"]
    snap = metrics.snapshot()
    assert snap["gauges"]["host_rss_bytes"]["max"] > 0


def test_report_serve_stage_and_waste_tables():
    """The tail-attribution table's p50/p95 columns are the stage
    breakdown of the request at that latency rank (stages sum to THAT
    request's measured total), and the waste table reproduces the
    row-weighted per-axis aggregate from the exact counter pairs."""
    def req(wall, solve):
        rest = wall - solve
        return {"t": 0.1, "event": "serve_request_stages", "pid": 1,
                "run_id": "r", "wall_s": wall, "queue_wait_s": rest * 0.5,
                "tick_wait_s": rest * 0.2, "dispatch_s": rest * 0.2,
                "solve_s": solve, "post_s": rest * 0.1, "escalated": False}

    events = [req(0.010, 0.006)] * 10 + [req(0.200, 0.012)]
    snap = {"counters": {"pad_valid_strips": 141, "pad_total_strips": 192,
                         "pad_valid_rows": 3, "pad_total_rows": 4},
            "histograms": {"pad_waste_strips":
                           {"count": 3, "mean": 0.2656, "p50": 0.25,
                            "p95": 0.3, "min": 0.2, "max": 0.3,
                            "sum": 0.8}}}
    events.append({"t": 0.5, "event": "metrics_snapshot", "pid": 1,
                   "run_id": "r", "snapshot": snap})
    data = obs_report.report_data(events)
    att = data["serve_stages"]
    assert att["n_requests"] == 11
    # stages sum EXACTLY to the ranked request's measured total
    assert att["p50"]["stages_sum_s"] == pytest.approx(
        att["p50"]["total_s"], rel=1e-6)
    assert att["p95"]["stages_sum_s"] == pytest.approx(
        att["p95"]["total_s"], rel=1e-6)
    # the tail request IS the p95 column: its solve+queue dominate
    assert att["p95"]["total_s"] == pytest.approx(0.200)
    assert att["p50"]["total_s"] == pytest.approx(0.010)
    waste = data["waste"]["axes"]
    assert waste["strips"] == {
        "valid": 141, "padded": 192,
        "waste_frac": pytest.approx(1 - 141 / 192),
        "rows": 3, "row_mean": 0.2656, "row_p95": 0.3}
    assert waste["rows"]["waste_frac"] == pytest.approx(0.25)
    txt = obs_report.render_report(events)
    assert "serve tail attribution" in txt
    assert "padding waste by axis" in txt
    # json CLI twin renders the same sections
    assert data["snapshot"]["counters"]["pad_total_strips"] == 192


def test_waste_attribution_falls_back_to_bucket_sweep_events():
    events = [
        {"t": 0.1, "event": "bucket_sweep", "pid": 1, "run_id": "r",
         "rows": 3, "n_buckets": 2, "n_designs": 3,
         "padding_waste_frac": 0.2656,
         "waste_by_axis": {"strips": {"valid": 141, "padded": 192,
                                      "waste_frac": 0.265625},
                           "rows": {"valid": 3, "padded": 4,
                                    "waste_frac": 0.25}}},
    ]
    waste = obs_report.waste_attribution(events, snapshot={})
    assert waste["axes"]["strips"]["waste_frac"] == pytest.approx(
        1 - 141 / 192)
    assert obs_report.waste_attribution([], snapshot={}) is None


# -------------------------------------------------------------- structlog


def test_stage_failure_includes_error(log_path):
    with pytest.raises(RuntimeError):
        with structlog.stage("doomed_stage", case=7):
            raise RuntimeError("kaput")
    (ev,) = _events(log_path, "doomed_stage")
    assert ev["ok"] is False and "kaput" in ev["error"] and ev["case"] == 7


def test_run_id_defaults_to_process_uuid(log_path, monkeypatch):
    monkeypatch.delenv("RAFT_TPU_RUN_ID", raising=False)
    rid = structlog.run_id()
    assert rid and rid == structlog.run_id()  # stable within the process
    monkeypatch.setenv("RAFT_TPU_RUN_ID", "pinned42")
    assert structlog.run_id() == "pinned42"

# -------------------------------------------------------- flight recorder


@pytest.fixture
def flight_ring(monkeypatch):
    """A fresh default-size flight ring with no dump directory (no
    flusher thread, no crash hooks) — reset again on exit so the ring
    never leaks captures across tests."""
    from raft_tpu.obs import flight

    monkeypatch.delenv("RAFT_TPU_FLIGHT_RING", raising=False)
    monkeypatch.delenv("RAFT_TPU_FLIGHT_DIR", raising=False)
    flight.reset()
    yield flight
    flight.reset()


def test_flight_ring_captures_with_logging_off(flight_ring, monkeypatch):
    monkeypatch.delenv("RAFT_TPU_LOG", raising=False)
    flight = flight_ring
    with span("boxed", shard=3):
        structlog.log_event("shard_start", shard=3, rows=8)
    assert not structlog.enabled()          # no sink anywhere...
    # ...yet the ring holds the span pair and the event payload
    recs = flight.ring_records()
    assert [r[0] for r in recs] == ["sb", "ev", "se"]
    assert recs[1][2] == "shard_start" and recs[1][4]["rows"] == 8
    # the ring is bounded: RAFT_TPU_FLIGHT_RING caps memory, oldest out
    monkeypatch.setenv("RAFT_TPU_FLIGHT_RING", "4")
    flight.reset()
    for i in range(10):
        structlog.log_event("shard_start", shard=i, rows=1)
    recs = flight.ring_records()
    assert len(recs) == 4 and recs[-1][4]["shard"] == 9
    # ...and 0 disables capture entirely
    monkeypatch.setenv("RAFT_TPU_FLIGHT_RING", "0")
    flight.reset()
    structlog.log_event("shard_start", shard=0, rows=1)
    assert flight.ring_records() == []


def test_flight_dump_synthesizes_deterministic_span_ids(flight_ring,
                                                        monkeypatch):
    """Fast-path span records carry no ids; the dump synthesizes them
    from the per-thread nesting order, deterministically — so repeated
    dumps of one ring agree and merge without orphans."""
    from raft_tpu.obs.report import chrome_trace, collect_spans

    monkeypatch.delenv("RAFT_TPU_LOG", raising=False)
    flight = flight_ring
    with span("outer", job=1):
        with span("inner"):
            structlog.log_event("shard_start", shard=0, rows=4)
        with span("inner"):
            pass
    recs = flight.serialize_records(trigger="manual")
    hdr = recs[0]
    assert hdr["event"] == "proc_start" and hdr["unix_t"] > 1e9
    assert hdr["flight"]["version"] == flight.SCHEMA_VERSION
    assert hdr["flight"]["trigger"] == "manual"
    assert hdr["flight"]["records"] == len(recs) - 1
    begins = [r for r in recs if r["event"] == "span_begin"]
    outer = next(r for r in begins if r["name"] == "outer")
    inners = [r for r in begins if r["name"] == "inner"]
    assert outer["parent_id"] is None and outer["job"] == 1
    assert len({r["span_id"] for r in begins}) == 3
    assert all(r["parent_id"] == outer["span_id"] for r in inners)
    assert all(r["trace_id"] == outer["trace_id"] for r in begins)
    spans_, unmatched = collect_spans(recs)
    assert len(spans_) == 3 and not unmatched
    assert chrome_trace(recs)["otherData"]["spans_orphaned"] == 0
    # a second dump of the SAME ring mints identical ids (overlapping
    # shards collapse in collect_spans instead of double-counting)
    recs2 = flight.serialize_records(trigger="again")

    def ids(rs):
        return [(r.get("span_id"), r.get("parent_id")) for r in rs[1:]]

    assert ids(recs2) == ids(recs)


def test_flight_dump_roundtrip_and_layout(flight_ring, tmp_path,
                                          monkeypatch, capsys):
    flight = flight_ring
    monkeypatch.delenv("RAFT_TPU_LOG", raising=False)
    with span("boxed"):
        pass
    # no RAFT_TPU_FLIGHT_DIR and no explicit path: nowhere to write
    assert flight.dump(trigger="manual") is None
    p = str(tmp_path / "flight.jsonl")
    assert flight.dump(trigger="manual", path=p) == p
    hdr, records = flight.read_shard(p)
    assert hdr["flight"]["trigger"] == "manual"
    assert [r["event"] for r in records[1:]] == ["span_begin", "span_end"]
    assert flight.show(p) == 0
    out = capsys.readouterr().out
    assert "flight shard v1" in out and "span_begin" in out
    # trigger-slugged filenames under the dump dir: an alert dump never
    # clobbers the stable per-process flush shard
    monkeypatch.setenv("RAFT_TPU_FLIGHT_DIR", str(tmp_path / "box"))
    auto = flight.dump(trigger="alert-p99 High!")
    assert auto.endswith(f"flight-{os.getpid()}-alert-p99-high.jsonl")
    assert flight.dump_path("flush").endswith(
        f"flight-{os.getpid()}.jsonl")


def test_flight_reader_rejects_damaged_shards(flight_ring, tmp_path,
                                              capsys):
    """Unlike the tolerant live-capture reader, a flight shard is
    written atomically — ANY damage means the artifact is not
    trustworthy, and show exits 1 (the lint.sh gate)."""
    flight = flight_ring
    structlog.log_event("shard_start", shard=0, rows=1)
    ok = tmp_path / "ok.jsonl"
    flight.dump(path=str(ok), quiet=True)
    text = ok.read_text()
    # torn tail (what a non-atomic writer would leave): refused
    trunc = tmp_path / "trunc.jsonl"
    trunc.write_text(text[:-15])
    with pytest.raises(flight.FlightError, match="unparseable"):
        flight.read_shard(str(trunc))
    assert flight.show(str(trunc)) == 1
    assert "FAILED" in capsys.readouterr().err
    # body without the proc_start anchor: unmergeable, refused
    headless = tmp_path / "headless.jsonl"
    headless.write_text("".join(text.splitlines(True)[1:]))
    with pytest.raises(flight.FlightError, match="anchor"):
        flight.read_shard(str(headless))
    # a shard from a NEWER writer is refused, not guessed at
    lines = text.splitlines(True)
    hdr = json.loads(lines[0])
    hdr["flight"]["version"] = flight.SCHEMA_VERSION + 1
    newer = tmp_path / "newer.jsonl"
    newer.write_text(json.dumps(hdr) + "\n" + "".join(lines[1:]))
    with pytest.raises(flight.FlightError, match="newer"):
        flight.read_shard(str(newer))
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    with pytest.raises(flight.FlightError, match="empty"):
        flight.read_shard(str(empty))


def test_flight_metrics_snapshot_rides_the_ring(flight_ring):
    """Periodic counter DELTAS in the ring: a postmortem shows rates
    (what moved in the last interval), not just lifetime totals."""
    flight = flight_ring
    metrics.counter("t_flight_rows").inc(5)
    # the first capture after configure takes the initial snapshot
    structlog.log_event("shard_start", shard=0, rows=5)
    recs = flight.serialize_records()
    mx = [r for r in recs if r["event"] == "flight_metrics"]
    assert mx and mx[0]["counters"]["t_flight_rows"] == 5
    # the next snapshot carries only the movement since the last one
    metrics.counter("t_flight_rows").inc(2)
    flight._snap_metrics(time.perf_counter() + 1000.0)
    mx = [r for r in flight.serialize_records()
          if r["event"] == "flight_metrics"]
    assert mx[-1]["counters"]["t_flight_rows"] == 2


def test_flight_flush_shard_survives_sigkill(tmp_path):
    """The postmortem drill: a SIGKILLed process (no atexit, no
    excepthook, nothing) leaves its periodic flush shard behind, and
    the shard is schema-valid and merges with zero orphan spans."""
    from raft_tpu.obs import flight

    box = tmp_path / "box"
    code = (
        "import time\n"
        "from raft_tpu.obs import flight, span\n"
        "from raft_tpu.utils import structlog\n"
        "assert flight.maybe_start()\n"
        "i = 0\n"
        "while True:\n"
        "    with span('burst', i=i):\n"
        "        with span('step'):\n"
        "            structlog.log_event('shard_start', shard=i, rows=1)\n"
        "    i += 1\n"
        "    time.sleep(0.001)\n")
    env = dict(os.environ,
               PYTHONPATH=REPO,
               RAFT_TPU_FLIGHT_DIR=str(box),
               RAFT_TPU_FLIGHT_FLUSH_S="0.2")
    env.pop("RAFT_TPU_LOG", None)   # logging OFF: only the black box
    proc = subprocess.Popen([sys.executable, "-c", code], cwd=REPO,
                            env=env)
    shard = box / f"flight-{proc.pid}.jsonl"
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and not shard.exists():
            assert proc.poll() is None, "burst process died early"
            time.sleep(0.02)
        assert shard.exists(), "flusher never wrote the stable shard"
        time.sleep(0.3)             # let one more flush land mid-burst
    finally:
        proc.kill()                 # SIGKILL — uncatchable by design
        proc.wait(timeout=30)
    hdr, records = flight.read_shard(str(shard))
    assert hdr["flight"]["trigger"] == "flush"
    names = {r["event"] for r in records}
    assert "span_begin" in names and "shard_start" in names
    # the dead replica's last seconds assemble onto the shared timeline
    evs, bad, info = obs_report.merge_captures([str(box)])
    assert bad == 0 and info["files"] == 1 and not info["unanchored_files"]
    meta = obs_report.chrome_trace(evs, merged=True)["otherData"]
    assert meta["spans_matched"] > 0
    assert meta["spans_orphaned"] == 0


# --------------------------------------------------------- tail exemplars


def test_histogram_exemplar_topk_admission_and_threshold(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_EXEMPLAR_K", "2")
    monkeypatch.delenv("RAFT_TPU_EXEMPLAR_MIN_S", raising=False)
    h = metrics.histogram("t_ex")
    h.observe(1.10, exemplar={"design": "a"})
    h.observe(1.30, exemplar={"design": "b"})
    h.observe(1.20, exemplar={"design": "c"})   # evicts the 1.10 slot
    h.observe(1.05)                             # no exemplar: count only
    ex = h.exemplars()
    assert len(ex) == 1           # one occupied quarter-decade bucket
    ((v, unix_t, labels),) = ex.values()
    assert v == 1.30 and labels == {"design": "b"} and unix_t > 1e9
    assert h.count == 4
    # values below RAFT_TPU_EXEMPLAR_MIN_S never claim a slot
    monkeypatch.setenv("RAFT_TPU_EXEMPLAR_MIN_S", "2.0")
    h2 = metrics.histogram("t_ex_min")
    h2.observe(1.5, exemplar={"design": "d"})
    assert h2.exemplars() == {}
    h2.observe(2.5, exemplar={"design": "e"})
    assert [e[2] for e in h2.exemplars().values()] == [{"design": "e"}]


def test_exemplar_renders_openmetrics_and_emits_event(log_path):
    h = metrics.histogram("t_ex_prom")
    h.observe(0.5, exemplar={"trace_id": "feed" * 4,
                             "design": 'sp"ar\\1'})
    text = metrics.to_prometheus()
    (line,) = [l for l in text.splitlines()
               if l.startswith("raft_tpu_t_ex_prom_bucket") and "# {" in l]
    # OpenMetrics clause: # {labels} value unix_ts — labels escaped
    assert 'trace_id="feedfeedfeedfeed"' in line
    assert 'design="sp\\"ar\\\\1"' in line
    tail = line.split("} ")[-1].split()
    assert float(tail[0]) == 0.5 and float(tail[1]) > 1e9
    # each ADMITTED exemplar logs the report --tail join key
    (ev,) = _events(log_path, "exemplar_recorded")
    assert ev["metric"] == "t_ex_prom" and ev["value"] == 0.5
    assert ev["design"] == 'sp"ar\\1'
    # a non-admitted observation (loses its bucket's top-K contest)
    # stays silent — no event spam from the fast majority of requests
    for _ in range(2):
        h.observe(0.0001, exemplar={"design": "tiny"})  # fill the slots
    n = len(_events(log_path, "exemplar_recorded"))
    for _ in range(3):
        h.observe(0.0001, exemplar={"design": "tied"})  # never beats
    assert len(_events(log_path, "exemplar_recorded")) == n


def test_window_tail_exemplars_rank_worst_first():
    w = metrics.window("t_ex_win")
    now = time.perf_counter()
    w.observe(0.1, t=now - 1.0, exemplar={"design": "a"})
    w.observe(0.9, t=now - 1.0, exemplar={"design": "b"})
    w.observe(0.5, t=now - 1.0, exemplar={"design": "c"})
    w.observe(5.0, t=now - 120.0, exemplar={"design": "old"})  # aged out
    w.observe(2.0, t=now - 1.0)                 # no exemplar attached
    tail = w.tail_exemplars(k=2, now=now)
    assert [(v, lab["design"]) for v, lab in tail] == \
        [(0.9, "b"), (0.5, "c")]
    assert w.tail_exemplars(k=10, window_s=0.1, now=now) == []


# ------------------------------------------------- heartbeat procfs fallback


def test_heartbeat_degrades_without_procfs(log_path, monkeypatch, tmp_path):
    """A host without procfs loses ONLY the rss gauges: the first failed
    open memoizes unavailability (no per-beat reopen, no error spam)
    and the heartbeat keeps beating."""
    from raft_tpu.obs import heartbeat

    monkeypatch.setattr(heartbeat, "PROC_STATUS_PATH",
                        str(tmp_path / "no-procfs" / "status"))
    monkeypatch.setattr(heartbeat, "_PROC_AVAILABLE", [True])
    assert heartbeat.sample_host_rss() == (None, None)
    assert heartbeat._PROC_AVAILABLE[0] is False
    # memoized: even a now-readable path is not re-probed
    ok = tmp_path / "status"
    ok.write_text("VmRSS:\t    2048 kB\nVmHWM:\t    4096 kB\n")
    monkeypatch.setattr(heartbeat, "PROC_STATUS_PATH", str(ok))
    assert heartbeat.sample_host_rss() == (None, None)
    # beats still sample devices/progress — just without the rss keys
    hb = Heartbeat(0.02)
    hb.beat()
    (ev,) = _events(log_path, "heartbeat")
    assert "host_rss_bytes" not in ev and "error" not in ev
    assert "host_rss_bytes" not in metrics.snapshot().get("gauges", {})
    # a fresh memo against a healthy status file parses VmRSS/VmHWM
    monkeypatch.setattr(heartbeat, "_PROC_AVAILABLE", [True])
    assert heartbeat.sample_host_rss() == (2048 * 1024, 4096 * 1024)
